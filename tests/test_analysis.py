"""The static-analysis subsystem (ISSUE 4).

Three layers of coverage:

- **Parser pins**: the structured HLO parse attributes ops to their
  computation (fusion bodies, reduction combiners, conditional branches)
  and ignores comment/metadata text — the exact miscounts the old
  line-regex ``_OPCODE`` counter was prone to.
- **Adversarial fixtures**: deliberately-broken graphs — a rank-0 scalar
  across a shard_map grad path, a ring with a mismatched ppermute
  permutation, a collective under an unagreed ``lax.cond``, a dropped
  donation — each must trip *exactly* its rule with a structured finding
  naming the location, and the clean twin of each graph must stay
  silent.  Nothing here executes the traced programs: the jaxpr tier
  stages abstractly and the HLO tier stops at ``compile().as_text()``.
- **The suite gate**: ``cli.main(["--all-entries"])`` — the same
  invocation as ``scripts/graph_lint.sh`` — must exit 0 on HEAD, so any
  red finding over the registered entry configs (3D GPT trainer, ZeRO
  steps, dryrun MoE config, overlap rings) fails the fast tier.
"""

import os
import subprocess

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import analysis
from apex_tpu import parallel
from apex_tpu.analysis import hlo as hlo_lib
from apex_tpu.parallel import collectives as cc


def _only_rule(report, rule_id):
    """Every finding in the report belongs to ``rule_id`` and there is at
    least one — 'trips exactly that rule'."""
    assert report.findings, f"expected {rule_id} findings, got none"
    rules = {f.rule for f in report.findings}
    assert rules == {rule_id}, (
        f"expected only {rule_id}, got {rules}:\n{report.format()}")
    return report.findings


# ---------------------------------------------------------------------------
# structured HLO parse — the fixed opcode counting (ISSUE 4 satellite)
# ---------------------------------------------------------------------------


_HLO_FIXTURE = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (1, {}, may-alias) }, entry_computation_layout={(f32[4]{0}, f32[4]{0})->f32[4]{0}}

// a comment: %ghost = f32[4]{0} add(%a, %b) must never count

%fused_computation (param_0: f32[4], param_1: f32[4]) -> f32[4] {
  %param_0 = f32[4]{0} parameter(0)
  %param_1 = f32[4]{0} parameter(1)
  %multiply.1 = f32[4]{0} multiply(f32[4]{0} %param_0, f32[4]{0} %param_1)
  ROOT %subtract.1 = f32[4]{0} subtract(f32[4]{0} %multiply.1, f32[4]{0} %param_1)
}

%region_0.24 (Arg_0.25: f32[], Arg_1.26: f32[]) -> f32[] {
  %Arg_0.25 = f32[] parameter(0)
  %Arg_1.26 = f32[] parameter(1)
  ROOT %add.27 = f32[] add(f32[] %Arg_0.25, f32[] %Arg_1.26)
}

ENTRY %main.29 (p0.1: f32[4], p1.2: f32[4]) -> f32[4] {
  %p0.1 = f32[4]{0} parameter(0)
  %p1.2 = f32[4]{0} parameter(1), metadata={op_name="jit(step)/jit(main)/mul(x)" source_file="a.py"}
  %fusion = f32[4]{0} fusion(f32[4]{0} %p0.1, f32[4]{0} %p1.2), kind=kLoop, calls=%fused_computation
  %ag = (f32[4]{0}, f32[8]{0}) all-gather-start(f32[4]{0} %fusion), dimensions={0}
  %agd = f32[8]{0} all-gather-done((f32[4]{0}, f32[8]{0}) %ag)
  %ar = f32[8]{0} all-reduce(f32[8]{0} %agd), replica_groups={}, to_apply=%region_0.24
  %cp = f32[8]{0} collective-permute(f32[8]{0} %ar), source_target_pairs={{0,1},{1,0}}
  ROOT %slice.1 = f32[4]{0} slice(f32[8]{0} %cp), slice={[0:4]}
}
"""


class TestHloParse:
    def test_per_computation_attribution(self):
        mod = hlo_lib.parse_hlo(_HLO_FIXTURE)
        assert set(mod.computations) == {
            "fused_computation", "region_0.24", "main.29"}
        assert mod.entry.name == "main.29"
        # fusion-body ops attributed to the fusion computation, not entry
        entry_counts = hlo_lib.hlo_op_counts(_HLO_FIXTURE, "entry")
        assert entry_counts["multiply"] == 0
        assert entry_counts["subtract"] == 0
        assert entry_counts["fusion"] == 1
        # the all-reduce combiner's add lives in its region
        assert entry_counts["add"] == 0
        assert hlo_lib.hlo_op_counts(
            _HLO_FIXTURE, "region_0.24")["add"] == 1

    def test_comments_and_metadata_never_count(self):
        counts = hlo_lib.hlo_op_counts(_HLO_FIXTURE)
        # the commented-out add does not count; the combiner add does
        assert counts["add"] == 1
        # metadata op_name="jit(step)/..." does not produce a "jit" op
        assert counts["jit"] == 0
        assert counts["mul"] == 0

    def test_async_pairs_fold_once(self):
        counts = hlo_lib.hlo_op_counts(_HLO_FIXTURE)
        assert counts["all-gather"] == 1
        assert hlo_lib.count_hlo_ops(_HLO_FIXTURE, "all-gather-done") == 0
        assert counts["collective-permute"] == 1

    def test_bare_fragment_still_parses(self):
        # back-compat: test snippets without module/computation headers
        text = """
  %cp.1 = f32[4]{0} collective-permute(%p0), source_target_pairs={{0,1}}
  %ag = (f32[4]{0}, f32[8]{0}) all-gather-start(%p1), dimensions={0}
  %agd = f32[8]{0} all-gather-done(%ag)
  %d = f32[4]{0} add(%p0, %p0)
"""
        counts = hlo_lib.hlo_op_counts(text)
        assert counts["collective-permute"] == 1
        assert counts["all-gather"] == 1
        assert counts["add"] == 1

    def test_alias_and_pair_extraction(self):
        mod = hlo_lib.parse_hlo(_HLO_FIXTURE)
        assert mod.aliased_parameters() == {1}
        (cp,) = [i for i in mod.instructions()
                 if i.base_opcode == "collective-permute"]
        assert cp.source_target_pairs() == [(0, 1), (1, 0)]


# ---------------------------------------------------------------------------
# adversarial jaxpr fixtures — each trips exactly its rule
# ---------------------------------------------------------------------------


class TestRank0AcrossShardMap:
    """APX101 — the PR 2 ``_SpecError`` footgun, mechanized."""

    def _loss(self, squeeze_inside):
        mesh = parallel.initialize_model_parallel()
        params = jnp.ones((4, 4))
        x = jnp.ones((8, 4))

        def body(p, xs):
            loss = jnp.mean((xs @ p) ** 2).reshape(1)
            loss = cc.all_reduce(loss, ("dcn", "dp"), op="mean")
            return loss[0] if squeeze_inside else loss

        inner = cc.shard_over(
            body, mesh=mesh,
            in_specs=(P(), P(("dcn", "dp"))),
            out_specs=P() if squeeze_inside else P(None))
        if squeeze_inside:
            return inner, (params, x)
        return (lambda p, xs: jnp.squeeze(inner(p, xs), 0)), (params, x)

    def test_rank0_grad_path_flagged(self):
        fn, args = self._loss(squeeze_inside=True)
        report = analysis.lint_traced(fn, *args, differentiated=True)
        (finding,) = _only_rule(report, "APX101")
        assert finding.severity == analysis.ERROR
        assert "shard_map outvar" in finding.location
        assert "(1,)" in finding.remediation

    def test_one_shaped_inside_is_silent(self):
        fn, args = self._loss(squeeze_inside=False)
        report = analysis.lint_traced(fn, *args, differentiated=True)
        assert report.ok and not report.findings, report.format()

    def test_not_differentiated_is_exempt(self):
        """A step taking grads INSIDE the boundary never transposes it —
        its scalar loss output is legal (the ZeRO entries rely on this)."""
        fn, args = self._loss(squeeze_inside=True)
        report = analysis.lint_traced(fn, *args, differentiated=False)
        assert not report.findings, report.format()


class TestCollectiveUnderCond:
    """APX102 — the sentinel's agreed-predicate contract."""

    def _step(self, agree):
        mesh = parallel.initialize_model_parallel()
        g = jnp.ones((8, 4))

        def body(gs):
            finite = jnp.all(jnp.isfinite(gs))
            if agree:
                finite = jax.lax.pmin(
                    finite.astype(jnp.int32), ("dcn", "dp")) > 0

            def apply(v):
                return cc.all_reduce(v, ("dcn", "dp"), op="sum")

            return jax.lax.cond(finite, apply, lambda v: v, gs)

        return cc.shard_over(
            body, mesh=mesh, in_specs=(P(("dcn", "dp")),),
            out_specs=P(("dcn", "dp"))), (g,)

    def test_rank_local_predicate_flagged(self):
        fn, args = self._step(agree=False)
        report = analysis.lint_traced(fn, *args)
        (finding,) = _only_rule(report, "APX102")
        assert finding.severity == analysis.ERROR
        assert "dp" in finding.message
        assert "sentinel_update" in finding.remediation

    def test_pmin_agreed_predicate_silent(self):
        fn, args = self._step(agree=True)
        report = analysis.lint_traced(fn, *args)
        assert not report.findings, report.format()

    def test_replicated_input_predicate_silent(self):
        """A predicate passed IN fully replicated (the 3D trainer's
        global-grads pattern) is mesh-uniform by construction."""
        mesh = parallel.initialize_model_parallel()
        g = jnp.ones((8, 4))
        flag = jnp.bool_(True)

        def body(finite, gs):
            return jax.lax.cond(
                finite,
                lambda v: cc.all_reduce(v, ("dcn", "dp"), op="sum"),
                lambda v: v, gs)

        fn = cc.shard_over(
            body, mesh=mesh, in_specs=(P(), P(("dcn", "dp"))),
            out_specs=P(("dcn", "dp")))
        report = analysis.lint_traced(fn, flag, g)
        assert not report.findings, report.format()


class TestAxisNotInMesh:
    """APX103 — collectives over axes the enclosing mesh lacks."""

    def test_unbound_axis_becomes_finding_not_crash(self):
        devices = np.array(jax.devices("cpu")[:2])
        mesh = Mesh(devices, ("dp",))
        fn = cc.shard_over(
            lambda x: jax.lax.psum(x, "tp"), mesh=mesh,
            in_specs=(P("dp"),), out_specs=P("dp"))
        report = analysis.lint_traced(fn, jnp.ones((4,)))
        (finding,) = _only_rule(report, "APX103")
        assert "unbound axis" in finding.message


class TestPpermutePermutation:
    """APX104 — mismatched ring permutations (jax does not validate)."""

    def _ring(self, perm_fn):
        mesh = parallel.initialize_model_parallel(
            tensor_model_parallel_size=4)

        def body(x):
            return jax.lax.ppermute(x, "tp", perm_fn(4))

        return cc.shard_over(
            body, mesh=mesh, in_specs=(P("tp"),), out_specs=P("tp"))

    def test_duplicate_target_flagged(self):
        fn = self._ring(lambda n: [(0, 1), (1, 1), (2, 3), (3, 0)])
        report = analysis.lint_traced(fn, jnp.ones((8,)))
        (finding,) = _only_rule(report, "APX104")
        assert "duplicate targets [1]" in finding.message
        assert "send_recv_next" in finding.remediation

    def test_out_of_range_rank_flagged(self):
        fn = self._ring(lambda n: [(0, 1), (1, 7)])
        report = analysis.lint_traced(fn, jnp.ones((8,)))
        (finding,) = _only_rule(report, "APX104")
        assert "outside axis size 4" in finding.message

    def test_valid_ring_silent(self):
        fn = self._ring(lambda n: [(i, (i + 1) % n) for i in range(n)])
        report = analysis.lint_traced(fn, jnp.ones((8,)))
        assert not report.findings, report.format()


# ---------------------------------------------------------------------------
# adversarial HLO fixtures
# ---------------------------------------------------------------------------


def _ring_hlo(pairs, extra=""):
    body = ",".join("{%d,%d}" % p for p in pairs)
    return f"""\
ENTRY %main (p0: f32[4]) -> f32[4] {{
  %p0 = f32[4]{{0}} parameter(0)
  %cp = f32[4]{{0}} collective-permute(f32[4]{{0}} %p0), source_target_pairs={{{body}}}
{extra}  ROOT %out = f32[4]{{0}} add(f32[4]{{0}} %cp, f32[4]{{0}} %p0)
}}
"""


class TestHloRules:
    def test_refused_ring_flagged(self):
        """A 'ring' whose collective-permutes were re-fused into one
        monolithic all-gather: both APX201 conditions fire."""
        text = """\
ENTRY %main (p0: f32[4]) -> f32[16] {
  %p0 = f32[4]{0} parameter(0)
  ROOT %ag = f32[16]{0} all-gather(f32[4]{0} %p0), dimensions={0}
}
"""
        report = analysis.lint_hlo(text, expect_ring=4,
                                   forbid_ops=("all-gather",))
        findings = _only_rule(report, "APX201")
        msgs = " | ".join(f.message for f in findings)
        assert "0 collective-permute(s) < tp-1 = 3" in msgs
        assert "monolithic all-gather reappeared" in msgs

    def test_intact_ring_silent(self):
        text = _ring_hlo([(0, 1), (1, 2), (2, 3), (3, 0)])
        report = analysis.lint_hlo(text, expect_ring=2,
                                   forbid_ops=("all-gather",))
        assert not report.findings, report.format()

    def test_mismatched_permutation_flagged(self):
        text = _ring_hlo([(0, 1), (1, 1), (2, 0)])
        report = analysis.lint_hlo(text)
        (finding,) = _only_rule(report, "APX202")
        assert "duplicate targets [1]" in finding.message
        assert "%cp" in finding.location

    def test_conditional_survival(self):
        gone = "ENTRY %main (p: f32[4]) -> f32[4] {\n" \
               "  ROOT %r = f32[4]{0} add(f32[4]{0} %p, f32[4]{0} %p)\n}\n"
        report = analysis.lint_hlo(gone, expect_conditional=True)
        (finding,) = _only_rule(report, "APX203")
        assert "no `conditional` survived" in finding.message
        kept = "ENTRY %main (p: pred[]) -> f32[4] {\n" \
               "  ROOT %c = f32[4]{0} conditional(pred[] %p, f32[4]{0} " \
               "%a, f32[4]{0} %b), true_computation=%t, " \
               "false_computation=%f\n}\n"
        assert analysis.lint_hlo(kept, expect_conditional=True).ok

    def test_dropped_donation_flagged(self):
        """The real thing, compiled (not executed): the same update step
        with and without donate_argnums."""
        def step(p, g):
            return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

        p = {"w": jnp.ones((8, 8)), "b": jnp.ones((8,))}
        g = {"w": jnp.ones((8, 8)), "b": jnp.ones((8,))}

        donated = jax.jit(step, donate_argnums=(0,))
        assert analysis.lint_traced(donated, p, g, hlo=True,
                                    expect_donation=2).ok

        dropped = jax.jit(step)
        report = analysis.lint_traced(dropped, p, g, hlo=True,
                                      expect_donation=2)
        (finding,) = _only_rule(report, "APX204")
        assert "only 0 input parameter(s) aliased" in finding.message
        assert "2x HBM" in finding.message


# ---------------------------------------------------------------------------
# the pytest fixture + the suite gate
# ---------------------------------------------------------------------------


class TestGraphLintFixture:
    def test_clean_program_passes_and_returns_report(self, graph_lint):
        report = graph_lint(lambda x: x * 2, jnp.ones((4,)))
        assert report.ok

    def test_errors_raise_with_findings(self, graph_lint):
        mesh = parallel.initialize_model_parallel(
            tensor_model_parallel_size=4)
        fn = cc.shard_over(
            lambda x: jax.lax.ppermute(x, "tp", [(0, 1), (1, 1)]),
            mesh=mesh, in_specs=(P("tp"),), out_specs=P("tp"))
        with pytest.raises(AssertionError, match="APX104"):
            graph_lint(fn, jnp.ones((8,)))


def test_graph_lint_all_entries_exits_zero():
    """The suite gate (ISSUE 4 acceptance, ISSUE 19 control tier): the
    full rulebook over every registered graph entry plus the
    control-plane AST tier — the same invocation as
    ``scripts/graph_lint.sh`` minus the stability pseudo-entry, whose
    churn-sweep traces are gated separately in test_aux_subsystems
    (fast: one cached program; slow: the full sweep) to keep the
    fast-tier budget.  Any ERROR finding fails the fast tier here."""
    from apex_tpu.analysis import cli
    from apex_tpu.analysis.entries import ENTRIES

    names = ",".join(list(ENTRIES) + ["control_plane"])
    assert cli.main(["--entries", names]) == 0


def test_graph_lint_script_lists_rules():
    """The CI script is runnable and wired to the same CLI (cheap path:
    --list-rules does not build entries)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        ["bash", "scripts/graph_lint.sh", "--list-rules"],
        capture_output=True, timeout=120, cwd=repo)
    assert proc.returncode == 0, proc.stderr.decode(errors="replace")
    assert b"APX101" in proc.stdout and b"APX204" in proc.stdout
    assert b"APX301" in proc.stdout and b"APX305" in proc.stdout
