"""Crash/resume smoke, fast tier (ISSUE 3 CI satellite).

Runs ``scripts/crash_resume_smoke.sh`` in a subprocess — the real
save→SIGKILL→resume sequence through the 3D GPT trainer with async
sharded checkpoints, plus a bit-flip of the newest checkpoint so the
resume must ALSO fall back past it by checksum.  Subprocess for the same
reason as ``tests/test_entry_dryrun.py``: platform pinning and the
device count must precede backend init, and a SIGKILL needs a process to
kill.  The script asserts the resumed loss curve is bit-identical to an
uninterrupted run (losses logged as raw fp32 bits) and that the kill
landed mid-run (a trainer that finished anyway fails the script).
"""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_crash_resume_smoke_bit_exact(tmp_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the trainer pins its own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["CORRUPT_NEWEST"] = "1"
    env["PYTHON"] = sys.executable
    proc = subprocess.run(
        ["bash", os.path.join(_REPO, "scripts", "crash_resume_smoke.sh"),
         str(tmp_path / "work")],
        cwd=_REPO, env=env, capture_output=True, timeout=540,
    )
    assert proc.returncode == 0, (
        f"crash_resume_smoke.sh rc={proc.returncode}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-3000:]}"
    )
    assert b"PASS" in proc.stderr