"""apex_tpu.serving.fleet — router policy, hermetically (ISSUE 11).

Every policy branch of :class:`FleetRouter` is exercised against an
in-memory fake replica implementing the transport surface
(``alive``/``poll``/``submit``/``begin_drain``/``close``) — no process
spawn, no jax, no engine.  The fake decodes with a *deterministic*
next-token function, which is exactly the property failover replay
rests on (greedy decode is a function of the prefix), so the
kill-at-token-k matrix here proves the router's replay bookkeeping
produces bitwise-identical streams without ever touching a model.  The
real-process, real-engine, real-SIGKILL leg is
``scripts/fleet_smoke.sh`` (wired in tests/test_aux_subsystems.py).
"""

import pytest

from apex_tpu.serving.fleet import FleetRouter
from apex_tpu.serving.scheduler import RequestState


def fake_fn(seq):
    """Deterministic 'greedy decode': next token from the whole prefix
    (position-sensitive, so a replay that lost or duplicated a token
    diverges immediately instead of accidentally passing)."""
    h = 17
    for i, t in enumerate(seq):
        h = (h * 31 + (i + 1) * int(t)) % 251
    return h % 97


def reference(prompt, n, eos_id=None):
    seq = list(prompt)
    out = []
    for _ in range(n):
        t = fake_fn(seq)
        seq.append(t)
        out.append(t)
        if eos_id is not None and t == eos_id:
            break
    return out


def seeded_fn(seq, sampling, abs_step):
    """Deterministic 'sampled decode': next token from (prefix, seed,
    absolute draw counter) — the FakeReplica mirror of the engine's
    ``fold_in(PRNGKey(seed), step_offset + output_index)`` keying.  A
    replay whose wire ``step_offset`` was not rebased by the emitted
    prefix re-draws from counter 0 and diverges immediately."""
    h = 23 + int(sampling.seed) * 7 + int(abs_step) * 13
    for i, t in enumerate(seq):
        h = (h * 31 + (i + 1) * int(t)) % 251
    return h % 97


def seeded_reference(prompt, n, sampling):
    seq, out = list(prompt), []
    for step in range(n):
        t = seeded_fn(seq, sampling, step)
        seq.append(t)
        out.append(t)
    return out


class FakeReplica:
    """In-memory replica: the client duck-type over a deterministic
    single-token-per-tick engine."""

    BLOCK = 4          # fake KV block size (tokens per exported block)

    def __init__(self, name, *, free_blocks=100, max_batch=4,
                 die_after_tokens=None, fn=fake_fn, meta=None,
                 kv_occupancy=0.0, prefix_cache_hits=0,
                 fail_export=False, refuse_import=False, adapters=()):
        self.name = name
        self._fn = fn
        self.free_blocks = free_blocks
        self.kv_occupancy = kv_occupancy
        self.prefix_cache_hits = prefix_cache_hits
        self.max_batch = max_batch
        self.die_after_tokens = die_after_tokens
        self.tokens_emitted = 0
        self._alive = True
        self.draining = False
        ready = {"pid": 0, "name": name, "ckpt_step": None}
        ready.update(meta or {})
        self._events = [("ready", ready)]
        self.waiting = []           # [frid, ...]
        self.running = {}           # frid -> {"seq", "remaining", "eos"}
        self.submissions = []       # (frid, prompt, max_new, eos) log
        self.closed = False
        # --- ISSUE 16 migration surface ---
        self.fail_export = fail_export
        self.refuse_import = refuse_import
        self.exports = {}           # frid -> exported running-state (pinned)
        self.export_acks = []       # (frid, ok) log
        self.pending_imports = {}   # frid -> {"meta", "blocks": {idx: ...}}
        self.imports_committed = 0
        self.defer_import_verdict = False   # hold kv_imported until flush
        self._deferred_verdicts = []
        # --- ISSUE 17 adapter surface ---
        self.adapters = set(adapters)       # resident adapter ids
        self.adapter_loads = []             # (adapter_id, payload) log
        self.refuse_adapter = False
        # --- ISSUE 18 autopilot surface ---
        self.prefill_len = 128              # engine default (knob base)
        self.spec_k_max = 4
        self.live_knobs = {"prefill_chunk": None, "spec_k": None}
        self.knob_calls = []                # payload log (token popped)
        self.refuse_knobs = False
        self.spec_acceptance = None         # None = no drafting stats
        self.spec_by_adapter = {}
        self._emit_state()

    # --- client surface -------------------------------------------------

    def alive(self):
        return self._alive

    def poll(self):
        evs, self._events = self._events, []
        return evs

    def submit(self, frid, prompt, max_new_tokens, eos_id,
               sampling=None, trace=None):
        if not self._alive:
            raise BrokenPipeError("dead replica")
        self.submissions.append((frid, list(prompt), max_new_tokens,
                                 eos_id, sampling))
        if self.draining:
            self._events.append(("rejected", frid, "rejected"))
            return
        self.waiting.append((frid, list(prompt), max_new_tokens, eos_id,
                             sampling))

    # --- ISSUE 16 migration surface (prefill/decode disaggregation) ---

    def export_kv(self, frid):
        """Export a running request's fake KV: the block run is the token
        prefix chunked ``BLOCK`` tokens per frame, each payload a tuple
        of one uint8 ndarray (picklable across the real wire, and the
        router's bytes-on-wire counter sees real ``nbytes``).  The
        request leaves ``running`` silently — exactly the engine's
        silent-removal contract — and stays pinned in ``exports`` until
        the ``kv_ack``."""
        import numpy as np

        if not self._alive:
            raise BrokenPipeError("dead replica")
        r = self.running.get(frid)
        if self.fail_export or r is None:
            self._events.append(("kv_export_failed", frid,
                                 "fake export refused"))
            return
        del self.running[frid]
        self.exports[frid] = r
        cache_len = len(r["seq"]) - 1        # all but the last wire token
        n_blocks = max(1, -(-cache_len // self.BLOCK))
        meta = {"n_out": r["emitted"], "cache_len": cache_len,
                "n_blocks": n_blocks, "block_size": self.BLOCK,
                "bytes": cache_len * 2}
        self._events.append(("kv_meta", frid, meta))
        for idx in range(n_blocks):
            chunk = bytes(t % 256 for t in
                          r["seq"][idx * self.BLOCK:(idx + 1) * self.BLOCK])
            self._events.append(("kv_block", frid, idx,
                                 (np.frombuffer(chunk, dtype=np.uint8),)))
        self._events.append(("kv_export_done", frid, n_blocks))

    def kv_ack(self, frid, ok):
        if not self._alive:
            raise BrokenPipeError("dead replica")
        self.exports.pop(frid, None)
        self.export_acks.append((frid, bool(ok)))

    def import_kv(self, frid, meta):
        if not self._alive:
            raise BrokenPipeError("dead replica")
        self.pending_imports[frid] = {"meta": meta, "blocks": {}}

    def kv_block(self, frid, idx, payload):
        if not self._alive:
            raise BrokenPipeError("dead replica")
        p = self.pending_imports.get(frid)
        if p is not None:
            p["blocks"][idx] = payload

    def import_commit(self, frid, item, n_blocks):
        if not self._alive:
            raise BrokenPipeError("dead replica")
        p = self.pending_imports.pop(frid, None)
        if p is None or len(p["blocks"]) != n_blocks or self.draining \
                or self.refuse_import:
            verdict = ("kv_imported", frid, False, "fake import refused")
        else:
            rid, prompt, max_new, eos, sampling, trace = item
            self.running[frid] = {"seq": list(prompt),
                                  "remaining": max_new, "eos": eos,
                                  "sampling": sampling, "emitted": 0}
            self.imports_committed += 1
            verdict = ("kv_imported", frid, True, None)
        if self.defer_import_verdict:
            self._deferred_verdicts.append(verdict)
        else:
            self._events.append(verdict)

    def flush_import_verdicts(self):
        self._events.extend(self._deferred_verdicts)
        self._deferred_verdicts = []

    def kv_abort(self, frid):
        if not self._alive:
            raise BrokenPipeError("dead replica")
        self.pending_imports.pop(frid, None)

    # --- ISSUE 17 adapter surface (batched multi-LoRA serving) ---

    def load_adapter(self, adapter_id, payload=None):
        if not self._alive:
            raise BrokenPipeError("dead replica")
        if self.refuse_adapter:
            self._events.append(("adapter_loaded", adapter_id, False,
                                 "fake adapter refused"))
            return
        self.adapters.add(adapter_id)
        self.adapter_loads.append((adapter_id, dict(payload or {})))
        self._events.append(("adapter_loaded", adapter_id, True,
                             {"slot": len(self.adapters),
                              "evicted": None}))
        self._emit_state()

    def unload_adapter(self, adapter_id):
        if not self._alive:
            raise BrokenPipeError("dead replica")
        self.adapters.discard(adapter_id)
        self._events.append(("adapter_unloaded", adapter_id, True, None))
        self._emit_state()

    # --- ISSUE 18 knob surface (live retune) ---

    def set_knobs(self, payload):
        if not self._alive:
            raise BrokenPipeError("dead replica")
        payload = dict(payload or {})
        token = payload.pop("token", None)
        self.knob_calls.append(dict(payload))
        if self.refuse_knobs:
            self._events.append(("knobs_set", token, False,
                                 "fake knobs refused"))
            return
        self.live_knobs.update(payload)
        applied = dict(self.live_knobs,
                       prefill_len=self.prefill_len,
                       spec_k_max=self.spec_k_max)
        self._events.append(("knobs_set", token, True, applied))
        self._emit_state()

    def begin_drain(self, **kw):
        self.draining = True
        for frid, *_ in self.waiting:
            self._events.append(("cancelled", frid))
        self.waiting = []
        self._emit_state()
        self._maybe_finish_drain()

    def close(self, timeout=None):
        self.closed = True
        self._alive = False

    def kill(self):
        self._alive = False

    # fail/revive: the flapping_replica helper's auto-detected
    # actuator pair (testing/faults.py, ISSUE 18)
    def fail(self):
        self._alive = False

    def revive(self):
        self._alive = True

    # --- fake engine ----------------------------------------------------

    def _emit_state(self):
        self._events.append(("state", {
            "free_blocks": self.free_blocks,
            "queue_depth": len(self.waiting),
            "draining": self.draining,
            "kv_occupancy": self.kv_occupancy,
            "prefix_cache_hits": self.prefix_cache_hits,
            "kv_pending_imports": len(self.pending_imports),
            "kv_exports_pinned": len(self.exports),
            "adapters_resident": sorted(self.adapters),
            "spec_acceptance": self.spec_acceptance,
            "spec_by_adapter": dict(self.spec_by_adapter),
            "knobs": dict(self.live_knobs,
                          prefill_len=self.prefill_len,
                          spec_k_max=self.spec_k_max),
        }))

    def _maybe_finish_drain(self):
        if self.draining and not self.running and not self.waiting:
            self._events.append(("drained", None))
            self._alive = False

    def _dead_now(self):
        return (self.die_after_tokens is not None
                and self.tokens_emitted >= self.die_after_tokens)

    def tick(self):
        """One decode step: admit, then one token per running request.
        ``die_after_tokens`` kills the replica the instant that many
        tokens have been emitted — BEFORE any terminal bookkeeping for
        the killing token (and before the first token at k=0), the
        tightest possible race."""
        if not self._alive:
            return
        if self._dead_now():          # k=0: dies before emitting at all
            self._alive = False
            return
        while self.waiting and len(self.running) < self.max_batch:
            frid, prompt, max_new, eos, sampling = self.waiting.pop(0)
            self.running[frid] = {"seq": list(prompt),
                                  "remaining": max_new, "eos": eos,
                                  "sampling": sampling, "emitted": 0}
        for frid in list(self.running):
            r = self.running[frid]
            if r["sampling"] is not None:
                # the engine's seeded-counter keying, mirrored
                tok = seeded_fn(
                    r["seq"], r["sampling"],
                    r["sampling"].step_offset + r["emitted"])
            else:
                tok = self._fn(r["seq"])
            r["seq"].append(tok)
            r["emitted"] += 1
            r["remaining"] -= 1
            self._events.append(("token", frid, tok))
            self.tokens_emitted += 1
            if self._dead_now():      # k=last: token out, finish lost
                self._alive = False
                return
            if r["remaining"] <= 0 or (r["eos"] is not None
                                       and tok == r["eos"]):
                del self.running[frid]
                self._events.append(("finished", frid))
        self._emit_state()
        self._maybe_finish_drain()


def make_router(replicas, **kw):
    from apex_tpu.observability.metrics import MetricRegistry

    kw.setdefault("registry", MetricRegistry(rank=0, world=1))
    kw.setdefault("heartbeat_timeout_s", 1e9)  # no false downs in tests
    return FleetRouter(replicas, **kw)


def drive(router, replicas, *, max_ticks=500):
    """Pump router + tick fakes until every request is terminal."""
    for _ in range(max_ticks):
        router.pump()
        if router.idle():
            return
        for r in replicas:
            r.tick()
    raise AssertionError(
        f"not idle after {max_ticks} ticks: "
        f"{[(q.rid, q.state) for q in router.requests.values() if not q.done]}")


# ------------------------------------------------------------ basic flow


def test_single_request_round_trip():
    rep = FakeReplica("a")
    router = make_router([rep])
    req = router.submit([3, 5, 7], 5)
    drive(router, [rep])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference([3, 5, 7], 5)
    assert req.replays == 0 and req.replica == "a"


def test_batched_submit_one_transport_command(monkeypatch):
    """ISSUE 12 satellite: N requests dispatched to one replica in one
    pump cross the transport as ONE submit_many command (not N submit
    commands), land in order, and finish identically to per-request
    submits."""
    rep = FakeReplica("a", max_batch=8)
    commands = []
    real_submit = rep.submit

    def submit_many(items):
        commands.append(("submit_many", len(items)))
        for item in items:
            real_submit(*item)

    def submit_one(*args):
        commands.append(("submit", 1))
        real_submit(*args)

    rep.submit_many = submit_many
    rep.submit = submit_one
    router = make_router([rep], replica_queue_limit=8)
    prompts = [[3, 5, 7], [2, 4], [9, 9, 1], [6]]
    reqs = [router.submit(p, 4) for p in prompts]
    router.pump()       # one pump seats all four
    assert commands == [("submit_many", 4)], commands
    drive(router, [rep])
    for req, p in zip(reqs, prompts):
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference(p, 4)
    assert int(router.registry.counter(
        "fleet/batched_submits").value) == 1
    # a single dispatch still goes through the plain submit path (no
    # pointless one-element batch command)
    solo = router.submit([1, 2], 3)
    router.pump()
    assert commands[-1] == ("submit", 1)
    drive(router, [rep])
    assert solo.state is RequestState.FINISHED


def test_batched_submit_falls_back_without_client_support():
    """A transport without submit_many (an old replica) still works:
    the router falls back to per-request submits."""
    rep = FakeReplica("a", max_batch=8)   # FakeReplica has no submit_many
    router = make_router([rep], replica_queue_limit=8)
    reqs = [router.submit(p, 3) for p in ([1, 2], [3, 4], [5, 6])]
    drive(router, [rep])
    for req in reqs:
        assert req.state is RequestState.FINISHED
    assert len(rep.submissions) == 3


def test_eos_stops_the_stream():
    prompt = [2, 4]
    full = reference(prompt, 8)
    eos = full[2]   # force a hit mid-stream
    rep = FakeReplica("a")
    router = make_router([rep])
    req = router.submit(prompt, 8, eos_id=eos)
    drive(router, [rep])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference(prompt, 8, eos_id=eos)
    assert req.output_tokens[-1] == eos and len(req.output_tokens) == 3


# -------------------------------------------------- kill-at-k replay


@pytest.mark.parametrize("k", [0, 1, 3, 6])   # 0, 1, mid, last
def test_failover_replay_token_identity_kill_at_k(k):
    """SIGKILL at token k ∈ {0, 1, mid, last}: the stitched stream
    (k tokens from the dead replica + the replay remainder) must equal
    the uninterrupted greedy reference bitwise.  k=last is the
    died-between-last-token-and-finish race: nothing to replay, the
    router must close the request from stream content alone."""
    n_new = 6
    prompt = [9, 1, 4]
    victim = FakeReplica("victim", free_blocks=1000,
                         die_after_tokens=k)
    survivor = FakeReplica("survivor", free_blocks=10)
    router = make_router([victim, survivor])
    req = router.submit(prompt, n_new)
    drive(router, [victim, survivor])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference(prompt, n_new)
    assert req.replays == (0 if k >= n_new else 1)
    # the replay was re-prefixed, not restarted: the survivor's submit
    # carried prompt + the k already-emitted tokens and the remaining
    # budget
    if 0 < k < n_new:
        frid, wire_prompt, wire_budget, _, _ = survivor.submissions[0]
        assert frid == req.rid
        assert wire_prompt == prompt + reference(prompt, k)
        assert wire_budget == n_new - k


def test_failover_replays_all_in_flight_of_dead_replica():
    victim = FakeReplica("victim", free_blocks=1000,
                         die_after_tokens=5)
    survivor = FakeReplica("survivor", free_blocks=10)
    router = make_router([victim, survivor], replica_queue_limit=8)
    waves = [([3, 5], 4), ([7, 2, 9], 5), ([1], 3)]
    reqs = [router.submit(p, n) for p, n in waves]
    drive(router, [victim, survivor])
    for req, (p, n) in zip(reqs, waves):
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference(p, n), req.rid
    assert sum(r.replays for r in reqs) >= 1
    snap = router.registry.snapshot()
    assert snap["fleet/failovers"] == 1.0
    assert snap["fleet/replays"] == sum(r.replays for r in reqs)


# ---------------------------------------------- failure detection


def test_missed_heartbeat_retry_backoff_then_down():
    """A silent-but-alive replica (wedged child) is probed
    ``probe_retries`` times, ``probe_backoff_s`` apart, before the down
    verdict — deterministic via the injected clock."""
    clock = [0.0]
    wedged = FakeReplica("wedged", free_blocks=1000)
    healthy = FakeReplica("healthy")
    router = make_router(
        [wedged, healthy], heartbeat_timeout_s=1.0,
        probe_retries=3, probe_backoff_s=0.5, clock=lambda: clock[0])
    req = router.submit([5, 5], 4)
    router.pump()                       # dispatched to wedged (more blocks)
    assert req.replica == "wedged"
    wedged._events = []                 # and now it goes silent forever
    wedged.tick = lambda: None
    for t in (0.5, 0.9):                # inside the timeout: no probes
        clock[0] = t
        healthy._emit_state()           # the healthy one keeps beating
        router.pump()
    view = router._views["wedged"]
    assert not view.down and view.probes == 0
    clock[0] = 1.5                      # past timeout: probe ladder arms
    healthy._emit_state()
    router.pump()
    assert view.probes == 0 and view.next_probe_t == 2.0
    for expect, t in ((1, 2.1), (2, 2.7), (3, 3.3)):
        clock[0] = t
        healthy._emit_state()
        router.pump()
        assert view.probes == expect
    assert view.down and "missed heartbeat" in view.down_reason
    # the replay landed on the healthy replica and completes
    drive(router, [healthy])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference([5, 5], 4)
    assert req.replays == 1


def test_heartbeat_probe_resets_when_replica_wakes():
    clock = [0.0]
    rep = FakeReplica("a")
    router = make_router([rep], heartbeat_timeout_s=1.0,
                         probe_retries=2, probe_backoff_s=0.5,
                         clock=lambda: clock[0])
    router.pump()
    clock[0] = 1.5
    router.pump()                       # silent: ladder armed
    view = router._views["a"]
    assert view.next_probe_t is not None
    rep._emit_state()                   # it was just slow, not dead
    clock[0] = 2.1
    router.pump()
    assert view.probes == 0 and view.next_probe_t is None
    assert not view.down


def test_down_replica_excluded_from_dispatch():
    dead = FakeReplica("dead", free_blocks=1000)
    live = FakeReplica("live", free_blocks=1)
    router = make_router([dead, live])
    router.pump()                       # both ready
    dead.kill()
    router.pump()                       # detected: down, zero in-flight
    assert router._views["dead"].down
    req = router.submit([1, 2], 3)
    drive(router, [live])
    assert req.replica == "live"
    assert req.state is RequestState.FINISHED
    # a clean-death replica with no work replays nothing but IS a
    # failover event
    assert router.registry.snapshot()["fleet/failovers"] == 1.0


# ------------------------------------------------------ shed / typed reject


def test_shed_on_overload_typed_rejected():
    rep = FakeReplica("a", max_batch=1)
    router = make_router([rep], max_queue_depth=3,
                         replica_queue_limit=1)
    router.pump()
    reqs = [router.submit([1], 4) for _ in range(6)]
    shed = [r for r in reqs if r.state is RequestState.REJECTED]
    kept = [r for r in reqs if r.state is not RequestState.REJECTED]
    assert len(shed) == 3 and len(kept) == 3
    for r in shed:
        assert r.done                  # typed TERMINAL state, not a hang
        assert r.output_tokens == []
    assert router.registry.snapshot()["serving/requests_rejected"] == 3.0
    drive(router, [rep])               # the admitted ones still finish
    for r in kept:
        assert r.state is RequestState.FINISHED
        assert r.output_tokens == reference([1], 4)


def test_replica_level_reject_is_rescheduled_not_terminal():
    """The engine-side typed reject (submit during drain — the ISSUE 11
    satellite) is a re-route signal at the fleet level, never a client-
    visible failure."""
    a = FakeReplica("a", free_blocks=1000)
    b = FakeReplica("b", free_blocks=10)
    router = make_router([a, b])
    router.pump()
    a.draining = True                  # drain starts; router unaware yet
    req = router.submit([4, 2], 3)
    router.pump()                      # dispatched to a -> rejected event
    drive(router, [a, b])
    assert req.state is RequestState.FINISHED
    assert req.replica == "b"
    # >= 1: the router may bounce off the draining replica more than
    # once before its draining state-event lands
    assert req.reschedules >= 1
    assert req.output_tokens == reference([4, 2], 3)


def test_replay_of_context_capped_stream_finishes_truncated():
    """The engine's third finish condition: a stream at the context cap
    is FINISHED (truncation is a response).  A kill that eats that
    ``finished`` event must not send the request into a replay no
    replica can prefill — the router recognizes the cap from the
    handshake-advertised limits and delivers the truncated stream."""
    prompt = [4, 2]                                 # p=2
    limits = {"max_seq": 5, "prefill_len": 5}
    victim = FakeReplica("victim", free_blocks=1000,
                         die_after_tokens=3, meta=limits)
    survivor = FakeReplica("survivor", meta=limits)
    router = make_router([victim, survivor])
    req = router.submit(prompt, 10)                 # wants 10, cap allows 3
    drive(router, [victim, survivor])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference(prompt, 3)   # truncated, intact
    assert req.replays == 0                            # never re-prefix'd
    assert not survivor.submissions                    # nothing bounced


def test_duplicate_replica_names_rejected():
    with pytest.raises(ValueError, match="duplicate replica name"):
        make_router([FakeReplica("a"), FakeReplica("a")])


def test_shed_ignores_actively_decoding_requests():
    """A fully-utilized fleet with empty queues is healthy: requests
    already decoding must not count toward the shed bound."""
    rep = FakeReplica("a", max_batch=4)
    router = make_router([rep], max_queue_depth=2, replica_queue_limit=8)
    router.pump()
    first = [router.submit([i + 1], 6) for i in range(2)]
    router.pump()
    rep.tick()            # both emit a first token -> actively decoding
    router.pump()
    late = router.submit([9], 2)
    assert late.state is not RequestState.REJECTED, \
        "active slots counted as backlog"
    drive(router, [rep])
    assert all(r.state is RequestState.FINISHED for r in first + [late])


def test_poison_request_parks_rejected_after_attempt_cap():
    """A request every replica bounces (replica-level reject on each
    dispatch) must converge to the typed REJECTED terminal state after
    ``max_attempts`` re-routes — never livelock the dispatch loop."""
    rep = FakeReplica("a")
    rep._emit_state()
    router = make_router([rep], max_attempts=3)
    router.pump()
    # the replica looks dispatchable (its state heartbeats say healthy)
    # but refuses every submit — the drain-window race shape, made
    # permanent
    rep.draining = True
    rep._emit_state = lambda: rep._events.append(
        ("state", {"free_blocks": 100, "queue_depth": 0,
                   "draining": False}))
    req = router.submit([1, 2], 4)
    for _ in range(50):
        router.pump()
        if req.done:
            break
    assert req.state is RequestState.REJECTED
    assert req.reschedules == 3
    snap = router.registry.snapshot()
    assert snap["serving/requests_rejected"] == 1.0
    assert router.idle()        # terminal, not ping-ponging


def test_terminal_requests_evicted_past_keep_done():
    """The router's per-request map is bounded: terminal requests past
    ``keep_done`` are forgotten (the caller's handle stays valid)."""
    rep = FakeReplica("a", max_batch=4)
    router = make_router([rep], keep_done=5)
    reqs = [router.submit([i + 1], 1) for i in range(12)]
    drive(router, [rep])
    assert all(r.state is RequestState.FINISHED for r in reqs)
    assert len(router.requests) == 5
    assert router.idle()        # evicted ones no longer scanned


# ------------------------------------------------- priority + fairness


def test_priority_class_strict_ordering():
    rep = FakeReplica("a", max_batch=1)
    router = make_router([rep], replica_queue_limit=1)
    router.pump()
    low = [router.submit([1], 2, priority=1) for _ in range(3)]
    high = [router.submit([2], 2, priority=0) for _ in range(3)]
    drive(router, [rep])
    order = [frid for frid, *_ in rep.submissions]
    assert order[:3] == [r.rid for r in high]
    assert order[3:] == [r.rid for r in low]


def test_weighted_tenant_fairness_stride():
    """Weight 3:1 within a class → of the first 8 dispatches, tenant b
    gets 6 and tenant a gets 2 (the stride-scheduling pattern)."""
    rep = FakeReplica("r", max_batch=1)
    router = make_router([rep], replica_queue_limit=1,
                         max_queue_depth=100)
    router.set_tenant_weight("a", 1.0)
    router.set_tenant_weight("b", 3.0)
    router.pump()
    for _ in range(8):
        router.submit([1], 1, tenant="a")
        router.submit([2], 1, tenant="b")
    drive(router, [rep])
    first8 = [frid for frid, *_ in rep.submissions][:8]
    tenants = [router.requests[frid].tenant for frid in first8]
    assert tenants.count("b") == 6 and tenants.count("a") == 2
    # the interleave is the stride pattern, not a 6-then-2 burst
    assert tenants[0] == "a" and "b" in tenants[:3]


def test_unweighted_tenants_round_robin():
    rep = FakeReplica("r", max_batch=1)
    router = make_router([rep], replica_queue_limit=1)
    router.pump()
    for _ in range(4):
        router.submit([1], 1, tenant="x")
        router.submit([2], 1, tenant="y")
    drive(router, [rep])
    tenants = [router.requests[frid].tenant
               for frid, *_ in rep.submissions][:8]
    assert tenants.count("x") == 4 and tenants.count("y") == 4
    assert tenants[:2] in (["x", "y"], ["y", "x"])


def test_dispatch_prefers_free_blocks():
    small = FakeReplica("small", free_blocks=2)
    big = FakeReplica("big", free_blocks=50)
    router = make_router([small, big])
    router.pump()
    req = router.submit([1, 2, 3], 2)
    router.pump()
    assert req.replica == "big"


# ---------------------------------------------------------- rollout


def test_rollout_drains_replaces_and_requeues():
    """Staggered rollout over fakes: queued requests at the draining
    replica reschedule (zero lost), in-flight ones deliver, the
    replacement rejoins under the same name and serves."""
    a = FakeReplica("a", free_blocks=1000, max_batch=1)
    b = FakeReplica("b", free_blocks=10, max_batch=1)
    router = make_router([a, b], replica_queue_limit=4)
    router.pump()
    reqs = [router.submit([i + 1], 3) for i in range(4)]
    router.pump()
    a.tick()
    b.tick()
    router.pump()

    replacements = []

    def factory(name):
        rep = FakeReplica(name, free_blocks=1000, max_batch=1)
        replacements.append(rep)
        return rep

    def on_tick():
        for rep in [a, b] + replacements:
            rep.tick()

    rolled = router.rollout(factory, names=["a"], on_tick=on_tick,
                            drain_timeout_s=10, ready_timeout_s=10)
    assert rolled == ["a"]
    assert replacements and router._views["a"].client is replacements[0]
    drive(router, [b] + replacements)
    for i, req in enumerate(reqs):
        assert req.state is RequestState.FINISHED, (req.rid, req.state)
        assert req.output_tokens == reference([i + 1], 3)
    # nothing was silently dropped and nothing failed
    snap = router.registry.snapshot()
    assert snap["fleet/rollouts"] == 1.0
    assert snap.get("serving/requests_rejected", 0.0) == 0.0
    assert router.introspect()["replicas"]["a"]["down"] is False


def test_rollout_all_replicas_under_load():
    reps = {n: FakeReplica(n, max_batch=2) for n in ("a", "b", "c")}
    router = make_router(list(reps.values()), replica_queue_limit=4,
                         max_queue_depth=200)
    router.pump()
    live = []

    def factory(name):
        rep = FakeReplica(name, max_batch=2)
        live.append(rep)
        return rep

    submitted = []
    budget = [18]

    def on_tick():
        if budget[0] > 0:
            submitted.append(router.submit([budget[0]], 2))
            budget[0] -= 1
        for rep in list(reps.values()) + live:
            rep.tick()

    router.rollout(factory, on_tick=on_tick, drain_timeout_s=10,
                   ready_timeout_s=10)
    # the in-memory fakes drain near-instantly, so the roll may finish
    # before the drip does — what matters is that load flowed THROUGH
    # the roll and every request of it completed; top the wave up after
    # so the replacement fleet serves too
    assert len(submitted) >= 3
    while budget[0] > 0:
        submitted.append(router.submit([budget[0]], 2))
        budget[0] -= 1
    drive(router, live)
    assert len(submitted) == 18
    for req in submitted:
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference(req.prompt.tolist(), 2)
    assert router.registry.snapshot()["fleet/rollouts"] == 3.0


# ---------------------------- ISSUE 13: sampling over the replica wire


def test_sampling_params_ride_the_wire():
    """A request's SamplingParams cross the transport with every
    dispatch and drive the replica-side stream."""
    from apex_tpu.serving import SamplingParams

    rep = FakeReplica("a")
    router = make_router([rep])
    sp = SamplingParams(temperature=1.0, top_p=0.9, seed=11)
    req = router.submit([3, 5], 6, sampling=sp)
    drive(router, [rep])
    assert req.state is RequestState.FINISHED
    assert rep.submissions[0][4] == sp
    assert req.output_tokens == seeded_reference([3, 5], 6, sp)


@pytest.mark.parametrize("k", [1, 3])
def test_sampled_stream_survives_failover_replay(k):
    """The ISSUE 13 satellite contract: a SIGKILL mid-sampled-stream is
    replayed with the draw counter REBASED by the emitted prefix
    (``step_offset``), so the stitched stream is bitwise the
    uninterrupted seeded stream — sampling joins the failover replay
    story instead of breaking it."""
    from apex_tpu.serving import SamplingParams

    n_new, prompt = 6, [9, 1, 4]
    sp = SamplingParams(temperature=0.8, seed=5)
    victim = FakeReplica("victim", free_blocks=1000, die_after_tokens=k)
    survivor = FakeReplica("survivor", free_blocks=10)
    router = make_router([victim, survivor])
    req = router.submit(prompt, n_new, sampling=sp)
    drive(router, [victim, survivor])
    assert req.state is RequestState.FINISHED
    assert req.replays == 1
    ref = seeded_reference(prompt, n_new, sp)
    assert req.output_tokens == ref, \
        "stitched sampled stream diverged from the uninterrupted draw"
    # the survivor's wire carried prompt+prefix AND the rebased counter
    frid, wire_prompt, wire_budget, _, wire_sp = survivor.submissions[0]
    assert frid == req.rid
    assert wire_prompt == prompt + ref[:k]
    assert wire_sp.step_offset == k and wire_sp.seed == sp.seed


# -------------------------- ISSUE 13: fleet prefix-cache affinity


def test_tenant_affinity_tie_break():
    """With free blocks and queue depth level, a tenant's requests
    stick to the replica that last served them (whose PrefixCache
    holds their template blocks); a fresh tenant still takes the
    name-order default."""
    a = FakeReplica("a", free_blocks=50)
    b = FakeReplica("b", free_blocks=80)   # more free: first pick
    router = make_router([a, b])
    first = router.submit([1, 2, 3], 2, tenant="t")
    drive(router, [a, b])
    assert first.replica == "b"
    assert router.introspect()["tenant_affinity"]["t"] == "b"
    b.free_blocks = 50                     # level the primary signal
    b._emit_state()
    a._emit_state()
    router.pump()
    again = router.submit([1, 2, 3], 2, tenant="t")
    fresh = router.submit([4, 4], 2, tenant="u")
    drive(router, [a, b])
    assert again.replica == "b", "affinity tie-break ignored"
    assert fresh.replica == "a", \
        "non-affine tenant should take the name-order default"


def test_affinity_never_overrides_free_block_pressure():
    """free_blocks still dominates: the affine replica under pool
    pressure loses to a roomier one — affinity is strictly a
    tie-break."""
    a = FakeReplica("a", free_blocks=100)
    b = FakeReplica("b", free_blocks=10)
    router = make_router([a, b])
    first = router.submit([7, 7], 2, tenant="t")
    drive(router, [a, b])
    assert first.replica == "a"
    a.free_blocks = 3                      # pool pressure on the warm one
    a._emit_state()
    router.pump()
    nxt = router.submit([7, 7], 2, tenant="t")
    drive(router, [a, b])
    assert nxt.replica == "b"


def test_affinity_yields_past_the_occupancy_cap():
    """A warm replica whose heartbeat reports kv_occupancy past the cap
    is under pool pressure — landing a template there would force
    evictions, so the tie-break stands down."""
    a = FakeReplica("a", free_blocks=50)
    b = FakeReplica("b", free_blocks=80)   # warm one = non-default pick
    router = make_router([a, b], affinity_occupancy_cap=0.95)
    first = router.submit([1, 2], 2, tenant="t")
    drive(router, [a, b])
    assert first.replica == "b"
    b.free_blocks = 50                     # level the primary signal
    b.kv_occupancy = 0.99                  # the cache is the pool now
    a._emit_state()
    b._emit_state()
    router.pump()
    nxt = router.submit([1, 2], 2, tenant="t")
    drive(router, [a, b])
    assert nxt.replica == "a", \
        "the tie-break must stand down past the occupancy cap"


# ------------------------------- ISSUE 13: streaming client API


def _ticking(router, replicas):
    """Consuming a stream pumps the router; in the hermetic harness the
    fakes only produce when ticked, so tick them on every pump (the
    real transport's events arrive asynchronously — this is its
    deterministic stand-in)."""
    orig = router.pump

    def pump():
        orig()
        for rep in replicas:
            rep.tick()

    router.pump = pump
    return router


def test_stream_yields_tokens_and_closes_on_finish():
    rep = FakeReplica("a")
    router = _ticking(make_router([rep]), [rep])
    req = router.submit([3, 5, 7], 5)
    seen = list(router.stream(req.rid, poll_s=0))
    assert seen == reference([3, 5, 7], 5)
    assert req.state is RequestState.FINISHED


def test_stream_continues_through_failover():
    """The iterator is failover-transparent: tokens emitted before the
    kill and the replayed remainder arrive on the same stream, stitched
    bitwise."""
    victim = FakeReplica("victim", free_blocks=1000, die_after_tokens=3)
    survivor = FakeReplica("survivor", free_blocks=10)
    router = _ticking(make_router([victim, survivor]),
                      [victim, survivor])
    req = router.submit([9, 1, 4], 6)
    seen = list(router.stream(req, poll_s=0))
    assert seen == reference([9, 1, 4], 6)
    assert req.replays == 1


def test_stream_of_shed_request_closes_empty():
    rep = FakeReplica("a", max_batch=1)
    router = make_router([rep], max_queue_depth=0)
    req = router.submit([1], 4)            # shed at the door
    assert req.state is RequestState.REJECTED
    assert list(router.stream(req, poll_s=0)) == []


def test_stream_of_unknown_rid_raises():
    router = make_router([FakeReplica("a")])
    with pytest.raises(KeyError, match="unknown"):
        next(router.stream(12345))


def test_stream_inactivity_deadline_resets_across_failover():
    """ISSUE 14 satellite: the stream timeout is an INACTIVITY bound
    and replayed tokens are activity — a healthy mid-stream failover
    must never trip it, even when the total stream duration is many
    times the timeout.  Pinned with an injected clock that advances
    4s per pump against a 10s timeout over a 6-token stream (24s of
    healthy streaming + a failover gap, all inside the bound only
    because the deadline resets on every surfaced token)."""
    clock = [0.0]
    victim = FakeReplica("victim", free_blocks=1000, die_after_tokens=3)
    survivor = FakeReplica("survivor", free_blocks=10)
    router = _ticking(
        make_router([victim, survivor], clock=lambda: clock[0]),
        [victim, survivor])
    orig_pump = router.pump

    def pump():
        orig_pump()
        clock[0] += 4.0

    router.pump = pump
    req = router.submit([9, 1, 4], 6)
    seen = list(router.stream(req, poll_s=0, timeout_s=10.0))
    assert seen == reference([9, 1, 4], 6)
    assert req.replays == 1
    assert clock[0] > 10.0, "the stream must outlive the raw timeout"


def test_stream_times_out_on_genuinely_dead_fleet():
    """The other half of the inactivity contract: a fleet that stops
    producing (every replica dead, nothing terminal) still trips the
    bound instead of hanging the consumer forever."""
    clock = [0.0]
    victim = FakeReplica("victim", die_after_tokens=2)
    router = _ticking(
        make_router([victim], clock=lambda: clock[0],
                    dispatch_deadline_s=float("inf")),
        [victim])
    orig_pump = router.pump

    def pump():
        orig_pump()
        clock[0] += 4.0

    router.pump = pump
    req = router.submit([9, 1, 4], 6)
    stream = router.stream(req, poll_s=0, timeout_s=10.0)
    with pytest.raises(RuntimeError, match="no token"):
        for _ in stream:
            pass
    assert not req.done                   # silence, not a terminal state


# --------------------------------- ISSUE 14: clocks + unreachable shed


def test_heartbeat_stamp_is_monotonic_under_wall_clock_jump(monkeypatch):
    """The replica's ``hb`` heartbeat stamp rides the monotonic clock:
    an NTP wall-clock step (hours, either direction) between two
    snapshots must not move heartbeat ages at all."""
    import time as time_mod

    from apex_tpu.serving.replica import _state_snapshot

    class Eng:
        def introspect(self):
            return {"queue_depth": 0}

    walls = iter([1e9, 1e9 + 7200.0, 1e9 - 3600.0])
    monkeypatch.setattr(time_mod, "time", lambda: next(walls, 0.0))
    s1 = _state_snapshot(Eng())
    s2 = _state_snapshot(Eng())
    s3 = _state_snapshot(Eng())
    assert 0.0 <= s2["hb"] - s1["hb"] < 5.0
    assert 0.0 <= s3["hb"] - s2["hb"] < 5.0


def test_wall_clock_jump_never_triggers_false_failover():
    """Router-side half of the satellite: liveness runs on event
    ARRIVAL times (the injected monotonic clock), so heartbeats whose
    ``hb`` payload jumps by hours — the NTP-step shape — arm no probes
    and produce no down verdict."""
    clock = [0.0]
    rep = FakeReplica("a")
    router = make_router([rep], heartbeat_timeout_s=1.0,
                         probe_retries=2, probe_backoff_s=0.2,
                         clock=lambda: clock[0])
    router.pump()
    for i, wild_hb in enumerate([1e9, 1e9 + 7200.0, 1e9 - 3600.0, 0.0]):
        clock[0] += 0.5                   # inside the timeout per beat
        rep._events.append(("state", {"free_blocks": 100,
                                      "queue_depth": 0,
                                      "hb": wild_hb}))
        router.pump()
        view = router._views["a"]
        assert not view.down and view.probes == 0, (i, wild_hb)


def test_unreachable_fleet_sheds_pending_after_bounded_deadline():
    """Hermetic twin of the ChaosProxy partition test: with every
    replica down, pending requests wait exactly the bounded deadline
    on the injected clock, then shed typed REJECTED."""
    clock = [0.0]
    rep = FakeReplica("a")
    router = make_router([rep], clock=lambda: clock[0],
                         dispatch_deadline_s=3.0)
    router.pump()
    rep.kill()
    router.pump()                         # down verdict (dead process)
    assert router._views["a"].down
    req = router.submit([1, 2], 4)
    router.pump()                         # window opens
    clock[0] = 2.9
    router.pump()
    assert req.state is RequestState.WAITING   # inside the bound: wait
    clock[0] = 3.2
    router.pump()
    assert req.state is RequestState.REJECTED
    assert router.registry.snapshot()["serving/requests_rejected"] == 1.0
    assert router.idle()


# ------------------------------------------------------ introspection


def test_introspect_duck_types_debug_server_engine():
    import json
    import urllib.request

    from apex_tpu.observability import DebugServer
    from apex_tpu.observability.metrics import MetricRegistry

    rep = FakeReplica("a")
    router = make_router([rep])
    router.pump()
    router.submit([1, 2], 3)
    router.pump()
    with DebugServer(registry=MetricRegistry(rank=0, world=1),
                     engine=router) as srv:
        body = json.loads(urllib.request.urlopen(
            srv.url("/statusz"), timeout=10).read())
        health = json.loads(urllib.request.urlopen(
            srv.url("/healthz"), timeout=10).read())
    assert body["serving"]["replicas"]["a"]["down"] is False
    assert body["serving"]["queue_depth"] >= 0
    assert health["status"] == "ok"   # one draining replica != fleet down
    snap = router.introspect()
    assert snap["requests"].get("running", 0) == 1
    assert snap["draining"] is False


# ---------------- ISSUE 16: disaggregated prefill/decode migration


def _disagg_pair(**pkw):
    """A 1-prefill / 1-decode fleet, the smallest disaggregated shape."""
    p = FakeReplica("p", meta={"role": "prefill"}, **pkw)
    d = FakeReplica("d", meta={"role": "decode"})
    router = make_router([p, d])
    router.pump()                  # drain ready events → roles known
    return p, d, router


def test_disagg_happy_path_token_identity_and_counters():
    """The tentpole contract: a prefill-role replica takes admission,
    the KV run streams to the decode replica block-by-block, and the
    stitched stream is bitwise the single-replica stream.  The source
    pin releases on the ack; every migration counter moves."""
    p, d, router = _disagg_pair()
    req = router.submit([9, 1, 4], 8)
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference([9, 1, 4], 8)
    # admission landed on the prefill replica, the stream finished on
    # the decode replica
    assert p.submissions and p.submissions[0][0] == req.rid
    assert req.replica == "d"
    assert d.imports_committed == 1
    assert not d.submissions          # handoff, not a replay dispatch
    # refcount story, fake edition: pinned until ack, then released
    assert p.exports == {} and p.export_acks == [(req.rid, True)]
    snap = router.registry.snapshot()
    assert snap.get("fleet/kv_migrate_started") == 1.0
    assert snap.get("fleet/kv_migrate_completed") == 1.0
    assert snap.get("fleet/kv_migrate_failed", 0.0) == 0.0
    assert snap.get("fleet/kv_migrate_blocks", 0.0) >= 1.0
    assert snap.get("fleet/kv_migrate_bytes", 0.0) >= 1.0
    assert snap.get("fleet/failovers", 0.0) == 0.0
    assert router._migrations == {}


def test_disagg_seeded_stream_identity():
    """Seeded sampling across the handoff: the wire item's rebased
    ``step_offset`` keeps the decode replica's draw counter aligned, so
    the migrated stream is bitwise the uninterrupted seeded stream."""
    from apex_tpu.serving import SamplingParams

    sp = SamplingParams(temperature=0.8, seed=5)
    p, d, router = _disagg_pair()
    req = router.submit([3, 5], 6, sampling=sp)
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    assert req.replica == "d"
    assert req.output_tokens == seeded_reference([3, 5], 6, sp)
    assert router.registry.snapshot().get(
        "fleet/kv_migrate_completed") == 1.0


def test_migrated_gap_excluded_from_role_tpot_only():
    """The inter-token gap spanning the handoff is kv_migrate cost (it
    has its own histogram), so the per-ROLE pool-health TPOT skips it
    exactly once — while the fleet-wide and tenant-facing TPOT keep it,
    because the stall is real user-visible latency."""
    p, d, router = _disagg_pair()
    req = router.submit([9, 1, 4], 8)
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    assert req.replica == "d"
    assert router.registry.snapshot().get(
        "fleet/kv_migrate_completed") == 1.0
    # 8 tokens -> 7 inter-token gaps, all of them in the fleet-wide
    # histogram (the handoff gap is not hidden from users)...
    assert router.registry.histogram("fleet/tpot_ms").count == 7
    # ...but exactly ONE gap — the handoff — is missing from the
    # role-split histograms, and the flag is consumed (set-once)
    role_gaps = (
        router.registry.histogram("fleet/role/prefill/tpot_ms").count
        + router.registry.histogram("fleet/role/decode/tpot_ms").count)
    assert role_gaps == 6
    assert req.migrated_gap is False


def test_role_both_fleet_never_migrates():
    """``role="both"`` everywhere (the default) is byte-for-byte the
    PR 15 fleet: no export ever fires."""
    a = FakeReplica("a")
    b = FakeReplica("b")
    router = make_router([a, b])
    reqs = [router.submit([i, 2], 5) for i in (1, 3, 7)]
    drive(router, [a, b])
    for r in reqs:
        assert r.state is RequestState.FINISHED
        assert r.output_tokens == reference(list(r.prompt), 5)
    snap = router.registry.snapshot()
    assert snap.get("fleet/kv_migrate_started", 0.0) == 0.0
    assert a.exports == {} and b.exports == {}
    assert a.imports_committed == 0 and b.imports_committed == 0


def test_migration_respects_min_remaining_budget():
    """A nearly-done stream is not worth shipping: with fewer than
    ``migrate_min_remaining`` tokens left the request finishes where it
    prefilled, even on a prefill-role replica."""
    p, d, router = _disagg_pair()
    req = router.submit([5, 6], 2)     # after token 1: remaining == 1
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference([5, 6], 2)
    assert req.replica == "p"
    assert router.registry.snapshot().get(
        "fleet/kv_migrate_started", 0.0) == 0.0


def test_prefill_role_preferred_for_admission():
    """Placement grows a role axis: fresh prompts land on prefill-
    capable replicas; a decode specialist only takes admissions when
    nothing else is up."""
    p, d, router = _disagg_pair()
    req = router.submit([5, 6], 2)
    router.pump()
    assert req.replica == "p" and not d.submissions
    # sole-survivor fallback: decode-role still serves when it is all
    # that is left (demotion is a preference, not an exclusion)
    p.kill()
    req2 = router.submit([7, 7], 2)
    drive(router, [p, d])
    assert req2.state is RequestState.FINISHED
    assert req2.output_tokens == reference([7, 7], 2)
    assert req2.replica == "d"


def test_export_failed_keeps_decoding_on_source():
    """``kv_export_failed`` means nothing left the source engine: the
    request keeps decoding in place — no requeue, no token loss."""
    p, d, router = _disagg_pair(fail_export=True)
    req = router.submit([9, 1, 4], 8)
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference([9, 1, 4], 8)
    assert req.replica == "p"
    assert d.imports_committed == 0
    snap = router.registry.snapshot()
    assert snap.get("fleet/kv_migrate_started", 0.0) >= 1.0
    assert snap.get("fleet/kv_migrate_failed", 0.0) >= 1.0
    assert snap.get("fleet/kv_migrate_completed", 0.0) == 0.0
    assert snap.get("fleet/failovers", 0.0) == 0.0


def test_import_refused_degrades_to_replay_identity():
    """Every refused commit walks the proven replay path: re-prefill on
    the source, bitwise stream, and the source pin released (not ok)
    each round — the handoff can fail forever without corrupting the
    stream or leaking a block."""
    p, d, router = _disagg_pair()
    d.refuse_import = True
    req = router.submit([9, 1, 4], 6)
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference([9, 1, 4], 6)
    assert req.replica == "p"
    assert d.imports_committed == 0
    assert p.exports == {}            # every pin released...
    assert p.export_acks and all(not ok for _, ok in p.export_acks)
    snap = router.registry.snapshot()
    assert snap.get("fleet/kv_migrate_failed", 0.0) >= 1.0
    assert snap.get("fleet/kv_migrate_completed", 0.0) == 0.0


def test_decode_replica_dies_mid_transfer_replays_on_source():
    """Destination death while blocks are in flight: the record aborts,
    the source un-pins, and the request re-prefills through the
    ordinary replay machinery — bitwise identical."""
    p, d, router = _disagg_pair()
    req = router.submit([9, 1, 4], 8)
    router.pump()                      # dispatch → p
    p.tick()                           # first token
    router.pump()                      # token seen; export_kv issued
    assert router._migrations[req.rid]["phase"] == "export"
    d.kill()                           # dies before the stream relays
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference([9, 1, 4], 8)
    assert req.replica == "p"
    # replay wire carried prompt + the emitted prefix (the PR 10 shape)
    assert len(p.submissions) == 2
    assert p.submissions[1][1] == [9, 1, 4] + req.output_tokens[:1]
    assert p.exports == {} and p.export_acks == [(req.rid, False)]
    snap = router.registry.snapshot()
    assert snap.get("fleet/kv_migrate_failed", 0.0) >= 1.0
    assert snap.get("fleet/kv_migrate_completed", 0.0) == 0.0


def test_prefill_replica_dies_mid_export_replays_on_decode():
    """Source death before the export frames flush: the ordinary
    failover replay re-prefills the stream on the surviving decode
    replica."""
    p, d, router = _disagg_pair()
    req = router.submit([9, 1, 4], 8)
    router.pump()
    p.tick()
    router.pump()                      # export_kv issued, phase=export
    assert router._migrations[req.rid]["phase"] == "export"
    p.kill()
    p._events.clear()                  # SIGKILL: unflushed frames lost
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference([9, 1, 4], 8)
    assert req.replica == "d"
    assert req.replays == 1
    assert d.submissions[0][1] == [9, 1, 4] + req.output_tokens[:1]
    snap = router.registry.snapshot()
    assert snap.get("fleet/kv_migrate_failed", 0.0) >= 1.0
    assert snap.get("fleet/kv_migrate_completed", 0.0) == 0.0


def test_prefill_dies_after_export_flushed_completes_no_replay():
    """Source death AFTER the export frames flushed: what reached the
    wire is real, so the handoff completes on the decode replica — and
    the death-time replay must NOT double-execute the request."""
    p, d, router = _disagg_pair()
    req = router.submit([9, 1, 4], 8)
    router.pump()
    p.tick()
    router.pump()                      # export_kv issued, phase=export
    p.kill()                           # frames already in the queue
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference([9, 1, 4], 8)
    assert req.replica == "d"
    assert req.replays == 0
    assert not d.submissions           # handoff, never a replay dispatch
    assert d.imports_committed == 1
    snap = router.registry.snapshot()
    assert snap.get("fleet/kv_migrate_completed") == 1.0
    assert snap.get("fleet/kv_migrate_failed", 0.0) == 0.0


def test_prefill_dies_at_commit_no_double_execution():
    """The tightest race: the commit is already on the decode replica
    when the source dies.  The request moves optimistically — it must
    NOT also replay (double execution) — and the ``kv_imported``
    verdict completes the handoff."""
    p, d, router = _disagg_pair()
    d.defer_import_verdict = True      # hold kv_imported in flight
    req = router.submit([9, 1, 4], 8)
    router.pump()
    p.tick()
    router.pump()                      # export_kv issued
    router.pump()                      # meta/blocks/done → import_commit
    assert router._migrations[req.rid]["phase"] == "commit"
    p.kill()                           # dies with the verdict in flight
    p._events.clear()
    router.pump()                      # p down → optimistic move to d
    d.defer_import_verdict = False
    d.flush_import_verdicts()
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    assert req.output_tokens == reference([9, 1, 4], 8)
    assert req.replica == "d"
    assert d.imports_committed == 1
    assert not d.submissions           # never replayed onto d
    snap = router.registry.snapshot()
    assert snap.get("fleet/kv_migrate_completed") == 1.0
    assert snap.get("fleet/kv_migrate_failed", 0.0) == 0.0


def test_statusz_splits_roles_and_reports_migration_backlog():
    """/fleet/statusz grows the ISSUE 16 panes: per-role SLO split and
    the migration block (counters + backlog depth)."""
    p, d, router = _disagg_pair()
    req = router.submit([9, 1, 4], 8)
    drive(router, [p, d])
    assert req.state is RequestState.FINISHED
    body = router.fleet_statusz()
    roles = body["roles"]
    assert roles["prefill"]["replicas"] == ["p"]
    assert roles["decode"]["replicas"] == ["d"]
    # TTFT was observed on the prefill side, TPOT on the decode side
    assert roles["prefill"]["ttft_ms"]["count"] >= 1
    assert roles["decode"]["tpot_ms"]["count"] >= 1
    mig = body["migrations"]
    assert mig["started"] == 1 and mig["completed"] == 1
    assert mig["failed"] == 0
    assert mig["blocks"] >= 1 and mig["bytes"] >= 1
    assert mig["inflight"] == 0 and mig["backlog"] == 0
    assert mig["migrate_ms"]["count"] == 1
    intro = router.introspect()["replicas"]
    assert intro["p"]["role"] == "prefill"
    assert intro["d"]["role"] == "decode"
    assert intro["p"]["kv_exports_pinned"] == 0
    assert intro["d"]["kv_pending_imports"] == 0


# --------------------- ISSUE 17: batched multi-LoRA serving


def test_adapter_load_broadcast_acks_and_introspect():
    """``router.load_adapter`` broadcasts the wire command to every
    live replica, pump-waits the ``adapter_loaded`` acks, and the
    residency rides the next state heartbeat into ``introspect()``;
    ``unload_adapter`` reverses it.  A dead replica reads as a failed
    ack, never a hang."""
    a = FakeReplica("a")
    b = FakeReplica("b")
    router = make_router([a, b])
    router.pump()
    acks = router.load_adapter("tenant-a", seed=3)
    assert acks["a"][0] and acks["b"][0], acks
    assert acks["a"][1]["slot"] >= 1
    assert a.adapter_loads[0][1] == {"seed": 3}
    assert int(router.registry.counter("fleet/adapter_loads").value) == 2
    intro = router.introspect()["replicas"]
    assert intro["a"]["adapters_resident"] == ["tenant-a"]
    assert intro["b"]["adapters_resident"] == ["tenant-a"]
    acks = router.unload_adapter("tenant-a")
    assert acks["a"][0] and acks["b"][0]
    assert router.introspect()["replicas"]["a"]["adapters_resident"] == []
    # the dead-replica shape: failed ack, not a hang
    b.kill()
    router.pump()
    acks = router.load_adapter("tenant-b", seed=1)
    assert acks["a"][0]
    assert not acks["b"][0]


def test_adapter_id_rides_wire_and_slo_plane():
    """The tentpole wire contract at the fleet layer: ``adapter_id``
    rides SamplingParams through dispatch unchanged, and the finished
    stream lands in the per-adapter SLO rows of /fleet/statusz —
    bare requests contribute nothing to the adapter axis."""
    from apex_tpu.serving import SamplingParams

    rep = FakeReplica("a")
    router = make_router([rep])
    router.pump()
    router.load_adapter("tenant-a", seed=3)
    sp = SamplingParams(temperature=0.8, seed=5, adapter_id="tenant-a")
    tagged = router.submit([3, 5], 6, sampling=sp)
    bare = router.submit([3, 5], 6)
    drive(router, [rep])
    assert tagged.state is RequestState.FINISHED
    assert bare.state is RequestState.FINISHED
    assert tagged.output_tokens == seeded_reference([3, 5], 6, sp)
    wire_sp = next(s for _, _, _, _, s in rep.submissions
                   if s is not None)
    assert wire_sp.adapter_id == "tenant-a"
    rows = router.fleet_statusz()["slo"]["adapters"]
    assert list(rows) == ["tenant-a"]
    assert rows["tenant-a"]["finished"] == 1
    assert rows["tenant-a"]["rejected"] == 0
    assert rows["tenant-a"]["ttft_ms"]["count"] == 1
    assert rows["tenant-a"]["tpot_ms"]["count"] == 5   # 6 tokens, 5 gaps


@pytest.mark.parametrize("k", [1, 3])
def test_adapter_tagged_stream_survives_failover_replay(k):
    """The acceptance-criteria replay contract: SIGKILL an adapter-
    tagged seeded stream at token k — the replay wire carries the SAME
    ``adapter_id`` (and the rebased draw counter), so the survivor
    gathers the same adapter rows and the stitched stream is bitwise
    the uninterrupted one."""
    from apex_tpu.serving import SamplingParams

    n_new, prompt = 6, [9, 1, 4]
    sp = SamplingParams(temperature=0.8, seed=5, adapter_id="tenant-a")
    victim = FakeReplica("victim", free_blocks=1000, die_after_tokens=k)
    survivor = FakeReplica("survivor", free_blocks=10)
    router = make_router([victim, survivor])
    router.pump()
    acks = router.load_adapter("tenant-a", seed=3)
    assert all(ok for ok, _ in acks.values())
    req = router.submit(prompt, n_new, sampling=sp)
    drive(router, [victim, survivor])
    assert req.state is RequestState.FINISHED
    assert req.replays == 1
    ref = seeded_reference(prompt, n_new, sp)
    assert req.output_tokens == ref
    frid, wire_prompt, _, _, wire_sp = survivor.submissions[0]
    assert frid == req.rid
    assert wire_prompt == prompt + ref[:k]
    assert wire_sp.adapter_id == "tenant-a"
    assert wire_sp.step_offset == k
    # the stream closed into the per-adapter SLO rows exactly once
    rows = router.fleet_statusz()["slo"]["adapters"]
    assert rows["tenant-a"]["finished"] == 1


def test_adapter_affinity_tie_break():
    """Placement's adapter axis (satellite): with free blocks level, an
    adapter-tagged request lands on the replica whose heartbeat says
    the adapter is RESIDENT (zero arena churn) instead of the
    name-order default — and the tie-break stands down past the same
    occupancy cap prefix affinity honors."""
    from apex_tpu.serving import SamplingParams

    a = FakeReplica("a", free_blocks=50)
    b = FakeReplica("b", free_blocks=50, adapters=("lora-x",))
    router = make_router([a, b], affinity_occupancy_cap=0.95)
    router.pump()
    sp = SamplingParams(temperature=0.8, seed=5, adapter_id="lora-x")
    warm = router.submit([1, 2], 2, sampling=sp)
    cold = router.submit([1, 2], 2)
    drive(router, [a, b])
    assert warm.replica == "b", "adapter residency should win the tie"
    assert cold.replica == "a", "bare request takes the name-order default"
    # pool pressure on the warm replica: affinity yields
    b.kv_occupancy = 0.99
    a._emit_state()
    b._emit_state()
    router.pump()
    nxt = router.submit([1, 2], 2,
                        sampling=SamplingParams(temperature=0.8, seed=5,
                                                adapter_id="lora-x"))
    drive(router, [a, b])
    assert nxt.replica == "a", \
        "the tie-break must stand down past the occupancy cap"


def test_adapter_hot_swap_under_live_drip_zero_failures():
    """The acceptance-criteria hot-swap contract: ``swap_adapter``
    walks the fleet one replica at a time under a live request drip —
    every request (tagged and bare) finishes, ZERO rejects, every
    replica took the new weights exactly once, and the rolling gate
    released."""
    from apex_tpu.serving import SamplingParams

    reps = [FakeReplica(n, max_batch=2) for n in ("a", "b")]
    router = make_router(reps, replica_queue_limit=4,
                         max_queue_depth=200)
    router.pump()
    acks = router.load_adapter("tenant-a", seed=3)
    assert all(ok for ok, _ in acks.values())

    submitted = []
    budget = [12]

    def drip():
        if budget[0] > 0:
            sp = SamplingParams(temperature=0.8, seed=budget[0],
                                adapter_id="tenant-a") \
                if budget[0] % 2 else None
            submitted.append(router.submit([budget[0]], 2, sampling=sp))
            budget[0] -= 1
        for rep in reps:
            rep.tick()

    acks = router.swap_adapter("tenant-a", seed=7, on_tick=drip)
    assert all(ok for ok, _ in acks.values()), acks
    # top the wave up (fakes drain near-instantly — what matters is
    # that load flowed THROUGH the swap; the rollout-test pattern)
    while budget[0] > 0:
        drip()
    drive(router, reps)
    assert len(submitted) == 12
    for req in submitted:
        assert req.state is RequestState.FINISHED, (req.rid, req.state)
        ref = (seeded_reference(req.prompt.tolist(), 2, req.sampling)
               if req.sampling is not None
               else reference(req.prompt.tolist(), 2))
        assert req.output_tokens == ref
    snap = router.registry.snapshot()
    assert snap.get("serving/requests_rejected", 0.0) == 0.0
    assert snap["fleet/adapter_swaps"] == 2.0
    for rep in reps:
        # initial load + the swap's in-place overwrite
        assert [aid for aid, _ in rep.adapter_loads] \
            == ["tenant-a", "tenant-a"]
        assert rep.adapter_loads[1][1] == {"seed": 7}
    intro = router.introspect()["replicas"]
    assert not intro["a"]["rolling"] and not intro["b"]["rolling"]
