"""Mesh builder tests — analog of ``tests/L0/run_transformer/test_parallel_state.py``."""

import jax
import pytest

from apex_tpu import parallel
from apex_tpu.parallel import mesh as mesh_lib


def test_initialize_default():
    m = parallel.initialize_model_parallel()
    assert parallel.model_parallel_is_initialized()
    assert parallel.get_tensor_model_parallel_world_size() == 1
    assert parallel.get_pipeline_model_parallel_world_size() == 1
    assert parallel.get_data_parallel_world_size() == len(jax.devices())
    assert m is parallel.get_mesh()


@pytest.mark.parametrize("tp,pp", [(2, 1), (4, 1), (2, 2), (1, 4), (2, 4), (8, 1)])
def test_grid_shapes(tp, pp):
    n = len(jax.devices())
    if tp * pp > n:
        pytest.skip("not enough devices")
    parallel.initialize_model_parallel(
        tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp
    )
    assert parallel.get_tensor_model_parallel_world_size() == tp
    assert parallel.get_pipeline_model_parallel_world_size() == pp
    assert parallel.get_data_parallel_world_size() == n // (tp * pp)


def test_indivisible_raises():
    if len(jax.devices()) % 3 == 0:
        pytest.skip("world size divisible by 3")
    with pytest.raises(ValueError):
        parallel.initialize_model_parallel(tensor_model_parallel_size=3)


def test_virtual_pipeline_bookkeeping():
    parallel.initialize_model_parallel(
        pipeline_model_parallel_size=2, virtual_pipeline_model_parallel_size=2
    )
    assert parallel.get_virtual_pipeline_model_parallel_world_size() == 2
    assert parallel.get_virtual_pipeline_model_parallel_rank() is None
    parallel.set_virtual_pipeline_model_parallel_rank(1)
    assert parallel.get_virtual_pipeline_model_parallel_rank() == 1


def test_virtual_pipeline_requires_pp():
    with pytest.raises(ValueError):
        parallel.initialize_model_parallel(
            pipeline_model_parallel_size=1, virtual_pipeline_model_parallel_size=2
        )


def test_destroy():
    parallel.initialize_model_parallel()
    parallel.destroy_model_parallel()
    assert not parallel.model_parallel_is_initialized()
    with pytest.raises(RuntimeError):
        parallel.get_mesh()


def test_mesh_axis_order_tp_innermost():
    """tp must be the innermost (fastest-varying) axis for ICI locality."""
    parallel.initialize_model_parallel(tensor_model_parallel_size=2)
    m = parallel.get_mesh()
    assert m.axis_names == ("dcn", "dp", "pp", "cp", "tp")
    devs = m.devices
    # Along tp, device ids should be adjacent.
    flat = devs.reshape(-1, devs.shape[-1])
    for row in flat:
        ids = [d.id for d in row]
        assert ids == sorted(ids)
