"""Packed (decode-free) input path: pack round-trip, loader contracts,
on-device augmentation.  Spec: apex_tpu/data/packed.py module docstring
(the DALI-role preprocessed-shard pipeline; reference recipe context
``examples/imagenet/main_amp.py:207-232``)."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from apex_tpu.data import ImageFolder
from apex_tpu.data.image_folder import center_crop_resize
from apex_tpu.data.packed import (
    PackedImageDataset,
    PackedLoader,
    center_crop,
    pack_image_folder,
    random_crop_flip,
)

N_CLASSES, PER_CLASS, SIDE = 3, 24, 40


@pytest.fixture(scope="module")
def image_tree(tmp_path_factory):
    from PIL import Image

    root = tmp_path_factory.mktemp("jpegs")
    rng = np.random.RandomState(0)
    for c in range(N_CLASSES):
        d = root / f"class_{c}"
        d.mkdir()
        for i in range(PER_CLASS):
            # varied source sizes: packing must normalize geometry
            h, w = rng.randint(SIDE, 80, size=2)
            arr = rng.randint(0, 256, (h, w, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.jpg", quality=95)
    return str(root)


@pytest.fixture(scope="module")
def packed(image_tree, tmp_path_factory):
    prefix = str(tmp_path_factory.mktemp("packed") / "train")
    ds = pack_image_folder(image_tree, prefix, side=SIDE, workers=4)
    return prefix, ds


def test_pack_round_trip(image_tree, packed):
    prefix, ds = packed
    src = ImageFolder(image_tree)
    assert len(ds) == len(src) == N_CLASSES * PER_CLASS
    assert ds.classes == src.classes
    # a fresh open sees the same bytes
    ds2 = PackedImageDataset(prefix)
    assert ds2.side == SIDE
    for i in (0, 7, len(ds) - 1):
        img, label = src.load(i)
        np.testing.assert_array_equal(
            np.asarray(ds2.images[i]), center_crop_resize(img, SIDE))
        assert int(ds2.labels[i]) == label


def test_loader_shapes_and_disjoint_dp_shards(packed):
    _, ds = packed
    with PackedLoader(ds, local_batch=4, data_parallel_size=2) as loader:
        images, labels = next(iter(loader))
    assert images.shape == (8, SIDE, SIDE, 3) and images.dtype == np.uint8
    assert labels.shape == (8,) and labels.dtype == np.int32
    # rank shards must match a per-rank gather of the same sampler state
    fresh = PackedLoader(ds, local_batch=4, data_parallel_size=2)
    idx = [next(iter(s)) for s in fresh.samplers]
    assert not set(idx[0]) & set(idx[1]), "dp shards overlap"
    np.testing.assert_array_equal(images[:4], ds.images[idx[0]])
    np.testing.assert_array_equal(images[4:], ds.images[idx[1]])


def test_loader_epoch_determinism_and_advance(packed):
    _, ds = packed
    def first_labels(consumed=0):
        with PackedLoader(ds, local_batch=6, consumed_samples=consumed,
                          seed=3) as loader:
            return [labels.tolist() for _, labels in loader]

    a, b = first_labels(), first_labels()
    assert a == b, "same consumed_samples must replay the same epoch"
    assert len(a) == (N_CLASSES * PER_CLASS) // 6
    # advancing by one batch drops exactly the first batch of the epoch
    c = first_labels(consumed=6)
    assert c == a[1:]


def test_loader_resume_contract(packed):
    _, ds = packed
    loader = PackedLoader(ds, local_batch=4)
    it = iter(loader)
    seen = [next(it) for _ in range(3)]
    consumed = loader.consumed_samples
    assert consumed == 12, consumed  # 3 yielded batches, prefetch excluded
    loader.close()
    # a fresh loader from the checkpoint yields batch 4 onward, bitwise
    with PackedLoader(ds, local_batch=4, consumed_samples=consumed) as l2:
        nxt = next(iter(l2))
    with PackedLoader(ds, local_batch=4) as l3:
        it3 = iter(l3)
        for _ in range(3):
            next(it3)
        expect = next(it3)
    np.testing.assert_array_equal(nxt[0], expect[0])
    np.testing.assert_array_equal(nxt[1], expect[1])


def test_device_prefetch_composition(packed):
    from apex_tpu.data import prefetch_to_device

    _, ds = packed
    with PackedLoader(ds, local_batch=4) as loader:
        pf = prefetch_to_device(loader, depth=1, place=lambda b: b)
        first = next(pf)
        assert first[0].shape == (4, SIDE, SIDE, 3)
        # wrapper subtracts its queued batches: multiples of the batch,
        # at least one batch delivered
        assert pf.consumed_samples % 4 == 0
        assert pf.consumed_samples >= 4


def test_random_crop_flip_on_device(packed):
    _, ds = packed
    batch = jnp.asarray(np.asarray(ds.images[:8]))
    out = random_crop_flip(batch, jax.random.PRNGKey(0), out_size=32)
    assert out.shape == (8, 32, 32, 3) and out.dtype == jnp.float32
    # jittable + dtype option
    out_bf16 = jax.jit(
        lambda x, k: random_crop_flip(x, k, 32, dtype=jnp.bfloat16)
    )(batch, jax.random.PRNGKey(1))
    assert out_bf16.dtype == jnp.bfloat16
    # every output row must be a (possibly flipped) contiguous crop of
    # its source image: un-normalize and search for it
    from apex_tpu.data.image_folder import IMAGENET_MEAN, IMAGENET_STD

    x = np.asarray(out)
    restored = np.rint(
        (x * np.asarray(IMAGENET_STD) + np.asarray(IMAGENET_MEAN)) * 255.0
    ).astype(np.int32)
    src = np.asarray(batch).astype(np.int32)
    for b in range(8):
        found = any(
            np.array_equal(cand[oh:oh + 32, ow:ow + 32], restored[b])
            for cand in (src[b], src[b][:, ::-1, :])
            for oh in range(SIDE - 32 + 1)
            for ow in range(SIDE - 32 + 1)
        )
        assert found, f"row {b} is not a crop/flip of its source"


def test_center_crop_on_device_matches_host(packed):
    _, ds = packed
    batch = jnp.asarray(np.asarray(ds.images[:4]))
    out = center_crop(batch, 32)
    off = (SIDE - 32) // 2
    host = np.asarray(ds.images[:4])[:, off:off + 32, off:off + 32, :]
    x = np.asarray(out)
    from apex_tpu.data.image_folder import IMAGENET_MEAN, IMAGENET_STD

    restored = np.rint(
        (x * np.asarray(IMAGENET_STD) + np.asarray(IMAGENET_MEAN)) * 255.0
    ).astype(np.uint8)
    np.testing.assert_array_equal(restored, host)


def test_abandoned_iteration_rewinds_samplers(packed):
    _, ds = packed
    loader = PackedLoader(ds, local_batch=4, prefetch=2)
    it = iter(loader)
    next(it)
    del it  # abandon mid-epoch with batches gathered ahead
    loader.close()
    # undelivered prefetched batches were rewound: consumed == yielded
    assert loader.consumed_samples == 4
    # and the next iteration replays exactly from batch 2 of this epoch
    with PackedLoader(ds, local_batch=4) as ref:
        rit = iter(ref)
        next(rit)
        expect = next(rit)
    got = next(iter(loader))
    np.testing.assert_array_equal(got[0], expect[0])
    loader.close()


def test_concurrent_iterators_do_not_deadlock(packed):
    _, ds = packed
    loader = PackedLoader(ds, local_batch=4, prefetch=1)
    it1 = iter(loader)
    next(it1)
    it2 = iter(loader)
    next(it2)
    it1.close()  # abandoning one iteration must not stop the other
    for _ in range(3):
        next(it2)  # deadlocked here before per-iteration state
    it2.close()
    # all undelivered batches rewound across both iterations
    assert loader.consumed_samples % 4 == 0
    loader.close()


def test_producer_error_propagates(packed):
    _, ds = packed
    loader = PackedLoader(ds, local_batch=4)
    loader._gather = lambda idx: (_ for _ in ()).throw(
        RuntimeError("boom"))  # simulate a gather failure
    with pytest.raises(RuntimeError, match="boom"):
        next(iter(loader))
    loader.close()


def test_pack_rejects_empty_and_bad_version(tmp_path):
    import json

    with pytest.raises(Exception):
        pack_image_folder(str(tmp_path / "nope"), str(tmp_path / "out"))
    # corrupt version must fail loudly, not misparse
    prefix = str(tmp_path / "bad")
    with open(prefix + ".json", "w") as f:
        json.dump({"n": 1, "side": 8, "classes": [], "version": 99}, f)
    with pytest.raises(ValueError, match="version"):
        PackedImageDataset(prefix)


def test_second_live_iterator_preempts_first(packed):
    """Shared samplers support ONE live iteration: starting a second
    tears down the first (rewinding its undelivered prefetch) instead of
    letting two producers double-advance consumed_samples with duplicate
    index streams (r4 ADVICE packed.py:283)."""
    _, ds = packed
    loader = PackedLoader(ds, local_batch=4, prefetch=2)
    it1 = iter(loader)
    next(it1)
    it2 = iter(loader)  # preempts it1
    b2 = next(it2)
    # b2 is exactly the batch after it1's, same epoch — nothing was
    # skipped or duplicated by the abandoned prefetch
    with PackedLoader(ds, local_batch=4) as ref:
        rit = iter(ref)
        next(rit)
        expect = next(rit)
    np.testing.assert_array_equal(b2[0], expect[0])
    # the preempted iterator terminates instead of blocking forever
    assert list(it1) == []
    for _ in it2:
        pass
    it2.close()
    assert loader.consumed_samples % 4 == 0
    loader.close()
