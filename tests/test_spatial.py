"""Spatial parallelism: halo exchange + H-split bottleneck vs the
unsharded computation (reference tests the halo exchanger and
SpatialBottleneck against single-GPU runs the same way,
``apex/contrib/test/bottleneck``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.contrib.spatial import (
    SpatialBottleneck,
    halo_exchange_1d,
    spatial_conv_nhwc,
)
from apex_tpu.parallel import collectives as cc

pytestmark = pytest.mark.slow

SP = 8


@pytest.fixture()
def mesh():
    m = parallel.initialize_model_parallel(context_parallel_size=SP)
    yield m
    parallel.destroy_model_parallel()


def test_halo_exchange_matches_manual(mesh):
    H = 32
    x = jax.random.normal(jax.random.PRNGKey(0), (2, H, 4, 3))

    def local(x):
        return halo_exchange_1d(x, "cp", 2, dim=1)

    out = cc.shard_over(local, in_specs=P(None, "cp"),
                        out_specs=P(None, "cp"))(x)
    out = np.asarray(out)  # [2, 8*(4+4), 4, 3] concat of padded shards
    xs = np.asarray(x)
    hs = H // SP
    padded = out.reshape(2, SP, hs + 4, 4, 3)
    for r in range(SP):
        lo = xs[:, r * hs - 2:r * hs] if r > 0 else np.zeros((2, 2, 4, 3))
        hi = (xs[:, (r + 1) * hs:(r + 1) * hs + 2]
              if r < SP - 1 else np.zeros((2, 2, 4, 3)))
        np.testing.assert_allclose(padded[:, r, :2], lo)
        np.testing.assert_allclose(padded[:, r, 2:-2],
                                   xs[:, r * hs:(r + 1) * hs])
        np.testing.assert_allclose(padded[:, r, -2:], hi)


def test_spatial_conv_matches_unsharded(mesh):
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 8, 4))
    k = jax.random.normal(jax.random.PRNGKey(2), (3, 3, 4, 6)) * 0.1

    ref = jax.lax.conv_general_dilated(
        x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))

    out = cc.shard_over(
        lambda x: spatial_conv_nhwc(x, k, "cp"),
        in_specs=P(None, "cp"), out_specs=P(None, "cp"))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_spatial_bottleneck_matches_serial(mesh):
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 32, 8, 16))

    serial = SpatialBottleneck(in_channels=16, bottleneck_channels=8,
                               out_channels=32, axis=None)
    params = serial.init(jax.random.PRNGKey(4), x)["params"]
    # graft the serial conv2 into the spatial variant's param layout
    sp_params = dict(params)
    sp_params["conv2_kernel"] = params["conv2"]["kernel"]
    del sp_params["conv2"]
    ref = serial.apply({"params": params}, x)

    spatial = SpatialBottleneck(in_channels=16, bottleneck_channels=8,
                                out_channels=32, axis="cp")
    out = cc.shard_over(
        lambda p, x: spatial.apply({"params": p}, x),
        in_specs=(P(), P(None, "cp")), out_specs=P(None, "cp"),
    )(sp_params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    # gradients flow through the halo exchange (ppermute transpose)
    def loss(p, x):
        out = cc.shard_over(
            lambda p, x: spatial.apply({"params": p}, x),
            in_specs=(P(), P(None, "cp")), out_specs=P(None, "cp"))(p, x)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(sp_params, x)
    assert np.all(np.isfinite(np.asarray(g["conv2_kernel"])))
    assert float(jnp.sum(jnp.abs(g["conv2_kernel"]))) > 0
