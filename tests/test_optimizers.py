"""Fused-optimizer numerics vs torch references.

Mirrors ``tests/L0/run_optimizers/test_fused_optimizer.py`` (FusedAdam/SGD vs
``torch.optim`` within tolerance) and ``test_lamb.py`` (reference LAMB
reimplemented in-test).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import optimizers as opt_mod
from apex_tpu.optimizers import (
    LARC,
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedLion,
    FusedNovoGrad,
    FusedSGD,
    clip_grad_norm,
    fused_step,
)


def _make_problem(seed=0, shapes=((7, 3), (11,), (2, 5, 3))):
    rng = np.random.RandomState(seed)
    params = {f"p{i}": rng.randn(*s).astype(np.float32) for i, s in enumerate(shapes)}
    grads_seq = [
        {k: rng.randn(*v.shape).astype(np.float32) for k, v in params.items()}
        for _ in range(5)
    ]
    return params, grads_seq


def _run_ours(opt, params_np, grads_seq, **step_kw):
    params = {k: jnp.asarray(v) for k, v in params_np.items()}
    state = opt.init(params)
    step = fused_step(opt)
    for g in grads_seq:
        params, state = step({k: jnp.asarray(v) for k, v in g.items()}, state, params, **step_kw)
    return {k: np.asarray(v) for k, v in params.items()}


def _run_torch(torch_opt_ctor, params_np, grads_seq):
    tparams = {
        k: torch.nn.Parameter(torch.tensor(v)) for k, v in params_np.items()
    }
    topt = torch_opt_ctor(list(tparams.values()))
    for g in grads_seq:
        for k, p in tparams.items():
            p.grad = torch.tensor(g[k])
        topt.step()
    return {k: p.detach().numpy() for k, p in tparams.items()}


class TestFusedAdam:
    @pytest.mark.parametrize("wd", [0.0, 0.1])
    def test_adamw_vs_torch(self, wd):
        params, grads = _make_problem()
        ours = _run_ours(
            FusedAdam(lr=1e-2, weight_decay=wd, adam_w_mode=True), params, grads
        )
        ref = _run_torch(
            lambda ps: torch.optim.AdamW(ps, lr=1e-2, weight_decay=wd, eps=1e-8),
            params,
            grads,
        )
        for k in params:
            np.testing.assert_allclose(ours[k], ref[k], rtol=1e-5, atol=1e-6)

    def test_flat_matches_per_leaf(self):
        """flat=True routes the same elementwise update through one
        chunked buffer (tree_map_flat) — no reductions, so the two can
        differ only by compiler instruction fusion (fma contraction),
        i.e. ~1 ulp."""
        params, grads = _make_problem(3)
        for wd, mode in [(0.1, True), (0.1, False), (0.0, True)]:
            a = _run_ours(FusedAdam(lr=1e-2, weight_decay=wd,
                                    adam_w_mode=mode, flat=False),
                          params, grads)
            b = _run_ours(FusedAdam(lr=1e-2, weight_decay=wd,
                                    adam_w_mode=mode, flat=True),
                          params, grads)
            for k in params:
                np.testing.assert_allclose(a[k], b[k], rtol=1e-6, atol=1e-7)

    @pytest.mark.parametrize("wd", [0.0, 0.1])
    def test_adam_l2_vs_torch(self, wd):
        params, grads = _make_problem(1)
        ours = _run_ours(
            FusedAdam(lr=1e-2, weight_decay=wd, adam_w_mode=False), params, grads
        )
        ref = _run_torch(
            lambda ps: torch.optim.Adam(ps, lr=1e-2, weight_decay=wd, eps=1e-8),
            params,
            grads,
        )
        for k in params:
            np.testing.assert_allclose(ours[k], ref[k], rtol=1e-5, atol=1e-6)

    def test_no_bias_correction(self):
        params, grads = _make_problem(2)
        ours = _run_ours(FusedAdam(lr=1e-2, bias_correction=False), params, grads)
        # hand reference
        m = {k: np.zeros_like(v) for k, v in params.items()}
        v_ = {k: np.zeros_like(v) for k, v in params.items()}
        p = {k: v.copy() for k, v in params.items()}
        for g in grads:
            for k in p:
                m[k] = 0.9 * m[k] + 0.1 * g[k]
                v_[k] = 0.999 * v_[k] + 0.001 * g[k] ** 2
                p[k] -= 1e-2 * m[k] / (np.sqrt(v_[k]) + 1e-8)
        for k in p:
            np.testing.assert_allclose(ours[k], p[k], rtol=1e-5, atol=1e-6)

    def test_amsgrad_rejected(self):
        with pytest.raises(RuntimeError):
            FusedAdam(amsgrad=True)

    def test_grad_scale_folding(self):
        """grad_scale=S with grads*S must equal the unscaled run."""
        params, grads = _make_problem(3)
        scaled = [{k: v * 128.0 for k, v in g.items()} for g in grads]
        a = _run_ours(FusedAdam(lr=1e-2), params, grads)
        b = _run_ours(FusedAdam(lr=1e-2), params, scaled, grad_scale=jnp.float32(128.0))
        for k in params:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6)

    def test_skip_update(self):
        params, grads = _make_problem(4)
        out = _run_ours(FusedAdam(lr=1e-2), params, grads, skip_update=jnp.asarray(True))
        for k in params:
            np.testing.assert_allclose(out[k], params[k])

    def test_skipped_steps_dont_advance_counter(self):
        """Reference predicates the step counter on the overflow flag
        (fused_adam.py:152): a skipped first step must not change the bias
        correction of the first applied step."""
        params, grads = _make_problem(4)
        opt = FusedAdam(lr=1e-2)
        jp = {k: jnp.asarray(v) for k, v in params.items()}
        state = opt.init(jp)
        # two skipped steps, then one real one
        for _ in range(2):
            jp, state = opt.step(
                {k: jnp.asarray(v) for k, v in grads[0].items()}, state, jp,
                skip_update=jnp.asarray(True),
            )
        assert int(state.step) == 0
        jp, state = opt.step(
            {k: jnp.asarray(v) for k, v in grads[0].items()}, state, jp
        )
        assert int(state.step) == 1
        ref = _run_ours(FusedAdam(lr=1e-2), params, grads[:1])
        for k in params:
            np.testing.assert_allclose(np.asarray(jp[k]), ref[k], rtol=1e-6)

    def test_master_weights_bf16(self):
        """bf16 params with masters must track the fp32 run closely."""
        params, grads = _make_problem(5)
        ref = _run_ours(FusedAdam(lr=1e-2), params, grads)

        opt = FusedAdam(lr=1e-2, master_weights=True)
        bf = {k: jnp.asarray(v, jnp.bfloat16) for k, v in params.items()}
        state = opt.init({k: jnp.asarray(v) for k, v in params.items()})
        step = fused_step(opt)
        for g in grads:
            bf, state = step({k: jnp.asarray(v, jnp.bfloat16) for k, v in g.items()}, state, bf)
        for k in params:
            assert bf[k].dtype == jnp.bfloat16
            # master (fp32) should match the fp32 run to fp32-accumulation
            # accuracy; grads were quantized to bf16 so allow that noise
            np.testing.assert_allclose(
                np.asarray(state.master[k]), ref[k], rtol=3e-2, atol=3e-2
            )

    def test_lr_override(self):
        params, grads = _make_problem(6)
        a = _run_ours(FusedAdam(lr=999.0), params, grads, lr=jnp.float32(1e-2))
        b = _run_ours(FusedAdam(lr=1e-2), params, grads)
        for k in params:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-6)


class TestFusedSGD:
    @pytest.mark.parametrize(
        "momentum,wd,nesterov",
        [(0.0, 0.0, False), (0.9, 0.0, False), (0.9, 0.01, False), (0.9, 0.0, True)],
    )
    def test_vs_torch(self, momentum, wd, nesterov):
        params, grads = _make_problem(7)
        ours = _run_ours(
            FusedSGD(lr=0.05, momentum=momentum, weight_decay=wd, nesterov=nesterov),
            params,
            grads,
        )
        ref = _run_torch(
            lambda ps: torch.optim.SGD(
                ps, lr=0.05, momentum=momentum, weight_decay=wd, nesterov=nesterov
            ),
            params,
            grads,
        )
        for k in params:
            np.testing.assert_allclose(ours[k], ref[k], rtol=1e-5, atol=1e-6)

    def test_dampening(self):
        params, grads = _make_problem(8)
        ours = _run_ours(FusedSGD(lr=0.05, momentum=0.9, dampening=0.5), params, grads)
        ref = _run_torch(
            lambda ps: torch.optim.SGD(ps, lr=0.05, momentum=0.9, dampening=0.5),
            params,
            grads,
        )
        for k in params:
            np.testing.assert_allclose(ours[k], ref[k], rtol=1e-5, atol=1e-6)


class TestFusedAdagrad:
    @pytest.mark.parametrize("wd", [0.0, 0.1])
    def test_vs_torch(self, wd):
        params, grads = _make_problem(9)
        ours = _run_ours(FusedAdagrad(lr=0.05, weight_decay=wd, eps=1e-10), params, grads)
        ref = _run_torch(
            lambda ps: torch.optim.Adagrad(ps, lr=0.05, weight_decay=wd, eps=1e-10),
            params,
            grads,
        )
        for k in params:
            np.testing.assert_allclose(ours[k], ref[k], rtol=1e-5, atol=1e-6)


class TestFusedLamb:
    def test_vs_reference_impl(self):
        """Hand-rolled LAMB reference (mirrors tests/L0/run_optimizers/test_lamb.py)."""
        params, grads = _make_problem(10)
        lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-6, 0.01
        max_gn = 1.0
        ours = _run_ours(
            FusedLAMB(lr=lr, betas=(b1, b2), eps=eps, weight_decay=wd,
                      max_grad_norm=max_gn),
            params, grads,
        )
        p = {k: v.copy() for k, v in params.items()}
        m = {k: np.zeros_like(v) for k, v in params.items()}
        v_ = {k: np.zeros_like(v) for k, v in params.items()}
        t = 0
        for g in grads:
            t += 1
            gn = np.sqrt(sum((g[k] ** 2).sum() for k in g))
            clip = max(gn / max_gn, 1.0)
            for k in p:
                gg = g[k] / clip
                m[k] = b1 * m[k] + (1 - b1) * gg
                v_[k] = b2 * v_[k] + (1 - b2) * gg * gg
                mhat = m[k] / (1 - b1**t)
                vhat = v_[k] / (1 - b2**t)
                u = mhat / (np.sqrt(vhat) + eps) + wd * p[k]
                wn = np.sqrt((p[k] ** 2).sum())
                un = np.sqrt((u**2).sum())
                ratio = wn / un if (wn > 0 and un > 0) else 1.0
                p[k] -= lr * ratio * u
        for k in p:
            np.testing.assert_allclose(ours[k], p[k], rtol=1e-4, atol=1e-5)

    def test_adam_w_mode_false_l2(self):
        """MODE_0: wd folded into the clipped grad, no decay in update
        (multi_tensor_lamb.cu:110-132)."""
        params, grads = _make_problem(17)
        lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-6, 0.01
        max_gn = 1.0
        ours = _run_ours(
            FusedLAMB(lr=lr, betas=(b1, b2), eps=eps, weight_decay=wd,
                      max_grad_norm=max_gn, adam_w_mode=False),
            params, grads,
        )
        p = {k: v.copy() for k, v in params.items()}
        m = {k: np.zeros_like(v) for k, v in params.items()}
        v_ = {k: np.zeros_like(v) for k, v in params.items()}
        t = 0
        for g in grads:
            t += 1
            gn = np.sqrt(sum((g[k] ** 2).sum() for k in g))
            clip = max(gn / max_gn, 1.0)
            for k in p:
                gg = g[k] / clip + wd * p[k]
                m[k] = b1 * m[k] + (1 - b1) * gg
                v_[k] = b2 * v_[k] + (1 - b2) * gg * gg
                u = (m[k] / (1 - b1**t)) / (np.sqrt(v_[k] / (1 - b2**t)) + eps)
                wn = np.sqrt((p[k] ** 2).sum())
                un = np.sqrt((u**2).sum())
                ratio = wn / un if (wn > 0 and un > 0) else 1.0
                p[k] -= lr * ratio * u
        for k in p:
            np.testing.assert_allclose(ours[k], p[k], rtol=1e-4, atol=1e-5)

    def test_mixed_precision_lamb_is_master(self):
        from apex_tpu.optimizers import FusedMixedPrecisionLamb

        o = FusedMixedPrecisionLamb(lr=1e-3)
        assert o.master_weights


class TestFusedLion:
    def test_vs_reference_impl(self):
        params, grads = _make_problem(11)
        lr, b1, b2, wd = 1e-3, 0.9, 0.99, 0.1
        ours = _run_ours(
            FusedLion(lr=lr, betas=(b1, b2), weight_decay=wd), params, grads
        )
        p = {k: v.copy() for k, v in params.items()}
        m = {k: np.zeros_like(v) for k, v in params.items()}
        for g in grads:
            for k in p:
                u = b1 * m[k] + (1 - b1) * g[k]
                u = np.where(u <= 0, -1.0, 1.0) + wd * p[k]  # apex sign: 0→-1
                p[k] -= lr * u
                m[k] = b2 * m[k] + (1 - b2) * g[k]
        for k in p:
            np.testing.assert_allclose(ours[k], p[k], rtol=1e-5, atol=1e-6)


class TestFusedNovoGrad:
    @pytest.mark.parametrize("norm_type", [0, 2])
    @pytest.mark.parametrize("reg_inside", [False, True])
    @pytest.mark.parametrize("init_zero", [False, True])
    def test_flat_matches_per_leaf(self, norm_type, reg_inside, init_zero):
        """The chunked-buffer form (segmented per-tensor grad norms)
        matches the per-leaf form across both moment modes, both norm
        types, and both norm-state inits."""
        params, grads = _make_problem(13)
        kw = dict(lr=1e-2, betas=(0.95, 0.98), eps=1e-8, weight_decay=0.01,
                  reg_inside_moment=reg_inside, norm_type=norm_type,
                  init_zero=init_zero)
        a = _run_ours(FusedNovoGrad(flat=False, **kw), params, grads)
        b = _run_ours(FusedNovoGrad(flat=True, **kw), params, grads)
        for k in params:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-7)

    def test_vs_reference_impl(self):
        params, grads = _make_problem(12)
        lr, b1, b2, eps, wd = 1e-2, 0.95, 0.98, 1e-8, 0.01
        ours = _run_ours(
            FusedNovoGrad(lr=lr, betas=(b1, b2), eps=eps, weight_decay=wd),
            params, grads,
        )
        p = {k: v.copy() for k, v in params.items()}
        m = {k: np.zeros_like(v) for k, v in params.items()}
        gn = {k: None for k in params}
        t = 0
        for g in grads:
            t += 1
            bc1 = 1 - b1**t
            bc2 = np.sqrt(1 - b2**t)
            for k in p:
                n = np.sqrt((g[k] ** 2).sum())
                if gn[k] is None:
                    gn[k] = n
                gn[k] = np.sqrt(b2 * gn[k] ** 2 + (1 - b2) * n**2)
                denom = gn[k] / bc2 + eps
                m[k] = b1 * m[k] + (1 - b1) * g[k]
                u = (m[k] / bc1) / denom + wd * p[k]
                p[k] -= lr * u
        for k in p:
            np.testing.assert_allclose(ours[k], p[k], rtol=1e-4, atol=1e-5)

    def test_inf_norm_mode(self):
        params, grads = _make_problem(13)
        out = _run_ours(FusedNovoGrad(lr=1e-2, norm_type=0), params, grads)
        for k in params:  # just sanity: moved and finite
            assert np.all(np.isfinite(out[k]))
            assert not np.allclose(out[k], params[k])


class TestLARC:
    def test_flat_matches_per_leaf(self):
        """One segmented-reduction pass == two small reductions per
        tensor, including the zero-norm leave-alone branch."""
        params, grads = _make_problem(15)
        params["zero"] = np.zeros((4, 4), np.float32)  # keep branch
        g0 = dict(grads[0])
        g0["zero"] = np.ones((4, 4), np.float32)
        jp = {k: jnp.asarray(v) for k, v in params.items()}
        jg = {k: jnp.asarray(v) for k, v in g0.items()}
        kw = dict(trust_coefficient=0.02, clip=True, eps=1e-8,
                  weight_decay=0.01)
        a = LARC(flat=False, **kw).transform_grads(jg, jp, lr=0.1)
        b = LARC(flat=True, **kw).transform_grads(jg, jp, lr=0.1)
        for k in jp:
            assert jnp.asarray(b[k]).dtype == jnp.asarray(a[k]).dtype
            np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                       rtol=1e-6, atol=1e-8)

    def test_transform_matches_reference_formula(self):
        params, grads = _make_problem(14)
        lr, tc, wd, eps = 0.1, 0.02, 0.01, 1e-8
        larc = LARC(trust_coefficient=tc, clip=True, eps=eps, weight_decay=wd)
        jp = {k: jnp.asarray(v) for k, v in params.items()}
        jg = {k: jnp.asarray(v) for k, v in grads[0].items()}
        out = larc.transform_grads(jg, jp, lr=lr)
        for k in params:
            pn = np.sqrt((params[k] ** 2).sum())
            gnn = np.sqrt((grads[0][k] ** 2).sum())
            adaptive = tc * pn / (gnn + pn * wd + eps)
            adaptive = min(adaptive / lr, 1.0)
            expect = (grads[0][k] + wd * params[k]) * adaptive
            np.testing.assert_allclose(np.asarray(out[k]), expect, rtol=1e-5)

    def test_wrapper_unscales_before_norms(self):
        """LARC adaptive rates must be computed on unscaled grads."""
        params, grads = _make_problem(18)
        S = 4096.0

        def run(gs, scale):
            inner = FusedSGD(lr=0.05, momentum=0.9)
            larc = LARC(inner, trust_coefficient=0.02, weight_decay=0.01)
            jp = {k: jnp.asarray(v) for k, v in params.items()}
            state = larc.init(jp)
            jg = {k: jnp.asarray(v) for k, v in gs.items()}
            return larc.step(jg, state, jp, grad_scale=scale)[0]

        a = run(grads[0], None)
        b = run({k: v * S for k, v in grads[0].items()}, jnp.float32(S))
        for k in params:
            np.testing.assert_allclose(
                np.asarray(a[k]), np.asarray(b[k]), rtol=1e-5, atol=1e-6
            )

    def test_wrapper_steps(self):
        params, grads = _make_problem(15)
        inner = FusedSGD(lr=0.05, momentum=0.9)
        larc = LARC(inner, trust_coefficient=0.02)
        jp = {k: jnp.asarray(v) for k, v in params.items()}
        state = larc.init(jp)
        newp, state = larc.step({k: jnp.asarray(v) for k, v in grads[0].items()}, state, jp)
        assert not np.allclose(np.asarray(newp["p0"]), params["p0"])


class TestClipGrad:
    def test_vs_torch(self):
        params, grads = _make_problem(16)
        jg = {k: jnp.asarray(v) for k, v in grads[0].items()}
        clipped, total = clip_grad_norm(jg, max_norm=1.0)
        tg = [torch.tensor(grads[0][k], requires_grad=False) for k in grads[0]]
        for t in tg:
            t.grad = None
        tp = [torch.nn.Parameter(t) for t in tg]
        for p, k in zip(tp, grads[0]):
            p.grad = torch.tensor(grads[0][k])
        tnorm = torch.nn.utils.clip_grad_norm_(tp, 1.0)
        np.testing.assert_allclose(float(total), float(tnorm), rtol=1e-5)
        for k, p in zip(grads[0], tp):
            np.testing.assert_allclose(
                np.asarray(clipped[k]), p.grad.numpy(), rtol=1e-4, atol=1e-6
            )

    def test_no_clip_when_small(self):
        g = {"a": jnp.full((2,), 1e-3)}
        clipped, total = clip_grad_norm(g, max_norm=10.0)
        np.testing.assert_allclose(np.asarray(clipped["a"]), 1e-3, rtol=1e-5)
