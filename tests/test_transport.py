"""apex_tpu.serving.transport + ChaosProxy — the cross-host wire (ISSUE 14).

The PR 10 fleet contracts were proven transport-agnostic over in-memory
fakes; this file proves them over REAL loopback TCP with injected
network faults.  A ``ServedFake`` puts the deterministic
``test_fleet.FakeReplica`` engine behind a real
:class:`~apex_tpu.serving.transport.TransportServer`, the router drives
it through :class:`~apex_tpu.serving.transport.SocketTransport`, and a
:class:`~apex_tpu.testing.faults.ChaosProxy` sits on the wire injecting
partition, half-open, slow-link, torn-frame, crc-corruption, and
reconnect churn — each stream still bitwise identical to the
uninterrupted reference.  Framing units at the top; the real-engine
socket leg is ``scripts/fleet_smoke.sh`` phase D.
"""

import queue
import socket
import threading
import time

import pytest

from apex_tpu.serving.fleet import FleetRouter
from apex_tpu.serving.scheduler import RequestState
from apex_tpu.serving.transport import (
    FRAME_VERSION,
    FrameDecoder,
    FrameError,
    SocketTransport,
    TransportError,
    TransportServer,
    encode_frame,
)
from apex_tpu.testing.faults import ChaosProxy

from test_fleet import FakeReplica, make_router, reference

# ------------------------------------------------------------- framing


def test_frame_round_trip_incremental():
    payloads = [("token", 3, 42), ("state", {"free_blocks": 7}),
                ("evt", 1, ("ready", {"pid": 1})), ("ping", 9)]
    wire = b"".join(encode_frame(p) for p in payloads)
    dec = FrameDecoder()
    got = []
    for i in range(0, len(wire), 3):      # drip 3 bytes at a time
        got.extend(dec.feed(wire[i:i + 3]))
    assert got == payloads
    assert not dec.partial


def test_frame_partial_flags_torn_state():
    frame = encode_frame(("token", 1, 2))
    dec = FrameDecoder()
    assert dec.feed(frame[:len(frame) - 2]) == []
    assert dec.partial                    # EOF now would tear a frame
    assert dec.feed(frame[len(frame) - 2:]) == [("token", 1, 2)]
    assert not dec.partial


def test_frame_version_mismatch_raises():
    frame = bytearray(encode_frame(("x",)))
    frame[0] = FRAME_VERSION + 1
    with pytest.raises(FrameError, match="version"):
        FrameDecoder().feed(bytes(frame))


def test_frame_crc_mismatch_raises():
    frame = bytearray(encode_frame(("token", 1, 2)))
    frame[-1] ^= 0x10                     # body bit flip
    with pytest.raises(FrameError, match="crc32"):
        FrameDecoder().feed(bytes(frame))


def test_frame_length_bound_raises():
    with pytest.raises(FrameError, match="bound"):
        FrameDecoder(max_frame_bytes=16).feed(
            encode_frame(("x" * 64,)))


# ---------------------------------------------------- harness plumbing


class ServedFake:
    """A deterministic FakeReplica engine behind a real
    TransportServer: the hermetic socket replica.  ``tick()`` plays the
    replica host's loop — apply wire commands, one decode step, relay
    events; the server closes with ``bye`` on a clean drain and without
    it on a kill (the crash shape)."""

    def __init__(self, name, event_ring=8192, **fake_kw):
        self.fake = FakeReplica(name, **fake_kw)
        self.name = name
        self.cmd_q = queue.Queue()
        self.evt_q = queue.Queue()
        self.server = TransportServer(self.cmd_q, self.evt_q,
                                      event_ring=event_ring)
        self.address = self.server.address
        self._closed = False
        self._relay()

    def _relay(self):
        for ev in self.fake.poll():
            self.evt_q.put(ev)

    def tick(self):
        if self._closed:
            return
        while True:
            try:
                cmd = self.cmd_q.get_nowait()
            except queue.Empty:
                break
            try:
                if cmd[0] == "submit":
                    self.fake.submit(*cmd[1:])
                elif cmd[0] == "submit_many":
                    for item in cmd[1]:
                        self.fake.submit(*item)
                elif cmd[0] == "drain":
                    self.fake.begin_drain()
                elif cmd[0] == "export_kv":
                    self.fake.export_kv(cmd[1])
                elif cmd[0] == "kv_ack":
                    self.fake.kv_ack(cmd[1], cmd[2])
                elif cmd[0] == "import_kv":
                    self.fake.import_kv(cmd[1], cmd[2])
                elif cmd[0] == "kv_block":
                    self.fake.kv_block(cmd[1], cmd[2], cmd[3])
                elif cmd[0] == "import_commit":
                    self.fake.import_commit(cmd[1], cmd[2], cmd[3])
                elif cmd[0] == "kv_abort":
                    self.fake.kv_abort(cmd[1])
                elif cmd[0] == "stop":
                    self._relay()
                    self._shutdown(bye=True)
                    return
            except BrokenPipeError:
                pass                      # command raced the death
        self.fake.tick()
        self._relay()
        if not self.fake.alive():
            # drained exit says goodbye; a crash just goes dark
            self._shutdown(bye=self.fake.draining)

    def kill(self):
        self.fake.kill()
        self._shutdown(bye=False)

    def _shutdown(self, bye):
        if not self._closed:
            self._closed = True
            self.server.close(bye=bye)

    def close(self):
        self._shutdown(bye=False)


def make_client(served_or_addr, name=None, **kw):
    addr = getattr(served_or_addr, "address", served_or_addr)
    name = name or getattr(served_or_addr, "name", "r")
    kw.setdefault("backoff_initial_s", 0.01)
    kw.setdefault("backoff_max_s", 0.2)
    kw.setdefault("ping_every_s", 0.05)
    return SocketTransport(name, addr, **kw)


def wait_states(router, *, tries=2000):
    """Pump until every non-down view has a state heartbeat (placement
    over the wire needs free_blocks to have ARRIVED, where the hermetic
    fakes delivered it synchronously)."""
    for _ in range(tries):
        router.pump()
        if all(v.state is not None
               for v in router._views.values() if not v.down):
            return
        time.sleep(0.001)
    raise AssertionError("state heartbeats never arrived")


def sock_drive(router, served, *, clock=None, step=0.05, max_iters=4000,
               sleep_s=0.001, tick_every=1):
    """Pump router + tick served fakes until idle; optionally advance
    an injected router clock per iteration (the failure-detection
    ladder's deterministic driver).  ``tick_every`` throttles replica
    ticks to one per N iterations: on a slowed link every tick's state
    heartbeat costs a full proxy delay on the wire, so un-throttled
    ticking floods the session ring ahead of the token events and
    starves them behind hours of queued heartbeats."""
    for i in range(max_iters):
        router.pump()
        if router.idle():
            return
        if i % tick_every == 0:
            for s in served:
                s.tick()
        if clock is not None:
            clock[0] += step
        time.sleep(sleep_s)
    raise AssertionError(
        f"not idle after {max_iters} iters: "
        f"{[(r.rid, r.state) for r in router.requests.values() if not r.done]}")


def cleanup(router, served, proxies=()):
    router.close()
    for s in served:
        s.close()
    for p in proxies:
        p.close()


# ------------------------------------------------------ basic round trip


def test_socket_round_trip_token_identity():
    served = ServedFake("a")
    client = make_client(served)
    meta = client.wait_ready(timeout=30)
    assert meta["name"] == "a"
    router = make_router([client])
    try:
        wait_states(router, tries=4000)
        req = router.submit([3, 5, 7], 5)
        sock_drive(router, [served])
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([3, 5, 7], 5)
        assert served.fake.submissions[0][0] == req.rid
        # the command outbox drained through acks — nothing pending
        assert not client._outbox
    finally:
        cleanup(router, [served])


def test_socket_batched_submit_many():
    served = ServedFake("a", max_batch=8)
    client = make_client(served)
    client.wait_ready(timeout=30)
    router = make_router([client], replica_queue_limit=8)
    try:
        wait_states(router, tries=4000)
        prompts = [[3, 5, 7], [2, 4], [9, 9, 1], [6]]
        reqs = [router.submit(p, 4) for p in prompts]
        router.pump()                     # one pump seats all four
        sock_drive(router, [served])
        for req, p in zip(reqs, prompts):
            assert req.state is RequestState.FINISHED
            assert req.output_tokens == reference(p, 4)
        assert int(router.registry.counter(
            "fleet/batched_submits").value) >= 1
    finally:
        cleanup(router, [served])


def test_socket_link_rtt_measured():
    served = ServedFake("a")
    client = make_client(served, ping_every_s=0.02)
    client.wait_ready(timeout=30)
    deadline = time.monotonic() + 10
    while client.link_rtt_s is None and time.monotonic() < deadline:
        client.poll()
        time.sleep(0.005)
    assert client.link_rtt_s is not None and client.link_rtt_s < 5.0
    client.close()
    served.close()


# ------------------------------------------------- reconnect (churn)


def test_reconnect_churn_is_lossless_no_failover():
    """Connections severed at frame boundaries mid-stream: the session
    seq-replay resumes without losing an event — the stream is bitwise
    intact, ``fleet/reconnects`` counts, and NO failover fired."""
    served = ServedFake("a")
    proxy = ChaosProxy(served.address)
    client = make_client(proxy, name="a")
    client.wait_ready(timeout=30)
    router = make_router([client])
    try:
        wait_states(router, tries=4000)
        req = router.submit([9, 1, 4], 8)
        drops = 0
        for _ in range(6000):
            router.pump()
            if router.idle():
                break
            if client._hello_done and served.server._active is not None \
                    and len(req.output_tokens) == served.fake.tokens_emitted:
                # tick the replica only over a live session AND in
                # lockstep with delivery — BOTH ends' view: tokens
                # generated into a severed link pile up in the server
                # ring and the reconnect replays them as one burst that
                # can blow past the next drop window entirely (the
                # observed flake; the client alone is not enough — it
                # learns of the cut ~20ms after the server does).  The
                # lockstep clause keeps at most one token in flight, so
                # a single replay burst cannot finish the stream and
                # each drop window must earn its own reconnect.
                served.tick()
            if drops < 2 and len(req.output_tokens) >= 2 * (drops + 1):
                proxy.drop_connections()   # ≥4 tokens still outstanding
                drops += 1
            time.sleep(0.001)
        assert drops == 2, "churn never engaged mid-stream"
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([9, 1, 4], 8)
        assert client.reconnects >= drops
        snap = router.registry.snapshot()
        assert snap.get("fleet/reconnects") == float(client.reconnects)
        assert snap.get("fleet/failovers", 0.0) == 0.0
        assert client.frames_corrupt == 0
    finally:
        cleanup(router, [served], [proxy])


# ---------------------------------------- torn / corrupt frame verdicts


@pytest.mark.parametrize("fault,reason", [
    ("corrupt_next_frame", "corrupt"),
    ("tear_next_frame", "torn"),
])
def test_bad_frame_counted_and_classified_replica_failure(fault, reason):
    """A crc-corrupt or torn frame is NEVER deserialized: the client
    counts it (``frames_corrupt``), fails the replica, and the router
    recovers through the ordinary down-verdict → replay path — the
    stitched stream bitwise identical to the uninterrupted one."""
    victim = ServedFake("victim", free_blocks=1000)
    survivor = ServedFake("survivor", free_blocks=10)
    proxy = ChaosProxy(victim.address)
    c_victim = make_client(proxy, name="victim")
    c_survivor = make_client(survivor)
    for c in (c_victim, c_survivor):
        c.wait_ready(timeout=30)
    router = make_router([c_victim, c_survivor])
    try:
        wait_states(router, tries=4000)
        req = router.submit([9, 1, 4], 6)
        armed = False
        for _ in range(6000):
            router.pump()
            if router.idle():
                break
            for s in (victim, survivor):
                s.tick()
            if not armed and req.output_tokens:
                getattr(proxy, fault)()   # next replica→router frame
                armed = True
            time.sleep(0.001)
        assert armed, "fault never armed mid-stream"
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([9, 1, 4], 6)
        view = router._views["victim"]
        assert view.down and reason in view.down_reason
        assert c_victim.frames_corrupt == 1
        snap = router.registry.snapshot()
        assert snap.get("fleet/frames_corrupt") == 1.0
        assert snap.get("fleet/failovers") == 1.0
        assert req.replays == 1
    finally:
        cleanup(router, [victim, survivor], [proxy])


# ------------------------------------------------- partition / half-open


def test_partition_failover_replay_token_identity():
    """A partitioned replica goes silent; the heartbeat→probe ladder
    produces the down verdict and its in-flight requests replay on the
    survivor, streams bitwise intact."""
    clock = [0.0]
    victim = ServedFake("victim", free_blocks=1000)
    survivor = ServedFake("survivor", free_blocks=10)
    proxy = ChaosProxy(victim.address)
    c_victim = make_client(proxy, name="victim")
    c_survivor = make_client(survivor)
    for c in (c_victim, c_survivor):
        c.wait_ready(timeout=30)
    router = make_router(
        [c_victim, c_survivor], heartbeat_timeout_s=0.5,
        probe_retries=2, probe_backoff_s=0.1, clock=lambda: clock[0])
    try:
        wait_states(router, tries=4000)
        req = router.submit([9, 1, 4], 6)
        cut = False
        for _ in range(6000):
            router.pump()
            if router.idle():
                break
            for s in (victim, survivor):
                s.tick()
            if not cut and req.output_tokens:
                proxy.partition()         # total silence from here
                cut = True
            if cut:
                clock[0] += 0.05          # drive the detection ladder
            time.sleep(0.001)
        assert cut, "partition never engaged mid-stream"
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([9, 1, 4], 6)
        assert router._views["victim"].down
        assert router.registry.snapshot().get("fleet/failovers") == 1.0
    finally:
        cleanup(router, [victim, survivor], [proxy])


def test_half_open_link_recovers_on_survivor():
    """Accept-then-silence: reconnects complete TCP but the session
    hello never answers.  The client churns through it with backoff
    (bounded, never wedged) and the router's ladder fails the replica
    over — streams intact."""
    clock = [0.0]
    victim = ServedFake("victim", free_blocks=1000)
    survivor = ServedFake("survivor", free_blocks=10)
    proxy = ChaosProxy(victim.address)
    c_victim = make_client(proxy, name="victim", send_timeout_s=0.1)
    c_survivor = make_client(survivor)
    for c in (c_victim, c_survivor):
        c.wait_ready(timeout=30)
    router = make_router(
        [c_victim, c_survivor], heartbeat_timeout_s=0.5,
        probe_retries=2, probe_backoff_s=0.1, clock=lambda: clock[0])
    try:
        wait_states(router, tries=4000)
        req = router.submit([9, 1, 4], 6)
        cut = False
        for _ in range(6000):
            router.pump()
            if router.idle():
                break
            for s in (victim, survivor):
                s.tick()
            if not cut and req.output_tokens:
                proxy.half_open()         # future accepts: black hole
                proxy.drop_connections()  # force it onto them
                cut = True
            if cut:
                clock[0] += 0.05
            time.sleep(0.001)
        assert cut
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([9, 1, 4], 6)
        view = router._views["victim"]
        assert view.down and "missed heartbeat" in view.down_reason
    finally:
        cleanup(router, [victim, survivor], [proxy])


def test_all_unreachable_sheds_typed_rejected_after_deadline():
    """Graceful degradation, pinned with an injected clock: with every
    replica unreachable, pending requests wait a BOUNDED deadline —
    not forever, not zero — then shed in the typed REJECTED state."""
    clock = [0.0]
    served = ServedFake("a")
    proxy = ChaosProxy(served.address)
    client = make_client(proxy, name="a")
    client.wait_ready(timeout=30)
    router = make_router(
        [client], heartbeat_timeout_s=0.5, probe_retries=2,
        probe_backoff_s=0.1, dispatch_deadline_s=2.0,
        clock=lambda: clock[0])
    try:
        wait_states(router, tries=4000)
        req = router.submit([5, 5], 6)
        cut = False
        for _ in range(6000):
            router.pump()
            served.tick()
            if not cut and req.output_tokens:
                proxy.partition()
                cut = True
            if cut:
                clock[0] += 0.05
            if router._views["a"].down:
                break
            time.sleep(0.001)
        assert cut and router._views["a"].down
        # the replayed request waits — inside the deadline it is NOT
        # shed (a blip must not refuse work the fleet could still do)
        router.pump()
        start = clock[0]
        clock[0] = start + 1.0
        router.pump()
        assert req.state is RequestState.WAITING
        late = router.submit([1, 2], 3)   # joins the bounded wait
        # past the deadline: both shed with the typed terminal state
        clock[0] = start + 2.6
        router.pump()
        assert req.state is RequestState.REJECTED
        assert late.state is RequestState.REJECTED
        snap = router.registry.snapshot()
        assert snap.get("serving/requests_rejected") == 2.0
        assert router.idle()
        # the stream API surfaces the shed as a clean close, not a hang
        assert list(router.stream(late, poll_s=0)) == []
    finally:
        cleanup(router, [served], [proxy])


# ------------------------------------------------------- slow link


def test_slow_link_demoted_in_placement_not_failed():
    """A degraded link (RTT past ``link_degraded_rtt_s``) loses
    placement even against better pool shape — but is NOT failed: no
    failover, not down, still visible in introspect with its RTT."""
    slow = ServedFake("slow", free_blocks=1000)
    fast = ServedFake("fast", free_blocks=10)
    proxy = ChaosProxy(slow.address)
    c_slow = make_client(proxy, name="slow", ping_every_s=0.05)
    c_fast = make_client(fast)
    for c in (c_slow, c_fast):
        c.wait_ready(timeout=30)
    router = make_router([c_slow, c_fast], link_degraded_rtt_s=0.1)
    try:
        wait_states(router, tries=4000)
        proxy.slow(0.2)                   # one-way per frame ≈ 0.4s RTT
        deadline = time.monotonic() + 15
        while not router._views["slow"].link_degraded and \
                time.monotonic() < deadline:
            router.pump()
            time.sleep(0.01)
        view = router._views["slow"]
        assert view.link_degraded and view.link_rtt_s > 0.1
        # demoted: the fast link wins despite 100x fewer free blocks
        req = router.submit([4, 2], 3)
        sock_drive(router, [slow, fast])
        assert req.replica == "fast"
        assert req.output_tokens == reference([4, 2], 3)
        # ...but never hard-failed
        assert not view.down
        snap = router.registry.snapshot()
        assert snap.get("fleet/failovers", 0.0) == 0.0
        assert snap.get("fleet/link_degraded") == 1.0
        intro = router.introspect()["replicas"]["slow"]
        assert intro["link_degraded"] is True
        assert intro["link_rtt_ms"] > 100.0
    finally:
        cleanup(router, [slow, fast], [proxy])


def test_sole_slow_replica_still_serves():
    """Demotion is a preference, not an exclusion: a fleet whose only
    replica has a degraded link still serves every request."""
    served = ServedFake("a")
    proxy = ChaosProxy(served.address)
    client = make_client(proxy, name="a", ping_every_s=0.05)
    client.wait_ready(timeout=30)
    router = make_router([client], link_degraded_rtt_s=0.05)
    try:
        wait_states(router, tries=4000)
        proxy.slow(0.1)
        deadline = time.monotonic() + 15
        while not router._views["a"].link_degraded and \
                time.monotonic() < deadline:
            router.pump()
            time.sleep(0.01)
        assert router._views["a"].link_degraded
        req = router.submit([7, 7], 2)
        # throttle ticks well below the 10-frames/s drain the 0.1s link
        # sustains, or per-tick state heartbeats flood the ring ahead
        # of the token events (see sock_drive)
        sock_drive(router, [served], max_iters=8000, sleep_s=0.02,
                   tick_every=10)
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([7, 7], 2)
    finally:
        cleanup(router, [served], [proxy])


# ------------------------------------- the PR 10 matrix over the socket


@pytest.mark.parametrize("k", [0, 1, 3, 6])   # 0, 1, mid, last
def test_socket_failover_replay_kill_at_k(k):
    """The PR 10 kill-at-k bitwise-replay matrix, through the socket
    transport: the replica host dies (server gone, connects refused),
    the ladder detects, the stitched stream equals the uninterrupted
    reference bitwise."""
    clock = [0.0]
    n_new, prompt = 6, [9, 1, 4]
    victim = ServedFake("victim", free_blocks=1000, die_after_tokens=k)
    survivor = ServedFake("survivor", free_blocks=10)
    c_victim = make_client(victim)
    c_survivor = make_client(survivor)
    for c in (c_victim, c_survivor):
        c.wait_ready(timeout=30)
    router = make_router(
        [c_victim, c_survivor], heartbeat_timeout_s=0.5,
        probe_retries=2, probe_backoff_s=0.1, clock=lambda: clock[0])
    try:
        wait_states(router, tries=4000)
        req = router.submit(prompt, n_new)
        sock_drive(router, [victim, survivor], clock=clock)
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference(prompt, n_new)
        assert req.replays == (0 if k >= n_new else 1)
        if 0 < k < n_new:
            frid, wire_prompt, wire_budget, _, _ = \
                survivor.fake.submissions[0]
            assert frid == req.rid
            assert wire_prompt == prompt + reference(prompt, k)
            assert wire_budget == n_new - k
    finally:
        cleanup(router, [victim, survivor])


def test_socket_flood_sheds_typed_and_admitted_finish():
    served = ServedFake("a", max_batch=1)
    client = make_client(served)
    client.wait_ready(timeout=30)
    router = make_router([client], max_queue_depth=3,
                         replica_queue_limit=1)
    try:
        wait_states(router, tries=4000)
        reqs = [router.submit([1], 4) for _ in range(6)]
        shed = [r for r in reqs if r.state is RequestState.REJECTED]
        kept = [r for r in reqs if r.state is not RequestState.REJECTED]
        assert len(shed) == 3 and len(kept) == 3
        assert router.registry.snapshot()[
            "serving/requests_rejected"] == 3.0
        sock_drive(router, [served])
        for r in kept:
            assert r.state is RequestState.FINISHED
            assert r.output_tokens == reference([1], 4)
    finally:
        cleanup(router, [served])


def test_socket_rollout_drains_over_the_wire():
    """Zero-downtime rollout cross-host: ``begin_drain`` rides the wire
    (no SIGTERM reaches a remote host), the drained replica says
    goodbye (``bye`` → ``alive() == False``), the replacement joins
    over a fresh connection, nothing is lost."""
    a = ServedFake("a", free_blocks=1000, max_batch=1)
    b = ServedFake("b", free_blocks=10, max_batch=1)
    c_a = make_client(a)
    c_b = make_client(b)
    for c in (c_a, c_b):
        c.wait_ready(timeout=30)
    router = make_router([c_a, c_b], replica_queue_limit=4)
    served = [a, b]
    try:
        wait_states(router, tries=4000)
        reqs = [router.submit([i + 1], 3) for i in range(4)]
        router.pump()

        def factory(name):
            rep = ServedFake(name, free_blocks=1000, max_batch=1)
            served.append(rep)
            return make_client(rep)

        def on_tick():
            for rep in served:
                rep.tick()

        rolled = router.rollout(factory, names=["a"], on_tick=on_tick,
                                drain_timeout_s=30, ready_timeout_s=30)
        assert rolled == ["a"]
        assert not c_a.alive()            # bye honoured: clean exit
        sock_drive(router, served)
        for i, req in enumerate(reqs):
            assert req.state is RequestState.FINISHED, (req.rid, req.state)
            assert req.output_tokens == reference([i + 1], 3)
        snap = router.registry.snapshot()
        assert snap["fleet/rollouts"] == 1.0
        assert snap.get("serving/requests_rejected", 0.0) == 0.0
    finally:
        cleanup(router, served)


@pytest.mark.parametrize("survivor_fault", ["slow", "churn"])
def test_kill_failover_composes_with_faulty_survivor_wire(survivor_fault):
    """Fault classes compose: the victim dies mid-decode while the
    SURVIVOR's own wire is degraded (slow link) or churning
    (reconnect drops) — the replay still lands and the stitched stream
    is bitwise the uninterrupted reference."""
    clock = [0.0]
    n_new, prompt = 6, [9, 1, 4]
    victim = ServedFake("victim", free_blocks=1000, die_after_tokens=3)
    survivor = ServedFake("survivor", free_blocks=10)
    proxy = ChaosProxy(survivor.address)
    c_victim = make_client(victim)
    c_survivor = make_client(proxy, name="survivor")
    for c in (c_victim, c_survivor):
        c.wait_ready(timeout=30)
    router = make_router(
        [c_victim, c_survivor], heartbeat_timeout_s=2.0,
        probe_retries=2, probe_backoff_s=0.1, clock=lambda: clock[0])
    try:
        wait_states(router, tries=4000)
        if survivor_fault == "slow":
            proxy.slow(0.02)
        req = router.submit(prompt, n_new)
        since_drop = 0
        for _ in range(8000):
            router.pump()
            if router.idle():
                break
            for s in (victim, survivor):
                s.tick()
            clock[0] += 0.05
            since_drop += 1
            if survivor_fault == "churn" and since_drop >= 50:
                proxy.drop_connections(wait_s=1.0)
                since_drop = 0
            time.sleep(0.001)
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference(prompt, n_new)
        assert req.replays == 1
        assert not router._views["survivor"].down
    finally:
        cleanup(router, [victim, survivor], [proxy])


# ------------------------------------------- client-side bounds


def test_outbox_backpressure_raises_bounded():
    """The send queue is bounded: past ``max_outbox`` unacked commands,
    submit raises — the router's dead-pipe class — instead of buffering
    without bound into a partition."""
    client = SocketTransport("a", ("127.0.0.1", 1), max_outbox=4,
                             backoff_initial_s=10.0)   # never connects
    for i in range(4):
        client.submit(i, [1, 2], 4)
    with pytest.raises(TransportError, match="backpressure"):
        client.submit(99, [1, 2], 4)
    client.close()


def test_send_timeout_raises_when_wire_wedges(monkeypatch):
    """A connected-but-not-reading peer (zero-window stall) trips the
    per-command send deadline on the injected clock instead of wedging
    the router's pump forever."""
    clock = [0.0]
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16384)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    held = []

    def acceptor():
        conn, _ = lsock.accept()
        held.append(conn)
        dec = FrameDecoder()
        while True:                       # answer the hello, then stall
            msgs = dec.feed(conn.recv(4096))
            if any(m[0] == "hello" for m in msgs):
                conn.sendall(encode_frame(("hello", 0, False, 0)))
                return                    # never reads again

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()
    real_finish = SocketTransport._finish_connect

    def small_buf_finish(self, sock, now):
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 8192)
        real_finish(self, sock, now)

    monkeypatch.setattr(SocketTransport, "_finish_connect",
                        small_buf_finish)
    client = SocketTransport("a", lsock.getsockname(),
                             send_timeout_s=0.5, ping_every_s=1e9,
                             clock=lambda: clock[0])
    try:
        deadline = time.monotonic() + 10
        while not client._hello_done and time.monotonic() < deadline:
            client.poll()
            time.sleep(0.005)
        assert client._hello_done
        client.submit(1, [7] * 500_000, 4)   # ~MBs: wedges the wire
        clock[0] += 1.0
        with pytest.raises(TransportError, match="send timeout"):
            for _ in range(200):
                client.poll()
                time.sleep(0.005)
    finally:
        client.close()
        for c in held:
            c.close()
        lsock.close()


def test_fresh_router_reattaches_to_long_lived_daemon():
    """A restarted router — a brand-new client session against a
    long-lived daemon — must neither be black-holed by the OLD
    session's command-dedupe watermark nor reset by an event ring that
    no longer reaches back to seq 0: the fresh hello resets the
    server's command-dedupe watermark, fast-forwards the client's event
    cursor, and re-emits the sticky ready/state, so the new router
    serves immediately."""
    served = ServedFake("a", event_ring=4)   # seq-0 history long gone
    c1 = make_client(served)
    c1.wait_ready(timeout=30)
    router1 = make_router([c1])
    try:
        wait_states(router1, tries=4000)
        req1 = router1.submit([3, 5, 7], 5)
        sock_drive(router1, [served])
        assert req1.output_tokens == reference([3, 5, 7], 5)
        c1._close_socks()                 # router host dies, no goodbye
        c2 = make_client(served, name="a")
        meta = c2.wait_ready(timeout=30)  # sticky ready re-emitted
        assert meta["name"] == "a"
        router2 = make_router([c2])
        wait_states(router2, tries=4000)  # sticky state re-emitted
        req2 = router2.submit([2, 4], 3)
        sock_drive(router2, [served])
        assert req2.state is RequestState.FINISHED
        assert req2.output_tokens == reference([2, 4], 3)
        assert c2.frames_corrupt == 0 and c2.alive()
        cleanup(router2, [])
    finally:
        cleanup(router1, [served])


# ------------------------------------------- server-side bounds


def test_server_mark_sent_tracks_frame_boundaries():
    """The server's partial-send bookkeeping: ``head_rem`` counts the
    un-flushed remainder of a half-sent head frame, and returns to 0
    exactly at frame boundaries — the only points where a deliberate
    stall-drop is allowed to sever the connection."""
    from apex_tpu.serving.transport import TransportServer, _ServerConn

    conn = _ServerConn(1 << 20)
    f1, f2 = encode_frame(("a", 1)), encode_frame(("bb", [2, 3, 4]))
    conn.out.extend(f1)
    conn.out.extend(f2)
    TransportServer._mark_sent(conn, 5)            # mid-f1
    assert conn.head_rem == len(f1) - 5
    del conn.out[:5]
    TransportServer._mark_sent(conn, conn.head_rem)  # f1 boundary
    del conn.out[:len(f1) - 5]
    assert conn.head_rem == 0
    TransportServer._mark_sent(conn, len(f2))      # whole f2 in one go
    del conn.out[:len(f2)]
    assert conn.head_rem == 0 and not conn.out
    # spanning a boundary in one send: finish nothing, start f2 mid-way
    conn.out.extend(f1)
    conn.out.extend(f2)
    TransportServer._mark_sent(conn, len(f1) + 3)
    assert conn.head_rem == len(f2) - 3


def test_stalled_connection_drop_severs_at_frame_boundary():
    """A live-but-stalled peer is dropped once its un-flushed backlog
    passes ``max_buffered_bytes`` — but the sever must land on a frame
    boundary: every byte the peer DID receive parses as whole frames,
    so the client classifies the cut as a connection loss (lossless
    seq-replay reconnect), never as a torn frame / corruption."""
    cmd_q, evt_q = queue.Queue(), queue.Queue()
    server = TransportServer(cmd_q, evt_q, max_buffered_bytes=4096)
    sock = None
    try:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # tiny receive window: the server's sends back up quickly
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        sock.settimeout(10)
        sock.connect(server.address)
        sock.sendall(encode_frame(("hello", 0, 0, True)))
        dec = FrameDecoder()
        got = []
        while not got:                    # read the hello reply only
            got.extend(dec.feed(sock.recv(4096)))
        assert got[0][0] == "hello"
        # flood far past every kernel buffer while never reading: the
        # server must stall-drop this connection
        big_evt = ("token", 0, list(range(1024)))
        for _ in range(4000):             # ~16 MB of frames
            evt_q.put(big_evt)
        saw_eof = False
        try:
            while True:                   # drain what was delivered
                data = sock.recv(65536)
                if data == b"":
                    saw_eof = True
                    break
                dec.feed(data)
        except OSError:
            saw_eof = True                # reset also ends the stream
        assert saw_eof, "server never dropped the stalled connection"
        assert not dec.partial, \
            "stall-drop severed mid-frame: the client would count " \
            "frames_corrupt for a wire that was never corrupted"
    finally:
        if sock is not None:
            sock.close()
        server.close(bye=False, timeout=1.0)


# ------------- ISSUE 16: KV migration over the wire, under ChaosProxy


def _disagg_served(proxy_on=None, **router_kw):
    """1-prefill/1-decode fleet over real sockets; optionally a
    ChaosProxy on one replica's link ("p" or "d").  Returns
    (p, d, proxy, clients, router)."""
    p = ServedFake("p", meta={"role": "prefill"}, free_blocks=1000)
    d = ServedFake("d", meta={"role": "decode"}, free_blocks=1000)
    proxy = None
    endpoints = {"p": p, "d": d}
    clients = []
    for name, s in endpoints.items():
        target = s
        if proxy_on == name:
            proxy = ChaosProxy(s.address)
            target = proxy
        c = make_client(target, name=name)
        c.wait_ready(timeout=30)
        clients.append(c)
    router = make_router(clients, **router_kw)
    wait_states(router, tries=4000)
    return p, d, proxy, clients, router


def test_socket_disagg_migration_identity_and_counters():
    """The tentpole over the real wire, fault-free: greedy AND seeded
    streams prefill on ``p``, migrate block-by-block through the framed
    transport, finish on ``d`` — bitwise the uninterrupted streams."""
    from apex_tpu.serving import SamplingParams

    sp = SamplingParams(temperature=0.8, seed=5)
    p, d, proxy, clients, router = _disagg_served()
    try:
        # long streams: over the real wire tokens surface in relay
        # bursts, so the trigger can fire several tokens in — the
        # budget must comfortably outlast it
        r1 = router.submit([9, 1, 4], 16)
        r2 = router.submit([3, 5], 12, sampling=sp)
        sock_drive(router, [p, d], max_iters=8000)
        assert r1.state is RequestState.FINISHED
        assert r1.output_tokens == reference([9, 1, 4], 16)
        from test_fleet import seeded_reference
        assert r2.state is RequestState.FINISHED
        assert r2.output_tokens == seeded_reference([3, 5], 12, sp)
        assert r1.replica == "d" and r2.replica == "d"
        assert d.fake.imports_committed == 2
        for _ in range(200):               # let the kv_ack land on p
            router.pump()
            p.tick()
            if p.fake.exports == {} and len(p.fake.export_acks) == 2:
                break
            time.sleep(0.001)
        assert p.fake.exports == {}
        assert sorted(ok for _, ok in p.fake.export_acks) == [True, True]
        snap = router.registry.snapshot()
        assert snap.get("fleet/kv_migrate_started") == 2.0
        assert snap.get("fleet/kv_migrate_completed") == 2.0
        assert snap.get("fleet/kv_migrate_failed", 0.0) == 0.0
        assert snap.get("fleet/kv_migrate_blocks", 0.0) >= 2.0
        assert snap.get("fleet/kv_migrate_bytes", 0.0) >= 2.0
        assert snap.get("fleet/failovers", 0.0) == 0.0
        body = router.fleet_statusz()
        assert set(body["roles"]) == {"prefill", "decode"}
        assert body["migrations"]["completed"] == 2
    finally:
        cleanup(router, [p, d], [proxy] if proxy else [])


def test_migration_link_partition_degrades_to_replay():
    """Partition on the decode replica's link mid-handoff: the import
    verdict can never arrive, the probe ladder downs the destination,
    and the request re-prefills on the source — bitwise intact."""
    clock = [0.0]
    p, d, proxy, clients, router = _disagg_served(
        proxy_on="d", heartbeat_timeout_s=0.5, probe_retries=2,
        probe_backoff_s=0.1, clock=lambda: clock[0])
    try:
        req = router.submit([9, 1, 4], 16)
        cut = False
        for _ in range(8000):
            router.pump()
            if router.idle():
                break
            for s in (p, d):
                s.tick()
            if not cut and req.output_tokens:
                proxy.partition()          # silence before the verdict
                cut = True
            if cut:
                clock[0] += 0.05           # drive the detection ladder
            time.sleep(0.001)
        assert cut, "partition never engaged mid-stream"
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([9, 1, 4], 16)
        assert req.replica == "p"
        assert router._views["d"].down
        assert router._migrations == {}
        snap = router.registry.snapshot()
        assert snap.get("fleet/kv_migrate_started", 0.0) >= 1.0
        assert snap.get("fleet/kv_migrate_completed", 0.0) == 0.0
        assert snap.get("fleet/kv_migrate_failed", 0.0) >= 1.0
        # the source's pin released into its prefix cache (not-ok ack)
        assert p.fake.exports == {}
    finally:
        cleanup(router, [p, d], [proxy])


def test_migration_survives_dst_reconnect_churn():
    """Per-block resumability: the connection to the decode replica is
    severed while the block stream is in flight — the session outbox
    resends exactly the unacked tail on reconnect, the import commits,
    and no re-prefill ever fires."""
    p, d, proxy, clients, router = _disagg_served(proxy_on="d")
    c_d = clients[1]
    try:
        req = router.submit([9, 1, 4, 2, 6, 8, 1, 3], 16)
        dropped = False
        for _ in range(8000):
            router.pump()
            if router.idle():
                break
            if not dropped and router._migrations:
                proxy.drop_connections()   # blocks mid-flight
                dropped = True
            for s in (p, d):
                s.tick()
            time.sleep(0.001)
        assert dropped, "migration never started"
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([9, 1, 4, 2, 6, 8, 1, 3], 16)
        assert req.replica == "d"
        assert d.fake.imports_committed == 1
        assert c_d.reconnects >= 1
        snap = router.registry.snapshot()
        assert snap.get("fleet/kv_migrate_completed") == 1.0
        assert snap.get("fleet/kv_migrate_failed", 0.0) == 0.0
        assert snap.get("fleet/failovers", 0.0) == 0.0
    finally:
        cleanup(router, [p, d], [proxy])


def test_migration_torn_frame_degrades_to_replay():
    """A frame torn mid-migration on the source's event leg: the
    decoder refuses the partial frame, the source verdicts, and the
    stream recovers through the ordinary replay — never a corrupt
    cache, never a divergent token."""
    clock = [0.0]
    p, d, proxy, clients, router = _disagg_served(
        proxy_on="p", heartbeat_timeout_s=0.5, probe_retries=2,
        probe_backoff_s=0.1, clock=lambda: clock[0])
    try:
        req = router.submit([9, 1, 4], 16)
        armed = False
        for _ in range(8000):
            router.pump()
            if router.idle():
                break
            if not armed and router._migrations:
                proxy.tear_next_frame()    # tears meta/block mid-export
                armed = True
            for s in (p, d):
                s.tick()
            if armed:
                clock[0] += 0.05
            time.sleep(0.001)
        assert armed, "migration never started"
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([9, 1, 4], 16)
        assert router._migrations == {}
        snap = router.registry.snapshot()
        started = snap.get("fleet/kv_migrate_started", 0.0)
        done = snap.get("fleet/kv_migrate_completed", 0.0)
        failed = snap.get("fleet/kv_migrate_failed", 0.0)
        assert started >= 1.0 and started == done + failed
    finally:
        cleanup(router, [p, d], [proxy])


def test_migration_slow_link_still_completes():
    """A slowed (not dead) migration link: the handoff takes longer but
    completes — block frames trickle through, the commit lands, and
    the stream stays bitwise identical."""
    p, d, proxy, clients, router = _disagg_served(proxy_on="d")
    try:
        proxy.slow(0.02)
        req = router.submit([9, 1, 4], 16)
        sock_drive(router, [p, d], max_iters=8000, sleep_s=0.02,
                   tick_every=2)
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([9, 1, 4], 16)
        assert req.replica == "d"
        snap = router.registry.snapshot()
        assert snap.get("fleet/kv_migrate_completed") == 1.0
        assert snap.get("fleet/kv_migrate_failed", 0.0) == 0.0
    finally:
        cleanup(router, [p, d], [proxy])


def test_migration_dst_sigkill_mid_migration_reprefills():
    """Decode-replica SIGKILL with the handoff in flight: the crash
    shape (no bye), the ladder downs it, and the request re-prefills on
    the surviving prefill replica — the ISSUE 16 torn-transfer
    contract: degrade to re-prefill, never a corrupt cache."""
    clock = [0.0]
    p, d, proxy, clients, router = _disagg_served(
        heartbeat_timeout_s=0.5, probe_retries=2,
        probe_backoff_s=0.1, clock=lambda: clock[0])
    try:
        req = router.submit([9, 1, 4], 16)
        killed = False
        for _ in range(8000):
            router.pump()
            if router.idle():
                break
            if not killed and router._migrations:
                d.kill()                   # SIGKILL shape: no goodbye
                killed = True
            for s in (p, d):
                s.tick()
            if killed:
                clock[0] += 0.05
            time.sleep(0.001)
        assert killed, "migration never started"
        assert req.state is RequestState.FINISHED
        assert req.output_tokens == reference([9, 1, 4], 16)
        assert req.replica == "p"
        assert router._views["d"].down
        assert router._migrations == {}
        snap = router.registry.snapshot()
        assert snap.get("fleet/kv_migrate_completed", 0.0) == 0.0
        assert snap.get("fleet/kv_migrate_failed", 0.0) >= 1.0
        assert p.fake.exports == {}        # pin released on resolve
    finally:
        cleanup(router, [p, d], [])
