"""Standalone GPT/BERT end-to-end tests.

Mirrors the reference's ``tests/L0/run_transformer/test_gpt_minimal.py`` /
``test_bert_minimal.py`` (loss-decrease runs of the standalone models across
parallel grids) plus targeted numerics for the new transformer modules.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.parallel import collectives as cc
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import pipeline_parallel as pp_lib
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.transformer.amp import GradScaler
from apex_tpu.transformer.testing import (
    BertModel,
    GPTModel,
    TransformerConfig,
    init_gpt_layer_stack,
)

pytestmark = pytest.mark.slow

VOCAB = 64
SEQ = 16
BATCH = 4


def small_cfg(**kw):
    base = dict(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=VOCAB, max_position_embeddings=SEQ,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
    )
    base.update(kw)
    return TransformerConfig(**base)


def lm_batch(key):
    return jax.random.randint(key, (BATCH, SEQ), 0, VOCAB)


def test_gpt_single_device_trains():
    cfg = small_cfg()
    model = GPTModel(cfg)
    tokens = lm_batch(jax.random.PRNGKey(0))
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            losses = model.apply({"params": p}, tokens, labels=tokens)
            return jnp.mean(losses)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.step(grads, state, params)
        return params, state, loss

    losses = []
    for _ in range(30):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[0] > losses[-1]
    assert losses[-1] < 0.8 * losses[0]


def test_gpt_logits_shape_and_finite():
    cfg = small_cfg()
    model = GPTModel(cfg)
    tokens = lm_batch(jax.random.PRNGKey(2))
    params = model.init(jax.random.PRNGKey(3), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (SEQ, BATCH, VOCAB)
    assert bool(jnp.all(jnp.isfinite(logits)))


@pytest.mark.parametrize("sp", [False, True])
def test_gpt_tensor_parallel_trains(sp):
    """tp=8 (optionally sequence-parallel) GPT under shard_map with honest
    param specs (tensor_parallel/partition.py): the loss matches the
    single-device model run on the *same global parameters* exactly, and
    training decreases it."""
    TP = 8
    parallel.initialize_model_parallel(tensor_model_parallel_size=TP)
    cfg = small_cfg(tensor_axis="tp", sequence_parallel=sp,
                    num_attention_heads=8)
    model = GPTModel(cfg)
    tokens = lm_batch(jax.random.PRNGKey(4))

    def tp_init(tokens):
        return model.init(jax.random.PRNGKey(5), tokens)["params"]

    param_specs = tp.infer_param_specs(
        jax.eval_shape(tp_init, tokens)
    )
    params = cc.shard_over(tp_init, in_specs=P(),
                           out_specs=param_specs)(tokens)

    def tp_loss(p, tokens):
        losses = model.apply({"params": p}, tokens, labels=tokens)
        return jax.lax.pmean(jnp.mean(losses), "tp")

    loss_f = cc.shard_over(tp_loss, in_specs=(param_specs, P()),
                           out_specs=P())
    loss0 = float(loss_f(params, tokens))

    # Exact parity: the honest-spec global params feed the tp=1 model as-is.
    model1 = GPTModel(small_cfg(num_attention_heads=8))
    losses1 = model1.apply({"params": jax.device_get(params)}, tokens,
                           labels=tokens)
    np.testing.assert_allclose(loss0, float(jnp.mean(losses1)), rtol=1e-5)
    assert abs(loss0 - np.log(VOCAB)) < 1.0  # ~ln(V) at random init

    opt = FusedAdam(lr=1e-3)
    # Optimizer slots mirror the param tree, so they inherit the param
    # specs; the step counter replicates (OptState, optimizers/_common.py:143).
    state0 = jax.eval_shape(opt.init, params)
    state_specs = type(state0)(
        step=P(),
        slots={k: param_specs for k in state0.slots},
        master=param_specs if state0.master is not None else None,
    )

    @jax.jit
    def step(params, state, tokens):
        def local(p, s, t):
            g = jax.grad(tp_loss)(p, t)
            new_p, new_s = opt.step(g, s, p)
            return new_p, new_s, tp_loss(p, t)
        return cc.shard_over(
            local,
            in_specs=(param_specs, state_specs, P()),
            out_specs=(param_specs, state_specs, P()),
        )(params, state, tokens)

    state = cc.shard_over(
        opt.init, in_specs=(param_specs,), out_specs=state_specs
    )(params)
    losses = []
    for _ in range(10):
        params, state, loss = step(params, state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_gpt_pipelined_layer_stack_matches_sequential():
    """pp=4 rotation over the GPT layer stack == sequential layer loop."""
    PP = 4
    parallel.initialize_model_parallel(pipeline_model_parallel_size=PP)
    cfg = small_cfg(num_layers=PP)
    hidden = jax.random.normal(jax.random.PRNGKey(6), (SEQ, BATCH,
                                                       cfg.hidden_size))
    make_stage_fn, per_layer = init_gpt_layer_stack(
        jax.random.PRNGKey(7), cfg, hidden
    )
    stage_fn = make_stage_fn()
    stacked = pp_lib.stack_stage_params(per_layer)

    m = 4
    x_mb = jax.random.normal(jax.random.PRNGKey(8),
                             (m, SEQ, BATCH, cfg.hidden_size))
    outs = pp_lib.pipeline_apply(stage_fn, stacked, x_mb)

    ref = []
    for i in range(m):
        h = x_mb[i]
        for p in per_layer:
            h = stage_fn(p, h)
        ref.append(h)
    np.testing.assert_allclose(np.asarray(outs), np.asarray(jnp.stack(ref)),
                               rtol=2e-5, atol=2e-5)


def test_bert_forward():
    cfg = small_cfg()
    model = BertModel(cfg)
    tokens = lm_batch(jax.random.PRNGKey(9))
    mask = jnp.ones((BATCH, SEQ), jnp.int32).at[:, -4:].set(0)
    params = model.init(jax.random.PRNGKey(10), tokens, mask)["params"]
    lm_logits, binary_logits = model.apply({"params": params}, tokens, mask)
    assert lm_logits.shape == (SEQ, BATCH, VOCAB)
    assert binary_logits.shape == (BATCH, 2)
    assert bool(jnp.all(jnp.isfinite(lm_logits)))


def test_grad_scaler_model_parallel_agreement():
    """grad_scaler.py:44-55 — one rank's overflow must skip every rank."""
    parallel.initialize_model_parallel(tensor_model_parallel_size=8)
    scaler = GradScaler(model_parallel_axes=("tp",))

    def local(x):
        r = cc.axis_index("tp")
        g = jnp.where(r == 3, jnp.inf, 1.0) * x
        return scaler.all_finite({"g": g}).reshape(1)

    finite = cc.shard_over(local, in_specs=P(), out_specs=P("tp"))(
        jnp.ones((8,))
    )
    assert not bool(np.asarray(finite).any())

    def local_ok(x):
        return scaler.all_finite({"g": x}).reshape(1)

    finite = cc.shard_over(local_ok, in_specs=P("tp"), out_specs=P("tp"))(
        jnp.ones((8,))
    )
    assert bool(np.asarray(finite).all())

    # update math identical to base DynamicLossScale
    st = scaler.init()
    st2 = scaler.update(st, jnp.asarray(False))
    assert float(st2.scale) == float(st.scale)  # hysteresis=2 absorbs first
    st3 = scaler.update(st2, jnp.asarray(False))
    assert float(st3.scale) == float(st.scale) / 2


def test_reference_import_paths():
    """Migrated apex imports must resolve."""
    from apex_tpu.transformer import get_forward_backward_func  # noqa: F401
    from apex_tpu.transformer.functional import FusedScaleMaskSoftmax  # noqa
    from apex_tpu.transformer.enums import (  # noqa: F401
        AttnMaskType, AttnType, LayerType, ModelType,
    )
    from apex_tpu.transformer.layers import FusedLayerNorm  # noqa: F401
    from apex_tpu.transformer.tensor_parallel import (  # noqa: F401
        infer_param_specs,
    )
    from apex_tpu.transformer.amp import GradScaler  # noqa: F401


def test_bert_flash_padding_matches_fused_softmax():
    """BERT's padding mask expressed as flash segment ids must reproduce
    the fused-softmax path's logits at every real (non-pad) position.
    Pad positions legitimately differ (fully-masked rows: the fused
    softmax yields a uniform mix, flash yields a pad-only mix; both are
    ignored downstream), so the comparison masks them out."""
    cfg = small_cfg(apply_query_key_layer_scaling=False)
    cfg_flash = small_cfg(apply_query_key_layer_scaling=False,
                          use_flash_attention=True)
    tokens = lm_batch(jax.random.PRNGKey(9))
    mask = jnp.ones((BATCH, SEQ), jnp.int32).at[:2, -5:].set(0)

    model = BertModel(cfg)
    params = model.init(jax.random.PRNGKey(10), tokens, mask)["params"]
    ref_lm, ref_bin = model.apply({"params": params}, tokens, mask)
    flash_lm, flash_bin = BertModel(cfg_flash).apply(
        {"params": params}, tokens, mask)

    real = np.asarray(mask, bool).T[:, :, None]  # [s, b, 1]
    np.testing.assert_allclose(
        np.asarray(flash_lm) * real, np.asarray(ref_lm) * real,
        rtol=2e-5, atol=2e-5,
    )
    # pooled/binary head reads sequence position 0 (always real here)
    np.testing.assert_allclose(np.asarray(flash_bin), np.asarray(ref_bin),
                               rtol=2e-5, atol=2e-5)
