"""log_util accessor surface (ISSUE 5 satellite).

``set_logging_level`` used to set only the ``apex_tpu`` *logger* level;
a handler sitting at a higher level kept filtering records the logger —
or a louder child logger — was configured to emit.  These tests pin the
fixed contract: the logger level is the one knob, handlers never
out-filter it, and every record that reaches the stream carries the
rank stamp.
"""

import io
import logging

import apex_tpu  # installs the rank-stamped handler
from apex_tpu import log_util


def _capture_handler():
    """Swap the library handler's stream for a StringIO we can read."""
    logger = logging.getLogger("apex_tpu")
    assert logger.handlers, "apex_tpu import must install a handler"
    handler = logger.handlers[0]
    buf = io.StringIO()
    old_stream = handler.stream
    handler.stream = buf
    return logger, handler, buf, old_stream


def _restore(handler, old_stream, old_logger_level, old_handler_level):
    handler.stream = old_stream
    logging.getLogger("apex_tpu").setLevel(old_logger_level)
    handler.setLevel(old_handler_level)


def test_rank_stamped_formatting():
    logger, handler, buf, old_stream = _capture_handler()
    old_levels = (logger.level, handler.level)
    try:
        log_util.set_logging_level(logging.INFO)
        log_util.get_logger().info("hello from the library")
        out = buf.getvalue()
        assert "hello from the library" in out
        # Single-process test run: process 0 of 1 (RankInfoFormatter).
        assert "[0/1]" in out
        assert "apex_tpu" in out
    finally:
        _restore(handler, old_stream, *old_levels)


def test_set_logging_level_propagates_to_handler():
    """The regression this satellite fixes: a handler level left above
    the logger level silently filtered everything below it."""
    logger, handler, buf, old_stream = _capture_handler()
    old_levels = (logger.level, handler.level)
    try:
        # Simulate the broken state: handler stuck at WARNING.
        handler.setLevel(logging.WARNING)
        log_util.set_logging_level(logging.DEBUG)
        log_util.get_logger().debug("debug must now flow")
        assert "debug must now flow" in buf.getvalue(), (
            "set_logging_level must lower the handler gate too")
        assert handler.level <= logging.DEBUG
    finally:
        _restore(handler, old_stream, *old_levels)


def test_child_logger_louder_than_library_is_not_filtered():
    """A child set to DEBUG while the library sits at INFO must emit:
    the handler (the library's single emission point) may not re-filter
    what the child logger explicitly allowed."""
    logger, handler, buf, old_stream = _capture_handler()
    old_levels = (logger.level, handler.level)
    child = log_util.get_transformer_logger("apex_tpu.transformer.moe")
    old_child_level = child.level
    try:
        handler.setLevel(logging.WARNING)  # stale tighter handler
        log_util.set_logging_level(logging.INFO)
        child.setLevel(logging.DEBUG)
        child.debug("child debug record")
        assert "child debug record" in buf.getvalue()
        # And the library level still gates the non-overridden loggers.
        buf.truncate(0), buf.seek(0)
        log_util.get_logger().debug("library debug record")
        assert "library debug record" not in buf.getvalue()
    finally:
        child.setLevel(old_child_level)
        _restore(handler, old_stream, *old_levels)


def test_get_transformer_logger_name_normalization():
    # Filename form: the extension is stripped (reference
    # ``log_util.py`` passes ``os.path.splitext(name)[0]``).
    assert log_util.get_transformer_logger(
        "my_module.py").name == "apex_tpu.my_module"
    # Reference-parity quirk: splitext treats the last dotted component
    # of a module path as an extension, so dotted names collapse to
    # their parent — but never escape the apex_tpu tree.
    assert log_util.get_transformer_logger(
        "apex_tpu.my_module").name == "apex_tpu"
    # A plain library name is not double-prefixed.
    assert log_util.get_transformer_logger("apex_tpu").name == "apex_tpu"
    # Children hang off the library root, so they inherit its handler.
    assert log_util.get_transformer_logger(
        "my_module.py").parent.name.startswith("apex_tpu")
