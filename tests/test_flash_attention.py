"""Flash attention + context parallelism numerics.

The reference tests fmha/multihead_attn against python reference
implementations (``apex/contrib/test/fmha/test_fmha.py``); same style here:
Pallas kernels (interpret mode on CPU) vs naive jnp attention, forward and
gradients, then the ring/Ulysses composition vs single-device flash.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.parallel import collectives as cc
from apex_tpu.transformer.context_parallel import (
    ring_attention,
    ulysses_attention,
)


def naive_attention(q, k, v, causal, scale=None):
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2:]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 2, 32, 8), (2, 1, 48, 16)])
def test_flash_matches_naive(causal, shape):
    b, h, s, d = shape
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, shape) for kk in ks)

    out = flash_attention(q, k, v, causal=causal)
    ref = naive_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    w = jax.random.normal(jax.random.PRNGKey(3), shape)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * w)

    def loss_ref(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal) * w)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_flash(causal):
    """cp=4 ring == single-device flash on the full sequence, fwd + grads."""
    CP = 4
    parallel.initialize_model_parallel(context_parallel_size=CP)
    b, h, s_local, d = 1, 2, 16, 8
    S = s_local * CP
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (b, h, S, d)) for kk in ks)
    w = jax.random.normal(jax.random.PRNGKey(4), (b, h, S, d))

    def ring_loss(q, k, v):
        def local(q, k, v, w):
            out = ring_attention(q, k, v, "cp", causal)
            return jnp.sum(out * w).reshape(1)
        losses = cc.shard_over(
            local,
            in_specs=(P(None, None, "cp"), P(None, None, "cp"),
                      P(None, None, "cp"), P(None, None, "cp")),
            out_specs=P("cp"),
        )(q, k, v, w)
        return jnp.sum(losses)

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * w)

    np.testing.assert_allclose(float(ring_loss(q, k, v)),
                               float(flash_loss(q, k, v)), rtol=1e-5)

    g = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_ulysses_attention_matches_flash():
    CP = 4
    parallel.initialize_model_parallel(context_parallel_size=CP)
    b, h, s_local, d = 1, 4, 16, 8
    S = s_local * CP
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (b, h, S, d)) for kk in ks)

    out = cc.shard_over(
        lambda q, k, v: ulysses_attention(q, k, v, "cp", True),
        in_specs=(P(None, None, "cp"),) * 3,
        out_specs=P(None, None, "cp"),
    )(q, k, v)
    ref = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # grads flow through the all_to_all pair
    def loss(q):
        o = cc.shard_over(
            lambda q, k, v: ulysses_attention(q, k, v, "cp", True),
            in_specs=(P(None, None, "cp"),) * 3,
            out_specs=P(None, None, "cp"),
        )(q, k, v)
        return jnp.sum(o * o)

    g = jax.grad(loss)(q)
    gr = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, causal=True)
                                    ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=2e-4, atol=2e-4)


def test_gpt_flash_attention_matches_fused_softmax():
    """CoreAttention flash path == fused-softmax path on the same params."""
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    def cfg(flash):
        return TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            padded_vocab_size=64, max_position_embeddings=16,
            hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
            use_flash_attention=flash,
        )

    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)
    m0, m1 = GPTModel(cfg(False)), GPTModel(cfg(True))
    params = m0.init(jax.random.PRNGKey(1), tokens)["params"]
    l0 = m0.apply({"params": params}, tokens, labels=tokens)
    l1 = m1.apply({"params": params}, tokens, labels=tokens)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1),
                               rtol=2e-5, atol=2e-5)
