"""Flash attention + context parallelism numerics.

The reference tests fmha/multihead_attn against python reference
implementations (``apex/contrib/test/fmha/test_fmha.py``); same style here:
Pallas kernels (interpret mode on CPU) vs naive jnp attention, forward and
gradients, then the ring/Ulysses composition vs single-device flash.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.parallel import collectives as cc
from apex_tpu.transformer.context_parallel import (
    ring_attention,
    ulysses_attention,
)

pytestmark = pytest.mark.slow


def naive_attention(q, k, v, causal, scale=None):
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2:]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 2, 32, 8), (2, 1, 48, 16)])
def test_flash_matches_naive(causal, shape):
    b, h, s, d = shape
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, shape) for kk in ks)

    out = flash_attention(q, k, v, causal=causal)
    ref = naive_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    w = jax.random.normal(jax.random.PRNGKey(3), shape)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * w)

    def loss_ref(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal) * w)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_flash(causal):
    """cp=4 ring == single-device flash on the full sequence, fwd + grads."""
    CP = 4
    parallel.initialize_model_parallel(context_parallel_size=CP)
    b, h, s_local, d = 1, 2, 16, 8
    S = s_local * CP
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (b, h, S, d)) for kk in ks)
    w = jax.random.normal(jax.random.PRNGKey(4), (b, h, S, d))

    def ring_loss(q, k, v):
        def local(q, k, v, w):
            out = ring_attention(q, k, v, "cp", causal)
            return jnp.sum(out * w).reshape(1)
        losses = cc.shard_over(
            local,
            in_specs=(P(None, None, "cp"), P(None, None, "cp"),
                      P(None, None, "cp"), P(None, None, "cp")),
            out_specs=P("cp"),
        )(q, k, v, w)
        return jnp.sum(losses)

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * w)

    np.testing.assert_allclose(float(ring_loss(q, k, v)),
                               float(flash_loss(q, k, v)), rtol=1e-5)

    g = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_ulysses_attention_matches_flash():
    CP = 4
    parallel.initialize_model_parallel(context_parallel_size=CP)
    b, h, s_local, d = 1, 4, 16, 8
    S = s_local * CP
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (b, h, S, d)) for kk in ks)

    out = cc.shard_over(
        lambda q, k, v: ulysses_attention(q, k, v, "cp", True),
        in_specs=(P(None, None, "cp"),) * 3,
        out_specs=P(None, None, "cp"),
    )(q, k, v)
    ref = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # grads flow through the all_to_all pair
    def loss(q):
        o = cc.shard_over(
            lambda q, k, v: ulysses_attention(q, k, v, "cp", True),
            in_specs=(P(None, None, "cp"),) * 3,
            out_specs=P(None, None, "cp"),
        )(q, k, v)
        return jnp.sum(o * o)

    g = jax.grad(loss)(q)
    gr = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, causal=True)
                                    ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=2e-4, atol=2e-4)


def test_gpt_flash_attention_matches_fused_softmax():
    """CoreAttention flash path == fused-softmax path on the same params."""
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    def cfg(flash):
        return TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            padded_vocab_size=64, max_position_embeddings=16,
            hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
            use_flash_attention=flash,
        )

    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)
    m0, m1 = GPTModel(cfg(False)), GPTModel(cfg(True))
    params = m0.init(jax.random.PRNGKey(1), tokens)["params"]
    l0 = m0.apply({"params": params}, tokens, labels=tokens)
    l1 = m1.apply({"params": params}, tokens, labels=tokens)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1),
                               rtol=2e-5, atol=2e-5)


def naive_attention_masked(q, k, v, causal, seg_q=None, seg_k=None,
                           scale=None):
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    sq, sk = s.shape[-2:]
    mask = jnp.ones((q.shape[0], 1, sq, sk), bool)
    if causal:
        mask = mask & jnp.tril(jnp.ones((sq, sk), bool))
    if seg_q is not None:
        mask = mask & (seg_q[:, None, :, None] == seg_k[:, None, None, :])
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)  # fully-masked rows -> zero output
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_segment_ids_match_naive(causal):
    """Packed-varlen via segment ids (fmha cu_seqlens parity)."""
    b, h, s, d = 2, 2, 64, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    # two packed sequences of length 24 and 40 per row
    seg = jnp.concatenate([jnp.zeros((b, 24), jnp.int32),
                           jnp.ones((b, 40), jnp.int32)], axis=1)

    out = flash_attention(q, k, v, causal=causal,
                          segment_ids_q=seg, segment_ids_kv=seg)
    ref = naive_attention_masked(q, k, v, causal, seg, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    w = jax.random.normal(jax.random.PRNGKey(5), (b, h, s, d))
    g = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=causal, segment_ids_q=seg,
                        segment_ids_kv=seg) * w), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        naive_attention_masked(q, k, v, causal, seg, seg) * w),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("s", [17, 100, 130])
def test_flash_non_power_of_two_lengths(s):
    """Odd lengths pad to the block grid instead of degrading to block=s."""
    b, h, d = 1, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    for causal in (False, True):
        out = flash_attention(q, k, v, causal=causal)
        ref = naive_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    g = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, causal=True)))(q)
    gr = jax.grad(lambda q: jnp.sum(naive_attention(q, k, v, True)))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=2e-4, atol=2e-4)


def test_flash_cross_attention_lengths():
    """sq != sk, both non-multiples of the block."""
    b, h, d = 2, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 33, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, h, 57, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, h, 57, d))
    out = flash_attention(q, k, v, causal=False)
    ref = naive_attention(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_fully_masked_rows_zero():
    """A q shard strictly before the kv shard under causal masking must
    produce zero output / NEG_INF lse, not mean(V) (round-1 ADVICE)."""
    from apex_tpu.ops.flash_attention import NEG_INF, flash_attention_with_lse

    b, h, s, d = 1, 1, 32, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    # kv chunk lives entirely *after* the q chunk: every row fully masked
    out, lse = flash_attention_with_lse(q, k, v, True, None, 256, 512,
                                        0, s + 64)
    assert np.allclose(np.asarray(out), 0.0)
    assert np.all(np.asarray(lse) <= NEG_INF * 0.5)

    # gradients through the chunk entry points are zero too
    from apex_tpu.ops.flash_attention import dkv_chunk, dq_chunk
    do = jax.random.normal(jax.random.PRNGKey(4), (b, h, s, d))
    delta = jnp.sum(do * out, axis=-1)
    dq = dq_chunk(q, k, v, do, lse, delta, causal=True, kv_offset=s + 64)
    dk, dv = dkv_chunk(q, k, v, do, lse, delta, causal=True,
                       kv_offset=s + 64)
    assert np.allclose(np.asarray(dq), 0.0)
    assert np.allclose(np.asarray(dk), 0.0)
    assert np.allclose(np.asarray(dv), 0.0)


def test_flash_dropout_statistics_and_determinism():
    b, h, s, d = 2, 2, 64, 8
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    rate = 0.3

    o1 = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=7)
    o2 = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=7)
    o3 = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=8)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert not np.allclose(np.asarray(o1), np.asarray(o3))

    # E[dropout(attn)] == attn: average over seeds approaches the clean out
    outs = [flash_attention(q, k, v, dropout_rate=rate, dropout_seed=i)
            for i in range(64)]
    mean = np.mean([np.asarray(o) for o in outs], axis=0)
    clean = np.asarray(flash_attention(q, k, v))
    np.testing.assert_allclose(mean, clean, atol=0.15)

    # gradient determinism (bwd regenerates the identical mask)
    g1 = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, dropout_rate=rate, dropout_seed=7)))(q)
    g2 = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, dropout_rate=rate, dropout_seed=7)))(q)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def test_flash_dropout_grad_matches_masked_reference():
    """Grads under dropout == grads of an explicitly-masked naive attention
    built from the kernel's own keep mask."""
    from apex_tpu.ops.flash_attention import _keep_mask

    b, h, s, d = 1, 2, 32, 8
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) for kk in ks)
    rate, seed = 0.25, 11

    rows = jnp.arange(s, dtype=jnp.int32)[:, None]
    cols = jnp.arange(s, dtype=jnp.int32)[None, :]
    keeps = jnp.stack([
        jnp.stack([_keep_mask(jnp.int32(seed), bh, rows, cols, rate)
                   for bh in range(b * h)]).reshape(h, s, s)
    ])  # b=1

    def ref(q, k, v):
        sc = 1.0 / np.sqrt(d)
        sm = jax.nn.softmax(
            jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sc,
            axis=-1)
        sm = jnp.where(keeps, sm / (1 - rate), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", sm.astype(q.dtype), v)

    out = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=seed)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref(q, k, v)),
                               rtol=2e-5, atol=2e-5)
    w = jax.random.normal(jax.random.PRNGKey(9), (b, h, s, d))
    g = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, dropout_rate=rate, dropout_seed=seed) * w),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(ref(q, k, v) * w),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)
