"""Observability subsystem (ISSUE 5): telemetry must be FREE and SAFE.

Three contract groups:

1. **Instrumentation adds nothing** (analyzer satellite): the
   instrumented (``collect_stats=True``) 3D GPT and ZeRO train steps
   compile to HLO with exactly the bare step's collective opcode counts
   and zero host-transfer ops — cross-rank stats ride widened existing
   reductions, never new ones (:mod:`apex_tpu.analysis.hlo` does the
   counting, async pairs folded).
2. **Instrumentation changes nothing**: params/optimizer state (and the
   sentinel) of the instrumented step are bit-identical to the bare
   step over multiple steps — observation never feeds back.
3. **The host pipeline survives its failure modes** (PR 3 fault
   harness): the JSONL writer retries transient I/O and its reader
   drops torn tails; the heartbeat monitor detects a hung checkpoint
   write (``faults.hung_writes``) and flags
   ``resilience.PreemptionGuard``; the stats logger fetches only on its
   ``every_n`` schedule; the trace window state machine opens/closes
   captures correctly.

Plus the end-to-end smoke: ``scripts/telemetry_smoke.sh`` runs the
driver dryrun with telemetry armed on a small virtual mesh and asserts
the JSONL metric catalog (fast tier, subprocess — the same idiom as
``tests/test_entry_dryrun.py``).
"""

import functools
import json
import os
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.analysis.hlo import compiled_hlo, hlo_op_counts
from apex_tpu.observability import (
    HeartbeatMonitor,
    JsonlWriter,
    MetricRegistry,
    TraceWindow,
    TrainStats,
    TrainStatsLogger,
    compiled_flops,
    mfu,
    peak_flops_for,
    read_jsonl,
    train_stats,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all")
HOST_TRANSFER = ("outfeed", "infeed", "send", "recv")


def _bits_equal(a, b):
    eq = jax.tree_util.tree_map(
        lambda x, y: np.asarray(x).tobytes() == np.asarray(y).tobytes(),
        a, b)
    return all(jax.tree_util.tree_leaves(eq))


def _collective_counts(counts):
    return {op: counts[op] for op in COLLECTIVES}


def _assert_no_host_transfers(counts, what):
    for op in HOST_TRANSFER:
        assert counts[op] == 0, (
            f"{what}: instrumentation must not add host transfers, found "
            f"{counts[op]} x {op}")


# ---------------------------------------------------------------------------
# 3D GPT: dp=2 x pp=2 x tp=2(+sp) on the virtual 8-device mesh
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _gpt3d_setup():
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    if len(jax.devices()) < 8:
        return None
    cfg = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=2,
        padded_vocab_size=64, max_position_embeddings=16,
        hidden_dropout=0.0, attention_dropout=0.0,
        tensor_axis="tp", sequence_parallel=True)
    mesh = mesh_lib.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
    init_fn, _, make_train_step = build_gpt_3d(
        cfg, num_chunks=1, num_microbatches=2, mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    params, specs = init_fn(jax.random.PRNGKey(0), tokens)
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    # The mesh object stays captured in the step closures, so the
    # conftest teardown clearing the global registry is harmless.
    mesh_lib.destroy_model_parallel()
    return {
        "bare": jax.jit(make_train_step(opt, specs)),
        "instr": jax.jit(make_train_step(opt, specs, collect_stats=True)),
        "params": params, "state": state, "tokens": tokens,
    }


def _gpt3d_or_skip():
    s = _gpt3d_setup()
    if s is None:
        pytest.skip("needs 8 virtual devices")
    return s


class TestInstrumentationAddsNothing:
    """The analyzer satellite: HLO opcode-count compare, bare vs
    instrumented, on the steady-state (non-logging) step — which IS the
    only compiled step; logging is a host-side fetch decision."""

    def test_gpt_3d_same_collectives_no_host_transfers(self):
        s = _gpt3d_or_skip()
        args = (s["params"], s["state"], s["tokens"])
        bare = hlo_op_counts(compiled_hlo(s["bare"], *args))
        instr = hlo_op_counts(compiled_hlo(s["instr"], *args))
        assert _collective_counts(instr) == _collective_counts(bare), (
            "TrainStats must ride existing collectives on the 3D step")
        _assert_no_host_transfers(instr, "gpt_3d instrumented")
        _assert_no_host_transfers(bare, "gpt_3d bare")
        # Sanity: this program really is collective-heavy (pipeline
        # ppermutes + dp/tp reductions) — the compare is not vacuous.
        assert bare["collective-permute"] > 0
        assert bare["all-reduce"] > 0

    def test_zero_same_collectives_no_host_transfers(self, devices8):
        z = _zero_setup()
        for name in ("plain", "scaler"):
            b, i, args = z[name]
            bare = hlo_op_counts(compiled_hlo(b, *args))
            instr = hlo_op_counts(compiled_hlo(i, *args))
            assert _collective_counts(instr) == _collective_counts(bare), (
                f"zero {name}: stats must ride the existing loss reduce")
            _assert_no_host_transfers(instr, f"zero {name} instrumented")
            assert bare["reduce-scatter"] > 0  # the ZeRO exchange is live


# ---------------------------------------------------------------------------
# ZeRO flat-bucket step over dp=8
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _zero_setup():
    from apex_tpu import parallel
    from apex_tpu.amp.scaler import DynamicLossScale
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.parallel.distributed import (
        dp_shard_batch, replicate, zero_data_parallel_train_step,
        zero_init)
    from apex_tpu.resilience import sentinel_init

    mesh = parallel.initialize_model_parallel()  # all 8 devices on dp
    params = replicate({"w": jnp.ones((16, 8)), "b": jnp.zeros((8,))},
                       mesh)

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    opt = DistributedFusedAdam(lr=1e-3, flat_bucket=True)
    state = zero_init(opt, params, mesh)
    x = jnp.arange(16 * 16, dtype=jnp.float32).reshape(16, 16) / 100.0
    batch = dp_shard_batch((x, jnp.ones((16, 8))), mesh)
    scaler = DynamicLossScale()
    sent = sentinel_init(scaler)

    def build(**kw):
        return zero_data_parallel_train_step(
            loss_fn, opt, mesh=mesh, donate=False, **kw)

    from apex_tpu.parallel import mesh as mesh_lib

    mesh_lib.destroy_model_parallel()
    return {
        "plain": (build(microbatches=2),
                  build(microbatches=2, collect_stats=True),
                  (params, state, batch)),
        "scaler": (build(scaler=scaler),
                   build(scaler=scaler, collect_stats=True),
                   (params, state, batch, sent)),
    }


class TestInstrumentationChangesNothing:
    """Bit-identical params/state: observation never feeds back."""

    def test_gpt_3d_parity_two_steps(self):
        s = _gpt3d_or_skip()
        p1, st1 = s["params"], s["state"]
        p2, st2 = p1, st1
        for step in range(2):
            p1, st1, l1 = s["bare"](p1, st1, s["tokens"])
            p2, st2, l2, stats = s["instr"](p2, st2, s["tokens"])
            assert _bits_equal(p1, p2), f"params diverged at step {step}"
            assert _bits_equal(st1, st2), f"state diverged at step {step}"
            assert np.float32(l1).tobytes() == np.float32(l2).tobytes()
        # The 3D step emits device-partial norms (zero extra
        # collectives); the host finalizes them at fetch time.
        host = jax.device_get(stats).finalize()
        assert np.isfinite(host.loss) and np.isfinite(host.grad_norm)
        assert host.param_norm > 0
        assert int(host.nonfinite_leaves) == 0
        assert float(host.loss_scale) == 1.0
        assert int(host.skipped_steps) == 0
        assert host.moe_aux.shape == (2,)  # per-microbatch (dense: zeros)

    def test_zero_parity_plain_and_scaler(self, devices8):
        z = _zero_setup()
        bare, instr, args = z["plain"]
        p1, s1, _ = bare(*args)
        p2, s2, _, stats = instr(*args)
        assert _bits_equal(p1, p2) and _bits_equal(s1, s2)
        host = jax.device_get(stats)
        assert host.grad_norm > 0 and int(host.nonfinite_leaves) == 0

        bare_s, instr_s, args_s = z["scaler"]
        p1, s1, se1, l1 = bare_s(*args_s)
        p2, s2, se2, l2, stats = instr_s(*args_s)
        assert _bits_equal(p1, p2) and _bits_equal(s1, s2)
        assert _bits_equal(se1, se2), "sentinel state must match too"
        assert np.float32(l1).tobytes() == np.float32(l2).tobytes()
        host = jax.device_get(stats)
        assert float(host.loss_scale) == 2.0 ** 16  # the scale used
        assert int(host.skipped_steps) == 0

    def test_zero_stats_see_poisoned_grads(self, devices8):
        """The sentinel path's stats report the overflow the sentinel
        acted on: NaN batch -> nonfinite_leaves > 0, skipped_steps 1,
        params bit-unchanged (the lax.cond skip)."""
        z = _zero_setup()
        _, instr_s, (params, state, batch, sent) = z["scaler"]
        bad_batch = jax.tree_util.tree_map(
            lambda x: jnp.full_like(x, jnp.nan)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, batch)
        p2, s2, se2, l2, stats = instr_s(params, state, bad_batch, sent)
        host = jax.device_get(stats)
        assert int(host.nonfinite_leaves) > 0
        assert int(host.skipped_steps) == 1
        assert _bits_equal(params, p2), "skipped step must not move params"


# ---------------------------------------------------------------------------
# Host pipeline: writer crash-safety, heartbeat, logger cadence, traces
# ---------------------------------------------------------------------------


class TestJsonlCrashSafety:
    def test_writer_retries_transient_os_errors(self, tmp_path):
        from apex_tpu.testing.faults import transient_os_errors

        path = str(tmp_path / "m.jsonl")
        w = JsonlWriter(path, backoff_s=0.01)
        with transient_os_errors(2, path_prefix=str(tmp_path),
                                 op="open") as counter:
            w.write({"step": 0, "loss": 1.5})
        assert counter.failed == 2, "the blips must actually have fired"
        assert read_jsonl(path) == [{"step": 0, "loss": 1.5}]

    def test_writer_gives_up_after_retry_budget(self, tmp_path):
        from apex_tpu.testing.faults import transient_os_errors

        path = str(tmp_path / "m.jsonl")
        w = JsonlWriter(path, retries=1, backoff_s=0.01)
        with transient_os_errors(5, path_prefix=str(tmp_path), op="open"):
            with pytest.raises(OSError):
                w.write({"step": 0})

    def test_reader_drops_torn_tail(self, tmp_path):
        from apex_tpu.testing.faults import truncate_file

        path = str(tmp_path / "m.jsonl")
        w = JsonlWriter(path)
        for i in range(3):
            w.write({"step": i, "loss": 1.0 / (i + 1)})
        size = os.path.getsize(path)
        # Tear mid-way into the LAST record (the crashed-writer shape).
        truncate_file(path, keep_frac=(size - 5) / size)
        records = read_jsonl(path)
        assert [r["step"] for r in records] == [0, 1]
        # strict mode still accepts a torn TAIL (expected crash artifact)
        assert len(read_jsonl(path, strict=True)) == 2

    def test_reader_interior_corruption(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        w = JsonlWriter(path)
        w.write({"step": 0})
        with open(path, "a") as f:
            f.write("{torn interior garbage\n")
        w.write({"step": 2})
        assert [r["step"] for r in read_jsonl(path)] == [0, 2]
        with pytest.raises(ValueError):
            read_jsonl(path, strict=True)

    def test_registry_flush_is_rank_aware(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        w = JsonlWriter(path)
        r1 = MetricRegistry(rank=1, world=2)
        r1.gauge("x").set(1.0)
        assert r1.flush(w, step=0) is None
        assert not os.path.exists(path), "rank 1 must not write"
        r0 = MetricRegistry(rank=0, world=2)
        r0.gauge("x").set(2.0)
        assert r0.flush(w, step=0)["metrics"]["x"] == 2.0
        assert len(read_jsonl(path)) == 1

    def test_histogram_percentiles(self):
        """keep_samples histograms (the serving latency metrics) expose
        nearest-rank percentiles over a BOUNDED window; plain
        histograms stay sample-free and answer None."""
        reg = MetricRegistry(rank=0)
        h = reg.histogram("serving/tpot_ms", keep_samples=100)
        assert h.percentile(50) is None        # nothing observed yet
        for v in range(1, 101):                # 1..100
            h.observe(float(v))
        assert h.percentile(50) == 50.0
        assert h.percentile(99) == 99.0
        assert h.percentile(0) == 1.0 and h.percentile(100) == 100.0
        s = h.summary()
        assert s["p50"] == 50.0 and s["p99"] == 99.0 and s["count"] == 100
        # window is bounded: 100 more observations evict the old ones
        for v in range(1000, 1100):
            h.observe(float(v))
        assert h.percentile(0) == 1000.0 and h.count == 200
        # keep_samples applies on first creation only (no silent
        # truncation of someone else's window)
        assert reg.histogram("serving/tpot_ms") is h
        plain = reg.histogram("plain")
        plain.observe(1.0)
        assert plain.percentile(50) is None
        assert "p50" not in plain.summary()

    def test_histogram_empty_and_single_sample_windows(self):
        """ISSUE 10 satellite: percentile() edge cases.  Empty window —
        every q answers None (never a fabricated 0); one sample — every
        q is that sample (nearest-rank with n=1); summary() mirrors."""
        from apex_tpu.observability.metrics import Histogram

        h = Histogram(keep_samples=8)
        for q in (0, 50, 99, 100):
            assert h.percentile(q) is None
        s = h.summary()
        assert s == {"count": 0, "total": 0.0, "mean": None, "min": None,
                     "max": None, "last": None, "p50": None, "p99": None}
        h.observe(7.25)
        for q in (0, 50, 99, 100):
            assert h.percentile(q) == 7.25
        s = h.summary()
        assert s["p50"] == s["p99"] == 7.25
        assert s["mean"] == 7.25 and s["count"] == 1

    def test_histogram_ring_wraparound_exact(self):
        """keep_samples ring wrap must retain EXACTLY the newest N
        observations — off-by-one here silently shifts every
        percentile."""
        from apex_tpu.observability.metrics import Histogram

        h = Histogram(keep_samples=4)
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        assert h.percentile(0) == 1.0 and h.percentile(100) == 4.0
        h.observe(5.0)  # evicts exactly 1.0
        assert h.percentile(0) == 2.0 and h.percentile(100) == 5.0
        assert sorted(h._samples) == [2.0, 3.0, 4.0, 5.0]
        for v in (6.0, 7.0, 8.0, 9.0):  # full wrap
            h.observe(v)
        assert sorted(h._samples) == [6.0, 7.0, 8.0, 9.0]
        assert h.percentile(50) == 7.0  # nearest-rank over the window

    def test_histogram_summary_mean_vs_percentile_semantics(self):
        """summary() keys answer over two documented domains: count/
        total/mean/min/max are LIFETIME moments, p50/p99 cover the
        bounded sample window — after a wrap they may legitimately
        disagree, and before one they must agree."""
        from apex_tpu.observability.metrics import Histogram

        h = Histogram(keep_samples=4)
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        s = h.summary()
        assert s["mean"] == pytest.approx(2.5)
        assert s["p50"] == 2.0  # nearest-rank(50%, n=4) = 2nd
        for v in (100.0, 100.0, 100.0, 100.0):
            h.observe(v)
        s = h.summary()
        # lifetime mean remembers the evicted small values...
        assert s["mean"] == pytest.approx((1 + 2 + 3 + 4 + 400) / 8)
        assert s["count"] == 8 and s["min"] == 1.0 and s["max"] == 100.0
        # ...while the windowed percentiles describe only the window
        assert s["p50"] == 100.0 and s["p99"] == 100.0

    def test_registry_per_rank_flush_opt_in(self, tmp_path):
        """ISSUE 10 satellite: host-local metrics (data/stall_ms,
        span_ms/*) are per-host facts — all_ranks=True lets every rank
        write its own rank-stamped record instead of rank 0's values
        silently standing in for the fleet."""
        from apex_tpu.observability.metrics import is_host_local

        w1 = JsonlWriter(str(tmp_path / "m.rank1.jsonl"))
        r1 = MetricRegistry(rank=1, world=2)
        r1.gauge("data/stall_ms").set(42.0)
        rec = r1.flush(w1, step=3, all_ranks=True)
        assert rec is not None and rec["rank"] == 1
        back = read_jsonl(str(tmp_path / "m.rank1.jsonl"))
        assert back[0]["rank"] == 1
        assert back[0]["metrics"]["data/stall_ms"] == 42.0
        # default stays rank-gated
        assert r1.flush(w1, step=4) is None
        # the catalog split the docs table is generated from
        assert is_host_local("data/stall_ms")
        assert is_host_local("span_ms/checkpoint/save")
        assert is_host_local("serving/ttft_ms")
        assert is_host_local("heartbeat/hangs")
        assert not is_host_local("train/loss")
        assert not is_host_local("train/grad_norm")


class TestHeartbeat:
    def test_flags_hung_checkpoint_write_to_preemption_guard(
            self, tmp_path):
        """faults.hung_writes parks the save mid-flight; no beat can
        arrive; the monitor flags the hang to the guard — the drain
        path a preemption would take."""
        from apex_tpu.resilience import CheckpointManager, PreemptionGuard
        from apex_tpu.testing.faults import hung_writes

        guard = PreemptionGuard(signals=())  # flag-only, no handlers
        reg = MetricRegistry(rank=0)
        hb = HeartbeatMonitor(timeout_s=0.15, on_hang=guard, registry=reg)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
        hb.beat(0)
        tree = {"w": np.arange(4.0, dtype=np.float32)}
        with hung_writes(path_prefix=str(tmp_path)) as h:
            t = threading.Thread(target=mgr.save, args=(tree, 1),
                                 daemon=True)
            t.start()
            assert h.entered.wait(10), "writer never reached the gate"
            time.sleep(0.2)  # step 1 cannot complete -> no beat
            assert hb.check_now() is True
            assert hb.hung and guard.triggered
            h.release()
            t.join(10)
        assert reg.snapshot()["heartbeat/hangs"] == 1
        # The next completed step re-arms the monitor.
        hb.beat(1)
        assert not hb.hung
        assert hb.check_now() is False

    def test_fires_once_per_episode(self):
        calls = []
        hb = HeartbeatMonitor(timeout_s=0.05, on_hang=lambda: calls.append(1))
        hb.beat(0)
        time.sleep(0.1)
        assert hb.check_now() and hb.check_now() and hb.check_now()
        assert calls == [1], "one hang episode -> one flag"

    def test_background_thread_detects(self):
        hb = HeartbeatMonitor(timeout_s=0.08, poll_s=0.02)
        with hb:
            hb.beat(0)
            time.sleep(0.3)
            assert hb.hung


class TestStatsLoggerCadence:
    def _stats(self):
        return train_stats(
            jnp.float32(2.5), {"g": jnp.ones((3,))}, {"p": jnp.ones((2,))})

    def test_log_every_n(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        logger = TrainStatsLogger(
            MetricRegistry(rank=0), every_n=3, writer=JsonlWriter(path))
        stats = self._stats()
        logged = [step for step in range(7)
                  if logger.maybe_log(step, stats) is not None]
        assert logged == [0, 3, 6], "fetch only on the every_n schedule"
        records = read_jsonl(path)
        assert len(records) == 3
        for rec in records:
            assert rec["loss"] == 2.5
            assert rec["nonfinite_leaves"] == 0
            assert rec["metrics"]["train/loss"] == 2.5
        assert [r["step"] for r in records] == [0, 3, 6]

    def test_fetch_flattens_trainstats(self):
        logger = TrainStatsLogger(MetricRegistry(rank=0), every_n=1)
        values = logger.fetch(self._stats())
        assert set(TrainStats._fields) - {"moe_aux"} <= set(values)
        assert isinstance(values["skipped_steps"], int)
        assert isinstance(values["loss"], float)


class _FakeProfiler:
    def __init__(self, fail_start=False):
        self.started, self.stops, self.fail_start = [], 0, fail_start

    def start_trace(self, path):
        if self.fail_start:
            raise RuntimeError("profiler unavailable")
        self.started.append(path)

    def stop_trace(self):
        self.stops += 1


class TestTraceWindow:
    def test_windowed_capture_state_machine(self, tmp_path):
        fp = _FakeProfiler()
        with TraceWindow(str(tmp_path), every_n=4, capture_steps=2,
                         _profiler=fp) as tw:
            for step in range(10):
                tw.on_step(step)
        # Windows at steps 0-2, 4-6, 8-(close).
        assert [os.path.basename(p) for p in fp.started] == [
            "step_00000000", "step_00000004", "step_00000008"]
        assert fp.stops == 3
        assert tw.windows_captured == 3
        assert os.path.isdir(os.path.join(str(tmp_path), "step_00000000"))

    def test_profiler_failure_disables_not_raises(self, tmp_path):
        tw = TraceWindow(str(tmp_path), every_n=1, capture_steps=1,
                         _profiler=_FakeProfiler(fail_start=True))
        tw.on_step(0)  # must not raise
        assert not tw.enabled
        tw.on_step(1)  # disabled: no-op


class TestMfu:
    def test_compiled_flops_handles_both_shapes(self):
        class L:
            def cost_analysis(self):
                return [{"flops": 123.0}]

        class D:
            def cost_analysis(self):
                return {"flops": 456.0}

        class N:
            def cost_analysis(self):
                raise NotImplementedError

        assert compiled_flops(L()) == 123.0
        assert compiled_flops(D()) == 456.0
        assert compiled_flops(N()) is None

    def test_mfu_math_and_unknown_peak(self):
        assert mfu(1e9, 0.01, peak_flops=1e12) == pytest.approx(0.1)
        assert mfu(1e9, 0.01, peak_flops=1e12, n_devices=2) == \
            pytest.approx(0.05)
        assert mfu(None, 0.01, peak_flops=1e12) is None
        assert mfu(1e9, 0.01) is None  # no peak, no device
        assert peak_flops_for(jax.devices()[0]) is None  # cpu: undefined

    def test_real_compiled_cost_analysis(self):
        compiled = jax.jit(lambda x: x @ x).lower(
            jnp.ones((64, 64))).compile()
        flops = compiled_flops(compiled)
        if flops is not None:  # backend-dependent; math must hold when set
            assert flops > 0
            assert mfu(flops, 1.0, peak_flops=1e12) > 0

    def test_mfu_none_carries_a_reason(self):
        """ISSUE 10 satellite: the two silently-conflated None cases
        (unknown device peak vs missing cost analysis) now name
        themselves, and exactly one of (value, reason) is None."""
        from apex_tpu.observability.metrics import (
            mfu_or_reason, peak_flops_reason)

        value, reason = mfu_or_reason(None, 0.01, peak_flops=1e12)
        assert value is None and "cost-analysis" in reason
        value, reason = mfu_or_reason(1e9, 0.01,
                                      device=jax.devices()[0])
        assert value is None and "'cpu'" in reason
        value, reason = mfu_or_reason(1e9, 0.01)
        assert value is None and "no device" in reason
        value, reason = mfu_or_reason(1e9, 0.0, peak_flops=1e12)
        assert value is None and "step time" in reason
        value, reason = mfu_or_reason(1e9, 0.01, peak_flops=1e12)
        assert reason is None and value == pytest.approx(0.1)
        # mfu() stays the value-only projection
        assert mfu(1e9, 0.01, peak_flops=1e12) == pytest.approx(0.1)
        peak, reason = peak_flops_reason(jax.devices()[0])
        assert peak is None and "platform 'cpu'" in reason
        peak, reason = peak_flops_reason(None)
        assert peak is None and "no device" in reason

        class _TpuDevice:
            platform = "tpu"
            device_kind = "TPU v4"

        peak, reason = peak_flops_reason(_TpuDevice())
        assert peak == 275e12 and reason is None


# ---------------------------------------------------------------------------
# End-to-end smoke: the dryrun entry with telemetry armed
# ---------------------------------------------------------------------------


def test_telemetry_smoke_script(tmp_path):
    """scripts/telemetry_smoke.sh on a 2-device virtual mesh: the full
    TrainStats -> TrainStatsLogger -> MetricRegistry -> JsonlWriter
    pipeline through the real driver entry, asserted against the metric
    catalog (the subprocess idiom of tests/test_entry_dryrun.py — the
    child must own its XLA flags)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        ["bash", os.path.join(_REPO, "scripts", "telemetry_smoke.sh"),
         "2", str(tmp_path)],
        cwd=_REPO, env=env, capture_output=True, timeout=540,
    )
    assert proc.returncode == 0, (
        f"telemetry_smoke rc={proc.returncode}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-2000:]}")
    records = read_jsonl(str(tmp_path / "metrics.jsonl"), strict=True)
    assert records and records[-1]["nonfinite_leaves"] == 0
