"""DDP + SyncBatchNorm tests on the virtual 8-device mesh.

Mirrors ``tests/distributed/synced_batchnorm`` (SyncBN numerics vs plain BN
over the full batch; subgroups) and the DDP grad-average semantics of
``apex/parallel/distributed.py``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import (
    DistributedDataParallel,
    SyncBatchNorm,
    all_reduce_gradients,
    data_parallel_train_step,
    dp_shard_batch,
    replicate,
)
from apex_tpu.parallel import collectives as cc

pytestmark = pytest.mark.slow


class TestDDP:
    def test_explicit_ddp_matches_single_device(self):
        """Grads from the 8-shard DDP wrapper == grads on the full batch."""
        mesh = parallel.initialize_model_parallel()
        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(6, 3).astype(np.float32))}
        X = jnp.asarray(rng.randn(32, 6).astype(np.float32))
        Y = jnp.asarray(rng.randn(32, 3).astype(np.float32))

        def grad_fn(p, batch):
            x, y = batch
            loss = jnp.mean((x @ p["w"] - y) ** 2)
            return loss, jax.grad(lambda q: jnp.mean((x @ q["w"] - y) ** 2))(p)

        ddp = DistributedDataParallel(grad_fn)
        step = ddp.build(mesh)
        loss, grads = step(params, (X, Y))

        ref_loss = jnp.mean((X @ params["w"] - Y) ** 2)
        ref_grads = jax.grad(lambda q: jnp.mean((X @ q["w"] - Y) ** 2))(params)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads["w"]), np.asarray(ref_grads["w"]), rtol=1e-5, atol=1e-6
        )

    def test_predivide_factor(self):
        """predivide/postdivide composition keeps the average invariant
        (distributed.py:434-450)."""
        mesh = parallel.initialize_model_parallel()
        g = {"w": jnp.ones((8, 2))}

        def run(**kw):
            f = cc.shard_over(
                lambda g: all_reduce_gradients(g, "dp", **kw),
                in_specs=(jax.tree_util.tree_map(lambda _: P("dp", None), g),),
                out_specs=jax.tree_util.tree_map(lambda _: P("dp", None), g),
            )
            return np.asarray(f(g)["w"])

        np.testing.assert_allclose(run(), 1.0)
        np.testing.assert_allclose(run(gradient_predivide_factor=4.0), 1.0)
        np.testing.assert_allclose(run(gradient_average=False), 8.0)
        # average=False + predivide: stays at sum/predivide (apex
        # distributed.py:455-456 never multiplies the predivide back)
        np.testing.assert_allclose(
            run(gradient_average=False, gradient_predivide_factor=4.0), 2.0)

    def test_fp32_allreduce_of_bf16(self):
        mesh = parallel.initialize_model_parallel()
        g = {"w": jnp.full((8, 2), 0.1, jnp.bfloat16)}
        f = cc.shard_over(
            lambda g: all_reduce_gradients(g, "dp", allreduce_always_fp32=True),
            in_specs=(jax.tree_util.tree_map(lambda _: P("dp", None), g),),
            out_specs=jax.tree_util.tree_map(lambda _: P("dp", None), g),
        )
        out = f(g)
        assert out["w"].dtype == jnp.bfloat16

    def test_pjit_train_step_converges_and_matches(self):
        """The pjit DP path trains identically to a single-device loop."""
        mesh = parallel.initialize_model_parallel()
        rng = np.random.RandomState(1)
        w0 = rng.randn(4, 1).astype(np.float32)
        X = rng.randn(64, 4).astype(np.float32)
        Y = (X @ rng.randn(4, 1)).astype(np.float32)

        def loss_fn(p, batch):
            x, y = batch
            return jnp.mean((x @ p["w"] - y) ** 2)

        opt = FusedSGD(lr=0.1, momentum=0.9)

        # distributed run
        params = replicate({"w": jnp.asarray(w0)}, mesh)
        state = replicate(opt.init(params), mesh)
        step = data_parallel_train_step(loss_fn, opt, mesh=mesh, donate=False)
        batch = dp_shard_batch((jnp.asarray(X), jnp.asarray(Y)), mesh)
        for _ in range(10):
            params, state, loss = step(params, state, batch)

        # single-device reference
        p2 = {"w": jnp.asarray(w0)}
        s2 = opt.init(p2)
        for _ in range(10):
            g = jax.grad(loss_fn)(p2, (jnp.asarray(X), jnp.asarray(Y)))
            p2, s2 = opt.step(g, s2, p2)

        np.testing.assert_allclose(
            np.asarray(params["w"]), np.asarray(p2["w"]), rtol=1e-4, atol=1e-5
        )


class TestSyncBatchNorm:
    def _data(self, seed=0, n=32, c=5):
        return np.random.RandomState(seed).randn(n, c).astype(np.float32) * 2 + 1

    def test_matches_torch_bn_single(self):
        x = self._data()
        bn = SyncBatchNorm(num_features=5, momentum=0.1)
        vars_ = bn.init(jax.random.PRNGKey(0), jnp.asarray(x))
        y, mut = bn.apply(vars_, jnp.asarray(x), mutable=["batch_stats"])

        tbn = torch.nn.BatchNorm1d(5, momentum=0.1)
        ty = tbn(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["running_mean"]),
            tbn.running_mean.numpy(), rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["running_var"]),
            tbn.running_var.numpy(), rtol=1e-4, atol=1e-5,
        )

    def test_sync_across_replicas_matches_full_batch(self):
        """Sharded SyncBN == BN over the full batch (the two_gpu_unit_test
        invariant, tests/distributed/synced_batchnorm)."""
        mesh = parallel.initialize_model_parallel()
        x = self._data(2, 64, 5)
        bn = SyncBatchNorm(num_features=5, axis_name="dp")
        vars_ = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:8]))

        def per_shard(x):
            y, mut = bn.apply(vars_, x, mutable=["batch_stats"])
            return y, mut["batch_stats"]["running_var"]

        f = cc.shard_over(
            per_shard,
            mesh=mesh,
            in_specs=P("dp", None),
            out_specs=(P("dp", None), P()),
        )
        y_dist, rv_dist = f(jnp.asarray(x))

        bn_ref = SyncBatchNorm(num_features=5)
        y_ref, mut_ref = bn_ref.apply(vars_, jnp.asarray(x), mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_dist), np.asarray(y_ref),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(rv_dist),
            np.asarray(mut_ref["batch_stats"]["running_var"]),
            rtol=1e-4, atol=1e-5,
        )

    def test_subgroups(self):
        """group_size semantics (apex/parallel/__init__.py:60-97): stats
        synced only within axis_index_groups."""
        mesh = parallel.initialize_model_parallel()
        x = self._data(3, 64, 4)
        groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
        bn = SyncBatchNorm(num_features=4, axis_name="dp",
                           axis_index_groups=groups)
        vars_ = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:8]))

        f = cc.shard_over(
            lambda x: bn.apply(vars_, x, mutable=["batch_stats"])[0],
            in_specs=P("dp", None),
            out_specs=P("dp", None),
        )
        y = np.asarray(f(jnp.asarray(x)))
        # first half uses stats of x[:32], second of x[32:]
        for half, sl in ((0, slice(0, 32)), (1, slice(32, 64))):
            ref, _ = SyncBatchNorm(num_features=4).apply(
                vars_, jnp.asarray(x[sl]), mutable=["batch_stats"]
            )
            np.testing.assert_allclose(y[sl], np.asarray(ref), rtol=1e-4, atol=1e-5)

    def test_track_running_stats_false_uses_batch_stats(self):
        """torch _BatchNorm semantics: track_running_stats=False always
        normalizes with batch statistics."""
        x = self._data(11, 64, 3)
        bn = SyncBatchNorm(num_features=3, affine=False,
                           track_running_stats=False)
        vars_ = bn.init(jax.random.PRNGKey(0), jnp.asarray(x))
        y = np.asarray(bn.apply(vars_, jnp.asarray(x)))
        np.testing.assert_allclose(y.mean(0), 0.0, atol=1e-4)
        np.testing.assert_allclose(y.std(0), 1.0, atol=1e-2)

    def test_dp_shard_batch_scalar_leaf(self):
        from apex_tpu.parallel import dp_shard_batch
        parallel.initialize_model_parallel()
        batch = (jnp.ones((16, 4)), jnp.float32(0.5))
        out = dp_shard_batch(batch)
        assert out[1].shape == ()

    def test_fused_add_relu(self):
        x = self._data(4, 16, 3)
        z = self._data(5, 16, 3)
        bn = SyncBatchNorm(num_features=3, fuse_relu=True)
        vars_ = bn.init(jax.random.PRNGKey(0), jnp.asarray(x))
        y = bn.apply(vars_, jnp.asarray(x), jnp.asarray(z),
                     mutable=["batch_stats"])[0]
        assert np.all(np.asarray(y) >= 0)

    def test_eval_uses_running_stats(self):
        x = self._data(6)
        bn = SyncBatchNorm(num_features=5)
        vars_ = bn.init(jax.random.PRNGKey(0), jnp.asarray(x))
        _, mut = bn.apply(vars_, jnp.asarray(x), mutable=["batch_stats"])
        vars2 = {"params": vars_["params"], "batch_stats": mut["batch_stats"]}
        y_eval = bn.apply(vars2, jnp.asarray(x), use_running_average=True)
        assert not np.allclose(
            np.asarray(y_eval),
            np.asarray(bn.apply(vars_, jnp.asarray(x), mutable=["batch_stats"])[0]),
        )

    def test_grad_flows_through_sync(self):
        mesh = parallel.initialize_model_parallel()
        x = self._data(7, 32, 4)
        bn = SyncBatchNorm(num_features=4, axis_name="dp")
        vars_ = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:4]))

        def per_shard(params, x):
            def loss(p):
                y, _ = bn.apply(
                    {"params": p, "batch_stats": vars_["batch_stats"]},
                    x, mutable=["batch_stats"],
                )
                return jnp.sum(y**2)

            l, g = jax.value_and_grad(loss)(params)
            return cc.all_reduce(l, "dp"), jax.tree_util.tree_map(
                lambda t: cc.all_reduce(t, "dp"), g
            )

        f = cc.shard_over(
            per_shard,
            in_specs=(jax.tree_util.tree_map(lambda _: P(), vars_["params"]),
                      P("dp", None)),
            out_specs=(P(), jax.tree_util.tree_map(lambda _: P(), vars_["params"])),
        )
        loss, grads = f(vars_["params"], jnp.asarray(x))
        assert np.isfinite(float(loss))
        assert np.all(np.isfinite(np.asarray(grads["scale"])))


class TestDistributedInvariants:
    """SPMD analogs of the reference's hand-built distributed regression
    tests (SURVEY §4 tier 4): the DDP stream-race detector
    (``tests/distributed/DDP/ddp_race_condition_test.py``) becomes a
    bitwise-determinism check (the SPMD failure mode is nondeterministic
    reduction scheduling, not stream races), and the amp master-params
    rank-consistency check (``tests/distributed/amp_master_params``)
    becomes per-device replica-buffer equality."""

    def _train(self, seed):
        import flax.linen as nn

        from apex_tpu.optimizers import FusedSGD
        from apex_tpu.parallel import (
            dp_shard_batch,
            mesh as mesh_lib,
            replicate,
        )

        mesh = mesh_lib.initialize_model_parallel()
        try:
            model = nn.Dense(8)
            x = jax.random.normal(jax.random.PRNGKey(seed), (32, 16))
            y = jax.random.normal(jax.random.PRNGKey(seed + 1), (32, 8))
            params = model.init(jax.random.PRNGKey(2), x)["params"]
            opt = FusedSGD(lr=0.05, momentum=0.9)
            state = opt.init(params)

            @jax.jit
            def step(p, s, xb, yb):
                def loss_fn(p):
                    return jnp.mean(
                        (model.apply({"params": p}, xb) - yb) ** 2)
                _, g = jax.value_and_grad(loss_fn)(p)
                return opt.step(g, s, p)

            params = replicate(params, mesh)
            state = replicate(state, mesh)
            xb, yb = dp_shard_batch((x, y), mesh)
            for _ in range(5):
                params, state = step(params, state, xb, yb)
            return params
        finally:
            mesh_lib.destroy_model_parallel()

    def test_dp_training_is_bitwise_deterministic(self):
        p1 = self._train(0)
        p2 = self._train(0)
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(p2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_replicated_params_identical_across_devices(self):
        params = self._train(3)
        for leaf in jax.tree_util.tree_leaves(params):
            shards = leaf.addressable_shards
            # fully replicated over every attached device
            assert len(shards) == len(jax.devices())
            ref = np.asarray(shards[0].data)
            for s in shards[1:]:
                np.testing.assert_array_equal(np.asarray(s.data), ref)
