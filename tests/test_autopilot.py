"""apex_tpu.serving.autopilot — the SLO control loop, hermetically
(ISSUE 18).

Every decision path of :class:`FleetAutopilot` is driven against the
fleet tests' in-memory :class:`FakeReplica` on an injected fake clock —
no process spawn, no jax, no wall time.  The fault matrix rows pinned
here: a flapping replica is quarantined under capped back-off (never
respawned in a hot loop), a slow link is demoted-not-scaled, a tenant
burst scales up and drains back, a partition during scale-up reaps the
half-born replica, and a canary host dying mid-observation yields an
inconclusive verdict with no rollback storm.  Determinism is pinned
directly: the same scripted signals produce the identical decision
sequence, run after run — and a fleet WITHOUT an autopilot emits no
event, no counter, and no per-replica histogram (disarmed is free).
"""

import pytest

from apex_tpu.observability import timeline
from apex_tpu.observability.timeline import FlightRecorder
from apex_tpu.serving.autopilot import AutopilotConfig, FleetAutopilot

from test_fleet import FakeReplica, drive, make_router


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


def make_fleet(names, *, clock, router_kw=None, **ap_kw):
    """Router + autopilot over FakeReplicas on one fake clock."""
    reps = {n: FakeReplica(n) for n in names}
    router = make_router(list(reps.values()), clock=clock,
                         **(router_kw or {}))
    ap = FleetAutopilot(router, clock=clock, **ap_kw)
    router.pump()        # consume ready handshakes + first heartbeats
    return router, ap, reps


def decision_kinds(ap, kind=None):
    if kind is None:
        return [(d["kind"], d.get("action"), d.get("verdict"))
                for d in ap.decisions]
    return [d for d in ap.decisions if d["kind"] == kind]


def counters(router, prefix="fleet/autopilot/"):
    return {k: v for k, v in router.registry.snapshot().items()
            if k.startswith(prefix)}


# ------------------------------------------------------------ scale loop


def burst(router, n, max_new=3):
    return [router.submit([3, 5, 7 + i], max_new) for i in range(n)]


def test_tenant_burst_scales_up_then_drains_back():
    """The burst row of the fault matrix: queue depth over threshold
    grows the pool through the ordinary ready handshake; once the
    burst drains and the tail is flat, the autopilot drains the
    spawned replica back — and no request is ever lost or left
    non-terminal."""
    clk = FakeClock()
    spawned = []

    def spawn(name):
        rep = FakeReplica(name)
        spawned.append(rep)
        return rep

    cfg = AutopilotConfig(min_replicas=1, max_replicas=3,
                          scale_up_queue_depth=4,
                          scale_down_queue_depth=1,
                          scale_cooldown_s=5.0)
    router, ap, reps = make_fleet(["a"], clock=clk, spawn=spawn,
                                  config=cfg)
    reqs = burst(router, 6)
    router.pump()
    ap.tick()
    assert [r.name for r in spawned] == ["auto1"]
    assert "auto1" in router._views
    assert ap.introspect()["joining"] == ["auto1"]
    router.pump()        # ready handshake arrives
    ap.tick()
    assert ap.introspect()["joining"] == []
    joined = [d for d in ap.decisions
              if d.get("verdict") == "joined"]
    assert [d["replica"] for d in joined] == ["auto1"]
    # burst served to completion across the grown pool
    drive(router, [reps["a"], spawned[0]])
    assert all(r.done for r in reqs)
    # pressure gone, cool-down elapsed: drain the spawned replica back
    clk.advance(10.0)
    ap.tick()
    assert ap.introspect()["draining"] == ["auto1"]
    router.pump()
    ap.tick()
    assert "auto1" not in router._views
    assert [d["verdict"] for d in ap.decisions
            if d["kind"] == "autopilot_verdict"][-1] == "drained"
    snap = counters(router)
    assert snap["fleet/autopilot/scale_up"] == 1
    assert snap["fleet/autopilot/scale_down"] == 1
    # min pool respected: the seed replica was never the drain victim
    assert not reps["a"].draining
    # one scale action per cool-down: the next tick does nothing
    before = len(ap.decisions)
    ap.tick()
    assert len(ap.decisions) == before


def test_scale_capped_at_max_replicas_and_cooldown():
    clk = FakeClock()
    spawned = []

    def spawn(name):
        rep = FakeReplica(name)
        spawned.append(rep)
        return rep

    cfg = AutopilotConfig(min_replicas=1, max_replicas=2,
                          scale_up_queue_depth=2, scale_cooldown_s=5.0)
    router, ap, reps = make_fleet(["a"], clock=clk, spawn=spawn,
                                  config=cfg)
    burst(router, 8)
    router.pump()
    ap.tick()            # spawns auto1
    router.pump()
    ap.tick()            # auto1 joined; still deep, but cooling
    assert len(spawned) == 1
    clk.advance(10.0)
    router.pump()
    ap.tick()            # cool-down over, but pool is at max
    assert len(spawned) == 1


def test_slow_link_demoted_not_scaled():
    """A rising p99 slope explained by a degraded link must NOT grow
    the pool — placement already demotes the slow replica; the
    explicit null decision is the proof the signal was read."""
    clk = FakeClock()
    spawned = []
    cfg = AutopilotConfig(min_replicas=2, scale_up_queue_depth=100,
                          scale_up_trend_ms_per_s=5.0,
                          scale_cooldown_s=5.0)
    router, ap, reps = make_fleet(
        ["a", "b"], clock=clk,
        spawn=lambda n: spawned.append(n) or FakeReplica(n),
        config=cfg)
    # a steep injected p99 slope + one degraded link
    router._trend["tpot_ms"].extend(
        [(0.0, 10.0), (0.5, 20.0), (1.0, 30.0)])
    router._views["b"].link_degraded = True
    ap.tick()
    assert spawned == []
    none = [d for d in ap.decisions
            if d["kind"] == "autopilot_decide"]
    assert len(none) == 1 and none[0]["action"] == "none"
    assert "degraded link" in none[0]["reason"]
    # the null decision is throttled, not re-emitted every tick
    ap.tick()
    assert len([d for d in ap.decisions
                if d["kind"] == "autopilot_decide"]) == 1
    # link heals -> the same trend NOW scales
    router._views["b"].link_degraded = False
    clk.advance(10.0)
    ap.tick()
    assert spawned == ["auto1"]


def test_flapping_replica_quarantined_with_capped_backoff():
    """The flap row: a replica that keeps dying is respawned at most
    ``flap_threshold`` times inside the window, then QUARANTINED under
    exponential back-off — never a respawn hot loop.  The quarantine
    releases after the back-off and doubles on relapse."""
    clk = FakeClock()
    spawned = []

    def spawn(name):
        rep = FakeReplica(name)
        spawned.append(rep)
        return rep

    cfg = AutopilotConfig(min_replicas=2, flap_threshold=3,
                          flap_window_s=100.0, quarantine_base_s=30.0,
                          quarantine_cap_s=120.0)
    router, ap, reps = make_fleet(["a", "b"], clock=clk, spawn=spawn,
                                  config=cfg)

    def kill_current_b():
        (spawned[-1] if spawned else reps["b"]).kill()
        router.pump()            # failure detection marks it down

    for edge in range(3):
        kill_current_b()
        clk.advance(1.0)
        ap.tick()                # notes the edge; respawns (or not)
        router.pump()            # respawned b's ready handshake
        ap.tick()
    # 3 edges in the window: quarantined after 2 respawns, and the
    # 3rd death did NOT respawn
    assert len(spawned) == 2
    snap = counters(router)
    assert snap["fleet/autopilot/quarantines"] == 1
    assert snap["fleet/autopilot/respawns"] == 2
    assert "b" in ap.introspect()["quarantined"]
    # hot-loop check: ticking inside the quarantine never respawns
    for _ in range(5):
        clk.advance(1.0)
        ap.tick()
    assert len(spawned) == 2
    # back-off elapses: repair resumes
    clk.advance(40.0)
    ap.tick()
    assert len(spawned) == 3
    assert ap.introspect()["quarantined"] == {}


def test_partition_during_scale_up_reaps_half_born_replica():
    """The partition row: a spawned replica that dies before its ready
    handshake is REAPED — removed from the routing table, counted,
    never dispatched to and never leaked — and the burst still
    completes on the survivor."""
    clk = FakeClock()

    class HalfBorn(FakeReplica):
        def __init__(self, name):
            super().__init__(name)
            self._events = []        # partitioned before the hello

    spawned = []

    def spawn(name):
        rep = HalfBorn(name)
        spawned.append(rep)
        return rep

    cfg = AutopilotConfig(min_replicas=1, scale_up_queue_depth=4,
                          scale_cooldown_s=100.0)
    router, ap, reps = make_fleet(["a"], clock=clk, spawn=spawn,
                                  config=cfg)
    reqs = burst(router, 6)
    router.pump()
    ap.tick()                        # scale_up: spawns auto1
    assert [r.name for r in spawned] == ["auto1"]
    spawned[0].kill()                # the partition
    router.pump()                    # dead pipe -> down verdict
    ap.tick()                        # join pump reaps it
    assert "auto1" not in router._views
    snap = counters(router)
    assert snap["fleet/autopilot/reaps"] == 1
    reaped = [d for d in ap.decisions
              if d.get("verdict") == "reaped"]
    assert len(reaped) == 1 and reaped[0]["replica"] == "auto1"
    assert reaped[0]["reason"] == "died before ready"
    # nothing was ever dispatched to the half-born replica
    assert spawned[0].submissions == []
    # and no request was lost: the survivor serves the whole burst
    drive(router, [reps["a"]])
    assert all(r.done for r in reqs)


def test_min_pool_repair_respawns_dead_replica():
    clk = FakeClock()
    spawned = []

    def spawn(name):
        rep = FakeReplica(name)
        spawned.append(rep)
        return rep

    router, ap, reps = make_fleet(
        ["a", "b"], clock=clk, spawn=spawn,
        config=AutopilotConfig(min_replicas=2))
    reps["b"].kill()
    router.pump()
    ap.tick()
    assert [r.name for r in spawned] == ["b"]
    router.pump()
    ap.tick()
    assert [d["verdict"] for d in ap.decisions
            if d["kind"] == "autopilot_verdict"] == ["joined"]
    assert counters(router)["fleet/autopilot/respawns"] == 1
    live = [n for n, v in router._views.items() if not v.down]
    assert live == ["a", "b"]


def test_spawn_failure_is_a_verdict_not_a_crash():
    clk = FakeClock()

    def spawn(name):
        raise RuntimeError("no capacity")

    router, ap, reps = make_fleet(
        ["a"], clock=clk, spawn=spawn,
        config=AutopilotConfig(min_replicas=1, scale_up_queue_depth=2,
                               scale_cooldown_s=1.0))
    burst(router, 4)
    router.pump()
    ap.tick()
    failed = [d for d in ap.decisions
              if d.get("verdict") == "spawn failed"]
    assert len(failed) == 1
    assert "no capacity" in failed[0]["reason"]


# ----------------------------------------------------------- retune loop


def canary_fleet(clk, *, attribution=None, names=("a", "b", "c"),
                 **cfg_kw):
    cfg_kw.setdefault("min_replicas", len(names))
    cfg_kw.setdefault("retune_cooldown_s", 60.0)
    cfg_kw.setdefault("canary_observe_s", 10.0)
    cfg_kw.setdefault("canary_rounds", 5)
    cfg_kw.setdefault("canary_min_rounds", 3)
    return make_fleet(list(names), clock=clk,
                      attribution=attribution,
                      config=AutopilotConfig(**cfg_kw))


def observe_tpot(router, name, values):
    h = router._slo_hist(f"fleet/replica/{name}/tpot_ms")
    for v in values:
        h.observe(float(v))


def run_canary_window(clk, ap, rounds=5, step=2.0):
    for _ in range(rounds):
        clk.advance(step)
        ap.tick()


def test_prefill_retune_canary_commits_when_healthy():
    """prefill dominates the tail -> shrink ``prefill_chunk`` on ONE
    canary replica; a non-regressing paired window commits the knob
    fleet-wide (every decision stage a typed event under one id)."""
    clk = FakeClock()
    attr = {"slowest_hop": "prefill", "share": 0.9, "tail": 10}
    router, ap, reps = canary_fleet(clk, attribution=lambda: attr)
    ap.tick()
    # canary = first live name; controls untouched so far
    assert reps["a"].knob_calls == [{"prefill_chunk": 64}]
    assert reps["b"].knob_calls == []
    assert ap.introspect()["canary"]["payload"] == {"prefill_chunk": 64}
    # healthy observation: canary p99 == control p99
    observe_tpot(router, "a", [10.0] * 8)
    observe_tpot(router, "b", [10.0] * 8)
    observe_tpot(router, "c", [10.0] * 8)
    run_canary_window(clk, ap)
    assert ap.introspect()["canary"] is None
    assert ap.knobs == {"prefill_chunk": 64}
    # committed to the controls too
    assert reps["b"].knob_calls == [{"prefill_chunk": 64}]
    assert reps["c"].knob_calls == [{"prefill_chunk": 64}]
    snap = counters(router)
    assert snap["fleet/autopilot/commits"] == 1
    assert "fleet/autopilot/rollbacks" not in snap
    verdict = [d for d in ap.decisions
               if d["kind"] == "autopilot_verdict"][-1]
    assert verdict["verdict"] == "commit"
    # the whole decision shares one id across its four stages
    did = verdict["decision_id"]
    stages = [d["kind"] for d in ap.decisions
              if d["decision_id"] == did]
    assert stages == ["autopilot_observe", "autopilot_decide",
                      "autopilot_act", "autopilot_verdict"]


def test_regressing_canary_rolls_back_automatically():
    """The acceptance-criteria leg: a deliberately-regressing knob
    change is rolled back automatically, and the rollback is visible
    as a typed decision event."""
    clk = FakeClock()
    attr = {"slowest_hop": "prefill", "share": 1.0, "tail": 4}
    router, ap, reps = canary_fleet(clk, attribution=lambda: attr)
    ap.tick()
    assert reps["a"].live_knobs["prefill_chunk"] == 64
    # the canary regresses: its paired p99 is 10x the controls'
    observe_tpot(router, "a", [100.0] * 8)
    observe_tpot(router, "b", [10.0] * 8)
    observe_tpot(router, "c", [10.0] * 8)
    run_canary_window(clk, ap)
    # rolled back on the canary, never applied to the controls
    assert reps["a"].live_knobs["prefill_chunk"] is None
    assert reps["b"].knob_calls == []
    assert ap.knobs == {}
    snap = counters(router)
    assert snap["fleet/autopilot/rollbacks"] == 1
    verdict = [d for d in ap.decisions
               if d["kind"] == "autopilot_verdict"][-1]
    assert verdict["verdict"] == "rollback"
    assert verdict["ratio"] > 1.2
    assert verdict["rolled_back"] == {"prefill_chunk": None}


def test_canary_host_death_is_inconclusive_no_rollback_storm():
    """The canary-death row: the host dying mid-observation yields
    verdict ``inconclusive`` — no rollback broadcast (the knob died
    with the host), no repeat verdicts, and the retune loop stays
    cooled down."""
    clk = FakeClock()
    attr = {"slowest_hop": "prefill", "share": 1.0, "tail": 4}
    router, ap, reps = canary_fleet(clk, attribution=lambda: attr)
    ap.tick()
    assert ap.introspect()["canary"]["canary"] == "a"
    reps["a"].kill()
    router.pump()                    # down verdict
    clk.advance(2.0)
    ap.tick()
    snap = counters(router)
    assert snap["fleet/autopilot/inconclusive"] == 1
    assert "fleet/autopilot/rollbacks" not in snap
    verdicts = [d for d in ap.decisions
                if d["kind"] == "autopilot_verdict"]
    assert [v["verdict"] for v in verdicts] == ["inconclusive"]
    assert verdicts[0]["reason"] == "canary host died mid-observation"
    # no rollback storm: further ticks emit no more verdicts and no
    # knob traffic to the survivors
    for _ in range(5):
        clk.advance(2.0)
        ap.tick()
    assert len([d for d in ap.decisions
                if d["kind"] == "autopilot_verdict"]) == 1
    assert reps["b"].knob_calls == [] and reps["c"].knob_calls == []


def test_too_few_samples_is_inconclusive_and_restores():
    clk = FakeClock()
    attr = {"slowest_hop": "prefill", "share": 1.0, "tail": 4}
    router, ap, reps = canary_fleet(clk, attribution=lambda: attr)
    ap.tick()
    # no per-replica samples at all -> every paired sample is None
    clk.advance(20.0)
    ap.tick()
    verdict = [d for d in ap.decisions
               if d["kind"] == "autopilot_verdict"][-1]
    assert verdict["verdict"] == "inconclusive"
    assert verdict["restored"] is True
    # the live canary was restored to the committed state (None)
    assert reps["a"].live_knobs["prefill_chunk"] is None
    assert counters(router)["fleet/autopilot/inconclusive"] == 1


def test_spec_acceptance_sag_lowers_spec_k():
    clk = FakeClock()
    router, ap, reps = canary_fleet(clk, attribution=lambda: None)
    reps["b"].spec_acceptance = 0.1          # below the 0.3 floor
    for rep in reps.values():
        rep._emit_state()
    router.pump()
    ap.tick()
    assert reps["a"].knob_calls == [{"spec_k": 3}]   # spec_k_max - 1
    decide = [d for d in ap.decisions
              if d["kind"] == "autopilot_decide"][-1]
    assert "spec acceptance" in decide["reason"]


def test_router_queue_retune_tightens_shed_bound():
    """router_queue dominating the tail tightens ``max_queue_depth``
    (shed earlier, protect admitted tails), judged before/after on the
    fleet window since the knob is router-local."""
    clk = FakeClock()
    attr = {"slowest_hop": "router_queue", "share": 0.8, "tail": 5}
    router, ap, reps = canary_fleet(clk, attribution=lambda: attr)
    base = router.max_queue_depth
    # a stable fleet p99 window: before == after -> commit
    h = router._slo_hist("fleet/tpot_ms")
    for _ in range(8):
        h.observe(10.0)
    ap.tick()
    assert router.max_queue_depth == base // 2
    run_canary_window(clk, ap)
    assert router.max_queue_depth == base // 2       # committed
    assert counters(router)["fleet/autopilot/commits"] == 1


def test_retune_cooldown_gates_one_knob_change_per_window():
    clk = FakeClock()
    attr = {"slowest_hop": "prefill", "share": 1.0, "tail": 4}
    router, ap, reps = canary_fleet(clk, attribution=lambda: attr,
                                    retune_cooldown_s=100.0)
    ap.tick()
    observe_tpot(router, "a", [10.0] * 8)
    observe_tpot(router, "b", [10.0] * 8)
    run_canary_window(clk, ap)
    assert counters(router)["fleet/autopilot/retunes"] == 1
    ap.tick()                        # still cooling: no second canary
    assert counters(router)["fleet/autopilot/retunes"] == 1
    assert ap.introspect()["canary"] is None


# --------------------------------------------------------- determinism


def scripted_run():
    """One full scripted scenario: burst -> scale -> flap -> retune."""
    clk = FakeClock()
    spawned = {}

    def spawn(name):
        rep = FakeReplica(name)
        spawned[name] = rep
        return rep

    attr = {"slowest_hop": "prefill", "share": 1.0, "tail": 4}
    cfg = AutopilotConfig(min_replicas=2, max_replicas=4,
                          scale_up_queue_depth=4, scale_cooldown_s=5.0,
                          retune_cooldown_s=3.0, canary_observe_s=4.0,
                          canary_rounds=2, canary_min_rounds=2)
    router, ap, reps = make_fleet(["a", "b"], clock=clk, spawn=spawn,
                                  config=cfg,
                                  attribution=lambda: dict(attr))
    reqs = burst(router, 6)
    router.pump()
    ap.tick()                                    # scale up
    router.pump()
    ap.tick()                                    # joined
    drive(router, [reps["a"], reps["b"]] + list(spawned.values()))
    # SLO token timing is wall-clock by design (real serving latency);
    # the determinism pin judges the canary on injected samples only
    for h in router.registry._histograms.values():
        h._samples.clear()
    clk.advance(6.0)
    ap.tick()                                    # scale down
    router.pump()
    ap.tick()                                    # drained
    clk.advance(6.0)
    ap.tick()                                    # retune canary opens
    observe_tpot(router, "a", [10.0] * 4)
    observe_tpot(router, "b", [10.0] * 4)
    for _ in range(2):
        clk.advance(2.0)
        ap.tick()                                # canary judged
    reps["b"].kill()
    router.pump()
    clk.advance(1.0)
    ap.tick()                                    # down edge + repair
    assert all(r.done for r in reqs)
    return ap.decisions


def test_same_signals_same_decision_sequence():
    """The reproducibility criterion: two runs of the identical
    scripted scenario on identical injected clocks produce the
    byte-identical decision timeline — ids, times, reasons, verdicts
    and all."""
    first = scripted_run()
    second = scripted_run()
    assert first == second
    assert len(first) > 8            # the script actually decided things
    kinds = {d["kind"] for d in first}
    assert kinds == {"autopilot_observe", "autopilot_decide",
                     "autopilot_act", "autopilot_verdict"}


# -------------------------------------------------------- disarmed-inert


def test_disarmed_fleet_is_untouched():
    """No autopilot constructed -> no decision event, no
    ``fleet/autopilot/*`` counter, no per-replica SLO histogram: the
    PR 17 fleet, byte for byte."""
    rec = FlightRecorder(None)
    timeline.arm(rec)
    try:
        clk = FakeClock()
        reps = [FakeReplica("a"), FakeReplica("b")]
        router = make_router(reps, clock=clk)
        reqs = burst(router, 6)
        drive(router, reps)
        assert all(r.done for r in reqs)
        assert router.per_replica_slo is False
        snap = router.registry.snapshot()
        assert not any("autopilot" in k for k in snap)
        assert not any(k.startswith("fleet/replica/")
                       for k in router.registry._histograms)
        assert [e for e in rec.events()
                if e["kind"].startswith("autopilot_")] == []
    finally:
        timeline.disarm()


def test_armed_autopilot_emits_timeline_decisions():
    """Armed, every decision rides the trace plane: the four typed
    kinds land in the flight recorder with their shared decision_id,
    and ``trace.collect_decisions`` reconstructs the timeline."""
    from apex_tpu.observability.trace import collect_decisions

    rec = FlightRecorder(None)
    timeline.arm(rec)
    try:
        clk = FakeClock()
        router, ap, reps = make_fleet(
            ["a"], clock=clk, spawn=FakeReplica,
            config=AutopilotConfig(min_replicas=1,
                                   scale_up_queue_depth=2))
        burst(router, 4)
        router.pump()
        ap.tick()
        router.pump()
        ap.tick()
        evs = [e for e in rec.events()
               if e["kind"].startswith("autopilot_")]
        assert {e["kind"] for e in evs} == {
            "autopilot_observe", "autopilot_decide",
            "autopilot_act", "autopilot_verdict"}
        rows = collect_decisions(evs)
        assert len(rows) == 1
        assert rows[0]["action"] == "scale_up"
        assert rows[0]["verdict"] == "joined"
        assert len(rows[0]["events"]) == 4
    finally:
        timeline.disarm()


# ------------------------------------------- controller-readable signals


def test_knob_broadcast_acks_and_down_replica():
    clk = FakeClock()
    reps = [FakeReplica("a"), FakeReplica("b")]
    router = make_router(reps, clock=clk)
    router.pump()
    res = router.set_knobs({"prefill_chunk": 16})
    assert res["a"][0] and res["b"][0]
    assert res["a"][1]["prefill_chunk"] == 16
    reps[1].kill()
    router.pump()
    res = router.set_knobs({"spec_k": 1})
    assert res["a"][0] is True
    assert res["b"] == (False, "replica down")
    # a refusal is a failed ack carrying the reason, not a hang
    reps[0].refuse_knobs = True
    res = router.set_knobs({"spec_k": 0})
    assert res["a"][0] is False and "refused" in res["a"][1]


def test_statusz_trend_backlog_and_spec_acceptance():
    """ISSUE 18 satellites 1+2: the windowed p99 slope, the backlog
    gauge, and per-adapter speculative acceptance are first-class
    controller-readable fields on introspect()/fleet_statusz()."""
    clk = FakeClock()
    reps = [FakeReplica("a"), FakeReplica("b")]
    router = make_router(reps, clock=clk, trend_window_s=1.0)
    reps[0].spec_by_adapter = {"t1": {"proposed": 10, "accepted": 5}}
    reps[1].spec_by_adapter = {"t1": {"proposed": 10, "accepted": 1},
                               "t2": {"proposed": 4, "accepted": 4}}
    for rep in reps:
        rep._emit_state()
    router.pump()
    # rising p99 across three trend windows on the injected clock
    h = router._slo_hist("fleet/tpot_ms")
    for v in (10.0, 20.0, 30.0):
        h.observe(v)
        clk.advance(1.1)
        router.pump()
    assert router.p99_trend("tpot_ms") > 0
    intro = router.introspect()
    assert intro["p99_trend"]["tpot_ms_per_s"] > 0
    assert intro["p99_trend"]["windows"]["tpot_ms"] == 3
    assert intro["backlog"] == 0
    statusz = router.fleet_statusz()
    assert statusz["p99_trend"] == intro["p99_trend"]
    assert statusz["backlog"] == 0
    acc = statusz["spec_acceptance"]
    assert acc["t1"] == {"proposed": 20, "accepted": 6,
                         "acceptance": 0.3}
    assert acc["t2"]["acceptance"] == 1.0
    # backlog rises with dispatched-but-not-decoding requests
    burst(router, 3)
    router.pump()
    assert router.introspect()["backlog"] == 3


# ----------------------------------------------- flapping_replica helper


def test_flapping_replica_helper_deterministic_schedule():
    from apex_tpu.testing.faults import flapping_replica

    clk = FakeClock()
    log = []
    flap = flapping_replica(down=lambda: log.append("down"),
                            up=lambda: log.append("up"),
                            period_s=1.0, max_flaps=2, clock=clk)
    assert flap.tick() is True            # t0 edge: down
    clk.advance(0.5)
    assert flap.tick() is True            # mid-period: unchanged
    clk.advance(0.5)
    assert flap.tick() is False           # edge: back up
    clk.advance(1.0)
    assert flap.tick() is True            # second flap
    clk.advance(1.0)
    assert flap.tick() is False
    clk.advance(5.0)
    assert flap.tick() is False           # max_flaps reached: stays up
    assert log == ["down", "up", "down", "up"]
    assert flap.flaps == 2


def test_flapping_replica_helper_autodetects_fake_replica():
    from apex_tpu.testing.faults import flapping_replica

    clk = FakeClock()
    rep = FakeReplica("a")
    with flapping_replica(rep, period_s=1.0, clock=clk) as flap:
        flap.tick()
        assert rep.alive() is False
        clk.advance(1.0)
        flap.tick()
        assert rep.alive() is True
    assert rep.alive() is True            # exit restores up
    with pytest.raises(TypeError, match="actuator"):
        flapping_replica(object())


def test_config_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        AutopilotConfig(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        AutopilotConfig(min_replicas=4, max_replicas=2)
    with pytest.raises(ValueError, match="prefill_shrink"):
        AutopilotConfig(prefill_shrink=1.5)
    with pytest.raises(ValueError, match="flap_threshold"):
        AutopilotConfig(flap_threshold=1)
    with pytest.raises(ValueError, match="queue_bound_step"):
        AutopilotConfig(queue_bound_step=1.0)


# ------------------------- ISSUE 20: predictive scale off the history


def _armed_kw(objective=100.0):
    """Router kwargs arming the longitudinal history + one TTFT SLO."""
    from apex_tpu.observability.slo import SLOPolicy

    return {"history_every_s": 1.0,
            "slo_policies": [SLOPolicy(
                name="ttft", metric="fleet/ttft_ms:p99",
                objective=objective, target=0.9,
                fast_window_s=5.0, slow_window_s=30.0,
                compliance_window_s=300.0)]}


def _predictive_cfg(**kw):
    """Depth and trend thresholds parked out of reach: only the
    predictive signal can trigger a scale here."""
    base = dict(min_replicas=1, max_replicas=3,
                scale_up_queue_depth=1000,
                scale_up_trend_ms_per_s=1e9,
                scale_cooldown_s=5.0,
                # the regression window must be COVERED by real fine
                # buckets before slope() reports (partial coverage
                # falls to a coarser ring) — keep it inside the few
                # seconds these scenarios run
                predictive_window_s=5.0)
    base.update(kw)
    return AutopilotConfig(**base)


def _run_predictive(values, cfg_kw=None, router_kw=None):
    clk = FakeClock()
    spawned = []

    def spawn(name):
        rep = FakeReplica(name)
        spawned.append(rep)
        return rep

    router, ap, reps = make_fleet(
        ["a"], clock=clk, spawn=spawn, config=_predictive_cfg(
            **(cfg_kw or {})),
        router_kw=router_kw if router_kw is not None else _armed_kw())
    try:
        for v in values:
            clk.advance(1.0)
            router.registry.histogram(
                "fleet/ttft_ms", keep_samples=512).observe(v)
            router.pump()        # history sample + SLO eval + joins
            ap.tick()
    finally:
        router.close()
    return ap, spawned


def test_predictive_scale_up_fires_before_depth_threshold():
    """The tentpole acceptance row: a rising TTFT tail projected over
    the horizon breaches the SLO objective (derived from the router's
    own policy — ``predictive_objective_ms`` stays 0) and grows the
    pool while the queue is EMPTY, long before the depth threshold."""
    ap, spawned = _run_predictive(
        [10.0, 20.0, 30.0, 40.0, 50.0, 60.0, 70.0])
    assert [r.name for r in spawned] == ["auto1"]
    decide = [d for d in ap.decisions
              if d["kind"] == "autopilot_decide"
              and d.get("action") == "scale_up"]
    assert len(decide) == 1
    assert decide[0]["reason"] == \
        "predicted p99 TTFT breach within horizon"
    obs = [d for d in ap.decisions
           if d["kind"] == "autopilot_observe"
           and d["decision_id"] == decide[0]["decision_id"]][0]
    # the depth signal was nowhere near its threshold: this fired on
    # the projection alone, and the evidence rode the observe event
    assert obs["queue_depth"] == 0
    assert obs["history_slope_ms_per_s"] > 0
    assert obs["history_p99_ms"] is not None
    assert obs["burn_slow"] == 0.0       # objective 100: nothing bad yet


def test_predictive_burn_trigger_without_slope():
    """The second predictive leg: a flat-but-bad tail never projects a
    breach (slope 0), yet the slow-window burn over the policy's
    objective trips ``predictive_burn``."""
    from apex_tpu.observability.slo import SLOPolicy

    router_kw = {"history_every_s": 1.0,
                 "slo_policies": [SLOPolicy(
                     name="ttft", metric="fleet/ttft_ms:p99",
                     objective=5.0, target=0.9,
                     fast_window_s=5.0, slow_window_s=30.0,
                     compliance_window_s=300.0)]}
    ap, spawned = _run_predictive(
        [10.0, 10.0, 10.0],
        cfg_kw={"predictive_objective_ms": 1e9, "predictive_burn": 1.0},
        router_kw=router_kw)
    assert [r.name for r in spawned] == ["auto1"]
    decide = [d for d in ap.decisions
              if d.get("action") == "scale_up"]
    assert decide and decide[0]["reason"] == \
        "predicted p99 TTFT breach within horizon"
    obs = [d for d in ap.decisions
           if d["kind"] == "autopilot_observe"][-1]
    assert obs["burn_slow"] >= 1.0


def test_predictive_decisions_byte_identical_across_runs():
    """Same scripted signals, same fake clock -> the identical decision
    stream, record for record (the determinism acceptance row)."""
    runs = [_run_predictive(
        [10.0, 20.0, 30.0, 40.0, 50.0, 60.0, 70.0, 80.0])[0]
        for _ in range(2)]
    assert runs[0].decisions == runs[1].decisions
    assert any(d.get("action") == "scale_up"
               for d in runs[0].decisions)


def test_disarmed_observe_payload_unchanged():
    """No history -> the predictive path is a no-op: the observe event
    carries exactly the PR 19 fields, nothing more."""
    clk = FakeClock()
    spawned = []

    def spawn(name):
        rep = FakeReplica(name)
        spawned.append(rep)
        return rep

    cfg = AutopilotConfig(min_replicas=1, max_replicas=2,
                          scale_up_queue_depth=4,
                          scale_down_queue_depth=1,
                          scale_cooldown_s=5.0)
    router, ap, reps = make_fleet(["a"], clock=clk, spawn=spawn,
                                  config=cfg)
    try:
        burst(router, 6)
        router.pump()
        ap.tick()
    finally:
        router.close()
    assert [r.name for r in spawned] == ["auto1"]
    obs = [d for d in ap.decisions
           if d["kind"] == "autopilot_observe"][0]
    assert set(obs) == {"kind", "decision_id", "t", "loop",
                        "queue_depth", "p99_trend_ms_per_s", "live"}
    decide = [d for d in ap.decisions
              if d.get("action") == "scale_up"][0]
    assert decide["reason"] == "queue depth over threshold"


def test_predictive_config_validation():
    with pytest.raises(ValueError, match="predictive"):
        AutopilotConfig(predictive_window_s=0.0)
    with pytest.raises(ValueError, match="predictive_burn"):
        AutopilotConfig(predictive_burn=0.0)
