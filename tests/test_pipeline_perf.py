"""Pipeline perf validation (round-1 VERDICT weak #4): sharded-microbatch
mode parity, bubble math vs theory, and live-buffer accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.parallel import collectives as cc
from apex_tpu.transformer.pipeline_parallel.schedules import (
    pipeline_apply,
    pipeline_bubble_fraction,
    split_into_microbatches,
    stack_stage_params,
)

pytestmark = pytest.mark.slow

PP = 4


@pytest.fixture()
def mesh():
    m = parallel.initialize_model_parallel(pipeline_model_parallel_size=PP)
    yield m
    parallel.destroy_model_parallel()


def make_stages(key, n_stages, width):
    ks = jax.random.split(key, n_stages)
    return [{"w": jax.random.normal(k, (width, width)) * 0.3,
             "b": jax.random.normal(jax.random.fold_in(k, 1), (width,))}
            for k in ks]


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


@pytest.mark.parametrize("vpp,m", [(1, 8), (2, 8)])
def test_shard_microbatches_matches_replicated(mesh, vpp, m):
    """Sharded-buffer mode is numerically identical (fwd + grads) to the
    replicated-buffer mode it optimizes."""
    width, mb = 16, 2
    stages = make_stages(jax.random.PRNGKey(0), PP * vpp, width)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (m * mb, width))
    mbs = split_into_microbatches(x, m)

    def run(shard):
        def loss(params, mbs):
            out = pipeline_apply(stage_fn, params, mbs, num_chunks=vpp,
                                 mesh=mesh, shard_microbatches=shard)
            return jnp.sum(out ** 2)
        l, g = jax.value_and_grad(loss)(stacked, mbs)
        return l, g

    l0, g0 = run(False)
    l1, g1 = run(True)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_shard_microbatches_buffers_are_sharded(mesh):
    """Drive the local-shard contract directly: each pp rank holds ONLY
    its m/pp microbatch rows (asserted inside the shard_map), and the
    result still matches the sequential reference — proving the mode
    really runs on 1/pp-size buffers, not silently re-replicated ones."""
    m, mb, width = 8, 2, 16
    mpp = m // PP
    stages = make_stages(jax.random.PRNGKey(2), PP, width)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (m * mb, width))
    mbs = split_into_microbatches(x, m)

    chunk_major = jax.tree_util.tree_map(
        lambda l: l.reshape((1, PP) + l.shape[1:]), stacked)

    def local(params_local, x_local):
        # the per-rank input really is the 1/pp shard
        assert x_local.shape == (mpp, mb, width), x_local.shape
        return pipeline_apply(stage_fn, params_local, x_local,
                              params_already_local=True,
                              shard_microbatches=True)

    out = cc.shard_over(
        local, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(None, "pp"),
                                         chunk_major), P("pp")),
        out_specs=P(),
    )(chunk_major, mbs)

    ref = mbs
    for p in stages:
        ref = jax.vmap(lambda xb, p=p: stage_fn(p, xb))(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)

    # the public wrapper enters the shard_map with P(pp) on the input too
    out2 = pipeline_apply(stage_fn, stacked, mbs, mesh=mesh,
                          shard_microbatches=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="divisible"):
        pipeline_apply(stage_fn, stacked,
                       split_into_microbatches(x[:6 * mb], 6), mesh=mesh,
                       shard_microbatches=True)


def test_bubble_fraction_matches_1f1b_theory():
    for m in (4, 8, 16, 64):
        for pp in (2, 4, 8):
            assert pipeline_bubble_fraction(m, pp, 1) == pytest.approx(
                (pp - 1) / (m + pp - 1))
    # interleaving shrinks the bubble (circular schedule)
    assert (pipeline_bubble_fraction(8, 4, 2)
            < pipeline_bubble_fraction(8, 4, 1))


def test_pipeline_tick_count_is_schedule_optimal(mesh):
    """Measured work: the scan executes exactly entry[-1] + pp*vpp ticks,
    i.e. the schedule's own bubble prediction — no hidden serialization."""
    from apex_tpu.transformer.pipeline_parallel.schedules import _entry_ticks

    m, vpp = 8, 2
    entry = _entry_ticks(m, PP, vpp)
    total = int(entry[-1]) + PP * vpp
    assert total == 19  # 8 microbatches, pp=4, vpp=2
    frac = pipeline_bubble_fraction(m, PP, vpp)
    assert frac == pytest.approx(1 - (m * vpp) / total)


def test_p2p_wrappers_build_a_custom_gpipe(mesh):
    """The standalone p2p surface composes into a hand-written GPipe-style
    forward sweep that matches the sequential model (the reference's
    custom-schedule use case for p2p_communication)."""
    from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

    width, mb, m = 16, 2, PP  # one microbatch per stage slot
    stages = make_stages(jax.random.PRNGKey(7), PP, width)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(8), (m * mb, width))
    mbs = split_into_microbatches(x, m)

    def local(params_local, mbs):
        s = jax.lax.axis_index("pp")
        p = jax.tree_util.tree_map(lambda l: l[0], params_local)
        # hand-written sweep: m + PP - 1 slots, stage s works at slot >= s
        carry = jnp.zeros((mb, width))
        outs = jnp.zeros_like(mbs)
        for t in range(m + PP - 1):
            j = min(t, m - 1)
            entry = mbs[j]
            x_in = jnp.where((s == 0) & (t < m), entry, carry)
            y = stage_fn(p, x_in)
            jo = t - (PP - 1)
            if jo >= 0:
                write = (s == PP - 1)
                outs = outs.at[jo].set(jnp.where(write, y, outs[jo]))
            carry = p2p.send_forward_recv_forward(y, "pp")
        return jax.lax.psum(outs, "pp")

    out = cc.shard_over(
        local, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), stacked), P()),
        out_specs=P(),
    )(stacked, mbs)

    ref = mbs
    for p_ in stages:
        ref = jax.vmap(lambda xb, p_=p_: stage_fn(p_, xb))(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_p2p_ring_and_edge_semantics(mesh):
    from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

    def local(x):
        fwd = p2p.send_forward_recv_forward(x, "pp")          # edge zeros
        ring = p2p.send_forward_recv_forward(x, "pp", ring=True)
        bwd = p2p.send_backward_recv_backward(x, "pp")
        return fwd, ring, bwd

    x = jnp.arange(PP, dtype=jnp.float32).reshape(PP, 1)
    fwd, ring, bwd = cc.shard_over(
        local, mesh=mesh, in_specs=P("pp"),
        out_specs=(P("pp"), P("pp"), P("pp")))(x)
    np.testing.assert_allclose(np.asarray(fwd)[:, 0], [0, 0, 1, 2])
    np.testing.assert_allclose(np.asarray(ring)[:, 0], [3, 0, 1, 2])
    np.testing.assert_allclose(np.asarray(bwd)[:, 0], [1, 2, 3, 0])


def test_grouped_remat_cuts_live_memory(mesh):
    """remat_ticks must reduce XLA temp (live-activation) memory by the
    predicted order: O(T) boundary residuals -> O(T/G + G).  Measured via
    the compiled executable's memory analysis (the round-1 VERDICT's
    'memory claim rests on remat with no measurement')."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_with_interleaving as fb_interleaved,
    )

    width, mb, vpp, m = 128, 4, 2, 32
    stages = make_stages(jax.random.PRNGKey(0), PP * vpp, width)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (m, mb, width))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (m, mb, width))

    def loss_fn(o, t):
        return jnp.sum((o - t) ** 2)

    def temp_bytes(remat_ticks):
        def fb(params):
            _, grads = fb_interleaved(
                stage_fn, loss_fn, params, x, tgt, num_chunks=vpp,
                remat_ticks=remat_ticks)
            return grads
        ma = jax.jit(fb).lower(stacked).compile().memory_analysis()
        return ma.temp_size_in_bytes

    flat, grouped = temp_bytes(None), temp_bytes(True)
    # measured ~9.6x at these shapes; assert a conservative 2x so the test
    # tracks the property, not the constant
    assert grouped * 2 < flat, (flat, grouped)
