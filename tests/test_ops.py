"""Fused softmax / dense / MLP / xentropy numerics vs references.

Mirrors ``tests/L0/run_transformer/test_fused_softmax.py``,
``tests/L0/run_mlp/test_mlp.py`` and
``apex/contrib/test/xentropy/test_label_smoothing.py``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.ops import (
    AttnMaskType,
    FusedScaleMaskSoftmax,
    MLP,
    fused_dense,
    fused_dense_gelu_dense,
    mlp_forward,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
    softmax_cross_entropy_loss,
)


def _rand(shape, seed=0, dtype=np.float32):
    return np.random.RandomState(seed).randn(*shape).astype(dtype)


class TestSoftmax:
    def test_scaled_softmax(self):
        x = _rand((2, 4, 8, 8), 0)
        y = scaled_softmax(jnp.asarray(x), 0.5)
        ref = torch.softmax(torch.tensor(x) * 0.5, dim=-1)
        np.testing.assert_allclose(np.asarray(y), ref.numpy(), rtol=1e-5, atol=1e-6)

    def test_scaled_masked_softmax(self):
        x = _rand((2, 4, 8, 8), 1)
        mask = np.random.RandomState(2).rand(2, 1, 8, 8) > 0.7
        y = scaled_masked_softmax(jnp.asarray(x), jnp.asarray(mask), 0.7)
        tx = torch.tensor(x) * 0.7
        tx = tx.masked_fill(torch.tensor(mask), -10000.0)
        ref = torch.softmax(tx, dim=-1)
        np.testing.assert_allclose(np.asarray(y), ref.numpy(), rtol=1e-5, atol=1e-6)

    def test_causal_softmax(self):
        x = _rand((8, 16, 16), 3)
        y = scaled_upper_triang_masked_softmax(jnp.asarray(x), 1.0)
        tx = torch.tensor(x)
        mask = torch.triu(torch.ones(16, 16, dtype=torch.bool), diagonal=1)
        ref = torch.softmax(tx.masked_fill(mask, -10000.0), dim=-1)
        ref = ref.masked_fill(mask, 0.0)
        np.testing.assert_allclose(np.asarray(y), ref.numpy(), rtol=1e-5, atol=1e-6)
        # strictly-upper triangle exactly zero (kernel parity)
        yy = np.asarray(y)
        assert np.all(yy[:, np.triu_indices(16, 1)[0], np.triu_indices(16, 1)[1]] == 0)

    def test_softmax_backward_saves_only_output(self):
        """custom_vjp backward: dx = scale*y*(dy - sum(dy*y))."""
        x = _rand((2, 2, 4, 4), 4)
        dy = _rand((2, 2, 4, 4), 5)
        dx = jax.grad(
            lambda x_: jnp.sum(scaled_softmax(x_, 2.0) * jnp.asarray(dy))
        )(jnp.asarray(x))
        tx = torch.tensor(x, requires_grad=True)
        ty = torch.softmax(tx * 2.0, dim=-1)
        ty.backward(torch.tensor(dy))
        np.testing.assert_allclose(np.asarray(dx), tx.grad.numpy(), rtol=1e-4, atol=1e-6)

    def test_masked_softmax_backward(self):
        x = _rand((2, 2, 4, 4), 6)
        mask = np.random.RandomState(7).rand(2, 1, 4, 4) > 0.6
        dy = _rand((2, 2, 4, 4), 8)
        dx = jax.grad(
            lambda x_: jnp.sum(
                scaled_masked_softmax(x_, jnp.asarray(mask), 1.3) * jnp.asarray(dy)
            )
        )(jnp.asarray(x))
        tx = torch.tensor(x, requires_grad=True)
        tm = torch.tensor(mask)
        ty = torch.softmax((tx * 1.3).masked_fill(tm, -10000.0), dim=-1)
        ty.backward(torch.tensor(dy))
        np.testing.assert_allclose(np.asarray(dx), tx.grad.numpy(), rtol=1e-4, atol=1e-6)

    def test_dispatcher_causal(self):
        x = _rand((2, 4, 8, 8), 9).astype(np.float32)
        sm = FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=True,
            attn_mask_type=AttnMaskType.causal, scale=0.5,
        )
        y = sm(jnp.asarray(x, jnp.bfloat16), None)
        assert y.shape == x.shape
        # rows sum to 1 over the visible prefix
        s = np.asarray(y, np.float32).sum(-1)
        np.testing.assert_allclose(s, 1.0, atol=2e-2)

    def test_unfused_fallback_restores_fp16(self):
        """fused_softmax.py:263-266: fp16 input → fp16 output in the
        softmax_in_fp32 unfused path (not bf16)."""
        sm = FusedScaleMaskSoftmax(
            input_in_fp16=True, input_in_bf16=False,
            scaled_masked_softmax_fusion=False, softmax_in_fp32=True,
        )
        x = jnp.asarray(_rand((2, 2, 4, 4), 60), jnp.float16)
        assert sm(x, None).dtype == jnp.float16

    def test_dispatcher_rejects_scale_without_fp32(self):
        with pytest.raises(RuntimeError):
            FusedScaleMaskSoftmax(softmax_in_fp32=False, scale=2.0)

    def test_dispatcher_rejects_both_dtypes(self):
        with pytest.raises(RuntimeError):
            FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)


class TestDense:
    def test_fused_dense_vs_torch(self):
        x = _rand((4, 8), 10)
        w = _rand((16, 8), 11)
        b = _rand((16,), 12)
        y = fused_dense(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
        ref = torch.nn.functional.linear(
            torch.tensor(x), torch.tensor(w), torch.tensor(b)
        )
        np.testing.assert_allclose(np.asarray(y), ref.numpy(), rtol=1e-5, atol=1e-5)

    def test_gelu_dense_vs_torch(self):
        x = _rand((4, 8), 13)
        w1, b1 = _rand((32, 8), 14), _rand((32,), 15)
        w2, b2 = _rand((8, 32), 16), _rand((8,), 17)
        y = fused_dense_gelu_dense(
            *(jnp.asarray(a) for a in (x, w1, b1, w2, b2))
        )
        h = torch.nn.functional.linear(torch.tensor(x), torch.tensor(w1), torch.tensor(b1))
        h = torch.nn.functional.gelu(h)  # erf gelu
        ref = torch.nn.functional.linear(h, torch.tensor(w2), torch.tensor(b2))
        np.testing.assert_allclose(np.asarray(y), ref.numpy(), rtol=1e-5, atol=1e-5)


class TestMLP:
    @pytest.mark.parametrize("activation", ["none", "relu", "sigmoid"])
    @pytest.mark.parametrize("use_bias", [True, False])
    def test_vs_torch_sequential(self, activation, use_bias):
        """Parity with tests/L0/run_mlp/test_mlp.py: activation after every
        layer."""
        sizes = [7, 16, 4]
        ws = [_rand((sizes[i + 1], sizes[i]), 20 + i) for i in range(2)]
        bs = [_rand((sizes[i + 1],), 30 + i) for i in range(2)] if use_bias else []
        x = _rand((5, 7), 40)
        y = mlp_forward(
            jnp.asarray(x), [jnp.asarray(w) for w in ws],
            [jnp.asarray(b) for b in bs], activation,
        )
        h = torch.tensor(x)
        for i in range(2):
            h = torch.nn.functional.linear(
                h, torch.tensor(ws[i]), torch.tensor(bs[i]) if use_bias else None
            )
            if activation == "relu":
                h = torch.relu(h)
            elif activation == "sigmoid":
                h = torch.sigmoid(h)
        np.testing.assert_allclose(np.asarray(y), h.numpy(), rtol=1e-5, atol=1e-5)

    def test_invalid_activation(self):
        with pytest.raises(ValueError):
            mlp_forward(jnp.ones((2, 4)), [jnp.ones((4, 4))], [], "tanh")

    def test_module(self):
        m = MLP(mlp_sizes=(7, 16, 4))
        x = jnp.asarray(_rand((5, 7), 41))
        params = m.init(jax.random.PRNGKey(0), x)
        assert m.apply(params, x).shape == (5, 4)


class TestXentropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_loss_vs_torch(self, smoothing):
        """Parity with apex/contrib/test/xentropy/test_label_smoothing.py's
        python reference (label_smoothing_raw)."""
        C, N = 11, 6
        logits = _rand((N, C), 50)
        labels = np.random.RandomState(51).randint(1, C, size=(N,))
        loss = softmax_cross_entropy_loss(
            jnp.asarray(logits), jnp.asarray(labels), smoothing, -100
        )
        tl = torch.tensor(logits)
        logprobs = torch.log_softmax(tl, dim=-1)
        nll = -logprobs[torch.arange(N), torch.tensor(labels)]
        smooth = -logprobs.mean(dim=-1)
        ref = (1 - smoothing) * nll + smoothing * smooth
        np.testing.assert_allclose(np.asarray(loss), ref.numpy(), rtol=1e-5, atol=1e-5)

    def test_padding_rows_zeroed(self):
        C = 5
        logits = _rand((4, C), 52)
        labels = np.array([0, 2, 0, 3])
        loss = softmax_cross_entropy_loss(
            jnp.asarray(logits), jnp.asarray(labels), 0.0, padding_idx=0
        )
        out = np.asarray(loss)
        assert out[0] == 0.0 and out[2] == 0.0
        assert out[1] != 0.0 and out[3] != 0.0

    @pytest.mark.parametrize("smoothing", [0.0, 0.15])
    def test_grad_vs_torch(self, smoothing):
        C, N = 9, 5
        logits = _rand((N, C), 53)
        labels = np.random.RandomState(54).randint(1, C, size=(N,))
        dl = jax.grad(
            lambda x: jnp.sum(
                softmax_cross_entropy_loss(x, jnp.asarray(labels), smoothing, -100)
            )
        )(jnp.asarray(logits))
        tl = torch.tensor(logits, requires_grad=True)
        logprobs = torch.log_softmax(tl, dim=-1)
        nll = -logprobs[torch.arange(N), torch.tensor(labels)]
        smooth = -logprobs.mean(dim=-1)
        ((1 - smoothing) * nll + smoothing * smooth).sum().backward()
        np.testing.assert_allclose(np.asarray(dl), tl.grad.numpy(), rtol=1e-4, atol=1e-6)

    def test_half_to_float(self):
        logits = jnp.asarray(_rand((4, 8), 55), jnp.bfloat16)
        labels = jnp.asarray([1, 2, 3, 4])
        out32 = softmax_cross_entropy_loss(logits, labels, 0.0, -100, True)
        out16 = softmax_cross_entropy_loss(logits, labels, 0.0, -100, False)
        assert out32.dtype == jnp.float32
        assert out16.dtype == jnp.bfloat16

    def test_grad_padding_rows_zero(self):
        C = 6
        logits = _rand((3, C), 56)
        labels = np.array([0, 2, 4])
        dl = jax.grad(
            lambda x: jnp.sum(
                softmax_cross_entropy_loss(x, jnp.asarray(labels), 0.1, 0)
            )
        )(jnp.asarray(logits))
        np.testing.assert_allclose(np.asarray(dl)[0], 0.0)
        assert np.abs(np.asarray(dl)[1]).sum() > 0
