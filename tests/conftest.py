"""Test configuration: run everything on a virtual 8-device CPU mesh.

The reference runs distributed tests by spawning world_size processes on one
host over NCCL (``apex/transformer/testing/distributed_test_base.py:22-93``,
``MultiProcessTestCase``).  The JAX analog (SURVEY.md §4) is a single process
with ``--xla_force_host_platform_device_count=N`` so every collective runs on
a real N-device mesh without hardware.

This must happen before any JAX backend is initialized.  The sandbox's
sitecustomize registers a TPU PJRT plugin and forces ``jax_platforms=axon``,
so we both set the env var and override the config back to cpu.
"""

import os

# Must precede jax import / backend init.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_parallel_state():
    """Reset the global mesh registry between tests (the analog of the
    reference's per-test ``destroy_model_parallel`` teardown)."""
    yield
    from apex_tpu.parallel import mesh

    mesh.destroy_model_parallel()


@pytest.fixture
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running distributed/model tests (deselect with "
        "-m 'not slow' for the fast tier)",
    )


# The graph-lint fixture (apex_tpu.analysis): importing it here registers
# it for every test module, so suites can lint any model they already
# trace against the shared rulebook (docs/analysis.md).
from apex_tpu.analysis.fixtures import graph_lint  # noqa: E402,F401
