"""Pallas norm kernels vs the jnp reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.normalization import fused_layer_norm_affine, fused_rms_norm_affine
from apex_tpu.ops import pallas_norm


@pytest.mark.skipif(not pallas_norm.PALLAS_AVAILABLE, reason="pallas missing")
class TestPallasNorm:
    def test_layer_norm_matches_reference(self):
        x = jnp.asarray(np.random.RandomState(0).randn(64, 128), jnp.float32)
        w = jnp.asarray(np.random.RandomState(1).randn(128) + 1, jnp.float32)
        b = jnp.asarray(np.random.RandomState(2).randn(128), jnp.float32)
        got = pallas_norm.pallas_layer_norm(x, w, b, interpret=True)
        want = fused_layer_norm_affine(x, w, b, (128,))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_rms_norm_matches_reference(self):
        x = jnp.asarray(np.random.RandomState(3).randn(32, 256), jnp.float32)
        w = jnp.asarray(np.random.RandomState(4).randn(256) + 1, jnp.float32)
        got = pallas_norm.pallas_rms_norm(x, w, interpret=True)
        want = fused_rms_norm_affine(x, w, (256,))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_3d_input(self):
        x = jnp.asarray(np.random.RandomState(5).randn(2, 8, 128), jnp.float32)
        w = jnp.ones(128)
        b = jnp.zeros(128)
        got = pallas_norm.pallas_layer_norm(x, w, b, interpret=True)
        want = fused_layer_norm_affine(x, w, b, (128,))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_availability_gate(self):
        assert pallas_norm.is_available(128)
        assert not pallas_norm.is_available(100)

    def test_layer_norm_grad(self):
        """Pallas norms must be differentiable (custom_vjp to analytic bwd)."""
        x = jnp.asarray(np.random.RandomState(7).randn(16, 128), jnp.float32)
        w = jnp.ones(128)
        b = jnp.zeros(128)
        dx = jax.grad(
            lambda x_: jnp.sum(
                pallas_norm.pallas_layer_norm(x_, w, b, interpret=True) ** 2
            )
        )(x)
        want = jax.grad(
            lambda x_: jnp.sum(fused_layer_norm_affine(x_, w, b, (128,)) ** 2)
        )(x)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_rms_norm_grad(self):
        x = jnp.asarray(np.random.RandomState(8).randn(16, 128), jnp.float32)
        w = jnp.ones(128) * 1.3
        dx, dw = jax.grad(
            lambda x_, w_: jnp.sum(
                pallas_norm.pallas_rms_norm(x_, w_, interpret=True) ** 2
            ),
            argnums=(0, 1),
        )(x, w)
        wantx, wantw = jax.grad(
            lambda x_, w_: jnp.sum(fused_rms_norm_affine(x_, w_, (128,)) ** 2),
            argnums=(0, 1),
        )(x, w)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(wantx),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(wantw),
                                   rtol=1e-4, atol=1e-5)

    def test_ragged_rows(self):
        """rows not divisible by block_rows exercises the grid remainder."""
        x = jnp.asarray(np.random.RandomState(6).randn(70, 128), jnp.float32)
        w = jnp.ones(128)
        b = jnp.zeros(128)
        got = pallas_norm.pallas_layer_norm(x, w, b, block_rows=64, interpret=True)
        want = fused_layer_norm_affine(x, w, b, (128,))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
