"""Mid-epoch SIGKILL/resume for the input pipeline (ISSUE 8 satellite).

Drives ``apex_tpu/testing/data_resume.py`` in subprocesses (a SIGKILL
needs a process to kill): a run streaming batches through
``loader -> prefetch_to_device`` while checkpointing the wrapper's
``consumed_samples`` through ``CheckpointManager`` is SIGKILLed
mid-epoch, resumed from the restored counter, and the delivered-batch
hash stream must equal an uninterrupted reference run **byte for byte**
— any skipped or duplicated sample shifts every subsequent batch hash.
Both loader families: the online-decode ``ImageFolderLoader`` and the
decode-free ``PackedSequenceLoader`` (packed.py's producer machinery).
"""

import os
import signal
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "apex_tpu", "testing", "data_resume.py")


def _run(args, expect_sigkill=False, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, _SCRIPT, *args],
        cwd=_REPO, env=env, capture_output=True, timeout=timeout)
    if expect_sigkill:
        assert proc.returncode == -signal.SIGKILL, (
            f"expected SIGKILL, rc={proc.returncode}\n"
            f"stderr:\n{proc.stderr.decode(errors='replace')[-2000:]}")
    else:
        assert proc.returncode == 0, (
            f"rc={proc.returncode}\n"
            f"stderr:\n{proc.stderr.decode(errors='replace')[-2000:]}")
    return proc


@pytest.mark.parametrize("family", ["image", "sequence"])
def test_midepoch_sigkill_resume_stream_exact(family, tmp_path):
    killed_work = str(tmp_path / "killed")
    ref_work = str(tmp_path / "ref")
    killed_stream = str(tmp_path / f"{family}_killed.log")
    ref_stream = str(tmp_path / f"{family}_ref.log")

    # run -> SIGKILL mid-epoch (after 5 of 13 batches; epochs are 12
    # batches, so the kill is mid-epoch and the stream crosses an epoch
    # boundary after resume)
    _run(["--family", family, "--work", killed_work, "--phase", "run",
          "--stream", killed_stream], expect_sigkill=True)
    assert os.path.exists(killed_stream)
    n_before = len(open(killed_stream).read().splitlines())
    assert 0 < n_before < 13, "kill landed too early/late to prove resume"

    # resume from the restored consumed_samples
    _run(["--family", family, "--work", killed_work, "--phase", "resume",
          "--stream", killed_stream])

    # uninterrupted reference over an identical (separately built)
    # dataset — the generators are seeded, so the bytes agree
    _run(["--family", family, "--work", ref_work, "--phase", "ref",
          "--stream", ref_stream])

    killed = open(killed_stream).read()
    ref = open(ref_stream).read()
    assert killed.splitlines() == ref.splitlines(), (
        f"{family}: killed+resumed stream != uninterrupted reference\n"
        f"killed ({len(killed.splitlines())} lines) vs "
        f"ref ({len(ref.splitlines())} lines)")
    assert len(killed.splitlines()) == 13
