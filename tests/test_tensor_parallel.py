"""Tensor/sequence-parallel numerics on the virtual CPU mesh.

Mirrors the reference's distributed L0 suite
(``tests/L0/run_transformer/test_layers.py``, ``test_mapping.py``,
``test_cross_entropy.py``, ``test_random.py``, ``test_data.py``): every
sharded component is compared against a single-device jnp reference for both
forward values and gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.parallel import collectives as cc
from apex_tpu.transformer import tensor_parallel as tp

pytestmark = pytest.mark.slow

TP = 8


@pytest.fixture()
def mesh():
    m = parallel.initialize_model_parallel(tensor_model_parallel_size=TP)
    yield m
    parallel.destroy_model_parallel()


# ---------------------------------------------------------------------------
# mappings
# ---------------------------------------------------------------------------


def test_copy_region_grad_sums(mesh):
    """Identity fwd; grads sum over the axis (mappings.py:143-155)."""
    x = jnp.ones((4,))

    def per_shard(x):
        y = tp.copy_to_tensor_model_parallel_region(x, "tp")
        local = jnp.sum(y * (1.0 + cc.axis_index("tp")))
        return cc.all_reduce(local, "tp")

    def loss(x):
        return cc.shard_over(per_shard, in_specs=P(), out_specs=P())(x)

    g = jax.grad(loss)(x)
    # d/dx sum_r (1+r)*x = sum_r (1+r) = 8*9/2 = 36
    np.testing.assert_allclose(np.asarray(g), np.full(4, 36.0))


def test_reduce_region(mesh):
    x = jnp.arange(8.0)
    f = cc.shard_over(
        lambda s: tp.reduce_from_tensor_model_parallel_region(s, "tp"),
        in_specs=P("tp"),
        out_specs=P("tp"),
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.full(8, 28.0))


def test_scatter_gather_last_dim_roundtrip(mesh):
    x = jnp.arange(32.0).reshape(2, 16)

    def fn(s):
        local = tp.scatter_to_tensor_model_parallel_region(s, "tp")
        assert local.shape == (2, 2)
        return tp.gather_from_tensor_model_parallel_region(local, "tp")

    f = cc.shard_over(fn, in_specs=P(), out_specs=P())
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x))


def test_sequence_parallel_roundtrip(mesh):
    x = jnp.arange(48.0).reshape(16, 3)

    def fn(s):
        local = tp.scatter_to_sequence_parallel_region(s, "tp")
        assert local.shape == (2, 3)
        return tp.gather_from_sequence_parallel_region(local, "tp", False)

    f = cc.shard_over(fn, in_specs=P(), out_specs=P())
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x))


def test_reduce_scatter_sequence_region(mesh):
    x = jnp.ones((16, 2))

    f = cc.shard_over(
        lambda s: tp.reduce_scatter_to_sequence_parallel_region(s, "tp"),
        in_specs=P(),
        out_specs=P("tp"),
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.full((16, 2), 8.0))


# ---------------------------------------------------------------------------
# layers vs dense reference
# ---------------------------------------------------------------------------


def _dense_ref(x, w, b):
    return jnp.matmul(x, w.T) + b


def test_column_row_composition_matches_dense(mesh):
    """Column(out-shard) -> Row(in-shard) == two dense layers, fwd + grads.

    The reference checks this shape of parity in
    ``tests/L0/run_transformer/test_layers.py`` (forward/backward of
    Column/RowParallelLinear vs unsharded Linear).
    """
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    batch, din, dmid, dout = 4, 6, 16, 5
    x = jax.random.normal(k1, (batch, din), jnp.float32)
    w1 = jax.random.normal(k2, (dmid, din)) / np.sqrt(din)
    w2 = jax.random.normal(k3, (dout, dmid)) / np.sqrt(dmid)

    col = tp.ColumnParallelLinear(din, dmid, use_bias=False, axis="tp")
    row = tp.RowParallelLinear(dmid, dout, use_bias=False, axis="tp")

    def per_shard(x, w1_local, w2_local):
        h = col.apply({"params": {"kernel": w1_local}}, x)
        y = row.apply({"params": {"kernel": w2_local}}, h)
        return y

    f = cc.shard_over(
        per_shard,
        in_specs=(P(), P("tp", None), P(None, "tp")),
        out_specs=P(),
    )

    def loss_sharded(x, w1, w2):
        return jnp.sum(jnp.sin(f(x, w1, w2)))

    def loss_ref(x, w1, w2):
        y = jnp.matmul(jnp.matmul(x, w1.T), w2.T)
        return jnp.sum(jnp.sin(y))

    np.testing.assert_allclose(
        loss_sharded(x, w1, w2), loss_ref(x, w1, w2), rtol=1e-5
    )
    gs = jax.grad(loss_sharded, argnums=(0, 1, 2))(x, w1, w2)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w1, w2)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


def test_column_row_sequence_parallel_matches_dense(mesh):
    """SP: seq-sharded input -> Column(SP gather) -> Row(SP reduce-scatter)."""
    key = jax.random.PRNGKey(1)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    seq, din, dmid = 16, 6, 16
    x = jax.random.normal(k1, (seq, din), jnp.float32)
    w1 = jax.random.normal(k2, (dmid, din)) / np.sqrt(din)
    w2 = jax.random.normal(k3, (din, dmid)) / np.sqrt(dmid)
    b2 = jax.random.normal(k4, (din,))

    col = tp.ColumnParallelLinear(din, dmid, use_bias=False,
                                  sequence_parallel=True, axis="tp")
    row = tp.RowParallelLinear(dmid, din, use_bias=True,
                               sequence_parallel=True, axis="tp")

    def per_shard(x_local, w1_local, w2_local, b2_full):
        h = col.apply({"params": {"kernel": w1_local}}, x_local)
        y = row.apply(
            {"params": {"kernel": w2_local, "bias": b2_full}}, h
        )
        return y

    f = cc.shard_over(
        per_shard,
        in_specs=(P("tp", None), P("tp", None), P(None, "tp"), P()),
        out_specs=P("tp", None),
    )

    def loss_sharded(x, w1, w2, b2):
        return jnp.sum(jnp.sin(f(x, w1, w2, b2)))

    def loss_ref(x, w1, w2, b2):
        y = jnp.matmul(jnp.matmul(x, w1.T), w2.T) + b2
        return jnp.sum(jnp.sin(y))

    np.testing.assert_allclose(
        loss_sharded(x, w1, w2, b2), loss_ref(x, w1, w2, b2), rtol=1e-5
    )
    gs = jax.grad(loss_sharded, argnums=(0, 1, 2, 3))(x, w1, w2, b2)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, w1, w2, b2)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


def test_column_parallel_init_shards_differ(mesh):
    """Sharded-weight init draws independent values per rank
    (layers.py:137-172 / random.py:204)."""
    col = tp.ColumnParallelLinear(8, 16, use_bias=False, axis="tp")

    def per_shard(x):
        v = col.init(jax.random.PRNGKey(7), x)
        return v["params"]["kernel"]

    f = cc.shard_over(per_shard, in_specs=P(), out_specs=P("tp", None))
    w = np.asarray(f(jnp.ones((2, 8))))  # [16, 8] global
    shard0, shard1 = w[:2], w[2:4]
    assert not np.allclose(shard0, shard1)


def test_vocab_parallel_embedding(mesh):
    vocab, dim = 32, 5
    key = jax.random.PRNGKey(2)
    table = jax.random.normal(key, (vocab, dim))
    ids = jnp.array([[0, 5, 31], [8, 16, 24]])

    emb = tp.VocabParallelEmbedding(vocab, dim, axis="tp")

    def per_shard(table_local, ids):
        return emb.apply({"params": {"embedding": table_local}}, ids)

    f = cc.shard_over(
        per_shard, in_specs=(P("tp", None), P()), out_specs=P()
    )
    np.testing.assert_allclose(
        np.asarray(f(table, ids)), np.asarray(jnp.take(table, ids, axis=0)),
        rtol=1e-6,
    )

    # gradient: rows touched get cotangents exactly once
    def loss(table):
        return jnp.sum(f(table, ids) * 2.0)

    g = np.asarray(jax.grad(loss)(table))
    expect = np.zeros((vocab, dim))
    for i in np.asarray(ids).ravel():
        expect[i] += 2.0
    np.testing.assert_allclose(g, expect, rtol=1e-6)


# ---------------------------------------------------------------------------
# vocab-parallel cross entropy
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_vocab_parallel_cross_entropy(mesh, smoothing):
    key = jax.random.PRNGKey(3)
    batch, seq, vocab = 2, 4, 32
    logits = jax.random.normal(key, (batch, seq, vocab)) * 3.0
    target = jax.random.randint(jax.random.PRNGKey(4), (batch, seq), 0, vocab)

    f = cc.shard_over(
        lambda lg, t: tp.vocab_parallel_cross_entropy(lg, t, "tp", smoothing),
        in_specs=(P(None, None, "tp"), P()),
        out_specs=P(),
    )

    def ref(logits, target):
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, target[..., None], axis=-1)[..., 0]
        if smoothing == 0.0:
            return nll
        s_hat = smoothing * vocab / (vocab - 1)
        return (1 - s_hat) * nll - s_hat * jnp.mean(logp, axis=-1)

    np.testing.assert_allclose(
        np.asarray(f(logits, target)), np.asarray(ref(logits, target)),
        rtol=1e-5, atol=1e-6,
    )

    def loss_sharded(lg):
        return jnp.mean(f(lg, target))

    def loss_ref(lg):
        return jnp.mean(ref(lg, target))

    np.testing.assert_allclose(
        np.asarray(jax.grad(loss_sharded)(logits)),
        np.asarray(jax.grad(loss_ref)(logits)),
        rtol=1e-5, atol=1e-6,
    )


def test_vocab_parallel_cross_entropy_unsharded_matches():
    logits = jax.random.normal(jax.random.PRNGKey(5), (3, 16))
    target = jnp.array([1, 15, 7])
    out = tp.vocab_parallel_cross_entropy(logits, target, None)
    logp = jax.nn.log_softmax(logits)
    ref = -jnp.take_along_axis(logp, target[:, None], 1)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


# ---------------------------------------------------------------------------
# ring-decomposed collective matmul (overlap_comm)
# ---------------------------------------------------------------------------


def _overlap_stack(sp, overlap):
    """Column -> elementwise -> Row under the bound tp axis, monolithic or
    ring-decomposed; returns ``loss(x, w1, w2, b2)`` over global arrays."""
    seq_specs = (P("tp", None) if sp else P(),
                 P("tp", None), P(None, "tp"), P())
    col = tp.ColumnParallelLinear(6, 16, use_bias=False,
                                  sequence_parallel=sp, axis="tp",
                                  overlap_comm=overlap)
    row = tp.RowParallelLinear(16, 6, use_bias=True,
                               sequence_parallel=sp, axis="tp",
                               overlap_comm=overlap)

    def per_shard(x_local, w1_local, w2_local, b2_full):
        h = col.apply({"params": {"kernel": w1_local}}, x_local)
        h = jnp.sin(h)
        return row.apply(
            {"params": {"kernel": w2_local, "bias": b2_full}}, h
        )

    f = cc.shard_over(
        per_shard, in_specs=seq_specs,
        out_specs=P("tp", None) if sp else P(),
    )

    def loss(x, w1, w2, b2):
        return jnp.sum(jnp.cos(f(x, w1, w2, b2)))

    return loss


@pytest.mark.parametrize("tp_size", [2, 4])
@pytest.mark.parametrize("sp", [False, True])
def test_overlap_comm_matches_monolithic_and_dense(tp_size, sp):
    """overlap_comm=True == monolithic == single-device reference, values
    and grads, on the virtual CPU mesh (the ISSUE-2 acceptance parity)."""
    parallel.initialize_model_parallel(tensor_model_parallel_size=tp_size)
    key = jax.random.PRNGKey(42)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    x = jax.random.normal(k1, (16, 6), jnp.float32)
    w1 = jax.random.normal(k2, (16, 6)) / np.sqrt(6)
    w2 = jax.random.normal(k3, (6, 16)) / np.sqrt(16)
    b2 = jax.random.normal(k4, (6,))
    args = (x, w1, w2, b2)

    def loss_dense(x, w1, w2, b2):
        y = jnp.matmul(jnp.sin(jnp.matmul(x, w1.T)), w2.T) + b2
        return jnp.sum(jnp.cos(y))

    losses = {
        "dense": loss_dense,
        "monolithic": _overlap_stack(sp, overlap=False),
        "overlap": _overlap_stack(sp, overlap=True),
    }
    vals = {k: np.asarray(f(*args)) for k, f in losses.items()}
    grads = {k: jax.grad(f, argnums=(0, 1, 2, 3))(*args)
             for k, f in losses.items()}
    for name in ("monolithic", "overlap"):
        np.testing.assert_allclose(vals[name], vals["dense"], rtol=1e-5)
        for a, b in zip(grads[name], grads["dense"]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("tp_size", [2, 4])
def test_overlap_gpt_train_loss_and_grads_match(tp_size):
    """Model-level parity: the testing GPT under tp+sp computes the same
    loss and grads with overlap_comm on and off (the flag threads through
    every Column/Row linear in the transformer block)."""
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    parallel.initialize_model_parallel(tensor_model_parallel_size=tp_size)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)

    def build(overlap):
        cfg = TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            padded_vocab_size=64, max_position_embeddings=16,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_axis="tp", sequence_parallel=True,
            overlap_comm=overlap,
        )
        model = GPTModel(cfg)

        def local_init(t):
            return model.init(jax.random.PRNGKey(1), t)["params"]

        specs = tp.infer_param_specs(jax.eval_shape(local_init, tokens))
        params = cc.shard_over(
            local_init, in_specs=P(), out_specs=specs)(tokens)

        def loss(p, t):
            def local(p, t):
                losses = model.apply({"params": p}, t, labels=t)
                return cc.all_reduce(jnp.mean(losses), "tp", "mean")[None]
            return cc.shard_over(
                local, in_specs=(specs, P()), out_specs=P(None))(p, t)[0]

        return params, loss

    params_m, loss_m = build(False)
    params_o, loss_o = build(True)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params_m, params_o)

    lm, gm = jax.jit(jax.value_and_grad(loss_m))(params_m, tokens)
    lo, go = jax.jit(jax.value_and_grad(loss_o))(params_o, tokens)
    np.testing.assert_allclose(np.asarray(lo), np.asarray(lm), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
        go, gm)


@pytest.mark.parametrize("tp_size", [2, 4])
def test_overlap_hlo_decomposition_survives_jit(tp_size):
    """The compiled overlap path carries >= tp-1 collective-permutes and NO
    monolithic all-gather/reduce-scatter; the monolithic path shows the
    inverse — proving the ring is not silently re-fused by XLA."""
    from apex_tpu.testing.hlo import compiled_hlo, count_hlo_ops

    parallel.initialize_model_parallel(tensor_model_parallel_size=tp_size)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 6))
    w1 = jax.random.normal(jax.random.PRNGKey(1), (16, 6))
    w2 = jax.random.normal(jax.random.PRNGKey(2), (6, 16))
    b2 = jnp.zeros((6,))

    txt_overlap = compiled_hlo(_overlap_stack(True, True),
                               x, w1, w2, b2)
    assert count_hlo_ops(txt_overlap, "collective-permute") >= 2 * (
        tp_size - 1), txt_overlap
    assert count_hlo_ops(txt_overlap, "all-gather") == 0
    assert count_hlo_ops(txt_overlap, "reduce-scatter") == 0

    txt_mono = compiled_hlo(_overlap_stack(True, False),
                            x, w1, w2, b2)
    assert count_hlo_ops(txt_mono, "collective-permute") == 0
    assert count_hlo_ops(txt_mono, "all-gather") >= 1


# ---------------------------------------------------------------------------
# rng / checkpoint / data
# ---------------------------------------------------------------------------


def test_model_parallel_rng_key_distinct(mesh):
    f = cc.shard_over(
        lambda: jax.random.normal(
            tp.model_parallel_rng_key(jax.random.PRNGKey(0), "tp"), (1, 4)
        ),
        in_specs=(),
        out_specs=P("tp", None),
    )
    draws = np.asarray(f())
    assert len({tuple(np.round(r, 6)) for r in draws}) == TP


def test_rng_tracker_fork_advances():
    tr = tp.RngStatesTracker()
    tr.add("model-parallel-rng", jax.random.PRNGKey(0))
    k1, k2 = tr.fork(), tr.fork()
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    tr.set_states(tr.get_states())
    with pytest.raises(RuntimeError):
        tr.add("model-parallel-rng", jax.random.PRNGKey(1))


def test_checkpoint_matches_uncheckpointed():
    x = jax.random.normal(jax.random.PRNGKey(6), (4, 4))

    def fn(x):
        return jnp.sum(jnp.tanh(x @ x.T))

    # atol: the recompute reassociates the contraction, so near-zero grad
    # entries carry ~1e-7 absolute float noise that an rtol-only check
    # flags (jax-version dependent — failed on 0.4.37 without it).
    np.testing.assert_allclose(
        np.asarray(jax.grad(lambda x: tp.checkpoint(fn, x))(x)),
        np.asarray(jax.grad(fn)(x)),
        rtol=1e-6, atol=1e-6,
    )


def test_broadcast_data(mesh):
    def per_shard():
        rank = cc.axis_index("tp")
        data = {"tokens": jnp.full((3,), rank, jnp.int32)}
        return tp.broadcast_data(["tokens"], data, jnp.int32, "tp")["tokens"]

    f = cc.shard_over(per_shard, in_specs=(), out_specs=P("tp"))
    out = np.asarray(f())
    np.testing.assert_array_equal(out, np.zeros(3 * TP, np.int32))
