"""fp16 (not bf16) end-to-end training with the full O2 contract under
*real* overflows: dynamic scaler + fp32 masters + skip-step + backoff +
recovery — the ``apex/amp/scaler.py:197-217`` semantics exercised by an
actual training loop rather than unit tests (round-1 VERDICT weak #6)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.optimizers import FusedSGD

pytestmark = pytest.mark.slow


class MLP(nn.Module):
    dtype: jnp.dtype = jnp.float16

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(32, dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.Dense(4, dtype=self.dtype)(x)


def test_fp16_o2_training_with_overflow_recovery():
    cfg, state = amp.initialize(opt_level="O2", half_dtype=jnp.float16)
    policy = cfg.policy
    assert policy.compute_dtype == jnp.float16
    scaler = amp.DynamicLossScale(init_scale=2.0**16, growth_interval=4)
    sstate = scaler.init()

    model = MLP(dtype=policy.compute_dtype)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 16), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, size=(64,)))

    params0 = model.init(jax.random.PRNGKey(0), x)["params"]
    params = policy.cast_to_param(params0)          # fp16 model params
    master = amp.make_master(params)                # fp32 masters
    opt = FusedSGD(lr=0.05, momentum=0.9, master_weights=False)
    opt_state = opt.init(master.params)

    @jax.jit
    def step(master_params, opt_state, sstate, batch_x):
        model_params = jax.tree_util.tree_map(
            lambda m: jnp.asarray(m, jnp.float16), master_params)

        def loss_fn(p):
            logits = model.apply(
                {"params": p}, policy.cast_to_compute(batch_x))
            losses = -jax.nn.log_softmax(
                logits.astype(jnp.float32))[jnp.arange(64), y]
            return scaler.scale(jnp.mean(losses), sstate)

        scaled_loss, grads = jax.value_and_grad(loss_fn)(model_params)
        # fp16 grads -> fp32 unscale (the O2 master-grad flow)
        grads = scaler.unscale(grads, sstate)
        finite = amp.all_finite(grads)
        new_sstate = scaler.update(sstate, finite)
        new_master, new_opt = opt.step(grads, opt_state, master_params,
                                       skip_update=~finite)
        loss = scaled_loss / sstate.scale
        return new_master, new_opt, new_sstate, loss, finite

    mp = master.params
    losses = []
    for i in range(6):
        mp, opt_state, sstate, loss, finite = step(mp, opt_state, sstate, x)
        assert bool(finite)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert float(sstate.scale) == 2.0**17  # grew once after 4 clean steps
    scale_before = float(sstate.scale)
    mp_before = jax.device_get(mp)

    # ---- inject a real overflow: huge activations -> inf fp16 grads ----
    mp, opt_state, sstate, loss, finite = step(mp, opt_state, sstate,
                                               x * 3e4)
    assert not bool(finite)
    assert bool(sstate.found_inf)
    assert float(sstate.scale) == scale_before * 0.5   # backoff
    for a, b in zip(jax.tree_util.tree_leaves(mp),
                    jax.tree_util.tree_leaves(mp_before)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # skip

    # ---- recovery: clean steps keep training and the scale regrows ----
    recov = []
    for i in range(5):
        mp, opt_state, sstate, loss, finite = step(mp, opt_state, sstate, x)
        assert bool(finite)
        recov.append(float(loss))
    assert float(sstate.scale) == scale_before  # regrew after interval
    assert np.isfinite(recov).all()
    assert recov[-1] <= losses[-1] + 1e-3  # training resumed, no regression


def test_fp16_hysteresis_delays_backoff():
    """hysteresis>1: the first overflow decrements the tracker only; the
    scale drops after `hysteresis` consecutive overflows
    (csrc/update_scale_hysteresis.cu behavior)."""
    scaler = amp.DynamicLossScale(init_scale=1024.0, hysteresis=2)
    s = scaler.init()
    s = scaler.update(s, False)
    assert float(s.scale) == 1024.0 and int(s.hysteresis_tracker) == 1
    s = scaler.update(s, False)
    assert float(s.scale) == 512.0
    s = scaler.update(s, True)
    assert int(s.hysteresis_tracker) == 2  # reset on clean step
