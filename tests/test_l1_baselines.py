"""L1 stored-baseline comparison (reference ``tests/L1/common/compare.py``
/ ``run_test.sh``): per-iteration loss + grad-norm traces must match the
checked-in baselines within tolerance — the strong form of numerics
regression testing the round-1 VERDICT asked for."""

import json
import os

import pytest

from apex_tpu.testing.l1 import CONFIGS, compare_traces, run_trace

pytestmark = pytest.mark.slow

BASE_DIR = os.path.join(os.path.dirname(__file__), "L1", "baselines")


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_trace_matches_baseline(name):
    path = os.path.join(BASE_DIR, f"{name}.json")
    assert os.path.exists(path), (
        f"missing baseline {path}; record with "
        f"`python -m apex_tpu.testing.l1 record tests/L1/baselines`")
    with open(path) as f:
        baseline = json.load(f)
    got = run_trace(name)
    problems = compare_traces(got, baseline)
    assert not problems, "\n".join(problems)
    # and the smoke run itself is healthy
    assert got["loss"][-1] < got["loss"][0]
