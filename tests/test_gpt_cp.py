"""Context-parallel GPT integration: ring attention inside the standalone
model stack, trained with the sequence dimension sharded over cp.

Parity target: the same modules, same params, full sequence, single
device (flash path) — the reference-style grid-vs-serial check
(``test_pipeline_parallel_fwd_bwd.py`` pattern applied to the cp axis).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import parallel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.ops.softmax import AttnMaskType
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
from apex_tpu.transformer.testing import TransformerConfig
from apex_tpu.transformer.testing.gpt_cp_train import build_gpt_cp
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    Embedding,
    ParallelTransformerLayer,
    parallel_lm_logits,
)

pytestmark = pytest.mark.slow

VOCAB, SEQ = 64, 32
DP, CP = 2, 4


def make_cfg(**kw):
    base = dict(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=VOCAB, max_position_embeddings=SEQ,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
        use_flash_attention=True, context_axis="cp",
    )
    base.update(kw)
    return TransformerConfig(**base)


def serial_loss(cfg_cp, params, tokens):
    """Same modules/params on the full sequence, no mesh (flash path)."""
    cfg = dataclasses.replace(cfg_cp, context_axis=None)
    embed = Embedding(cfg)
    layer = ParallelTransformerLayer(
        cfg, self_attn_mask_type=AttnMaskType.causal)
    ln = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon)

    h = embed.apply({"params": params["embedding"]}, tokens)
    for i in range(cfg.num_layers):
        h = layer.apply({"params": params[f"layer_{i}"]}, h, None)
    h = ln.apply({"params": params["final_ln"]}, h)
    logits = parallel_lm_logits(
        h, params["embedding"]["word_embeddings"]["embedding"], cfg)
    # next-token objective over the full sequence
    labels = tokens[:, 1:]
    lg = logits[:-1]
    per_tok = softmax_cross_entropy_loss(
        jnp.transpose(lg, (1, 0, 2)).reshape(-1, lg.shape[-1])
        .astype(jnp.float32),
        labels.reshape(-1), padding_idx=-1)
    return jnp.mean(per_tok)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_cp_loss_and_grads_match_serial(impl):
    mesh = parallel.initialize_model_parallel(context_parallel_size=CP)
    cfg = make_cfg(context_impl=impl)
    init_fn, make_loss_fn, _ = build_gpt_cp(cfg, mesh=mesh)
    batch = DP * 2
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, SEQ), 0,
                                VOCAB)
    params, specs = init_fn(jax.random.PRNGKey(0), tokens)

    loss_fn = make_loss_fn(specs)
    l_cp = float(jax.jit(loss_fn)(params, tokens))
    l_ref = float(serial_loss(cfg, params, tokens))
    np.testing.assert_allclose(l_cp, l_ref, rtol=1e-5)

    g_cp = jax.jit(jax.grad(loss_fn))(params, tokens)
    g_ref = jax.grad(lambda p: serial_loss(cfg, p, tokens))(params)
    flat_cp, _ = jax.tree_util.tree_flatten_with_path(g_cp)
    flat_ref, _ = jax.tree_util.tree_flatten_with_path(g_ref)
    for (path, a), (_, b) in zip(flat_cp, flat_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=str(path))


def test_cp_gpt_trains():
    mesh = parallel.initialize_model_parallel(context_parallel_size=CP)
    cfg = make_cfg()
    init_fn, _, make_step = build_gpt_cp(cfg, mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (DP * 2, SEQ), 0,
                                VOCAB)
    params, specs = init_fn(jax.random.PRNGKey(2), tokens)
    opt = FusedAdam(lr=2e-3)
    state = opt.init(params)
    step = jax.jit(make_step(opt, specs))
    losses = []
    for _ in range(8):
        params, state, loss = step(params, state, tokens)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_cp_rejects_bad_config():
    parallel.initialize_model_parallel(context_parallel_size=CP)
    with pytest.raises(ValueError, match="context_axis"):
        build_gpt_cp(make_cfg(context_axis=None))
    with pytest.raises(ValueError, match="tensor_axis"):
        build_gpt_cp(make_cfg(tensor_axis="tp"))
