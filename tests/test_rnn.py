"""RNN family (SURVEY row 19): torch parity + scan/grad behavior.

The reference (``apex/RNN``) wraps torch cells; the ground truth for the
gate math is therefore ``torch.nn.LSTM``/``GRU``/``RNN`` itself — these
tests copy torch's weights into the scan-based implementation leaf-for-
leaf (same ``[gates*h, in]`` layout) and require matching outputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.rnn import GRU, LSTM, RNN, ReLU, Tanh, mLSTM

torch = pytest.importorskip("torch")

T, B, IN, H = 5, 3, 6, 8


def _torch_weights_to_params(tm, num_layers, bidirectional, bias):
    """torch RNNBase -> the flax param dict of apex_tpu.rnn.RNN."""
    params = {}
    dirs = 2 if bidirectional else 1
    for layer in range(num_layers):
        for d in range(dirs):
            name = f"l{layer}{'_rev' if d else ''}"
            sfx = f"l{layer}{'_reverse' if d else ''}"
            params[f"{name}_w_ih"] = jnp.asarray(
                getattr(tm, f"weight_ih_{sfx}").detach().numpy())
            params[f"{name}_w_hh"] = jnp.asarray(
                getattr(tm, f"weight_hh_{sfx}").detach().numpy())
            if bias:
                params[f"{name}_b_ih"] = jnp.asarray(
                    getattr(tm, f"bias_ih_{sfx}").detach().numpy())
                params[f"{name}_b_hh"] = jnp.asarray(
                    getattr(tm, f"bias_hh_{sfx}").detach().numpy())
    return params


@pytest.mark.parametrize("kind,cls,tcls", [
    ("lstm", LSTM, torch.nn.LSTM),
    ("gru", GRU, torch.nn.GRU),
])
@pytest.mark.parametrize("layers,bidi,bias", [
    (1, False, True), (2, True, True), (2, False, False),
])
def test_torch_parity(kind, cls, tcls, layers, bidi, bias):
    tm = tcls(IN, H, num_layers=layers, bias=bias, bidirectional=bidi)
    tm.eval()
    x = np.random.RandomState(0).randn(T, B, IN).astype(np.float32)

    with torch.no_grad():
        t_out, t_hidden = tm(torch.from_numpy(x))

    model = cls(IN, H, num_layers=layers, bias=bias, bidirectional=bidi)
    params = _torch_weights_to_params(tm, layers, bidi, bias)
    out, hidden = model.apply({"params": params}, jnp.asarray(x))

    np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                               rtol=1e-5, atol=1e-5)
    if kind == "lstm":
        th, tc = t_hidden
        np.testing.assert_allclose(np.asarray(hidden[0]), th.numpy(),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(hidden[1]), tc.numpy(),
                                   rtol=1e-5, atol=1e-5)
    else:
        np.testing.assert_allclose(np.asarray(hidden[0]),
                                   t_hidden.numpy(), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("kind,tmode", [("relu", "RNN_RELU"),
                                        ("tanh", "RNN_TANH")])
def test_elementary_cells_torch_parity(kind, tmode):
    tm = torch.nn.RNN(IN, H, num_layers=1,
                      nonlinearity=kind, bias=True)
    tm.eval()
    x = np.random.RandomState(1).randn(T, B, IN).astype(np.float32)
    with torch.no_grad():
        t_out, t_h = tm(torch.from_numpy(x))

    cls = ReLU if kind == "relu" else Tanh
    model = cls(IN, H, num_layers=1)
    params = _torch_weights_to_params(tm, 1, False, True)
    out, hidden = model.apply({"params": params}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hidden[0]), t_h.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_batch_first_and_hidden_roundtrip():
    model = LSTM(IN, H, num_layers=2, batch_first=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (B, T, IN))
    params = model.init(jax.random.PRNGKey(1), x)["params"]
    out, (h, c) = model.apply({"params": params}, x)
    assert out.shape == (B, T, H)
    assert h.shape == (2, B, H) and c.shape == (2, B, H)
    # continuing from the returned hidden == running the concat sequence
    x2 = jax.random.normal(jax.random.PRNGKey(2), (B, T, IN))
    out2, _ = model.apply({"params": params}, x2, hidden=(h, c))
    out_full, _ = model.apply({"params": params},
                              jnp.concatenate([x, x2], axis=1))
    np.testing.assert_allclose(np.asarray(out2),
                               np.asarray(out_full[:, T:]),
                               rtol=1e-5, atol=1e-5)


def test_mlstm_forward_matches_reference_math():
    """mLSTM (cells.py:55-80): m = (x @ w_mih.T) * (h @ w_mhh.T), LSTM
    gates on x and m — checked against a direct numpy transcription."""
    model = mLSTM(IN, H, num_layers=1, bias=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, IN))
    params = model.init(jax.random.PRNGKey(1), x)["params"]
    out, (hT, cT) = model.apply({"params": params}, x)

    p = {k: np.asarray(v) for k, v in params.items()}
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    xs = np.asarray(x)

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    for t in range(T):
        m = (xs[t] @ p["l0_w_mih"].T) * (h @ p["l0_w_mhh"].T)
        gates = (xs[t] @ p["l0_w_ih"].T + p["l0_b_ih"]
                 + m @ p["l0_w_hh"].T + p["l0_b_hh"])
        i, f, g, o = np.split(gates, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        np.testing.assert_allclose(np.asarray(out[t]), h,
                                   rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hT[0]), h, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cT[0]), c, rtol=1e-5, atol=1e-5)


def test_output_size_projection():
    """RNNCell's w_ho path (RNNBackend.py:361-363): the recurrent state is
    the *projected* output, so w_hh consumes output_size features."""
    model = LSTM(IN, H, num_layers=1, output_size=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, IN))
    params = model.init(jax.random.PRNGKey(1), x)["params"]
    assert params["l0_w_ho"].shape == (4, H)
    assert params["l0_w_hh"].shape == (4 * H, 4)
    out, (h, c) = model.apply({"params": params}, x)
    assert out.shape == (T, B, 4)
    assert h.shape == (1, B, 4) and c.shape == (1, B, H)


def test_mlstm_output_size_projection():
    """mLSTM + w_ho: the reference sizes w_mih/w_mhh/w_hh by *output_size*
    (RNNBackend.py:258, cells.py:20-22) — m is output_size-dimensional."""
    model = mLSTM(IN, H, num_layers=1, output_size=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, IN))
    params = model.init(jax.random.PRNGKey(1), x)["params"]
    assert params["l0_w_mih"].shape == (4, IN)
    assert params["l0_w_mhh"].shape == (4, 4)
    assert params["l0_w_hh"].shape == (4 * H, 4)
    out, (h, c) = model.apply({"params": params}, x)
    assert out.shape == (T, B, 4)
    assert h.shape == (1, B, 4) and c.shape == (1, B, H)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_amp_compute_dtype():
    """The amp-policy contract (COVERAGE row 7): fp32 params, bf16
    compute/output — the module casts at its boundary like every flax
    module under the O1/O2 policies."""
    from apex_tpu import amp

    policy = amp.policy("O1")  # bf16 compute, fp32 params
    model = LSTM(IN, H, num_layers=1, dtype=policy.compute_dtype)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, IN))
    params = model.init(jax.random.PRNGKey(1), x)["params"]
    assert params["l0_w_ih"].dtype == jnp.float32  # storage stays fp32
    out, (h, c) = model.apply({"params": params}, x)
    assert out.dtype == jnp.bfloat16
    assert h.dtype == jnp.bfloat16

    # gradients flow (through the bf16 scan) back to fp32 params
    g = jax.grad(lambda p: jnp.sum(
        model.apply({"params": p}, x)[0].astype(jnp.float32)))(params)
    assert g["l0_w_ih"].dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(g["l0_w_ih"])))


def test_gru_output_size_rejected():
    """GRU's convex update can't carry a projected state — clear error
    instead of a trace-time broadcast crash (r3 review finding)."""
    model = GRU(IN, H, num_layers=1, output_size=4)
    x = jnp.zeros((T, B, IN))
    with pytest.raises(ValueError, match="does not support output_size"):
        model.init(jax.random.PRNGKey(0), x)


def test_trains_under_jit():
    """The whole stack is differentiable through the scan and trains."""
    model = GRU(IN, H, num_layers=2, dropout=0.1)
    x = jax.random.normal(jax.random.PRNGKey(0), (T, B, IN))
    y = jnp.roll(x, 1, axis=0)  # memorize-previous-input task
    variables = model.init(jax.random.PRNGKey(1), x)
    params = variables["params"]

    head = jax.random.normal(jax.random.PRNGKey(2), (H, IN)) * 0.1

    @jax.jit
    def step(params, head, key):
        def loss_fn(params, head):
            out, _ = model.apply(
                {"params": params}, x, deterministic=False,
                rngs={"dropout": key})
            return jnp.mean((out @ head - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            params, head)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 0.2 * g, params, grads[0])
        return params, head - 0.2 * grads[1], loss

    losses = []
    key = jax.random.PRNGKey(3)
    for i in range(300):
        key, k = jax.random.split(key)
        params, head, loss = step(params, head, k)
        losses.append(float(loss))
    # the wrapped roll target makes t=0 unlearnable (causal RNN), so the
    # loss has a floor; 300 sgd steps reliably reach ~0.58x of init
    assert losses[-1] < losses[0] * 0.7, losses[::50]
