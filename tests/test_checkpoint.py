"""Checkpoint/resume: bit-exact round trips for full train state, incl.
gathering/scattering ZeRO-sharded optimizer state (the reference's
``DistributedFusedAdam.state_dict(gather_on_root)`` contract)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.checkpoint import (
    gather_zero_state,
    restore_checkpoint,
    save_checkpoint,
    scatter_zero_state,
)
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import collectives as cc

pytestmark = pytest.mark.slow


def test_roundtrip_bit_exact_resume(tmp_path):
    """Save at step 3, train to 6; restore at 3, train to 6: identical."""
    import flax.linen as nn

    from apex_tpu import amp

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x)

    model = MLP()
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 8))
    y = jax.random.randint(jax.random.PRNGKey(1), (32,), 0, 4)
    params = model.init(jax.random.PRNGKey(2), x)["params"]
    opt = FusedAdam(lr=1e-2)
    scaler = amp.DynamicLossScale()

    @jax.jit
    def step(params, opt_state, sstate):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            losses = -jax.nn.log_softmax(logits)[jnp.arange(32), y]
            return scaler.scale(jnp.mean(losses), sstate)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = scaler.unscale(grads, sstate)
        finite = amp.all_finite(grads)
        sstate = scaler.update(sstate, finite)
        params, opt_state = opt.step(grads, opt_state, params,
                                     skip_update=~finite)
        return params, opt_state, sstate, loss

    opt_state = opt.init(params)
    sstate = scaler.init()
    for _ in range(3):
        params, opt_state, sstate, _ = step(params, opt_state, sstate)

    ckpt = {"params": params, "opt": opt_state, "scaler": sstate}
    save_checkpoint(str(tmp_path / "ck.npz"), ckpt, step=3)

    cont = []
    p2, o2, s2 = params, opt_state, sstate
    for _ in range(3):
        p2, o2, s2, loss = step(p2, o2, s2)
        cont.append(np.asarray(loss))

    restored, at = restore_checkpoint(str(tmp_path / "ck.npz"), ckpt)
    assert at == 3
    p3, o3, s3 = restored["params"], restored["opt"], restored["scaler"]
    resumed = []
    for _ in range(3):
        p3, o3, s3, loss = step(p3, o3, s3)
        resumed.append(np.asarray(loss))

    np.testing.assert_array_equal(np.stack(cont), np.stack(resumed))
    for a, b in zip(jax.tree_util.tree_leaves(p2),
                    jax.tree_util.tree_leaves(p3)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_save_roundtrip(tmp_path):
    """save_checkpoint_async: the device state may be donated/overwritten
    immediately after the call (D2H completes synchronously); the write
    completes in the background and restores bit-exact."""
    from apex_tpu.checkpoint import restore_checkpoint, save_checkpoint_async

    path = str(tmp_path / "async.npz")
    host_counter = np.arange(4)  # host-numpy leaf (e.g. consumed_samples)
    tree = {"w": jnp.arange(8.0), "counter": host_counter}
    fut = save_checkpoint_async(path, tree, step=7)
    # mutate the sources immediately: the snapshot must not see it —
    # including *in-place* mutation of the host-numpy leaf (zero-copy
    # aliasing hazard, r3 review finding)
    tree["w"] = tree["w"] + 100.0
    host_counter += 50
    assert fut.result(timeout=30) == path
    restored, step = restore_checkpoint(path, like=tree)
    assert step == 7
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(8.0))
    np.testing.assert_array_equal(np.asarray(restored["counter"]),
                                  np.arange(4))


def test_restore_mismatch_raises(tmp_path):
    tree = {"a": jnp.ones((3,)), "b": jnp.zeros((2, 2))}
    save_checkpoint(str(tmp_path / "c.npz"), tree)
    with pytest.raises(ValueError, match="shape"):
        restore_checkpoint(str(tmp_path / "c.npz"),
                           {"a": jnp.ones((4,)), "b": jnp.zeros((2, 2))})
    with pytest.raises(ValueError, match="leaves"):
        restore_checkpoint(str(tmp_path / "c.npz"), {"a": jnp.ones((3,))})


@pytest.mark.parametrize("flat_bucket", [True, False])
@pytest.mark.parametrize("remainders", [False, True])
def test_zero_state_gather_scatter(remainders, flat_bucket):
    """Portable ZeRO state: gather -> full fp32 per-param state; scatter
    back -> bitwise-identical sharded state; resumed sharded training
    matches uninterrupted training exactly.  Runs for both state layouts
    (flat-bucket buffers and per-leaf chunks) — the portable format is
    layout-independent."""
    mesh = parallel.initialize_model_parallel()  # dp=8
    try:
        dtype = jnp.bfloat16 if remainders else jnp.float32
        params = {
            "w": jax.random.normal(jax.random.PRNGKey(0), (13, 7), dtype),
            "b": jax.random.normal(jax.random.PRNGKey(1), (8,), dtype),
        }
        grads = {
            "w": jax.random.normal(jax.random.PRNGKey(2), (13, 7)),
            "b": jax.random.normal(jax.random.PRNGKey(3), (8,)),
        }
        opt = DistributedFusedAdam(lr=1e-2,
                                   store_param_remainders=remainders,
                                   flat_bucket=flat_bucket, n_buckets=2)

        def train(params, grads, steps):
            def local(p, g):
                state = opt.init(p)
                for _ in range(steps):
                    p, state = opt.step(g, state, p)
                return p, state
            return local

        state_specs = opt.state_partition_specs(params)

        p1, s1 = cc.shard_over(
            train(params, grads, 2), in_specs=(P(), P()),
            out_specs=(P(), state_specs))(params, grads)

        portable = gather_zero_state(opt, s1, p1)
        for name, tree in portable["slots"].items():
            for leaf, p in zip(jax.tree_util.tree_leaves(tree),
                               jax.tree_util.tree_leaves(p1)):
                assert leaf.shape == p.shape
        if remainders:
            assert portable["master"]["w"].dtype == jnp.float32

        resharded = scatter_zero_state(opt, portable, s1, p1)
        for a, b in zip(jax.tree_util.tree_leaves(s1),
                        jax.tree_util.tree_leaves(resharded)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # resume from the re-scattered state == uninterrupted run
        def resume(p, g, state):
            def local(p, g, state):
                for _ in range(2):
                    p, state = opt.step(g, state, p)
                return p
            return cc.shard_over(
                local, in_specs=(P(), P(), state_specs), out_specs=P()
            )(p, g, state)

        p_resumed = resume(p1, grads, resharded)
        p_straight, _ = cc.shard_over(
            train(params, grads, 4), in_specs=(P(), P()),
            out_specs=(P(), state_specs))(params, grads)
        for a, b in zip(jax.tree_util.tree_leaves(p_resumed),
                        jax.tree_util.tree_leaves(p_straight)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        parallel.destroy_model_parallel()


def test_sharded_roundtrip_single_process(tmp_path):
    """Per-process sharded save/restore: dp/tp-sharded leaves come back
    bit-exact with their shardings, each distinct slice stored once."""
    from jax.sharding import NamedSharding

    from apex_tpu.checkpoint import (
        restore_checkpoint_sharded,
        save_checkpoint_sharded,
    )

    mesh = parallel.initialize_model_parallel(tensor_model_parallel_size=2)
    try:
        rng = np.random.RandomState(0)
        w = jax.device_put(
            rng.randn(16, 8).astype(np.float32),
            NamedSharding(mesh, P(("dcn", "dp"), "tp")))
        b = jax.device_put(rng.randn(8).astype(np.float32),
                           NamedSharding(mesh, P("tp")))
        scale = jax.device_put(jnp.float32(3.5), NamedSharding(mesh, P()))
        tree = {"w": w, "b": b, "scale": scale, "host": np.arange(3)}

        ckpt = str(tmp_path / "sharded")
        save_checkpoint_sharded(ckpt, tree, step=11)

        like = jax.tree_util.tree_map(lambda x: x, tree)
        restored, step = restore_checkpoint_sharded(ckpt, like)
        assert step == 11
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(w))
        np.testing.assert_array_equal(np.asarray(restored["b"]),
                                      np.asarray(b))
        assert float(restored["scale"]) == 3.5
        np.testing.assert_array_equal(restored["host"], np.arange(3))
        assert restored["w"].sharding.is_equivalent_to(w.sharding, w.ndim)

        # replicated/partially-replicated leaves stored once per slice,
        # not once per replica: b is tp-sharded (2 slices) but replicated
        # over dp — exactly 2 stored pieces
        import json as _json

        with np.load(f"{ckpt}/shard_0.npz") as data:
            manifest = _json.loads(str(data["__manifest__"]))
            b_i = next(i for i, rec in enumerate(manifest["leaves"])
                       if rec["path"] == "b")
            b_keys = [k for k in data.files
                      if k.startswith(f"leaf_{b_i}|")]
        assert len(b_keys) == 2, b_keys
    finally:
        parallel.mesh.destroy_model_parallel()


def test_sharded_restore_across_mesh_shapes(tmp_path):
    """Save under tp=2, restore under tp=4 (different slice boundaries):
    the stitcher reassembles the needed slices."""
    from jax.sharding import NamedSharding

    from apex_tpu.checkpoint import (
        restore_checkpoint_sharded,
        save_checkpoint_sharded,
    )

    rng = np.random.RandomState(1)
    host_w = rng.randn(8, 8).astype(np.float32)

    mesh = parallel.initialize_model_parallel(tensor_model_parallel_size=2)
    try:
        w = jax.device_put(host_w, NamedSharding(mesh, P(None, "tp")))
        save_checkpoint_sharded(str(tmp_path / "c"), {"w": w}, step=1)
    finally:
        parallel.mesh.destroy_model_parallel()

    mesh4 = parallel.initialize_model_parallel(tensor_model_parallel_size=4)
    try:
        like = {"w": jax.device_put(jnp.zeros((8, 8), jnp.float32),
                                    NamedSharding(mesh4, P("tp", None)))}
        restored, _ = restore_checkpoint_sharded(str(tmp_path / "c"), like)
        np.testing.assert_array_equal(np.asarray(restored["w"]), host_w)
        assert restored["w"].sharding.is_equivalent_to(
            like["w"].sharding, 2)
    finally:
        parallel.mesh.destroy_model_parallel()


def test_sharded_rejects_stale_and_casts_dtype(tmp_path):
    """Stale extra shard files are ignored when the committed
    ``manifest.json`` is present (it names exactly the files the save
    owns) and fail loudly on legacy dirs without one; restore casts to
    the template's dtype (the portable-precision flow)."""
    import os

    from jax.sharding import NamedSharding

    from apex_tpu.checkpoint import (
        restore_checkpoint_sharded,
        save_checkpoint_sharded,
    )

    ckpt = str(tmp_path / "c")
    mesh = parallel.initialize_model_parallel()
    try:
        w = jax.device_put(jnp.arange(32.0).reshape(8, 4),
                           NamedSharding(mesh, P(("dcn", "dp"), None)))
        save_checkpoint_sharded(ckpt, {"w": w}, step=2)

        # stale file from an imaginary larger-cluster run: the committed
        # manifest does not reference it, so restore ignores it
        import shutil

        shutil.copy(f"{ckpt}/shard_0.npz", f"{ckpt}/shard_7.npz")
        like = {"w": w}
        restored, step = restore_checkpoint_sharded(ckpt, like)
        assert step == 2

        # legacy dir (no committed manifest): the stale file fails loudly
        os.unlink(f"{ckpt}/manifest.json")
        with pytest.raises(ValueError, match="stale|duplicate"):
            restore_checkpoint_sharded(ckpt, like)

        # re-saving into the legacy dir cleans the stale file (the old
        # index-vs-process_count rule still applies without a committed
        # manifest) and recommits manifest.json
        save_checkpoint_sharded(ckpt, {"w": w}, step=3)
        assert not os.path.exists(f"{ckpt}/shard_7.npz")
        restored, step = restore_checkpoint_sharded(ckpt, like)
        assert step == 3

        # dtype follows the template: restore fp32 shards into bf16
        like_bf16 = {"w": jax.device_put(
            jnp.zeros((8, 4), jnp.bfloat16),
            NamedSharding(mesh, P(("dcn", "dp"), None)))}
        r2, _ = restore_checkpoint_sharded(ckpt, like_bf16)
        assert r2["w"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(r2["w"], np.float32), np.arange(32.0).reshape(8, 4))
    finally:
        parallel.mesh.destroy_model_parallel()


def test_sharded_async_save_roundtrip(tmp_path):
    """Async sharded save: device buffers may be donated immediately; the
    background write lands and restores bit-exact after finalize()."""
    from jax.sharding import NamedSharding

    from apex_tpu.checkpoint import (
        restore_checkpoint_sharded,
        save_checkpoint_sharded_async,
    )

    mesh = parallel.initialize_model_parallel()
    try:
        sharding = NamedSharding(mesh, P(("dcn", "dp"), None))
        w = jax.device_put(jnp.arange(32.0).reshape(8, 4), sharding)
        host = np.arange(5)
        ckpt = str(tmp_path / "async_sharded")
        handle = save_checkpoint_sharded_async(
            ckpt, {"w": w, "host": host}, step=9)

        # overwrite the sources immediately (donation hazard): the
        # snapshot must not see it
        w_new = jax.jit(lambda a: a * 0 - 1.0, donate_argnums=0)(w)
        host += 100
        assert float(w_new[0, 0]) == -1.0

        path = handle.finalize(timeout=30)
        assert path.endswith("shard_0.npz")
        like = {"w": jax.device_put(jnp.zeros((8, 4)), sharding),
                "host": np.zeros(5, np.int64)}
        restored, step = restore_checkpoint_sharded(ckpt, like)
        assert step == 9
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(32.0).reshape(8, 4))
        np.testing.assert_array_equal(restored["host"], np.arange(5))
    finally:
        parallel.mesh.destroy_model_parallel()
