"""Elastic resume smoke, fast tier (ISSUE 6 CI satellite).

Runs ``scripts/elastic_resume_smoke.sh`` in a subprocess — the real
kill-at-mesh-N / resume-at-mesh-M sequence: an async-sharded-saving
trainer is SIGKILLed mid-save on the source mesh and resumed on a
DIFFERENT mesh shape, where ``restore_latest`` reshards the newest
intact checkpoint through the logical-spec layer
(``apex_tpu.resilience.reshard``).  The script asserts the pre-kill
loss prefix matches the uninterrupted source-mesh reference
bit-exactly, the post-resume curve matches a clean (no-kill) reshard
continuation bit-exactly, and the final mesh-independent state digests
(``reshard.load_logical``, per-leaf sha256) are identical.

The fast tier runs the flat-bucket ZeRO leg — save at dp=4, SIGKILL
mid-save, resume at dp=2 — because it is the hard case of
restore-anywhere (the ``(rows, chunk)`` optimizer buffers are
mesh-shape-DEPENDENT and must be unflattened and re-chunked for the
new world) and compiles in seconds.  The 3D GPT legs (dp 4->2 and the
tp=2,pp=2 -> tp=4,pp=1 ``[vpp, pp]`` layer-stack re-factor) each cost
two full trainer compiles, so they carry ``-m slow``; the remaining
transitions (dp 2->4, reverses) run the same script with
``SRC_ARGS``/``DST_ARGS`` — see docs/resilience.md "restore-anywhere".
Subprocess for the same reason as ``tests/test_crash_resume.py``:
device-count pinning must precede backend init, and a SIGKILL needs a
process to kill.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_smoke(workdir, mode, src_args=None, dst_args=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the trainer pins its own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["MODE"] = mode
    env["PYTHON"] = sys.executable
    if src_args:
        env["SRC_ARGS"] = src_args
    if dst_args:
        env["DST_ARGS"] = dst_args
    proc = subprocess.run(
        ["bash", os.path.join(_REPO, "scripts",
                              "elastic_resume_smoke.sh"), str(workdir)],
        cwd=_REPO, env=env, capture_output=True, timeout=540,
    )
    assert proc.returncode == 0, (
        f"elastic_resume_smoke.sh [{mode}] rc={proc.returncode}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-3000:]}"
    )
    assert b"PASS" in proc.stderr


def test_elastic_resume_zero_flat_bucket_dp4_to_dp2(tmp_path):
    _run_smoke(tmp_path / "work", "zero")


@pytest.mark.slow
def test_elastic_resume_gpt_dp4_to_dp2(tmp_path):
    """The 3D GPT dp 4->2 leg (layer placement + replicated FusedAdam
    state through the spec layer).  Slow tier: two trainer compiles
    (~107 s) — the fast-tier budget keeps the ZeRO leg, whose state is
    the one that actually changes shape with the mesh."""
    _run_smoke(tmp_path / "work", "gpt")


@pytest.mark.slow
def test_elastic_resume_gpt_tp2pp2_to_tp4pp1(tmp_path):
    """The model-parallel re-factor leg: a tp=2,pp=2 checkpoint resumed
    at tp=4,pp=1 (layer stacks merged [vpp, pp] -> [L] and re-split,
    tp shardings re-placed).  Slow tier: two distinct 3D compiles."""
    _run_smoke(tmp_path / "work", "gpt",
               src_args="--tp 2 --pp 2 --devices 4",
               dst_args="--tp 4 --pp 1 --devices 4")
