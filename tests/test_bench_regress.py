"""The bench regression gate (ISSUE 10): ``scripts/bench_regress.py``
must exit 0 on the repo's real BENCH_r01→r05 / MULTICHIP_r01→r05
history and nonzero on a fixture with an injected >tolerance
regression — the five rounds of driver evidence finally get an
automated check instead of a human reading JSON."""

import copy
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "bench_regress.py")

sys.path.insert(0, os.path.join(REPO, "scripts"))
import bench_regress  # noqa: E402


def _run(*args):
    return subprocess.run(
        [sys.executable, SCRIPT, *args], capture_output=True, text=True,
        cwd=REPO)


def _copy_history(tmp_path):
    for name in sorted(os.listdir(REPO)):
        if name.startswith(("BENCH_r", "MULTICHIP_r")) and \
                name.endswith(".json"):
            shutil.copy(os.path.join(REPO, name), tmp_path / name)


def _newest_bench(tmp_path):
    names = sorted(p for p in os.listdir(tmp_path)
                   if p.startswith("BENCH_r"))
    with open(tmp_path / names[-1]) as f:
        rec = json.load(f)
    return names[-1], rec


def _write_round(tmp_path, name, rec, n):
    rec = copy.deepcopy(rec)
    rec["n"] = n
    with open(tmp_path / name, "w") as f:
        json.dump(rec, f)
    return rec


class TestRealHistory:
    def test_exit_zero_on_repo_records(self):
        """The standing acceptance: the real r01→r05 evidence is not a
        regression against itself."""
        proc = _run()
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "no regressions" in proc.stdout

    def test_no_records_is_a_usage_error(self, tmp_path):
        proc = _run("--dir", str(tmp_path))
        assert proc.returncode == 2


class TestInjectedRegression:
    def test_value_drop_beyond_tolerance_fails(self, tmp_path):
        """A >tolerance drop on a higher-is-better whitelist row in a
        new round exits nonzero and names the row."""
        _copy_history(tmp_path)
        _, newest = _newest_bench(tmp_path)
        assert newest["parsed"], "fixture expects r05's parsed compact"
        bad = copy.deepcopy(newest)
        # 70% drop >> the 40% default tolerance
        bad["parsed"]["rows"]["gpt_flash"]["value"] *= 0.3
        _write_round(tmp_path, "BENCH_r06.json", bad, n=6)
        proc = _run("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "gpt_flash" in proc.stdout and "REGRESSION" in proc.stdout

    def test_within_tolerance_noise_passes(self, tmp_path):
        """A 10% dip is CPU noise, not a regression."""
        _copy_history(tmp_path)
        _, newest = _newest_bench(tmp_path)
        ok = copy.deepcopy(newest)
        ok["parsed"]["rows"]["gpt_flash"]["value"] *= 0.9
        _write_round(tmp_path, "BENCH_r06.json", ok, n=6)
        proc = _run("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_lower_is_better_direction(self, tmp_path):
        """us/step rows regress UPWARD: a 2x slower fused_adam_step
        fails, a 2x faster one does not."""
        _copy_history(tmp_path)
        _, newest = _newest_bench(tmp_path)
        slow = copy.deepcopy(newest)
        slow["parsed"]["rows"]["fused_adam_step"]["value"] *= 2.0
        _write_round(tmp_path, "BENCH_r06.json", slow, n=6)
        assert _run("--dir", str(tmp_path)).returncode == 1
        fast = copy.deepcopy(newest)
        fast["parsed"]["rows"]["fused_adam_step"]["value"] *= 0.5
        _write_round(tmp_path, "BENCH_r06.json", fast, n=6)
        assert _run("--dir", str(tmp_path)).returncode == 0

    def test_row_turning_error_fails(self, tmp_path):
        """A row that errors where history has clean values is fatal
        regardless of tolerance (noise-free signal)."""
        _copy_history(tmp_path)
        _, newest = _newest_bench(tmp_path)
        bad = copy.deepcopy(newest)
        bad["parsed"]["rows"]["bert_large"] = {"error": "rc=1: boom"}
        _write_round(tmp_path, "BENCH_r06.json", bad, n=6)
        proc = _run("--dir", str(tmp_path))
        assert proc.returncode == 1
        assert "bert_large" in proc.stdout

    def test_vs_bare_gate_ceiling(self, tmp_path):
        """The free-telemetry acceptance (vs_bare <= 1.05) is a hard
        ceiling, no history needed."""
        _copy_history(tmp_path)
        _, newest = _newest_bench(tmp_path)
        bad = copy.deepcopy(newest)
        bad["parsed"]["rows"]["telemetry_overhead"] = {
            "value": 180000.0, "unit": "us/step", "platform": "cpu",
            "vs_bare": 1.31}
        _write_round(tmp_path, "BENCH_r06.json", bad, n=6)
        proc = _run("--dir", str(tmp_path))
        assert proc.returncode == 1
        assert "vs_bare" in proc.stdout and "1.05" in proc.stdout

    def test_serving_spec_vs_baseline_floor(self, tmp_path):
        """The ISSUE 13 acceptance bar (speculation never slower than
        the plain engine) is a hard floor, no history needed — and a
        passing ratio is not flagged."""
        _copy_history(tmp_path)
        _, newest = _newest_bench(tmp_path)
        bad = copy.deepcopy(newest)
        bad["parsed"]["rows"]["serving_spec"] = {
            "value": 900.0, "unit": "tokens/sec", "platform": "cpu",
            "vs_baseline": 0.82, "mean_accept_len": 1.1}
        _write_round(tmp_path, "BENCH_r06.json", bad, n=6)
        proc = _run("--dir", str(tmp_path))
        assert proc.returncode == 1
        assert "vs_baseline" in proc.stdout and "floor" in proc.stdout
        ok = copy.deepcopy(newest)
        ok["parsed"]["rows"]["serving_spec"] = {
            "value": 2100.0, "unit": "tokens/sec", "platform": "cpu",
            "vs_baseline": 2.26, "mean_accept_len": 4.0}
        _write_round(tmp_path, "BENCH_r06.json", ok, n=6)
        assert _run("--dir", str(tmp_path)).returncode == 0

    def test_multichip_ok_drop_fails(self, tmp_path):
        _copy_history(tmp_path)
        rec = {"n_devices": 8, "rc": 1, "ok": False, "skipped": False,
               "tail": "boom"}
        with open(tmp_path / "MULTICHIP_r06.json", "w") as f:
            json.dump(rec, f)
        proc = _run("--dir", str(tmp_path))
        assert proc.returncode == 1
        assert "multichip" in proc.stdout

    def test_driver_rc_regression_fails(self, tmp_path):
        _copy_history(tmp_path)
        _, newest = _newest_bench(tmp_path)
        bad = copy.deepcopy(newest)
        bad["rc"] = 137
        bad["parsed"] = None
        bad["tail"] = "killed"
        _write_round(tmp_path, "BENCH_r06.json", bad, n=6)
        proc = _run("--dir", str(tmp_path))
        assert proc.returncode == 1


class TestRecordParsing:
    def test_parse_compact_prefers_parsed_field(self):
        rec = {"parsed": {"metric": "m", "value": 1.0},
               "tail": '{"metric": "other", "value": 9.0}'}
        assert bench_regress.parse_compact(rec)["value"] == 1.0

    def test_parse_compact_falls_back_to_tail(self):
        rec = {"parsed": None, "tail":
               'noise\n{"not": "a record"}\n'
               '{"metric": "m", "value": 3.0, "rows": {}}'}
        assert bench_regress.parse_compact(rec)["value"] == 3.0

    def test_parse_compact_none_when_tail_is_garbage(self):
        assert bench_regress.parse_compact(
            {"parsed": None, "tail": "Traceback ... mid-json {\"val"}) \
            is None

    def test_direction_from_unit(self):
        assert bench_regress.lower_is_better("us/step") is True
        assert bench_regress.lower_is_better("ms/reshard-restore") is True
        assert bench_regress.lower_is_better("tokens/sec/chip") is False
        assert bench_regress.lower_is_better(None) is None

    def test_pseudo_headline_row(self):
        rows = bench_regress._rows_of(
            {"metric": "m", "value": 5.0, "unit": "images/sec/chip",
             "platform": "cpu", "rows": {"a": {"value": 1.0}, "b": 2.0}})
        assert rows["headline"]["value"] == 5.0
        assert rows["b"] == {"value": 2.0}  # degraded record re-dicted


@pytest.mark.parametrize("platform_mix", ["cross", "same"])
def test_platform_isolation(tmp_path, platform_mix):
    """A CPU round is never judged against TPU history (and vice
    versa): an apparent 100x 'regression' across platforms is not
    compared at all."""
    hist = {"n": 1, "rc": 0, "tail": "", "parsed": {
        "metric": "m", "value": 8000.0, "unit": "images/sec/chip",
        "platform": "tpu", "rows": {
            "gpt_flash": {"value": 90000.0, "unit": "tokens/sec/chip",
                          "platform": "tpu"}}}}
    new_platform = "tpu" if platform_mix == "same" else "cpu"
    newest = {"n": 2, "rc": 0, "tail": "", "parsed": {
        "metric": "m", "value": 9.0, "unit": "images/sec/chip",
        "platform": new_platform, "rows": {
            "gpt_flash": {"value": 15000.0, "unit": "tokens/sec/chip",
                          "platform": new_platform}}}}
    for name, rec in (("BENCH_r01.json", hist), ("BENCH_r02.json", newest)):
        with open(tmp_path / name, "w") as f:
            json.dump(rec, f)
    with open(tmp_path / "MULTICHIP_r01.json", "w") as f:
        json.dump({"n_devices": 8, "rc": 0, "ok": True, "tail": ""}, f)
    rc = _run("--dir", str(tmp_path)).returncode
    # same-platform: 15000 vs 90000 tokens/sec is a real regression;
    # cross-platform: no comparison, no failure
    assert rc == (1 if platform_mix == "same" else 0)
