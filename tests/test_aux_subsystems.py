"""Aux subsystems: timers export, autoresume protocol, rank logger
(SURVEY §5 tracing / failure-detection / observability rows), the
input-pipeline smoke script (ISSUE 8 CI satellite), the serving smoke
script (ISSUE 9 CI satellite), the fleet-serving smoke script
(ISSUE 11 CI satellite), and the APX305 jit-stability sweep over the
registered serving programs (ISSUE 19 tier gate)."""

import json
import logging
import os
import subprocess
import sys

import pytest

from apex_tpu.log_util import get_transformer_logger, set_logging_level
from apex_tpu.transformer.testing.global_vars import (
    AutoResume,
    check_autoresume_termination,
    get_args,
    set_args,
    set_autoresume,
)
from apex_tpu.utils.timers import Timers


def test_timers_write_jsonl(tmp_path):
    t = Timers()
    t("fwd").start()
    t("fwd").stop()
    path = tmp_path / "timers.jsonl"
    t.write(["fwd", "missing"], str(path), iteration=3)
    rec = json.loads(path.read_text().strip())
    assert rec["iteration"] == 3
    assert "fwd" in rec["timers"] and rec["timers"]["fwd"] >= 0
    assert "missing" not in rec["timers"]


def test_timers_write_tensorboard_ducktype():
    calls = []

    class Writer:
        def add_scalar(self, tag, value, step):
            calls.append((tag, value, step))

    t = Timers()
    t("step").start()
    t("step").stop()
    t.write(["step"], Writer(), iteration=7)
    assert calls and calls[0][0] == "timers/step" and calls[0][2] == 7


def test_autoresume_file_protocol(tmp_path):
    sig = tmp_path / "preempt"
    ar = AutoResume(signal_file=str(sig), min_poll_interval=0.0)
    set_autoresume(ar)
    saved = []
    assert not check_autoresume_termination(1, saved.append)
    sig.write_text("now")
    assert check_autoresume_termination(2, saved.append)
    assert saved == [2]
    assert not sig.exists()  # request_resume cleared the sentinel
    set_autoresume(None)


def test_autoresume_env_protocol(monkeypatch):
    monkeypatch.setenv("APEX_TPU_AUTORESUME_TERMINATE", "1")
    ar = AutoResume(min_poll_interval=0.0)
    assert ar.termination_requested()
    # falsy strings mean "disabled", not "requested"
    for off in ("0", "false", "no", ""):
        monkeypatch.setenv("APEX_TPU_AUTORESUME_TERMINATE", off)
        ar.init()
        assert not ar.termination_requested(), off
    monkeypatch.delenv("APEX_TPU_AUTORESUME_TERMINATE")
    ar.init()
    assert not ar.termination_requested()


def test_global_args_registry():
    set_args(None)
    with pytest.raises(RuntimeError):
        get_args()
    set_args({"lr": 0.1})
    assert get_args()["lr"] == 0.1
    set_args(None)


def test_rank_logger_stamps_rank_info():
    import io

    import apex_tpu

    lg = get_transformer_logger(__name__)
    assert lg.name.startswith("apex_tpu.")
    set_logging_level(logging.INFO)
    root = logging.getLogger("apex_tpu")
    # capture through the installed rank-stamped formatter
    buf = io.StringIO()
    cap = logging.StreamHandler(buf)
    cap.setFormatter(root.handlers[0].formatter)
    root.addHandler(cap)
    try:
        lg.info("hello from the library logger")
    finally:
        root.removeHandler(cap)
    out = buf.getvalue()
    assert "hello from the library logger" in out
    assert "[0/1]" in out  # rank info stamped by RankInfoFormatter


def test_data_pipeline_smoke_script(tmp_path):
    """scripts/data_pipeline_smoke.sh end to end (the telemetry_smoke
    wiring pattern): process-pool decode + double-buffered prefetch must
    show nonzero overlap, the packed LM stream must flow through a
    DataService, and shutdown must leak no worker processes.  Subprocess
    because the process-pool spawn re-imports __main__ and the smoke
    owns its own platform pinning."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHON"] = sys.executable
    proc = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "data_pipeline_smoke.sh"),
         str(tmp_path / "work")],
        cwd=repo, env=env, capture_output=True, timeout=240)
    assert proc.returncode == 0, (
        f"data_pipeline_smoke.sh rc={proc.returncode}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-3000:]}")
    assert b"PASS" in proc.stderr


def test_serving_smoke_script():
    """scripts/serving_smoke.sh end to end (ISSUE 9): continuously-
    batched greedy decode token-identical to the per-request
    full-forward reference across staggered request churn, exactly one
    decode compile, int8 + speculative drafting with the k+1 verify at
    occupancy pressure (A2 — ISSUE 12/13), and a clean SIGTERM drain
    (in-flight delivered, queue cancelled).  Subprocess because the
    smoke sends itself a real SIGTERM and owns its own platform/mesh
    pinning."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHON"] = sys.executable
    proc = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "serving_smoke.sh")],
        cwd=repo, env=env, capture_output=True, timeout=300)
    assert proc.returncode == 0, (
        f"serving_smoke.sh rc={proc.returncode}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-3000:]}")
    assert b"PASS" in proc.stderr
    assert b"phase A OK" in proc.stderr and b"phase B OK" in proc.stderr
    assert b"phase A2 OK" in proc.stderr


def test_fleet_smoke_script():
    """scripts/fleet_smoke.sh end to end (ISSUE 11): the 3-replica
    fault matrix with real processes and real signals — SIGKILL one
    replica mid-decode and the replayed streams stay bitwise identical
    to the uninterrupted greedy reference; overload sheds with typed
    REJECTED + serving/requests_rejected; a staggered SIGTERM-drain
    weight rollout under load restores the newest VERIFIED checkpoint
    (corrupt newest falls back), finishes every request, and keeps p99
    TPOT bounded; /healthz answers on live replicas and refuses on the
    killed one.  Phase D (ISSUE 14): the same fleet contract over
    framed loopback TCP — replica_serve daemons behind ChaosProxy, one
    wire partitioned and one host SIGKILLed mid-decode, every stream
    token-identical.  Subprocess because the smoke spawns replica
    processes and owns its own platform pinning (the serving-smoke
    pattern).

    Fast tier runs phases A-C only (FLEET_SMOKE_PHASES=ABC): phase D
    stands up a second 3-daemon socket fleet and the whole script was
    the single heaviest fast-tier item (550s of the aux tier's 783s) —
    the slow-tier twin below runs all phases (ISSUE 18 tier budget
    satellite, the trace-smoke precedent).  The fast tier still asserts
    the demoted phase's artifact: the script must *say* it skipped D
    (so a silently-dropped phase can never pass as a skip)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHON"] = sys.executable
    env["FLEET_SMOKE_PHASES"] = "ABC"
    proc = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "fleet_smoke.sh")],
        cwd=repo, env=env, capture_output=True, timeout=700)
    assert proc.returncode == 0, (
        f"fleet_smoke.sh rc={proc.returncode}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-3000:]}")
    assert b"PASS" in proc.stderr
    for phase in (b"phase A OK", b"phase B OK", b"phase C OK"):
        assert phase in proc.stderr
    assert b"phase D skipped" in proc.stderr


@pytest.mark.slow
def test_fleet_smoke_script_socket_chaos():
    """The full fleet smoke including phase D (the second socket-daemon
    fleet behind ChaosProxy wires: a partition + a SIGKILL mid-decode
    over framed TCP) — slow tier: it spawns three more engine hosts on
    top of the phase A-C fleet (ISSUE 18 tier budget satellite)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHON"] = sys.executable
    env["FLEET_SMOKE_PHASES"] = "ABCD"
    proc = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "fleet_smoke.sh")],
        cwd=repo, env=env, capture_output=True, timeout=900)
    assert proc.returncode == 0, (
        f"fleet_smoke.sh rc={proc.returncode}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-3000:]}")
    assert b"PASS" in proc.stderr
    for phase in (b"phase A OK", b"phase B OK", b"phase C OK",
                  b"phase D OK"):
        assert phase in proc.stderr


def test_trace_smoke_script():
    """scripts/trace_smoke.sh end to end (ISSUE 15 CI satellite): a
    3-replica loopback socket fleet with tracing armed in every
    process — one replica SIGKILLed mid-decode yields ONE merged trace
    spanning both replicas with failover_replay attributed and the
    per-request hop books exactly closed (overcommit 0, unattributed
    0); every request's hop sum matches the router-side stopwatch
    within 2%; /fleet/statusz serves the per-tenant SLO plane; and
    scripts/trace_report.py parses the spill dir strictly.  Subprocess
    because the smoke spawns replica daemons and owns its platform
    pinning (the fleet-smoke pattern).

    Fast tier runs phases A-C only (TRACE_SMOKE_PHASES=ABC): phase D
    stands up a second 4-daemon fleet and was the slowest fast-tier
    phase — the slow-tier twin below runs all phases (ISSUE 17 tier
    budget satellite)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHON"] = sys.executable
    env["TRACE_SMOKE_PHASES"] = "ABC"
    proc = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "trace_smoke.sh")],
        cwd=repo, env=env, capture_output=True, timeout=600)
    assert proc.returncode == 0, (
        f"trace_smoke.sh rc={proc.returncode}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-3000:]}")
    assert b"PASS" in proc.stderr
    for phase in (b"phase A OK", b"phase B OK", b"phase C OK"):
        assert phase in proc.stderr


@pytest.mark.slow
def test_trace_smoke_script_disagg():
    """The full trace smoke including phase D (the disaggregated
    2-prefill/2-decode fleet with kv_migrate hops on real daemons) —
    slow tier: it stands up a second fleet of four daemons on top of
    the phase A-C fleet."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHON"] = sys.executable
    env["TRACE_SMOKE_PHASES"] = "ABCD"
    proc = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "trace_smoke.sh")],
        cwd=repo, env=env, capture_output=True, timeout=900)
    assert proc.returncode == 0, (
        f"trace_smoke.sh rc={proc.returncode}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-3000:]}")
    assert b"PASS" in proc.stderr
    for phase in (b"phase A OK", b"phase B OK", b"phase C OK",
                  b"phase D OK"):
        assert phase in proc.stderr


def test_obs_smoke_script(tmp_path):
    """scripts/obs_smoke.sh end to end (ISSUE 10 CI satellite): the
    driver dryrun with the FLIGHT RECORDER armed — the spilled timeline
    parses under strict torn-tail semantics, the goodput buckets close
    the books against an independent stopwatch (exhaustive + disjoint),
    online accounting matches the offline recompute, and the debug
    server's /metrics + /statusz scrape.  2-device mesh to keep the XLA
    compile in the fast tier (the telemetry_smoke wiring pattern)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the script's dryrun pins its own
    proc = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "obs_smoke.sh"),
         "2", str(tmp_path / "out")],
        cwd=repo, env=env, capture_output=True, timeout=560)
    assert proc.returncode == 0, (
        f"obs_smoke.sh rc={proc.returncode}\n"
        f"stdout: {proc.stdout.decode(errors='replace')[-2000:]}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-2000:]}")
    assert b"obs_smoke OK" in proc.stdout


def test_stability_lint_decode_fast():
    """APX305 over the flagship program (ISSUE 19 tier gate, fast
    tier): the no-LoRA decode step traced at 3 distinct churn configs
    — the all-zeros entry shape plus two randomized live mixes — must
    hash to one jaxpr structure.  One engine build, trace-only (no XLA
    compile), so this rides the fast tier; the slow twin below sweeps
    every registered program at 4 configs."""
    from apex_tpu.analysis.stability import run_stability

    report, n = run_stability(programs=["decode"], n_configs=3)
    assert n == 1
    assert report.ok and not report.findings, report.format()


@pytest.mark.slow
def test_stability_lint_full_sweep_slow():
    """APX305 full sweep (ISSUE 19 acceptance): every registered
    serving program — decode, prefill, speculative, LoRA — at 4 churn
    configs each, identical structure hash across all of them."""
    from apex_tpu.analysis.stability import run_stability

    report, n = run_stability(n_configs=4)
    assert n == 4
    assert report.ok and not report.findings, report.format()
