"""The APX3xx control-plane analyzer tier (ISSUE 19).

Red-fixture coverage: every rule gets a deliberately-broken injected
source (an orphan wire command, a transport arity drift, an unconsumed
event kind, a stale allowlist entry, an undocumented counter, a stale
catalog row, an unlocked cross-thread write, a shape-varying churn
knob) that must trip *exactly* its rule — and a clean twin that stays
silent.  The rules are total, so a :class:`ControlCtx` carrying only
the files one rule reads exercises that rule in isolation.

The live-tree gates (the control tier green over HEAD, the stability
sweep green over the registered serving programs) live in
``tests/test_aux_subsystems.py`` next to the other subsystem smokes.
"""

import json
import textwrap

import numpy as np

from apex_tpu.analysis.control_plane import ControlCtx, run_control_plane
from apex_tpu.analysis.stability import (
    check_hashes,
    structure_hash,
    trace_hash,
)


def _only_rule(report, rule_id):
    assert report.findings, f"expected {rule_id} findings, got none"
    rules = {f.rule for f in report.findings}
    assert rules == {rule_id}, (
        f"expected only {rule_id}, got {rules}:\n{report.format()}")
    return report.findings


def _ctx(sources=None, docs=None):
    return ControlCtx(sources=dict(sources or {}), docs=dict(docs or {}))


def _run(sources=None, docs=None):
    report, _ = run_control_plane(_ctx(sources, docs))
    return report


# ---------------------------------------------------------------------------
# APX301 — wire-protocol completeness
# ---------------------------------------------------------------------------

_SOCK = textwrap.dedent("""\
    class SocketTransport:
        def submit(self, frid, prompt):
            self._send_cmd(("submit", frid, prompt, 0))

        def drain(self):
            self._send_cmd(("drain",))

        def close(self):
            self._stage(encode_frame(
                ("cmd", self._cmd_seq + 1, ("stop",))))
    """)

_REPL = textwrap.dedent("""\
    class ReplicaProcess:
        def submit(self, frid, prompt):
            self._cmd.put(("submit", frid, prompt, 0))

        def drain(self):
            self._cmd.put(("drain",))

        def stop(self):
            self._cmd.put_nowait(("stop",))


    def _replica_worker(cmd_q):
        while True:
            cmd = cmd_q.get()
            if cmd[0] == "submit":
                pass
            elif cmd[0] == "drain":
                pass
            elif cmd[0] == "stop":
                return
    """)

_WIRE_KEYS = ("serving/transport.py", "serving/replica.py")


def test_apx301_clean_protocol_is_silent():
    report = _run(dict(zip(_WIRE_KEYS, (_SOCK, _REPL))))
    assert report.ok and not report.findings, report.format()


def test_apx301_orphan_command_fires():
    """A command both clients send but no worker arm handles."""
    sock = _SOCK + "\n    def frob(self):\n" \
                   "        self._send_cmd((\"frob\", 1))\n"
    repl = _REPL.replace(
        "    def stop(self):",
        "    def frob(self):\n"
        "        self._cmd.put((\"frob\", 1))\n\n"
        "    def stop(self):")
    findings = _only_rule(
        _run(dict(zip(_WIRE_KEYS, (sock, repl)))), "APX301")
    assert any("'frob'" in f.message and "no _replica_worker handler"
               in f.message for f in findings)


def test_apx301_arity_drift_fires():
    """The PR 15 class: one transport's submit tuple grew an element."""
    sock = _SOCK.replace('("submit", frid, prompt, 0)',
                         '("submit", frid, prompt, 0, "grew")')
    findings = _only_rule(
        _run(dict(zip(_WIRE_KEYS, (sock, _REPL)))), "APX301")
    assert any("'submit'" in f.message and "arity drift" in f.message
               for f in findings)


def test_apx301_one_sided_command_fires():
    sock = _SOCK + "\n    def frob(self):\n" \
                   "        self._send_cmd((\"frob\", 1))\n"
    findings = _only_rule(
        _run(dict(zip(_WIRE_KEYS, (sock, _REPL)))), "APX301")
    msgs = "\n".join(f.message for f in findings)
    assert "socket transport only" in msgs      # set drift
    assert "no _replica_worker handler" in msgs  # and unhandled


def test_apx301_dead_handler_fires():
    repl = _REPL.replace(
        '        elif cmd[0] == "stop":',
        '        elif cmd[0] == "ghost":\n'
        '            pass\n'
        '        elif cmd[0] == "stop":')
    findings = _only_rule(
        _run(dict(zip(_WIRE_KEYS, (_SOCK, repl)))), "APX301")
    assert any("'ghost'" in f.message and "dead" in f.message
               for f in findings)


# ---------------------------------------------------------------------------
# APX302 — event-schema closure
# ---------------------------------------------------------------------------

_EMITTER = textwrap.dedent("""\
    def submit(req):
        timeline.emit("request_submit", rid=req.rid)
    """)

_CONSUMER = textwrap.dedent("""\
    _KIND_RANK = {"request_submit": 0}

    TRACE_UNATTRIBUTED_KINDS = {}
    """)


def test_apx302_clean_schema_is_silent():
    report = _run({"serving/engine.py": _EMITTER,
                   "observability/trace.py": _CONSUMER})
    assert report.ok and not report.findings, report.format()


def test_apx302_unconsumed_kind_fires():
    emitter = _EMITTER + "    timeline.emit(\"mystery_evt\", x=1)\n"
    findings = _only_rule(
        _run({"serving/engine.py": emitter,
              "observability/trace.py": _CONSUMER}), "APX302")
    assert any("'mystery_evt'" in f.message for f in findings)


def test_apx302_allowlisted_kind_is_silent():
    emitter = _EMITTER + "    timeline.emit(\"mystery_evt\", x=1)\n"
    consumer = _CONSUMER.replace(
        "TRACE_UNATTRIBUTED_KINDS = {}",
        'TRACE_UNATTRIBUTED_KINDS = {"mystery_evt": "a marker"}')
    report = _run({"serving/engine.py": emitter,
                   "observability/trace.py": consumer})
    assert report.ok and not report.findings, report.format()


def test_apx302_stale_allowlist_fires():
    consumer = _CONSUMER.replace(
        "TRACE_UNATTRIBUTED_KINDS = {}",
        'TRACE_UNATTRIBUTED_KINDS = {"ghost_kind": "gone"}')
    findings = _only_rule(
        _run({"serving/engine.py": _EMITTER,
              "observability/trace.py": consumer}), "APX302")
    assert any("'ghost_kind'" in f.message and "stale" in f.message
               for f in findings)


_AUTOPILOT_OK = textwrap.dedent("""\
    class Autopilot:
        def _emit(self, kind, decision_id, **fields):
            timeline.emit(kind, decision_id=decision_id, **fields)

        def decide(self, did):
            self._emit("autopilot_observe", did)
            self._emit("autopilot_decide", did)
            self._emit("autopilot_act", did)
            self._emit("autopilot_verdict", did)
    """)

_AP_CONSUMER = _CONSUMER + textwrap.dedent("""\


    def classify(kind):
        return kind.startswith("autopilot_")
    """)


def test_apx302_decision_schema_closure():
    report = _run({"serving/autopilot.py": _AUTOPILOT_OK,
                   "observability/trace.py": _AP_CONSUMER})
    assert report.ok, report.format()

    broken = _AUTOPILOT_OK.replace(
        '        self._emit("autopilot_verdict", did)\n', "")
    findings = _only_rule(
        _run({"serving/autopilot.py": broken,
              "observability/trace.py": _AP_CONSUMER}), "APX302")
    assert any("autopilot_verdict" in f.message for f in findings)

    no_did = _AUTOPILOT_OK.replace(
        "def _emit(self, kind, decision_id, **fields):",
        "def _emit(self, kind, **fields):").replace(
        "timeline.emit(kind, decision_id=decision_id, **fields)",
        "timeline.emit(kind, **fields)")
    findings = _only_rule(
        _run({"serving/autopilot.py": no_did,
              "observability/trace.py": _AP_CONSUMER}), "APX302")
    assert any("decision_id" in f.message for f in findings)


# ---------------------------------------------------------------------------
# APX303 — metric-catalog drift
# ---------------------------------------------------------------------------

_METRIC_SRC = textwrap.dedent("""\
    class Engine:
        def tick(self):
            self.registry.counter("serving/good_counter").inc()
            self.registry.histogram(
                f"fleet/tenant/{self.tenant}/ttft_ms").observe(1.0)
    """)

_CATALOG = textwrap.dedent("""\
    | metric | type | meaning |
    |---|---|---|
    | `serving/good_counter` | counter | a documented counter |
    | `fleet/tenant/<t>/ttft_ms` | histogram | per-tenant TTFT |
    """)


def test_apx303_clean_catalog_is_silent():
    report = _run({"serving/engine.py": _METRIC_SRC},
                  {"docs/serving.md": _CATALOG})
    assert report.ok and not report.findings, report.format()


def test_apx303_undocumented_counter_fires():
    src = _METRIC_SRC.replace(
        '"serving/good_counter"',
        '"serving/good_counter").inc()\n'
        '        self.registry.counter("serving/ghost_counter"')
    findings = _only_rule(
        _run({"serving/engine.py": src},
             {"docs/serving.md": _CATALOG}), "APX303")
    assert any("'serving/ghost_counter'" in f.message
               and "no row" in f.message for f in findings)


def test_apx303_stale_doc_row_fires():
    docs = _CATALOG + \
        "| `serving/stale_row` | gauge | nothing emits this |\n"
    findings = _only_rule(
        _run({"serving/engine.py": _METRIC_SRC},
             {"docs/serving.md": docs}), "APX303")
    assert any("'serving/stale_row'" in f.message
               and "nothing" in f.message for f in findings)


def test_apx303_wrapper_resolution():
    """A ``_count``-style wrapper (name templated around a parameter)
    resolves to concrete names, so an undocumented wrapped counter is
    still caught."""
    src = textwrap.dedent("""\
        class Pilot:
            def _count(self, name):
                self.registry.counter(f"fleet/autopilot/{name}").inc()

            def act(self):
                self._count("decisions")
                self._count("mystery_knob")
        """)
    docs = _CATALOG + \
        "| `fleet/autopilot/decisions` | counter | decisions taken |\n"
    findings = _only_rule(
        _run({"serving/engine.py": _METRIC_SRC,
              "serving/autopilot.py": src},
             {"docs/serving.md": docs}), "APX303")
    msgs = "\n".join(f.message for f in findings)
    assert "fleet/autopilot/mystery_knob" in msgs
    assert "fleet/autopilot/decisions" not in msgs


# ---------------------------------------------------------------------------
# APX304 — lock/teardown discipline
# ---------------------------------------------------------------------------

_LOCKED = textwrap.dedent("""\
    import threading


    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._thread = threading.Thread(target=self._run)

        def _run(self):
            with self._lock:
                self._count += 1

        def poke(self):
            with self._lock:
                self._count += 1
    """)


def test_apx304_locked_writes_are_silent():
    report = _run({"data/_producer.py": _LOCKED})
    assert report.ok and not report.findings, report.format()


def test_apx304_unlocked_cross_thread_write_fires():
    """The PR 18 class: a field both the producer thread and the main
    thread mutate, with the main-thread write outside the lock."""
    src = _LOCKED.replace(
        "    def poke(self):\n"
        "        with self._lock:\n"
        "            self._count += 1",
        "    def poke(self):\n"
        "        self._count += 1")
    findings = _only_rule(_run({"data/_producer.py": src}), "APX304")
    assert any("self._count" in f.message and "poke" in f.location
               for f in findings)


def test_apx304_single_assignment_is_exempt():
    """One write site total (post-init) is publication, not a race."""
    src = _LOCKED + "\n    def finish(self):\n        self._done = True\n"
    report = _run({"data/_producer.py": src})
    assert report.ok and not report.findings, report.format()


def test_apx304_thread_reached_helper_counts_as_thread_domain():
    """A write inside a helper only the thread target calls is in the
    thread domain; an unlocked main-thread write to the same field
    fires even though neither write is in ``_run`` itself."""
    src = textwrap.dedent("""\
        import threading


        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = 0
                self._thread = threading.Thread(target=self._run)

            def _run(self):
                self._step()

            def _step(self):
                self._state = 1

            def reset(self):
                self._state = 0
        """)
    findings = _only_rule(_run({"data/_producer.py": src}), "APX304")
    assert any("self._state" in f.message for f in findings)


# ---------------------------------------------------------------------------
# APX305 — jit-stability (shape-varying churn knob fixture)
# ---------------------------------------------------------------------------

def _slicer(k):
    def fn(x):
        return x[:k] * 2.0
    return fn


def test_apx305_stable_program_is_silent():
    x = np.ones((8,), np.float32)
    hashes = [(f"churn{i}", trace_hash(_slicer(4), (x,)))
              for i in range(3)]
    report = check_hashes("toy", hashes)
    assert report.ok and not report.findings, report.format()


def test_apx305_shape_varying_knob_fires():
    """A churn knob consumed as a python int changes the sliced shape —
    the traced structure differs between configs."""
    x = np.ones((8,), np.float32)
    hashes = [("k=4", trace_hash(_slicer(4), (x,))),
              ("k=6", trace_hash(_slicer(6), (x,)))]
    findings = _only_rule(check_hashes("toy", hashes), "APX305")
    assert "toy" in findings[0].location
    assert "k=4" in findings[0].message and "k=6" in findings[0].message


def test_apx305_baked_literal_fires_at_fixed_shape():
    """Same avals, different baked constant: a scalar knob folded into
    the trace as a literal still changes the structure hash."""
    x = np.ones((8,), np.float32)
    hashes = [("t=0.5", trace_hash(lambda v: v * 0.5, (x,))),
              ("t=0.9", trace_hash(lambda v: v * 0.9, (x,)))]
    _only_rule(check_hashes("toy", hashes), "APX305")


def test_structure_hash_ignores_values_at_fixed_structure():
    import jax

    a = structure_hash(jax.make_jaxpr(lambda v: v + 1.0)(
        np.zeros((4,), np.float32)))
    b = structure_hash(jax.make_jaxpr(lambda v: v + 1.0)(
        np.ones((4,), np.float32) * 7))
    assert a == b


# ---------------------------------------------------------------------------
# CLI wiring: pseudo-entries + structured --json
# ---------------------------------------------------------------------------

def test_cli_lists_pseudo_entries(capsys):
    from apex_tpu.analysis import cli

    assert cli.main(["--list-entries"]) == 0
    out = capsys.readouterr().out.split()
    assert "control_plane" in out and "stability" in out
    assert "serving_decode" in out


def test_cli_json_is_structured(capsys):
    """--json emits one machine-readable object (satellite: CI consumes
    verdicts without parsing human text) — stdout is pure JSON, the
    human verdict line goes to stderr."""
    from apex_tpu.analysis import cli

    rc = cli.main(["--entries", "control_plane", "--json"])
    captured = capsys.readouterr()
    assert rc == 0
    doc = json.loads(captured.out)
    assert doc["verdict"] == "PASS"
    assert doc["counts"]["errors"] == 0
    assert doc["entries"][0]["name"] == "control_plane"
    assert isinstance(doc["findings"], list)
    assert "APX305" in captured.err or "apex_tpu.analysis" in captured.err


def test_control_rules_registered():
    from apex_tpu.analysis.registry import RULEBOOK, rules_for

    assert {"APX301", "APX302", "APX303", "APX304"} <= set(RULEBOOK)
    assert {r.id for r in rules_for("stability")} == {"APX305"}
    for rid in ("APX301", "APX302", "APX303", "APX304", "APX305"):
        rule = RULEBOOK[rid]
        assert rule.catches and rule.motivation and rule.title
