"""apex_tpu.observability.trace — the distributed-tracing plane
(ISSUE 15), hermetically.

Three layers, no process spawns:

- the **clock algebra**: injected-clock units for the offset estimator
  (skewed and NTP-stepped replica clocks map back onto the router clock
  within the RTT bound — the hard error bound of the NTP midpoint
  construction) and the nearest-sample era selection;
- the **stitcher**: synthesized spills reproducing the kill-mid-decode
  failover shape — ONE merged trace whose hops span both replicas with
  zero unattributed and zero double-counted time (the per-request
  goodput books);
- the **live router**: a real FleetRouter over the hermetic FakeReplica
  mints trace ids only when a recorder is armed, emits the hop events,
  and serves the /fleet/statusz SLO plane through the DebugServer.

The real-process, real-SIGKILL, real-socket leg is
``scripts/trace_smoke.sh`` (wired in tests/test_aux_subsystems.py).
"""

import json
import queue
import time
import urllib.error
import urllib.request

import pytest

from apex_tpu.observability import timeline
from apex_tpu.observability.debug_server import DebugServer
from apex_tpu.observability.metrics import MetricRegistry
from apex_tpu.observability.timeline import FlightRecorder
from apex_tpu.observability.trace import (
    TRACE_HOP_BUCKETS,
    estimate_offset,
    map_time,
    merge_dir,
    read_fleet_spills,
    stitch_traces,
    summarize_traces,
)
from apex_tpu.serving.scheduler import RequestState

from test_fleet import FakeReplica, make_router, reference


# ------------------------------------------------------- clock algebra


@pytest.mark.parametrize("true_offset", [0.0, 1234.5, -9876.25])
@pytest.mark.parametrize("stamp_frac", [0.0, 0.3, 0.5, 1.0])
def test_estimate_offset_within_rtt_bound(true_offset, stamp_frac):
    """However skewed the remote clock and however asymmetric the link
    (the remote may stamp anywhere inside the round trip), the estimate
    errs by at most RTT/2 — the bound the merger's clamp accounting
    relies on."""
    t_send, rtt = 100.0, 0.008
    t_recv = t_send + rtt
    # the remote stamps its (offset-shifted) clock at stamp_frac of the
    # window: local true instant t_send + stamp_frac*rtt
    remote_mono = (t_send + stamp_frac * rtt) - true_offset
    offset, err = estimate_offset(t_send, t_recv, remote_mono)
    assert err == pytest.approx(rtt / 2)
    assert abs(offset - true_offset) <= rtt / 2 + 1e-12


def test_estimate_offset_rejects_backwards_window():
    with pytest.raises(ValueError, match="precedes"):
        estimate_offset(10.0, 9.0, 5.0)


def test_map_time_identity_without_samples():
    # same-host transports (mp queues) share CLOCK_MONOTONIC: no
    # samples means the identity map, not a crash
    assert map_time(123.456, []) == 123.456


def test_map_time_nearest_sample_selects_clock_era():
    """An NTP-stepped (or restarted) replica clock leaves offset
    samples from two eras; each event must map through the sample of
    ITS OWN era (nearest on the remote's clock), not a stale one."""
    samples = [(100.0, 50.0), (200.0, 70.0)]   # step of +20 between
    assert map_time(120.0, samples) == pytest.approx(170.0)   # era 1
    assert map_time(190.0, samples) == pytest.approx(260.0)   # era 2
    assert map_time(150.1, samples) == pytest.approx(220.1)   # nearest


# ---------------------------------------------------------- stitching


def _spill(tmp_path, name, meta, events):
    from apex_tpu.observability.writers import JsonlWriter

    w = JsonlWriter(str(tmp_path / name), fsync=False)
    head = {"t": 0.0, "kind": "run_begin", "wall_ts": 0.0}
    head.update(meta)
    w.write(head)
    for ev in events:
        w.write(ev)


def _build_failover_spills(tmp_path, *, r0_t0=1000.0, r0_off=0.0,
                           r1_t0=1000.0, r1_off=0.0):
    """Spills for the kill-at-mid-decode failover.  Router mono epoch
    is 1000.0; each replica's monotonic clock runs ``r*_off`` BEHIND
    the router's (``router = replica + off`` — a different boot epoch)
    and its recorder armed when its own clock read ``r*_t0``.  The
    ROUTER-clock story is identical whatever the skew:

      0.00 submit  0.02 dispatch#1(r0)  0.03 r0 submit  0.05 r0 admit
      0.06 r0 chunk start .. 0.10 prefilled  0.30 last decode_tick
      (kill)  0.55 fleet_replay  0.60 dispatch#2(r1)  0.62 r1 submit
      0.63 r1 admit  0.64 chunk start .. 0.70 prefilled
      0.90 r1 finish  0.92 fleet_finish
    """
    tid = "feedc0de"
    router_t0 = 1000.0

    def rel_to(replica_t0, off, t_router_rel):
        # the replica-local relative stamp of the same physical moment:
        # replica_mono = router_mono - off, minus its recorder epoch
        return (router_t0 + t_router_rel) - off - replica_t0

    off0, off1 = r0_off, r1_off
    _spill(tmp_path, "timeline.router.router.1.jsonl",
           {"role": "router", "name": "router", "pid": 1,
            "mono_t0": router_t0},
           [
               {"t": 0.005, "kind": "link_clock", "replica": "r0",
                "rtt_s": 0.002, "offset_s": off0,
                "remote_mono": router_t0 + 0.005 - off0},
               {"t": 0.005, "kind": "link_clock", "replica": "r1",
                "rtt_s": 0.002, "offset_s": off1,
                "remote_mono": router_t0 + 0.005 - off1},
               {"t": 0.00, "kind": "fleet_submit", "rid": 3,
                "trace_id": tid, "tenant": "acme", "priority": 0,
                "prompt_tokens": 4, "max_new_tokens": 8},
               {"t": 0.02, "kind": "fleet_dispatch", "rid": 3,
                "trace_id": tid, "attempt": 1, "replica": "r0",
                "prior_tokens": 0},
               {"t": 0.55, "kind": "fleet_replay", "rid": 3,
                "trace_id": tid, "replica": "r0", "reason": "down"},
               {"t": 0.60, "kind": "fleet_dispatch", "rid": 3,
                "trace_id": tid, "attempt": 2, "replica": "r1",
                "prior_tokens": 3},
               {"t": 0.92, "kind": "fleet_finish", "rid": 3,
                "trace_id": tid, "tokens": 8},
           ])
    _spill(tmp_path, "timeline.replica.r0.2.jsonl",
           {"role": "replica", "name": "r0", "pid": 2, "mono_t0": r0_t0},
           [
               {"t": rel_to(r0_t0, off0, 0.03), "kind": "request_submit",
                "rid": 0, "trace_id": tid, "attempt": 1},
               {"t": rel_to(r0_t0, off0, 0.05), "kind": "request_admit",
                "rid": 0, "trace_id": tid, "attempt": 1},
               {"t": rel_to(r0_t0, off0, 0.10), "kind": "prefill",
                "rids": [0], "tokens": 4, "dur_s": 0.04},
               {"t": rel_to(r0_t0, off0, 0.10), "kind": "request_prefilled",
                "rid": 0, "trace_id": tid, "attempt": 1},
               {"t": rel_to(r0_t0, off0, 0.30), "kind": "decode_tick",
                "rid": 0, "trace_id": tid, "tokens": 3},
               # SIGKILL here: no finish, torn-tail spill
           ])
    _spill(tmp_path, "timeline.replica.r1.3.jsonl",
           {"role": "replica", "name": "r1", "pid": 3, "mono_t0": r1_t0},
           [
               {"t": rel_to(r1_t0, off1, 0.62), "kind": "request_submit",
                "rid": 0, "trace_id": tid, "attempt": 2},
               {"t": rel_to(r1_t0, off1, 0.63), "kind": "request_admit",
                "rid": 0, "trace_id": tid, "attempt": 2},
               {"t": rel_to(r1_t0, off1, 0.70), "kind": "prefill",
                "rids": [0], "tokens": 7, "dur_s": 0.06},
               {"t": rel_to(r1_t0, off1, 0.70), "kind": "request_prefilled",
                "rid": 0, "trace_id": tid, "attempt": 2},
               {"t": rel_to(r1_t0, off1, 0.90), "kind": "request_finish",
                "rid": 0, "trace_id": tid, "tokens": 8},
           ])
    return tid


def _expected_hops():
    return {
        "router_queue": 0.02,             # 0.00 -> 0.02
        # dispatch->submit legs (0.02->0.03, 0.60->0.62) + the return
        # leg (0.90 -> 0.92)
        "wire": 0.01 + 0.02 + 0.02,
        "replica_queue": 0.02 + 0.01,     # 0.03->0.05, 0.62->0.63
        "admission_wait": 0.01 + 0.01,    # admit -> own chunk start
        "prefill": 0.04 + 0.06,           # chunk start -> prefilled
        "decode": 0.20 + 0.20,            # prefilled -> tick / finish
        "preempted": 0.0,
        # r0's last flushed event (0.30) -> re-dispatch (0.60): kill,
        # detection ladder, requeue — the failover COST
        "failover_replay": 0.30,
    }


@pytest.mark.parametrize("r0_t0,r0_off,r1_t0,r1_off", [
    (1000.0, 0.0, 1000.0, 0.0),     # aligned clocks (loopback shape)
    (5.25, 987654.0, 2e6, -777.5),  # wildly skewed boot epochs, both
    #                                 directions (the cross-host shape)
])
def test_failover_yields_one_fully_attributed_trace(tmp_path, r0_t0,
                                                    r0_off, r1_t0,
                                                    r1_off):
    """The acceptance shape: a request surviving a mid-decode SIGKILL
    failover produces ONE merged trace whose hops span both replicas,
    with every wall-clock second in exactly one bucket (overcommit 0,
    unattributed 0) — and the attribution is invariant to the replicas'
    clock epochs, because the link_clock samples map them out."""
    tid = _build_failover_spills(tmp_path, r0_t0=r0_t0, r0_off=r0_off,
                                 r1_t0=r1_t0, r1_off=r1_off)
    report = merge_dir(str(tmp_path))
    assert list(report["traces"]) == [tid]
    rec = report["traces"][tid]
    assert rec["state"] == "finished"
    assert rec["attempts"] == 2
    assert rec["replicas"] == ["r0", "r1"]
    assert rec["tenant"] == "acme" and rec["rid"] == 3
    assert rec["overcommit_s"] == 0.0
    assert rec["unattributed_s"] == 0.0
    assert rec["clock_clamped_s"] == 0.0
    assert rec["wall_s"] == pytest.approx(0.92, abs=1e-6)
    for bucket, want in _expected_hops().items():
        assert rec["hops"][bucket] == pytest.approx(want, abs=1e-6), \
            bucket
    assert sum(rec["hops"].values()) == pytest.approx(rec["wall_s"],
                                                      abs=1e-5)
    summary = report["summary"]
    assert summary["states"] == {"finished": 1}
    assert summary["overcommit_s"] == 0.0
    # the tail row names the dominant hop (decode at 0.40s here, with
    # failover_replay the visible runner-up in the hops dict)
    assert summary["tail"][0]["slowest_hop"] == "decode"
    assert summary["tail"][0]["replicas"] == ["r0", "r1"]


def test_stitch_tolerates_offset_error_by_clamping(tmp_path):
    """A slightly wrong link offset can map a replica event BEFORE the
    dispatch that caused it; the walk must clamp (and account) rather
    than reorder or go negative — hop sums still close the books."""
    tid = _build_failover_spills(tmp_path)
    # poison r0's offset by +25ms (beyond any hop gap around dispatch)
    path = tmp_path / "timeline.router.router.1.jsonl"
    lines = path.read_text().strip().splitlines()
    out = []
    for line in lines:
        ev = json.loads(line)
        if ev.get("kind") == "link_clock" and ev.get("replica") == "r0":
            ev["offset_s"] -= 0.025
        out.append(json.dumps(ev))
    path.write_text("\n".join(out) + "\n")
    rec = merge_dir(str(tmp_path))["traces"][tid]
    assert rec["clock_clamped_s"] > 0.0
    assert rec["overcommit_s"] == 0.0 and rec["unattributed_s"] == 0.0
    assert sum(rec["hops"].values()) == pytest.approx(rec["wall_s"],
                                                      abs=1e-5)


def test_read_fleet_spills_requires_router_and_splits_roles(tmp_path):
    with pytest.raises(ValueError, match="no router spill"):
        read_fleet_spills(str(tmp_path / "empty"))
    _build_failover_spills(tmp_path, r0_off=4.5, r1_off=-2.0)
    router_run, replicas = read_fleet_spills(str(tmp_path))
    assert router_run[0]["role"] == "router"
    assert sorted(replicas) == ["r0", "r1"]


# ------------------------------------------------- live router tracing


def drive(router, reps, *, max_iters=5000):
    for _ in range(max_iters):
        router.pump()
        if router.idle():
            return
        for rep in reps:
            rep.tick()
    raise AssertionError("fleet not idle")


def test_router_mints_traces_only_when_armed():
    rep = FakeReplica("a")
    router = make_router([rep])
    try:
        req_dark = router.submit([3, 5], 3)
        rec = timeline.arm(FlightRecorder(None))
        req_lit = router.submit([3, 5, 7], 3)
        drive(router, [rep])
    finally:
        timeline.disarm()
        router.close()
    assert req_dark.trace_id is None
    assert req_lit.trace_id is not None
    kinds = [(e["kind"], e.get("trace_id")) for e in rec.events()
             if e.get("trace_id") == req_lit.trace_id]
    assert [k for k, _ in kinds] == ["fleet_submit", "fleet_dispatch",
                                     "fleet_finish"]
    # the hop stamp rode the wire: the fake saw trace=None for the dark
    # request and the {trace_id, attempt} dict for the lit one
    assert req_lit.output_tokens == reference([3, 5, 7], 3)


def test_router_only_trace_stitches_and_closes_books():
    """A fleet whose replicas spill no timeline (hermetic fakes, or
    replicas simply unarmed) still yields a closed router-side trace:
    dispatch -> finish all lands in `wire` (the router cannot see
    inside), and the books still balance exactly."""
    rep = FakeReplica("a")
    router = make_router([rep])
    try:
        rec = timeline.arm(FlightRecorder(None))
        req = router.submit([9, 2], 4)
        drive(router, [rep])
    finally:
        timeline.disarm()
        router.close()
    traces = stitch_traces(rec.events(), {})
    assert list(traces) == [req.trace_id]
    t = traces[req.trace_id]
    assert t["state"] == "finished"
    assert t["overcommit_s"] == 0.0 and t["unattributed_s"] == 0.0
    assert sum(t["hops"].values()) == pytest.approx(t["wall_s"],
                                                    abs=1e-5)
    assert t["hops"]["wire"] > 0.0
    summary = summarize_traces(traces)
    assert summary["requests"] == 1
    assert set(summary["hop_totals_s"]) == set(TRACE_HOP_BUCKETS)


def test_shed_request_trace_terminates_rejected():
    rep = FakeReplica("a")
    router = make_router([rep], max_queue_depth=1)
    try:
        rec = timeline.arm(FlightRecorder(None))
        reqs = [router.submit([5], 2) for _ in range(6)]
        drive(router, [rep])
    finally:
        timeline.disarm()
        router.close()
    shed = [r for r in reqs if r.state is RequestState.REJECTED]
    assert shed
    traces = stitch_traces(rec.events(), {})
    for req in shed:
        assert traces[req.trace_id]["state"] == "rejected"


# ----------------------------------------------------- SLO plane


def test_fleet_statusz_slo_plane_and_http():
    rep = FakeReplica("a", max_batch=8)
    router = make_router([rep], replica_queue_limit=8,
                         max_queue_depth=6)
    srv = DebugServer(registry=router.registry, engine=router).start()
    try:
        reqs = [router.submit([3, 5, 7], 4, tenant="acme"),
                router.submit([2, 4], 4, tenant="beta", priority=1)]
        shed = [router.submit([8], 2, tenant="acme")
                for _ in range(8)]
        drive(router, [rep])
        status = router.fleet_statusz()
        tenants = status["slo"]["tenants"]
        assert set(tenants) >= {"acme", "beta"}
        assert tenants["acme"]["finished"] >= 1
        assert tenants["acme"]["ttft_ms"]["count"] >= 1
        assert tenants["acme"]["ttft_ms"]["p99"] is not None
        assert tenants["beta"]["tpot_ms"]["count"] >= 1
        assert tenants["acme"]["queue_wait_ms"]["count"] >= 1
        n_shed = sum(1 for r in shed
                     if r.state is RequestState.REJECTED)
        assert n_shed >= 1
        assert tenants["acme"]["rejected"] == n_shed
        prios = status["slo"]["priorities"]
        assert set(prios) >= {"0", "1"}
        assert prios["1"]["finished"] == 1
        assert status["totals"]["submitted"] == len(reqs) + len(shed)
        assert status["totals"]["rejected"] == n_shed
        # the HTTP plane: /fleet/statusz serves the same payload
        with urllib.request.urlopen(
                srv.url("/fleet/statusz"), timeout=10) as resp:
            assert resp.status == 200
            payload = json.loads(resp.read())
        assert payload["slo"]["tenants"]["acme"]["finished"] >= 1
        assert "replicas" in payload
    finally:
        srv.close()
        router.close()
    # no fleet attached -> 404, not a fake-empty answer
    srv2 = DebugServer(registry=MetricRegistry(rank=0, world=1)).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(srv2.url("/fleet/statusz"),
                                   timeout=10)
        assert exc.value.code == 404
    finally:
        srv2.close()


def test_slo_key_space_is_bounded():
    """Tenants are caller-supplied strings: past ``slo_key_cap``
    distinct keys, new arrivals account under "(other)" — a client
    stamping a unique tenant per request must not grow the registry
    (3 windowed histograms + counters per key) without bound."""
    rep = FakeReplica("a", max_batch=8)
    router = make_router([rep], replica_queue_limit=8,
                         max_queue_depth=64, slo_key_cap=3)
    try:
        for i in range(10):
            router.submit([3, 5], 2, tenant=f"t{i}")
        drive(router, [rep])
        tenants = router.fleet_statusz()["slo"]["tenants"]
        assert len(tenants) == 4                  # 3 real + overflow
        assert "(other)" in tenants
        # overflow traffic is accounted, not dropped
        assert tenants["(other)"]["finished"] == 7
        assert sum(t["finished"] for t in tenants.values()) == 10
    finally:
        router.close()


def test_introspect_has_link_rtt_percentiles():
    rep = FakeReplica("a")
    # duck-typed RTT samples: the router drains them into the windowed
    # per-replica histogram (ISSUE 15 satellite)
    samples = [(0.001, 0.0, 10.0), (0.002, 0.0, 10.5),
               (0.100, 0.0, 11.0)]
    rep.take_rtt_samples = \
        lambda: [samples.pop(0)] if samples else []
    router = make_router([rep])
    try:
        for _ in range(5):
            router.pump()
        intro = router.introspect()["replicas"]["a"]
        assert intro["link_rtt_p50_ms"] is not None
        assert intro["link_rtt_p99_ms"] >= intro["link_rtt_p50_ms"]
    finally:
        router.close()


# ------------------------------- ISSUE 16: the kv_migrate hop bucket


def _build_migration_spills(tmp_path):
    """Spills for the disaggregation handoff: prefill on r0, the KV
    run streamed to r1, decode finishing there.  Router-clock story:

      0.00 submit  0.02 dispatch#1(r0)  0.03 r0 submit  0.05 r0 admit
      0.06 r0 chunk start .. 0.10 prefilled  0.30 last decode_tick
      0.32 fleet_migrate_start  0.40 dispatch#2(r1, migrated)
      0.42 r1 submit  0.43 r1 admit  0.44 chunk start .. 0.45
      prefilled (the one-token re-prefill)  0.60 r1 finish
      0.62 fleet_finish
    """
    tid = "00c0ffee"
    router_t0 = 1000.0
    _spill(tmp_path, "timeline.router.router.1.jsonl",
           {"role": "router", "name": "router", "pid": 1,
            "mono_t0": router_t0},
           [
               {"t": 0.00, "kind": "fleet_submit", "rid": 7,
                "trace_id": tid, "tenant": "acme", "priority": 0,
                "prompt_tokens": 3, "max_new_tokens": 8},
               {"t": 0.02, "kind": "fleet_dispatch", "rid": 7,
                "trace_id": tid, "attempt": 1, "replica": "r0",
                "prior_tokens": 0},
               {"t": 0.32, "kind": "fleet_migrate_start", "rid": 7,
                "trace_id": tid, "attempt": 1, "src": "r0",
                "dst": "r1", "prior_tokens": 3},
               {"t": 0.40, "kind": "fleet_dispatch", "rid": 7,
                "trace_id": tid, "attempt": 2, "replica": "r1",
                "migrated": True, "prior_tokens": 3},
               {"t": 0.62, "kind": "fleet_finish", "rid": 7,
                "trace_id": tid, "tokens": 8},
           ])
    _spill(tmp_path, "timeline.replica.r0.2.jsonl",
           {"role": "replica", "name": "r0", "pid": 2,
            "mono_t0": router_t0},
           [
               {"t": 0.03, "kind": "request_submit", "rid": 0,
                "trace_id": tid, "attempt": 1},
               {"t": 0.05, "kind": "request_admit", "rid": 0,
                "trace_id": tid, "attempt": 1},
               {"t": 0.10, "kind": "prefill", "rids": [0],
                "tokens": 3, "dur_s": 0.04},
               {"t": 0.10, "kind": "request_prefilled", "rid": 0,
                "trace_id": tid, "attempt": 1},
               {"t": 0.30, "kind": "decode_tick", "rid": 0,
                "trace_id": tid, "tokens": 3},
               # the export itself is replica bookkeeping, not a walk
               # milestone — it must not disturb the hop books
               {"t": 0.33, "kind": "request_export", "rid": 0,
                "trace_id": tid, "blocks": 1},
           ])
    _spill(tmp_path, "timeline.replica.r1.3.jsonl",
           {"role": "replica", "name": "r1", "pid": 3,
            "mono_t0": router_t0},
           [
               {"t": 0.42, "kind": "request_submit", "rid": 0,
                "trace_id": tid, "attempt": 2},
               {"t": 0.43, "kind": "request_admit", "rid": 0,
                "trace_id": tid, "attempt": 2},
               {"t": 0.45, "kind": "prefill", "rids": [0],
                "tokens": 1, "dur_s": 0.01},
               {"t": 0.45, "kind": "request_prefilled", "rid": 0,
                "trace_id": tid, "attempt": 2},
               {"t": 0.60, "kind": "request_finish", "rid": 0,
                "trace_id": tid, "tokens": 8},
           ])
    return tid


def test_migration_trace_attributes_kv_migrate_hop(tmp_path):
    """The disaggregation handoff yields ONE merged trace spanning both
    roles: migrate-start → dispatch-onto-decode lands in the
    ``kv_migrate`` bucket, decode time on BOTH sides stays decode, and
    the books still close exactly (every second in exactly one
    bucket)."""
    tid = _build_migration_spills(tmp_path)
    report = merge_dir(str(tmp_path))
    rec = report["traces"][tid]
    assert rec["state"] == "finished"
    assert rec["attempts"] == 2
    assert rec["replicas"] == ["r0", "r1"]
    assert rec["overcommit_s"] == 0.0
    assert rec["unattributed_s"] == 0.0
    assert rec["wall_s"] == pytest.approx(0.62, abs=1e-6)
    want = {
        "router_queue": 0.02,          # 0.00 -> 0.02
        # dispatch->submit legs (0.02->0.03, 0.40->0.42) + the return
        # leg (0.60 -> 0.62)
        "wire": 0.01 + 0.02 + 0.02,
        "replica_queue": 0.02 + 0.01,  # 0.03->0.05, 0.42->0.43
        "admission_wait": 0.01 + 0.01,
        "prefill": 0.04 + 0.01,        # full prefill + 1-token re-do
        "decode": 0.22 + 0.15,         # prefilled -> migrate_start,
        #                                prefilled -> finish
        "preempted": 0.0,
        "failover_replay": 0.0,        # a handoff is not a failure
        "kv_migrate": 0.08,            # migrate_start -> dispatch#2
    }
    for bucket, val in want.items():
        assert rec["hops"][bucket] == pytest.approx(val, abs=1e-6), \
            bucket
    assert sum(rec["hops"].values()) == pytest.approx(rec["wall_s"],
                                                      abs=1e-5)
    summary = report["summary"]
    assert summary["states"] == {"finished": 1}
    assert "kv_migrate" in summary["hop_totals_s"]


def test_live_disagg_router_emits_and_closes_kv_migrate():
    """A live disaggregated fleet (prefill + decode FakeReplicas) emits
    the migrate-start hop event between the two dispatches, and the
    router-only stitch closes the books with kv_migrate > 0."""
    p = FakeReplica("p", meta={"role": "prefill"})
    d = FakeReplica("d", meta={"role": "decode"})
    router = make_router([p, d])
    try:
        router.pump()                  # roles known before arming
        rec = timeline.arm(FlightRecorder(None))
        req = router.submit([9, 1, 4], 8)
        drive(router, [p, d])
    finally:
        timeline.disarm()
        router.close()
    assert req.state is RequestState.FINISHED
    assert req.replica == "d"
    assert req.output_tokens == reference([9, 1, 4], 8)
    evs = [e for e in rec.events()
           if e.get("trace_id") == req.trace_id]
    assert [e["kind"] for e in evs] == [
        "fleet_submit", "fleet_dispatch", "fleet_migrate_start",
        "fleet_dispatch", "fleet_finish"]
    mig = evs[2]
    assert mig["src"] == "p" and mig["dst"] == "d"
    assert evs[3]["migrated"] is True
    assert evs[3]["replica"] == "d"
    assert evs[3]["attempt"] == 2
    traces = stitch_traces(rec.events(), {})
    t = traces[req.trace_id]
    assert t["state"] == "finished"
    assert t["hops"]["kv_migrate"] > 0.0
    assert t["overcommit_s"] == 0.0 and t["unattributed_s"] == 0.0
    assert sum(t["hops"].values()) == pytest.approx(t["wall_s"],
                                                    abs=1e-5)
    assert t["replicas"] == ["p", "d"]


# ------------------------------------------------- batched event relay


def test_transport_server_unpacks_batched_relay():
    """The worker's one-put-per-turn ("batch", [...]) payload: each
    sub-event gets its OWN wire sequence number — the client never sees
    the wrapper."""
    from apex_tpu.serving.transport import TransportServer

    cmd_q, evt_q = queue.Queue(), queue.Queue()
    server = TransportServer(cmd_q, evt_q)
    try:
        evt_q.put(("batch", [("token", 1, 5), ("token", 1, 6),
                             ("finished", 1)]))
        evt_q.put(("state", {"queue_depth": 0}))
        deadline = time.monotonic() + 10
        while len(server._ring) < 4 and time.monotonic() < deadline:
            time.sleep(0.005)
        ring = list(server._ring)
        assert [seq for seq, _ in ring] == [1, 2, 3, 4]
        assert [ev[0] for _, ev in ring] == ["token", "token",
                                             "finished", "state"]
    finally:
        server.close(bye=False)


def test_replica_process_poll_unpacks_batches():
    """ReplicaProcess.poll flattens ("batch", ...) payloads in order
    and keeps the relay counters the router mirrors into
    fleet/relay_batch*."""
    from apex_tpu.serving.replica import ReplicaProcess

    rp = ReplicaProcess.__new__(ReplicaProcess)   # no child spawn
    rp.relay_batches = 0
    rp.relay_batched_events = 0
    rp._evt = queue.Queue()
    rp._evt.put(("ready", {"pid": 1}))
    rp._evt.put(("batch", [("token", 0, 1), ("token", 0, 2)]))
    rp._evt.put(("batch", [("finished", 0)]))
    events = rp.poll()
    assert events == [("ready", {"pid": 1}), ("token", 0, 1),
                      ("token", 0, 2), ("finished", 0)]
    assert rp.relay_batches == 2
    assert rp.relay_batched_events == 3


def test_trace_report_check_gate(tmp_path, capsys):
    """scripts/trace_report.py --check (ISSUE 19 satellite): the trace
    plane's invariants as an exit code.  The synthetic failover spill
    closes its books exactly (0 overcommit, 0 unattributed), so the
    default budget passes; forcing the unattributed budget below zero
    proves the gate actually fires instead of always printing ok."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(repo, "scripts", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    _build_failover_spills(tmp_path)
    assert mod.main([str(tmp_path), "--check"]) == 0
    assert "check ok" in capsys.readouterr().err
    assert mod.main(
        [str(tmp_path), "--check", "--max-unattributed-pct=-1"]) == 1
    assert "UNATTRIBUTED" in capsys.readouterr().err
