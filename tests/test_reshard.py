"""Restore-anywhere (ISSUE 6): logical sharding specs, resharded
restores, manifest back-compat, and the retention pin.

The kill-at-N/resume-at-M proof lives in
``scripts/elastic_resume_smoke.sh`` (driven fast-tier by
``tests/test_elastic_resume.py``); these tests pin the pieces in
isolation with the fault-injection harness:

- :func:`apex_tpu.resilience.reshard.build_spec` / ``ShardingSpec``
  JSON round trip, and spec validation errors that NAME the
  missing/invalid field (corruption-class, so ``restore_latest`` can
  fall back past a bad spec);
- ZeRO flat-bucket state saved at one dp world restores bit-exactly
  onto another (buffers unflattened to logical leaves, re-chunked),
  proven by comparing mesh-independent ``load_logical`` digests;
- folded layer stacks (``[vpp, pp, ...]``) re-factor across pipeline
  depth changes by pure reshape;
- manifest back-compat: a pre-PR-6 (version-1, spec-less) manifest
  still restores onto the same mesh shape, a NEWER manifest version is
  corruption-class, and a shape-mismatched spec-less checkpoint fails
  with an error naming the missing ``sharding_spec``;
- retention (the ISSUE 6 bugfix): keep-last-k counts and deletes only
  COMMITTED checkpoints, so crash artifacts or an in-flight async save
  (parked provably mid-write with ``faults.hung_writes``) can neither
  displace the last durable checkpoint out of the keep window nor be
  deleted under the writer.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import checkpoint as ckpt
from apex_tpu import parallel
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.parallel.distributed import replicate, zero_init
from apex_tpu.resilience import CheckpointManager, reshard
from apex_tpu.testing import faults


def _zero_pack(mesh, opt, seed=0):
    """A small flat-bucket ZeRO train state committed to ``mesh`` —
    params replicated, optimizer buffers dp-chunked (mesh-shape-
    dependent) — plus its logical spec."""
    params = {
        "w": jax.random.normal(jax.random.PRNGKey(seed), (13, 7)),
        "b": jnp.arange(7.0) * 0.25,
    }
    p = replicate(params, mesh)
    pack = {"params": p, "opt": zero_init(opt, p, mesh)}
    spec = reshard.build_spec(pack, mesh=mesh,
                              zero_states=[("opt", opt, p)])
    return pack, spec


# ---------------------------------------------------------------------------
# ShardingSpec: build / serialize / validate
# ---------------------------------------------------------------------------


def test_spec_json_roundtrip(devices8):
    mesh = parallel.initialize_model_parallel(devices=devices8[:4])
    opt = DistributedFusedAdam(lr=1e-2, flat_bucket=True, n_buckets=2)
    _, spec = _zero_pack(mesh, opt)
    doc = json.loads(json.dumps(spec.to_json()))  # through real JSON
    back = reshard.ShardingSpec.from_json(doc)
    assert back.to_json() == spec.to_json()
    assert spec.mesh["dp"] == 4
    # every bucket leaf is annotated with its group membership
    grouped = [p for p, rec in spec.leaves.items() if "group" in rec]
    assert grouped and all(p.startswith("opt/.") for p in grouped)


@pytest.mark.parametrize("doc, names", [
    ("not-a-dict", ["not an object"]),
    ({"version": 99, "leaves": {}, "groups": {}}, ["version", "99"]),
    ({"version": 1, "groups": {}}, ["leaves", "missing"]),
    ({"version": 1, "leaves": {}}, ["groups", "missing"]),
])
def test_spec_validation_names_the_field(doc, names):
    """A missing/invalid spec field is corruption-class and the message
    names it — the fallback log must say WHAT was wrong, not just that
    a restore failed."""
    with pytest.raises(ckpt.CheckpointCorruptError) as e:
        reshard.ShardingSpec.from_json(doc)
    for frag in names:
        assert frag in str(e.value)


def test_group_spec_validation_names_the_field(tmp_path, devices8):
    """An embedded spec whose flat-bucket group record lost a required
    field fails the resharded restore with the field named (and is
    therefore fallback-eligible in ``restore_latest``)."""
    mesh = parallel.initialize_model_parallel(devices=devices8[:4])
    opt = DistributedFusedAdam(lr=1e-2, flat_bucket=True, n_buckets=2)
    pack, spec = _zero_pack(mesh, opt)
    path = str(tmp_path / "c.npz")
    ckpt.save_checkpoint(path, pack, step=0, spec=spec)

    with np.load(path, allow_pickle=False) as data:
        manifest = json.loads(str(data["__manifest__"]))
        arrays = {k: data[k] for k in data.files if k != "__manifest__"}
    key = next(iter(manifest["sharding_spec"]["groups"]))
    del manifest["sharding_spec"]["groups"][key]["chunk"]
    with open(path, "wb") as f:
        np.savez(f, __manifest__=json.dumps(manifest), **arrays)

    parallel.destroy_model_parallel()
    mesh = parallel.initialize_model_parallel(devices=devices8[:2])
    like, spec2 = _zero_pack(mesh, opt)
    with pytest.raises(ckpt.CheckpointCorruptError, match="chunk"):
        reshard.restore_resharded(path, like, spec2)


def test_manager_embeds_spec_in_manifest(tmp_path, devices8):
    mesh = parallel.initialize_model_parallel(devices=devices8[:4])
    opt = DistributedFusedAdam(lr=1e-2, flat_bucket=True, n_buckets=2)
    pack, spec = _zero_pack(mesh, opt)
    mgr = CheckpointManager(str(tmp_path / "m"), sharded=True, spec=spec)
    mgr.save(pack, 0)
    manifest = mgr.verify(0)
    assert manifest["version"] == ckpt.MANIFEST_VERSION
    assert manifest["sharding_spec"]["version"] == reshard.SPEC_VERSION
    assert manifest["sharding_spec"]["mesh"]["dp"] == 4


# ---------------------------------------------------------------------------
# Resharded restores: ZeRO flat buckets + folded layer stacks
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("src_n, dst_n", [(4, 2), (2, 4)])
def test_zero_flat_bucket_reshard_bit_exact(tmp_path, devices8,
                                            src_n, dst_n):
    """The hard case of restore-anywhere: flat-bucket buffers are
    ``(rows, chunk)`` with rows padded to a multiple of
    ``world * n_buckets`` — a different dp world is a different GLOBAL
    shape.  Save at dp=src, restore_latest at dp=dst (shapes mismatch
    -> resharded path), re-save, and compare the two checkpoints'
    mesh-independent logical views bit for bit."""
    opt = DistributedFusedAdam(lr=1e-2, flat_bucket=True, n_buckets=2)
    d_src = str(tmp_path / "src")
    d_dst = str(tmp_path / "dst")

    mesh = parallel.initialize_model_parallel(devices=devices8[:src_n])
    pack, spec = _zero_pack(mesh, opt)
    src_mgr = CheckpointManager(d_src, sharded=True, spec=spec)
    src_path = src_mgr.save(pack, 3)
    parallel.destroy_model_parallel()

    mesh = parallel.initialize_model_parallel(devices=devices8[:dst_n])
    like, spec2 = _zero_pack(mesh, opt, seed=1)  # different values
    dst_mgr = CheckpointManager(d_dst, sharded=True, spec=spec2)
    restored, at = CheckpointManager(
        d_src, sharded=True, spec=spec2).restore_latest(like)
    assert at == 3
    # buffers really are laid out for the NEW world
    for (pth, a), b in zip(
            jax.tree_util.tree_leaves_with_path(restored),
            jax.tree_util.tree_leaves(like)):
        assert np.shape(a) == np.shape(b), pth
    dst_path = dst_mgr.save(restored, 3)

    src_logical, _ = reshard.load_logical(src_path)
    dst_logical, _ = reshard.load_logical(dst_path)
    assert sorted(src_logical) == sorted(dst_logical)
    for key in src_logical:
        np.testing.assert_array_equal(src_logical[key],
                                      dst_logical[key], err_msg=key)


def test_bare_spec_mesh_kwarg_reshards_zero_state(tmp_path, devices8):
    """``restore_latest(like, mesh=...)`` — no hand-built spec — must
    still reshard ZeRO flat-bucket state: the group layouts and
    ``fold``/``ravel_of`` markers are mesh-independent, so the bare
    target spec inherits them from the SOURCE checkpoint's spec (every
    target-dependent size comes from ``like``)."""
    opt = DistributedFusedAdam(lr=1e-2, flat_bucket=True, n_buckets=2)
    root = str(tmp_path / "m")

    mesh = parallel.initialize_model_parallel(devices=devices8[:4])
    pack, spec = _zero_pack(mesh, opt)
    CheckpointManager(root, sharded=True, spec=spec).save(pack, 0)
    src_logical, _ = reshard.load_logical(
        CheckpointManager(root, sharded=True)._path(0))
    parallel.destroy_model_parallel()

    mesh = parallel.initialize_model_parallel(devices=devices8[:2])
    like, _ = _zero_pack(mesh, opt, seed=1)
    restored, at = CheckpointManager(root, sharded=True).restore_latest(
        like, mesh=mesh)
    assert at == 0
    d2 = str(tmp_path / "m2")
    spec2 = reshard.build_spec(like, mesh=mesh,
                               zero_states=[("opt", opt, like["params"])])
    CheckpointManager(d2, sharded=True, spec=spec2).save(restored, 0)
    dst_logical, _ = reshard.load_logical(
        CheckpointManager(d2, sharded=True)._path(0))
    for key in src_logical:
        np.testing.assert_array_equal(src_logical[key],
                                      dst_logical[key], err_msg=key)


def test_mixed_step_shard_dir_is_corruption(tmp_path, devices8):
    """A legacy (manifest-less) shard dir holding shards of two
    DIFFERENT steps must fail as corruption, not silently assemble a
    chimera state — the same torn/mixed guard as the plain sharded
    restore, on the reshard source reader."""
    mesh = parallel.initialize_model_parallel(devices=devices8[:2])
    from jax.sharding import NamedSharding, PartitionSpec as P

    d = str(tmp_path / "s")
    w = jax.device_put(jnp.arange(16.0).reshape(8, 2),
                       NamedSharding(mesh, P(("dcn", "dp"), None)))
    ckpt.save_checkpoint_sharded(d, {"w": w}, step=0)
    # simulate an overlapping save torn mid-flight: shard_1 from a
    # LATER step survives next to shard_0 of the committed one
    import shutil

    shutil.copy(os.path.join(d, "shard_0.npz"),
                os.path.join(d, "shard_1.npz"))
    with np.load(os.path.join(d, "shard_1.npz"),
                 allow_pickle=False) as data:
        manifest = json.loads(str(data["__manifest__"]))
        arrays = {k: data[k] for k in data.files if k != "__manifest__"}
    manifest["step"] = 1
    with open(os.path.join(d, "shard_1.npz"), "wb") as f:
        np.savez(f, __manifest__=json.dumps(manifest), **arrays)
    os.unlink(os.path.join(d, "manifest.json"))  # legacy layout

    with pytest.raises(ckpt.CheckpointCorruptError, match="mixed"):
        reshard.load_logical(d)


def test_load_logical_propagates_malformed_spec(tmp_path):
    """Only a truly ABSENT spec falls back to the plain-leaf
    fingerprint; a malformed one must raise (naming the bad field), or
    the harness would misread a corrupt spec as training-state
    divergence."""
    path = str(tmp_path / "c.npz")
    ckpt.save_checkpoint(path, {"w": jnp.arange(6.0)}, step=0)
    leaves, _ = reshard.load_logical(path)  # spec-less: plain leaves
    assert list(leaves) == ["w"]

    with np.load(path, allow_pickle=False) as data:
        manifest = json.loads(str(data["__manifest__"]))
        arrays = {k: data[k] for k in data.files if k != "__manifest__"}
    manifest["sharding_spec"] = {"version": reshard.SPEC_VERSION,
                                 "leaves": ["not", "a", "dict"],
                                 "groups": {}}
    with open(path, "wb") as f:
        np.savez(f, __manifest__=json.dumps(manifest), **arrays)
    with pytest.raises(ckpt.CheckpointCorruptError, match="leaves"):
        reshard.load_logical(path)


def test_folded_layer_stack_refactors(tmp_path, devices8):
    """A ``[vpp, pp, ...]`` layer stack marked ``fold=2`` restores
    across a pipeline-depth change by pure reshape: (vpp=1, pp=2) ->
    (vpp=2, pp=1) — the tp/pp elastic transition — bit-exactly and in
    the virtual-stage-major order the interleaved schedule assigns."""
    mesh = parallel.initialize_model_parallel(devices=devices8[:2])
    stack = jnp.arange(2 * 4 * 3.0).reshape(1, 2, 4, 3)  # [vpp=1, pp=2]
    tree = {"layers": replicate(stack, mesh), "tail": jnp.ones((5,))}
    spec = reshard.build_spec(tree, mesh=mesh,
                              folds={"layers": 2, "tail": 0})
    path = str(tmp_path / "c.npz")
    ckpt.save_checkpoint(path, tree, step=0, spec=spec)
    parallel.destroy_model_parallel()

    mesh = parallel.initialize_model_parallel(devices=devices8[:2])
    like = {"layers": replicate(jnp.zeros((2, 1, 4, 3)), mesh),
            "tail": jnp.zeros((5,))}
    spec2 = reshard.build_spec(like, mesh=mesh,
                               folds={"layers": 2, "tail": 0})
    restored, _ = reshard.restore_resharded(path, like, spec2)
    np.testing.assert_array_equal(
        np.asarray(restored["layers"]).reshape(2, 4, 3),
        np.asarray(stack).reshape(2, 4, 3))
    np.testing.assert_array_equal(np.asarray(restored["tail"]),
                                  np.ones((5,)))


# ---------------------------------------------------------------------------
# Manifest back-compat
# ---------------------------------------------------------------------------


def _downgrade_to_v1(path):
    """Rewrite a flat checkpoint as its pre-PR-6 self: manifest version
    1, no ``sharding_spec``."""
    with np.load(path, allow_pickle=False) as data:
        manifest = json.loads(str(data["__manifest__"]))
        arrays = {k: data[k] for k in data.files if k != "__manifest__"}
    manifest["version"] = 1
    manifest.pop("sharding_spec", None)
    with open(path, "wb") as f:
        np.savez(f, __manifest__=json.dumps(manifest), **arrays)


def test_legacy_v1_manifest_restores_same_mesh(tmp_path):
    """A pre-PR-6 manifest (version 1, spec-less) still restores onto
    the mesh shape that wrote it — both through the raw reader and
    through ``restore_latest`` WITH a target spec configured (the
    same-shape check routes it down the plain path)."""
    root = str(tmp_path / "m")
    mgr = CheckpointManager(root, keep=3)
    tree = {"w": jnp.arange(12.0).reshape(3, 4), "n": jnp.ones((2,))}
    mgr.save(tree, 0)
    _downgrade_to_v1(mgr._path(0))

    restored, at = ckpt.restore_checkpoint(mgr._path(0), tree)
    assert at == 0
    mesh = parallel.initialize_model_parallel()
    spec = reshard.build_spec(tree, mesh=mesh)
    restored, at = CheckpointManager(
        root, keep=3, spec=spec).restore_latest(tree)
    assert at == 0
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(tree["w"]))


def test_legacy_manifest_shape_mismatch_names_missing_spec(tmp_path):
    """A spec-less checkpoint CANNOT reshard: asking it to (template
    shapes differ) fails with an error naming the missing
    ``sharding_spec`` — and ``restore_latest`` reports it in the
    no-checkpoint error after falling back past it."""
    root = str(tmp_path / "m")
    mgr = CheckpointManager(root, keep=3)
    mgr.save({"w": jnp.arange(12.0).reshape(3, 4)}, 0)
    _downgrade_to_v1(mgr._path(0))

    like = {"w": jnp.zeros((4, 3))}  # a different layout
    mesh = parallel.initialize_model_parallel()
    spec = reshard.build_spec(like, mesh=mesh)
    with pytest.raises(FileNotFoundError, match="sharding_spec"):
        CheckpointManager(root, keep=3, spec=spec).restore_latest(like)


def test_newer_manifest_version_is_corruption_class(tmp_path):
    """A manifest NEWER than this reader supports must fail loudly (and
    fallback-eligibly) rather than be misread."""
    root = str(tmp_path / "m")
    mgr = CheckpointManager(root, keep=3)
    tree = {"w": jnp.arange(4.0)}
    mgr.save(tree, 0)
    mgr.save(tree, 1)
    path = mgr._path(1)
    with np.load(path, allow_pickle=False) as data:
        manifest = json.loads(str(data["__manifest__"]))
        arrays = {k: data[k] for k in data.files if k != "__manifest__"}
    manifest["version"] = ckpt.MANIFEST_VERSION + 1
    with open(path, "wb") as f:
        np.savez(f, __manifest__=json.dumps(manifest), **arrays)

    with pytest.raises(ckpt.CheckpointCorruptError, match="newer"):
        ckpt.restore_checkpoint(path, tree)
    # restore_latest falls back past it to the intact older step
    restored, at = mgr.restore_latest(tree, verify=False)
    assert at == 0


# ---------------------------------------------------------------------------
# Retention: committed-only counting + the hung-writer pin (ISSUE 6 fix)
# ---------------------------------------------------------------------------


def test_retention_ignores_uncommitted_crash_artifacts(tmp_path):
    """Crash artifacts (step dirs with shards but no committed
    ``manifest.json``) must never count toward ``keep``: two artifacts
    above the last durable save used to push it out of the window and
    retention deleted the only restorable state.  Artifacts NEWER than
    the newest committed step are left alone (their writer may still be
    in flight); artifacts strictly older are provably dead — saves are
    step-monotonic — and are reaped so repeated crashes cannot grow the
    directory without bound."""
    root = str(tmp_path / "m")
    mgr = CheckpointManager(root, keep=2, sharded=True)
    tree = {"w": jnp.arange(8.0)}
    mgr.save(tree, 0)
    mgr.save(tree, 1)
    # two uncommitted artifacts above every durable save, one below 4
    for s in (2, 3, 5):
        os.makedirs(mgr._path(s))
        with open(os.path.join(mgr._path(s), "shard_0.npz"), "wb") as f:
            f.write(b"torn")
    mgr.save(tree, 4)  # triggers retention
    # committed ledger is [0, 1, 4]: 0 dropped, 1 and 4 kept — the
    # artifacts did NOT push 1 out of the keep=2 window
    assert not os.path.exists(mgr._path(0))
    assert mgr.verify(1) and mgr.verify(4)
    # dead artifacts (older than committed step 4) reaped; the one
    # above the newest commit may be a live writer — untouched
    assert not os.path.exists(mgr._path(2))
    assert not os.path.exists(mgr._path(3))
    assert os.path.exists(mgr._path(5))
    _, at = mgr.restore_latest(tree)
    assert at == 4


def test_retention_never_deletes_last_committed_under_hung_write(
        tmp_path):
    """The ISSUE 6 retention bug, pinned with ``faults.hung_writes``:
    with ``keep=1`` and an async save provably parked mid-write (step
    dir visible, zero bytes committed), a retention pass must NOT drop
    the last-committed step — pre-fix, ``all_steps()`` counted the
    in-flight dir, pushed the durable step out of the window, and a
    crash at that moment lost the only restorable state."""
    root = str(tmp_path / "m")
    mgr = CheckpointManager(root, keep=1, sharded=True)
    tree = {"w": jnp.arange(8.0)}
    mgr.save(tree, 0)
    with faults.hung_writes(path_prefix=root) as gate:
        handle = mgr.save_async({"w": jnp.full((8,), 9.0)}, 1)
        assert gate.entered.wait(timeout=30)
        # the retention pass any concurrent save/wait would run
        mgr._apply_retention()
        assert mgr.verify(0)  # durable step survived
        assert os.path.exists(mgr._path(1))  # in-flight dir untouched
        gate.release()
        handle.result(timeout=30)
    mgr.wait()  # commits step 1; retention now drops step 0
    _, at = mgr.restore_latest(tree)
    assert at == 1
    assert not os.path.exists(mgr._path(0))


def test_retention_pins_step_a_restore_is_reading(tmp_path):
    """The restore-side pin: a step a concurrent ``restore_latest`` is
    reading is exempt from retention until the read finishes."""
    root = str(tmp_path / "m")
    mgr = CheckpointManager(root, keep=1, sharded=True)
    tree = {"w": jnp.arange(8.0)}
    mgr.save(tree, 0)
    mgr._pinned.add(0)  # what restore_latest holds while reading step 0
    try:
        mgr.save(tree, 1)  # retention would otherwise drop step 0
        assert mgr.verify(0)
    finally:
        mgr._pinned.discard(0)
    mgr.save(tree, 2)  # unpinned: the normal window applies again
    assert not os.path.exists(mgr._path(0))
    assert not os.path.exists(mgr._path(1))


# ---------------------------------------------------------------------------
# Observability satellite: fallback-depth counter
# ---------------------------------------------------------------------------


def test_restore_latest_counts_fallback_depth(tmp_path):
    """``restore_latest`` flushes a ``ckpt/fallback_depth`` counter (how
    many corrupt candidates were skipped before success) and a
    ``checkpoint/restore_latest`` span through the default rank-aware
    registry."""
    from apex_tpu.observability.metrics import default_registry

    root = str(tmp_path / "m")
    mgr = CheckpointManager(root, keep=3)
    tree = {"w": jnp.arange(64.0)}
    for s in range(3):
        mgr.save({"w": jnp.full((64,), float(s))}, s)
    faults.corrupt_checkpoint(mgr._path(2))
    faults.corrupt_checkpoint(mgr._path(1))

    reg = default_registry()
    before = reg.counter("ckpt/fallback_depth").value
    restored, at = mgr.restore_latest(tree)
    assert at == 0
    assert reg.counter("ckpt/fallback_depth").value - before == 2
    assert any(k.startswith("span_ms/checkpoint/restore_latest")
               for k in reg.snapshot())
