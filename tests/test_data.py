"""Input pipeline (apex_tpu.data): ImageFolder contract, DP sharding,
augmentation determinism, on-device normalization.

Reference contract: ``examples/imagenet/main_amp.py:207-232`` (ImageFolder
+ RandomResizedCrop/flip + DistributedSampler) and ``fast_collate``/
prefetcher normalize (``:48-63,256-276``).
"""

import numpy as np
import pytest

from apex_tpu.data import (
    ImageFolder,
    ImageFolderLoader,
    center_crop_resize,
    normalize_on_device,
    random_resized_crop,
    synthetic_image_batches,
)


@pytest.fixture(scope="module")
def image_root(tmp_path_factory):
    """Tiny 2-class x 8-image folder tree (PNG, varied sizes)."""
    from PIL import Image

    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = root / cls
        d.mkdir()
        for i in range(8):
            h, w = rng.randint(40, 80), rng.randint(40, 80)
            arr = rng.randint(0, 256, (h, w, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.png")
    return str(root)


def test_image_folder_scan(image_root):
    ds = ImageFolder(image_root)
    assert ds.classes == ["cat", "dog"]  # sorted subdirs
    assert ds.class_to_idx == {"cat": 0, "dog": 1}
    assert len(ds) == 16
    img, label = ds.load(0)
    assert label == 0 and img.mode == "RGB"
    _, label_last = ds.load(15)
    assert label_last == 1


def test_transforms_shapes_and_determinism(image_root):
    ds = ImageFolder(image_root)
    img, _ = ds.load(3)
    a = random_resized_crop(np.random.RandomState(7), img, 32)
    b = random_resized_crop(np.random.RandomState(7), img, 32)
    c = random_resized_crop(np.random.RandomState(8), img, 32)
    assert a.shape == (32, 32, 3) and a.dtype == np.uint8
    np.testing.assert_array_equal(a, b)  # same seed, same crop
    assert not np.array_equal(a, c)      # different seed, different crop

    e = center_crop_resize(img, 32)
    assert e.shape == (32, 32, 3) and e.dtype == np.uint8
    np.testing.assert_array_equal(e, center_crop_resize(img, 32))


def test_loader_dp_sharding(image_root):
    """Global batches carry dp disjoint per-rank rows; epoch-deterministic."""
    ds = ImageFolder(image_root)
    mk = lambda: ImageFolderLoader(  # noqa: E731
        ds, local_batch=2, data_parallel_size=2, image_size=16, seed=1)
    x, y = next(iter(mk()))
    assert x.shape == (4, 16, 16, 3) and x.dtype == np.uint8
    assert y.shape == (4,) and y.dtype == np.int32
    x2, y2 = next(iter(mk()))
    np.testing.assert_array_equal(x, x2)  # same consumed_samples, same batch
    np.testing.assert_array_equal(y, y2)

    # the two rank windows come from disjoint sampler buckets: one epoch of
    # per-rank sample indices must not intersect
    loader = mk()
    rank_indices = [set(), set()]
    for per_rank in zip(*loader.samplers):
        for r, ids in enumerate(per_rank):
            rank_indices[r].update(ids)
    assert rank_indices[0] and rank_indices[1]
    assert not rank_indices[0] & rank_indices[1], rank_indices
    assert loader.consumed_samples > 0  # iterating advanced the epoch state


def test_loader_prefetch_determinism(image_root):
    """Prefetch depth never changes the delivered batch stream (samples,
    order, or augmentation)."""
    ds = ImageFolder(image_root)
    mk = lambda pf: ImageFolderLoader(  # noqa: E731
        ds, local_batch=2, data_parallel_size=2, image_size=16, seed=1,
        prefetch=pf)
    import itertools

    with mk(0) as sync_loader, mk(3) as pf_loader:
        sync_batches = list(itertools.islice(iter(sync_loader), 3))
        pf_batches = list(itertools.islice(iter(pf_loader), 3))
    for (xs, ys), (xp, yp) in zip(sync_batches, pf_batches):
        np.testing.assert_array_equal(xs, xp)
        np.testing.assert_array_equal(ys, yp)


def test_loader_prefetch_consumed_samples(image_root):
    """consumed_samples counts *yielded* batches only, and an abandoned
    iterator rewinds its in-flight batches (checkpoint-resume contract)."""
    ds = ImageFolder(image_root)
    with ImageFolderLoader(ds, local_batch=2, data_parallel_size=2,
                           image_size=16, seed=1, prefetch=2) as loader:
        it = iter(loader)
        a = next(it)
        assert loader.consumed_samples == 4  # one global batch delivered
        b = next(it)
        assert loader.consumed_samples == 8
        it.close()  # abandon with batches still in flight
        assert loader.consumed_samples == 8
        # a fresh iterator resumes at the first undelivered batch: it must
        # not replay batch 1 or 2
        c = next(iter(loader))
        assert loader.consumed_samples == 12
    assert not (np.array_equal(a[0], c[0]) or np.array_equal(b[0], c[0]))


def test_loader_prefetch_overlaps_decode(image_root):
    """With a slow consumer, prefetch hides decode latency: total wall
    time ~= consumer time, not consumer + decode."""
    import time

    ds = ImageFolder(image_root)

    class SlowFolder:
        classes = ds.classes
        samples = ds.samples

        def __len__(self):
            return len(ds)

        def load(self, index):
            time.sleep(0.05)
            return ds.load(index)

    def run(pf):
        with ImageFolderLoader(SlowFolder(), local_batch=4, image_size=16,
                               seed=1, workers=4, prefetch=pf) as loader:
            it = iter(loader)
            next(it)  # warm: first batch always pays full decode latency
            t0 = time.perf_counter()
            for _ in range(2):
                time.sleep(0.1)  # the "train step"
                next(it)
            return time.perf_counter() - t0

    # sync: each step pays 0.1 consumer + ~0.05 decode; prefetch: decode
    # hides under the consumer sleep.  Generous margins for CI jitter.
    assert run(2) < run(0) - 0.05


def test_normalize_on_device_matches_numpy():
    import jax

    x = np.random.RandomState(0).randint(
        0, 256, (2, 8, 8, 3), dtype=np.uint8)
    out = jax.jit(normalize_on_device)(x)
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)
    ref = (x.astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_prefetch_to_device_sharding_and_order():
    """Batches come back on-device, dp-sharded, in order, depth ahead."""
    import itertools

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.data import prefetch_to_device

    mesh = parallel.initialize_model_parallel()
    try:
        host = list(itertools.islice(synthetic_image_batches(8, 8, 10), 4))
        dev = list(prefetch_to_device(iter(host), mesh, depth=2))
        assert len(dev) == 4
        want = NamedSharding(mesh, P(("dcn", "dp"), None, None, None))
        for (hx, hy), (dx, dy) in zip(host, dev):
            assert dx.sharding.is_equivalent_to(want, dx.ndim)
            np.testing.assert_array_equal(np.asarray(dx), hx)
            np.testing.assert_array_equal(np.asarray(dy), hy)
    finally:
        parallel.mesh.destroy_model_parallel()


def test_prefetch_to_device_resume_composition(image_root):
    """The documented resume recipe: re-wrapping a restored loader with
    prefetch_to_device continues the exact batch stream (the loader
    rewinds its own in-flight decode; the device wrapper adds no state)."""
    import itertools

    from apex_tpu.data import prefetch_to_device

    ds = ImageFolder(image_root)

    def run(consumed, n):
        with ImageFolderLoader(ds, local_batch=4, image_size=16, seed=3,
                               prefetch=2, consumed_samples=consumed) as ld:
            dev = prefetch_to_device(ld, depth=2)
            out = [(np.asarray(x), np.asarray(y))
                   for x, y in itertools.islice(dev, n)]
            # checkpoint the WRAPPER's count: the loader's own runs ahead
            # by the device queue (dev.in_flight batches)
            assert dev.consumed_samples == ld.consumed_samples - (
                dev.in_flight * 4)
            return out, dev.consumed_samples

    full, _ = run(0, 3)
    head, consumed = run(0, 1)
    assert consumed == 4  # one delivered batch, despite prefetch depth 2
    # crash/restore: a fresh loader + wrapper from the checkpointed
    # consumed_samples picks up at the first undelivered batch
    tail, _ = run(consumed, 2)
    for (ax, ay), (bx, by) in zip(full[1:], tail):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)


def test_prefetch_to_device_plain_device_put():
    """Without a mesh, falls back to plain device_put; depth=0 works."""
    import jax

    from apex_tpu.data import prefetch_to_device

    host = [np.arange(6, dtype=np.float32).reshape(2, 3) + i
            for i in range(3)]
    out = list(prefetch_to_device(host, depth=0))
    assert len(out) == 3
    for h, d in zip(host, out):
        assert isinstance(d, jax.Array)
        np.testing.assert_array_equal(np.asarray(d), h)


def test_synthetic_batches_contract():
    it = synthetic_image_batches(4, 16, 10)
    x, y = next(it)
    assert x.shape == (4, 16, 16, 3) and x.dtype == np.uint8
    assert y.shape == (4,) and y.dtype == np.int32
    assert y.max() < 10
