"""Input pipeline (apex_tpu.data): ImageFolder contract, DP sharding,
augmentation determinism, on-device normalization.

Reference contract: ``examples/imagenet/main_amp.py:207-232`` (ImageFolder
+ RandomResizedCrop/flip + DistributedSampler) and ``fast_collate``/
prefetcher normalize (``:48-63,256-276``).
"""

import numpy as np
import pytest

from apex_tpu.data import (
    ImageFolder,
    ImageFolderLoader,
    center_crop_resize,
    normalize_on_device,
    random_resized_crop,
    synthetic_image_batches,
)


@pytest.fixture(scope="module")
def image_root(tmp_path_factory):
    """Tiny 2-class x 8-image folder tree (PNG, varied sizes)."""
    from PIL import Image

    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = root / cls
        d.mkdir()
        for i in range(8):
            h, w = rng.randint(40, 80), rng.randint(40, 80)
            arr = rng.randint(0, 256, (h, w, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.png")
    return str(root)


def test_image_folder_scan(image_root):
    ds = ImageFolder(image_root)
    assert ds.classes == ["cat", "dog"]  # sorted subdirs
    assert ds.class_to_idx == {"cat": 0, "dog": 1}
    assert len(ds) == 16
    img, label = ds.load(0)
    assert label == 0 and img.mode == "RGB"
    _, label_last = ds.load(15)
    assert label_last == 1


def test_transforms_shapes_and_determinism(image_root):
    ds = ImageFolder(image_root)
    img, _ = ds.load(3)
    a = random_resized_crop(np.random.RandomState(7), img, 32)
    b = random_resized_crop(np.random.RandomState(7), img, 32)
    c = random_resized_crop(np.random.RandomState(8), img, 32)
    assert a.shape == (32, 32, 3) and a.dtype == np.uint8
    np.testing.assert_array_equal(a, b)  # same seed, same crop
    assert not np.array_equal(a, c)      # different seed, different crop

    e = center_crop_resize(img, 32)
    assert e.shape == (32, 32, 3) and e.dtype == np.uint8
    np.testing.assert_array_equal(e, center_crop_resize(img, 32))


def test_loader_dp_sharding(image_root):
    """Global batches carry dp disjoint per-rank rows; epoch-deterministic."""
    ds = ImageFolder(image_root)
    mk = lambda: ImageFolderLoader(  # noqa: E731
        ds, local_batch=2, data_parallel_size=2, image_size=16, seed=1)
    x, y = next(iter(mk()))
    assert x.shape == (4, 16, 16, 3) and x.dtype == np.uint8
    assert y.shape == (4,) and y.dtype == np.int32
    x2, y2 = next(iter(mk()))
    np.testing.assert_array_equal(x, x2)  # same consumed_samples, same batch
    np.testing.assert_array_equal(y, y2)

    # the two rank windows come from disjoint sampler buckets: one epoch of
    # per-rank sample indices must not intersect
    loader = mk()
    rank_indices = [set(), set()]
    for per_rank in zip(*loader.samplers):
        for r, ids in enumerate(per_rank):
            rank_indices[r].update(ids)
    assert rank_indices[0] and rank_indices[1]
    assert not rank_indices[0] & rank_indices[1], rank_indices
    assert loader.consumed_samples > 0  # iterating advanced the epoch state


def test_loader_prefetch_determinism(image_root):
    """Prefetch depth never changes the delivered batch stream (samples,
    order, or augmentation)."""
    ds = ImageFolder(image_root)
    mk = lambda pf: ImageFolderLoader(  # noqa: E731
        ds, local_batch=2, data_parallel_size=2, image_size=16, seed=1,
        prefetch=pf)
    import itertools

    with mk(0) as sync_loader, mk(3) as pf_loader:
        sync_batches = list(itertools.islice(iter(sync_loader), 3))
        pf_batches = list(itertools.islice(iter(pf_loader), 3))
    for (xs, ys), (xp, yp) in zip(sync_batches, pf_batches):
        np.testing.assert_array_equal(xs, xp)
        np.testing.assert_array_equal(ys, yp)


def test_loader_prefetch_consumed_samples(image_root):
    """consumed_samples counts *yielded* batches only, and an abandoned
    iterator rewinds its in-flight batches (checkpoint-resume contract)."""
    ds = ImageFolder(image_root)
    with ImageFolderLoader(ds, local_batch=2, data_parallel_size=2,
                           image_size=16, seed=1, prefetch=2) as loader:
        it = iter(loader)
        a = next(it)
        assert loader.consumed_samples == 4  # one global batch delivered
        b = next(it)
        assert loader.consumed_samples == 8
        it.close()  # abandon with batches still in flight
        assert loader.consumed_samples == 8
        # a fresh iterator resumes at the first undelivered batch: it must
        # not replay batch 1 or 2
        c = next(iter(loader))
        assert loader.consumed_samples == 12
    assert not (np.array_equal(a[0], c[0]) or np.array_equal(b[0], c[0]))


def test_loader_prefetch_overlaps_decode(image_root, monkeypatch):
    """With a slow consumer, prefetch hides decode latency: total wall
    time ~= consumer time, not consumer + decode.  Slowness is injected
    at the decode-core seam (``_decode_one`` — the one function both
    worker backends run), since decode no longer flows through
    ``dataset.load``."""
    import time

    from apex_tpu.data import image_folder as ifm

    ds = ImageFolder(image_root)
    real_decode = ifm._decode_one

    def slow_decode(spec, index, marker):
        time.sleep(0.05)
        return real_decode(spec, index, marker)

    monkeypatch.setattr(ifm, "_decode_one", slow_decode)

    def run(pf):
        with ImageFolderLoader(ds, local_batch=4, image_size=16,
                               seed=1, workers=4, prefetch=pf) as loader:
            it = iter(loader)
            next(it)  # warm: first batch always pays full decode latency
            t0 = time.perf_counter()
            for _ in range(2):
                time.sleep(0.1)  # the "train step"
                next(it)
            return time.perf_counter() - t0

    # sync: each step pays 0.1 consumer + ~0.05 decode; prefetch: decode
    # hides under the consumer sleep.  Generous margins for CI jitter.
    assert run(2) < run(0) - 0.05


def test_normalize_on_device_matches_numpy():
    import jax

    x = np.random.RandomState(0).randint(
        0, 256, (2, 8, 8, 3), dtype=np.uint8)
    out = jax.jit(normalize_on_device)(x)
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)
    ref = (x.astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_prefetch_to_device_sharding_and_order():
    """Batches come back on-device, dp-sharded, in order, depth ahead."""
    import itertools

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.data import prefetch_to_device

    mesh = parallel.initialize_model_parallel()
    try:
        host = list(itertools.islice(synthetic_image_batches(8, 8, 10), 4))
        dev = list(prefetch_to_device(iter(host), mesh, depth=2))
        assert len(dev) == 4
        want = NamedSharding(mesh, P(("dcn", "dp"), None, None, None))
        for (hx, hy), (dx, dy) in zip(host, dev):
            assert dx.sharding.is_equivalent_to(want, dx.ndim)
            np.testing.assert_array_equal(np.asarray(dx), hx)
            np.testing.assert_array_equal(np.asarray(dy), hy)
    finally:
        parallel.mesh.destroy_model_parallel()


def test_prefetch_to_device_resume_composition(image_root):
    """The documented resume recipe: re-wrapping a restored loader with
    prefetch_to_device continues the exact batch stream (the loader
    rewinds its own in-flight decode; the device wrapper adds no state)."""
    import itertools

    from apex_tpu.data import prefetch_to_device

    ds = ImageFolder(image_root)

    def run(consumed, n):
        with ImageFolderLoader(ds, local_batch=4, image_size=16, seed=3,
                               prefetch=2, consumed_samples=consumed) as ld:
            dev = prefetch_to_device(ld, depth=2)
            out = [(np.asarray(x), np.asarray(y))
                   for x, y in itertools.islice(dev, n)]
            # checkpoint the WRAPPER's count: the loader's own runs ahead
            # by the device queue (dev.in_flight batches)
            assert dev.consumed_samples == ld.consumed_samples - (
                dev.in_flight * 4)
            return out, dev.consumed_samples

    full, _ = run(0, 3)
    head, consumed = run(0, 1)
    assert consumed == 4  # one delivered batch, despite prefetch depth 2
    # crash/restore: a fresh loader + wrapper from the checkpointed
    # consumed_samples picks up at the first undelivered batch
    tail, _ = run(consumed, 2)
    for (ax, ay), (bx, by) in zip(full[1:], tail):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)


def test_prefetch_to_device_plain_device_put():
    """Without a mesh, falls back to plain device_put; depth=0 works."""
    import jax

    from apex_tpu.data import prefetch_to_device

    host = [np.arange(6, dtype=np.float32).reshape(2, 3) + i
            for i in range(3)]
    out = list(prefetch_to_device(host, depth=0))
    assert len(out) == 3
    for h, d in zip(host, out):
        assert isinstance(d, jax.Array)
        np.testing.assert_array_equal(np.asarray(d), h)


def test_synthetic_batches_contract():
    it = synthetic_image_batches(4, 16, 10)
    x, y = next(it)
    assert x.shape == (4, 16, 16, 3) and x.dtype == np.uint8
    assert y.shape == (4,) and y.dtype == np.int32
    assert y.max() < 10


# ---------------------------------------------------------------------------
# ISSUE 8: process-pool backend, per-host sharding, double-buffered
# prefetch stall metric, composition enforcement, data service
# ---------------------------------------------------------------------------


def test_process_backend_matches_thread_backend(image_root):
    """The process pool delivers the SAME batches (samples, order,
    augmentation) as the thread pool — the decode core is one pure
    function, so the backend is a pure throughput knob."""
    import itertools

    ds = ImageFolder(image_root)

    def batches(backend):
        with ImageFolderLoader(ds, local_batch=2, data_parallel_size=2,
                               image_size=16, seed=1, workers=2,
                               backend=backend) as loader:
            return list(itertools.islice(iter(loader), 3))

    for (xt, yt), (xp, yp) in zip(batches("thread"), batches("process")):
        np.testing.assert_array_equal(xt, xp)
        np.testing.assert_array_equal(yt, yp)


def test_unknown_backend_rejected(image_root):
    with pytest.raises(ValueError, match="backend"):
        ImageFolderLoader(ImageFolder(image_root), local_batch=2,
                          backend="dali")


def test_dp_ranks_host_shard_window(image_root):
    """A dp_ranks-restricted loader yields exactly its ranks' windows of
    the full global batch, with GLOBAL consumed_samples — each host
    decodes only its own shards, one checkpoint integer resumes all."""
    ds = ImageFolder(image_root)
    with ImageFolderLoader(ds, local_batch=2, data_parallel_size=2,
                           image_size=16, seed=1) as full, \
            ImageFolderLoader(ds, local_batch=2, data_parallel_size=2,
                              image_size=16, seed=1,
                              dp_ranks=[1]) as host1:
        xf, yf = next(iter(full))
        x1, y1 = next(iter(host1))
    assert x1.shape == (2, 16, 16, 3)
    np.testing.assert_array_equal(x1, xf[2:])
    np.testing.assert_array_equal(y1, yf[2:])
    assert host1.consumed_samples == full.consumed_samples == 4
    with pytest.raises(ValueError, match="dp_ranks"):
        ImageFolderLoader(ds, local_batch=2, data_parallel_size=2,
                          dp_ranks=[2])


def test_host_dp_ranks_and_local_placement():
    """host_dp_ranks covers all shards in a single process, and
    dp_shard_batch(local_ranks=...) assembles the identical global
    array; a rank set that misses an addressable shard raises."""
    from apex_tpu import parallel
    from apex_tpu.parallel.distributed import dp_shard_batch, host_dp_ranks

    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=2)  # dp=4, tp=2: shards replicate on tp
    try:
        ranks = host_dp_ranks(mesh)
        assert ranks == [0, 1, 2, 3]
        x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
        y = np.float32(0.5)  # scalar leaf replicates
        ga, sa = dp_shard_batch((x, y), mesh)
        gb, sb = dp_shard_batch((x, y), mesh, local_ranks=ranks)
        np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))
        assert ga.sharding.is_equivalent_to(gb.sharding, ga.ndim)
        np.testing.assert_array_equal(np.asarray(sa), np.asarray(sb))
        with pytest.raises(ValueError, match="local_ranks"):
            dp_shard_batch(x[:2], mesh, local_ranks=[0])
    finally:
        parallel.mesh.destroy_model_parallel()


def test_prefetch_records_stall_metric(image_root):
    """Every delivered batch records its blocking wait into the
    data/stall_ms gauge + span_ms/data/next_wait histogram — the in-run
    stall measurement the bench cross-checks."""
    from apex_tpu.data import prefetch_to_device
    from apex_tpu.observability.metrics import MetricRegistry

    reg = MetricRegistry(rank=0, world=1)
    ds = ImageFolder(image_root)
    with ImageFolderLoader(ds, local_batch=4, image_size=16,
                           seed=1) as loader:
        dev = prefetch_to_device(loader, depth=2, place=lambda b: b,
                                 registry=reg)
        for _ in range(3):
            next(dev)
        dev.close(close_source=False)
    assert reg.gauge("data/stall_ms").value is not None
    hist = reg.histogram("span_ms/data/next_wait")
    assert hist.count == 3
    assert hist.mean is not None and hist.mean >= 0.0


def test_nested_prefetcher_rejected():
    from apex_tpu.data import prefetch_to_device

    inner = prefetch_to_device([np.zeros(2)], depth=0)
    with pytest.raises(TypeError, match="nested"):
        prefetch_to_device(inner)


def test_prefetcher_plain_iterator_has_no_resume_surface():
    """A plain iterator wraps fine for streaming, but consumed_samples
    names the composition contract instead of mis-counting."""
    from apex_tpu.data import prefetch_to_device

    dev = prefetch_to_device(iter([np.zeros(2), None, np.ones(2)]),
                             depth=0, place=lambda b: b)
    with pytest.raises(AttributeError, match="composition order"):
        dev.consumed_samples
    # a legitimately-None item is DELIVERED, not conflated with
    # exhaustion (the old next(it, None) bug)
    out = list(dev)
    assert len(out) == 3 and out[1] is None


def test_prefetcher_close_passthrough_and_rewind(image_root):
    """close() stops the transfer thread, rewinds undelivered batches on
    the source samplers, and shuts the loader's decode pool — the leak
    satellite.  After close, loader and wrapper agree."""
    from apex_tpu.data import prefetch_to_device

    ds = ImageFolder(image_root)
    loader = ImageFolderLoader(ds, local_batch=4, image_size=16, seed=3,
                               prefetch=2)
    dev = prefetch_to_device(loader, depth=2, place=lambda b: b)
    next(dev)
    dev.close()  # passthrough: also closes the loader
    assert dev.consumed_samples == 4
    assert loader.consumed_samples == 4
    # the decode pool is really closed: submitting to it must fail
    with pytest.raises(RuntimeError):
        loader._pool.submit(int, 0)
    # idempotent
    dev.close()


def _image_loader_factory(root: str, consumed: int):
    """Module-level (picklable) DataService factory."""
    from apex_tpu.data import ImageFolder, ImageFolderLoader

    return ImageFolderLoader(ImageFolder(root), local_batch=4,
                             image_size=16, seed=1, workers=2,
                             consumed_samples=consumed)


def test_data_service_streams_and_resumes(image_root):
    """DataService: the loader lives in a dedicated process; batches,
    the resume surface, and prefetch_to_device composition all match the
    in-process loader."""
    import functools

    from apex_tpu.data import DataService, prefetch_to_device

    factory = functools.partial(_image_loader_factory, image_root)
    with _image_loader_factory(image_root, 0) as ref_loader:
        ref = [next(iter(ref_loader))]
        it = iter(ref_loader)
    with DataService(factory) as svc:
        assert (svc.local_batch, svc.dp) == (4, 1)
        x, y = next(svc)
        np.testing.assert_array_equal(x, ref[0][0])
        np.testing.assert_array_equal(y, ref[0][1])
        assert svc.consumed_samples == 4
        # crosses the epoch boundary without ending the stream
        for _ in range(4):
            next(svc)
        assert svc.consumed_samples == 20
    # resume mid-stream: a fresh service continues bit-exact
    with DataService(factory) as a:
        first = [next(a) for _ in range(3)]
    with DataService(factory, consumed_samples=8) as b:
        cont = next(b)
    np.testing.assert_array_equal(cont[0], first[2][0])
    np.testing.assert_array_equal(cont[1], first[2][1])
    # prefetch composes on top (the documented stack)
    with DataService(factory) as svc:
        dev = prefetch_to_device(svc, depth=1, place=lambda t: t)
        next(dev)
        assert dev.consumed_samples == 4
        # close_source=False must leave the service alive even though a
        # self-iterating source IS its own iterator (the re-wrap shape)
        dev.close(close_source=False)
        next(svc)
        dev2 = prefetch_to_device(svc, depth=1, place=lambda t: t)
        next(dev2)
        dev2.close()  # full close reaps the service


def _process_loader_factory(root: str, consumed: int):
    from apex_tpu.data import ImageFolder, ImageFolderLoader

    return ImageFolderLoader(ImageFolder(root), local_batch=4,
                             image_size=16, seed=1, workers=2,
                             backend="process",
                             consumed_samples=consumed)


def test_data_service_hosts_process_backend_loader(image_root):
    """The documented composition: a DataService whose loader itself
    runs a process pool.  Requires the service process to be
    NON-daemonic (daemonic processes may not have children) — pinned
    here because the failure mode is a fatal relayed AssertionError on
    the first batch."""
    import functools

    from apex_tpu.data import DataService

    factory = functools.partial(_process_loader_factory, image_root)
    with DataService(factory) as svc:
        x, y = next(svc)
        assert x.shape == (4, 16, 16, 3) and y.shape == (4,)
        assert svc.consumed_samples == 4
    # matches the in-process loader bitwise
    with _process_loader_factory(image_root, 0) as ref:
        xr, yr = next(iter(ref))
    np.testing.assert_array_equal(x, xr)
    np.testing.assert_array_equal(y, yr)


def test_data_service_relays_loader_errors():
    import functools

    from apex_tpu.data import DataService

    factory = functools.partial(_image_loader_factory, "/nonexistent/dir")
    with DataService(factory) as svc:
        with pytest.raises(Exception):
            next(svc)
