"""ZeRO-sharded optimizer numerics.

Mirrors ``apex/contrib/test/optimizers/test_dist_adam.py``: the distributed
(sharded) optimizer must match the single-rank fused optimizer bit-for-bit
(up to fp reduction order) on the same gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    join_fp32,
    split_fp32,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.parallel import collectives as cc

pytestmark = pytest.mark.slow

DP = 8


@pytest.fixture()
def mesh():
    m = parallel.initialize_model_parallel()  # all 8 devices on dp
    yield m
    parallel.destroy_model_parallel()


def make_params(key):
    ks = jax.random.split(key, 3)
    return {
        "w": jax.random.normal(ks[0], (13, 7)),   # 91 elems: pad path
        "b": jax.random.normal(ks[1], (8,)),
        "e": jax.random.normal(ks[2], (4, 4, 2)),
    }


def per_rank_grads(params, key):
    """Distinct grads per rank; their mean is what a DP step sees."""
    leaves, treedef = jax.tree_util.tree_flatten(params)

    def mk(r):
        return jax.tree_util.tree_unflatten(treedef, [
            jax.random.normal(jax.random.fold_in(key, r * 1000 + i),
                              leaf.shape)
            for i, leaf in enumerate(leaves)
        ])
    return [mk(r) for r in range(DP)]


def run_dist(opt, params, grads_by_rank, steps=3, **step_kw):
    grads_stacked = jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls), *grads_by_rank
    )

    def local(params, grads_stacked):
        r = cc.axis_index("dp")
        g = jax.tree_util.tree_map(
            lambda l: jax.lax.dynamic_index_in_dim(l, r, 0, keepdims=False),
            grads_stacked,
        )
        state = opt.init(params)
        p = params
        for _ in range(steps):
            p, state = opt.step(g, state, p, **step_kw)
        return p

    return cc.shard_over(
        local, in_specs=(P(), P()), out_specs=P()
    )(params, grads_stacked)


def run_ref(opt, params, grads_by_rank, steps=3, **step_kw):
    mean_g = jax.tree_util.tree_map(
        lambda *ls: sum(ls) / DP, *grads_by_rank
    )
    state = opt.init(params)
    p = params
    for _ in range(steps):
        p, state = opt.step(mean_g, state, p, **step_kw)
    return p


def test_dist_adam_matches_fused_adam(mesh):
    params = make_params(jax.random.PRNGKey(0))
    grads = per_rank_grads(params, jax.random.PRNGKey(1))
    dist = run_dist(DistributedFusedAdam(lr=1e-2, weight_decay=0.01),
                    params, grads)
    ref = run_ref(FusedAdam(lr=1e-2, weight_decay=0.01, master_weights=True),
                  params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(dist[k]), np.asarray(ref[k]),
                                   rtol=1e-5, atol=1e-6)


def test_dist_adam_bf16_param_remainders(mesh):
    """store_param_remainders: bf16 params + u16 remainder == fp32 master."""
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16), make_params(jax.random.PRNGKey(2))
    )
    grads = [jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), g)
             for g in per_rank_grads(params, jax.random.PRNGKey(3))]
    dist = run_dist(
        DistributedFusedAdam(lr=1e-2, store_param_remainders=True),
        params, grads,
    )
    # reference: plain sharded master path, truncate final to bf16
    ref = run_dist(DistributedFusedAdam(lr=1e-2), params, grads)
    for k in params:
        a = np.asarray(dist[k], np.float32)
        b = np.asarray(ref[k], np.float32)
        # both bf16 outputs; remainder path truncates vs rounds -> 1 ulp
        np.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-2)


def test_split_join_fp32_roundtrip():
    x = jnp.asarray(np.random.RandomState(0).randn(64), jnp.float32)
    hi, lo = split_fp32(x)
    np.testing.assert_array_equal(np.asarray(join_fp32(hi, lo)),
                                  np.asarray(x))


def test_dist_adam_skip_update(mesh):
    params = make_params(jax.random.PRNGKey(4))
    grads = per_rank_grads(params, jax.random.PRNGKey(5))
    dist = run_dist(DistributedFusedAdam(lr=1e-2), params, grads,
                    skip_update=jnp.asarray(True))
    for k in params:
        np.testing.assert_array_equal(np.asarray(dist[k]),
                                      np.asarray(params[k]))


def test_dist_lamb_matches_fused_lamb(mesh):
    params = make_params(jax.random.PRNGKey(6))
    grads = per_rank_grads(params, jax.random.PRNGKey(7))
    dist = run_dist(DistributedFusedLAMB(lr=1e-2, weight_decay=0.01),
                    params, grads)
    ref = run_ref(FusedLAMB(lr=1e-2, weight_decay=0.01, master_weights=True),
                  params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(dist[k]), np.asarray(ref[k]),
                                   rtol=1e-5, atol=1e-6)


def test_dist_lamb_flat_matches_per_leaf(mesh):
    """The chunked shard-local form (flat=True default) matches the
    per-leaf form — same math, same single psum of norm partials."""
    params = make_params(jax.random.PRNGKey(16))
    grads = per_rank_grads(params, jax.random.PRNGKey(17))
    a = run_dist(DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                      flat=True), params, grads)
    b = run_dist(DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                      flat=False), params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                   rtol=1e-6, atol=1e-7)


def test_distributed_lamb_global_norm_clip(mesh):
    """max_grad_norm clipping (reference _pipeline_block_reductions:728):
    with a tiny max_grad_norm the effective grads shrink by
    global_norm/max_norm — verified against the unsharded FusedLAMB fed
    pre-clipped mean grads."""
    params = make_params(jax.random.PRNGKey(0))
    grads_by_rank = per_rank_grads(params, jax.random.PRNGKey(1))

    max_norm = 0.5
    dist = DistributedFusedLAMB(lr=1e-2, max_grad_norm=max_norm)
    p_dist = run_dist(dist, params, grads_by_rank, steps=3)

    # reference: mean grads, clip by their global norm, plain FusedLAMB
    mean_grads = jax.tree_util.tree_map(
        lambda *ls: sum(ls) / DP, *grads_by_rank)
    gn = float(jnp.sqrt(sum(
        jnp.sum(jnp.square(g))
        for g in jax.tree_util.tree_leaves(mean_grads))))
    assert gn > max_norm  # the clip engages
    clipped = jax.tree_util.tree_map(
        lambda g: g / (gn / max_norm), mean_grads)
    ref_opt = FusedLAMB(lr=1e-2, max_grad_norm=0.0)
    p_ref = params
    state = ref_opt.init(p_ref)
    for _ in range(3):
        p_ref, state = ref_opt.step(clipped, state, p_ref)

    for a, b in zip(jax.tree_util.tree_leaves(p_dist),
                    jax.tree_util.tree_leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-6, atol=2e-6)
