"""The driver-record contract (VERDICT r4 item 1).

The driver keeps only the last 2000 bytes of bench stdout and parses the
last JSON line.  Rounds 1-4 all delivered ``parsed: null`` because the
full record line grew past the tail size.  These tests pin the fix: every
emission ends with a compact line that (a) is <= 1500 bytes, (b) parses,
(c) carries the driver contract fields, and (d) survives a simulated
2000-byte tail even in the worst case (all nineteen BENCH_ORDER rows
verbose — including ``real_data_rn50`` with its ``vs_synthetic``
composition, ``zero_adam_step`` with ``vs_per_leaf``, ``tp_gpt``
with its overlap_comm A/B fields (``overlap_tokens_per_sec`` /
``vs_monolithic``), ``ckpt_save_restore`` with ``vs_sharded``,
``ckpt_reshard`` with ``vs_same_mesh``, ``telemetry_overhead``
with ``vs_bare``, ``serving`` with its per-concurrency
tokens/sec + p50/p99 TPOT sub-rows and ``vs_unfused``,
``serving_occupancy`` with its per-oversubscription curve,
``vs_reserve`` and the prefix-cache TTFT A/B, ``serving_fleet``
with its steady/roll p99-TPOT pair and ``roll_vs_steady``, and
``serving_spec`` with its speculative-vs-baseline curve,
``vs_baseline`` and ``mean_accept_len``, and ``serving_autopilot``
with its burst-TTFT A/B (``vs_static``) and drain-back timing — +
embedded prior TPU evidence).
"""

import io
import json
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
import bench  # noqa: E402


def _worst_case_results():
    """All nineteen BENCH_ORDER rows, each fattened with prose fields,
    like a CPU-fallback day — the REAL worst case (the pre-fix nine-row
    set under-tested the <=1500-byte guarantee once ``real_data_rn50``,
    ``zero_adam_step``, ``ckpt_save_restore``, ``ckpt_reshard``,
    ``telemetry_overhead``, the ``serving`` row with its
    per-concurrency sub-dicts, and the ``serving_fleet`` row landed)."""
    rows = {
        "resnet50_o2": {"value": 8824.6, "unit": "images/sec/chip"},
        "gpt_flash": {"value": 95167.3, "unit": "tokens/sec/chip",
                      "mfu": 0.4155},
        "bert_large": {"value": 45956.4, "unit": "tokens/sec/chip",
                       "mfu": 0.5059},
        "resnet50_lamb_syncbn": {"value": 2566.8,
                                 "unit": "images/sec/chip"},
        "tp_gpt": {"value": 761.9, "unit": "tokens/sec",
                   "overlap_tokens_per_sec": 700.1, "vs_monolithic": 1.088},
        "fused_adam_step": {"value": 4777.5, "unit": "us/step",
                            "vs_native": 0.706},
        "zero_adam_step": {"value": 359273.7, "unit": "us/step",
                           "vs_per_leaf": 0.655},
        "ckpt_save_restore": {"value": 523.4,
                              "unit": "ms/save+verify+restore",
                              "vs_sharded": 1.113},
        "ckpt_reshard": {"value": 188.2, "unit": "ms/reshard-restore",
                         "vs_same_mesh": 1.74},
        "telemetry_overhead": {"value": 183451.2, "unit": "us/step",
                               "vs_bare": 1.012},
        "serving": {"value": 1843.7, "unit": "tokens/sec",
                    "vs_unfused": 1.31,
                    "tokens_per_sec_at": {"1": 241.2, "4": 962.5,
                                          "8": 1843.7},
                    "tpot_p50_ms_at": {"1": 4.11, "4": 4.19, "8": 4.32},
                    "tpot_p99_ms_at": {"1": 6.9, "4": 7.4, "8": 9.8}},
        "serving_occupancy": {"value": 1211.4, "unit": "tokens/sec",
                              "vs_reserve": 1.402,
                              "tokens_per_sec_at": {"1x": 1104.0,
                                                    "2x": 1211.4,
                                                    "4x": 1160.5},
                              "tpot_p99_ms_at": {"1x": 9.6, "2x": 10.9,
                                                 "4x": 24.9},
                              "preemptions_at": {"1x": 0, "2x": 4,
                                                 "4x": 10},
                              "ttft_cold_ms": 69.98,
                              "ttft_hit_ms": 35.39,
                              "ttft_hit_vs_cold": 0.506},
        "serving_fleet": {"value": 3104.2, "unit": "tokens/sec",
                          "replicas": 3,
                          "p99_tpot_ms_steady": 3.4,
                          "p99_tpot_ms_roll": 4.1,
                          "roll_vs_steady": 1.206,
                          "roll_wall_s": 46.7,
                          "tokens_per_sec_socket": 2688.2,
                          "wire_vs_inproc": 0.866},
        "serving_spec": {"value": 2154.2, "unit": "tokens/sec",
                         "vs_baseline": 2.256,
                         "mean_accept_len": 4.0,
                         "acceptance_rate": 0.933,
                         "tokens_per_sec_at": {"1": 357.6, "4": 1218.7,
                                               "8": 2154.2},
                         "baseline_tokens_per_sec_at": {
                             "1": 120.5, "4": 478.4, "8": 954.7},
                         "vs_baseline_at": {"1": 2.969, "4": 2.547,
                                            "8": 2.256}},
        "serving_autopilot": {"value": 612.4, "unit": "tokens/sec",
                              "p99_ttft_ms_burst": 112.6,
                              "p99_ttft_ms_static": 403.5,
                              "p99_tpot_ms_burst": 9.4,
                              "vs_static": 3.583,
                              "actions": 4,
                              "recover_s": 9.7},
        "gpt_flash_fp8": {"value": 4112.3, "unit": "tokens/sec/chip"},
        "gpt_long_context": {"value": 2580.7, "unit": "tokens/sec/chip"},
        "input_pipeline": {
            "value": 9685.0, "unit": "images/sec",
            # ISSUE 8 sub-rows: backend A/B, per-path stall, LM stream
            "loader_ips_per_backend": {"thread": 4211.5, "process": 9685.0},
            "stall_ms_per_step": {"thread": 241.31, "process": 98.22,
                                  "packed": 0.02},
            "packed_lm_tokens_per_sec": 18273451.9},
        "real_data_rn50": {"value": 6113.9, "unit": "images/sec/chip",
                           "vs_synthetic": 0.693,
                           "stall_ms_per_step": 12.07},
    }
    for r in rows.values():
        r["platform"] = "cpu"
        r["measured"] = "provenance prose " * 12   # ~200 bytes each
    return rows


def _tail_parse(stdout_text, tail_bytes=2000):
    """The driver's behavior: last JSON line of the last N bytes."""
    tail = stdout_text[-tail_bytes:]
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            return None   # last line decapitated -> the r1-r4 failure mode
    return None


def test_compact_record_under_1500_bytes():
    record = bench.build_record(_worst_case_results(), "cpu")
    compact = bench.compact_record(record)
    encoded = json.dumps(compact, separators=(",", ":"))
    assert len(encoded) <= 1500, len(encoded)
    for key in ("metric", "value", "unit", "vs_baseline", "platform"):
        assert key in compact
    assert compact["metric"] == "resnet50_o2_train_throughput"
    # Per-row essentials survive the distillation.
    assert compact["rows"]["gpt_flash"]["mfu"] == 0.4155
    assert compact["rows"]["fused_adam_step"]["vs_native"] == 0.706
    assert compact["rows"]["real_data_rn50"]["vs_synthetic"] == 0.693
    assert compact["rows"]["zero_adam_step"]["vs_per_leaf"] == 0.655
    assert compact["rows"]["tp_gpt"]["vs_monolithic"] == 1.088
    assert compact["rows"]["ckpt_save_restore"]["vs_sharded"] == 1.113
    assert compact["rows"]["ckpt_reshard"]["vs_same_mesh"] == 1.74
    assert compact["rows"]["telemetry_overhead"]["vs_bare"] == 1.012
    # ISSUE 9 serving sub-rows survive the distillation; at the worst
    # case the per-concurrency curves degrade to their top point (the
    # headline the gates read) — the full record keeps the full curves
    sv = compact["rows"]["serving"]
    assert sv["vs_unfused"] == 1.31
    assert sv["tokens_per_sec_at"]["8"] == 1843.7
    assert sv["tpot_p99_ms_at"]["8"] == 9.8
    assert record["extras"]["serving"]["tokens_per_sec_at"]["1"] == 241.2
    # ISSUE 12 occupancy sub-rows survive the distillation
    # (``preemptions_at`` stays in the full record only)
    oc = compact["rows"]["serving_occupancy"]
    assert oc["vs_reserve"] == 1.402
    assert oc["tokens_per_sec_at"]["4x"] == 1160.5
    assert oc["ttft_hit_vs_cold"] == 0.506
    # ISSUE 11 fleet sub-rows survive the distillation (``replicas`` /
    # ``roll_wall_s`` stay in the full record's config/prose only)
    fl = compact["rows"]["serving_fleet"]
    assert fl["p99_tpot_ms_steady"] == 3.4
    assert fl["roll_vs_steady"] == 1.206
    # the worst case sheds the roll p99 (== steady * roll_vs_steady);
    # the full record keeps it
    assert "p99_tpot_ms_roll" not in fl
    assert record["extras"]["serving_fleet"]["p99_tpot_ms_roll"] == 4.1
    # ISSUE 14 socket-transport sub-row: the wire ratio is tracked
    # (``tokens_per_sec_socket`` stays in the full record only)
    assert fl["wire_vs_inproc"] == 0.866
    # ISSUE 13 speculative sub-rows survive the distillation (the
    # per-concurrency baseline/ratio curves and ``acceptance_rate`` —
    # reconstructible from the accept length — stay in the full record)
    sp = compact["rows"]["serving_spec"]
    assert sp["vs_baseline"] == 2.256
    assert sp["mean_accept_len"] == 4.0
    assert sp["tokens_per_sec_at"]["8"] == 2154.2
    assert record["extras"]["serving_spec"]["acceptance_rate"] == 0.933
    # ISSUE 18 autopilot sub-rows: the worst case sheds everything but
    # the gated A/B ratio — the absolute burst/static TTFTs, drain-back
    # wall, and action count all stay in the full record
    apn = compact["rows"]["serving_autopilot"]
    assert apn["vs_static"] == 3.583
    assert "p99_ttft_ms_burst" not in apn
    assert "recover_s" not in apn
    assert "p99_ttft_ms_static" not in apn
    extras_ap = record["extras"]["serving_autopilot"]
    assert extras_ap["p99_ttft_ms_burst"] == 112.6
    assert extras_ap["recover_s"] == 9.7
    assert extras_ap["actions"] == 4
    # ISSUE 8 input-pipeline sub-rows survive the distillation
    ip = compact["rows"]["input_pipeline"]
    assert ip["loader_ips_per_backend"]["process"] == 9685.0
    assert ip["stall_ms_per_step"]["packed"] == 0.02
    assert ip["packed_lm_tokens_per_sec"] == 18273451.9
    assert compact["rows"]["real_data_rn50"]["stall_ms_per_step"] == 12.07


def test_compact_record_degrades_instead_of_overflowing():
    results = _worst_case_results()
    # Pathological: 40 extra rows with long names.
    for i in range(40):
        results[f"synthetic_extra_row_with_a_long_name_{i:02d}"] = {
            "value": float(i), "unit": "widgets/sec", "platform": "cpu"}
    record = bench.build_record(results, "cpu")
    compact = bench.compact_record(record)
    assert len(json.dumps(compact, separators=(",", ":"))) <= 1500
    assert compact["metric"] == "resnet50_o2_train_throughput"


def test_emission_survives_driver_tail(monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "_REPO", str(tmp_path))  # sandbox the stamps
    buf = io.StringIO()
    monkeypatch.setattr(sys, "stdout", buf)
    bench.emit_record(_worst_case_results(), "cpu")
    parsed = _tail_parse(buf.getvalue())
    assert parsed is not None, "last JSON line of the 2000-byte tail " \
                               "must parse (BENCH parsed:null regression)"
    assert parsed["metric"] == "resnet50_o2_train_throughput"
    assert parsed["value"] == 8824.6
    # Full provenance landed on disk even though the stdout tail is short.
    latest = json.load(open(tmp_path / "bench_results" /
                            "latest_record.json"))
    assert "measured" in latest["headline"]


def test_unrun_rows_still_emit_parseable_record(monkeypatch, tmp_path):
    """Day-zero emission (empty results) must already satisfy the tail."""
    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    buf = io.StringIO()
    monkeypatch.setattr(sys, "stdout", buf)
    bench.emit_record({}, "cpu")
    parsed = _tail_parse(buf.getvalue())
    assert parsed is not None
    assert parsed["value"] == 0.0
