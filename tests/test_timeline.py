"""The flight-recorder / goodput / debug-server layer (ISSUE 10).

Pins the tentpole contracts:

- event log semantics: monotonic clock, bounded ring, typed helpers,
  JSONL spill readable under the strict torn-tail rules;
- crash safety: a SIGKILL'd emitter loses at most the torn tail (the
  fault-injection acceptance);
- goodput: buckets exhaustive + disjoint, online (incremental) ==
  offline (recompute over the spilled file), serving per-request
  attribution;
- free telemetry: arming the recorder changes NOTHING in the compiled
  step — identical optimized HLO (zero extra collectives or host
  transfers, the PR 5 property extended to the timeline layer);
- instrumented subsystems: CheckpointManager and DevicePrefetcher emit
  the documented events, with disjoint attribution;
- the debug server: /metrics Prometheus text, /statusz timeline tail +
  goodput + engine state.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import jax

from apex_tpu.observability import (
    DebugServer,
    FlightRecorder,
    MetricRegistry,
    read_jsonl,
)
from apex_tpu.observability import timeline
from apex_tpu.observability.goodput import (
    TRAIN_BUCKETS,
    classify_event,
    goodput_report,
    serving_goodput_report,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm():
    """No test leaks an armed process-global recorder into the next."""
    yield
    timeline.disarm()


# ---------------------------------------------------------------------------
# recorder core
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_events_monotonic_and_typed(self):
        rec = FlightRecorder()
        with rec.step(0):
            pass
        rec.data_stall(0.01)
        rec.sentinel_skip(3, skipped_steps=1)
        evs = rec.events()
        kinds = [e["kind"] for e in evs]
        assert kinds == ["run_begin", "step", "data_stall",
                         "sentinel_skip"]
        ts = [e["t"] for e in evs]
        assert ts == sorted(ts) and all(t >= 0 for t in ts)
        assert evs[1]["step"] == 0 and "dur_s" in evs[1]
        assert evs[3]["skipped_steps"] == 1

    def test_ring_bounded_but_accounting_exact(self):
        rec = FlightRecorder(ring=8)
        for i in range(50):
            rec.emit("step", dur_s=0.001, step=i)
        assert len(rec.events()) == 8
        assert rec.events_emitted == 51  # + run_begin
        # goodput survived the wrap: all 50 steps still attributed
        assert rec.report()["buckets"]["compute"] == pytest.approx(
            0.05, abs=1e-9)

    def test_tail(self):
        rec = FlightRecorder()
        for i in range(10):
            rec.emit("step", step=i)
        tail = rec.tail(3)
        assert [e["step"] for e in tail] == [7, 8, 9]

    def test_scope_emits_on_exception(self):
        rec = FlightRecorder()
        with pytest.raises(RuntimeError):
            with rec.scope("compile", what="x"):
                raise RuntimeError("boom")
        assert rec.events()[-1]["kind"] == "compile"

    def test_ring_validation(self):
        with pytest.raises(ValueError):
            FlightRecorder(ring=0)

    def test_spill_round_trip_strict(self, tmp_path):
        path = str(tmp_path / "tl.jsonl")
        rec = FlightRecorder(path)
        with rec.step(0):
            pass
        rec.flush()
        back = read_jsonl(path, strict=True)
        assert [e["kind"] for e in back] == ["run_begin", "step",
                                            "run_end"]
        assert back == rec.events()

    def test_flush_writes_goodput_json(self, tmp_path):
        rec = FlightRecorder()
        rec.emit("step", dur_s=0.01, step=0)
        gp = str(tmp_path / "sub" / "goodput.json")
        report = rec.flush(gp)
        with open(gp) as f:
            assert json.load(f) == report

    def test_module_level_arming(self, tmp_path):
        assert timeline.active() is None
        assert timeline.emit("step", step=0) is None  # unarmed no-op
        with timeline.scope("step", step=0):
            pass
        rec = timeline.arm(str(tmp_path / "tl.jsonl"))
        assert timeline.active() is rec
        timeline.emit("compile", dur_s=0.1, what="x")
        with timeline.scope("step", step=1):
            pass
        assert [e["kind"] for e in rec.events()] == [
            "run_begin", "compile", "step"]
        assert timeline.disarm() is rec
        assert timeline.active() is None

    def test_arm_from_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv(timeline.TIMELINE_ENV_VAR, raising=False)
        assert timeline.arm_from_env() is None
        monkeypatch.setenv(timeline.TIMELINE_ENV_VAR, str(tmp_path))
        rec = timeline.arm_from_env()
        assert rec is not None and timeline.active() is rec
        rec.emit("step", step=0)
        assert os.path.exists(tmp_path / "timeline.jsonl")


# ---------------------------------------------------------------------------
# crash safety (the fault-injection acceptance)
# ---------------------------------------------------------------------------


_EMITTER = r"""
import sys
from apex_tpu.observability.timeline import FlightRecorder
rec = FlightRecorder(sys.argv[1])
print("armed", flush=True)
i = 0
while True:
    rec.emit("step", dur_s=0.0001, step=i)
    i += 1
"""


class TestCrashSafety:
    def test_sigkill_loses_at_most_the_torn_tail(self, tmp_path):
        """A SIGKILL'd emitter leaves a timeline whose intact prefix
        parses under strict semantics, with a contiguous step sequence
        — the reuse of the read_jsonl torn-tail contract."""
        path = str(tmp_path / "tl.jsonl")
        proc = subprocess.Popen(
            [sys.executable, "-c", _EMITTER, path],
            stdout=subprocess.PIPE, cwd=REPO)
        assert proc.stdout.readline().strip() == b"armed"
        # let it write enough to make the kill land mid-stream
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if os.path.exists(path) and os.path.getsize(path) > 4096:
                break
            time.sleep(0.01)
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        events = read_jsonl(path, strict=True)  # strict: no interior tears
        steps = [e["step"] for e in events if e["kind"] == "step"]
        assert len(steps) > 10
        assert steps == list(range(len(steps))), "lost interior events"

        # and even a genuinely torn tail (truncate mid-final-line) still
        # yields the intact prefix under strict
        from apex_tpu.testing.faults import truncate_file

        truncate_file(path, keep_frac=0.9)
        again = read_jsonl(path, strict=True)
        assert [e["step"] for e in again if e["kind"] == "step"] == \
            list(range(len(again) - 1))


# ---------------------------------------------------------------------------
# goodput
# ---------------------------------------------------------------------------


class TestGoodput:
    def test_classification(self):
        assert classify_event({"kind": "step"}) == "compute"
        assert classify_event({"kind": "step", "skipped": True}) == \
            "skipped_step"
        assert classify_event({"kind": "compile"}) == "compile"
        assert classify_event({"kind": "checkpoint_save"}) == "checkpoint"
        assert classify_event(
            {"kind": "checkpoint_save_async_submit"}) == "checkpoint"
        assert classify_event({"kind": "checkpoint_verify"}) == "checkpoint"
        assert classify_event({"kind": "checkpoint_restore"}) == "restore"
        assert classify_event({"kind": "data_stall"}) == "data_stall"
        assert classify_event({"kind": "drain"}) == "drain"
        # markers and serving lifecycle carry no training attribution
        for kind in ("run_begin", "run_end", "preemption", "sentinel_skip",
                     "request_submit", "decode_tick", "prefill"):
            assert classify_event({"kind": kind}) is None

    def test_buckets_exhaustive_and_disjoint(self):
        events = [
            {"t": 0.0, "kind": "run_begin"},
            {"t": 1.0, "kind": "compile", "dur_s": 1.0},
            {"t": 1.2, "kind": "data_stall", "dur_s": 0.2},
            {"t": 2.2, "kind": "step", "dur_s": 1.0, "step": 0},
            {"t": 2.7, "kind": "checkpoint_save", "dur_s": 0.5},
            {"t": 3.2, "kind": "step", "dur_s": 0.5, "step": 1,
             "skipped": True},
            {"t": 3.4, "kind": "drain", "dur_s": 0.2},
            {"t": 4.0, "kind": "run_end", "wall_s": 4.0},
        ]
        rep = goodput_report(events)
        assert rep["wall_s"] == 4.0
        assert set(rep["buckets"]) == set(TRAIN_BUCKETS)
        assert rep["buckets"]["compute"] == 1.0
        assert rep["buckets"]["skipped_step"] == 0.5
        assert rep["buckets"]["other"] == pytest.approx(0.6)
        assert sum(rep["buckets"].values()) == pytest.approx(4.0)
        assert rep["goodput_fraction"] == pytest.approx(0.25)
        assert rep["overcommit_s"] == 0.0

    def test_overcommit_surfaces_not_hides(self):
        """Attributed time beyond wall-clock (nested instrumentation
        bug) is reported, never silently clamped into the fractions."""
        rep = goodput_report([
            {"t": 1.0, "kind": "step", "dur_s": 5.0, "step": 0}],
            wall_s=1.0)
        assert rep["overcommit_s"] == pytest.approx(4.0)
        assert rep["buckets"]["other"] == 0.0

    def test_crash_wall_clock_from_last_event(self):
        """No run_end (the crash case): wall is the newest event's t —
        the unknowable post-crash tail is not attributed."""
        rep = goodput_report([
            {"t": 0.0, "kind": "run_begin"},
            {"t": 2.5, "kind": "step", "dur_s": 1.0, "step": 0}])
        assert rep["wall_s"] == 2.5

    def test_multi_run_spill_reports_newest_segment(self, tmp_path):
        """A spill path reused across restarts (crash -> resume)
        appends runs with restarting clocks; the offline report covers
        the NEWEST run and split_runs exposes the history."""
        from apex_tpu.observability.goodput import split_runs

        path = str(tmp_path / "tl.jsonl")
        first = FlightRecorder(path)
        first.emit("step", dur_s=1.0, step=0)
        first.flush()
        second = FlightRecorder(path)  # the resumed process re-arms
        second.emit("step", dur_s=0.25, step=1)
        second.flush()
        events = read_jsonl(path, strict=True)
        runs = split_runs(events)
        assert len(runs) == 2
        assert [e["kind"] for e in runs[0]][0] == "run_begin"
        rep = goodput_report(events)
        assert rep["buckets"]["compute"] == pytest.approx(0.25)
        assert goodput_report(runs[0])["buckets"]["compute"] == \
            pytest.approx(1.0)

    def test_online_equals_offline(self, tmp_path):
        path = str(tmp_path / "tl.jsonl")
        rec = FlightRecorder(path)
        for i in range(5):
            with rec.step(i):
                time.sleep(0.002)
        rec.data_stall(0.004)
        with rec.scope("checkpoint_save", step=4):
            time.sleep(0.002)
        online = rec.report()
        offline = goodput_report(read_jsonl(path, strict=True),
                                 wall_s=online["wall_s"])
        for name in TRAIN_BUCKETS:
            # the spill rounds dur_s to 6 dp per event; the online path
            # accumulates unrounded floats — agreement is to ~n*5e-7
            assert online["buckets"][name] == pytest.approx(
                offline["buckets"][name], abs=1e-5), name

    def test_serving_attribution(self):
        events = [
            {"t": 0.0, "kind": "request_submit", "rid": 1,
             "prompt_tokens": 4, "max_new_tokens": 8},
            {"t": 0.5, "kind": "request_admit", "rid": 1, "slot": 0},
            {"t": 1.0, "kind": "decode_tick", "rid": 1, "tokens": 8},
            {"t": 1.5, "kind": "request_finish", "rid": 1, "tokens": 10},
            {"t": 0.2, "kind": "request_submit", "rid": 2,
             "prompt_tokens": 2, "max_new_tokens": 4},
            {"t": 0.9, "kind": "request_cancel", "rid": 2},
            {"t": 1.0, "kind": "request_submit", "rid": 3,
             "prompt_tokens": 2, "max_new_tokens": 4},
            # rid 4: refused at submit (drain window / overload shed,
            # ISSUE 11) — a typed terminal state holding ~zero seconds
            {"t": 1.2, "kind": "request_submit", "rid": 4,
             "prompt_tokens": 2, "max_new_tokens": 4},
            {"t": 1.2, "kind": "request_reject", "rid": 4},
        ]
        rep = serving_goodput_report(events)
        assert rep["requests"][1] == {
            "state": "finished", "tokens": 10, "queue_wait_s": 0.5,
            "active_s": 1.0}
        assert rep["requests"][2]["state"] == "cancelled"
        assert rep["requests"][2]["drained_s"] == pytest.approx(0.7)
        assert rep["requests"][3]["state"] == "open"
        assert rep["requests"][4]["state"] == "rejected"
        assert rep["requests"][4]["drained_s"] == pytest.approx(0.0)
        assert rep["totals"] == {
            "finished": 1, "cancelled": 1, "rejected": 1, "open": 1,
            "queue_wait_s": 0.5, "active_s": 1.0,
            "drained_s": pytest.approx(0.7)}
        assert rep["goodput_fraction"] == pytest.approx(1.0 / 2.2,
                                                        abs=1e-6)

    def test_serving_attribution_survives_ring_wrap(self):
        """A terminal request whose submit event was evicted by the
        bounded ring still counts toward finished/cancelled (totals
        must never contradict per-request states); it just contributes
        no seconds to the fraction."""
        events = [
            # rid 1: submit evicted — only the finish survived
            {"t": 5.0, "kind": "request_finish", "rid": 1, "tokens": 9},
            # rid 2: fully observed
            {"t": 5.2, "kind": "request_submit", "rid": 2,
             "prompt_tokens": 2, "max_new_tokens": 4},
            {"t": 5.3, "kind": "request_admit", "rid": 2, "slot": 0},
            {"t": 6.3, "kind": "request_finish", "rid": 2, "tokens": 4},
            # rid 3: submit evicted, cancel survived
            {"t": 6.4, "kind": "request_cancel", "rid": 3},
        ]
        rep = serving_goodput_report(events)
        assert rep["requests"][1] == {"state": "finished", "tokens": 9}
        assert rep["totals"]["finished"] == 2
        assert rep["totals"]["cancelled"] == 1
        assert rep["totals"]["open"] == 0
        assert rep["totals"]["active_s"] == pytest.approx(1.0)
        assert rep["goodput_fraction"] == pytest.approx(1.0 / 1.1,
                                                        abs=1e-6)


# ---------------------------------------------------------------------------
# free telemetry: arming changes nothing in the compiled program
# ---------------------------------------------------------------------------


class TestArmedRecorderIsFree:
    def test_identical_optimized_hlo_with_recorder_armed(self, devices8):
        """The recorder is host-side by construction; this pins it —
        tracing and compiling the SAME sharded step under an armed
        recorder (scopes wrapping the trace AND the dispatch) yields
        byte-identical optimized HLO: zero extra collectives, zero
        host transfers, zero anything."""
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        mesh = Mesh(np.array(devices8[:4]), ("dp",))

        def make_step():
            def local(x):
                return jax.lax.pmean(x * 2.0, "dp")

            return jax.jit(shard_map(
                local, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))

        x = np.arange(8.0, dtype=np.float32)
        bare = make_step().lower(x).compile().as_text()

        timeline.arm(FlightRecorder())
        with timeline.scope("compile", what="step"):
            armed_fn = make_step()
            armed = armed_fn.lower(x).compile().as_text()
        with timeline.scope("step", step=0):
            armed_fn(x)
        assert armed == bare
        assert timeline.active().events_emitted >= 3


# ---------------------------------------------------------------------------
# instrumented subsystems
# ---------------------------------------------------------------------------


class TestSubsystemEvents:
    def test_checkpoint_manager_events_disjoint(self, tmp_path):
        """save / save_async_submit / verify / restore land as their
        own intervals; the restore_latest wrapper is NOT an event (it
        contains verify+restore — counting it would double-attribute)."""
        from apex_tpu.resilience import CheckpointManager

        rec = timeline.arm(FlightRecorder())
        tree = {"w": np.arange(6.0, dtype=np.float32)}
        mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
        mgr.save(tree, 0)
        mgr.save_async(tree, 1)
        mgr.wait()
        restored, at = mgr.restore_latest(tree)
        assert at == 1 and _bits(restored["w"]) == _bits(tree["w"])
        kinds = [e["kind"] for e in rec.events()]
        assert "checkpoint_save" in kinds
        assert "checkpoint_save_async_submit" in kinds
        assert "checkpoint_verify" in kinds
        assert "checkpoint_restore" in kinds
        assert "restore_latest" not in " ".join(kinds)
        ev = [e for e in rec.events()
              if e["kind"] == "checkpoint_restore"][0]
        assert ev["step"] == 1 and ev["resharded"] is False
        # every interval is attributable
        rep = rec.report()
        assert rep["buckets"]["checkpoint"] > 0
        assert rep["buckets"]["restore"] > 0
        assert rep["overcommit_s"] == 0.0

    def test_prefetcher_emits_data_stall(self):
        from apex_tpu.data.prefetch import prefetch_to_device

        rec = timeline.arm(FlightRecorder())
        batches = [np.ones((2, 2)) * i for i in range(4)]
        pf = prefetch_to_device(iter(batches), depth=1,
                                place=lambda b: b)
        got = list(pf)
        pf.close()
        assert len(got) == 4
        stalls = [e for e in rec.events() if e["kind"] == "data_stall"]
        # one per delivered batch + one for the exhaustion pull (the
        # wait for the end marker is real blocking time too)
        assert len(stalls) == 5
        assert all(e["dur_s"] >= 0 for e in stalls)


def _bits(a):
    return np.asarray(a).tobytes()


# ---------------------------------------------------------------------------
# debug server
# ---------------------------------------------------------------------------


class _FakeEngine:
    def introspect(self):
        return {"active_slots": 2, "free_blocks": 7, "queue_depth": 1,
                "draining": False, "mfu": None,
                "mfu_reason": "no peak-FLOPs table entry"}


class TestDebugServer:
    def _get(self, srv, path):
        return urllib.request.urlopen(srv.url(path), timeout=10)

    def test_metrics_prometheus_format(self):
        reg = MetricRegistry(rank=0, world=1)
        reg.counter("serving/tokens_generated").inc(42)
        reg.gauge("data/stall_ms").set(1.5)
        reg.gauge("unset/gauge")  # None: must be omitted, not NaN
        h = reg.histogram("serving/tpot_ms", keep_samples=16)
        for v in (1.0, 2.0, 3.0):
            h.observe(v)
        with DebugServer(registry=reg) as srv:
            body = self._get(srv, "/metrics").read().decode()
        assert '# TYPE apex_serving_tokens_generated counter' in body
        assert 'apex_serving_tokens_generated{rank="0"} 42.0' in body
        assert 'apex_data_stall_ms{rank="0"} 1.5' in body
        assert "apex_unset_gauge" not in body
        assert 'apex_serving_tpot_ms_count{rank="0"} 3.0' in body
        assert 'quantile="0.5"' in body and 'quantile="0.99"' in body

    def test_statusz_carries_timeline_goodput_and_engine(self):
        rec = FlightRecorder()
        with rec.step(0):
            time.sleep(0.001)
        with DebugServer(registry=MetricRegistry(rank=0, world=1),
                         recorder=rec, engine=_FakeEngine()) as srv:
            body = json.loads(self._get(srv, "/statusz").read())
        assert body["timeline"][-1]["kind"] == "step"
        assert body["goodput"]["buckets"]["compute"] > 0
        assert body["serving"]["free_blocks"] == 7
        assert "no peak-FLOPs" in body["serving"]["mfu_reason"]

    def test_statusz_uses_armed_recorder_by_default(self):
        rec = timeline.arm(FlightRecorder())
        rec.emit("compile", dur_s=0.5, what="x")
        with DebugServer(registry=MetricRegistry(rank=0, world=1)) as srv:
            body = json.loads(self._get(srv, "/statusz").read())
        assert body["goodput"]["buckets"]["compile"] == pytest.approx(0.5)

    def test_unknown_path_404(self):
        with DebugServer(registry=MetricRegistry(rank=0, world=1)) as srv:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(srv, "/nope")
            assert ei.value.code == 404

    def test_healthz_ok_draining_down(self):
        """ISSUE 11 satellite: the one health contract router and
        external probes share — ok is HTTP 200, draining/down are 503
        with the status named, so both a stock prober (code only) and
        the fleet router (JSON) read the same endpoint."""

        class Engine:
            draining = False
            broken = False

            def introspect(self):
                if self.broken:
                    raise RuntimeError("decode wedged")
                return {"draining": self.draining}

        eng = Engine()
        with DebugServer(registry=MetricRegistry(rank=0, world=1),
                         engine=eng) as srv:
            body = json.loads(self._get(srv, "/healthz").read())
            assert body["status"] == "ok" and body["engine"] is True
            eng.draining = True
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(srv, "/healthz")
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["status"] == "draining"
            eng.broken = True
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(srv, "/healthz")
            assert ei.value.code == 503
            payload = json.loads(ei.value.read())
            assert payload["status"] == "down"
            assert "decode wedged" in payload["error"]

    def test_healthz_without_engine_is_liveness_only(self):
        with DebugServer(registry=MetricRegistry(rank=0, world=1)) as srv:
            body = json.loads(self._get(srv, "/healthz").read())
        assert body == {"status": "ok", "engine": False}

    def test_ephemeral_port_and_close(self):
        srv = DebugServer(registry=MetricRegistry(rank=0, world=1)).start()
        assert srv.port > 0
        srv.close()
        with pytest.raises(Exception):
            urllib.request.urlopen(srv.url("/metrics"), timeout=1)


# The obs_smoke.sh end-to-end run is wired fast-tier in
# tests/test_aux_subsystems.py alongside the data/serving/telemetry
# smokes (ISSUE 10 CI satellite).
