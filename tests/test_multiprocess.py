"""2-process CPU integration test for the multi-host bring-up.

The reference's distributed tests spawn world_size processes over NCCL on
one host (``MultiProcessTestCase``); the analog here is
``apex_tpu.parallel.launch.run_multiprocess`` spawning 2 ranks that join a
``jax.distributed`` cluster, build a (dcn=2, dp=2) mesh across the process
boundary, and run a psum + a dp-sharded train-like reduction.
"""

import os
import subprocess
import sys
import textwrap

import pytest

RANK_SCRIPT = textwrap.dedent("""
    import os

    import numpy as np

    from apex_tpu.parallel.launch import initialize_distributed

    initialize_distributed()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.parallel import collectives as cc

    nproc = jax.process_count()
    assert nproc == 2, f"expected 2 processes, got {nproc}"
    assert len(jax.devices()) == 8, jax.devices()

    mesh = parallel.initialize_model_parallel(tensor_model_parallel_size=2)
    assert mesh.shape["dcn"] == 2, mesh.shape      # across processes
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2

    # the dcn axis really spans the process boundary
    dcn_procs = [[d.process_index for d in row.flatten()]
                 for row in mesh.devices]
    assert all(p == 0 for p in dcn_procs[0]), dcn_procs
    assert all(p == 1 for p in dcn_procs[1]), dcn_procs

    # cross-process psum over every axis
    def f(x):
        return cc.all_reduce(x, ("dcn", "dp", "tp"), "sum")

    g = cc.shard_over(f, mesh=mesh,
                      in_specs=P(("dcn", "dp", "tp")), out_specs=P())

    x = jax.device_put(
        jnp.ones((8, 4)),
        NamedSharding(mesh, P(("dcn", "dp", "tp"))))
    out = g(x)
    np.testing.assert_allclose(np.asarray(out), 8.0)
    print(f"rank {jax.process_index()} OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_cpu_cluster(tmp_path):
    script = tmp_path / "rank_script.py"
    script.write_text(RANK_SCRIPT)
    # Run the launcher itself in a clean subprocess so this pytest process's
    # already-initialized single-process backend is not involved.
    driver = tmp_path / "driver.py"
    driver.write_text(textwrap.dedent(f"""
        from apex_tpu.parallel.launch import run_multiprocess
        results = run_multiprocess({str(script)!r}, num_processes=2,
                                   devices_per_process=4, timeout=300)
        for r in results:
            out = r.stdout.decode()
            assert "OK" in out, out
        print("LAUNCH OK")
    """))
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(driver)], env=env,
                          capture_output=True, timeout=600)
    assert proc.returncode == 0, proc.stderr.decode()[-3000:]
    assert "LAUNCH OK" in proc.stdout.decode()
