"""2-process CPU integration test for the multi-host bring-up.

The reference's distributed tests spawn world_size processes over NCCL on
one host (``MultiProcessTestCase``); the analog here is
``apex_tpu.parallel.launch.run_multiprocess`` spawning 2 ranks that join a
``jax.distributed`` cluster, build a (dcn=2, dp=2) mesh across the process
boundary, and run a psum + a dp-sharded train-like reduction.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RANK_SCRIPT = textwrap.dedent("""
    import os

    import numpy as np

    from apex_tpu.parallel.launch import initialize_distributed

    initialize_distributed()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.parallel import collectives as cc

    nproc = jax.process_count()
    assert nproc == 2, f"expected 2 processes, got {nproc}"
    assert len(jax.devices()) == 8, jax.devices()

    mesh = parallel.initialize_model_parallel(tensor_model_parallel_size=2)
    assert mesh.shape["dcn"] == 2, mesh.shape      # across processes
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2

    # the dcn axis really spans the process boundary
    dcn_procs = [[d.process_index for d in row.flatten()]
                 for row in mesh.devices]
    assert all(p == 0 for p in dcn_procs[0]), dcn_procs
    assert all(p == 1 for p in dcn_procs[1]), dcn_procs

    # cross-process psum over every axis
    def f(x):
        return cc.all_reduce(x, ("dcn", "dp", "tp"), "sum")

    g = cc.shard_over(f, mesh=mesh,
                      in_specs=P(("dcn", "dp", "tp")), out_specs=P())

    x = jax.device_put(
        jnp.ones((8, 4)),
        NamedSharding(mesh, P(("dcn", "dp", "tp"))))
    out = g(x)
    np.testing.assert_allclose(np.asarray(out), 8.0)
    print(f"rank {jax.process_index()} OK", flush=True)
""")


RANK_SCRIPT_4P = textwrap.dedent("""
    import os
    import sys

    import numpy as np

    from apex_tpu.parallel.launch import initialize_distributed

    initialize_distributed()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.checkpoint import (
        gather_zero_state,
        restore_checkpoint,
        save_checkpoint,
        scatter_zero_state,
    )
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.optimizers._common import OptState
    from apex_tpu.parallel import collectives as cc

    ckpt_path = sys.argv[1]

    assert jax.process_count() == 4, jax.process_count()
    assert len(jax.devices()) == 8, jax.devices()

    # (dcn=4) x (tp=2): dcn on the process boundary, tp inside each process
    mesh = parallel.initialize_model_parallel(tensor_model_parallel_size=2)
    assert mesh.shape["dcn"] == 4 and mesh.shape["tp"] == 2, dict(mesh.shape)
    for i, row in enumerate(mesh.devices):
        procs = {d.process_index for d in row.flatten()}
        assert procs == {i}, (i, procs)  # each dcn slice = one process

    # ZeRO over the cross-process dcn axis: state chunks live on
    # different *processes* — the real multi-host sharding regime
    opt = DistributedFusedAdam(lr=1e-2, axis="dcn")
    params = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (13, 7)),
        "b": jax.random.normal(jax.random.PRNGKey(1), (8,)),
    }
    grads = {
        "w": jax.random.normal(jax.random.PRNGKey(2), (13, 7)),
        "b": jax.random.normal(jax.random.PRNGKey(3), (8,)),
    }

    chunk_spec = jax.tree_util.tree_map(lambda _: P("dcn"), params)
    state_specs = OptState(
        step=P(),
        slots={"exp_avg": chunk_spec, "exp_avg_sq": chunk_spec},
        master=chunk_spec,
    )

    def steps(n):
        def local(p, g, state):
            for _ in range(n):
                p, state = opt.step(g, state, p)
            return p, state
        return local

    def init_and_run(p, g):
        def local(p, g):
            state = opt.init(p)
            return steps(2)(p, g, state)
        return cc.shard_over(local, in_specs=(P(), P()),
                             out_specs=(P(), state_specs))(p, g)

    p2, s2 = init_and_run(params, grads)
    # the ZeRO state is genuinely sharded across processes
    assert not s2.slots["exp_avg"]["w"].is_fully_addressable

    # cross-rank checkpoint: collective gather -> rank-0 write -> barrier
    portable = gather_zero_state(opt, s2, p2)
    save_checkpoint(ckpt_path, {"params": p2, "opt": portable}, step=2)

    restored, step = restore_checkpoint(
        ckpt_path, {"params": p2, "opt": portable})
    assert step == 2

    def put(tree, specs):
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(
                jnp.asarray(x), NamedSharding(mesh, s)), tree, specs)

    p_r = put(restored["params"], jax.tree_util.tree_map(
        lambda _: P(), params))
    s_r = scatter_zero_state(opt, restored["opt"], s2, p_r)
    s_r = OptState(step=jnp.asarray(restored["opt"]["step"]),
                   slots=put(s_r.slots,
                             {"exp_avg": chunk_spec,
                              "exp_avg_sq": chunk_spec}),
                   master=put(s_r.master, chunk_spec))

    # resume 2 steps from the checkpoint == 4 uninterrupted steps
    p_resumed, _ = cc.shard_over(
        steps(2), in_specs=(P(), P(), state_specs),
        out_specs=(P(), state_specs))(p_r, grads, s_r)

    def init_and_run4(p, g):
        def local(p, g):
            state = opt.init(p)
            return steps(4)(p, g, state)
        return cc.shard_over(local, in_specs=(P(), P()),
                             out_specs=(P(), state_specs))(p, g)

    p4, _ = init_and_run4(params, grads)
    from jax.experimental import multihost_utils

    for k in ("w", "b"):
        a = np.asarray(multihost_utils.process_allgather(
            p_resumed[k], tiled=True))
        b = np.asarray(multihost_utils.process_allgather(p4[k], tiled=True))
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)

    print(f"rank {jax.process_index()} OK", flush=True)
""")


@pytest.mark.slow
def test_four_process_cluster_zero_checkpoint(tmp_path):
    """(dcn=4) x (tp=2) cluster with ZeRO state sharded across processes:
    checkpoint save (collective gather + rank-0 write), restore, scatter,
    and resume matching the uninterrupted run (VERDICT r2 item 7)."""
    script = tmp_path / "rank4.py"
    script.write_text(RANK_SCRIPT_4P)
    ckpt = tmp_path / "zero_ckpt.npz"
    driver = tmp_path / "driver4.py"
    driver.write_text(textwrap.dedent(f"""
        from apex_tpu.parallel.launch import run_multiprocess
        results = run_multiprocess({str(script)!r}, num_processes=4,
                                   devices_per_process=2, timeout=540,
                                   script_args=[{str(ckpt)!r}])
        for r in results:
            assert b"OK" in r.stdout, r.stdout
        print("LAUNCH OK")
    """))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(driver)], env=env,
                          capture_output=True, timeout=900)
    assert proc.returncode == 0, (proc.stderr.decode()[-3000:],
                                  proc.stdout.decode()[-1000:])
    assert "LAUNCH OK" in proc.stdout.decode()


@pytest.mark.slow
def test_two_process_cpu_cluster(tmp_path):
    script = tmp_path / "rank_script.py"
    script.write_text(RANK_SCRIPT)
    # Run the launcher itself in a clean subprocess so this pytest process's
    # already-initialized single-process backend is not involved.
    driver = tmp_path / "driver.py"
    driver.write_text(textwrap.dedent(f"""
        from apex_tpu.parallel.launch import run_multiprocess
        results = run_multiprocess({str(script)!r}, num_processes=2,
                                   devices_per_process=4, timeout=300)
        for r in results:
            out = r.stdout.decode()
            assert "OK" in out, out
        print("LAUNCH OK")
    """))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(driver)], env=env,
                          capture_output=True, timeout=600)
    assert proc.returncode == 0, proc.stderr.decode()[-3000:]
    assert "LAUNCH OK" in proc.stdout.decode()


RANK_SCRIPT_SHARDED_CKPT = textwrap.dedent("""
    import sys

    import numpy as np

    from apex_tpu.parallel.launch import initialize_distributed

    initialize_distributed()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.checkpoint import (
        restore_checkpoint_sharded,
        save_checkpoint_sharded,
    )

    ckpt_dir = sys.argv[1]

    assert jax.process_count() == 4, jax.process_count()
    mesh = parallel.initialize_model_parallel(tensor_model_parallel_size=2)

    # dcn-sharded leaf: rows live on different PROCESSES (each process
    # holds 2 of 8 rows); tp-sharded leaf inside each process; replicated
    # scalar.  Deterministic values so every rank can verify globally.
    host_w = np.arange(8 * 6, dtype=np.float32).reshape(8, 6)
    host_b = np.arange(6, dtype=np.float32) * 0.5
    w = jax.device_put(host_w, NamedSharding(mesh, P(("dcn", "dp"), None)))
    b = jax.device_put(host_b, NamedSharding(mesh, P("tp")))
    s = jax.device_put(jnp.float32(2.25), NamedSharding(mesh, P()))
    assert not w.is_fully_addressable  # the real multi-host regime
    tree = {"w": w, "b": b, "s": s}

    save_checkpoint_sharded(ckpt_dir, tree, step=5)

    # every process wrote only its own shards
    import os
    mine = os.path.join(ckpt_dir, f"shard_{jax.process_index()}.npz")
    assert os.path.exists(mine), os.listdir(ckpt_dir)

    like = {"w": jax.device_put(jnp.zeros((8, 6), jnp.float32),
                                NamedSharding(mesh, P(("dcn", "dp"), None))),
            "b": jax.device_put(jnp.zeros((6,), jnp.float32),
                                NamedSharding(mesh, P("tp"))),
            "s": jax.device_put(jnp.float32(0), NamedSharding(mesh, P()))}
    restored, step = restore_checkpoint_sharded(ckpt_dir, like)
    assert step == 5

    # verify each local shard against the deterministic global values
    for sh in restored["w"].addressable_shards:
        np.testing.assert_array_equal(np.asarray(sh.data),
                                      host_w[sh.index])
    for sh in restored["b"].addressable_shards:
        np.testing.assert_array_equal(np.asarray(sh.data),
                                      host_b[sh.index])
    assert float(restored["s"]) == 2.25

    # the global mean is a collective over restored cross-process shards:
    # proves the restored arrays are real, computable global arrays
    got = float(jnp.mean(restored["w"]))
    assert abs(got - host_w.mean()) < 1e-6, got

    print("OK", jax.process_index())
""")


@pytest.mark.slow
def test_four_process_sharded_checkpoint(tmp_path):
    """Pod-style per-process sharded checkpoint across a 4-process
    cluster: each rank writes/reads only its own shards; restored arrays
    are real global arrays (collective-verified)."""
    script = tmp_path / "rank_sharded.py"
    script.write_text(RANK_SCRIPT_SHARDED_CKPT)
    ckpt = tmp_path / "sharded_ckpt"
    driver = tmp_path / "driver_sharded.py"
    driver.write_text(textwrap.dedent(f"""
        from apex_tpu.parallel.launch import run_multiprocess
        results = run_multiprocess({str(script)!r}, num_processes=4,
                                   devices_per_process=2, timeout=540,
                                   script_args=[{str(ckpt)!r}])
        for r in results:
            assert b"OK" in r.stdout, r.stdout
        print("LAUNCH OK")
    """))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(driver)], env=env,
                          capture_output=True, timeout=900)
    assert proc.returncode == 0, (proc.stderr.decode()[-3000:],
                                  proc.stdout.decode()[-1000:])
    assert "LAUNCH OK" in proc.stdout.decode()
