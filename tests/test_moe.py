"""Switch-MoE + expert parallelism (parity-plus; the reference stubs MoE
out at ``standalone_transformer_lm.py:675``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.parallel import collectives as cc
from apex_tpu.transformer.moe import SwitchMLP, switch_route

pytestmark = pytest.mark.slow

S, B, H, FFN, E = 8, 4, 16, 32, 4


def test_switch_route_properties():
    logits = jax.random.normal(jax.random.PRNGKey(0), (32, E))
    dispatch, gate, aux = switch_route(logits, capacity=16)
    d = np.asarray(dispatch)
    assert d.shape == (32, E, 16)
    # each token goes to at most one (expert, slot)
    assert (d.reshape(32, -1).sum(axis=1) <= 1).all()
    # no slot is double-booked
    assert (d.sum(axis=0) <= 1).all()
    # capacity 16 > 32/4: nothing dropped here
    assert d.sum() == 32
    assert float(aux) >= 1.0 - 1e-6  # E * sum f_e P_e >= 1 (Cauchy-Schwarz)
    np.testing.assert_allclose(
        np.asarray(gate),
        np.asarray(jax.nn.softmax(logits, -1).max(axis=-1)), rtol=1e-6)


def test_switch_route_capacity_drops():
    # all tokens want expert 0; capacity 2 keeps exactly the first 2
    logits = jnp.zeros((8, E)).at[:, 0].set(10.0)
    dispatch, _, _ = switch_route(logits, capacity=2)
    d = np.asarray(dispatch)
    assert d[:, 0].sum() == 2
    assert d[:2, 0].sum() == 2  # first-come-first-served (cumsum order)
    assert d[:, 1:].sum() == 0


def test_switch_mlp_matches_manual_expert_apply():
    """With ample capacity, the dispatch/combine einsums equal routing
    each token through its argmax expert directly."""
    m = SwitchMLP(hidden_size=H, ffn_size=FFN, num_experts=E,
                  capacity_factor=E * 1.0)  # capacity = T: nothing dropped
    x = jax.random.normal(jax.random.PRNGKey(1), (S, B, H))
    params = m.init(jax.random.PRNGKey(2), x)["params"]
    (y, aux), _ = m.apply({"params": params}, x, mutable=["losses"])

    p = jax.device_get(params)
    flat = np.asarray(x).reshape(-1, H)
    logits = flat @ p["router"]
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), -1))
    idx = probs.argmax(-1)
    ref = np.zeros_like(flat)
    for t in range(flat.shape[0]):
        e = idx[t]
        hmid = np.asarray(jax.nn.gelu(
            jnp.asarray(flat[t] @ p["w1"][e] + p["b1"][e])))
        ref[t] = (hmid @ p["w2"][e] + p["b2"][e]) * probs[t, e]
    np.testing.assert_allclose(np.asarray(y).reshape(-1, H), ref,
                               rtol=2e-5, atol=2e-5)


def _moe_specs():
    return {"router": P(), "w1": P("cp"), "b1": P("cp"),
            "w2": P("cp"), "b2": P("cp")}


def test_expert_parallel_matches_dense():
    """EP over an 8-way axis == the dense path run on the gathered global
    expert stacks: each rank holds ONLY its E/ep experts (true memory
    sharding), tokens move via the all_to_all pair."""
    EP = 8
    parallel.initialize_model_parallel(context_parallel_size=EP)
    try:
        m_dense = SwitchMLP(hidden_size=H, ffn_size=FFN, num_experts=8,
                            capacity_factor=8.0)
        m_ep = SwitchMLP(hidden_size=H, ffn_size=FFN, num_experts=8,
                         capacity_factor=8.0, expert_axis="cp")
        x = jax.random.normal(jax.random.PRNGKey(3), (S, B * EP, H))
        specs = _moe_specs()

        # rank-folded init inside the shard_map: local [E/ep, ...] stacks
        params = cc.shard_over(
            lambda xb: m_ep.init(jax.random.PRNGKey(4), xb)["params"],
            in_specs=P(None, "cp"), out_specs=specs)(x)
        # local shards really are 1 expert per rank
        assert params["w1"].shape == (8, H, FFN)  # global view: 8 experts
        # expert groups decorrelated by the rank-folded init
        assert not np.allclose(np.asarray(params["w1"][0]),
                               np.asarray(params["w1"][1]))

        def local(p, xb):
            (y, aux), _ = m_ep.apply({"params": p}, xb, mutable=["losses"])
            return y

        y_ep = cc.shard_over(
            local, in_specs=(specs, P(None, "cp")),
            out_specs=P(None, "cp"))(params, x)

        # dense reference on the gathered global stacks (global arrays ARE
        # the concatenation of the local shards)
        (y_ref, _), _ = m_dense.apply(
            {"params": jax.device_get(params)}, x, mutable=["losses"])
        np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                                   rtol=2e-5, atol=2e-5)

        # grads flow through the all_to_all pair and stay shard-local
        def loss(p, xb):
            y = cc.shard_over(
                local, in_specs=(specs, P(None, "cp")),
                out_specs=P(None, "cp"))(p, xb)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(params, x)
        g_ref = jax.grad(
            lambda p: jnp.sum(m_dense.apply({"params": p}, x,
                                            mutable=["losses"])[0][0] ** 2)
        )(jax.device_get(params))
        for a, b in zip(jax.tree_util.tree_leaves(g),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)
    finally:
        parallel.destroy_model_parallel()


def test_moe_gpt_trains():
    """TransformerConfig.num_experts swaps the dense MLP for SwitchMLP and
    the LM still trains."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    cfg = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=64, max_position_embeddings=16,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
        num_experts=4)
    model = GPTModel(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    # expert stacks exist in the tree
    leaf_paths = [p for p, _ in
                  jax.tree_util.tree_leaves_with_path(params)]
    assert any("router" in str(p) for p in leaf_paths)
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)

    from apex_tpu.transformer.moe import collect_moe_aux

    @jax.jit
    def step(p, s):
        def loss_fn(p):
            losses, mut = model.apply({"params": p}, tokens, labels=tokens,
                                      mutable=["losses"])
            aux = collect_moe_aux(mut)
            return jnp.mean(losses) + 1e-2 * aux, aux
        (l, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        p, s = opt.step(g, s, p)
        return p, s, l, aux

    losses = []
    for _ in range(15):
        params, state, l, aux = step(params, state)
        losses.append(float(l))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
    assert float(aux) >= 1.0 - 1e-6  # the aux loss is real and in the objective
