"""apex_tpu.observability.{timeseries,slo} — the longitudinal metrics
history and the SLO burn-rate plane, golden (ISSUE 20).

Everything here runs on an injected fake clock: the counter→rate
arithmetic (monotonic-reset handling: never a negative rate), the
multi-resolution downsampling invariant (a coarse bucket's mean/max IS
the mean/max of the fine buckets it spans), the compacted delta wire
(export on one clock, ingest rebased onto another), the multi-window
burn thresholds (a fast-window spike alone never pages; both windows
over → exactly one alert), the clear hysteresis (a relapse inside
``clear_after_s`` resets the recovery timer), and the budget /
exhaustion arithmetic — all pinned to hand-computed values.  The
OpenMetrics exposition is linted line by line, the JSONL size-rotation
contract is proven record-exact, and the fleet wiring (statusz blocks,
replica delta ingestion, series-overflow accounting) is exercised over
the fleet tests' in-memory FakeReplica.
"""

import glob
import json
import os
import urllib.request

import pytest

from apex_tpu.observability import timeline
from apex_tpu.observability.debug_server import (DebugServer,
                                                 render_openmetrics)
from apex_tpu.observability.metrics import MetricRegistry
from apex_tpu.observability.slo import SLOEvaluator, SLOPolicy
from apex_tpu.observability.timeline import FlightRecorder
from apex_tpu.observability.timeseries import (OVERFLOW_SERIES,
                                               MetricHistory,
                                               match_series)
from apex_tpu.observability.trace import collect_slo_events, \
    read_fleet_spills
from apex_tpu.observability.writers import JsonlWriter, read_jsonl

from test_fleet import FakeReplica, drive, make_router


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


# ================================================== MetricHistory


def test_counter_becomes_rate():
    clk = FakeClock()
    reg = MetricRegistry(rank=0, world=1)
    h = MetricHistory(reg, clock=clk)
    reg.counter("c").inc(100)
    h.sample()                       # first sample: no prev, no rate
    assert h.series_names() == []
    clk.advance(1.0)
    reg.counter("c").inc(5)
    h.sample()
    assert h.latest("c") == pytest.approx(5.0)
    clk.advance(2.0)
    reg.counter("c").inc(8)
    h.sample()
    assert h.latest("c") == pytest.approx(4.0)   # 8 over 2 s


def test_counter_reset_never_negative():
    """A replica restart drops its counters to near zero; the
    post-reset value is the delta, never a negative rate."""
    clk = FakeClock()
    reg = MetricRegistry(rank=0, world=1)
    h = MetricHistory(reg, clock=clk)
    reg.counter("c").inc(100)
    h.sample()
    clk.advance(1.0)
    reg.counter("c").inc(5)
    h.sample()
    clk.advance(1.0)
    reg.counter("c").value = 3.0     # the restart: 105 -> 3
    h.sample()
    assert h.latest("c") == pytest.approx(3.0)
    pts = h.bucket_points("c", 10.0, now=clk())
    assert pts and all(v >= 0.0 for _t, v in pts)


def test_gauges_and_histograms_sampled():
    clk = FakeClock()
    reg = MetricRegistry(rank=0, world=1)
    h = MetricHistory(reg, clock=clk)
    reg.gauge("g").set(2.5)
    reg.gauge("g_unset")             # None: skipped, never a series
    hist = reg.histogram("lat", keep_samples=64)
    for v in (1.0, 2.0, 5.0, 9.0):
        hist.observe(v)
    h.sample()
    assert h.latest("g") == pytest.approx(2.5)
    assert "g_unset" not in h.series_names()
    assert {"lat:p50", "lat:p99"} <= set(h.series_names())
    assert "lat:rate" not in h.series_names()    # needs a count delta
    assert h.latest("lat:p99") >= h.latest("lat:p50")
    clk.advance(2.0)
    for v in (3.0, 4.0):
        hist.observe(v)
    h.sample()
    assert h.latest("lat:rate") == pytest.approx(1.0)   # 2 obs / 2 s
    clk.advance(1.0)
    reg.gauge("g").set(7.0)
    h.sample()
    assert h.latest("g") == pytest.approx(7.0)
    assert h.introspect()["samples"] == 3


def test_downsampling_coarse_equals_fine():
    """The downsample invariant: after the fine ring has evicted, the
    coarse bucket still reports the mean/max/min/last of every raw
    sample that landed in its span."""
    clk = FakeClock()
    vals = [3.0, 5.0, 7.0, 11.0, 13.0, 17.0, 19.0, 23.0, 29.0, 31.0]
    h = MetricHistory(resolutions=((1.0, 4), (10.0, 64)), clock=clk)
    for i, v in enumerate(vals):
        h.record("s", v, now=i + 0.5)
    # fine ring (maxlen 4) kept only t in [6, 10); the 10 s bucket kept
    # everything — asking for the full window falls through to it
    pts = h.bucket_points("s", 10.0, now=10.0)
    assert pts == [(5.0, pytest.approx(sum(vals) / len(vals)))]
    assert h.bucket_points("s", 10.0, now=10.0, field="max") == \
        [(5.0, max(vals))]
    assert h.bucket_points("s", 10.0, now=10.0, field="min") == \
        [(5.0, min(vals))]
    w = h.window("s", 10.0, now=10.0)
    assert w["count"] == len(vals)
    assert w["mean"] == pytest.approx(sum(vals) / len(vals))
    assert (w["min"], w["max"], w["last"]) == (3.0, 31.0, 31.0)
    # a narrow window is still served from the surviving fine buckets
    fine = h.bucket_points("s", 4.0, now=10.0)
    assert fine == [(6.5, 19.0), (7.5, 23.0), (8.5, 29.0), (9.5, 31.0)]


def test_series_overflow_bounded():
    clk = FakeClock()
    fired = []
    h = MetricHistory(max_series=2, clock=clk,
                      on_overflow=lambda: fired.append(1))
    h.record("a", 1.0, now=0.0)
    h.record("b", 2.0, now=0.0)
    h.record("c", 3.0, now=0.0)      # past the cap: lands in (other)
    h.record("d", 4.0, now=0.0)
    h.record("c", 5.0, now=0.0)
    assert h.series_names() == [OVERFLOW_SERIES, "a", "b"]
    assert len(fired) == 3
    assert h.window(OVERFLOW_SERIES, 10.0, now=0.0)["count"] == 3
    intro = h.introspect()
    assert intro["overflowed"] and intro["series"] == 3


def test_export_delta_then_ingest_rebases():
    clk_a = FakeClock()
    ha = MetricHistory(clock=clk_a)
    ha.record("x", 1.0, now=0.2)
    ha.record("x", 3.0, now=1.2)
    d1 = ha.export_delta(now=2.0)
    assert d1["v"] == 1 and d1["res"] == 1.0 and d1["now"] == 2.0
    assert len(d1["series"]["x"]) == 2
    # nothing new finished -> no payload on the wire
    assert ha.export_delta(now=2.5) is None
    ha.record("x", 7.0, now=2.2)
    assert ha.export_delta(now=2.9) is None      # bucket 2 still open
    d2 = ha.export_delta(now=3.1)
    assert len(d2["series"]["x"]) == 1
    # ingest on a different clock: buckets rebase by the export offset
    clk_b = FakeClock(100.0)
    hb = MetricHistory(clock=clk_b)
    assert hb.ingest_delta(d1, prefix="replica/a/", now=100.0) == 2
    pts = hb.bucket_points("replica/a/x", 2.0, now=100.0)
    assert pts == [(98.5, 1.0), (99.5, 3.0)]
    assert hb.latest("replica/a/x") == 3.0
    assert hb.ingest_delta({}) == 0
    assert hb.ingest_delta(None) == 0


def test_slope_golden():
    clk = FakeClock()
    h = MetricHistory(clock=clk)
    h.record("s", 2.0, now=1.0)
    assert h.slope("s", 9.0, now=1.0) == 0.0     # one bucket: no slope
    for t in range(2, 11):
        h.record("s", 2.0 * t, now=float(t))
    # window 9 at t=10 cuts at t=1, exactly where the fine ring starts
    assert h.slope("s", 9.0, now=10.0) == pytest.approx(2.0)
    assert h.slope("missing", 9.0, now=10.0) == 0.0


def test_match_and_match_series():
    assert match_series("fleet/tenant/*/ttft_ms:p99",
                        "fleet/tenant/acme/ttft_ms:p99")
    assert not match_series("fleet/tenant/*/ttft_ms:p99",
                            "fleet/tenant/acme/extra/ttft_ms:p99")
    assert not match_series("*", "a/b")          # one segment exactly
    clk = FakeClock()
    h = MetricHistory(clock=clk)
    for name in ("svc/a/m", "svc/b/m", "svc/a/other", "top"):
        h.record(name, 1.0, now=0.0)
    assert h.match("svc/*/m") == ["svc/a/m", "svc/b/m"]
    assert h.match("svc/a/m") == ["svc/a/m"]
    assert h.match("svc/zz/m") == []
    assert h.match("*") == ["top"]


def test_history_validation():
    with pytest.raises(ValueError):
        MetricHistory(resolutions=())
    with pytest.raises(ValueError):
        MetricHistory(resolutions=((1.0, 4), (1.0, 4)))   # not ascending
    with pytest.raises(ValueError):
        MetricHistory(resolutions=((0.0, 4),))
    with pytest.raises(ValueError):
        MetricHistory(max_series=0)
    with pytest.raises(ValueError):
        MetricHistory().sample()     # no registry to snapshot


# ================================================ SLO burn rates


def _tick(h, ev, clk, value, metric="m"):
    clk.advance(1.0)
    h.record(metric, value)
    ev.evaluate()


def test_fast_window_alone_never_pages():
    """The multi-window rule: a short spike trips the fast window
    immediately but the alert waits for the slow window — then fires
    exactly once however long the burn continues."""
    clk = FakeClock()
    h = MetricHistory(clock=clk)
    pol = SLOPolicy(name="p", metric="m", objective=100.0, target=0.9,
                    fast_window_s=10.0, slow_window_s=50.0,
                    compliance_window_s=500.0,
                    fast_burn=1.5, slow_burn=1.0, clear_after_s=1e9)
    ev = SLOEvaluator(h, [pol], clock=clk)
    for _ in range(60):
        _tick(h, ev, clk, 10.0)
    assert ev.alerts == 0
    _tick(h, ev, clk, 200.0)                       # t=61: 1 bad bucket
    _tick(h, ev, clk, 200.0)                       # t=62: 2 bad buckets
    row = ev.last_rows[0]
    # fast window holds 11 one-second buckets here, slow holds 51
    assert row["burn_fast"] == pytest.approx(round(2 / 11 / 0.1, 4))
    assert row["burn_slow"] == pytest.approx(round(2 / 51 / 0.1, 4))
    assert row["burn_fast"] >= pol.fast_burn       # fast is over...
    assert ev.alerts == 0                          # ...but no page yet
    for _ in range(3):                             # t=63..65
        _tick(h, ev, clk, 200.0)
    assert ev.alerts == 0                          # slow still under 1.0
    _tick(h, ev, clk, 200.0)                       # t=66: 6/51 over budget
    assert ev.alerts == 1
    assert ev.last_rows[0]["alerting"] is True
    for _ in range(4):
        _tick(h, ev, clk, 200.0)
    assert ev.alerts == 1                          # fires exactly once


def test_hysteresis_relapse_resets_clear_timer():
    clk = FakeClock()
    h = MetricHistory(clock=clk)
    pol = SLOPolicy(name="p", metric="m", objective=100.0, target=0.5,
                    fast_window_s=2.0, slow_window_s=2.0,
                    compliance_window_s=100.0,
                    fast_burn=1.0, slow_burn=1.0, clear_after_s=5.0)
    ev = SLOEvaluator(h, [pol], clock=clk)
    rec = timeline.arm(FlightRecorder(None))
    try:
        for v in (10.0, 10.0):                     # t=1..2 healthy
            _tick(h, ev, clk, v)
        _tick(h, ev, clk, 200.0)                   # t=3: 1/3 bad
        assert ev.alerts == 0
        _tick(h, ev, clk, 200.0)                   # t=4: 2/3 bad -> page
        assert ev.alerts == 1
        assert ev.introspect()["alerting"] == ["p:m"]
        _tick(h, ev, clk, 200.0)                   # t=5
        for v in (10.0, 10.0):                     # t=6..7: recovery opens
            _tick(h, ev, clk, v)
        assert ev.last_rows[0]["alerting"] is True  # hysteresis holds
        _tick(h, ev, clk, 200.0)                   # t=8
        _tick(h, ev, clk, 200.0)                   # t=9: relapse refires
        assert ev.alerts == 1 and ev.clears == 0   # no storm either way
        for _ in range(6):                         # t=10..15: healthy
            _tick(h, ev, clk, 10.0)
        assert ev.clears == 0                      # recovery at t=11: 4 s
        _tick(h, ev, clk, 10.0)                    # t=16: 5 s sustained
        assert ev.clears == 1
        assert ev.last_rows[0]["alerting"] is False
        assert ev.introspect()["alerting"] == []
    finally:
        timeline.disarm()
    events = rec.events()
    alerts = [e for e in events if e["kind"] == "slo_burn_alert"]
    clears = [e for e in events if e["kind"] == "slo_burn_clear"]
    assert len(alerts) == 1 and len(clears) == 1
    a = alerts[0]
    assert a["policy"] == "p" and a["metric"] == "m"
    assert a["objective"] == 100.0
    assert a["burn_fast"] == pytest.approx(round(2 / 3 / 0.5, 4))
    assert a["burn_slow"] == a["burn_fast"]        # same window here
    assert "budget_remaining" in a and "budget_remaining" in clears[0]
    states = [e for e in events if e["kind"] == "slo_state"]
    assert len(states) >= 10                       # one per cadence tick
    assert states[-1]["rows"][0]["alerting"] is False
    # the offline reducer agrees with the live evaluator
    slo = collect_slo_events(events)
    assert len(slo["alerts"]) == 1 and len(slo["clears"]) == 1
    assert slo["open"] == []


def test_budget_and_exhaustion_golden():
    clk = FakeClock()
    h = MetricHistory(clock=clk)
    pol = SLOPolicy(name="p", metric="m", objective=100.0, target=0.9,
                    fast_window_s=2.0, slow_window_s=10.0,
                    compliance_window_s=100.0)
    idle = SLOPolicy(name="idle", metric="fleet/nothing",
                     objective=1.0, target=0.9,
                     fast_window_s=2.0, slow_window_s=10.0,
                     compliance_window_s=100.0)
    ev = SLOEvaluator(h, [pol, idle], clock=clk)
    # run LONGER than the compliance window so every window is served
    # from the fine ring (the multi-resolution fallback would otherwise
    # re-aggregate the tail into 10 s buckets)
    for t in range(1, 118):
        clk.advance(1.0)
        h.record("m", 10.0)
    for t in range(3):                             # t=118..120 bad
        clk.advance(1.0)
        h.record("m", 200.0)
    rows = ev.evaluate()
    row = rows[0]
    # fast: 3/3 bad over budget 0.1; slow: 3 of 11 buckets;
    # compliance: 3 of the 101 buckets in (t-101, t]
    assert row["burn_fast"] == pytest.approx(10.0)
    assert row["burn_slow"] == pytest.approx(round(3 / 11 / 0.1, 4))
    remaining = 1.0 - 3 / 101 / 0.1
    assert row["budget_remaining"] == pytest.approx(remaining, abs=1e-6)
    assert row["exhaustion_s"] == pytest.approx(
        remaining * 100.0 / (3 / 11 / 0.1), abs=1e-3)
    # an explicit series with no data reports idle, burns nothing
    quiet = rows[1]
    assert quiet["metric"] == "fleet/nothing"
    assert quiet["burn_slow"] == 0.0
    assert quiet["budget_remaining"] == 1.0
    assert quiet["exhaustion_s"] is None
    assert ev.worst()["policy"] == "p"


def test_wildcard_policy_expands_per_series():
    clk = FakeClock()
    h = MetricHistory(clock=clk)
    pol = SLOPolicy(name="tenants", metric="svc/*/m", objective=100.0,
                    fast_window_s=2.0, slow_window_s=4.0,
                    compliance_window_s=60.0)
    ghost = SLOPolicy(name="ghost", metric="zz/*/m", objective=1.0,
                      fast_window_s=2.0, slow_window_s=4.0,
                      compliance_window_s=60.0)
    ev = SLOEvaluator(h, [pol, ghost], clock=clk)
    clk.advance(1.0)
    h.record("svc/a/m", 1.0)
    h.record("svc/b/m", 1.0)
    rows = ev.evaluate()
    # one row per matched series; a matchless wildcard yields no
    # phantom row for the pattern itself
    assert [r["metric"] for r in rows] == ["svc/a/m", "svc/b/m"]
    assert ev.introspect()["series_tracked"] == 2


def test_slo_policy_validation():
    ok = dict(name="p", metric="m", objective=1.0)
    SLOPolicy(**ok)
    with pytest.raises(ValueError):
        SLOPolicy(**dict(ok, target=1.0))
    with pytest.raises(ValueError):
        SLOPolicy(**dict(ok, fast_window_s=500.0))   # fast > slow
    with pytest.raises(ValueError):
        SLOPolicy(**dict(ok, fast_burn=0.0))
    with pytest.raises(ValueError):
        SLOPolicy(**dict(ok, clear_after_s=-1.0))
    with pytest.raises(ValueError):
        SLOPolicy(**dict(ok, field="p42"))
    with pytest.raises(ValueError):
        SLOPolicy(**dict(ok, name=""))


# ============================================ OpenMetrics exposition


def test_openmetrics_exposition_lint():
    reg = MetricRegistry(rank=0, world=1)
    reg.counter("serving/requests").inc(3)
    reg.gauge("serving/queue_depth").set(2.5)
    reg.gauge("serving/unset")       # None gauge: not exposed
    hist = reg.histogram("serving/latency_ms", keep_samples=32)
    for v in (1.0, 2.0, 5.0, 9.0):
        hist.observe(v)
    text = render_openmetrics(reg)
    lines = text.splitlines()
    assert text.endswith("# EOF\n")
    assert lines.index("# EOF") == len(lines) - 1   # nothing after EOF
    # every family: # HELP immediately before # TYPE, samples known
    families = {}
    for i, ln in enumerate(lines):
        if ln.startswith("# TYPE "):
            _h, _t, name, mtype = ln.split()
            assert lines[i - 1].startswith(f"# HELP {name} ")
            families[name] = mtype
        elif ln.startswith("#"):
            continue
        else:
            base = ln.split("{")[0]
            owners = [n for n in families
                      if base == n or (base.startswith(n) and
                                       base[len(n):] in ("_total",
                                                         "_count",
                                                         "_sum"))]
            assert owners, f"sample without a TYPE line: {ln}"
    assert families["apex_serving_requests"] == "counter"
    assert families["apex_serving_queue_depth"] == "gauge"
    assert families["apex_serving_latency_ms"] == "summary"
    # counter SAMPLES carry the mandatory _total suffix
    assert 'apex_serving_requests_total{rank="0"} 3.0' in lines
    assert "apex_serving_requests{" not in text
    assert 'apex_serving_queue_depth{rank="0"} 2.5' in lines
    assert "apex_serving_unset" not in text
    assert 'apex_serving_latency_ms_count{rank="0"} 4.0' in lines
    assert 'apex_serving_latency_ms_sum{rank="0"} 17.0' in lines
    assert any('quantile="0.5"' in ln for ln in lines)
    assert any('quantile="0.99"' in ln for ln in lines)


def test_metrics_prom_endpoint():
    reg = MetricRegistry(rank=0, world=1)
    reg.counter("serving/requests").inc(2)
    srv = DebugServer(registry=reg).start()
    try:
        with urllib.request.urlopen(srv.url("/metrics.prom"),
                                    timeout=10) as resp:
            assert resp.status == 200
            ctype = resp.headers.get("Content-Type", "")
            body = resp.read().decode()
        assert "application/openmetrics-text" in ctype
        assert "version=1.0.0" in ctype
        assert body.endswith("# EOF\n")
        assert 'apex_serving_requests_total{rank="0"} 2.0' in body
    finally:
        srv.close()


# ============================================== JSONL size rotation


def _stream_records(path):
    """A rotated stream's records in append order: segments by
    rotation seq, then the live file."""
    stem = path[:-len(".jsonl")]
    segs = sorted(glob.glob(stem + ".rot-*.jsonl"))
    out = []
    for p in segs + [path]:
        out.extend(read_jsonl(p, strict=True))
    return out


def test_rotation_preserves_every_record(tmp_path):
    path = str(tmp_path / "m.jsonl")
    w = JsonlWriter(path, fsync=False, rotate_bytes=150)
    for i in range(10):
        w.write({"i": i, "pad": "x" * 48})
    w.close()
    segs = sorted(glob.glob(str(tmp_path / "m.rot-*.jsonl")))
    assert w.rotations >= 2 and len(segs) == w.rotations
    # rotation happens BETWEEN records: every segment within bound,
    # every line intact, nothing lost, order exact
    for seg in segs:
        assert os.path.getsize(seg) <= 150
    assert [r["i"] for r in _stream_records(path)] == list(range(10))
    # a restarted writer (keep_open leg) seq-scans past history
    w2 = JsonlWriter(path, fsync=False, rotate_bytes=150, keep_open=True)
    for i in range(10, 20):
        w2.write({"i": i, "pad": "x" * 48})
    w2.close()
    assert [r["i"] for r in _stream_records(path)] == list(range(20))
    assert len(glob.glob(str(tmp_path / "m.rot-*.jsonl"))) > len(segs)


def test_rotation_off_by_default(tmp_path):
    path = str(tmp_path / "m.jsonl")
    w = JsonlWriter(path, fsync=False)
    for i in range(50):
        w.write({"i": i, "pad": "x" * 48})
    assert w.rotations == 0
    assert glob.glob(str(tmp_path / "m.rot-*.jsonl")) == []
    assert len(read_jsonl(path)) == 50
    with pytest.raises(ValueError):
        JsonlWriter(path, rotate_bytes=0)


def test_read_fleet_spills_concatenates_rotated_segments(tmp_path):
    w = JsonlWriter(str(tmp_path / "timeline.router.router.1.jsonl"),
                    fsync=False, rotate_bytes=200)
    w.write({"kind": "run_begin", "t": 0.0, "role": "router",
             "name": "router", "pid": 1, "mono_t0": 0.0, "wall_ts": 1.0})
    for i in range(20):
        w.write({"kind": "fleet_submit", "t": 0.1 * i, "rid": i,
                 "trace_id": f"t{i:04d}"})
    w.close()
    assert w.rotations >= 2
    rw = JsonlWriter(str(tmp_path / "timeline.replica.a.2.jsonl"),
                     fsync=False)
    rw.write({"kind": "run_begin", "t": 0.0, "role": "replica",
              "name": "a", "pid": 2, "mono_t0": 0.0, "wall_ts": 1.0})
    rw.write({"kind": "step", "t": 0.5, "step": 1})
    router_run, replica_runs = read_fleet_spills(str(tmp_path))
    assert router_run[0]["kind"] == "run_begin"
    assert [e["rid"] for e in router_run
            if e["kind"] == "fleet_submit"] == list(range(20))
    assert list(replica_runs) == ["a"] and len(replica_runs["a"]) == 1


# ================================================= fleet wiring


def _armed_router(rep, clk, **kw):
    policies = [SLOPolicy(name="ttft", metric="fleet/ttft_ms:p99",
                          objective=1e9, fast_window_s=5.0,
                          slow_window_s=10.0, compliance_window_s=60.0)]
    return make_router([rep], clock=clk, history_every_s=1.0,
                       slo_policies=policies, **kw)


def test_fleet_statusz_grows_history_and_burn_blocks():
    clk = FakeClock()
    rep = FakeReplica("a")
    router = _armed_router(rep, clk)
    try:
        reqs = [router.submit([3, 5, 7], 3), router.submit([2, 4], 3)]
        for _ in range(12):
            clk.advance(1.0)
            router.pump()
            rep.tick()
        assert all(r.done for r in reqs)
        status = router.fleet_statusz()
        assert status["history"]["samples"] >= 2
        assert status["history"]["max_series"] == 512
        burn = status["slo"]["burn"]
        assert [r["policy"] for r in burn["rows"]] == ["ttft"]
        assert burn["worst"]["metric"] == "fleet/ttft_ms:p99"
        assert burn["alerts"] == 0 and burn["alerting"] == []
        # real longitudinal data accrued from the router's own registry
        assert router.history.latest("fleet/ttft_ms:p99") is not None
    finally:
        router.close()


def test_fleet_statusz_disarmed_is_unchanged():
    rep = FakeReplica("a")
    router = make_router([rep])
    try:
        status = router.fleet_statusz()
        assert "history" not in status
        assert "burn" not in status["slo"]
        assert router.history is None and router.slo is None
    finally:
        router.close()
    with pytest.raises(ValueError):
        make_router([FakeReplica("b")], slo_policies=[
            SLOPolicy(name="p", metric="m", objective=1.0)])


def test_replica_history_delta_merges_under_prefix():
    clk = FakeClock()
    rep = FakeReplica("a")
    router = _armed_router(rep, clk)
    try:
        # a replica-side history exports a compacted delta; the state
        # heartbeat carries it and the router rebases it under the
        # replica prefix
        rh = MetricHistory(clock=FakeClock(50.0))
        rh.record("serving/tokens_per_s", 42.0, now=50.2)
        delta = rh.export_delta(now=51.5)
        assert delta is not None
        rep._emit_state()
        rep._events[-1][1]["history"] = delta
        clk.advance(1.0)
        router.pump()
        assert "replica/a/serving/tokens_per_s" in \
            router.history.series_names()
        assert router.history.latest(
            "replica/a/serving/tokens_per_s") == 42.0
    finally:
        router.close()
    # a disarmed router drops the delta without a wobble
    rep2 = FakeReplica("b")
    router2 = make_router([rep2])
    try:
        rep2._emit_state()
        rep2._events[-1][1]["history"] = delta
        router2.pump()
        assert router2.history is None
    finally:
        router2.close()


def test_history_series_cap_feeds_overflow_counter():
    clk = FakeClock()
    rep = FakeReplica("a")
    router = make_router([rep], clock=clk, history_every_s=1.0,
                         history_max_series=1)
    try:
        router.registry.gauge("fleet/x1").set(1.0)
        router.registry.gauge("fleet/x2").set(2.0)
        for _ in range(3):
            clk.advance(1.0)
            router.pump()
        assert OVERFLOW_SERIES in router.history.series_names()
        snap = router.registry.snapshot()
        assert snap["fleet/series_overflow"] >= 1
        assert router.fleet_statusz()["history"]["overflowed"] is True
    finally:
        router.close()


def test_collect_slo_events_open_alert():
    events = [
        {"kind": "run_begin", "t": 0.0},
        {"kind": "slo_burn_alert", "t": 1.0, "policy": "p",
         "metric": "m", "burn_fast": 2.0, "burn_slow": 2.0,
         "budget_remaining": 0.5, "objective": 10.0},
        {"kind": "slo_state", "t": 1.0, "rows": []},
        {"kind": "slo_burn_clear", "t": 5.0, "policy": "p",
         "metric": "m", "burn_fast": 0.0, "burn_slow": 0.0,
         "budget_remaining": 0.5},
        {"kind": "slo_burn_alert", "t": 9.0, "policy": "p",
         "metric": "m", "burn_fast": 3.0, "burn_slow": 3.0,
         "budget_remaining": 0.2, "objective": 10.0},
    ]
    slo = collect_slo_events(events)
    assert len(slo["alerts"]) == 2 and len(slo["clears"]) == 1
    assert len(slo["states"]) == 1
    assert slo["open"] == [("p", "m")]      # newest transition: alert
