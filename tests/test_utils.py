"""Tree flatten/unflatten + RNG policy tests (apex_C / multi_tensor_l2norm /
random.py analogs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.parallel import collectives as cc
from apex_tpu.utils import (
    chunked_per_leaf_sumsq,
    flatten_to_buffer,
    flatten_to_chunked,
    unflatten_from_buffer,
    unflatten_from_chunked,
    per_leaf_l2_norms,
    tree_l2_norm,
    tree_size,
    model_parallel_rngs,
)


class TestFlatten:
    def test_roundtrip(self):
        tree = {
            "a": jnp.arange(6.0).reshape(2, 3),
            "b": jnp.ones((4,), jnp.bfloat16),
            "c": jnp.float32(7.0),
        }
        buf, meta = flatten_to_buffer(tree, dtype=jnp.float32)
        assert buf.ndim == 1 and buf.dtype == jnp.float32
        out = unflatten_from_buffer(buf, meta)
        assert out["b"].dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out["a"]), np.arange(6.0).reshape(2, 3))
        np.testing.assert_allclose(float(out["c"]), 7.0)

    def test_padding(self):
        buf, meta = flatten_to_buffer({"a": jnp.ones(5)}, pad_to=8)
        assert buf.shape == (8,)
        assert meta.total == 5

    def test_jit_roundtrip(self):
        tree = {"a": jnp.arange(4.0), "b": jnp.ones((2, 2))}

        _, meta = flatten_to_buffer(tree)

        @jax.jit
        def f(t):
            buf, _ = flatten_to_buffer(t)
            return unflatten_from_buffer(buf, meta)

        out = f(tree)
        np.testing.assert_allclose(np.asarray(out["a"]), np.arange(4.0))


class TestChunkedFlatten:
    """flatten_to_chunked / unflatten_from_chunked / chunked_per_leaf_sumsq
    — the (rows, chunk) multi_tensor workspace behind FusedLAMB(flat=True)."""

    def test_roundtrip_mixed_shapes(self):
        tree = {
            "w": jnp.arange(300, dtype=jnp.float32).reshape(30, 10),
            "b": jnp.arange(7, dtype=jnp.float32),
            "scalar": jnp.float32(3.5),
            "half": jnp.ones((130,), jnp.bfloat16),
        }
        buf, meta = flatten_to_chunked(tree, chunk=64)
        assert buf.shape[1] == 64
        # leaf boundaries are row-aligned: each leaf starts a fresh row
        assert meta.leaf_ids.shape == (buf.shape[0],)
        out = jax.tree_util.tree_map(lambda x: x, unflatten_from_chunked(buf, meta))
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(tree)):
            assert a.dtype == jnp.asarray(b).dtype
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))

    def test_zero_size_leaves(self):
        # zero-size leaves occupy no rows and must round-trip (the
        # r5 review's reproduced crash: all-empty trees)
        for tree in ({"e": jnp.zeros((0, 4))},
                     {"e": jnp.zeros((0, 4)), "w": jnp.ones((5,))}):
            buf, meta = flatten_to_chunked(tree, chunk=8)
            out = unflatten_from_chunked(buf, meta)
            for a, b in zip(jax.tree_util.tree_leaves(out),
                            jax.tree_util.tree_leaves(tree)):
                assert a.shape == b.shape and a.dtype == b.dtype

    def test_per_leaf_sumsq_exact(self):
        tree = {"a": jnp.full((100,), 2.0), "b": jnp.full((3, 3), -1.0),
                "z": jnp.zeros((0,))}
        buf, meta = flatten_to_chunked(tree, chunk=32)
        got = np.asarray(chunked_per_leaf_sumsq(buf, meta))
        np.testing.assert_allclose(sorted(got), sorted([0.0, 9.0, 400.0]))

    def test_jit_roundtrip(self):
        tree = {"a": jnp.ones((50,)), "b": jnp.ones((4, 4))}
        _, meta = flatten_to_chunked(tree)

        @jax.jit
        def f(t):
            buf, _ = flatten_to_chunked(t)
            return unflatten_from_chunked(buf * 2.0, meta)

        out = f(tree)
        np.testing.assert_array_equal(np.asarray(out["b"]), 2.0 * np.ones((4, 4)))


class TestNorms:
    def test_global_norm(self):
        tree = {"a": jnp.full((3,), 2.0), "b": jnp.full((4,), 2.0)}
        np.testing.assert_allclose(float(tree_l2_norm(tree)), np.sqrt(7 * 4.0))

    def test_per_leaf(self):
        norms = per_leaf_l2_norms({"a": jnp.full((4,), 3.0)})
        np.testing.assert_allclose(float(norms[0]), 6.0)

    def test_size(self):
        assert tree_size({"a": jnp.ones((2, 3)), "b": jnp.float32(1)}) == 7

    def test_size_empty_leaf(self):
        assert tree_size({"a": jnp.zeros((0,)), "b": jnp.ones(3)}) == 3

    def test_mixed_dtype_without_explicit_dtype_raises(self):
        with pytest.raises(ValueError):
            flatten_to_buffer({"a": jnp.ones(2), "b": jnp.ones(2, jnp.bfloat16)})


class TestModelParallelRng:
    def test_mp_keys_differ_across_ranks(self):
        parallel.initialize_model_parallel(tensor_model_parallel_size=8)

        def fn(_):
            key = jax.random.PRNGKey(0)
            rep, mp = model_parallel_rngs(key)
            return (
                jax.random.uniform(rep, (1, 2)),
                jax.random.uniform(mp, (1, 2)),
            )

        f = cc.shard_over(
            fn, in_specs=P("tp"), out_specs=(P("tp", None), P("tp", None))
        )
        rep, mp = f(jnp.zeros(8))
        rep, mp = np.asarray(rep), np.asarray(mp)
        # replicated stream identical on all ranks
        for r in range(1, 8):
            np.testing.assert_allclose(rep[r], rep[0])
        # model-parallel stream unique per rank
        assert len({tuple(row) for row in mp}) == 8


class TestTunedRecords:
    """apex_tpu.utils.tuning.load_tuned_record — the sweep auto-land
    adoption protocol (device-gated tuned defaults)."""

    class _Dev:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    class _Jax:
        @classmethod
        def devices(cls):
            return [TestTunedRecords._Dev()]

    def _write(self, monkeypatch, tmp_path, payload):
        import json

        from apex_tpu.utils import tuning

        monkeypatch.setattr(tuning, "_REPO", str(tmp_path))
        d = tmp_path / "bench_results"
        d.mkdir(exist_ok=True)
        if payload is not None:
            (d / "x_tuned.json").write_text(json.dumps(payload))

    def test_adopts_on_matching_device_kind(self, monkeypatch, tmp_path):
        from apex_tpu.utils.tuning import load_tuned_record

        self._write(monkeypatch, tmp_path,
                    {"base_batch": 16, "device_kind": "TPU v5 lite"})
        rec = load_tuned_record("x_tuned.json", self._Jax)
        assert rec and rec["base_batch"] == 16

    def test_rejects_kind_mismatch_and_cpu(self, monkeypatch, tmp_path):
        from apex_tpu.utils.tuning import load_tuned_record

        self._write(monkeypatch, tmp_path,
                    {"base_batch": 16, "device_kind": "TPU v4"})
        assert load_tuned_record("x_tuned.json", self._Jax) is None

        class CpuDev:
            platform = "cpu"
            device_kind = "TPU v5 lite"  # lying kind on a cpu backend

        class CpuJax:
            @classmethod
            def devices(cls):
                return [CpuDev()]

        self._write(monkeypatch, tmp_path,
                    {"base_batch": 16, "device_kind": "TPU v5 lite"})
        assert load_tuned_record("x_tuned.json", CpuJax) is None

    def test_missing_or_corrupt_degrades_to_none(self, monkeypatch,
                                                 tmp_path):
        from apex_tpu.utils import tuning

        self._write(monkeypatch, tmp_path, None)
        assert tuning.load_tuned_record("x_tuned.json", self._Jax) is None
        (tmp_path / "bench_results" / "x_tuned.json").write_text("{broken")
        assert tuning.load_tuned_record("x_tuned.json", self._Jax) is None
