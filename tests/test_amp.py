"""amp policy + loss scaler tests.

Scaler behavior matrix mirrors ``tests/L0/run_amp`` (dynamic scale growth /
backoff, hysteresis — ``tests/L0/run_amp/test_update_scale_hysteresis.py``)
re-expressed against the functional API.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp


class TestPolicy:
    def test_presets(self):
        o2 = amp.policy("O2")
        assert o2.param_dtype == jnp.bfloat16
        assert o2.master_weights
        assert o2.loss_scale == "dynamic"
        o0 = amp.policy("O0")
        assert o0.param_dtype == jnp.float32
        assert o0.loss_scale is None
        o3 = amp.policy("O3")
        assert o3.output_dtype == jnp.bfloat16
        assert not o3.master_weights

    def test_fp16_variant(self):
        o1 = amp.policy("O1", half_dtype=jnp.float16)
        assert o1.compute_dtype == jnp.float16
        assert o1.loss_scale == "dynamic"  # fp16 O1 needs scaling
        assert amp.policy("O1").loss_scale is None  # bf16 O1 does not

    def test_cast_preserves_nonfloat(self):
        p = amp.policy("O2")
        tree = {"w": jnp.ones((2, 2)), "ids": jnp.arange(3), "n": 5}
        out = p.cast_to_compute(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["ids"].dtype == jnp.int32
        assert out["n"] == 5

    def test_unknown_level_raises(self):
        with pytest.raises(ValueError):
            amp.policy("O4")

    def test_o2_keeps_norm_params_fp32(self):
        """keep_batchnorm_fp32 exemption (apex/fp16_utils/fp16util.py:22)."""
        p = amp.policy("O2")
        tree = {
            "Dense_0": {"kernel": jnp.ones((2, 2))},
            "BatchNorm_0": {"scale": jnp.ones(2), "bias": jnp.zeros(2)},
            "LayerNorm_1": {"scale": jnp.ones(2)},
        }
        out = p.cast_to_param(tree)
        assert out["Dense_0"]["kernel"].dtype == jnp.bfloat16
        assert out["BatchNorm_0"]["scale"].dtype == jnp.float32
        assert out["LayerNorm_1"]["scale"].dtype == jnp.float32

    def test_o3_casts_norms_too(self):
        p = amp.policy("O3")
        out = p.cast_to_param({"BatchNorm_0": {"scale": jnp.ones(2)}})
        assert out["BatchNorm_0"]["scale"].dtype == jnp.bfloat16


class TestDynamicLossScale:
    def test_growth_after_interval(self):
        algo = amp.DynamicLossScale(init_scale=4.0, growth_interval=3)
        s = algo.init()
        for _ in range(2):
            s = algo.update(s, True)
            assert float(s.scale) == 4.0
        s = algo.update(s, True)
        assert float(s.scale) == 8.0
        assert int(s.growth_tracker) == 0

    def test_backoff_on_overflow(self):
        algo = amp.DynamicLossScale(init_scale=16.0)
        s = algo.init()
        s = algo.update(s, False)
        assert float(s.scale) == 8.0
        assert bool(s.found_inf)

    def test_overflow_resets_growth(self):
        algo = amp.DynamicLossScale(init_scale=4.0, growth_interval=2)
        s = algo.init()
        s = algo.update(s, True)
        s = algo.update(s, False)  # overflow: halve, reset tracker
        s = algo.update(s, True)
        assert float(s.scale) == 2.0
        assert int(s.growth_tracker) == 1

    def test_hysteresis(self):
        """First overflow tolerated with hysteresis=2; second backs off.
        (csrc/update_scale_hysteresis.cu semantics)."""
        algo = amp.DynamicLossScale(init_scale=16.0, hysteresis=2)
        s = algo.init()
        s = algo.update(s, False)
        assert float(s.scale) == 16.0  # tolerated
        s = algo.update(s, False)
        assert float(s.scale) == 8.0  # exhausted → backoff
        # clean step restores hysteresis budget
        s = algo.update(s, True)
        s = algo.update(s, False)
        assert float(s.scale) == 8.0

    def test_min_scale_clamp(self):
        algo = amp.DynamicLossScale(init_scale=2.0, min_scale=1.0)
        s = algo.init()
        for _ in range(5):
            s = algo.update(s, False)
        assert float(s.scale) == 1.0

    def test_scale_unscale_roundtrip(self):
        algo = amp.DynamicLossScale(init_scale=2.0**10)
        s = algo.init()
        grads = {"a": jnp.full((4,), 2.0**10, jnp.float16)}
        un = algo.unscale(grads, s)
        assert un["a"].dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(un["a"]), np.ones(4))

    def test_update_inside_jit(self):
        algo = amp.DynamicLossScale(init_scale=4.0, growth_interval=1)

        @jax.jit
        def step(s, ok):
            return algo.update(s, ok)

        s = algo.init()
        s = step(s, jnp.asarray(True))
        assert float(s.scale) == 8.0
        s = step(s, jnp.asarray(False))
        assert float(s.scale) == 4.0

    def test_skip_step_adjust(self):
        algo = amp.DynamicLossScale()
        s = algo.init()
        s = algo.update(s, False)  # overflow
        old = {"w": jnp.zeros(3)}
        new = {"w": jnp.ones(3)}
        kept = algo.adjust(new, old, s)
        np.testing.assert_allclose(np.asarray(kept["w"]), 0.0)
        s = algo.update(s, True)
        kept = algo.adjust(new, old, s)
        np.testing.assert_allclose(np.asarray(kept["w"]), 1.0)

    def test_adjust_mixed_dtype_tree(self):
        """``adjust`` over a realistic mixed train state (bf16 params,
        fp8 quantized buffers + delayed-scaling ``Fp8Meta``, int
        counters): the predicated select must preserve every leaf's
        dtype and pick per-leaf correctly on both branches (ISSUE 3
        satellite — the unified sentinel predicates whole state trees,
        not just fp16 params)."""
        from apex_tpu.amp.fp8 import E4M3, Fp8Meta

        algo = amp.DynamicLossScale()

        def tree(v):
            return {
                "w": jnp.full((2, 2), v, jnp.bfloat16),
                "q": jnp.full((3,), v, E4M3),
                "meta": Fp8Meta(
                    amax_history=jnp.full((4,), v, jnp.float32),
                    scale=jnp.float32(v)),
                "steps": jnp.int32(int(v)),
            }

        old, new = tree(1.0), tree(2.0)
        for finite, want in [(False, old), (True, new)]:
            s = algo.update(algo.init(), finite)
            kept = algo.adjust(new, old, s)
            for k, w in zip(jax.tree_util.tree_leaves(kept),
                            jax.tree_util.tree_leaves(want)):
                assert k.dtype == w.dtype
                np.testing.assert_array_equal(np.asarray(k),
                                              np.asarray(w))


class TestAllFinite:
    def test_finite(self):
        assert bool(amp.all_finite({"a": jnp.ones(3), "b": jnp.zeros(2)}))

    def test_nan(self):
        assert not bool(amp.all_finite({"a": jnp.array([1.0, jnp.nan])}))

    def test_inf(self):
        assert not bool(amp.all_finite({"a": jnp.array([jnp.inf])}))

    def test_ignores_ints(self):
        assert bool(amp.all_finite({"ids": jnp.arange(3)}))

    # Mixed-dtype trees (ISSUE 3 satellite): the unified sentinel runs
    # all_finite over whole train-state grads/trees — fp8 delayed-scaling
    # state, int leaves, bool flags — so only the fp16 happy path being
    # covered would let a dtype regression slip under the sentinel.

    def test_mixed_tree_with_fp8_and_ints_finite(self):
        from apex_tpu.amp.fp8 import E4M3, E5M2, Fp8Meta

        tree = {
            "w": jnp.ones((2, 2), jnp.bfloat16),
            "q_act": jnp.ones((3,), E4M3),
            "q_grad": jnp.ones((3,), E5M2),
            "fp8_meta": Fp8Meta.init(history_len=4),
            "ids": jnp.arange(3),
            "flag": jnp.asarray(True),
            "count": 5,
        }
        assert bool(amp.all_finite(tree))

    def test_fp8_nan_detected(self):
        """e4m3fn has NaN (no inf): a NaN fp8 leaf must trip the
        sentinel exactly like an fp16 one."""
        from apex_tpu.amp.fp8 import E4M3

        bad = jnp.asarray(jnp.nan, jnp.float32).astype(E4M3)
        assert not bool(amp.all_finite({"q": jnp.array([bad, bad])}))

    def test_fp8_e5m2_inf_detected(self):
        from apex_tpu.amp.fp8 import E5M2

        bad = jnp.asarray(jnp.inf, jnp.float32).astype(E5M2)
        assert not bool(amp.all_finite({"q": jnp.array([bad])}))

    def test_nonfinite_int_neighbor_does_not_mask(self):
        """Int leaves are skipped but must not short-circuit a NaN in a
        floating sibling (regression guard on the leaf filter)."""
        tree = {"ids": jnp.arange(4), "g": jnp.array([jnp.nan]),
                "more_ids": jnp.zeros((2,), jnp.int8)}
        assert not bool(amp.all_finite(tree))

    def test_all_int_tree_is_finite(self):
        assert bool(amp.all_finite({"a": jnp.arange(2),
                                    "b": np.arange(3)}))


class TestMasterWeights:
    def test_roundtrip(self):
        params = {"w": jnp.ones((2, 2), jnp.bfloat16), "step": jnp.int32(0)}
        m = amp.make_master(params)
        assert m.params["w"].dtype == jnp.float32
        assert m.params["step"].dtype == jnp.int32
        back = amp.master_to_model(m)
        assert back["w"].dtype == jnp.bfloat16

    def test_master_precision_survives(self):
        """fp32 master accumulates updates a bf16 param would lose."""
        params = {"w": jnp.ones((1,), jnp.bfloat16)}
        m = amp.make_master(params)
        small = 1e-4
        new_master = m._replace(
            params={"w": m.params["w"] + small}
        )
        assert float(new_master.params["w"][0]) != 1.0  # fp32 keeps it
        assert float(amp.master_to_model(new_master)["w"][0]) == 1.0  # bf16 rounds


class TestFrontend:
    def test_initialize_o2(self):
        params = {"w": jnp.ones((2, 2))}
        conf, state = amp.initialize(params, opt_level="O2")
        assert conf.policy.name == "O2"
        assert isinstance(conf.loss_scaler, amp.DynamicLossScale)
        assert state.master is not None
        assert state.master.params["w"].dtype == jnp.float32

    def test_initialize_override_scale(self):
        conf, state = amp.initialize(opt_level="O2", loss_scale=128.0)
        assert isinstance(conf.loss_scaler, amp.StaticLossScale)
        assert float(state.scaler.scale) == 128.0

    def test_state_dict_roundtrip(self):
        conf, state = amp.initialize(opt_level="O2")
        s2 = conf.loss_scaler.update(state.scaler, False)
        sd = amp.state_dict(state._replace(scaler=s2))
        restored = amp.load_state_dict(state, sd)
        assert float(restored.scaler.scale) == float(s2.scale)

    def test_multiple_losses_independent_scalers(self):
        """The reference's multiple-models/optimizers/losses contract
        (tests/L0/run_amp/test_multiple_models_optimizers_losses.py):
        num_losses > 1 gives each loss its own dynamic scaler whose
        overflow backoff does not disturb the others."""
        conf, state = amp.initialize(opt_level="O2", num_losses=2)
        assert isinstance(state.scaler, tuple) and len(state.scaler) == 2
        s0, s1 = state.scaler
        start = float(s0.scale)
        # loss 0 overflows; loss 1 is clean
        s0 = conf.loss_scaler.update(s0, jnp.asarray(False))
        s1 = conf.loss_scaler.update(s1, jnp.asarray(True))
        assert float(s0.scale) == start / 2.0       # backed off
        assert float(s1.scale) == start             # untouched
        assert bool(s0.found_inf) and not bool(s1.found_inf)

        # per-loss scaling uses the per-loss state
        l0 = amp.scale_loss(jnp.float32(1.0), s0)
        l1 = amp.scale_loss(jnp.float32(1.0), s1)
        assert float(l0) == float(s0.scale)
        assert float(l1) == float(s1.scale)

        # state-dict round-trips the scaler list
        sd = amp.state_dict(state._replace(scaler=(s0, s1)))
        assert isinstance(sd, list) and len(sd) == 2
        restored = amp.load_state_dict(state, sd)
        assert float(restored.scaler[0].scale) == float(s0.scale)
        assert float(restored.scaler[1].scale) == float(s1.scale)

    def test_load_state_dict_num_losses_mismatch(self):
        """Resume with a different num_losses loads the overlapping prefix
        with a warning (reference: apex/amp/frontend.py:394 skips extra
        saved scalers rather than refusing the checkpoint)."""
        conf, state = amp.initialize(opt_level="O2", num_losses=2)
        s0 = conf.loss_scaler.update(state.scaler[0], jnp.asarray(True))
        sd = amp.state_dict(state._replace(scaler=(s0, state.scaler[1])))

        # fewer saved than expected: prefix loads, the rest stays fresh
        with pytest.warns(UserWarning, match="overlapping prefix"):
            restored = amp.load_state_dict(state, sd[:1])
        assert float(restored.scaler[0].scale) == float(s0.scale)
        assert float(restored.scaler[1].scale) == float(state.scaler[1].scale)

        # more saved than expected: extras dropped
        _, single = amp.initialize(opt_level="O2")
        with pytest.warns(UserWarning, match="overlapping prefix"):
            restored = amp.load_state_dict(single, sd)
        assert float(restored.scaler.scale) == float(s0.scale)
