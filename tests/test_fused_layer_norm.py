"""Fused LayerNorm/RMSNorm numerics — analog of
``tests/L0/run_fused_layer_norm/test_fused_layer_norm.py`` (fused vs
framework-native reference across affine/RMS/mixed-dtype/memory-efficient)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.normalization import (
    FusedLayerNorm,
    FusedRMSNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
    manual_rms_norm,
)


def _rand(shape, seed=0, dtype=np.float32):
    return np.random.RandomState(seed).randn(*shape).astype(dtype)


SHAPES = [((4, 16), (16,)), ((2, 3, 8), (8,)), ((5, 4, 6), (4, 6))]


class TestLayerNorm:
    @pytest.mark.parametrize("xshape,nshape", SHAPES)
    @pytest.mark.parametrize("mem_eff", [False, True])
    def test_affine_fwd_bwd_vs_torch(self, xshape, nshape, mem_eff):
        x = _rand(xshape, 1)
        w = _rand(nshape, 2) * 0.5 + 1.0
        b = _rand(nshape, 3) * 0.1

        y = fused_layer_norm_affine(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), nshape,
            memory_efficient=mem_eff,
        )
        tx = torch.tensor(x, requires_grad=True)
        tw = torch.tensor(w, requires_grad=True)
        tb = torch.tensor(b, requires_grad=True)
        ty = torch.nn.functional.layer_norm(tx, nshape, tw, tb, 1e-5)
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   rtol=1e-5, atol=1e-5)

        # backward
        dy = _rand(xshape, 4)
        dx, dw, db = jax.grad(
            lambda x_, w_, b_: jnp.sum(
                fused_layer_norm_affine(x_, w_, b_, nshape,
                                        memory_efficient=mem_eff)
                * jnp.asarray(dy)
            ),
            argnums=(0, 1, 2),
        )(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
        ty.backward(torch.tensor(dy))
        np.testing.assert_allclose(np.asarray(dx), tx.grad.numpy(), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dw), tw.grad.numpy(), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(db), tb.grad.numpy(), rtol=1e-4, atol=1e-5)

    def test_non_affine(self):
        x = _rand((4, 16), 5)
        y = fused_layer_norm(jnp.asarray(x), (16,))
        ty = torch.nn.functional.layer_norm(torch.tensor(x), (16,))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), rtol=1e-5, atol=1e-5)

    def test_bf16_input_fp32_stats(self):
        """Mixed dtype: bf16 input, fp32 weights (MixedFused variant)."""
        x = _rand((8, 32), 6)
        w = np.ones(32, np.float32)
        b = np.zeros(32, np.float32)
        y = fused_layer_norm_affine(
            jnp.asarray(x, jnp.bfloat16), jnp.asarray(w), jnp.asarray(b), (32,)
        )
        assert y.dtype == jnp.bfloat16
        ty = torch.nn.functional.layer_norm(torch.tensor(x), (32,))
        np.testing.assert_allclose(
            np.asarray(y, np.float32), ty.numpy(), rtol=2e-2, atol=2e-2
        )

    def test_memory_efficient_matches_standard(self):
        x = _rand((4, 16), 7)
        w = _rand((16,), 8) + 1.0
        b = _rand((16,), 9)
        f = lambda me: jax.grad(
            lambda x_: jnp.sum(
                fused_layer_norm_affine(x_, jnp.asarray(w), jnp.asarray(b),
                                        (16,), memory_efficient=me) ** 2
            )
        )(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(f(True)), np.asarray(f(False)),
                                   rtol=1e-4, atol=1e-5)

    def test_memory_efficient_zero_gamma_no_nan(self):
        """clamp_by_magnitude parity (layer_norm_cuda_kernel.cu:443): zero
        gamma must not produce NaN grads in the memory-efficient backward."""
        x = jnp.asarray(_rand((4, 16), 30))
        w = jnp.zeros(16)
        b = jnp.zeros(16)
        dx = jax.grad(
            lambda x_: jnp.sum(
                fused_layer_norm_affine(x_, w, b, (16,), memory_efficient=True)
            )
        )(x)
        assert np.all(np.isfinite(np.asarray(dx)))

    def test_module(self):
        m = FusedLayerNorm(normalized_shape=16)
        x = jnp.asarray(_rand((4, 16), 10))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        ty = torch.nn.functional.layer_norm(torch.tensor(np.asarray(x)), (16,))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), rtol=1e-5, atol=1e-5)


class TestRMSNorm:
    @pytest.mark.parametrize("xshape,nshape", SHAPES)
    @pytest.mark.parametrize("mem_eff", [False, True])
    def test_affine_fwd_bwd_vs_manual(self, xshape, nshape, mem_eff):
        x = _rand(xshape, 11)
        w = _rand(nshape, 12) * 0.5 + 1.0
        y = fused_rms_norm_affine(
            jnp.asarray(x), jnp.asarray(w), nshape, memory_efficient=mem_eff
        )
        ref = manual_rms_norm(jnp.asarray(x), nshape, jnp.asarray(w), 1e-5)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

        # grads vs autodiff of the manual implementation
        dy = _rand(xshape, 13)
        got = jax.grad(
            lambda x_, w_: jnp.sum(
                fused_rms_norm_affine(x_, w_, nshape, memory_efficient=mem_eff)
                * jnp.asarray(dy)
            ),
            argnums=(0, 1),
        )(jnp.asarray(x), jnp.asarray(w))
        want = jax.grad(
            lambda x_, w_: jnp.sum(
                manual_rms_norm(x_, nshape, w_, 1e-5) * jnp.asarray(dy)
            ),
            argnums=(0, 1),
        )(jnp.asarray(x), jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(got[1]), np.asarray(want[1]),
                                   rtol=1e-4, atol=1e-5)

    def test_torch_rms_norm_parity(self):
        x = _rand((4, 16), 14)
        w = _rand((16,), 15) + 1.0
        y = fused_rms_norm_affine(jnp.asarray(x), jnp.asarray(w), (16,))
        ty = torch.nn.functional.rms_norm(
            torch.tensor(x), (16,), torch.tensor(w), 1e-5
        )
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), rtol=1e-5, atol=1e-5)

    def test_module(self):
        m = FusedRMSNorm(normalized_shape=16)
        x = jnp.asarray(_rand((4, 16), 16))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        assert y.shape == x.shape
