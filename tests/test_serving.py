"""apex_tpu.serving — paged KV cache, fused decode kernels, engine.

Fast tier: kernel parity (fused Pallas vs unfused XLA vs a dense
reference — GQA, bf16 dequant, int8 per-row-scale dequant, and the
chunked-prefill kernel pair included), the fused residual/norm
epilogue, block-allocator refcount/copy-on-write invariants, the
prefix cache, decode-vs-prefill logits parity at tp=1, zero-recompile
churn, occupancy admission (eviction + preemption with
recompute-on-readmit at 2x pool oversubscription), chunked prefill,
the sampling policies, the int8 cache, and programmatic preemption
drain (the real-SIGTERM drain lives in scripts/serving_smoke.sh).
Slow tier: the tp=2 parity leg and the train-mesh -> serve-mesh
restore.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import parallel
from apex_tpu.serving import (
    BlockAllocator,
    OutOfBlocksError,
    PrefixCache,
    SamplingParams,
    ServingConfig,
    ServingEngine,
)
from apex_tpu.serving.fused_ops import (
    fused_residual_norm,
    residual_norm_unfused,
)
from apex_tpu.serving.paged_attention import (
    paged_attention_decode,
    paged_attention_decode_unfused,
    paged_prefill_attention,
    paged_prefill_attention_unfused,
)
from apex_tpu.transformer.testing import TransformerConfig
from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

VOCAB, MAX_SEQ = 64, 32


def _int8_quantize(arr):
    """Host-side mirror of the in-graph per-row symmetric quant."""
    amax = np.abs(arr).max(-1)
    scales = np.maximum(amax / 127.0, 1e-8).astype(np.float32)
    q = np.clip(np.round(arr / scales[..., None]), -127, 127)
    return q.astype(np.int8), scales


# ---------------------------------------------------------------- kernels


def _dense_paged_reference(q, ka, va, tables, lengths, bs):
    """O(everything) host reference: walk each slot's block table."""
    b, n, d = q.shape
    g = ka.shape[2]
    out = np.zeros((b, n, d), np.float32)
    for i in range(b):
        L = int(lengths[i])
        if L == 0:
            continue
        rows_k, rows_v = [], []
        for t in range(L):
            blk = int(tables[i, t // bs])
            rows_k.append(np.asarray(ka[blk, t % bs], np.float32))
            rows_v.append(np.asarray(va[blk, t % bs], np.float32))
        k = np.repeat(np.stack(rows_k), n // g, axis=1)
        v = np.repeat(np.stack(rows_v), n // g, axis=1)
        s = np.einsum("nd,tnd->nt", np.asarray(q[i], np.float32), k)
        s /= np.sqrt(d)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[i] = np.einsum("nt,tnd->nd", p, v)
    return out


class TestPagedAttentionKernel:
    def _case(self, *, g, cache_dtype):
        rng = np.random.RandomState(0)
        b, n, d, bs, n_blocks, mb = 4, 8, 64, 8, 16, 3
        q = jnp.asarray(rng.randn(b, n, d), jnp.float32)
        ka = jnp.asarray(rng.randn(n_blocks, bs, g, d), cache_dtype)
        va = jnp.asarray(rng.randn(n_blocks, bs, g, d), cache_dtype)
        tables = jnp.asarray(
            rng.permutation(n_blocks)[:b * mb].reshape(b, mb), jnp.int32)
        lengths = jnp.asarray([17, 0, 8, 24], jnp.int32)
        return q, ka, va, tables, lengths, bs

    @pytest.mark.parametrize("g", [8, 4])   # MHA and GQA (2 heads/group)
    def test_fused_matches_dense_reference(self, g):
        q, ka, va, tables, lengths, bs = self._case(
            g=g, cache_dtype=jnp.float32)
        out = paged_attention_decode(q, ka, va, tables, lengths)
        ref = _dense_paged_reference(q, ka, va, tables, lengths, bs)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)
        # inactive slot (length 0) produces exactly zeros
        assert np.abs(np.asarray(out[1])).max() == 0.0

    def test_unfused_matches_fused_incl_bf16_dequant(self):
        for dtype in (jnp.float32, jnp.bfloat16):
            q, ka, va, tables, lengths, _ = self._case(
                g=4, cache_dtype=dtype)
            fused = paged_attention_decode(q, ka, va, tables, lengths)
            unfused = paged_attention_decode_unfused(
                q, ka, va, tables, lengths)
            np.testing.assert_allclose(
                np.asarray(fused, np.float32),
                np.asarray(unfused, np.float32), atol=2e-5)

    def test_stale_table_entries_are_harmless(self):
        """Columns past the live blocks may hold garbage ids — the
        clamped index map must never read them."""
        q, ka, va, tables, lengths, bs = self._case(
            g=8, cache_dtype=jnp.float32)
        poisoned = np.asarray(tables).copy()
        for i, L in enumerate(np.asarray(lengths)):
            live = max((int(L) + bs - 1) // bs, 1)
            poisoned[i, live:] = 10_000   # far out of range
        out = paged_attention_decode(
            q, ka, va, jnp.asarray(poisoned), lengths)
        ref = _dense_paged_reference(q, ka, va, tables, lengths, bs)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)

    def test_int8_per_row_scale_dequant(self):
        """ISSUE 12: int8 arenas with per-row fp32 scales — the fused
        in-kernel dequant must match the unfused twin exactly and the
        fp32 cache closely (the quantization error bound, not kernel
        error)."""
        q, ka, va, tables, lengths, bs = self._case(
            g=4, cache_dtype=jnp.float32)
        ka_np, va_np = np.asarray(ka), np.asarray(va)
        qk, sk = _int8_quantize(ka_np)
        qv, sv = _int8_quantize(va_np)
        fused = paged_attention_decode(
            q, jnp.asarray(qk), jnp.asarray(qv), tables, lengths,
            k_scales=jnp.asarray(sk), v_scales=jnp.asarray(sv))
        unfused = paged_attention_decode_unfused(
            q, jnp.asarray(qk), jnp.asarray(qv), tables, lengths,
            k_scales=jnp.asarray(sk), v_scales=jnp.asarray(sv))
        np.testing.assert_allclose(np.asarray(fused), np.asarray(unfused),
                                   atol=2e-5)
        ref = _dense_paged_reference(q, ka, va, tables, lengths, bs)
        np.testing.assert_allclose(np.asarray(fused), ref, atol=0.05)
        # scale arenas must pair up
        with pytest.raises(ValueError, match="both k_scales"):
            paged_attention_decode(q, jnp.asarray(qk), jnp.asarray(qv),
                                   tables, lengths,
                                   k_scales=jnp.asarray(sk))


class TestPagedPrefillKernel:
    """The chunked-prefill sweep: per-token causal limits over history
    + the chunk's own just-scattered rows (ISSUE 12)."""

    def _case(self, g=4, dtype=jnp.float32):
        rng = np.random.RandomState(4)
        b, T, n, d, bs, n_blocks, mb = 3, 5, 8, 16, 4, 12, 4
        q = jnp.asarray(rng.randn(b, T, n, d), jnp.float32)
        ka = jnp.asarray(rng.randn(n_blocks, bs, g, d), dtype)
        va = jnp.asarray(rng.randn(n_blocks, bs, g, d), dtype)
        tables = jnp.asarray(
            rng.permutation(n_blocks)[:b * mb].reshape(b, mb), jnp.int32)
        hist = np.asarray([3, 0, 7], np.int32)     # cached history
        chunk = np.asarray([5, 0, 4], np.int32)    # this tick's tokens
        limits = np.zeros((b, T), np.int32)
        for i in range(b):
            for t in range(int(chunk[i])):
                limits[i, t] = int(hist[i]) + t + 1
        lengths = jnp.asarray(hist + chunk, jnp.int32)
        return q, ka, va, tables, lengths, jnp.asarray(limits), bs

    def _reference(self, q, ka, va, tables, limits, bs):
        b, T, n, d = q.shape
        g = ka.shape[2]
        out = np.zeros((b, T, n, d), np.float32)
        for i in range(b):
            for t in range(T):
                L = int(limits[i, t])
                if L == 0:
                    continue
                rk = [np.asarray(ka[int(tables[i, p // bs]), p % bs],
                                 np.float32) for p in range(L)]
                rv = [np.asarray(va[int(tables[i, p // bs]), p % bs],
                                 np.float32) for p in range(L)]
                k = np.repeat(np.stack(rk), n // g, axis=1)
                v = np.repeat(np.stack(rv), n // g, axis=1)
                s = np.einsum("nd,pnd->np",
                              np.asarray(q[i, t], np.float32), k)
                s /= np.sqrt(d)
                p_ = np.exp(s - s.max(-1, keepdims=True))
                p_ /= p_.sum(-1, keepdims=True)
                out[i, t] = np.einsum("np,pnd->nd", p_, v)
        return out

    def test_fused_matches_unfused_and_reference(self):
        q, ka, va, tables, lengths, limits, bs = self._case()
        fused = paged_prefill_attention(q, ka, va, tables, lengths,
                                        limits)
        unfused = paged_prefill_attention_unfused(
            q, ka, va, tables, lengths, limits)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(unfused),
                                   atol=2e-5)
        ref = self._reference(q, ka, va, tables, limits, bs)
        np.testing.assert_allclose(np.asarray(fused), ref, atol=2e-5)
        # the all-padding slot (limit 0 everywhere) emits exact zeros
        assert np.abs(np.asarray(fused[1])).max() == 0.0

    def test_int8_scales(self):
        q, ka, va, tables, lengths, limits, bs = self._case()
        qk, sk = _int8_quantize(np.asarray(ka))
        qv, sv = _int8_quantize(np.asarray(va))
        fused = paged_prefill_attention(
            q, jnp.asarray(qk), jnp.asarray(qv), tables, lengths, limits,
            k_scales=jnp.asarray(sk), v_scales=jnp.asarray(sv))
        unfused = paged_prefill_attention_unfused(
            q, jnp.asarray(qk), jnp.asarray(qv), tables, lengths, limits,
            k_scales=jnp.asarray(sk), v_scales=jnp.asarray(sv))
        np.testing.assert_allclose(np.asarray(fused), np.asarray(unfused),
                                   atol=2e-5)
        ref = self._reference(q, ka, va, tables, limits, bs)
        np.testing.assert_allclose(np.asarray(fused), ref, atol=0.05)


class TestFusedEpilogue:
    def test_matches_unfused(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(3, 2, 128), jnp.float32)
        res = jnp.asarray(rng.randn(3, 2, 128), jnp.float32)
        w = jnp.asarray(rng.randn(128), jnp.float32)
        bl = jnp.asarray(rng.randn(128), jnp.float32)
        bias = jnp.asarray(rng.randn(128), jnp.float32)
        for b in (bias, None):
            y1, r1 = fused_residual_norm(x, res, w, bl, bias=b)
            y2, r2 = residual_norm_unfused(x, res, w, bl, bias=b)
            np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                       atol=1e-5)
            np.testing.assert_allclose(np.asarray(r1), np.asarray(r2),
                                       atol=1e-6)

    def test_bf16_wire_dequant(self):
        """bf16 projection output (the 'dequant' input) normalizes in
        fp32 — the fused result must match the unfused fp32-math twin
        at bf16 resolution."""
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(4, 128), jnp.bfloat16)
        res = jnp.asarray(rng.randn(4, 128), jnp.float32)
        w = jnp.ones((128,), jnp.float32)
        bl = jnp.zeros((128,), jnp.float32)
        y1, r1 = fused_residual_norm(x, res, w, bl)
        y2, r2 = residual_norm_unfused(x, res, w, bl)
        np.testing.assert_allclose(np.asarray(y1, np.float32),
                                   np.asarray(y2, np.float32), atol=1e-2)
        np.testing.assert_allclose(np.asarray(r1), np.asarray(r2))


# -------------------------------------------------------------- allocator


class TestBlockAllocator:
    def test_alloc_free_roundtrip_and_invariants(self):
        al = BlockAllocator(10)
        a = al.alloc(4, owner="a")
        b = al.alloc(6, owner="b")
        assert sorted(a + b) == list(range(10)) and al.n_free == 0
        al.check()
        al.free(a, owner="a")
        assert al.n_free == 4
        al.check()
        c = al.alloc(3, owner="c")
        assert set(c) <= set(a)        # LIFO reuse of the freed blocks
        al.check()

    def test_exhaustion_is_atomic(self):
        al = BlockAllocator(4)
        al.alloc(3, owner="x")
        with pytest.raises(OutOfBlocksError):
            al.alloc(2, owner="y")
        assert al.n_free == 1          # failed alloc took nothing
        al.check()

    def test_double_free_and_foreign_free_raise(self):
        al = BlockAllocator(4)
        blocks = al.alloc(2, owner="a")
        al.free(blocks, owner="a")
        with pytest.raises(ValueError, match="double free"):
            al.free(blocks, owner="a")
        more = al.alloc(1, owner="b")
        with pytest.raises(ValueError, match="owned by"):
            al.free(more, owner="intruder")
        al.check()

    def test_fragmentation_free_by_construction(self):
        """Interleaved alloc/free churn: any n <= n_free request always
        succeeds (fixed-size blocks cannot strand capacity) and the
        free/owned partition stays exact."""
        rng = np.random.RandomState(3)
        al = BlockAllocator(32)
        held = {}
        for step in range(200):
            if held and (al.n_free == 0 or rng.rand() < 0.45):
                key = rng.choice(list(held))
                al.free(held.pop(key), owner=key)
            else:
                n = int(rng.randint(1, 6))
                if n <= al.n_free:     # the ONLY admission question
                    key = f"r{step}"
                    held[key] = al.alloc(n, owner=key)
            al.check()
        assert al.n_free + al.n_owned == 32

    # ------------------------- ISSUE 12: refcount / copy-on-write

    def test_shared_free_decrements_not_releases(self):
        """The copy-on-write invariant: freeing a shared block removes
        one holder — the block returns to the pool only from its LAST
        holder."""
        al = BlockAllocator(4)
        (b,) = al.alloc(1, owner="writer")
        al.share(b, "reader")
        assert al.refcount(b) == 2
        al.free([b], owner="writer")      # decrement, NOT release
        assert al.n_free == 3 and al.refcount(b) == 1
        al.check()
        # the writer's hold is gone: a second writer-free is foreign
        with pytest.raises(ValueError, match="owned by"):
            al.free([b], owner="writer")
        al.free([b], owner="reader")      # last holder -> pool
        assert al.n_free == 4 and al.refcount(b) == 0
        with pytest.raises(ValueError, match="double free"):
            al.free([b], owner="reader")
        al.check()

    def test_share_guards(self):
        al = BlockAllocator(2)
        (b,) = al.alloc(1, owner="a")
        with pytest.raises(ValueError, match="free block"):
            al.share(1, "a")              # block 1 was never allocated
        with pytest.raises(ValueError, match="already holds"):
            al.share(b, "a")              # double hold by one owner
        al.check()

    def test_churn_with_sharing_strands_no_capacity(self):
        """200 interleaved alloc/share/free steps: the refcounts must
        drain exactly — at every step free + held partitions the pool,
        and full release returns everything."""
        rng = np.random.RandomState(9)
        al = BlockAllocator(24)
        held = {}                # owner -> list of blocks (ref held)
        for step in range(200):
            r = rng.rand()
            if held and (al.n_free == 0 or r < 0.35):
                key = rng.choice(list(held))
                al.free(held.pop(key), owner=key)
            elif held and r < 0.55:
                # a new owner shares a random existing holder's blocks
                # (the prefix-cache hit shape)
                src = rng.choice(list(held))
                key = f"s{step}"
                for b in held[src]:
                    al.share(b, key)
                held[key] = list(held[src])
            else:
                n = int(rng.randint(1, 5))
                if n <= al.n_free:
                    key = f"r{step}"
                    held[key] = al.alloc(n, owner=key)
            al.check()
        for key in list(held):
            al.free(held.pop(key), owner=key)
        al.check()
        assert al.n_free == 24 and al.n_owned == 0


class TestPrefixCache:
    """The token-hash index over shared blocks (ISSUE 12)."""

    def test_lookup_shares_longest_chain_and_caps(self):
        al = BlockAllocator(8)
        pc = PrefixCache(al, block_size=4)
        toks = list(range(10, 22))           # 12 tokens = 3 full blocks
        blocks = al.alloc(3, owner="w")
        pc.insert(toks, blocks, upto_tokens=12)
        assert len(pc) == 3
        # identical prompt: capped so >= 1 token is left to recompute
        hit = pc.lookup(toks, "r", max_blocks=(len(toks) - 1) // 4)
        assert hit == blocks[:2] and pc.hits == 2
        assert all(al.refcount(b) == 3 for b in hit)  # w + cache + r
        # divergent second block: only the first block chains
        other = toks[:4] + [99] * 8
        hit2 = pc.lookup(other, "r2", max_blocks=2)
        assert hit2 == blocks[:1]
        al.free(hit, "r")
        al.free(hit2, "r2")
        pc.check()

    def test_insert_only_covers_written_tokens(self):
        """Blocks whose K/V has not landed must not be indexed — a
        same-tick hit would read garbage."""
        al = BlockAllocator(8)
        pc = PrefixCache(al, block_size=4)
        toks = list(range(8))
        blocks = al.alloc(2, owner="w")
        pc.insert(toks, blocks, upto_tokens=5)   # only block 0 complete
        assert len(pc) == 1
        pc.insert(toks, blocks, upto_tokens=8)   # chunk 2 lands
        assert len(pc) == 2

    def test_blocked_admit_rolls_back_hit_accounting(self):
        """A FIFO head that hits the cache but cannot admit (pool full)
        hands its shared refs back AND un-counts the hits — a head
        stuck for N ticks must not inflate serving/prefix_cache_hits N
        times with blocks that were never served."""
        from apex_tpu.serving.kv_cache import KVCacheConfig
        from apex_tpu.serving.scheduler import Scheduler

        cache = KVCacheConfig(n_layers=1, n_blocks=4, block_size=4,
                              kv_heads=1, head_dim=8, max_seq=32)
        sched = Scheduler(cache, max_batch=3, chunk_tokens=8)
        a = sched.submit(list(range(8)), 4)          # 2 full blocks
        hog = sched.submit(list(range(20, 27)), 4)   # 2 more blocks
        assert sched.admit() == [a, hog]
        sched.note_prefilled(a, 8)     # a's 2 prompt blocks now cached
        assert len(sched.prefix_cache) == 2
        c = sched.submit(list(range(8)), 4)          # would hit a's chain
        for _ in range(5):             # pool is full: head blocks
            assert sched.admit() == []
        assert sched.prefix_cache.hits == 0, \
            "phantom hits counted for blocks that were handed back"
        sched.allocator.check()
        # capacity appears -> the head admits and the hit finally counts
        sched.note_prefilled(hog, 7)
        sched.finish(a)
        sched.finish(hog)
        assert sched.admit() == [c]
        assert c.hit_blocks == 1 and sched.prefix_cache.hits == 1

    def test_evict_is_lru_and_skips_shared(self):
        al = BlockAllocator(8)
        pc = PrefixCache(al, block_size=4)
        # 5-token sequences: one full shareable block each, one token
        # always left to recompute (the enforced CoW cap)
        a_toks, b_toks = [1] * 4 + [9], [2] * 4 + [9]
        (a,) = al.alloc(1, owner="wa")
        (b,) = al.alloc(1, owner="wb")
        pc.insert(a_toks, [a], 4)
        pc.insert(b_toks, [b], 4)
        al.free([a], "wa")
        al.free([b], "wb")          # both now cache-only (evictable)
        assert pc.lookup(a_toks, "reader") == [a]   # a: shared + MRU
        assert pc.evictable() == 1
        assert pc.evict_one() == b  # LRU *sole-holder* entry
        assert pc.evict_one() is None   # a is shared: not evictable
        assert pc.evict_many(4) == 0    # the sweep skips it too
        al.free([a], "reader")
        assert pc.evict_many(4) == 1    # now sole-holder: one sweep
        assert al.n_free == 8 and pc.evictions == 2
        pc.check()

    def test_lookup_enforces_the_recompute_cap(self):
        """A block-aligned prompt must never be fully served from
        cache — lookup itself caps at (len-1)//block_size even when the
        caller passes no max_blocks (writes stay off shared blocks by
        construction)."""
        al = BlockAllocator(8)
        pc = PrefixCache(al, block_size=4)
        toks = list(range(8))                 # exactly 2 full blocks
        blocks = al.alloc(2, owner="w")
        pc.insert(toks, blocks, 8)
        assert pc.lookup(toks, "r") == blocks[:1]   # never both
        al.free(blocks[:1], "r")


# ----------------------------------------------------------------- engine


def _tiny_cfg(**kw):
    base = dict(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=VOCAB, max_position_embeddings=MAX_SEQ,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)
    base.update(kw)
    return TransformerConfig(**base)


# (mesh, cfg, params) per model config, shared across this module's
# engine tests: the param init is an ~8s XLA compile and every engine
# test would otherwise pay it again.  The cached Mesh object stays
# valid after the autouse registry teardown (only the registration is
# global state), and params are read-only inputs to every engine.
_MODEL_CACHE = {}


def _model(tp, **cfg_kw):
    key = (tp, tuple(sorted(cfg_kw.items())))
    if key not in _MODEL_CACHE:
        mesh = parallel.initialize_model_parallel(
            tensor_model_parallel_size=tp,
            devices=jax.devices()[:max(tp, 1)])
        cfg = _tiny_cfg(**cfg_kw)
        init_fn, _, _ = build_gpt_3d(cfg, num_chunks=cfg.num_layers,
                                     num_microbatches=1, mesh=mesh)
        params, _ = init_fn(jax.random.PRNGKey(0),
                            jnp.zeros((2, 4), jnp.int32))
        _MODEL_CACHE[key] = (mesh, cfg, params)
    return _MODEL_CACHE[key]


def _build_engine(tp, serving=None, **cfg_kw):
    mesh, cfg, params = _model(tp, **cfg_kw)
    serving = serving or ServingConfig(max_batch=3, block_size=4,
                                       max_seq=MAX_SEQ, prefill_len=MAX_SEQ)
    from apex_tpu.observability.metrics import MetricRegistry

    eng = ServingEngine(cfg, serving, params, mesh=mesh,
                        registry=MetricRegistry())
    return mesh, cfg, eng


def _sampling_zeros(B):
    """Greedy policy arrays (temperature 0) for direct program calls."""
    return (np.zeros((B,), np.float32), np.zeros((B,), np.int32),
            np.ones((B,), np.float32), np.zeros((B,), np.uint32),
            np.zeros((B,), np.int32))


def _teacher_forced_parity(eng, seq, prefix_len):
    """Prefill ``seq[:prefix_len]``, then decode the rest teacher-forced;
    every step's logits must match a fresh full prefill of the prefix."""
    from apex_tpu.serving.kv_cache import init_kv_arena

    cache = eng.cache
    bs = cache.block_size
    B, T = eng.serving.max_batch, eng.prefill_len
    mb = cache.max_blocks_per_request
    blocks = list(range(mb))
    tables = np.zeros((B, mb), np.int32)
    tables[0, :mb] = blocks

    def prefill_logits(upto, arenas):
        tokens = np.zeros((B, T), np.int32)
        tokens[0, :upto] = seq[:upto]
        pos = np.zeros((B, T), np.int32)
        pos[0, :upto] = np.arange(upto)
        limits = np.zeros((B, T), np.int32)
        limits[0, :upto] = np.arange(1, upto + 1)
        lengths = np.zeros((B,), np.int32)
        lengths[0] = upto
        db = np.full((B, T), cache.n_blocks, np.int32)
        do = np.zeros((B, T), np.int32)
        db[0, :upto] = [blocks[t // bs] for t in range(upto)]
        do[0, :upto] = [t % bs for t in range(upto)]
        sample_index = np.full((B,), T, np.int32)
        return eng._prefill(arenas, eng.params, tokens, pos,
                            jnp.asarray(tables), lengths, limits, db, do,
                            sample_index, *_sampling_zeros(B))

    arenas, _, _ = prefill_logits(prefix_len, eng.arenas)
    max_err = 0.0
    for t in range(prefix_len, len(seq)):
        toks = np.zeros((B, eng.spec_width), np.int32)
        toks[0, 0] = seq[t]
        pos = np.zeros((B,), np.int32)
        pos[0] = t
        act = np.zeros((B,), bool)
        act[0] = True
        arenas, _, _, logits = eng._decode(
            arenas, eng.params, toks, pos, jnp.asarray(tables), act,
            np.zeros((B,), np.int32), *_sampling_zeros(B))
        arenas2 = init_kv_arena(cache, eng.mesh, eng.tp_axis)
        _, _, full = prefill_logits(t + 1, arenas2)
        err = float(jnp.max(jnp.abs(logits[0, 0] - full[0, t])))
        max_err = max(max_err, err)
    return max_err


def test_decode_vs_prefill_logits_parity_tp1():
    _, _, eng = _build_engine(tp=1)
    seq = np.asarray([5, 9, 33, 12, 44, 2, 17, 60], np.int32)
    err = _teacher_forced_parity(eng, seq, prefix_len=3)
    assert err < 2e-4, err


@pytest.mark.slow
def test_decode_vs_prefill_logits_parity_tp2():
    _, _, eng = _build_engine(tp=2, num_query_groups=2,
                              position_embedding_type="rope")
    seq = np.asarray([5, 9, 33, 12, 44, 2, 17, 60, 21], np.int32)
    err = _teacher_forced_parity(eng, seq, prefix_len=4)
    assert err < 2e-4, err


def test_join_leave_churn_zero_recompiles():
    """Requests joining and leaving mid-flight never change a shape:
    the decode executable compiles exactly once, the fused and unfused
    paths emit identical tokens, and the pool drains clean."""
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, VOCAB - 1,
                           size=rng.randint(2, 10)).tolist()
               for _ in range(6)]

    def run(fused):
        _, _, eng = _build_engine(
            tp=1, serving=ServingConfig(
                max_batch=2, block_size=4, max_seq=MAX_SEQ,
                prefill_len=MAX_SEQ, fused_attention=fused,
                fuse_epilogue=fused))
        reqs = [eng.submit(prompts[0], 5), eng.submit(prompts[1], 3)]
        pending = iter(prompts[2:])
        for step in range(60):
            if step % 2 == 1:
                p = next(pending, None)
                if p is not None:
                    reqs.append(eng.submit(p, 2 + step % 4))
            eng.step()
            if eng.scheduler.idle and len(reqs) == len(prompts):
                break
        eng.run_until_drained()
        assert eng.decode_compile_count() == 1
        assert eng.prefill_compile_count() == 1
        eng.scheduler.allocator.check()
        # a drained pool is free blocks + prefix-cached blocks (finished
        # requests' full blocks stay behind as evictable capacity)
        al = eng.scheduler.allocator
        pc = eng.scheduler.prefix_cache
        assert al.n_free + pc.n_blocks == al.n_blocks
        assert all(al.refcount(b) == 1
                   for b in pc._entries.values())   # cache-only holds
        pc.check()
        return [r.output_tokens for r in reqs]

    assert run(True) == run(False)


def test_preemption_drain_delivers_in_flight():
    from apex_tpu.resilience import PreemptionGuard
    from apex_tpu.serving.scheduler import RequestState

    guard = PreemptionGuard(signals=())   # programmatic trigger only
    _, _, eng = _build_engine(
        tp=1, serving=ServingConfig(max_batch=2, block_size=4,
                                    max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    eng.guard = guard
    running = [eng.submit([3, 5, 7], 4), eng.submit([11, 13], 4)]
    eng.step()                             # both admitted + first tokens
    queued = [eng.submit([17, 19], 4)]
    guard.trigger()                        # preemption notice
    eng.run_until_drained(max_steps=100)
    assert eng.draining
    for req in running:
        assert req.state is RequestState.FINISHED
        assert len(req.output_tokens) == 4
    assert queued[0].state is RequestState.CANCELLED
    # a post-drain submit is REJECTED at the door (typed, distinct from
    # the drain cancellation of the already-queued request) and counted
    # in its own catalog entry — the signal a fleet router re-routes on
    late = eng.submit([2, 4], 2)
    assert late.state is RequestState.REJECTED
    assert late.done
    # metrics recorded through the registry (catalog: docs/serving.md)
    snap = eng.registry.snapshot()
    assert snap["serving/requests_cancelled"] == 1.0
    assert snap["serving/requests_rejected"] == 1.0
    assert snap["serving/requests_finished"] == 2.0
    assert snap["serving/tpot_ms"]["count"] > 0


def test_cache_dtype_bf16_serves():
    """bf16 KV arena (half the cache HBM; in-kernel dequant) still
    decodes the same greedy tokens as the fp32 cache on this tiny
    model."""
    def run(dtype):
        _, _, eng = _build_engine(
            tp=1, serving=ServingConfig(
                max_batch=2, block_size=4, max_seq=MAX_SEQ,
                prefill_len=MAX_SEQ, cache_dtype=dtype))
        r = eng.submit([5, 6, 7, 8, 9], 4)
        eng.run_until_drained()
        return r.output_tokens

    assert run(jnp.bfloat16) == run(jnp.float32)


# ------------------------------------------------- ISSUE 12: occupancy


def _wave(seed=5, n=6):
    rng = np.random.RandomState(seed)
    return [(rng.randint(1, VOCAB - 1, size=rng.randint(4, 14)).tolist(),
             int(rng.randint(6, 14))) for _ in range(n)]


def _run_wave(wave, *, n_blocks=None, admission="occupancy",
              prefill_len=8, sampling=None, cache_dtype=None,
              speculative=None, proposer=None):
    _, _, eng = _build_engine(
        tp=1, serving=ServingConfig(
            max_batch=4, block_size=4, max_seq=MAX_SEQ,
            prefill_len=prefill_len, n_blocks=n_blocks,
            admission=admission, cache_dtype=cache_dtype,
            speculative=speculative))
    if proposer is not None:
        eng.proposer = proposer
    reqs = [eng.submit(p, n, sampling=sampling) for p, n in wave]
    eng.run_until_drained(max_steps=2000)
    eng.scheduler.allocator.check()
    assert eng.decode_compile_count() == 1
    assert eng.prefill_compile_count() == 1
    return eng, [r.output_tokens for r in reqs]


def test_occupancy_2x_oversubscription_finishes_all():
    """The ISSUE 12 acceptance bar: with the pool at a fraction of the
    worst-case demand, occupancy admission (grow + evict + preempt with
    recompute-on-readmit) still FINISHES every admitted request, with
    streams token-identical to an ample-pool run, zero recompiles, and
    the preemption machinery demonstrably exercised."""
    wave = _wave()
    _, ref = _run_wave(wave)                      # ample pool
    worst = sum(-(-min(len(p) + n, MAX_SEQ) // 4) for p, n in wave)
    eng, over = _run_wave(wave, n_blocks=max(8, worst // 4))
    assert over == ref
    assert all(r.state.value == "finished"
               for r in eng.scheduler.running() or []) or \
        eng.scheduler.idle
    assert eng.scheduler.preemptions > 0, \
        "the undersized pool never preempted — the test is not testing"
    assert eng.scheduler.prefix_cache.evictions > 0
    snap = eng.registry.snapshot()
    assert snap["serving/preemptions"] == eng.scheduler.preemptions
    assert snap["serving/evictions"] == eng.scheduler.prefix_cache.evictions


def test_reserve_admission_is_the_pr8_baseline():
    """admission='reserve' keeps worst-case reservation: same outputs,
    no prefix cache, zero preemptions (requests just queue longer)."""
    wave = _wave()
    _, ref = _run_wave(wave)
    worst = sum(-(-min(len(p) + n, MAX_SEQ) // 4) for p, n in wave)
    eng, res = _run_wave(wave, n_blocks=max(8, worst // 4),
                         admission="reserve")
    assert res == ref
    assert eng.scheduler.preemptions == 0
    assert eng.scheduler.prefix_cache is None
    assert eng.scheduler.allocator.n_free == \
        eng.scheduler.allocator.n_blocks      # reserve frees fully


def test_prefix_cache_hit_shares_blocks_and_matches_cold():
    """A repeated prompt prefix hits the cache: blocks shared (counted
    in serving/prefix_cache_hits), outputs identical to the cold run."""
    _, _, eng = _build_engine(
        tp=1, serving=ServingConfig(max_batch=2, block_size=4,
                                    max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    template = [7, 11, 13, 17, 19, 23, 29, 31]     # two full blocks
    cold = eng.submit(template + [3], 4)
    eng.run_until_drained()
    assert cold.hit_blocks == 0
    warm = eng.submit(template + [5], 4)
    eng.run_until_drained()
    assert warm.hit_blocks == 2                    # both template blocks
    # identical full prompt: the whole prefix short of the cap is shared
    again = eng.submit(template + [3], 4)
    eng.run_until_drained()
    assert again.hit_blocks == 2
    assert again.output_tokens == cold.output_tokens
    snap = eng.registry.snapshot()
    assert snap["serving/prefix_cache_hits"] >= 4
    assert eng.introspect()["prefix_cached_blocks"] > 0
    eng.scheduler.prefix_cache.check()


def test_chunked_prefill_matches_one_shot():
    """A prompt longer than the chunk width slices across ticks and
    produces exactly the one-shot engine's stream (and compiles the
    prefill exactly once)."""
    wave = [(list(range(1, 25)), 5), ([30, 31], 3)]   # 24 > chunk of 4
    _, one_shot = _run_wave(wave, prefill_len=MAX_SEQ)
    eng, chunked = _run_wave(wave, prefill_len=4)
    assert chunked == one_shot


def test_sampling_policies_reproducible_and_data_only():
    """Seeded sampling redraws the same stream; top_k=1 degenerates to
    greedy; mixing policies in one batch is data, never shape (zero
    decode recompiles across the whole mix)."""
    wave = [([9, 8, 7], 6), ([4, 5], 6)]
    sp = SamplingParams(temperature=1.5, top_p=0.9, seed=42)
    _, a = _run_wave(wave, sampling=sp, prefill_len=MAX_SEQ)
    _, b = _run_wave(wave, sampling=sp, prefill_len=MAX_SEQ)
    assert a == b                                   # same seeds, same stream
    _, greedy = _run_wave(wave, prefill_len=MAX_SEQ)
    _, k1 = _run_wave(wave, prefill_len=MAX_SEQ,
                      sampling=SamplingParams(temperature=2.0, top_k=1,
                                              seed=7))
    assert k1 == greedy                             # only the argmax survives
    # mixed policies in ONE engine: churn through greedy + sampled slots
    _, _, eng = _build_engine(
        tp=1, serving=ServingConfig(max_batch=4, block_size=4,
                                    max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    r1 = eng.submit([9, 8, 7], 6)
    r2 = eng.submit([9, 8, 7], 6, sampling=sp)
    r3 = eng.submit([9, 8, 7], 6,
                    sampling=SamplingParams(temperature=0.7, top_k=4,
                                            seed=3))
    eng.run_until_drained()
    assert eng.decode_compile_count() == 1
    assert r1.output_tokens == greedy[0][:6] or len(r1.output_tokens) == 6
    assert all(0 <= t < VOCAB for r in (r1, r2, r3)
               for t in r.output_tokens)
    with pytest.raises(ValueError, match="temperature"):
        SamplingParams(temperature=-1.0)
    with pytest.raises(ValueError, match="top_p"):
        SamplingParams(top_p=0.0)


def test_sampled_stream_survives_preemption():
    """The seeded-counter construction: a preempted sampled request
    replayed through the chunked prefill redraws the SAME stream —
    recompute-on-readmit does not fork a stochastic stream."""
    wave = _wave(seed=12, n=5)
    sp = SamplingParams(temperature=1.0, top_p=0.95, seed=99)
    _, ample = _run_wave(wave, sampling=sp)
    worst = sum(-(-min(len(p) + n, MAX_SEQ) // 4) for p, n in wave)
    eng, tight = _run_wave(wave, sampling=sp, n_blocks=max(8, worst // 4))
    assert eng.scheduler.preemptions > 0
    assert tight == ample


def test_int8_cache_greedy_identity():
    """int8 KV (per-row scales, in-kernel dequant) emits the same
    greedy tokens as the fp32 cache on this model — including under
    occupancy pressure."""
    wave = _wave(seed=3, n=5)
    _, fp32 = _run_wave(wave)
    eng, i8 = _run_wave(wave, cache_dtype=jnp.int8)
    assert i8 == fp32
    assert eng.cache.quantized and len(eng.arenas) == 4
    worst = sum(-(-min(len(p) + n, MAX_SEQ) // 4) for p, n in wave)
    eng2, i8_tight = _run_wave(wave, cache_dtype=jnp.int8,
                               n_blocks=max(8, worst // 4))
    assert i8_tight == fp32
    assert eng2.scheduler.preemptions + \
        eng2.scheduler.prefix_cache.evictions > 0


def test_serving_config_validates_admission():
    with pytest.raises(ValueError, match="admission"):
        ServingConfig(admission="optimistic")


@pytest.mark.slow
def test_restore_train_mesh_to_serving_mesh():
    """Train-side [vpp=1, pp=2] layer stack restores bit-exactly onto
    the serving mesh's [L, 1] stack through the PR 6 spec layer, and
    the engine serves from the restored params."""
    import shutil
    import tempfile

    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.resilience import CheckpointManager, reshard
    from apex_tpu.serving.loader import restore_gpt_for_serving
    from apex_tpu.transformer.testing.gpt_parallel_train import (
        gpt3d_logical_folds,
    )

    cfg = _tiny_cfg()
    workdir = tempfile.mkdtemp(prefix="apex_serving_restore_")
    try:
        mesh = parallel.initialize_model_parallel(
            tensor_model_parallel_size=2, pipeline_model_parallel_size=2,
            devices=jax.devices()[:4])
        init_fn, _, _ = build_gpt_3d(cfg, num_chunks=1,
                                     num_microbatches=1, mesh=mesh)
        params, _ = init_fn(jax.random.PRNGKey(0),
                            jnp.zeros((2, 4), jnp.int32))
        tree = {"params": params, "step_count": np.asarray(7)}
        spec = reshard.build_spec(tree, mesh=mesh,
                                  folds=gpt3d_logical_folds(tree))
        CheckpointManager(workdir, sharded=True, spec=spec).save(tree, 7)
        train_host = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), params)
        mesh_lib.destroy_model_parallel()

        mesh = parallel.initialize_model_parallel(
            tensor_model_parallel_size=2, devices=jax.devices()[:2])
        sparams, _ = restore_gpt_for_serving(workdir, cfg, mesh=mesh)
        serve_host = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), sparams)
        L = cfg.num_layers
        for a, b in zip(jax.tree_util.tree_leaves(train_host.layers),
                        jax.tree_util.tree_leaves(serve_host.layers)):
            assert np.array_equal(a.reshape((L,) + a.shape[2:]),
                                  b.reshape((L,) + b.shape[2:]))
        for a, b in zip(
                jax.tree_util.tree_leaves(train_host.embedding),
                jax.tree_util.tree_leaves(serve_host.embedding)):
            assert np.array_equal(a, b)

        eng = ServingEngine(
            cfg, ServingConfig(max_batch=2, block_size=4, max_seq=MAX_SEQ,
                               prefill_len=MAX_SEQ),
            sparams, mesh=mesh)
        r = eng.submit([5, 6, 7, 8], 3)
        eng.run_until_drained()
        assert len(r.output_tokens) == 3
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def test_scheduler_rejects_unserviceable_request():
    """A request whose worst-case block need exceeds the WHOLE pool can
    never be admitted — accepting it would park it at the head of the
    FIFO queue forever, starving everything behind it.  Rejected at
    submit, with serviceable requests unaffected."""
    from apex_tpu.serving.kv_cache import KVCacheConfig
    from apex_tpu.serving.scheduler import Scheduler

    cache = KVCacheConfig(n_layers=1, n_blocks=4, block_size=4,
                          kv_heads=1, head_dim=8, max_seq=64)
    sched = Scheduler(cache, max_batch=2)
    with pytest.raises(ValueError, match="worst-case"):
        sched.submit(list(range(1, 21)), 20)   # 10 blocks > 4 in pool
    ok = sched.submit([1, 2, 3], 4)            # 2 blocks: queues fine
    assert sched.admit() == [ok]


def test_engine_rejects_oversized_prompt_and_position_table():
    _, cfg, eng = _build_engine(tp=1)
    # chunked prefill removed the prefill_len bound (a long prompt just
    # slices across ticks); the context cap is the one real limit
    with pytest.raises(ValueError, match="max_seq"):
        eng.submit(list(range(MAX_SEQ + 4)), 2)
    with pytest.raises(ValueError, match="max_seq"):
        ServingEngine(cfg, ServingConfig(max_batch=2, block_size=4,
                                         max_seq=MAX_SEQ * 8),
                      eng_params_of(eng), mesh=eng.mesh)


def eng_params_of(eng):
    """Re-wrap engine params into the [vpp=L, pp=1] canonical input."""
    params = eng.params
    return params._replace(layers=jax.tree_util.tree_map(
        lambda l: l.reshape((l.shape[0], 1) + l.shape[1:]), params.layers))


# ------------------------------------------------- ISSUE 10: observability


def test_heartbeat_hung_decode_triggers_drain():
    """ISSUE 10 satellite: the heartbeat armed on the decode loop.  A
    device step that wedges (parked behind an event, the
    faults.hung_writes shape applied to the decode dispatch) stops the
    beats; the monitor's on_hang fires the PreemptionGuard, and the
    engine's next alive step() DRAINS — in-flight requests deliver,
    the queue cancels — instead of the scheduler wedging forever."""
    import threading

    from apex_tpu.observability.metrics import HeartbeatMonitor
    from apex_tpu.resilience import PreemptionGuard
    from apex_tpu.serving.scheduler import RequestState

    guard = PreemptionGuard(signals=())
    hb = HeartbeatMonitor(timeout_s=0.05, on_hang=guard)
    _, _, eng = _build_engine(
        tp=1, serving=ServingConfig(max_batch=2, block_size=4,
                                    max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    eng.guard = guard
    eng.heartbeat = hb

    running = [eng.submit([3, 5, 7], 6), eng.submit([11, 13], 6)]
    eng.step()                        # healthy tick: beat recorded
    queued = [eng.submit([17, 19], 4)]
    assert hb.last_step == 1 and not hb.check_now()

    # park the NEXT decode mid-flight on another thread (the hung
    # device step); the main thread plays the monitor's poll loop
    gate = threading.Event()
    real_decode = eng._decode

    def parked_decode(*args):
        gate.wait()
        return real_decode(*args)

    eng._decode = parked_decode
    t = threading.Thread(target=eng.step, daemon=True)
    t.start()
    deadline = time.monotonic() + 10.0
    while not hb.check_now():         # deterministic poll, no bg thread
        assert time.monotonic() < deadline, "hang never detected"
        time.sleep(0.01)
    assert guard.triggered, "on_hang must fire the guard"
    # the wedge clears (preempted hosts come back long enough to drain)
    gate.set()
    t.join(timeout=30)
    assert not t.is_alive()
    eng._decode = real_decode
    eng.run_until_drained(max_steps=100)
    assert eng.draining
    for req in running:
        assert req.state is RequestState.FINISHED
        assert len(req.output_tokens) == req.max_new_tokens
    assert queued[0].state is RequestState.CANCELLED
    assert hb.hang_count == 1
    assert int(eng.registry.counter(
        "serving/preemption_drains").value) == 1


def test_engine_timeline_lifecycle_and_goodput():
    """With a flight recorder armed, every request leaves a complete
    submit -> admit -> prefill -> decode ticks -> finish trail keyed by
    rid, and serving_goodput_report closes the books over it."""
    from apex_tpu.observability import timeline
    from apex_tpu.observability.goodput import serving_goodput_report
    from apex_tpu.observability.timeline import FlightRecorder

    rec = timeline.arm(FlightRecorder())
    try:
        _, _, eng = _build_engine(
            tp=1, serving=ServingConfig(max_batch=2, block_size=4,
                                        max_seq=MAX_SEQ,
                                        prefill_len=MAX_SEQ))
        eng.timeline_tick_every = 2
        reqs = [eng.submit([3, 5, 7], 5), eng.submit([11, 13], 3)]
        eng.run_until_drained()
        events = rec.events()
        for req in reqs:
            mine = [e for e in events if e.get("rid") == req.rid]
            kinds = [e["kind"] for e in mine]
            assert kinds[0] == "request_submit"
            assert "request_admit" in kinds
            assert kinds[-1] == "request_finish"
            assert any(k == "decode_tick" for k in kinds)
            ticks = [e["tokens"] for e in mine
                     if e["kind"] == "decode_tick"]
            assert all(n % 2 == 0 for n in ticks)  # sampled every 2
        prefills = [e for e in events if e["kind"] == "prefill"]
        assert prefills and "dur_s" in prefills[0]
        assert sorted(r for e in prefills for r in e["rids"]) == \
            sorted(r.rid for r in reqs)
        rep = serving_goodput_report(events)
        assert rep["totals"]["finished"] == 2
        assert rep["totals"]["cancelled"] == 0
        assert rep["goodput_fraction"] is not None
        assert 0.0 < rep["goodput_fraction"] <= 1.0
    finally:
        timeline.disarm()


def test_engine_introspect_and_mfu_reason():
    """introspect() (the /statusz payload) reports live slots/blocks/
    queue plus MFU-or-reason; on the CPU test mesh the reason must name
    the unknown platform peak, never fabricate a number (and the
    serving/mfu gauge stays unset)."""
    _, _, eng = _build_engine(
        tp=1, serving=ServingConfig(max_batch=2, block_size=4,
                                    max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    snap = eng.introspect()
    assert snap["steps"] == 0 and snap["mfu_reason"] is not None
    eng.submit([3, 5, 7], 3)
    eng.step()
    snap = eng.introspect()
    assert snap["active_slots"] == 1
    assert snap["queue_depth"] == 0
    assert snap["decode_compiles"] == 1
    assert snap["free_blocks"] < snap["total_blocks"]
    assert snap["last_decode_ms"] is not None
    # CPU: flops may exist (XLA:CPU reports them) but the peak is
    # undefined -> mfu None with the platform named
    assert snap["mfu"] is None
    assert "cpu" in snap["mfu_reason"]
    assert eng.registry.gauge("serving/mfu").value is None
    eng.run_until_drained()
    assert eng.introspect()["active_slots"] == 0
    assert eng.decode_compile_count() == 1, \
        "the MFU lowering probe must not add a decode compile"


def test_engine_statusz_through_debug_server():
    """The debug server serves the live engine: /statusz carries the
    introspection dict while requests are in flight."""
    import json as _json
    import urllib.request

    from apex_tpu.observability import DebugServer
    from apex_tpu.observability.metrics import MetricRegistry

    _, _, eng = _build_engine(
        tp=1, serving=ServingConfig(max_batch=2, block_size=4,
                                    max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    eng.submit([3, 5, 7], 4)
    eng.step()
    with DebugServer(registry=eng.registry, engine=eng) as srv:
        body = _json.loads(urllib.request.urlopen(
            srv.url("/statusz"), timeout=10).read())
        metrics = urllib.request.urlopen(
            srv.url("/metrics"), timeout=10).read().decode()
    assert body["serving"]["active_slots"] == 1
    assert body["serving"]["draining"] is False
    assert "apex_serving_tokens_generated" in metrics
    assert "apex_serving_active_slots" in metrics
    eng.run_until_drained()


# ------------- ISSUE 16: KV export/import (the disaggregation handoff)


def _migrated_stream(sampling=None, spec=False, after=3, n_new=10):
    """Prefill+decode ``after`` tokens on one engine, export/import the
    paged KV into a second engine, finish there; returns the stitched
    stream plus both engines for invariant checks."""
    import dataclasses

    kw = dict(max_batch=3, block_size=4, max_seq=MAX_SEQ,
              prefill_len=MAX_SEQ)
    if spec:
        from apex_tpu.serving.speculative import SpeculativeConfig
        kw["speculative"] = SpeculativeConfig(k=3)
    _, _, src = _build_engine(1, serving=ServingConfig(
        max_batch=3, block_size=4, max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    _, _, dst = _build_engine(1, serving=ServingConfig(**kw))
    prompt = np.arange(1, 9, dtype=np.int32)
    req = src.submit(prompt, max_new_tokens=n_new, sampling=sampling)
    while len(req.output_tokens) < after and not req.done:
        src.step()
    assert not req.done
    pre = list(req.output_tokens)
    meta, payloads = src.export_request(req)
    # the export invariants the router's phase cross-check rests on
    assert meta["n_out"] == len(pre)
    assert meta["cache_len"] == len(prompt) + len(pre) - 1
    assert meta["n_blocks"] == len(payloads) >= 1
    wire = np.concatenate([prompt, np.asarray(pre, np.int32)])
    s2 = sampling
    if s2 is not None:
        s2 = dataclasses.replace(
            s2, step_offset=s2.step_offset + len(pre))
    req2 = dst.import_request(wire, n_new - len(pre), sampling=s2,
                              cache_len=int(meta["cache_len"]),
                              payloads=payloads)
    src.release_export(req.rid, ok=True)
    for _ in range(120):
        dst.step()
        if req2.done:
            break
    assert req2.done
    return pre + list(req2.output_tokens), src, dst


def _single_stream(sampling=None, spec=False, n_new=10):
    kw = dict(max_batch=3, block_size=4, max_seq=MAX_SEQ,
              prefill_len=MAX_SEQ)
    if spec:
        from apex_tpu.serving.speculative import SpeculativeConfig
        kw["speculative"] = SpeculativeConfig(k=3)
    _, _, eng = _build_engine(1, serving=ServingConfig(**kw))
    req = eng.submit(np.arange(1, 9, dtype=np.int32),
                     max_new_tokens=n_new, sampling=sampling)
    for _ in range(120):
        eng.step()
        if req.done:
            break
    assert req.done
    return list(req.output_tokens)


def test_export_import_greedy_bitwise_identity():
    """The tentpole contract at the engine layer: a stream exported
    after 3 tokens and imported into a fresh engine is bitwise the
    single-engine stream — the imported KV plus a one-token re-prefill
    reproduce the exact decode state."""
    single = _single_stream()
    migrated, src, dst = _migrated_stream()
    assert migrated == single
    # refcount story: the pin released into the prefix cache, every
    # block in both pools is free XOR held
    assert len(src.exports) == 0
    src.scheduler.allocator.check()
    dst.scheduler.allocator.check()


def test_export_import_seeded_bitwise_identity():
    """Seeded sampling across the handoff: the rebased ``step_offset``
    keys the destination's draws at the absolute stream position, so
    sampled streams are bitwise identical too."""
    sp = SamplingParams(temperature=0.8, top_k=8, seed=7)
    single = _single_stream(sampling=sp)
    migrated, src, dst = _migrated_stream(sampling=sp)
    assert migrated == single


def test_export_import_speculative_decode_identity():
    """The decode side of a disaggregated fleet runs k-speculative: an
    imported request verified k+1 at a time still matches the plain
    single-engine stream bitwise (speculation is exact)."""
    single = _single_stream()                      # plain greedy engine
    migrated, src, dst = _migrated_stream(spec=True)
    assert migrated == single


def test_export_refused_while_prefilling_or_unstarted():
    """Export demands a quiescent decode-state request: no slot, a
    pending prefill, or zero emitted tokens must refuse (ValueError)
    rather than ship a cache that disagrees with the stream."""
    _, _, eng = _build_engine(1, serving=ServingConfig(
        max_batch=2, block_size=4, max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    req = eng.submit([3, 5, 7], 4)
    with pytest.raises(ValueError):
        eng.export_request(req)        # nothing prefilled yet
    eng.run_until_drained()
    with pytest.raises(ValueError):
        eng.export_request(req)        # finished: no slot anymore


def test_import_shape_mismatch_refused_before_scatter():
    """A payload whose shape disagrees with the arenas must refuse
    BEFORE any device put — a torn/mismatched transfer can never
    corrupt the destination cache."""
    single = _single_stream(n_new=6)   # warm reference engine unused
    _, _, src = _build_engine(1, serving=ServingConfig(
        max_batch=3, block_size=4, max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    _, _, dst = _build_engine(1, serving=ServingConfig(
        max_batch=3, block_size=4, max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    prompt = np.arange(1, 9, dtype=np.int32)
    req = src.submit(prompt, max_new_tokens=6)
    while len(req.output_tokens) < 2:
        src.step()
    meta, payloads = src.export_request(req)
    torn = [tuple(p[:-1]) for p in payloads]       # one slab short
    wire = np.concatenate(
        [prompt, np.asarray(req.output_tokens, np.int32)])
    with pytest.raises(ValueError):
        dst.import_request(wire, 4, cache_len=int(meta["cache_len"]),
                           payloads=torn)
    src.release_export(req.rid, ok=False)
    dst.scheduler.allocator.check()
    src.scheduler.allocator.check()


def test_export_churn_200_steps_leaks_no_blocks():
    """The refcount-hardening satellite: 200 migrate/fail/retry churn
    steps — export, then either abandon (the dies-before-ack shape,
    released not-ok) or land it — and the allocator invariant stays
    free-XOR-held on both pools; stale double-acks are no-ops."""
    _, _, src = _build_engine(1, serving=ServingConfig(
        max_batch=3, block_size=4, max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    _, _, dst = _build_engine(1, serving=ServingConfig(
        max_batch=3, block_size=4, max_seq=MAX_SEQ, prefill_len=MAX_SEQ))
    prompt = np.arange(1, 9, dtype=np.int32)
    for step in range(200):
        req = src.submit(prompt, max_new_tokens=4)
        while len(req.output_tokens) < 2 and not req.done:
            src.step()
        meta, payloads = src.export_request(req)
        if step % 3 == 0:
            # failed handoff: un-pin not-ok (re-prefill would follow)
            src.release_export(req.rid, ok=False)
            src.release_export(req.rid, ok=False)   # stale ack: no-op
        else:
            wire = np.concatenate(
                [prompt, np.asarray(req.output_tokens, np.int32)])
            req2 = dst.import_request(
                wire, 4 - len(req.output_tokens),
                cache_len=int(meta["cache_len"]), payloads=payloads)
            src.release_export(req.rid, ok=True)
            src.release_export(req.rid, ok=True)    # stale ack: no-op
            while not req2.done:
                dst.step()
        if step % 20 == 0:
            src.scheduler.allocator.check()
            dst.scheduler.allocator.check()
    assert len(src.exports) == 0
    src.exports.check()
    src.scheduler.allocator.check()
    dst.scheduler.allocator.check()
    assert src.introspect()["kv_exports_pinned"] == 0
