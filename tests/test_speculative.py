"""apex_tpu.serving.speculative — the ISSUE 13 vertical slice.

Proposer units (suffix matching, adaptive back-off) plus the engine
contracts the acceptance bar names: speculative greedy output bitwise
token-identical to the non-speculative reference at k ∈ {2, 4} — with
eviction/preemption forced mid-run and an int8 cache — zero decode and
prefill recompiles across acceptance churn, forced-acceptance and
forced-rejection legs through the duck-typed proposer slot, and the
seeded sampled stream surviving speculation unchanged.

Engines are cached per shape and reused across waves (policies, drafts
and churn are data — reuse costs nothing and keeps the tier-1 compile
budget flat); the shared tiny GPT comes from ``test_serving``'s
module-level model cache.
"""

import numpy as np
import pytest

from apex_tpu.serving import (
    NGramProposer,
    SamplingParams,
    ServingConfig,
    SpeculativeConfig,
    ngram_propose,
)
from apex_tpu.serving.scheduler import Request

from test_serving import MAX_SEQ, VOCAB, _build_engine, _wave

# ----------------------------------------------------------- proposer


class TestNGramPropose:
    def test_matches_most_recent_suffix_occurrence(self):
        # suffix [2, 3] occurred at index 1; continuation 4, 1, 2
        assert ngram_propose([1, 2, 3, 4, 1, 2, 3], 3) == [4, 1, 2]

    def test_prefers_longer_ngrams(self):
        # trigram [7, 8, 9] matches at the start; the bigram [8, 9]
        # also occurs later with a different continuation — the longer
        # match must win
        toks = [7, 8, 9, 5, 8, 9, 6, 7, 8, 9]
        assert ngram_propose(toks, 2, max_ngram=3) == [5, 8]
        assert ngram_propose(toks, 2, max_ngram=2) == [6, 7]

    def test_no_match_returns_empty(self):
        assert ngram_propose([1, 2, 3, 4, 5], 4) == []
        assert ngram_propose([1], 4) == []
        assert ngram_propose([1, 1, 1], 0) == []

    def test_cycle_is_fully_self_predictive(self):
        toks = [3, 9, 4, 9, 4, 9]
        assert ngram_propose(toks, 4) == [4, 9, 4, 9]

    def test_continuation_may_overlap_suffix(self):
        # repeated unigram: the previous occurrence's continuation runs
        # into the suffix itself — legal, and exactly the cycling shape
        assert ngram_propose([5, 6, 6], 2, max_ngram=1) == [6, 6]

    def test_config_validation(self):
        with pytest.raises(ValueError, match="k must be >= 1"):
            SpeculativeConfig(k=0)
        with pytest.raises(ValueError, match="min_ngram"):
            SpeculativeConfig(min_ngram=3, max_ngram=2)
        with pytest.raises(ValueError, match="backoff"):
            SpeculativeConfig(backoff=0)

    def test_adaptive_backoff_probe_and_rearm(self):
        """``backoff`` consecutive all-rejected proposals silence a
        request; a probe fires every ``probe_every`` quiet ticks, and
        one accepted probe re-arms full-rate drafting."""
        prop = NGramProposer(SpeculativeConfig(k=2, backoff=2,
                                               probe_every=3))
        req = Request(rid=0, prompt=np.asarray([1, 2, 1, 2], np.int32),
                      max_new_tokens=8)
        assert prop.propose(req, 2) == [1, 2]
        prop.observe(req, 2, 0)
        assert req.spec_fails == 1
        assert prop.propose(req, 2) == [1, 2]    # still armed
        prop.observe(req, 2, 0)
        assert req.spec_fails == 2
        assert prop.propose(req, 2) == []        # backed off
        prop.observe(req, 0, 0)                  # no-op: nothing proposed
        assert req.spec_fails == 2
        assert prop.propose(req, 2) == []        # quiet tick 2 of 3
        probe = prop.propose(req, 2)             # tick 3: the probe
        assert probe == [1], "a probe wastes ONE query position, not k"
        prop.observe(req, 1, 0)                  # probe rejected too
        assert prop.propose(req, 2) == []        # quiet again
        assert prop.propose(req, 2) == []
        assert prop.propose(req, 2) == [1]       # next probe
        prop.observe(req, 1, 1)                  # an acceptance re-arms
        assert req.spec_fails == 0
        assert prop.propose(req, 2) == [1, 2]    # full rate restored
        with pytest.raises(ValueError, match="probe_every"):
            SpeculativeConfig(probe_every=0)

    def test_backoff_keyed_per_slot_adapter(self):
        """ISSUE 18 satellite: adapter-tagged requests key back-off per
        ``(slot, adapter_id)`` — one template-poor adapter backing off
        neither touches the bare per-request counters nor silences a
        different adapter sharing the slot later."""
        prop = NGramProposer(SpeculativeConfig(k=2, backoff=2,
                                               probe_every=3))

        def tagged(rid, slot, aid):
            req = Request(rid=rid,
                          prompt=np.asarray([1, 2, 1, 2], np.int32),
                          max_new_tokens=8,
                          sampling=SamplingParams(adapter_id=aid))
            req.slot = slot
            return req

        poor = tagged(0, slot=3, aid="poor")
        for _ in range(2):
            assert prop.propose(poor, 2) == [1, 2]
            prop.observe(poor, 2, 0)
        assert prop.propose(poor, 2) == []       # (3, "poor") backed off
        # the bare per-request counters were NEVER touched
        assert poor.spec_fails == 0 and poor.spec_quiet == 0
        # a different adapter landing in the SAME slot drafts at full k
        rich = tagged(1, slot=3, aid="rich")
        assert prop.propose(rich, 2) == [1, 2]
        # ... and the poor adapter's NEXT request (same slot) inherits
        # the cell: still silenced, probe on the 3rd quiet tick
        poor2 = tagged(2, slot=3, aid="poor")
        assert prop.propose(poor2, 2) == []      # quiet 2
        assert prop.propose(poor2, 2) == [1]     # quiet 3: the probe
        prop.observe(poor2, 1, 1)                # accepted: cell re-arms
        assert prop.propose(poor2, 2) == [1, 2]
        # a bare request in the same engine keeps per-request state
        bare = Request(rid=3, prompt=np.asarray([1, 2, 1, 2], np.int32),
                       max_new_tokens=8)
        assert prop.propose(bare, 2) == [1, 2]
        prop.observe(bare, 2, 0)
        assert bare.spec_fails == 1

    def test_keyed_state_capped(self):
        """The (slot, adapter) table is bounded: the oldest cell is
        evicted at the cap, never unbounded growth."""
        prop = NGramProposer(SpeculativeConfig(k=2))
        cap = NGramProposer._STATE_CAP
        for i in range(cap + 7):
            req = Request(rid=i,
                          prompt=np.asarray([1, 2, 1, 2], np.int32),
                          max_new_tokens=8,
                          sampling=SamplingParams(adapter_id=f"a{i}"))
            req.slot = i % 8
            prop.propose(req, 2)
        assert len(prop._adapter_state) <= cap


# ------------------------------------------------------------- kernel


def test_decode_entry_4d_is_the_multi_query_sweep():
    """``paged_attention_decode`` with 4-D q + limits (the k+1 verify)
    must equal the chunked-prefill kernel and its unfused twin — one
    multi-query implementation behind both entry points — and reject
    mismatched arguments loudly."""
    import jax.numpy as jnp

    from apex_tpu.serving.paged_attention import (
        paged_attention_decode,
        paged_attention_decode_unfused,
        paged_prefill_attention,
    )

    rng = np.random.RandomState(2)
    b, S, n, d, bs, n_blocks, mb = 3, 4, 4, 16, 4, 10, 3
    q = jnp.asarray(rng.randn(b, S, n, d), jnp.float32)
    ka = jnp.asarray(rng.randn(n_blocks, bs, n, d), jnp.float32)
    va = jnp.asarray(rng.randn(n_blocks, bs, n, d), jnp.float32)
    tables = jnp.asarray(
        rng.permutation(n_blocks)[:b * mb].reshape(b, mb), jnp.int32)
    pos = np.asarray([3, 0, 6], np.int32)      # per-slot base position
    n_draft = np.asarray([3, 0, 2], np.int32)  # slot 1 inactive
    limits = np.zeros((b, S), np.int32)
    for i in range(b):
        w = (n_draft[i] + 1) if pos[i] or n_draft[i] else 0
        limits[i, :w] = pos[i] + 1 + np.arange(w)
    lengths = jnp.asarray(limits.max(axis=1), jnp.int32)
    limits = jnp.asarray(limits)
    verify = paged_attention_decode(q, ka, va, tables, lengths,
                                    limits=limits)
    prefill = paged_prefill_attention(q, ka, va, tables, lengths, limits)
    unfused = paged_attention_decode_unfused(q, ka, va, tables, lengths,
                                             limits=limits)
    np.testing.assert_array_equal(np.asarray(verify), np.asarray(prefill))
    np.testing.assert_allclose(np.asarray(verify), np.asarray(unfused),
                               atol=2e-5)
    with pytest.raises(ValueError, match="limits"):
        paged_attention_decode(q, ka, va, tables, lengths)   # 4-D, none
    with pytest.raises(ValueError, match="limits"):
        paged_attention_decode(q[:, 0], ka, va, tables, lengths,
                               limits=limits)                # 3-D, some


# ------------------------------------------------------------- engine


_ENGINES = {}

# The module's one greedy workload and its one plain-engine reference
# run (lazily computed, shared by every identity/forced test): engines
# and waves are both reused — compiles and reference ticks are the
# tier-1 cost here, drafts/policies/proposers are data.
WAVE = _wave(seed=5, n=6)
_SHARED = {}


def _engine(k=None, prefix_caching=False, **cfg_kw):
    """One cached engine per (spec width, cache shape).  Prefix caching
    is OFF by default so re-serving the same wave on a reused engine
    stays cold — tick-count assertions compare like with like; the
    eviction-pressure test opts back in on its own engine."""
    key = (k, prefix_caching,
           tuple(sorted(cfg_kw.items(), key=lambda i: i[0])))
    if key not in _ENGINES:
        spec = SpeculativeConfig(k=k, backoff=4) if k else None
        _, _, eng = _build_engine(
            tp=1, serving=ServingConfig(
                max_batch=4, block_size=4, max_seq=MAX_SEQ,
                prefill_len=8, speculative=spec,
                prefix_caching=prefix_caching, **cfg_kw))
        _ENGINES[key] = eng
    return _ENGINES[key]


def _shared_ref():
    """(streams, decode_calls) of WAVE on the plain fp32 engine."""
    if not _SHARED:
        refs, (calls, _, _) = _serve(_engine(None), WAVE)
        _SHARED["refs"], _SHARED["calls"] = refs, calls
    return _SHARED["refs"], _SHARED["calls"]


def _serve(eng, wave, *, sampling=None, proposer=None, max_steps=5000):
    """Run one wave on a (possibly reused) engine; returns the streams
    and this wave's (decode_calls, proposed, accepted) deltas.
    ``proposer`` may be a factory called with the submitted requests
    (rids are engine-lifetime, so per-request oracles bind late)."""
    old = eng.proposer
    calls0, prop0, acc0 = (eng._decode_calls, eng.spec_proposed,
                           eng.spec_accepted)
    try:
        reqs = [eng.submit(p, n, sampling=sampling) for p, n in wave]
        if proposer is not None:
            if not hasattr(proposer, "propose"):
                proposer = proposer(reqs)
            eng.proposer = proposer
        eng.run_until_drained(max_steps=max_steps)
    finally:
        eng.proposer = old
    eng.scheduler.allocator.check()
    assert eng.decode_compile_count() == 1, \
        "speculative churn must never recompile the decode step"
    assert eng.prefill_compile_count() == 1
    assert all(r.state.value == "finished" for r in reqs)
    return ([r.output_tokens for r in reqs],
            (eng._decode_calls - calls0, eng.spec_proposed - prop0,
             eng.spec_accepted - acc0))


class _OracleProposer:
    """Forced acceptance: drafts ARE the reference continuation."""

    def __init__(self, refs):
        self.refs = refs

    def propose(self, req, max_k):
        ref = self.refs[req.rid]
        done = len(req.output_tokens)
        return ref[done:done + max_k]

    def observe(self, req, proposed, accepted):
        assert accepted == proposed, \
            f"oracle draft rejected ({accepted}/{proposed})"


class _WrongProposer(NGramProposer):
    """Forced rejection: every draft misses the true next token, so the
    verify accepts nothing and the inherited adaptive back-off must
    silence the slot after ``backoff`` ticks."""

    def __init__(self, config, refs):
        super().__init__(config)
        self.refs = refs
        self.proposals = 0

    def propose(self, req, max_k):
        if req.spec_fails >= self.config.backoff:
            return []
        self.proposals += 1
        ref = self.refs[req.rid]
        done = len(req.output_tokens)
        want = ref[done:done + max_k] or [0]
        return [(t + 1) % VOCAB for t in want]


def test_greedy_identity_k4_with_real_drafting():
    """k=4 n-gram drafting: bitwise identical streams, fewer device
    steps than tokens once the tiny model's greedy loops make the
    stream self-predictive."""
    ref, ref_calls = _shared_ref()
    out, (calls, proposed, accepted) = _serve(_engine(4), WAVE)
    assert out == ref
    assert proposed > 0 and accepted > 0, \
        "nothing drafted/accepted — the verify path went untested"
    assert calls < ref_calls, \
        f"speculation saved no device steps ({calls} vs {ref_calls})"
    eng = _engine(4)
    snap = eng.registry.snapshot()
    assert snap["serving/spec_proposed"] == eng.spec_proposed
    assert snap["serving/spec_accepted"] == eng.spec_accepted
    intro = eng.introspect()
    assert intro["spec_width"] == 5
    assert intro["spec_acceptance"] == round(
        eng.spec_accepted / eng.spec_proposed, 4)


def test_greedy_identity_k2_int8_with_forced_preemption():
    """The acceptance bar's hard leg: k=2 over an int8 cache with the
    pool undersized so eviction AND preemption fire mid-speculation —
    streams stay bitwise identical to the non-speculative int8 engine,
    recompute-on-readmit included."""
    # the reference is the shared fp32 plain run: int8 greedy identity
    # vs fp32 is its own pinned contract
    # (test_serving.test_int8_cache_greedy_identity) and holds for this
    # wave too — one reference run serves the whole module
    ref, _ = _shared_ref()
    worst = sum(-(-min(len(p) + n, MAX_SEQ) // 4) for p, n in WAVE)
    eng = _engine(2, prefix_caching=True, cache_dtype=np.int8,
                  n_blocks=max(8, worst // 4))
    out, (_, proposed, _) = _serve(eng, WAVE, max_steps=20000)
    assert out == ref
    assert eng.scheduler.preemptions > 0, \
        "the undersized pool never preempted — the leg tested nothing"
    assert eng.scheduler.prefix_cache.evictions > 0
    assert proposed > 0


def test_forced_acceptance_bursts_through_the_budget():
    """An oracle proposer (drafts == the reference continuation) drives
    the all-accept path: every draft accepted, each verify emits a full
    burst, and the wave finishes in far fewer device steps."""
    refs, _ = _shared_ref()
    eng = _engine(4)
    out, (calls, proposed, accepted) = _serve(
        eng, WAVE, proposer=lambda reqs: _OracleProposer(
            {r.rid: ref for r, ref in zip(reqs, refs)}))
    assert out == refs
    assert accepted == proposed > 0
    total = sum(n for _, n in WAVE)
    # k=4: every decode call emits up to 5 tokens; even with ragged
    # tails the all-accept path must beat one-call-per-token soundly
    assert calls <= total // 2, (calls, total)


def test_forced_rejection_degrades_to_plain_ticks_and_backs_off():
    """An always-wrong proposer: zero drafts accepted, streams still
    bitwise correct (the verify's own outputs are the stream), and the
    adaptive back-off stops drafting after ``backoff`` wasted ticks per
    request — the worst case is today's one-token tick, never below."""
    refs, ref_calls = _shared_ref()
    eng = _engine(4)
    holder = []

    def factory(reqs):
        holder.append(_WrongProposer(
            SpeculativeConfig(k=4, backoff=2),
            {r.rid: ref for r, ref in zip(reqs, refs)}))
        return holder[0]

    out, (calls, proposed, accepted) = _serve(eng, WAVE,
                                              proposer=factory)
    wrong = holder[0]
    assert out == refs
    assert accepted == 0 and proposed > 0
    # every request burnt exactly `backoff` proposals, then went quiet
    assert wrong.proposals <= 2 * len(WAVE)
    assert calls == ref_calls, \
        "rejected drafts must not change the tick count — worst case " \
        "is exactly the plain decode"


def test_sampled_stream_identical_under_speculation():
    """Seeded sampling composes with the verify: every position draws
    at its own output counter, so accepted draws are the sequential
    draws and the sampled stream is bitwise unchanged by drafting."""
    wave = [([9, 8, 7, 9, 8, 7], 8), ([4, 5, 4, 5], 6)]
    sp = SamplingParams(temperature=1.1, top_p=0.9, seed=21)
    ref, _ = _serve(_engine(None), wave, sampling=sp)
    out, _ = _serve(_engine(4), wave, sampling=sp)
    assert out == ref


def test_spec_width_bounds_and_validation():
    """A verify wider than the context cap can never run a full burst —
    rejected at engine construction, before anything compiles."""
    from apex_tpu.serving import ServingEngine
    from test_serving import _model

    mesh, cfg, params = _model(1)
    with pytest.raises(ValueError, match="below the speculative"):
        ServingEngine(
            cfg, ServingConfig(max_batch=2, block_size=4, max_seq=4,
                               speculative=SpeculativeConfig(k=8)),
            params, mesh=mesh)
