"""FP8 delayed-scaling primitives (TransformerEngine-recipe math; the
reference only ships the amax process groups — SURVEY §2.2 row 24)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.amp.fp8 import (
    E4M3,
    E5M2,
    Fp8Dense,
    Fp8Meta,
    fp8_quantize,
    update_meta,
)
from apex_tpu.parallel import collectives as cc


def test_quantize_roundtrip_precision():
    meta = Fp8Meta.init()
    # warm the scale to the tensor's range
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 64)) * 3.0
    meta = update_meta(meta, jnp.max(jnp.abs(x)))
    q, amax = fp8_quantize(x, meta)
    assert q.dtype == E4M3
    deq = np.asarray(q, np.float32) / np.asarray(meta.scale)
    rel = np.abs(deq - np.asarray(x)) / (np.abs(np.asarray(x)) + 1e-6)
    assert np.median(rel) < 0.05  # ~2-3 mantissa bits
    np.testing.assert_allclose(float(amax), float(jnp.max(jnp.abs(x))),
                               rtol=1e-6)


def test_update_meta_rolls_history_and_scales():
    meta = Fp8Meta.init(history_len=4)
    meta = update_meta(meta, jnp.float32(2.0))
    assert float(meta.scale) == pytest.approx(448.0 / 2.0)
    meta = update_meta(meta, jnp.float32(8.0))
    assert float(meta.scale) == pytest.approx(448.0 / 8.0)
    # rolling max keeps the larger historical amax for 4 steps
    meta = update_meta(meta, jnp.float32(1.0))
    assert float(meta.scale) == pytest.approx(448.0 / 8.0)
    for _ in range(3):
        meta = update_meta(meta, jnp.float32(1.0))
    assert float(meta.scale) == pytest.approx(448.0 / 1.0)
    # e5m2 uses its own dynamic range
    g = update_meta(Fp8Meta.init(), jnp.float32(2.0), E5M2)
    assert float(g.scale) == pytest.approx(57344.0 / 2.0)


def test_amax_reduces_over_model_parallel_axis():
    parallel.initialize_model_parallel(tensor_model_parallel_size=8)
    try:
        def local(amax):
            return update_meta(Fp8Meta.init(), amax, axis="tp").scale[None]

        amaxes = jnp.arange(1.0, 9.0)  # rank r sees amax r+1
        scales = cc.shard_over(local, in_specs=P("tp"),
                               out_specs=P("tp"))(amaxes)
        # every rank derived the scale from the group max (8.0)
        np.testing.assert_allclose(np.asarray(scales), 448.0 / 8.0,
                                   rtol=1e-6)
    finally:
        parallel.destroy_model_parallel()


def test_fp8_dense_trains_close_to_fp32():
    """After the scales warm up, the fp8 layer trains a regression task to
    near the fp32 layer's loss."""
    import flax.linen as nn

    from apex_tpu.optimizers import FusedAdam

    x = jax.random.normal(jax.random.PRNGKey(0), (128, 16))
    w_true = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
    y_true = x @ w_true

    def train(module, steps=200):
        variables = module.init(jax.random.PRNGKey(2), x)
        params = variables["params"]
        state = dict(variables.get("fp8_meta", {}))
        opt = FusedAdam(lr=5e-2)
        ostate = opt.init(params)

        @jax.jit
        def step(params, ostate, fp8_state):
            def loss_fn(p):
                out = module.apply(
                    {"params": p, **({"fp8_meta": fp8_state}
                                     if fp8_state else {})},
                    x, mutable=["fp8_meta"] if fp8_state else [])
                y, mut = out
                return jnp.mean((y - y_true) ** 2), dict(mut)
            (l, mut), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
            params, ostate = opt.step(g, ostate, params)
            return params, ostate, mut.get("fp8_meta", fp8_state), l

        for _ in range(steps):
            params, ostate, state, loss = step(params, ostate, state)
        return float(loss), state

    loss8, meta = train(Fp8Dense(features=4, use_bias=False))
    loss32, _ = train(nn.Dense(features=4, use_bias=False))
    assert np.isfinite(loss8)
    # fp8 converges to near the quantization noise floor (e4m3 gives
    # ~2-3% per-tensor relative error -> MSE floor well below 0.1 here)
    assert loss8 < 0.1, loss8
    assert loss32 < 1e-4  # fp32 solves the task outright
    # scales actually adapted away from 1.0
    assert float(meta["metas"]["x"].scale) != 1.0
    assert float(meta["metas"]["w"].scale) != 1.0
    assert set(meta["metas"]) == {"x", "w"}  # grads scale just-in-time


def test_fp8_dense_grad_dtype_path():
    """The backward quantizes the cotangent to e5m2 with a just-in-time
    scale — grads differ from exact fp32 grads but stay within fp8
    tolerance even when the cotangent is loss-scaled by 2^16."""
    m = Fp8Dense(features=8, use_bias=False)
    x = jax.random.normal(jax.random.PRNGKey(3), (32, 16))
    variables = m.init(jax.random.PRNGKey(4), x)
    params, meta = variables["params"], variables["fp8_meta"]

    # warm the metas one step so scales match the data
    _, mut = m.apply({"params": params, "fp8_meta": meta}, x,
                     mutable=["fp8_meta"])
    meta = dict(mut)["fp8_meta"]

    def loss(p):
        y, _ = m.apply({"params": p, "fp8_meta": meta}, x,
                       mutable=["fp8_meta"])
        return jnp.sum(y ** 2)

    g = jax.grad(loss)(params)["kernel"]
    g_ref = jax.grad(
        lambda p: jnp.sum((x @ p) ** 2))(params["kernel"])
    rel = np.abs(np.asarray(g) - np.asarray(g_ref)) / (
        np.abs(np.asarray(g_ref)) + 1e-3)
    assert np.median(rel) < 0.15

    # loss-scaled cotangent (the DynamicLossScale contract): grads scale
    # linearly instead of saturating the e5m2 clip
    g_scaled = jax.grad(lambda p: loss(p) * 2.0 ** 16)(params)["kernel"]
    np.testing.assert_allclose(np.asarray(g_scaled),
                               np.asarray(g) * 2.0 ** 16,
                               rtol=0.05, atol=1e-2)


def test_fp8_dense_output_dtype_bf16():
    m = Fp8Dense(features=8)
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 16), jnp.bfloat16)
    v = m.init(jax.random.PRNGKey(6), x)
    y, _ = m.apply(v, x, mutable=["fp8_meta"])
    assert y.dtype == jnp.bfloat16  # bias add must not promote to fp32


def test_fp8_matmul_t_matches_dense_math():
    """The torch-layout GEMM core (w [out, in], y = x @ w.T) agrees with
    the full-precision product to e4m3 tolerance once scales are warm, in
    forward and both gradients."""
    from apex_tpu.amp.fp8 import fp8_matmul_t

    x = jax.random.normal(jax.random.PRNGKey(0), (3, 8, 16))
    w = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
    xm = update_meta(Fp8Meta.init(), jnp.max(jnp.abs(x)))
    wm = update_meta(Fp8Meta.init(), jnp.max(jnp.abs(w)))

    y = fp8_matmul_t(x, w, xm, wm)
    y_ref = x @ w.T
    assert y.shape == y_ref.shape
    rel = np.abs(np.asarray(y - y_ref)) / (np.abs(np.asarray(y_ref)) + 1e-3)
    assert np.median(rel) < 0.1

    def loss8(x, w):
        return jnp.sum(fp8_matmul_t(x, w, xm, wm) ** 2)

    def loss_ref(x, w):
        return jnp.sum((x @ w.T) ** 2)

    gx, gw = jax.grad(loss8, argnums=(0, 1))(x, w)
    gx_ref, gw_ref = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    for g, g_ref in ((gx, gx_ref), (gw, gw_ref)):
        rel = np.abs(np.asarray(g - g_ref)) / (
            np.abs(np.asarray(g_ref)) + 1e-2)
        assert np.median(rel) < 0.15


def _gpt_cfg(**kw):
    from apex_tpu.transformer.testing import TransformerConfig

    kw.setdefault("num_layers", 2)
    return TransformerConfig(
        hidden_size=64, num_attention_heads=4,
        padded_vocab_size=256, max_position_embeddings=32,
        hidden_dropout=0.0, attention_dropout=0.0, **kw)


def _train_gpt(cfg, tokens, steps=10, seed=0):
    """Train a GPT with the fp8_meta collection threaded through the step;
    returns the per-step losses and the final fp8 state."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import GPTModel

    model = GPTModel(cfg)
    variables = model.init(jax.random.PRNGKey(seed), tokens)
    params = variables["params"]
    fp8_state = dict(variables.get("fp8_meta", {}))
    opt = FusedAdam(lr=1e-3)
    ostate = opt.init(params)

    @jax.jit
    def step(params, ostate, fp8_state):
        def loss_fn(p):
            if fp8_state:
                losses, mut = model.apply(
                    {"params": p, "fp8_meta": fp8_state},
                    tokens, labels=tokens, mutable=["fp8_meta"])
                return jnp.mean(losses), dict(mut)
            losses = model.apply({"params": p}, tokens, labels=tokens)
            return jnp.mean(losses), {}
        (l, mut), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        params, ostate = opt.step(g, ostate, params)
        return params, ostate, mut.get("fp8_meta", fp8_state), l

    losses = []
    for _ in range(steps):
        params, ostate, fp8_state, loss = step(params, ostate, fp8_state)
        losses.append(float(loss))
    return losses, fp8_state


@pytest.mark.slow
def test_fp8_gpt_trains():
    """e2e: TransformerConfig(fp8=True) routes the four transformer-layer
    GEMMs through fp8_matmul_t; the model trains (loss decreases), tracks
    the bf16 run closely, and the delayed scales adapt."""
    tokens = jax.random.randint(jax.random.PRNGKey(42), (4, 32), 0, 256)

    losses8, fp8_state = _train_gpt(
        _gpt_cfg(fp8=True, tensor_axis=None), tokens)
    losses_ref, ref_state = _train_gpt(
        _gpt_cfg(fp8=False, tensor_axis=None), tokens)

    assert not ref_state  # bf16 run has no fp8 collection
    assert losses8[-1] < losses8[0]  # trains
    # same init/data: first-step losses nearly identical, trajectory close
    assert losses8[0] == pytest.approx(losses_ref[0], rel=0.05)
    assert losses8[-1] == pytest.approx(losses_ref[-1], rel=0.10)

    # every transformer-layer GEMM carries adapted delayed scales:
    # 2 layers x (qkv, attn out, fc1, fc2) = 8 meta dicts
    leaves = [m for path, m in jax.tree_util.tree_leaves_with_path(
        fp8_state, is_leaf=lambda x: isinstance(x, Fp8Meta))
        if isinstance(m, Fp8Meta)]
    assert len(leaves) == 16  # 8 GEMMs x {x, w}
    assert all(float(m.scale) != 1.0 for m in leaves)


@pytest.mark.slow
def test_fp8_gpt_inference_without_mutable():
    """Plain apply() (no mutable) must work for eval/serving: the delayed
    scales are read but not rolled (r3 review finding — _fp8_roll used to
    write unconditionally and raise ModifyScopeVariableError)."""
    from apex_tpu.transformer.testing import GPTModel

    cfg = _gpt_cfg(fp8=True, tensor_axis=None)
    model = GPTModel(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    variables = model.init(jax.random.PRNGKey(0), tokens)

    logits = model.apply(
        {"params": variables["params"], "fp8_meta": variables["fp8_meta"]},
        tokens)  # no mutable: frozen scales, plain output
    assert logits.shape == (32, 2, 256)
    assert bool(jnp.all(jnp.isfinite(logits.astype(jnp.float32))))


@pytest.mark.slow
def test_fp8_gpt_tp_amax_sharing():
    """Under tp=2 the per-rank amaxes are pmax-shared over the tensor axis
    (the reference's amax groups): every rank ends with identical delayed
    scales even though weight shards differ per rank."""
    from apex_tpu.transformer import tensor_parallel as tp
    from apex_tpu.transformer.testing import GPTModel

    parallel.initialize_model_parallel(tensor_model_parallel_size=2)
    try:
        # one layer: the pmax-sharing property is per-GEMM; a second layer
        # only doubles the (expensive) shard_map compiles
        cfg = _gpt_cfg(fp8=True, tensor_axis="tp", num_layers=1)
        model = GPTModel(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 32), 0, 256)

        def tp_init(tokens):
            return model.init(jax.random.PRNGKey(0), tokens)

        shapes = jax.eval_shape(tp_init, tokens)
        param_specs = tp.infer_param_specs(shapes["params"])
        meta_specs = jax.tree_util.tree_map(lambda _: P(), shapes["fp8_meta"])
        variables = cc.shard_over(
            tp_init, in_specs=P(),
            out_specs={"params": param_specs, "fp8_meta": meta_specs},
        )(tokens)

        def fwd(params, fp8_state, tokens):
            _, mut = model.apply(
                {"params": params, "fp8_meta": fp8_state},
                tokens, labels=tokens, mutable=["fp8_meta"])
            new = dict(mut)["fp8_meta"]
            # stack each rank's scalar scale so the test can compare ranks
            return jax.tree_util.tree_map(
                lambda m: m.scale[None], new,
                is_leaf=lambda x: isinstance(x, Fp8Meta))

        scale_specs = jax.tree_util.tree_map(
            lambda _: P("tp"), shapes["fp8_meta"],
            is_leaf=lambda x: isinstance(x, Fp8Meta))
        per_rank = cc.shard_over(
            fwd,
            in_specs=(param_specs, meta_specs, P()),
            out_specs=scale_specs,
        )(variables["params"], variables["fp8_meta"], tokens)

        for path, scales in jax.tree_util.tree_leaves_with_path(per_rank):
            arr = np.asarray(scales)
            assert arr.shape[0] == 2
            np.testing.assert_allclose(arr[0], arr[1], rtol=0, atol=0,
                                       err_msg=str(path))
            assert arr[0] != 1.0  # the scale really updated

        # and the *training* path differentiates: the amax pmax is pure
        # bookkeeping (stop_gradient inside update_meta), so grad through
        # the step with the rolled metas as aux must work (r3 dryrun
        # regression: 'Differentiation rule for pmax not implemented')
        def train_local(params, fp8_state, tokens):
            def loss_fn(p):
                losses, mut = model.apply(
                    {"params": p, "fp8_meta": fp8_state}, tokens,
                    labels=tokens, mutable=["fp8_meta"])
                return jax.lax.pmean(jnp.mean(losses), "tp"), (
                    dict(mut)["fp8_meta"])

            (loss, new_meta), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return loss, grads

        loss, grads = cc.shard_over(
            train_local,
            in_specs=(param_specs, meta_specs, P()),
            out_specs=(P(), param_specs),
        )(variables["params"], variables["fp8_meta"], tokens)
        assert np.isfinite(float(loss))
        assert all(bool(jnp.all(jnp.isfinite(g)))
                   for g in jax.tree_util.tree_leaves(grads))
    finally:
        parallel.destroy_model_parallel()
