"""Native JPEG decode kernel (``_native/jpegdec.c``) and its loader wiring.

The kernel is the decode stage of the input pipeline the reference recipe
gets from DataLoader workers/DALI (``examples/imagenet/main_amp.py:207-232``):
DCT-scaled decode fused with crop + bilinear resize.  Bit-exactness with
PIL is a non-goal (different resamplers: PIL's BILINEAR is an antialiased
filter, the kernel point-samples); the contract tested here is
  - geometry: same crop region, same output shape, close pixels on
    smooth images;
  - the augmentation RNG stream is identical on the native and PIL paths
    (same boxes, same flips), so swapping decoders never changes the
    data order or the draw sequence;
  - every failure (corrupt file, CMYK, non-JPEG) degrades to PIL
    per-image, never raises out of the loader.
"""

import io
import os

import numpy as np
import pytest
from PIL import Image

from apex_tpu.data import _jpeg_native as jn
from apex_tpu.data import (
    ImageFolder,
    ImageFolderLoader,
    center_crop_resize,
    random_resized_crop,
    sample_crop_box,
)

pytestmark = pytest.mark.skipif(
    not jn.native_available(), reason="no cc/libjpeg: native decode absent")


def smooth_image(h, w):
    yy, xx = np.mgrid[0:h, 0:w]
    return np.stack([xx * 255 // max(w, 1), yy * 255 // max(h, 1),
                     (xx + yy) * 255 // (h + w)], -1).astype(np.uint8)


def jpeg_bytes(arr, quality=95):
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def test_dims():
    data = jpeg_bytes(smooth_image(300, 400))
    assert jn.jpeg_dims(data) == (300, 400)
    assert jn.jpeg_dims(data[:50]) is None


@pytest.mark.parametrize("src,crop,out", [
    ((300, 400), (10, 20, 280, 360), 224),   # downscale, 7/8 DCT scale
    ((600, 800), (0, 0, 600, 800), 224),     # deep downscale, <=4/8
    ((100, 120), (5, 5, 90, 110), 224),      # upscale (crop < out)
    ((300, 300), (140, 140, 20, 20), 64),    # tiny crop upscaled
])
def test_decode_matches_pil_geometry(src, crop, out):
    arr = smooth_image(*src)
    data = jpeg_bytes(arr)
    cy, cx, ch, cw = crop
    got = jn.decode_crop_resize(data, cy, cx, ch, cw, out, out)
    assert got.shape == (out, out, 3) and got.dtype == np.uint8
    img = Image.open(io.BytesIO(data)).convert("RGB")
    ref = np.asarray(
        img.crop((cx, cy, cx + cw, cy + ch)).resize((out, out),
                                                    Image.BILINEAR),
        np.uint8)
    # smooth content: resampler differences stay small
    assert np.abs(got.astype(int) - ref.astype(int)).mean() < 4.0


def test_hflip_is_exact_mirror():
    data = jpeg_bytes(smooth_image(200, 260))
    a = jn.decode_crop_resize(data, 8, 12, 180, 240, 128, 128)
    b = jn.decode_crop_resize(data, 8, 12, 180, 240, 128, 128, hflip=True)
    assert np.array_equal(b, a[:, ::-1])


def test_grayscale_promoted_to_rgb():
    arr = smooth_image(180, 220)[:, :, 0]
    buf = io.BytesIO()
    Image.fromarray(arr, "L").save(buf, format="JPEG", quality=95)
    got = jn.decode_crop_resize(buf.getvalue(), 0, 0, 180, 220, 96, 96)
    assert got.shape == (96, 96, 3)
    assert np.ptp(got[..., 0].astype(int) - got[..., 1].astype(int)) <= 2


def test_failures_return_none():
    data = jpeg_bytes(smooth_image(100, 100))
    assert jn.decode_crop_resize(data[:60], 0, 0, 50, 50, 32, 32) is None
    assert jn.decode_crop_resize(b"not a jpeg", 0, 0, 1, 1, 8, 8) is None
    # out-of-bounds crop is an argument error, not a crash
    assert jn.decode_crop_resize(data, 90, 90, 50, 50, 32, 32) is None
    assert jn.decode_crop_resize(data, 0, 0, 0, 10, 8, 8) is None


def test_truncated_body_is_rejected_not_gray_padded():
    """libjpeg fakes an EOI for streams cut mid-scan and pads gray; the
    kernel must report that (rc!=0 -> None), not return garbage rows."""
    data = jpeg_bytes(smooth_image(300, 300), quality=95)
    # cut inside the entropy-coded body (past the headers)
    for frac in (0.4, 0.7, 0.95):
        cut = data[:int(len(data) * frac)]
        assert jn.decode_crop_resize(cut, 0, 0, 300, 300, 128, 128) is None


def _folder(tmp_path, n_classes=2, per_class=6, sizes=((240, 300),)):
    for c in range(n_classes):
        d = tmp_path / f"class_{c}"
        d.mkdir()
        for i in range(per_class):
            h, w = sizes[i % len(sizes)]
            Image.fromarray(smooth_image(h, w)).save(
                str(d / f"{i}.jpg"), quality=95)
    return ImageFolder(str(tmp_path))


def _collect(loader, n):
    it = iter(loader)
    return [next(it) for _ in range(n)]


@pytest.mark.parametrize("train", [True, False])
def test_loader_native_vs_pil_same_stream(tmp_path, train):
    ds = _folder(tmp_path, sizes=((240, 300), (320, 260)))
    kw = dict(local_batch=4, image_size=64, train=train, workers=2,
              seed=7, prefetch=1)
    with ImageFolderLoader(ds, native=True, **kw) as nat, \
            ImageFolderLoader(ds, native=False, **kw) as pil:
        assert nat._native and not pil._native
        for (xn, yn), (xp, yp) in zip(_collect(nat, 2), _collect(pil, 2)):
            # identical sample order + labels (same sampler draw),
            # identical shapes, close pixels (different resamplers)
            assert np.array_equal(yn, yp)
            assert xn.shape == xp.shape
            assert np.abs(xn.astype(int) - xp.astype(int)).mean() < 6.0


def test_loader_native_is_deterministic(tmp_path):
    ds = _folder(tmp_path)
    kw = dict(local_batch=4, image_size=64, train=True, workers=2, seed=3)
    with ImageFolderLoader(ds, **kw) as a, ImageFolderLoader(ds, **kw) as b:
        for (xa, ya), (xb, yb) in zip(_collect(a, 2), _collect(b, 2)):
            assert np.array_equal(xa, xb) and np.array_equal(ya, yb)


def test_loader_corrupt_file_falls_back_without_stream_skew(tmp_path):
    """A truncated JPEG must decode via PIL (PIL tolerates truncation with
    LOAD_TRUNCATED_IMAGES off -> raises; our loader falls back per-image
    only when native fails, so make the file valid-for-PIL but
    native-feasible) — here we check the RNG-restore contract instead:
    native failure after the box draws hands PIL the same stream."""
    ds = _folder(tmp_path, n_classes=1, per_class=4)
    # overwrite one sample with a PNG disguised as .jpg: native rejects
    # (header parse fails before any RNG draw), PIL decodes fine
    path, _ = ds.samples[1]
    Image.fromarray(smooth_image(240, 300)).save(path, format="PNG")
    kw = dict(local_batch=4, image_size=64, train=True, workers=2, seed=5)
    with ImageFolderLoader(ds, native=True, **kw) as nat, \
            ImageFolderLoader(ds, native=False, **kw) as pil:
        (xn, yn), = _collect(nat, 1)
        (xp, yp), = _collect(pil, 1)
        assert np.array_equal(yn, yp)
        assert np.abs(xn.astype(int) - xp.astype(int)).mean() < 6.0


def test_eval_crop_region_matches_pil_semantics():
    """Eval path: native's source-coordinate center crop covers the same
    region as Resize(256)+CenterCrop(224)."""
    arr = smooth_image(375, 500)
    data = jpeg_bytes(arr)
    got = None
    h, w = 375, 500
    size, resize = 224, 256
    short = min(w, h)
    side = min(int(round(short * size / resize)), short)
    x0, y0 = (w - side) // 2, (h - side) // 2
    got = jn.decode_crop_resize(data, y0, x0, side, side, size, size)
    img = Image.open(io.BytesIO(data)).convert("RGB")
    ref = center_crop_resize(img, size)
    assert got.shape == ref.shape
    assert np.abs(got.astype(int) - ref.astype(int)).mean() < 6.0


def test_sample_crop_box_stream_stability():
    """Pin the RNG draw-count contract: the PIL path
    (random_resized_crop) consumes exactly sample_crop_box's draws plus
    ONE flip draw — the native path's accounting.  If either side's
    draw count drifts, the two augmentation streams desync and this
    equality fails."""
    for seed in (11, 12, 13, 99):
        rng1 = np.random.RandomState(seed)
        rng2 = np.random.RandomState(seed)
        x0, y0, cw, ch = sample_crop_box(rng1, 300, 240)
        assert 0 <= x0 <= 300 - cw and 0 <= y0 <= 240 - ch
        rng1.rand()  # the flip draw the loader's native path performs
        img = Image.fromarray(smooth_image(240, 300))
        random_resized_crop(rng2, img, 64)
        # streams aligned again -> next draws identical
        assert rng1.rand() == rng2.rand()


def test_fallback_crop_is_ratio_clamped():
    """10 rejected draws -> torchvision's fallback: whole image when its
    aspect is within ratio bounds, largest in-bounds region otherwise."""
    class NoFit:
        """rng whose draws always request more area than the image has"""
        def uniform(self, a, b):
            return b
        def randint(self, a, b=None):
            return a
        def rand(self):
            return 0.9

    # 300x240 (ratio 1.25, inside (3/4, 4/3)): full image kept
    x0, y0, cw, ch = sample_crop_box(NoFit(), 300, 240, scale=(2.0, 2.0))
    assert (x0, y0, cw, ch) == (0, 0, 300, 240)
    # 600x200 (ratio 3.0 > 4/3): height-bound, width clamped to 4/3*h
    x0, y0, cw, ch = sample_crop_box(NoFit(), 600, 200, scale=(2.0, 2.0))
    assert ch == 200 and cw == int(round(200 * 4 / 3)) and y0 == 0
    # 200x600 (ratio 1/3 < 3/4): width-bound, height clamped to w/(3/4)
    x0, y0, cw, ch = sample_crop_box(NoFit(), 200, 600, scale=(2.0, 2.0))
    assert cw == 200 and ch == int(round(200 / (3 / 4))) and x0 == 0
