"""Megatron pretraining samplers (reference
``tests/L0/run_transformer/test_batch_sampler.py`` style)."""

import numpy as np
import pytest

from apex_tpu.transformer._data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)


def test_contiguous_sampler_disjoint_cover():
    dp, lmb, total = 4, 2, 32
    per_rank = [list(MegatronPretrainingSampler(
        total_samples=total, consumed_samples=0, local_minibatch_size=lmb,
        data_parallel_rank=r, data_parallel_size=dp)) for r in range(dp)]
    # every rank yields the same number of equal-size minibatches
    assert all(len(b) == total // (dp * lmb) for b in per_rank)
    for step in range(total // (dp * lmb)):
        got = sorted(i for r in range(dp) for i in per_rank[r][step])
        lo = step * dp * lmb
        assert got == list(range(lo, lo + dp * lmb))


def test_contiguous_sampler_resume_and_drop_last():
    s = MegatronPretrainingSampler(
        total_samples=10, consumed_samples=4, local_minibatch_size=2,
        data_parallel_rank=0, data_parallel_size=2, drop_last=False)
    batches = list(s)
    assert batches[0] == [4, 5]   # resumes at consumed_samples
    # tail: samples 8,9 form a partial global batch; rank 0 gets [8, 9]
    assert batches[-1] == [8, 9]
    # with drop_last (default) the partial tail disappears
    s2 = MegatronPretrainingSampler(
        total_samples=10, consumed_samples=4, local_minibatch_size=2,
        data_parallel_rank=0, data_parallel_size=2)
    assert list(s2) == [[4, 5]]


def test_random_sampler_determinism_and_shards():
    dp, lmb, total = 2, 4, 64
    runs = []
    for r in range(dp):
        s = MegatronPretrainingRandomSampler(
            total_samples=total, consumed_samples=0,
            local_minibatch_size=lmb, data_parallel_rank=r,
            data_parallel_size=dp)
        runs.append(list(s))
    # same epoch seed -> rerun identical
    s0b = list(MegatronPretrainingRandomSampler(
        total_samples=total, consumed_samples=0, local_minibatch_size=lmb,
        data_parallel_rank=0, data_parallel_size=dp))
    assert runs[0] == s0b
    # ranks draw from disjoint contiguous buckets
    flat = [set(i for b in run for i in b) for run in runs]
    assert flat[0].isdisjoint(flat[1])
    assert all(i < 32 for i in flat[0]) and all(i >= 32 for i in flat[1])


def test_random_sampler_epoch_reshuffle_and_resume():
    total, lmb = 64, 4
    a = MegatronPretrainingRandomSampler(
        total_samples=total, consumed_samples=0, local_minibatch_size=lmb,
        data_parallel_rank=0, data_parallel_size=2)
    epoch0 = list(a)
    # consumed a full epoch -> next iteration reshuffles with new seed
    b = MegatronPretrainingRandomSampler(
        total_samples=total, consumed_samples=total,
        local_minibatch_size=lmb, data_parallel_rank=0,
        data_parallel_size=2)
    epoch1 = list(b)
    assert epoch0 != epoch1
    # mid-epoch resume: consumed 16 (= 8 per rank) skips first 2 batches
    c = MegatronPretrainingRandomSampler(
        total_samples=total, consumed_samples=16, local_minibatch_size=lmb,
        data_parallel_rank=0, data_parallel_size=2)
    assert list(c) == epoch0[2:]


def test_sampler_validation():
    with pytest.raises(ValueError):
        MegatronPretrainingSampler(0, 0, 2, 0, 2)
    with pytest.raises(ValueError):
        MegatronPretrainingSampler(8, 8, 2, 0, 2)
    with pytest.raises(ValueError):
        MegatronPretrainingSampler(8, 0, 2, 2, 2)
