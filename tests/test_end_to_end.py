"""End-to-end slice tests — the L1-style integration tier (SURVEY.md §4.3):
examples must train with loss decreasing under each opt level."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, parallel
from apex_tpu.models import ResNet18
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import dp_shard_batch, replicate

pytestmark = pytest.mark.slow


class TestSimpleDistributed:
    def test_example_trains(self):
        from examples.simple_distributed import main

        final = main(steps=40)
        assert final < 0.5  # 1.0 at init; clear learning in 40 bf16 steps


class TestResNetSlice:
    @pytest.mark.parametrize("opt_level", ["O0", "O2"])
    def test_resnet18_syncbn_trains(self, opt_level):
        """Mini imagenet slice: ResNet-18, 32x32, SyncBN over dp, amp policy."""
        mesh = parallel.initialize_model_parallel()
        policy = amp.policy(opt_level)
        # pjit style: batch is a global dp-sharded array, so BN stats are
        # global (SyncBN) without axis_name
        model = ResNet18(num_classes=10, axis_name=None,
                         dtype=policy.compute_dtype)

        rng = np.random.RandomState(0)
        X = rng.randn(16, 32, 32, 3).astype(np.float32)
        # learnable signal: class = sign of channel mean
        Y = (X.mean((1, 2, 3)) > 0).astype(np.int64)

        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((2, 32, 32, 3)), train=True)
        params = policy.cast_to_param(variables["params"])
        batch_stats = variables["batch_stats"]
        opt = FusedSGD(lr=0.02, momentum=0.9,
                       master_weights=policy.master_weights)
        opt_state = opt.init(params)

        def loss_fn(params, batch_stats, batch):
            x, y = batch
            logits, mut = model.apply(
                {"params": params, "batch_stats": batch_stats},
                policy.cast_to_compute(x), train=True,
                mutable=["batch_stats"],
            )
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(y.shape[0]), y]), mut["batch_stats"]

        @jax.jit
        def step(params, batch_stats, opt_state, batch):
            (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch_stats, batch
            )
            params, opt_state = opt.step(grads, opt_state, params)
            return params, stats, opt_state, loss

        params = replicate(params, mesh)
        batch_stats = replicate(batch_stats, mesh)
        opt_state = replicate(opt_state, mesh)
        batch = dp_shard_batch((jnp.asarray(X), jnp.asarray(Y)), mesh)

        losses = []
        for _ in range(6):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, batch
            )
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], f"no learning: {losses}"

        if opt_level == "O2":
            # norm params stayed fp32 under O2 (keep_batchnorm_fp32)
            flat = jax.tree_util.tree_leaves_with_path(params)
            bn_scale = [
                v for p, v in flat
                if "bn_init" in jax.tree_util.keystr(p) and v.dtype == jnp.float32
            ]
            assert bn_scale, "expected fp32 norm params under O2"


class TestExampleCLIs:
    """The examples run end-to-end on synthetic data (CI contract of
    VERDICT r2 item 5; real-data invocations documented in each file)."""

    def test_imagenet_amp_synthetic(self):
        from examples.imagenet_amp import main

        ips = main(["--arch", "resnet18", "--batch-size", "8",
                    "--image-size", "32", "--num-classes", "10",
                    "--steps", "4"])
        assert ips > 0

    def test_imagenet_amp_real_data_loader(self, tmp_path):
        """--data path: ImageFolder -> sharded uint8 batches -> O2 step."""
        from PIL import Image

        rng = np.random.RandomState(0)
        for cls in ("a", "b"):
            (tmp_path / cls).mkdir()
            for i in range(12):
                arr = rng.randint(0, 256, (48, 48, 3), dtype=np.uint8)
                Image.fromarray(arr).save(tmp_path / cls / f"{i}.png")

        from examples.imagenet_amp import main

        ips = main(["--data", str(tmp_path), "--arch", "resnet18",
                    "--batch-size", "8", "--image-size", "32",
                    "--num-classes", "2", "--steps", "3", "--workers", "2"])
        assert ips > 0

    def test_imagenet_amp_evaluate(self, tmp_path, capsys):
        """--evaluate: train/val layout, full-coverage top-k validation
        incl. a val set smaller than one batch (padded+masked tail)."""
        from PIL import Image

        rng = np.random.RandomState(1)
        for split, per_cls in (("train", 12), ("val", 5)):
            for ci, cls in enumerate(("dark", "bright")):
                d = tmp_path / split / cls
                d.mkdir(parents=True)
                lo, hi = (0, 100) if ci == 0 else (156, 256)
                for i in range(per_cls):
                    arr = rng.randint(lo, hi, (48, 48, 3), dtype=np.uint8)
                    Image.fromarray(arr).save(d / f"{i}.png")

        from examples.imagenet_amp import main

        # val set (10) < batch (16): exercises the padded/masked tail
        main(["--data", str(tmp_path), "--arch", "resnet18",
              "--batch-size", "16", "--image-size", "32",
              "--num-classes", "2", "--steps", "25", "--lr", "0.01",
              "--workers", "2", "--evaluate"])
        out = capsys.readouterr().out
        line = [l for l in out.splitlines() if "validation:" in l]
        assert line, out
        prec1 = float(line[0].split("prec@1")[1].split()[0])
        assert prec1 >= 0.8, line[0]  # separable classes: learned

    def test_dcgan_amp(self):
        from examples.dcgan_amp import main

        errD, errG = main(["--steps", "4", "--batch-size", "4",
                           "--ngf", "8", "--ndf", "8"])
        assert np.isfinite(errD) and np.isfinite(errG)
