"""Contrib kernel tier numerics: GroupNorm NHWC(+SiLU), focal loss,
index_mul_2d, transducer joint+loss — each vs a pure-jnp/numpy reference
(the reference tests them against python impls the same way,
``apex/contrib/test/*``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.group_norm import GroupNorm, group_norm_nhwc
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.transducer import (
    TransducerJoint,
    transducer_joint,
    transducer_loss,
)


# ---------------------------------------------------------------- group norm

@pytest.mark.parametrize("act", ["", "silu"])
def test_group_norm_matches_reference(act):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 4, 16).astype(np.float32)
    w = rng.randn(16).astype(np.float32)
    b = rng.randn(16).astype(np.float32)
    out = group_norm_nhwc(jnp.asarray(x), 4, w, b, eps=1e-5, act=act)

    # reference: torch.nn.GroupNorm semantics in numpy (NCHW order)
    xr = x.transpose(0, 3, 1, 2).reshape(2, 4, 4 * 4 * 4)
    mean = xr.mean(axis=2, keepdims=True)
    var = xr.var(axis=2, keepdims=True)
    ref = ((xr - mean) / np.sqrt(var + 1e-5)).reshape(2, 16, 4, 4)
    ref = ref * w[None, :, None, None] + b[None, :, None, None]
    if act == "silu":
        ref = ref * (1 / (1 + np.exp(-ref)))
    ref = ref.transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_group_norm_module_and_bf16():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 32), jnp.bfloat16)
    gn = GroupNorm(num_groups=8, num_channels=32, act="silu")
    params = gn.init(jax.random.PRNGKey(1), x)
    out = gn.apply(params, x)
    assert out.dtype == jnp.bfloat16 and out.shape == x.shape
    # stats in fp32: per-group mean ~0 before affine regardless of bf16 input
    plain = group_norm_nhwc(x, 8)
    g = np.asarray(plain, np.float32).reshape(2, 64, 8, 4)
    assert abs(g.mean()) < 1e-2


# ---------------------------------------------------------------- focal loss

def _focal_ref(x, y, npos, K_real, alpha, gamma, s):
    """Direct per-element reference following the CUDA kernel conventions."""
    total = 0.0
    N, K = x.shape
    for i in range(N):
        if y[i] == -2:
            continue
        for c in range(min(K, K_real)):
            p = float(x[i, c])
            sig = 1 / (1 + np.exp(-p))
            pos = y[i] >= 0 and c == y[i]
            q = (1 - s + s / K_real) if pos else s / K_real
            bce = np.log1p(np.exp(-abs(p))) + max(p, 0) - q * p
            coeff = alpha * (1 - sig) ** gamma if pos \
                else (1 - alpha) * sig ** gamma
            total += coeff * bce
    return total / npos


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_focal_loss_matches_reference(smoothing):
    rng = np.random.RandomState(1)
    N, K = 32, 8
    x = rng.randn(N, K).astype(np.float32) * 2
    y = rng.randint(-2, K - 1, size=(N,))  # mix of ignore/negative/positive
    npos = max((y >= 0).sum(), 1)
    got = focal_loss(jnp.asarray(x), jnp.asarray(y), float(npos),
                     num_real_classes=K - 1, alpha=0.25, gamma=2.0,
                     label_smoothing=smoothing)
    ref = _focal_ref(x, y, float(npos), K - 1, 0.25, 2.0, smoothing)
    np.testing.assert_allclose(float(got), ref, rtol=1e-5)

    # ignored anchors contribute zero gradient
    g = jax.grad(lambda x: focal_loss(x, jnp.asarray(y), float(npos),
                                      K - 1, 0.25, 2.0, smoothing))(
        jnp.asarray(x))
    g = np.asarray(g)
    assert np.all(g[y == -2] == 0)
    assert np.all(g[:, K - 1:] == 0)  # pad class
    assert np.any(g[y != -2][:, :K - 1] != 0)


# ------------------------------------------------------------- index_mul_2d

def test_index_mul_2d_forward_and_grads():
    rng = np.random.RandomState(2)
    in1 = jnp.asarray(rng.randn(10, 8).astype(np.float32))
    in2 = jnp.asarray(rng.randn(6, 8).astype(np.float32))
    idx = jnp.asarray(rng.randint(0, 10, size=(6,)))

    out = index_mul_2d(in1, in2, idx)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(in1)[np.asarray(idx)] * np.asarray(in2))

    w = jnp.asarray(rng.randn(6, 8).astype(np.float32))
    g1, g2 = jax.grad(lambda a, b: jnp.sum(index_mul_2d(a, b, idx) * w),
                      argnums=(0, 1))(in1, in2)
    # scatter-add reference for grad_in1
    ref1 = np.zeros((10, 8), np.float32)
    np.add.at(ref1, np.asarray(idx), np.asarray(w) * np.asarray(in2))
    np.testing.assert_allclose(np.asarray(g1), ref1, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g2),
                               np.asarray(in1)[np.asarray(idx)] * np.asarray(w),
                               rtol=1e-6)

    with pytest.raises(ValueError):
        index_mul_2d(in1[0], in2, idx)


# ----------------------------------------------------------------- transducer

def test_transducer_joint():
    rng = np.random.RandomState(3)
    f = jnp.asarray(rng.randn(2, 5, 8).astype(np.float32))
    g = jnp.asarray(rng.randn(2, 4, 8).astype(np.float32))
    out = transducer_joint(f, g)
    ref = np.asarray(f)[:, :, None, :] + np.asarray(g)[:, None, :, :]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)

    f_len = jnp.asarray([5, 3])
    g_len = jnp.asarray([3, 2])
    joint = TransducerJoint(relu=True)
    out = joint(f, g, f_len, g_len)
    assert np.all(np.asarray(out) >= 0)
    assert np.all(np.asarray(out)[1, 3:] == 0)      # t >= f_len zeroed
    assert np.all(np.asarray(out)[1, :, 3:] == 0)   # u >= g_len+1 zeroed

    with pytest.raises(NotImplementedError):
        TransducerJoint(pack_output=True)


def _naive_rnnt_loss(logp, label, T, U):
    """Plain-python alpha recursion on log-probs [T, U+1, K]."""
    import math
    alpha = np.full((T, U + 1), -np.inf)
    alpha[0, 0] = 0.0
    blank = logp[..., -1]  # tests put blank at the last index
    for t in range(T):
        for u in range(U + 1):
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + blank[t - 1, u])
            if u > 0:
                cands.append(alpha[t, u - 1] + logp[t, u - 1, label[u - 1]])
            if cands:
                m = max(cands)
                alpha[t, u] = m + math.log(sum(math.exp(c - m)
                                               for c in cands))
    return -(alpha[T - 1, U] + blank[T - 1, U])


def test_transducer_loss_matches_naive_dp():
    rng = np.random.RandomState(4)
    B, T, U, K = 3, 6, 4, 5
    x = rng.randn(B, T, U + 1, K).astype(np.float32)
    label = rng.randint(0, K - 1, size=(B, U))
    f_len = np.array([6, 4, 5])
    y_len = np.array([4, 2, 3])
    blank = K - 1

    got = transducer_loss(jnp.asarray(x), jnp.asarray(label),
                          jnp.asarray(f_len), jnp.asarray(y_len), blank)
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(x), axis=-1))
    for b in range(B):
        ref = _naive_rnnt_loss(logp[b, :f_len[b], :y_len[b] + 1],
                               label[b], f_len[b], y_len[b])
        np.testing.assert_allclose(float(got[b]), ref, rtol=1e-4)


def test_transducer_loss_gradients_match_naive():
    """Autodiff through the wavefront scan == autodiff through an unrolled
    python DP (same math, independent structure)."""
    rng = np.random.RandomState(5)
    B, T, U, K = 2, 4, 3, 4
    x = jnp.asarray(rng.randn(B, T, U + 1, K).astype(np.float32))
    label = jnp.asarray(rng.randint(0, K - 1, size=(B, U)))
    f_len = jnp.asarray([4, 3])
    y_len = jnp.asarray([3, 2])
    blank = K - 1

    def unrolled(x):
        logp = jax.nn.log_softmax(x, axis=-1)
        total = 0.0
        for b in range(B):
            Tb, Ub = int(f_len[b]), int(y_len[b])
            alpha = {}
            alpha[(0, 0)] = 0.0
            for t in range(Tb):
                for u in range(Ub + 1):
                    if t == 0 and u == 0:
                        continue
                    cands = []
                    if t > 0:
                        cands.append(alpha[(t - 1, u)]
                                     + logp[b, t - 1, u, blank])
                    if u > 0:
                        cands.append(alpha[(t, u - 1)]
                                     + logp[b, t, u - 1, label[b, u - 1]])
                    alpha[(t, u)] = (cands[0] if len(cands) == 1
                                     else jnp.logaddexp(*cands))
            total = total - (alpha[(Tb - 1, Ub)]
                             + logp[b, Tb - 1, Ub, blank])
        return total

    def scanned(x):
        return jnp.sum(transducer_loss(x, label, f_len, y_len, blank))

    np.testing.assert_allclose(float(scanned(x)), float(unrolled(x)),
                               rtol=1e-5)
    g_scan = jax.grad(scanned)(x)
    g_ref = jax.grad(unrolled)(x)
    np.testing.assert_allclose(np.asarray(g_scan), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)
