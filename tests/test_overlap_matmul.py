"""Unit coverage for the ring-decomposed collective matmul (ISSUE 2).

Fast tier: exercises :func:`gather_matmul` / :func:`matmul_scatter` directly
against their monolithic definitions (``all_gather . matmul`` /
``matmul . reduce_scatter``) on the virtual CPU mesh — values, grads, the
fp8 composition, the :func:`ring_chunks` layout helper, and the
HLO-level proof that the decomposition survives jit (via
:mod:`apex_tpu.testing.hlo`).  The layer/model-level parity suite lives in
``tests/test_tensor_parallel.py`` (slow tier).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.analysis import (
    compiled_hlo,
    count_hlo_ops,
    hlo_op_counts,
    lint_hlo,
)
from apex_tpu.parallel import collectives as cc
from apex_tpu.transformer.tensor_parallel.overlap import (
    gather_matmul,
    matmul_scatter,
)


@pytest.fixture(params=[2, 4])
def tp_mesh(request):
    yield parallel.initialize_model_parallel(
        tensor_model_parallel_size=request.param), request.param
    parallel.destroy_model_parallel()


def _data(key, s=16, b=3, din=8, dout=24):
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (s, b, din), jnp.float32)
    w = jax.random.normal(k2, (dout, din), jnp.float32) / np.sqrt(din)
    return x, w


def test_ring_chunks_layout():
    x = jnp.arange(24.0).reshape(6, 4)
    c0 = cc.ring_chunks(x, 3, 0)
    assert c0.shape == (3, 2, 4)
    np.testing.assert_array_equal(np.asarray(c0[1]), np.asarray(x[2:4]))
    c1 = cc.ring_chunks(x, 2, 1)
    assert c1.shape == (2, 6, 2)
    np.testing.assert_array_equal(np.asarray(c1[1]), np.asarray(x[:, 2:]))
    with pytest.raises(ValueError):
        cc.ring_chunks(x, 5, 0)


def test_gather_matmul_matches_allgather_gemm(tp_mesh):
    """Ring == all_gather(x) @ w.T, values and both grads."""
    _, tp_size = tp_mesh
    x, w = _data(jax.random.PRNGKey(0))

    ring = cc.shard_over(
        lambda xs, ws: gather_matmul(xs, ws, "tp"),
        in_specs=(P("tp", None, None), P("tp", None)),
        out_specs=P(None, None, "tp"),
    )
    mono = cc.shard_over(
        lambda xs, ws: jnp.matmul(
            cc.all_gather(xs, "tp", concat_axis=0), ws.T),
        in_specs=(P("tp", None, None), P("tp", None)),
        out_specs=P(None, None, "tp"),
    )
    np.testing.assert_allclose(np.asarray(ring(x, w)),
                               np.asarray(mono(x, w)),
                               rtol=1e-5, atol=1e-6)

    def loss(f):
        return lambda x, w: jnp.sum(jnp.sin(f(x, w)))

    g_ring = jax.grad(loss(ring), argnums=(0, 1))(x, w)
    g_mono = jax.grad(loss(mono), argnums=(0, 1))(x, w)
    for a, b in zip(g_ring, g_mono):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_matmul_scatter_matches_gemm_reduce_scatter(tp_mesh):
    """Ring == reduce_scatter(x @ w.T), values and both grads."""
    _, tp_size = tp_mesh
    x, w = _data(jax.random.PRNGKey(1))

    ring = cc.shard_over(
        lambda xs, ws: matmul_scatter(xs, ws, "tp"),
        in_specs=(P(None, None, "tp"), P(None, "tp")),
        out_specs=P("tp", None, None),
    )
    mono = cc.shard_over(
        lambda xs, ws: cc.reduce_scatter(
            jnp.matmul(xs, ws.T), "tp", scatter_axis=0),
        in_specs=(P(None, None, "tp"), P(None, "tp")),
        out_specs=P("tp", None, None),
    )
    np.testing.assert_allclose(np.asarray(ring(x, w)),
                               np.asarray(mono(x, w)),
                               rtol=1e-5, atol=1e-6)

    def loss(f):
        return lambda x, w: jnp.sum(jnp.sin(f(x, w)))

    g_ring = jax.grad(loss(ring), argnums=(0, 1))(x, w)
    g_mono = jax.grad(loss(mono), argnums=(0, 1))(x, w)
    for a, b in zip(g_ring, g_mono):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_overlap_degenerates_without_axis():
    """axis=None (or unbound) -> one local GEMM, usable outside shard_map."""
    x, w = _data(jax.random.PRNGKey(2))
    ref = jnp.matmul(x, w.T)
    np.testing.assert_allclose(np.asarray(gather_matmul(x, w, None)),
                               np.asarray(ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(matmul_scatter(x, w, None)),
                               np.asarray(ref), rtol=1e-6)


def test_gather_matmul_fp8_composes(tp_mesh):
    """fp8 delayed-scaling GEMMs through the ring: per-tensor scales
    commute with sequence chunking, so forward matches the monolithic fp8
    path tightly; grads match under a unit cotangent (where the e5m2
    just-in-time quantization is exact on both paths)."""
    from apex_tpu.amp.fp8 import Fp8Meta, fp8_matmul_t

    _, tp_size = tp_mesh
    x, w = _data(jax.random.PRNGKey(3))
    metas = {"x": Fp8Meta.init(), "w": Fp8Meta.init()}

    ring = cc.shard_over(
        lambda xs, ws: gather_matmul(xs, ws, "tp", fp8_metas=metas),
        in_specs=(P("tp", None, None), P("tp", None)),
        out_specs=P(None, None, "tp"),
    )
    mono = cc.shard_over(
        lambda xs, ws: fp8_matmul_t(
            cc.all_gather(xs, "tp", concat_axis=0), ws,
            metas["x"], metas["w"]),
        in_specs=(P("tp", None, None), P("tp", None)),
        out_specs=P(None, None, "tp"),
    )
    np.testing.assert_allclose(np.asarray(ring(x, w)),
                               np.asarray(mono(x, w)),
                               rtol=1e-5, atol=1e-6)

    def loss(f):
        return lambda x, w: jnp.sum(f(x, w))

    g_ring = jax.grad(loss(ring), argnums=(0, 1))(x, w)
    g_mono = jax.grad(loss(mono), argnums=(0, 1))(x, w)
    for a, b in zip(g_ring, g_mono):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_ring_survives_jit_as_collective_permutes(tp_mesh):
    """Compiled forward HLO: >= tp-1 collective-permutes, zero all-gathers
    (gather ring) / zero reduce-scatters (scatter ring) — the acceptance
    check that XLA did not re-fuse the decomposition, enforced by the
    shared analyzer rule APX201 (with APX202 riding along on the ring's
    source_target_pairs) rather than per-test opcode counts."""
    _, tp_size = tp_mesh
    x, w = _data(jax.random.PRNGKey(4))

    gm = cc.shard_over(
        lambda xs, ws: gather_matmul(xs, ws, "tp"),
        in_specs=(P("tp", None, None), P("tp", None)),
        out_specs=P(None, None, "tp"),
    )
    report = lint_hlo(compiled_hlo(gm, x, w), name="gather_matmul",
                      expect_ring=tp_size, forbid_ops=("all-gather",))
    assert report.ok, report.format()

    ms = cc.shard_over(
        lambda xs, ws: matmul_scatter(xs, ws, "tp"),
        in_specs=(P(None, None, "tp"), P(None, "tp")),
        out_specs=P("tp", None, None),
    )
    report = lint_hlo(compiled_hlo(ms, x, w), name="matmul_scatter",
                      expect_ring=tp_size, forbid_ops=("reduce-scatter",))
    assert report.ok, report.format()


def test_hlo_op_counts_folds_async_pairs():
    text = """
  %cp.1 = f32[4]{0} collective-permute(%p0), source_target_pairs={{0,1}}
  %ag = (f32[4]{0}, f32[8]{0}) all-gather-start(%p1), dimensions={0}
  %agd = f32[8]{0} all-gather-done(%ag)
  %d = f32[4]{0} add(%p0, %p0)
"""
    counts = hlo_op_counts(text)
    assert counts["collective-permute"] == 1
    assert counts["all-gather"] == 1
    assert counts["add"] == 1
    assert count_hlo_ops(text, "all-gather-done") == 0
