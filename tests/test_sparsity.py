"""ASP 2:4 sparsity numerics vs pure-numpy references.

Mirrors the reference's ``apex/contrib/test/sparsity`` style: mask-lib
properties (exact n-of-m, magnitude optimality) checked against argsort
references, then the ASP end-to-end recipe (prune → masked finetune keeps
the pattern and trains).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.sparsity import (
    ASP,
    apply_masks,
    create_mask,
    kept_magnitude,
    mask_sparsity,
    mn_1d_best,
    mn_2d_best,
    permuted_mask,
    search_permutation,
)


def test_mn_1d_best_keeps_top_n_per_group():
    rng = np.random.RandomState(0)
    w = rng.randn(16, 32).astype(np.float32)
    mask = np.asarray(mn_1d_best(w, 4, 2))
    g = mask.reshape(-1, 4)
    np.testing.assert_array_equal(g.sum(axis=1), 2)
    # kept magnitude equals the top-2-per-group optimum
    a = np.abs(w).reshape(-1, 4)
    ref = np.sort(a, axis=1)[:, 2:].sum()
    np.testing.assert_allclose((a * g).sum(), ref, rtol=1e-6)


def test_mn_1d_best_pads_odd_widths():
    rng = np.random.RandomState(1)
    w = rng.randn(8, 30).astype(np.float32)  # 30 % 4 != 0
    mask = np.asarray(mn_1d_best(w, 4, 2))
    assert mask.shape == w.shape
    # full groups obey 2:4 exactly
    full = mask[:, :28].reshape(-1, 4)
    np.testing.assert_array_equal(full.sum(axis=1), 2)
    # the zero-padded tail group keeps at most 2 real entries
    assert (mask[:, 28:].sum(axis=1) <= 2).all()


def test_mn_2d_best_row_and_column_sparse():
    rng = np.random.RandomState(2)
    w = rng.randn(16, 16).astype(np.float32)
    mask = np.asarray(mn_2d_best(w, 4, 2))
    blocks = mask.reshape(4, 4, 4, 4).transpose(0, 2, 1, 3).reshape(-1, 4, 4)
    np.testing.assert_array_equal(blocks.sum(axis=2), 2)  # rows
    assert (blocks.sum(axis=1) <= 2).all()                # cols
    # 2d masks also leave the transpose 2:4-prunable (dgrad direction)
    assert abs(mask.mean() - 0.5) < 1e-6


@pytest.mark.parametrize("shape", [(32, 16), (3, 3, 8, 16)])
def test_create_mask_layouts(shape):
    """Dense [in, out] and Conv [kh, kw, in, out]: 2:4 along the reduction
    (all-but-last) dims, mask shaped like the weight."""
    rng = np.random.RandomState(3)
    w = rng.randn(*shape).astype(np.float32)
    mask = np.asarray(create_mask(w))
    assert mask.shape == w.shape
    mat = np.moveaxis(mask, -1, 0).reshape(shape[-1], -1)
    np.testing.assert_array_equal(mat.reshape(-1, 4).sum(axis=1), 2)


def test_permutation_search_improves_crafted_matrix():
    """Columns arranged so identity grouping loses half the large entries;
    a permutation recovers them."""
    rng = np.random.RandomState(4)
    rows, cols = 64, 16
    w = rng.randn(rows, cols).astype(np.float32) * 0.01
    # large magnitude on columns 0..3 — but interleave them across groups
    big = np.abs(rng.randn(rows, 8).astype(np.float32)) + 5.0
    w[:, [0, 1, 4, 5, 8, 9, 12, 13]] = big  # 2 big per group of 4: fine
    # worst case: 4 big columns in one group lose 2 entirely
    w2 = w.copy()
    w2[:, [0, 1, 2, 3]] = big[:, :4]
    w2[:, [4, 5, 6, 7]] = 0.01 * rng.randn(rows, 4)

    base = kept_magnitude(np.abs(w2))
    perm, gain = search_permutation(w2, seed=0)
    assert sorted(perm.tolist()) == list(range(cols))
    assert gain > 0.0
    assert kept_magnitude(np.abs(w2)[:, perm]) >= base + gain - 1e-3

    pm = np.asarray(permuted_mask(jnp.asarray(w2.T)))  # flax [in, out]
    assert pm.shape == w2.T.shape
    kept_perm = (np.abs(w2) * pm.T).sum()
    kept_id = (np.abs(w2) * np.asarray(create_mask(jnp.asarray(w2.T))).T).sum()
    assert kept_perm >= kept_id - 1e-3


def test_asp_end_to_end_masked_training():
    """prune_trained_model: pruned params stay exactly 2:4 through masked
    optimizer steps and the loss still decreases (reference recipe)."""
    import flax.linen as nn

    from apex_tpu.optimizers import FusedAdam

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(32)(x)
            x = nn.relu(x)
            return nn.Dense(8)(x)

    model = MLP()
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (64, 16))
    y = jax.random.randint(jax.random.PRNGKey(1), (64,), 0, 8)
    params = model.init(rng, x)["params"]

    asp = ASP()
    assert len(asp.eligible_paths(params)) == 2  # both Dense kernels
    pruned, masks, opt = asp.prune_trained_model(params, FusedAdam(lr=1e-2))
    assert ASP.is_sparsity_enabled(masks)
    sp = mask_sparsity(masks)
    assert all(abs(v - 0.5) < 1e-6 for v in sp.values())

    state = opt.init(pruned)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            return -jnp.mean(jax.nn.log_softmax(logits)[jnp.arange(64), y])
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.step(grads, state, params)
        return params, state, loss

    p = pruned
    losses = []
    for _ in range(20):
        p, state, loss = step(p, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    # the 2:4 pattern survived momentum + weight decay updates
    for leaf, m in zip(jax.tree_util.tree_leaves(p),
                       jax.tree_util.tree_leaves(masks)):
        m = np.asarray(m)
        if m.ndim == 0:
            continue
        np.testing.assert_array_equal(np.asarray(leaf)[m == 0], 0.0)


def test_asp_layer_name_filters():
    params = {"enc": {"kernel": jnp.ones((8, 8))},
              "head": {"kernel": jnp.ones((8, 8))},
              "tiny": {"kernel": jnp.ones((2, 2))},
              "norm": {"scale": jnp.ones((8,))}}
    asp = ASP(disallowed_layer_names=("head",))
    paths = asp.eligible_paths(params)
    assert paths == ["enc/kernel"]
    asp2 = ASP(allowed_layer_names=("head",))
    assert asp2.eligible_paths(params) == ["head/kernel"]
    masks = asp.compute_sparse_masks(params)
    pruned = apply_masks(params, masks)
    assert float(jnp.sum(pruned["norm"]["scale"])) == 8.0
