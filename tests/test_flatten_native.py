"""apex_C flatten/unflatten analog: native kernel + numpy routing."""

import shutil

import numpy as np
import pytest

from apex_tpu.utils.flatten import (
    flatten_dense_tensors,
    native_available,
    unflatten_dense_tensors,
)


@pytest.mark.parametrize("n,shape", [(3, (13, 7)), (200, (17,)), (1, (4, 4, 2))])
def test_flatten_roundtrip(n, shape):
    rng = np.random.RandomState(0)
    xs = [rng.randn(*shape).astype(np.float32) for _ in range(n)]
    flat = flatten_dense_tensors(xs)
    np.testing.assert_array_equal(
        flat, np.concatenate([x.ravel() for x in xs]))
    back = unflatten_dense_tensors(flat, xs)
    for a, b in zip(back, xs):
        np.testing.assert_array_equal(a, b)
        assert a.shape == b.shape


@pytest.mark.skipif(shutil.which("cc") is None,
                    reason="no C toolchain; numpy fallback is by design")
def test_flatten_native_kernel_builds():
    assert native_available()


def test_flatten_validation():
    with pytest.raises(ValueError, match="dtype"):
        flatten_dense_tensors([np.zeros(2, np.float32),
                               np.zeros(2, np.float64)])
    with pytest.raises(ValueError, match="elements"):
        unflatten_dense_tensors(np.zeros(3, np.float32),
                                [np.zeros(2, np.float32)] * 2)


def test_flatten_dtypes():
    for dt in (np.float16, np.float32, np.int32, np.uint16):
        xs = [np.arange(10, dtype=dt), np.arange(7, dtype=dt)]
        back = unflatten_dense_tensors(flatten_dense_tensors(xs), xs)
        for a, b in zip(back, xs):
            np.testing.assert_array_equal(a, b)
