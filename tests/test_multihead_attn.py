"""Fused MHA modules vs naive reference (the reference tests its CUDA
paths against python impls the same way,
``apex/contrib/test/multihead_attn/test_self_multihead_attn.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)

T, B, C, H = 16, 2, 32, 4


def naive_mha(x_q, x_kv, params, heads, key_padding_mask=None,
              attn_mask=None, self_attn=True):
    if self_attn:
        qkv = x_q @ params["in_proj"]["kernel"]
        q, k, v = np.split(qkv, 3, axis=-1)
    else:
        q = x_q @ params["q_proj"]["kernel"]
        kv = x_kv @ params["kv_proj"]["kernel"]
        k, v = np.split(kv, 2, axis=-1)
    d = q.shape[-1] // heads

    def sh(x):
        t, b, c = x.shape
        return x.reshape(t, b, heads, d).transpose(1, 2, 0, 3)

    qh, kh, vh = sh(q), sh(k), sh(v)
    s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(d)
    if attn_mask is not None:
        s = s + attn_mask
    if key_padding_mask is not None:
        s = np.where(key_padding_mask[:, None, None, :].astype(bool),
                     -1e30, s)
    p = jax.nn.softmax(jnp.asarray(s), axis=-1)
    o = np.einsum("bhqk,bhkd->bhqd", np.asarray(p), vh)
    o = o.transpose(2, 0, 1, 3).reshape(x_q.shape[0], x_q.shape[1], -1)
    return o @ params["out_proj"]["kernel"]


def test_self_attn_matches_naive():
    x = np.random.RandomState(0).randn(T, B, C).astype(np.float32)
    m = SelfMultiheadAttn(embed_dim=C, num_heads=H)
    params = m.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]
    out = m.apply({"params": params}, jnp.asarray(x))
    ref = naive_mha(x, x, jax.device_get(params), H)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_self_attn_key_padding_mask():
    rng = np.random.RandomState(1)
    x = rng.randn(T, B, C).astype(np.float32)
    pad = np.zeros((B, T), np.int32)
    pad[:, -5:] = 1  # last 5 keys padded
    m = SelfMultiheadAttn(embed_dim=C, num_heads=H)
    params = m.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]
    out = m.apply({"params": params}, jnp.asarray(x),
                  key_padding_mask=jnp.asarray(pad))
    ref = naive_mha(x, x, jax.device_get(params), H, key_padding_mask=pad)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_self_attn_additive_mask():
    rng = np.random.RandomState(2)
    x = rng.randn(T, B, C).astype(np.float32)
    causal = np.triu(np.full((T, T), -1e9, np.float32), k=1)[None, None]
    m = SelfMultiheadAttn(embed_dim=C, num_heads=H, mask_additive=True)
    params = m.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]
    out = m.apply({"params": params}, jnp.asarray(x),
                  attn_mask=jnp.asarray(causal))
    ref = naive_mha(x, x, jax.device_get(params), H, attn_mask=causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_self_attn_norm_add_residual():
    rng = np.random.RandomState(3)
    x = rng.randn(T, B, C).astype(np.float32)
    m = SelfMultiheadAttn(embed_dim=C, num_heads=H, include_norm_add=True)
    params = m.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]
    assert "lyr_nrm" in params
    out = m.apply({"params": params}, jnp.asarray(x))
    # deterministic + zero-dropout: out = x + attn(LN(x))
    from apex_tpu.normalization import FusedLayerNorm

    ln = FusedLayerNorm(C)
    xn = ln.apply({"params": params["lyr_nrm"]}, jnp.asarray(x))
    ref = x + naive_mha(np.asarray(xn), np.asarray(xn),
                        jax.device_get(params), H)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    with pytest.raises(ValueError):
        SelfMultiheadAttn(embed_dim=C, num_heads=H, include_norm_add=True,
                          mask_additive=True).init(
            jax.random.PRNGKey(0), jnp.asarray(x))


def test_encdec_attn_matches_naive():
    rng = np.random.RandomState(4)
    q = rng.randn(8, B, C).astype(np.float32)   # decoder stream
    kv = rng.randn(T, B, C).astype(np.float32)  # encoder stream
    m = EncdecMultiheadAttn(embed_dim=C, num_heads=H)
    params = m.init(jax.random.PRNGKey(0), jnp.asarray(q),
                    jnp.asarray(kv))["params"]
    out = m.apply({"params": params}, jnp.asarray(q), jnp.asarray(kv))
    ref = naive_mha(q, kv, jax.device_get(params), H, self_attn=False)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_self_attn_dropout_trains():
    """Dropout path (flash in-kernel dropout) is deterministic per rng and
    differentiable."""
    x = jnp.asarray(np.random.RandomState(5).randn(T, B, C), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=C, num_heads=H, dropout=0.2)
    params = m.init(jax.random.PRNGKey(0), x)["params"]

    def run(p, seed):
        return m.apply({"params": p}, x, deterministic=False,
                       rngs={"dropout": jax.random.PRNGKey(seed)})

    o1, o2 = run(params, 7), run(params, 7)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = run(params, 8)
    assert not np.allclose(np.asarray(o1), np.asarray(o3))
    g = jax.grad(lambda p: jnp.sum(run(p, 7) ** 2))(params)
    assert all(np.all(np.isfinite(np.asarray(l)))
               for l in jax.tree_util.tree_leaves(g))
