"""3D-parallel GPT integration: dp×pp×tp(+sp) vs single-device parity.

The SPMD analog of the reference's schedule-parity suite
(``test_pipeline_parallel_fwd_bwd.py:99-170``: forward/backward parity of
parallel grids against the serial model).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import parallel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer.testing import TransformerConfig
from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

pytestmark = pytest.mark.slow

VOCAB, SEQ = 64, 16
DPW, PP, TP, VPP = 2, 2, 2, 2
M = 2  # microbatches


def setup():
    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=TP,
        pipeline_model_parallel_size=PP,
        virtual_pipeline_model_parallel_size=VPP,
    )
    cfg = TransformerConfig(
        hidden_size=32, num_layers=PP * VPP, num_attention_heads=4,
        padded_vocab_size=VOCAB, max_position_embeddings=SEQ,
        hidden_dropout=0.0, attention_dropout=0.0,
        tensor_axis="tp", sequence_parallel=True,
    )
    return mesh, cfg


def serial_loss(cfg, params, tokens):
    """Same modules, same global params, no mesh (degraded single-rank)."""
    from apex_tpu.ops.softmax import AttnMaskType
    from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
    from apex_tpu.transformer.testing.standalone_gpt import gpt_next_token_loss
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        Embedding, ParallelTransformerLayer, parallel_lm_logits,
    )

    embed = Embedding(cfg)
    layer = ParallelTransformerLayer(
        cfg, self_attn_mask_type=AttnMaskType.causal)
    ln = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon)

    losses = []
    mb = tokens.shape[0] // M
    for i in range(M):
        t = tokens[i * mb:(i + 1) * mb]
        h = embed.apply({"params": params.embedding}, t)
        for v in range(cfg.num_layers):
            c, s = v // PP, v % PP
            lp = jax.tree_util.tree_map(lambda l: l[c, s], params.layers)
            h = layer.apply({"params": lp}, h, None)
        h = ln.apply({"params": params.final_ln}, h)
        logits = parallel_lm_logits(
            h, params.embedding["word_embeddings"]["embedding"], cfg)
        losses.append(jnp.mean(gpt_next_token_loss(logits, t, cfg)))
    return jnp.mean(jnp.stack(losses))


def test_3d_loss_matches_serial_and_trains():
    mesh, cfg = setup()
    init_fn, make_loss_fn, make_train_step = build_gpt_3d(
        cfg, num_chunks=VPP, num_microbatches=M, mesh=mesh,
    )
    batch = DPW * M * 2
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, SEQ), 0,
                                VOCAB)
    params, specs = init_fn(jax.random.PRNGKey(0), tokens)

    loss_fn = make_loss_fn(specs)
    l3d = float(loss_fn(params, tokens))

    # serial: average the per-dp-shard serial losses
    per_shard = batch // DPW
    serial = np.mean([
        float(serial_loss(cfg, jax.tree_util.tree_map(jax.device_get,
                                                      params),
                          tokens[i * per_shard:(i + 1) * per_shard]))
        for i in range(DPW)
    ])
    np.testing.assert_allclose(l3d, serial, rtol=1e-5)
    assert abs(l3d - np.log(VOCAB)) < 1.0

    opt = FusedAdam(lr=2e-3)
    state = opt.init(params)
    step = jax.jit(make_train_step(opt, specs))
    losses = []
    for _ in range(8):
        params, state, loss = step(params, state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_3d_with_grouped_remat_matches():
    """The flagship 3D step with remat_ticks=True (1F1B-class activation
    bound) must produce the same loss/gradient flow as the flat schedule:
    first-step loss equal, training still converges."""
    mesh, cfg = setup()
    init_fn, make_loss_fn, make_train_step = build_gpt_3d(
        cfg, num_chunks=VPP, num_microbatches=M, mesh=mesh,
    )
    init_g, make_loss_g, make_step_g = build_gpt_3d(
        cfg, num_chunks=VPP, num_microbatches=M, mesh=mesh,
        remat_ticks=True,
    )
    batch = DPW * M * 2
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, SEQ), 0,
                                VOCAB)
    params, specs = init_fn(jax.random.PRNGKey(0), tokens)

    l_flat = float(jax.jit(make_loss_fn(specs))(params, tokens))
    l_grp = float(jax.jit(make_loss_g(specs))(params, tokens))
    np.testing.assert_allclose(l_grp, l_flat, rtol=1e-6)

    opt = FusedAdam(lr=2e-3)
    state = opt.init(params)
    step = jax.jit(make_step_g(opt, specs))
    losses = []
    for _ in range(6):
        params, state, loss = step(params, state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] and np.isfinite(losses).all()
