"""Wgrad-accumulation donation proof (SURVEY row 42 / VERDICT r2 weak #7).

The reference fuses the weight-gradient GEMM's accumulation into a
persistent ``weight.main_grad`` buffer (``gradient_accumulation_fusion``,
``csrc/megatron/fused_weight_gradient_dense.cpp:19`` — a beta=1 GEMM into
main_grad).  The TPU-native claim (``tensor_parallel/layers.py:17-19``) is
that buffer donation gives the same thing: the jit-carried accumulator is
updated in place, with no second grad-sized output allocation.  These
tests turn that claim into compiled-HLO assertions:

- the donated accumulator appears in ``input_output_alias`` (XLA writes
  the result into the argument buffer — in-place accumulation);
- the non-donated variant allocates a fresh grad-sized output instead;
- temp memory for a scan over M microbatches does not scale with M (the
  accumulator is carried, not copied per microbatch).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.transformer.tensor_parallel import (
    linear_with_grad_accumulation,
)

OUT, IN, MB = 256, 128, 32


def _wgrad_step(main_grad, weight, x, g):
    """One microbatch's wgrad accumulated into main_grad: the functional
    analog of fused_weight_gradient_dense's beta=1 GEMM, taken through the
    public GEMM entry point's vjp."""
    wgrad = jax.vjp(
        lambda w: linear_with_grad_accumulation(x, w, axis=None), weight
    )[1](g)[0]
    return main_grad + wgrad


def _compile(donate):
    fn = jax.jit(_wgrad_step,
                 donate_argnums=(0,) if donate else ())
    mg = jnp.zeros((OUT, IN))
    w = jnp.ones((OUT, IN))
    x = jnp.ones((MB, IN))
    g = jnp.ones((MB, OUT))
    return fn.lower(mg, w, x, g).compile(), (mg, w, x, g), fn


def test_donated_accumulator_aliases_output():
    comp, _, _ = _compile(donate=True)
    header = comp.as_text().splitlines()[0]
    assert "input_output_alias" in header, header
    # parameter 0 (main_grad) aliases the (single) output
    assert "(0, {}" in header.split("input_output_alias=")[1], header


def test_undonated_accumulator_does_not_alias():
    comp, _, _ = _compile(donate=False)
    header = comp.as_text().splitlines()[0]
    assert "input_output_alias" not in header, header


def test_donation_eliminates_output_allocation():
    """Peak-footprint accounting: with donation the grad-sized output
    lives in the argument buffer, so (output bytes not aliased) drops by
    exactly one accumulator."""
    grad_bytes = OUT * IN * 4
    comp_d, _, _ = _compile(donate=True)
    comp_u, _, _ = _compile(donate=False)
    ma_d, ma_u = comp_d.memory_analysis(), comp_u.memory_analysis()
    # both report the same logical output size...
    assert ma_d.output_size_in_bytes == ma_u.output_size_in_bytes
    # ...but the donated program's output aliases an argument
    assert ma_d.alias_size_in_bytes >= grad_bytes, (
        ma_d.alias_size_in_bytes)
    assert ma_u.alias_size_in_bytes == 0


def test_in_place_semantics_and_numerics():
    """The donated buffer is consumed (in-place write), and M accumulation
    steps produce exactly M * wgrad."""
    comp, (mg, w, x, g), fn = _compile(donate=True)
    out = fn(mg, w, x, g)
    assert mg.is_deleted()  # the argument buffer was donated
    out2 = fn(out, w, x, g)
    expected = 2.0 * np.asarray(
        jnp.einsum("bo,bi->oi", g, x))
    np.testing.assert_allclose(np.asarray(out2), expected, rtol=1e-6)


def test_scan_accumulation_temp_memory_flat_in_microbatches():
    """A scan over M microbatches carrying main_grad must not allocate
    per-microbatch grad buffers: temp bytes stay flat as M grows 4x."""

    def accum(main_grad, weight, xs, gs):
        def body(acc, mb):
            x, g = mb
            wgrad = jax.vjp(
                lambda w: linear_with_grad_accumulation(x, w, axis=None),
                weight)[1](g)[0]
            return acc + wgrad, ()

        acc, _ = lax.scan(body, main_grad, (xs, gs))
        return acc

    def temp_bytes(m):
        fn = jax.jit(accum, donate_argnums=(0,))
        args = (jnp.zeros((OUT, IN)), jnp.ones((OUT, IN)),
                jnp.ones((m, MB, IN)), jnp.ones((m, MB, OUT)))
        comp = fn.lower(*args).compile()
        header = comp.as_text().splitlines()[0]
        assert "input_output_alias" in header
        return comp.memory_analysis().temp_size_in_bytes

    t4, t16 = temp_bytes(4), temp_bytes(16)
    grad_bytes = OUT * IN * 4
    assert t16 <= t4 + grad_bytes, (t4, t16)  # flat, not 4x
