#!/usr/bin/env python
"""Bench regression gate (ISSUE 10): newest BENCH/MULTICHIP record vs
history, exit nonzero on regression.

Five driver rounds of evidence (``BENCH_r01..r05.json``,
``MULTICHIP_r01..r05.json``) sit in the repo with no automated check —
a PR that halves ``gpt_flash`` throughput or breaks the multichip
dryrun would only be caught by a human reading JSON.  This gate
mechanizes the comparison on the **compact-record whitelist** (the
per-row ``{value, unit, platform, vs_*}`` dicts ``bench.compact_record``
emits — the only fields every round durably carries):

- each round's compact record is taken from the driver's ``parsed``
  field, falling back to the last parseable JSON line of the 2000-byte
  stdout ``tail`` (rounds 1–4 predate the compact-line fix and may
  yield nothing — a round with no usable record contributes no
  baseline, exactly like an errored row);
- rows are compared **only against history measured on the same
  platform** (a CPU fallback round must never be judged against a TPU
  round);
- the baseline per row is the **median** of its history values, and
  each row gets a **noise tolerance** (CPU fallback rows on a shared
  host are noisy: the observed round-to-round spread of the headline is
  ~15%, so the default tolerance is deliberately wide; per-row
  overrides in ``TOLERANCES``).  Direction comes from the unit:
  ``*/sec*`` rows regress downward, ``us/step``/``ms/*`` rows regress
  upward;
- three regression classes are noise-free and always fatal: the newest
  round's driver ``rc`` going nonzero while history succeeded, a row
  that now ``error``s but previously produced a value, and a hard
  **gate** field exceeding its standing ceiling
  (``telemetry_overhead.vs_bare`` ≤ 1.05 — the free-telemetry
  acceptance from ISSUE 5/10);
- MULTICHIP records regress when the newest round's ``ok`` flag drops
  (or ``rc`` goes nonzero) while any historical round passed.

Exit status: 0 = no regression, 1 = regression (each printed with its
row, baseline, and tolerance), 2 = usage/IO error.  Wired fast-tier in
``tests/test_bench_regress.py``: exit 0 on the real r01→r05 history,
nonzero on a fixture with an injected >tolerance regression.

Usage::

    python scripts/bench_regress.py                     # repo history
    python scripts/bench_regress.py --dir /path/to/dir  # a fixture dir
    python scripts/bench_regress.py --tolerance 0.5     # override default
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys
from typing import List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Default fractional tolerance: CPU fallback rounds on a shared host
# show ~15% round-to-round drift on the headline alone; 0.4 keeps the
# gate quiet on noise while still catching the 2x-class regressions
# that matter.
DEFAULT_TOLERANCE = 0.4

# Per-row overrides (fraction of the baseline).  Rows with tiny absolute
# values or known environment sensitivity get more room.
TOLERANCES = {
    "headline": 0.5,          # resnet50_o2 CPU throughput, host-load bound
    "real_data_rn50": 0.8,    # ~0.6 images/sec absolute on CPU
    "input_pipeline": 0.7,    # scales with the host's free cores
    "tp_gpt": 0.6,            # 8-way shard_map on a shared CPU
    # preemption/recompute cadence is host-load sensitive on CPU (the
    # interpret-mode prefill dominates the recompute cost)
    "serving_occupancy": 0.6,
    # acceptance length couples throughput to the model's greedy
    # cycling, which shifts with any model/config change; the ratio
    # vs_baseline is the stable signal, the absolute rate is not
    "serving_spec": 0.6,
    # 3 replica processes + the loopback socket leg (wire_vs_inproc)
    # on a shared CPU host: process scheduling noise dominates both
    # the absolute rate and the transport ratio
    "serving_fleet": 0.6,
    # absolute decode p99 on a shared CPU host is scheduling-noise
    # bound; the gated signal is the vs_colocated floor below
    "serving_disagg": 0.6,
    # absolute wave rate on a shared CPU host is noisy; the gated
    # signal is the vs_bare ceiling above, not the rate
    "serving_trace_overhead": 0.6,
    # same A/B discipline as serving_trace_overhead: the rate is
    # noise, vs_bare is the gated signal
    "serving_slo_overhead": 0.6,
    # the delta kernel runs interpret-mode Pallas on CPU, so the
    # absolute rate couples to host load twice over; the gated signal
    # is the vs_bare_1adapter floor below
    "serving_lora": 0.6,
    # four replica processes timesharing a CPU host: the absolute
    # burst token rate is scheduling-noise bound; the gated signal is
    # the vs_static floor below
    "serving_autopilot": 0.6,
}

# Hard ceilings on whitelist fields — standing acceptance gates, not
# noise comparisons ((row, field) -> max allowed value).
GATES = {
    ("telemetry_overhead", "vs_bare"): 1.05,
    # ISSUE 15: the distributed-tracing plane armed on the serving hot
    # path must ride inside the same free-telemetry budget
    ("serving_trace_overhead", "vs_bare"): 1.05,
    # ISSUE 20: the longitudinal history + SLO burn-rate plane, armed
    # at a hotter-than-shipped cadence, rides the same budget
    ("serving_slo_overhead", "vs_bare"): 1.05,
}

# Hard floors, same idea in the other direction ((row, field) -> min
# allowed value).  serving_spec.vs_baseline is the ISSUE 13 acceptance
# bar: speculation must never make serving slower than the plain
# engine, even on CPU where the verify's FLOPs are not free.
FLOORS = {
    ("serving_spec", "vs_baseline"): 1.0,
    # ISSUE 16: disaggregating prefill from decode must protect the
    # decode tail — co-located p99 / disaggregated p99 under the same
    # prefill flood at equal pool size
    ("serving_disagg", "vs_colocated"): 1.0,
    # ISSUE 17: a single resident adapter may cost at most ~10% of the
    # bare engine's decode rate — the gathered delta rides the tick,
    # it must not own it
    ("serving_lora", "vs_bare_1adapter"): 0.9,
    # ISSUE 18: the SLO autopilot must beat the static fleet it
    # operates on the burst tail it exists to protect — paired
    # median-of-ratios of p99 TTFT, static / autopilot
    ("serving_autopilot", "vs_static"): 1.0,
}


def lower_is_better(unit: Optional[str]) -> Optional[bool]:
    """Regression direction from the row's unit; ``None`` (skip) when
    the unit is unknown (a size-degraded compact record drops units)."""
    if not unit:
        return None
    return "/sec" not in unit


def parse_compact(record: dict) -> Optional[dict]:
    """The round's compact record: the driver's ``parsed`` field, else
    the last parseable JSON object line in the stdout tail."""
    parsed = record.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    tail = record.get("tail", "")
    for line in reversed(tail.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            return obj
    return None


def load_rounds(paths: List[str]) -> List[dict]:
    """``[{path, n, rc, compact}]`` sorted oldest→newest (by the
    driver's round number when present, else by filename)."""
    rounds = []
    for path in paths:
        with open(path) as f:
            rec = json.load(f)
        rounds.append({
            "path": path,
            "n": rec.get("n"),
            "rc": rec.get("rc"),
            "ok": rec.get("ok"),
            "compact": parse_compact(rec),
            "raw": rec,
        })
    rounds.sort(key=lambda r: (r["n"] if isinstance(r["n"], int)
                               else 10**9, r["path"]))
    return rounds


def _rows_of(compact: Optional[dict]) -> dict:
    """Whitelist rows of one compact record, with the headline folded in
    as a pseudo-row so it is gated like everything else."""
    if not isinstance(compact, dict):
        return {}
    rows = dict(compact.get("rows") or {})
    if compact.get("value") is not None:
        rows["headline"] = {
            "value": compact["value"],
            "unit": compact.get("unit"),
            "platform": compact.get("platform"),
        }
    # a size-degraded compact record flattens rows to bare numbers
    return {name: (row if isinstance(row, dict) else {"value": row})
            for name, row in rows.items()}


def check_bench(rounds: List[dict], tolerance: float,
                failures: List[str], notes: List[str]) -> None:
    if not rounds:
        notes.append("bench: no records found (nothing to gate)")
        return
    newest, history = rounds[-1], rounds[:-1]
    label = os.path.basename(newest["path"])

    rc_history_ok = any(h["rc"] == 0 for h in history)
    if newest["rc"] not in (0, None) and rc_history_ok:
        failures.append(
            f"bench {label}: driver rc={newest['rc']} but history has "
            "successful rounds")
    if newest["compact"] is None:
        if newest["rc"] in (0, None) and any(
                h["compact"] is not None for h in history):
            failures.append(
                f"bench {label}: no parseable compact record (the "
                "driver-contract last-line guarantee broke) though "
                "history has them")
        else:
            notes.append(f"bench {label}: no compact record (round "
                         f"failed, rc={newest['rc']}) — skipping rows")
        return

    new_rows = _rows_of(newest["compact"])
    hist_rows = [_rows_of(h["compact"]) for h in history]

    for name, row in sorted(new_rows.items()):
        # hard gates first: a ceiling/floor needs no history
        for (gname, field), ceiling in GATES.items():
            if name == gname and row.get(field) is not None:
                if float(row[field]) > ceiling:
                    failures.append(
                        f"bench {label}: {name}.{field}="
                        f"{row[field]} exceeds the {ceiling} gate")
                else:
                    notes.append(f"bench {label}: gate {name}.{field}="
                                 f"{row[field]} <= {ceiling} ok")
        for (gname, field), floor in FLOORS.items():
            if name == gname and row.get(field) is not None:
                if float(row[field]) < floor:
                    failures.append(
                        f"bench {label}: {name}.{field}="
                        f"{row[field]} below the {floor} floor")
                else:
                    notes.append(f"bench {label}: floor {name}.{field}="
                                 f"{row[field]} >= {floor} ok")

        platform = row.get("platform")
        prior = [h[name] for h in hist_rows if name in h]
        prior_clean = [
            p for p in prior
            if p.get("value") is not None and "error" not in p
            and (platform is None or p.get("platform") in (None, platform))]
        if "error" in row:
            if prior_clean:
                failures.append(
                    f"bench {label}: row {name} now errors "
                    f"({row['error']!r}) but history has clean values")
            continue
        value = row.get("value")
        if value is None or not prior_clean:
            continue
        baseline = statistics.median(
            float(p["value"]) for p in prior_clean)
        unit = row.get("unit") or next(
            (p.get("unit") for p in prior_clean if p.get("unit")), None)
        direction = lower_is_better(unit)
        if direction is None or baseline == 0:
            notes.append(f"bench {label}: row {name} has no unit/"
                         "baseline — direction unknown, skipped")
            continue
        tol = TOLERANCES.get(name, tolerance)
        ratio = float(value) / baseline
        if direction:
            regressed = ratio > 1.0 + tol
        else:
            regressed = ratio < 1.0 - tol
        verdict = "REGRESSION" if regressed else "ok"
        line = (f"bench {label}: {name} {value} {unit or ''} vs median "
                f"{baseline:g} (x{ratio:.3f}, tol ±{tol:.0%}, "
                f"{'lower' if direction else 'higher'}-is-better, "
                f"n={len(prior_clean)}) {verdict}")
        (failures if regressed else notes).append(line)


def check_multichip(rounds: List[dict], failures: List[str],
                    notes: List[str]) -> None:
    if not rounds:
        notes.append("multichip: no records found")
        return
    newest, history = rounds[-1], rounds[:-1]
    label = os.path.basename(newest["path"])
    ever_ok = any(h["raw"].get("ok") for h in history)
    new_ok = bool(newest["raw"].get("ok")) and newest["rc"] in (0, None)
    if ever_ok and not new_ok:
        failures.append(
            f"multichip {label}: ok={newest['raw'].get('ok')} "
            f"rc={newest['rc']} but history has passing rounds")
    else:
        notes.append(f"multichip {label}: ok={new_ok}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bench/multichip regression gate over the driver "
                    "record history")
    ap.add_argument("--dir", default=_REPO,
                    help="directory holding the record files "
                         "(default: the repo root)")
    ap.add_argument("--bench-glob", default="BENCH_r*.json")
    ap.add_argument("--multichip-glob", default="MULTICHIP_r*.json")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="default fractional noise tolerance "
                         f"(default {DEFAULT_TOLERANCE}; per-row "
                         "overrides in TOLERANCES)")
    ap.add_argument("--quiet", action="store_true",
                    help="print only regressions")
    args = ap.parse_args(argv)

    bench_paths = sorted(glob.glob(os.path.join(args.dir, args.bench_glob)))
    multi_paths = sorted(glob.glob(
        os.path.join(args.dir, args.multichip_glob)))
    if not bench_paths and not multi_paths:
        print(f"bench_regress: no records match {args.bench_glob} / "
              f"{args.multichip_glob} under {args.dir}", file=sys.stderr)
        return 2

    failures: List[str] = []
    notes: List[str] = []
    try:
        check_bench(load_rounds(bench_paths), args.tolerance,
                    failures, notes)
        check_multichip(load_rounds(multi_paths), failures, notes)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_regress: cannot read records: {e!r}",
              file=sys.stderr)
        return 2

    if not args.quiet:
        for line in notes:
            print(line)
    for line in failures:
        print(f"FAIL {line}")
    if failures:
        print(f"bench_regress: {len(failures)} regression(s)")
        return 1
    print("bench_regress: no regressions "
          f"({len(bench_paths)} bench + {len(multi_paths)} multichip "
          "rounds)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
