#!/bin/bash
# TPU evidence capture, round 5 — the VERDICT r4 "Next round" queue:
#
#   1. bench.py full 10-row matrix  (8 h internal poller + wedge-pause;
#      re-benches the flagship 4 post-dtype-fix, captures the 5 CPU-only
#      rows, runs the new real_data_rn50 end-to-end row, refreshes the
#      stale input_pipeline row with packed fields; fused_adam_step now
#      runs 5th, tp_gpt still last; every emission ends with the compact
#      <=1500-byte record line the driver tail can parse)
#   2. lamb-vs-syncbn A/B           (--one diagnostics; FusedLAMB now
#      runs the chunked flat-buffer update — the A/B shows what remains)
#   3. GPT batch sweep              (auto-lands gpt_batch_tuned.json)
#   4. flash block sweep seq 1024   (auto-lands tuned blocks)
#   5. GPT step profile             (if MFU still < 0.5, the trace)
#   6. RN50 lamb+syncbn profile
#   7. remat_ticks memory on chip   (overwrite the CPU-platform record)
#   8. pipeline tick anchor
#   9. flash block sweep seq 8192   (stretch: biggest dtype-fix lift)
#  10. re-bench                     (picks up tuned configs = second
#      stamped window for variance)
#
# Every non-bench stage gates on a live-chip probe: a wedge costs
# probe-time, not stage budget.  Evidence lands incrementally.
set -u
cd "$(dirname "$0")/.."
LOG=.tpu_watch/capture5.log
# Hard wall-clock stop (epoch seconds): the driver runs its own round-end
# bench on this 1-CPU host ~12 h after round start; this watcher must be
# silent by then (default: just a very large number = no deadline).
END_EPOCH=${CAPTURE5_END_EPOCH:-9999999999}
check_deadline() {
  if [ "$(date +%s)" -ge "$END_EPOCH" ]; then
    log "wall-clock deadline reached; exiting to leave the host quiet"
    exit 0
  fi
}
mkdir -p .tpu_watch bench_results
stamp() { date +%H:%M:%S; }
log() { echo "== $(stamp) $*" >> "$LOG"; }
probe() {
  timeout 90 python -c \
    "import jax; assert jax.devices()[0].platform == 'tpu'" \
    >/dev/null 2>&1
}
wait_for_chip() {
  check_deadline
  until probe; do
    check_deadline
    log "chip down; re-probing in 120s"
    sleep 120
  done
  log "chip up"
}
run() {
  check_deadline
  # clamp the stage budget to the wall-clock deadline: an in-flight stage
  # must not outlive END_EPOCH either
  local budget="${STAGE_TIMEOUT:-2400}"
  local rem=$((END_EPOCH - $(date +%s)))
  if [ "$rem" -lt "$budget" ]; then budget="$rem"; fi
  log "start (budget ${budget}s): $*"
  timeout "$budget" "$@" >> "$LOG" 2>&1
  log "rc=$? ($1 $2)"
}

log "capture5 start"
STAGE_TIMEOUT=29200 BENCH_DEADLINE_S=28800 run python bench.py

wait_for_chip
STAGE_TIMEOUT=600 run python bench.py --one resnet50_sgd_syncbn
wait_for_chip
STAGE_TIMEOUT=600 run python bench.py --one resnet50_lamb_nosync
wait_for_chip
run python examples/tune_gpt_batch.py
wait_for_chip
run python examples/tune_flash_blocks.py --seq 1024 --timeout 600
wait_for_chip
STAGE_TIMEOUT=1200 run python examples/profile_gpt.py
wait_for_chip
STAGE_TIMEOUT=1200 run python examples/profile_resnet.py --optimizer lamb --sync-bn
wait_for_chip
STAGE_TIMEOUT=1200 run python examples/measure_remat_memory.py
wait_for_chip
STAGE_TIMEOUT=1200 run python examples/measure_pipeline_tick.py
wait_for_chip
run python examples/tune_flash_blocks.py --seq 8192 --steps 5 --timeout 600
wait_for_chip
BENCH_DEADLINE_S=2100 run python bench.py
log "capture5 done"
