#!/usr/bin/env bash
# Telemetry smoke (ISSUE 5 satellite): run the driver dryrun entry with
# in-graph telemetry armed and a JSONL metrics sink, then assert the
# output parses and carries the metric-catalog keys
# (docs/observability.md).  This is the end-to-end proof that the
# TrainStats device layer, the log_every_n host fetch, the rank-aware
# MetricRegistry, and the crash-safe JsonlWriter compose on the full 3D
# mesh — exactly the pipeline a real run logs through.
#
# Usage: scripts/telemetry_smoke.sh [N_DEVICES] [OUT_DIR]
#   N_DEVICES  virtual CPU mesh size for dryrun_multichip (default 8;
#              the fast-tier test uses 2 to keep the XLA compile small)
#   OUT_DIR    where metrics.jsonl lands (default: a fresh mktemp dir)
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
N_DEVICES="${1:-8}"
OUT_DIR="${2:-$(mktemp -d /tmp/apex_tpu_telemetry.XXXXXX)}"
mkdir -p "$OUT_DIR"

echo "telemetry_smoke: dryrun_multichip(${N_DEVICES}) -> ${OUT_DIR}" >&2

cd "$REPO"
APEX_TPU_TELEMETRY_DIR="$OUT_DIR" python -c \
  "import __graft_entry__ as g; g.dryrun_multichip(${N_DEVICES})"

python - "$OUT_DIR/metrics.jsonl" <<'EOF'
import sys

from apex_tpu.observability import read_jsonl

path = sys.argv[1]
records = read_jsonl(path, strict=True)
assert records, f"no telemetry records in {path}"
rec = records[-1]
# The metric-catalog keys every logged step must carry
# (docs/observability.md; TrainStatsLogger.log flattens TrainStats into
# the record and mirrors it under metrics/ as gauges).
expected = ("loss", "grad_norm", "param_norm", "nonfinite_leaves",
            "loss_scale", "skipped_steps", "moe_aux", "step_time_ms",
            "step", "ts", "rank", "metrics")
missing = [k for k in expected if k not in rec]
assert not missing, f"telemetry record missing keys {missing}: {rec}"
assert rec["nonfinite_leaves"] == 0, rec
assert rec["metrics"]["train/loss"] == rec["loss"], rec
print(f"telemetry_smoke OK: {len(records)} record(s), "
      f"loss={rec['loss']:.4f} grad_norm={rec['grad_norm']:.4f} "
      f"scale={rec['loss_scale']}")
EOF
