#!/bin/bash
# Full TPU evidence capture — run the moment the tunneled chip accepts a
# backend init (the .tpu_watch poller's success hook, or by hand).
#
# Produces, in order of evidentiary value:
#   1. bench.py full matrix           -> stamped bench_results/tpu_*.json
#      (headline RN50 img/s vs baseline, GPT/BERT MFU, fp8-vs-bf16 ratio,
#       fused-optimizer vs-native, input pipeline rate)
#   2. flash block sweep (seq 1024 + 8192) -> bench_results/flash_sweep_*.json
#      (auto-lands the winning block_q/block_k defaults when on TPU)
#   3. GPT step profile               -> bench_results/profile_gpt/
#   4. remat_ticks memory measurement -> bench_results/remat_memory.json
#   5. pipeline tick-time anchor      -> bench_results/pipeline_tick.json
#
# Every stage appends to .tpu_watch/capture.log and continues on failure —
# a mid-capture tunnel wedge must not forfeit earlier stages' evidence.
set -u
cd "$(dirname "$0")/.."
LOG=.tpu_watch/capture.log
mkdir -p .tpu_watch bench_results
stamp() { date +%H:%M:%S; }
run() {
  echo "== $(stamp) $*" >> "$LOG"
  timeout "${STAGE_TIMEOUT:-2400}" "$@" >> "$LOG" 2>&1
  echo "== $(stamp) rc=$?" >> "$LOG"
}

echo "==== $(stamp) capture start ====" >> "$LOG"
BENCH_DEADLINE_S=2100 run python bench.py
run python examples/tune_flash_blocks.py --seq 1024
run python examples/tune_flash_blocks.py --seq 8192 --steps 5
run python examples/profile_gpt.py
run python examples/measure_remat_memory.py
run python examples/measure_pipeline_tick.py
# re-bench with any newly landed flash blocks (headline + MFU rows only
# need to improve; earlier stamped records are never overwritten)
BENCH_DEADLINE_S=1500 run python bench.py
echo "==== $(stamp) capture done ====" >> "$LOG"
