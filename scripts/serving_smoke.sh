#!/usr/bin/env bash
# Serving smoke (ISSUE 9 satellite): spin up the continuous-batching
# decode runtime (apex_tpu.serving) on the virtual CPU mesh, stream N
# requests with staggered arrivals and lengths, and assert:
#   - continuously-batched greedy decode is TOKEN-IDENTICAL to a
#     per-request full-forward argmax reference,
#   - the decode step compiled exactly ONCE across all request churn
#     (the zero-recompile contract),
#   - a real SIGTERM drains cleanly: in-flight responses delivered,
#     queued requests cancelled, exit 0.
# (The KV-arena donation contract is the analyzer's job:
#  scripts/graph_lint.sh --entries serving_decode, rule APX204.)
# Wired fast-tier in tests/test_aux_subsystems.py like the PR 7 data
# smoke.
#
# Usage: scripts/serving_smoke.sh
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
PYTHON="${PYTHON:-python}"

cd "$REPO"
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  "$PYTHON" apex_tpu/testing/serving_smoke.py
echo "PASS" >&2
