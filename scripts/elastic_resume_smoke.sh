#!/usr/bin/env bash
# Elastic resume smoke (ISSUE 6): kill the trainer at mesh shape N,
# resume it at mesh shape M, end to end through the crash_resume trainer
# (apex_tpu.testing.crash_resume) and the restore-anywhere path
# (apex_tpu.resilience.reshard — docs/resilience.md).
#
#   1. an uninterrupted N-step run on the SOURCE mesh records its loss
#      curve, keeping every checkpoint;
#   2. a second SOURCE-mesh run is SIGKILLed mid-async-sharded-save
#      (after >= KILL_AFTER checkpoints landed);
#   3. the killed run is resumed on the TARGET mesh: restore_latest
#      reshards the newest intact checkpoint (layer stacks re-factored,
#      ZeRO flat buckets re-chunked) and the run continues to the end.
#      The pre-kill prefix of its loss curve must equal the
#      uninterrupted reference's BIT-EXACTLY (losses are raw fp32 bits);
#   4. a clean-reshard reference: the SAME step's checkpoint from the
#      UNINTERRUPTED run (no kill, no torn files) is resumed on the
#      target mesh.  The killed run's post-resume curve must equal this
#      clean continuation bit-exactly;
#   5. both target-mesh runs write the canonical mesh-independent state
#      digest of their final checkpoint
#      (reshard.load_logical — per-leaf sha256 of the logical bytes);
#      the digests must be identical: fp32-bit-consistent parameters
#      and optimizer state through SIGKILL + reshard.
#
# Step arithmetic re-associates across a mesh change (dp reduction
# widths, tp matmul splits), so a single-mesh curve cannot be the
# post-resume reference — the clean N->M continuation is, and the PR 3
# smoke (crash_resume_smoke.sh) separately pins clean-resume ==
# uninterrupted on a fixed mesh.  Together: kill + reshard == clean
# reshard == uninterrupted, bit for bit.
#
# Usage: scripts/elastic_resume_smoke.sh [workdir]
# Env: MODE (gpt|zero, default gpt), SRC_ARGS / DST_ARGS (mesh flags,
#      default "--devices 4" -> "--devices 2": save at dp=4, resume at
#      dp=2), STEPS (default 6), KILL_AFTER (default 2), GLOBAL_BATCH
#      (default 8 — fixed so the input stream is mesh-independent),
#      PYTHON (default python).
# Examples:
#   scripts/elastic_resume_smoke.sh                      # gpt dp 4 -> 2
#   SRC_ARGS="--devices 2" DST_ARGS="--devices 4" \
#     scripts/elastic_resume_smoke.sh                    # gpt dp 2 -> 4
#   SRC_ARGS="--tp 2 --pp 2 --devices 4" \
#     DST_ARGS="--tp 4 --pp 1 --devices 4" \
#     scripts/elastic_resume_smoke.sh                    # tp/pp refactor
#   MODE=zero SRC_ARGS="--devices 4" DST_ARGS="--devices 2" \
#     scripts/elastic_resume_smoke.sh                    # ZeRO flat bucket
# Exit 0 = bit-exact elastic resume; non-zero otherwise.
set -u -o pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
WORK="${1:-$(mktemp -d)}"
MODE="${MODE:-gpt}"
SRC_ARGS="${SRC_ARGS:---devices 4}"
DST_ARGS="${DST_ARGS:---devices 2}"
STEPS="${STEPS:-6}"
KILL_AFTER="${KILL_AFTER:-2}"
GLOBAL_BATCH="${GLOBAL_BATCH:-8}"
PYTHON="${PYTHON:-python}"
mkdir -p "$WORK"
cd "$REPO"

COMMON=(--steps "$STEPS" --global-batch "$GLOBAL_BATCH")
if [ "$MODE" = "zero" ]; then COMMON+=(--zero); fi

echo "elastic_resume_smoke: [1/5] uninterrupted reference on source" \
     "mesh ($SRC_ARGS)" >&2
rm -f "$WORK/losses_ref.txt"
# keep every checkpoint: leg 4 needs the same step the kill resumes from
# shellcheck disable=SC2086
"$PYTHON" -m apex_tpu.testing.crash_resume \
  --ckpt-dir "$WORK/ckpt_ref" --losses "$WORK/losses_ref.txt" \
  --keep "$STEPS" "${COMMON[@]}" $SRC_ARGS || exit 1
[ "$(wc -l < "$WORK/losses_ref.txt")" -eq "$STEPS" ] || {
  echo "reference run logged wrong number of steps" >&2; exit 1; }

echo "elastic_resume_smoke: [2/5] interrupted run (SIGKILL mid-save," \
     "source mesh)" >&2
rm -rf "$WORK/ckpt_crash"; rm -f "$WORK/losses_crash.txt"
# background the python DIRECTLY (no function/subshell wrapper): $! must
# be the trainer's own PID or the SIGKILL hits a wrapper and the trainer
# survives to completion, making the resume vacuous.  --step-delay
# throttles ONLY this run (cache is warm from leg 1) so the kill window
# is deterministic.
# shellcheck disable=SC2086
"$PYTHON" -m apex_tpu.testing.crash_resume \
  --ckpt-dir "$WORK/ckpt_crash" --losses "$WORK/losses_crash.txt" \
  "${COMMON[@]}" $SRC_ARGS --step-delay 0.6 &
PID=$!
# KILL_WAIT_S bounds how long we poll for the kill point — generous,
# because the model-parallel legs (tp/pp > 1) recompile a larger program
# and a loaded CI host can take minutes to log the first loss line.
n=0
for _ in $(seq 1 "$((${KILL_WAIT_S:-420} * 10))"); do
  n=0
  [ -f "$WORK/losses_crash.txt" ] && n=$(wc -l < "$WORK/losses_crash.txt")
  if [ "$n" -ge "$KILL_AFTER" ]; then break; fi
  if ! kill -0 "$PID" 2>/dev/null; then
    echo "trainer exited before the kill point" >&2; wait "$PID"; exit 1
  fi
  sleep 0.1
done
[ "$n" -ge "$KILL_AFTER" ] || {
  kill -9 "$PID" 2>/dev/null; wait "$PID" 2>/dev/null
  echo "trainer never reached the kill point ($n/$KILL_AFTER steps in" \
       "${KILL_WAIT_S:-420}s) — raise KILL_WAIT_S" >&2; exit 1; }
kill -9 "$PID" 2>/dev/null
wait "$PID" 2>/dev/null
KILLED_AT=$(wc -l < "$WORK/losses_crash.txt")
echo "elastic_resume_smoke: killed after $KILLED_AT steps" >&2
[ "$KILLED_AT" -lt "$STEPS" ] || {
  echo "trainer completed before SIGKILL landed — raise STEPS" >&2; exit 1; }

echo "elastic_resume_smoke: [3/5] resume on target mesh ($DST_ARGS)" >&2
# shellcheck disable=SC2086
"$PYTHON" -m apex_tpu.testing.crash_resume \
  --ckpt-dir "$WORK/ckpt_crash" --losses "$WORK/losses_crash.txt" \
  "${COMMON[@]}" $DST_ARGS --resume \
  --fingerprint "$WORK/fp_elastic.txt" 2> "$WORK/resume.log" || {
    cat "$WORK/resume.log" >&2; exit 1; }
cat "$WORK/resume.log" >&2
R=$(sed -n 's/.*resumed from step \([0-9]*\).*/\1/p' "$WORK/resume.log")
[ -n "$R" ] || { echo "resume leg never restored a checkpoint" >&2; exit 1; }
# pre-kill prefix: source-mesh steps must match the uninterrupted
# source-mesh reference bit-exactly (0..R survived the kill + truncate)
if ! cmp -s <(head -n "$((R + 1))" "$WORK/losses_ref.txt") \
            <(head -n "$((R + 1))" "$WORK/losses_crash.txt"); then
  echo "elastic_resume_smoke: FAIL — pre-kill loss prefix differs:" >&2
  diff <(head -n "$((R + 1))" "$WORK/losses_ref.txt") \
       <(head -n "$((R + 1))" "$WORK/losses_crash.txt") >&2 || true
  exit 1
fi

echo "elastic_resume_smoke: [4/5] clean-reshard reference (step $R," \
     "no kill) on target mesh" >&2
STEP_DIR=$(printf 'step_%08d' "$R")
rm -rf "$WORK/ckpt_clean"; mkdir -p "$WORK/ckpt_clean"
cp -r "$WORK/ckpt_ref/$STEP_DIR" "$WORK/ckpt_clean/" || {
  echo "reference checkpoint $STEP_DIR missing" >&2; exit 1; }
cp "$WORK/losses_ref.txt" "$WORK/losses_clean.txt"
# shellcheck disable=SC2086
"$PYTHON" -m apex_tpu.testing.crash_resume \
  --ckpt-dir "$WORK/ckpt_clean" --losses "$WORK/losses_clean.txt" \
  "${COMMON[@]}" $DST_ARGS --resume \
  --fingerprint "$WORK/fp_clean.txt" 2> "$WORK/clean.log" || {
    cat "$WORK/clean.log" >&2; exit 1; }
cat "$WORK/clean.log" >&2
R2=$(sed -n 's/.*resumed from step \([0-9]*\).*/\1/p' "$WORK/clean.log")
[ "$R2" = "$R" ] || {
  echo "clean leg resumed from step ${R2:-none}, expected $R" >&2; exit 1; }

echo "elastic_resume_smoke: [5/5] comparing curves + state digests" >&2
[ "$(wc -l < "$WORK/losses_crash.txt")" -eq "$STEPS" ] || {
  echo "resumed run logged wrong number of steps" >&2; exit 1; }
if ! cmp -s "$WORK/losses_crash.txt" "$WORK/losses_clean.txt"; then
  echo "elastic_resume_smoke: FAIL — post-resume loss curves differ:" >&2
  diff "$WORK/losses_crash.txt" "$WORK/losses_clean.txt" >&2 || true
  exit 1
fi
if ! cmp -s "$WORK/fp_elastic.txt" "$WORK/fp_clean.txt"; then
  echo "elastic_resume_smoke: FAIL — final state digests differ:" >&2
  diff "$WORK/fp_elastic.txt" "$WORK/fp_clean.txt" >&2 || true
  exit 1
fi
echo "elastic_resume_smoke: PASS — killed-at-N / resumed-at-M run is" \
     "bit-identical to the clean reshard continuation" >&2
exit 0
