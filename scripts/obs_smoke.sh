#!/usr/bin/env bash
# Observability smoke (ISSUE 10 satellite): run the driver dryrun with
# the FLIGHT RECORDER armed and prove the whole run-timeline layer end
# to end:
#   - the spilled timeline parses under read_jsonl(strict=True) (the
#     crash-safe torn-tail contract),
#   - the goodput report's buckets are exhaustive and disjoint — they
#     sum to the recorder's wall-clock, the recorder's wall-clock
#     matches the driver's independent stopwatch within 2%, and the
#     offline recompute over the spilled file agrees with the armed
#     recorder's incremental accounting,
#   - the /metrics endpoint scrapes (Prometheus text) and /statusz
#     serves the timeline tail + goodput-so-far.
# Companion to telemetry_smoke.sh (ISSUE 5, the metrics pipeline) —
# wired fast-tier in tests/test_aux_subsystems.py.
#
# Usage: scripts/obs_smoke.sh [N_DEVICES] [OUT_DIR]
#   N_DEVICES  virtual CPU mesh size for dryrun_multichip (default 8;
#              the fast-tier test uses 2 to keep the XLA compile small)
#   OUT_DIR    where timeline.jsonl/goodput.json land (default: mktemp)
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
N_DEVICES="${1:-8}"
OUT_DIR="${2:-$(mktemp -d /tmp/apex_tpu_obs.XXXXXX)}"
mkdir -p "$OUT_DIR"

echo "obs_smoke: dryrun_multichip(${N_DEVICES}) with flight recorder -> ${OUT_DIR}" >&2

cd "$REPO"
APEX_TPU_TIMELINE_DIR="$OUT_DIR" python -c \
  "import __graft_entry__ as g; g.dryrun_multichip(${N_DEVICES})" \
  2> >(tee "$OUT_DIR/dryrun.stderr" >&2)

python - "$OUT_DIR" <<'EOF'
import json
import re
import sys
import urllib.request

out_dir = sys.argv[1]

from apex_tpu.observability import (
    DebugServer, FlightRecorder, MetricRegistry, read_jsonl)
from apex_tpu.observability.goodput import goodput_report

# -- timeline parses under the strict crash-safety semantics ----------
events = read_jsonl(f"{out_dir}/timeline.jsonl", strict=True)
assert events, "no timeline events spilled"
kinds = [e["kind"] for e in events]
assert kinds[0] == "run_begin" and "run_end" in kinds, kinds
assert "compile" in kinds and "step" in kinds, kinds

# -- goodput: exhaustive + disjoint, and online == offline ------------
with open(f"{out_dir}/goodput.json") as f:
    flushed = json.load(f)
wall = flushed["wall_s"]
ssum = sum(flushed["buckets"].values())
assert abs(ssum - wall) <= 0.02 * wall, (
    f"buckets sum {ssum} != wall {wall}")
assert flushed["overcommit_s"] <= 0.02 * wall, flushed
offline = goodput_report(events)
assert abs(offline["wall_s"] - wall) <= 0.02 * wall, (offline, flushed)
for name, sec in flushed["buckets"].items():
    assert abs(offline["buckets"][name] - sec) <= max(0.02 * wall, 1e-3), (
        name, offline["buckets"][name], sec)
assert flushed["buckets"]["compile"] > 0, flushed
assert flushed["buckets"]["compute"] > 0, flushed

# -- the recorder's clock vs the driver's independent stopwatch -------
stderr = open(f"{out_dir}/dryrun.stderr").read()
m = re.search(r"driver_wall_s=([0-9.]+) recorder_wall_s=([0-9.]+)", stderr)
assert m, f"no goodput stopwatch line in dryrun stderr:\n{stderr[-500:]}"
driver_wall, rec_wall = float(m.group(1)), float(m.group(2))
assert abs(driver_wall - rec_wall) <= 0.02 * driver_wall, (
    driver_wall, rec_wall)

# -- /metrics scrapes + /statusz serves the tail ----------------------
registry = MetricRegistry()
registry.counter("smoke/events").inc(len(events))
registry.histogram("smoke/lat_ms", keep_samples=8).observe(1.5)
rec = FlightRecorder()
for ev in events[1:]:  # replay into a live recorder (skip its run_begin)
    ev = dict(ev)
    ev.pop("t", None)
    rec.emit(ev.pop("kind"), dur_s=ev.pop("dur_s", None), **ev)
with DebugServer(registry=registry, recorder=rec) as srv:
    metrics = urllib.request.urlopen(srv.url("/metrics"), timeout=10).read()
    text = metrics.decode()
    assert "apex_smoke_events" in text and "# TYPE" in text, text[:400]
    assert "apex_smoke_lat_ms_count" in text, text[:400]
    statusz = json.loads(urllib.request.urlopen(
        srv.url("/statusz"), timeout=10).read())
    assert statusz["timeline"], statusz
    assert statusz["goodput"]["buckets"]["compile"] > 0, statusz

print(f"obs_smoke OK: {len(events)} timeline events, wall {wall:.2f}s, "
      f"goodput {flushed['goodput_fraction']:.3f} "
      f"(compile {flushed['buckets']['compile']:.2f}s, "
      f"compute {flushed['buckets']['compute']:.2f}s), "
      "/metrics + /statusz scraped")
EOF
