#!/bin/bash
# TPU evidence capture, v3 — capture2's wedge-aware structure plus the
# harnesses built since it launched, ordered by evidentiary value:
#
#   1. bench.py full 9-row matrix   (internal poller + wedge-pause, 6 h window)
#   2. lamb-vs-syncbn A/B           (--one diagnostics: which factor costs 3.4x)
#   3. GPT batch sweep              (MFU 0.4155 @ batch 8 -> probe 16/32)
#   4. flash block sweep seq 1024   (auto-lands tuned defaults on TPU)
#   5. GPT step profile             (the MFU gap's trace)
#   6. RN50 lamb+syncbn profile     (the slow row's trace)
#   7. flash block sweep seq 8192
#   8. remat_ticks memory           (virtual-mesh 4-10x claim -> XLA stats)
#   9. pipeline tick anchor
#  10. re-bench                     (picks up tuned blocks; never overwrites)
#
# Every non-bench stage gates on a live-chip probe: a wedge costs
# probe-time, not stage budget.  Evidence lands incrementally.
set -u
cd "$(dirname "$0")/.."
LOG=.tpu_watch/capture3.log
mkdir -p .tpu_watch bench_results
stamp() { date +%H:%M:%S; }
log() { echo "== $(stamp) $*" >> "$LOG"; }
probe() {
  timeout 90 python -c \
    "import jax; assert jax.devices()[0].platform == 'tpu'" \
    >/dev/null 2>&1
}
wait_for_chip() {
  until probe; do log "chip down; re-probing in 120s"; sleep 120; done
  log "chip up"
}
run() {
  log "start: $*"
  timeout "${STAGE_TIMEOUT:-2400}" "$@" >> "$LOG" 2>&1
  log "rc=$? ($1 $2)"
}

log "capture3 start"
STAGE_TIMEOUT=22000 BENCH_DEADLINE_S=21600 run python bench.py

wait_for_chip
STAGE_TIMEOUT=600 run python bench.py --one resnet50_sgd_syncbn
wait_for_chip
STAGE_TIMEOUT=600 run python bench.py --one resnet50_lamb_nosync
wait_for_chip
run python examples/tune_gpt_batch.py
wait_for_chip
run python examples/tune_flash_blocks.py --seq 1024 --timeout 600
wait_for_chip
STAGE_TIMEOUT=1200 run python examples/profile_gpt.py
wait_for_chip
STAGE_TIMEOUT=1200 run python examples/profile_resnet.py --optimizer lamb --sync-bn
wait_for_chip
run python examples/tune_flash_blocks.py --seq 8192 --steps 5 --timeout 600
wait_for_chip
STAGE_TIMEOUT=1200 run python examples/measure_remat_memory.py
wait_for_chip
STAGE_TIMEOUT=1200 run python examples/measure_pipeline_tick.py
wait_for_chip
BENCH_DEADLINE_S=2100 run python bench.py
log "capture3 done"
