#!/usr/bin/env bash
# Fleet-serving smoke (ISSUE 11): the 3-replica fault matrix on the CPU
# mesh, with real processes and real signals —
#   - SIGKILL one replica mid-decode: the router detects the dead pipe,
#     replays its in-flight requests on the survivors, and every stream
#     stays BITWISE IDENTICAL to an uninterrupted greedy reference;
#   - submit flood past the fleet bound: typed REJECTED terminal states
#     + serving/requests_rejected, never a silent hang;
#   - staggered zero-downtime weight rollout under load: SIGTERM drain
#     -> restore newest VERIFIED checkpoint (corrupt newest falls back)
#     -> rejoin, with zero failed requests and bounded p99 TPOT;
#   - /healthz answers ok on live replicas, refuses on the killed one;
#   - socket-transport leg (ISSUE 14): three replica_serve daemons over
#     loopback framed TCP behind ChaosProxy — one wire PARTITIONED and
#     one host SIGKILLed mid-decode, every stream token-identical to
#     the in-process reference, the router unchanged.
# Router policy logic is unit-tested hermetically in
# tests/test_fleet.py (transport + chaos in tests/test_transport.py);
# this script is the end-to-end proof.  Wired fast-tier in
# tests/test_aux_subsystems.py like the PR 8/9 smokes.
#
# Usage: scripts/fleet_smoke.sh
#   FLEET_SMOKE_PHASES=ABC skips the socket-chaos phase D (fast tier;
#   the slow-tier twin runs ABCD — ISSUE 18 tier-budget satellite).
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
PYTHON="${PYTHON:-python}"

cd "$REPO"
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  "$PYTHON" apex_tpu/testing/fleet_smoke.py
echo "PASS" >&2
