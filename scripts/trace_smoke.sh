#!/usr/bin/env bash
# Distributed-tracing smoke (ISSUE 15): a 3-replica loopback socket
# fleet with tracing armed in EVERY process —
#   - one replica SIGKILLed mid-decode: the merged spill directory
#     yields ONE trace for the killed request, spanning both replicas,
#     with failover_replay time attributed and the per-request books
#     exactly closed (overcommit 0, unattributed 0);
#   - every request's hop-bucket sum matches a router-side stopwatch
#     within 2%;
#   - /fleet/statusz serves the per-tenant SLO plane over HTTP, and
#     scripts/trace_report.py parses the spill dir strictly (exit 0).
# The stitcher's clock algebra is unit-tested with injected clocks in
# tests/test_trace.py; this script is the end-to-end proof.  Wired
# fast-tier in tests/test_aux_subsystems.py like the PR 8/9/11 smokes.
#
# Usage: scripts/trace_smoke.sh
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
PYTHON="${PYTHON:-python}"

cd "$REPO"
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  "$PYTHON" apex_tpu/testing/trace_smoke.py
echo "PASS" >&2
