#!/usr/bin/env bash
# Crash/resume smoke (ISSUE 3): the save→SIGKILL→resume proof, end to
# end through the 3D GPT trainer (apex_tpu.testing.crash_resume).
#
#   1. an uninterrupted N-step run records its loss curve;
#   2. a second run is SIGKILLed mid-run (after >= KILL_AFTER checkpoints
#      landed — the kill races the in-flight async sharded save on
#      purpose: whatever state disk is in, recovery must work);
#   3. optionally ($CORRUPT_NEWEST=1) the newest checkpoint is bit-flipped
#      on top, so the resume must ALSO fall back past it by checksum;
#   4. the run is resumed from the latest verified checkpoint and must
#      reproduce the uninterrupted loss curve BIT-EXACTLY (losses are
#      logged as raw fp32 bits).
#
# Usage: scripts/crash_resume_smoke.sh [workdir]
# Env: STEPS (default 6), KILL_AFTER (default 2), CORRUPT_NEWEST (0/1),
#      PYTHON (default python).
# Exit 0 = bit-exact resume; non-zero otherwise.
set -u -o pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
WORK="${1:-$(mktemp -d)}"
STEPS="${STEPS:-6}"
KILL_AFTER="${KILL_AFTER:-2}"
CORRUPT_NEWEST="${CORRUPT_NEWEST:-0}"
PYTHON="${PYTHON:-python}"
mkdir -p "$WORK"
cd "$REPO"

run_trainer() { # args: ckpt_dir losses_file [extra flags...]
  "$PYTHON" -m apex_tpu.testing.crash_resume \
    --ckpt-dir "$1" --losses "$2" --steps "$STEPS" "${@:3}"
}

echo "crash_resume_smoke: [1/4] uninterrupted run" >&2
rm -f "$WORK/losses_ref.txt"
run_trainer "$WORK/ckpt_ref" "$WORK/losses_ref.txt" || exit 1
[ "$(wc -l < "$WORK/losses_ref.txt")" -eq "$STEPS" ] || {
  echo "reference run logged wrong number of steps" >&2; exit 1; }

echo "crash_resume_smoke: [2/4] interrupted run (SIGKILL mid-run)" >&2
rm -rf "$WORK/ckpt_crash"; rm -f "$WORK/losses_crash.txt"
# background the python DIRECTLY (no function/subshell wrapper): $! must
# be the trainer's own PID or the SIGKILL hits a wrapper and the trainer
# survives to completion, making the resume vacuous.  --step-delay
# throttles ONLY this run: with the compilation cache warm from run 1,
# an unthrottled trainer can finish all steps between two poll ticks and
# the SIGKILL would race (observed flake) — the per-step sleep while the
# async save is in flight makes the kill window deterministic.
"$PYTHON" -m apex_tpu.testing.crash_resume \
  --ckpt-dir "$WORK/ckpt_crash" --losses "$WORK/losses_crash.txt" \
  --steps "$STEPS" --step-delay 0.6 &
PID=$!
# wait until KILL_AFTER losses are logged (=> that many saves kicked
# off), then SIGKILL — possibly mid-async-sharded-write
for _ in $(seq 1 600); do
  n=0
  [ -f "$WORK/losses_crash.txt" ] && n=$(wc -l < "$WORK/losses_crash.txt")
  if [ "$n" -ge "$KILL_AFTER" ]; then break; fi
  if ! kill -0 "$PID" 2>/dev/null; then
    echo "trainer exited before the kill point" >&2; wait "$PID"; exit 1
  fi
  sleep 0.1
done
kill -9 "$PID" 2>/dev/null
wait "$PID" 2>/dev/null
KILLED_AT=$(wc -l < "$WORK/losses_crash.txt")
echo "crash_resume_smoke: killed after $KILLED_AT steps" >&2
# the crash must be real: a trainer that finished anyway proves nothing
[ "$KILLED_AT" -lt "$STEPS" ] || {
  echo "trainer completed before SIGKILL landed — raise STEPS" >&2; exit 1; }

if [ "$CORRUPT_NEWEST" = "1" ]; then
  echo "crash_resume_smoke: [3/4] bit-flipping the newest checkpoint" >&2
  # the injection must not fail silently: a skipped corruption would
  # green-light a run that never exercised the checksum-fallback path
  "$PYTHON" - "$WORK/ckpt_crash" <<'EOF'
import os, sys
from apex_tpu.testing import faults
root = sys.argv[1]
# newest step dir that actually HAS a shard: the SIGKILL may have left
# the very newest dir empty (created, shard never durable)
steps = sorted(d for d in os.listdir(root) if d.startswith("step_")
               and os.path.exists(os.path.join(root, d, "shard_0.npz")))
if not steps:
    sys.exit("no corruptible checkpoint found")
target = os.path.join(root, steps[-1])
print("corrupting", faults.corrupt_checkpoint(target), file=sys.stderr)
EOF
  [ $? -eq 0 ] || { echo "corruption injection failed" >&2; exit 1; }
else
  echo "crash_resume_smoke: [3/4] skipping corruption (CORRUPT_NEWEST=0)" >&2
fi

echo "crash_resume_smoke: [4/4] resume from latest verified checkpoint" >&2
run_trainer "$WORK/ckpt_crash" "$WORK/losses_crash.txt" --resume || exit 1

if cmp -s "$WORK/losses_ref.txt" "$WORK/losses_crash.txt"; then
  echo "crash_resume_smoke: PASS — resumed loss curve bit-identical" >&2
  exit 0
else
  echo "crash_resume_smoke: FAIL — loss curves differ:" >&2
  diff "$WORK/losses_ref.txt" "$WORK/losses_crash.txt" >&2 || true
  exit 1
fi
