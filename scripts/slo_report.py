#!/usr/bin/env python
"""Reconstruct a fleet run's SLO story from its timeline spills.

The offline half of the ISSUE 20 burn-rate plane: point it at the same
spill directory ``trace_report.py`` reads and it replays the router's
``slo_burn_alert`` / ``slo_burn_clear`` transitions and the periodic
``slo_state`` budget-table snapshots into

- a **budget table** — per (policy, metric): latest fast/slow burn
  rates, remaining error budget, projected time-to-exhaustion at the
  current slow burn, alerting flag;
- the **worst burner** — the row with the highest slow-window burn
  (the one that exhausts budgets);
- the **alert timeline** — every transition in spill order with its
  in-record evidence.

Usage::

    python scripts/slo_report.py <spill-dir>           # human block
    python scripts/slo_report.py <spill-dir> --json    # full JSON
    python scripts/slo_report.py <spill-dir> --check   # CI gate

Exit status: 0 clean, 2 on usage/IO errors.  ``--check`` exits 1 when
the run ended in a bad SLO state: any budget fully exhausted in the
final snapshot, any alert still open at end of spill, or a
clear-without-alert imbalance (more clears than alerts for one
(policy, metric) — an evaluator state-machine bug, never hidden).
A spill with no SLO events passes trivially: a disarmed fleet has
nothing to gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _counts(rows, key):
    out = {}
    for ev in rows:
        k = (str(ev.get("policy")), str(ev.get("metric")))
        out[k] = out.get(k, 0) + 1
    return out


def _format(slo: dict) -> str:
    lines = ["== slo report =="]
    states = slo["states"]
    rows = states[-1]["rows"] if states else []
    if rows:
        lines.append("budget table (latest snapshot):")
        lines.append(f"  {'policy':<16} {'metric':<36} {'fast':>8} "
                     f"{'slow':>8} {'budget':>8} {'exhaust_s':>10}  state")
        for r in sorted(rows, key=lambda r: (-r["burn_slow"],
                                             r["policy"], r["metric"])):
            ex = r.get("exhaustion_s")
            lines.append(
                f"  {r['policy']:<16} {r['metric']:<36} "
                f"{r['burn_fast']:>8.2f} {r['burn_slow']:>8.2f} "
                f"{r['budget_remaining']:>8.4f} "
                f"{'-' if ex is None else format(ex, '>10.1f'):>10}  "
                f"{'ALERT' if r.get('alerting') else 'ok'}")
        worst = max(rows, key=lambda r: r["burn_slow"])
        lines.append(f"worst burner: {worst['policy']} on "
                     f"{worst['metric']} (slow burn "
                     f"{worst['burn_slow']:.2f}x)")
    else:
        lines.append("no slo_state snapshots in spill")
    timeline = sorted(
        ([("alert", ev) for ev in slo["alerts"]]
         + [("clear", ev) for ev in slo["clears"]]),
        key=lambda kv: kv[1].get("t", 0.0))
    lines.append(f"alert timeline ({len(slo['alerts'])} alert(s), "
                 f"{len(slo['clears'])} clear(s)):")
    for what, ev in timeline:
        lines.append(
            f"  t={ev.get('t', 0.0):>10.3f} {what.upper():<5} "
            f"{ev.get('policy')} on {ev.get('metric')} "
            f"(fast {ev.get('burn_fast')}x, slow {ev.get('burn_slow')}x, "
            f"budget {ev.get('budget_remaining')})")
    if slo["open"]:
        lines.append("OPEN at end of spill: " + ", ".join(
            f"{p}:{m}" for p, m in slo["open"]))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="replay a fleet spill's SLO burn-rate story: budget "
                    "table, worst burner, alert timeline")
    ap.add_argument("dir", help="the fleet run's timeline spill dir")
    ap.add_argument("--json", action="store_true",
                    help="print the collected SLO events as JSON")
    ap.add_argument("--no-strict", action="store_true",
                    help="tolerate interior JSONL corruption")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: exit 1 on exhausted budget, open "
                         "alert at end of spill, or alert/clear "
                         "imbalance")
    args = ap.parse_args(argv)

    from apex_tpu.observability.trace import (
        collect_slo_events, read_fleet_spills)

    try:
        router_run, _replicas = read_fleet_spills(
            args.dir, strict=not args.no_strict)
    except (OSError, ValueError) as e:
        print(f"slo_report: {e}", file=sys.stderr)
        return 2
    slo = collect_slo_events(router_run)

    if args.json:
        print(json.dumps(
            dict(slo, open=[list(k) for k in slo["open"]]), indent=1))
    else:
        print(_format(slo))

    if args.check:
        bad = []
        final_rows = slo["states"][-1]["rows"] if slo["states"] else []
        for r in final_rows:
            if r["budget_remaining"] <= 0:
                bad.append(f"budget exhausted: {r['policy']} on "
                           f"{r['metric']}")
        alerts, clears = _counts(slo["alerts"], "a"), \
            _counts(slo["clears"], "c")
        for k, n in sorted(clears.items()):
            if n > alerts.get(k, 0):
                bad.append(f"clear/alert imbalance: {k[0]} on {k[1]} "
                           f"({n} clears > {alerts.get(k, 0)} alerts)")
        for p, m in slo["open"]:
            bad.append(f"alert still open at end of spill: {p} on {m}")
        if bad:
            for msg in bad:
                print(f"slo_report: {msg}", file=sys.stderr)
            return 1
        print(f"slo_report: check ok ({len(slo['alerts'])} alert(s), "
              f"{len(slo['clears'])} clear(s), "
              f"{len(slo['states'])} snapshot(s))", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
