#!/bin/bash
# TPU evidence capture, v2 — wedge-aware sequel to tpu_capture.sh.
#
# What r4's first window taught us (.tpu_watch/capture.log):
#   * the tunneled chip gives SHORT windows (15 min up, then wedged for
#     40+ min) — a fixed stage list burns hours of timeouts against a
#     dead tunnel (observed: 4x 420 s sweep-point timeouts in a row);
#   * bench.py's own poll loop (probe -> suite -> wedge-pause -> re-poll)
#     is the right shape, so stage 1 just runs it with a LONG window and
#     the stages that lack a poller get an explicit wait_for_chip gate.
#
# Evidence lands incrementally (stamped bench_results/tpu_*.json after
# every config; sweep jsonl per point), so a kill at any moment keeps
# everything already measured.
set -u
cd "$(dirname "$0")/.."
LOG=.tpu_watch/capture2.log
mkdir -p .tpu_watch bench_results
stamp() { date +%H:%M:%S; }
log() { echo "== $(stamp) $*" >> "$LOG"; }
probe() {
  timeout 90 python -c \
    "import jax; assert jax.devices()[0].platform == 'tpu'" \
    >/dev/null 2>&1
}
wait_for_chip() {
  until probe; do log "chip down; re-probing in 120s"; sleep 120; done
  log "chip up"
}
run() {
  log "start: $*"
  timeout "${STAGE_TIMEOUT:-2400}" "$@" >> "$LOG" 2>&1
  log "rc=$? ($1 $2)"
}

log "capture2 start"
# Stage 1: the full 9-config matrix. bench.py polls for the chip across
# the whole window and handles mid-suite wedges itself; 6 h window.
STAGE_TIMEOUT=22000 BENCH_DEADLINE_S=21600 run python bench.py

# Hardware tuning/profiling stages: each gated on a live chip so a wedge
# costs probe-time, not stage-timeouts.  Sweep points get 600 s (420 s
# proved tight even healthy: full train-step recompile per block size).
wait_for_chip
run python examples/tune_flash_blocks.py --seq 1024 --timeout 600
wait_for_chip
run python examples/profile_gpt.py
wait_for_chip
run python examples/tune_flash_blocks.py --seq 8192 --steps 5 --timeout 600
wait_for_chip
run python examples/measure_remat_memory.py
wait_for_chip
run python examples/measure_pipeline_tick.py
# Final re-bench picks up any tuned flash blocks; never overwrites
# earlier stamped records.
wait_for_chip
BENCH_DEADLINE_S=2100 run python bench.py
log "capture2 done"
