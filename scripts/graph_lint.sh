#!/usr/bin/env bash
# Graph lint (ISSUE 4): run the static-analysis rulebook over every
# registered entry config (3D GPT trainer, ZeRO train steps, dryrun MoE
# config, overlap rings, reshard restore, serving decode) on the CPU
# mesh.  Exit 0 = no ERROR finding.
#
# This is the CI face of apex_tpu.analysis: the rules that mechanize the
# repo's mesh-correctness invariants (docs/analysis.md has the rulebook).
# The fast tier runs the identical check in-process
# (tests/test_analysis.py::test_graph_lint_all_entries_exits_zero), so a
# red finding fails the suite; this script is for shells, pre-push hooks
# and bench boxes.
#
# Usage: scripts/graph_lint.sh [extra apex_tpu.analysis args]
#   e.g. scripts/graph_lint.sh --entries overlap,zero_flat
#        scripts/graph_lint.sh --list-rules
# Env: PYTHON (default python).
set -u -o pipefail

cd "$(dirname "$0")/.."
args=("$@")
if [ ${#args[@]} -eq 0 ]; then
    args=(--all-entries)
fi
exec "${PYTHON:-python}" -m apex_tpu.analysis "${args[@]}"
