#!/usr/bin/env bash
# Graph lint (ISSUE 4, control tier ISSUE 19): run the static-analysis
# rulebook over every registered entry config (3D GPT trainer, ZeRO
# train steps, dryrun MoE config, overlap rings, reshard restore,
# serving decode) on the CPU mesh, plus the two whole-tier
# pseudo-entries: control_plane (APX301-304 AST lint over the serving
# control-plane sources) and stability (APX305 churn-sweep structure
# hashes of the serving programs).  Exit 0 = no ERROR finding.
#
# This is the CI face of apex_tpu.analysis: the rules that mechanize the
# repo's mesh-correctness invariants (docs/analysis.md has the rulebook).
# The fast tier runs the same check in-process
# (tests/test_analysis.py::test_graph_lint_all_entries_exits_zero covers
# the graph entries + control tier; tests/test_aux_subsystems.py gates
# the stability sweep), so a red finding fails the suite; this script is
# for shells, pre-push hooks and bench boxes.
#
# Usage: scripts/graph_lint.sh [extra apex_tpu.analysis args]
#   e.g. scripts/graph_lint.sh --entries overlap,zero_flat
#        scripts/graph_lint.sh --list-rules
# Env: PYTHON (default python).
set -u -o pipefail

cd "$(dirname "$0")/.."
args=("$@")
if [ ${#args[@]} -eq 0 ]; then
    args=(--all-entries)
fi
exec "${PYTHON:-python}" -m apex_tpu.analysis "${args[@]}"
