#!/usr/bin/env bash
# Input-pipeline smoke (ISSUE 8 satellite): drive every layer of
# apex_tpu.data end to end — synthetic JPEG tree through the
# process-pool ImageFolderLoader + double-buffered prefetch_to_device,
# and a packed LM token stream through a DataService loader process —
# asserting NONZERO OVERLAP (double-buffered stall < synchronous pull on
# the same loader) and CLEAN SHUTDOWN (no leaked worker/service
# processes).  Wired into the fast tier like telemetry_smoke.sh
# (tests/test_aux_subsystems.py::test_data_pipeline_smoke_script).
#
# Usage: scripts/data_pipeline_smoke.sh [WORK_DIR]
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
WORK="${1:-$(mktemp -d /tmp/apex_tpu_data_smoke.XXXXXX)}"
PYTHON="${PYTHON:-python}"

echo "data_pipeline_smoke: -> ${WORK}" >&2
cd "$REPO"
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  "$PYTHON" apex_tpu/testing/data_pipeline_smoke.py "$WORK"
echo "PASS" >&2
