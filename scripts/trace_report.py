#!/usr/bin/env python
"""Merge a fleet run's timeline spills into per-request traces.

The offline half of the ISSUE 15 tracing plane: point it at the
directory every fleet process spilled into (the router armed via
``trace.arm_process(dir, "router", ...)``, each replica via
``ReplicaSpec(timeline_dir=dir)``) and it stitches one span tree per
``trace_id`` across all processes — clock-aligned onto the router
host's monotonic clock through the spilled ``link_clock`` samples —
and attributes every wall-clock millisecond of every request to
exactly one hop bucket (router_queue / wire / replica_queue /
admission_wait / prefill / decode / preempted / failover_replay /
kv_migrate).

Hop glossary: router_queue = waiting in the router pool; wire =
dispatch → replica submit plus the replica-finish → router-finish
return leg; replica_queue = the engine's waiting deque;
admission_wait = admitted but the packed prefill hasn't picked the
slot up; prefill = chunked-prefill activity; decode = steady-state
token generation; preempted = evicted-awaiting-readmit; failover_replay
= death detection + probe ladder + requeue after a replica died;
kv_migrate = the disaggregation handoff (ISSUE 16): KV export on the
prefill replica + the per-block relay + the import commit, from
``fleet_migrate_start`` to the dispatch onto the decode replica.

When an SLO autopilot ran (ISSUE 18), the router spill also carries
its typed decision events; the report appends the reconstructed
decision timeline (``apN [loop] action -> verdict  # reason``) so the
"why did the fleet change shape" answer prints next to the request
traces that made it.

Usage::

    python scripts/trace_report.py <spill-dir>            # human block
    python scripts/trace_report.py <spill-dir> --json     # full JSON
    python scripts/trace_report.py <spill-dir> --trace <id>  # one tree
    python scripts/trace_report.py <spill-dir> --tail-pct 95
    python scripts/trace_report.py <spill-dir> --check    # CI gate

Exit status: 0 on a clean merge, 1 when any trace carries overcommit
(double-counted time — an instrumentation bug, never hidden), 2 on
usage/IO errors.  ``--no-strict`` tolerates interior JSONL corruption
(the default is strict: a torn *tail* is always tolerated — that is
the expected SIGKILL artifact — but a torn interior line fails the
merge).

``--check`` makes the trace plane's own invariant CI-checkable instead
of merely printable: beyond the overcommit gate it also fails (exit 1)
when the merge left more than ``--max-unattributed-pct`` (default 5%)
of total request wall time in no hop bucket — attribution rotting
quietly is exactly how a tail regression hides.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="stitch fleet timeline spills into per-request "
                    "hop-attributed traces")
    ap.add_argument("dir", help="the fleet run's timeline spill dir")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--trace", default=None,
                    help="print one trace's span tree by trace_id")
    ap.add_argument("--tail-pct", type=float, default=99.0,
                    help="tail percentile for slowest-hop attribution "
                         "(default 99)")
    ap.add_argument("--no-strict", action="store_true",
                    help="tolerate interior JSONL corruption")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: exit 1 on any overcommit OR on "
                         "unattributed time above --max-unattributed-pct")
    ap.add_argument("--max-unattributed-pct", type=float, default=5.0,
                    help="--check threshold: max unattributed share of "
                         "total request wall time, in percent "
                         "(default 5)")
    args = ap.parse_args(argv)

    from apex_tpu.observability.trace import (
        format_trace_report, merge_dir)

    try:
        report = merge_dir(args.dir, strict=not args.no_strict,
                           tail_pct=args.tail_pct)
    except (OSError, ValueError) as e:
        print(f"trace_report: {e}", file=sys.stderr)
        return 2

    if args.trace is not None:
        rec = report["traces"].get(args.trace)
        if rec is None:
            print(f"trace_report: unknown trace_id {args.trace!r}",
                  file=sys.stderr)
            return 2
        print(json.dumps(rec, indent=1))
    elif args.json:
        print(json.dumps(report, indent=1))
    else:
        print(format_trace_report(report))
    summary = report["summary"]
    overcommit = summary["overcommit_s"]
    if overcommit > 0:
        print(f"trace_report: OVERCOMMIT {overcommit:.6f}s (double-"
              "counted time — instrumentation bug)", file=sys.stderr)
        return 1
    if args.check:
        unattributed = summary.get("unattributed_s", 0.0)
        wall = sum(summary.get("hop_totals_s", {}).values()) \
            + unattributed
        pct = 100.0 * unattributed / wall if wall > 0 else 0.0
        if pct > args.max_unattributed_pct:
            print(f"trace_report: UNATTRIBUTED {unattributed:.6f}s "
                  f"({pct:.2f}% of wall > "
                  f"{args.max_unattributed_pct:g}% budget) — hop "
                  "attribution is rotting", file=sys.stderr)
            return 1
        print(f"trace_report: check ok ({summary['requests']} "
              f"request(s), 0 overcommit, {pct:.2f}% unattributed)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
