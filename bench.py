"""Headline benchmark: ResNet-50 mixed-precision (O2) training throughput.

Runs the reference's headline config (``examples/imagenet/main_amp.py``:
ResNet-50, amp O2, FusedSGD) as apex_tpu's SPMD train step on whatever
devices are attached and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

``vs_baseline`` normalizes against an adopted per-A100 figure for Apex RN50
AMP training (the repo itself publishes no numbers — BASELINE.md): NVIDIA NGC
PyTorch+Apex RN50 AMP convergence runs report ~2.5k images/sec per A100-80GB
at batch 256 with DALI input.  We record throughput per chip so the number is
comparable across mesh sizes.
"""

import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

APEX_A100_IMAGES_PER_SEC = 2500.0  # adopted baseline, see module docstring


def main():
    from apex_tpu import amp
    from apex_tpu.models import ResNet50
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.parallel import dp_shard_batch, mesh as mesh_lib, replicate

    n_chips = len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"
    batch_per_chip = 128 if on_tpu else 4
    image_size = 224 if on_tpu else 32
    steps = 30 if on_tpu else 3
    batch = batch_per_chip * n_chips

    mesh = mesh_lib.initialize_model_parallel()
    policy = amp.policy("O2")
    model = ResNet50(num_classes=1000, axis_name=None,
                     dtype=policy.compute_dtype)

    x0 = jnp.zeros((2, image_size, image_size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x0, train=True)
    params = policy.cast_to_param(variables["params"])
    batch_stats = variables["batch_stats"]
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4,
                   master_weights=policy.master_weights)
    opt_state = opt.init(params)

    def loss_fn(params, batch_stats, batch):
        x, y = batch
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            policy.cast_to_compute(x),
            train=True,
            mutable=["batch_stats"],
        )
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(logp[jnp.arange(y.shape[0]), y])
        return loss, mutated["batch_stats"]

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(params, batch_stats, opt_state, batch):
        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch_stats, batch
        )
        params, opt_state = opt.step(grads, opt_state, params)
        return params, new_stats, opt_state, loss

    params = replicate(params, mesh)
    batch_stats = replicate(batch_stats, mesh)
    opt_state = replicate(opt_state, mesh)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, image_size, image_size, 3),
                    jnp.float32)
    y = jnp.asarray(rng.randint(0, 1000, size=(batch,)))
    sharded = dp_shard_batch((x, y), mesh)

    # warmup / compile
    params, batch_stats, opt_state, loss = train_step(
        params, batch_stats, opt_state, sharded
    )
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, sharded
        )
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    ips_per_chip = batch * steps / dt / n_chips
    record = {
        "metric": "resnet50_o2_train_throughput",
        "value": round(ips_per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips_per_chip / APEX_A100_IMAGES_PER_SEC, 3),
        "platform": jax.devices()[0].platform,
        "n_chips": n_chips,
        "batch_per_chip": batch_per_chip,
        "image_size": image_size,
    }
    if not on_tpu:
        # toy CPU-fallback shapes: the A100 comparison is meaningless there
        record["vs_baseline"] = None
    print(json.dumps(record))


if __name__ == "__main__":
    main()
