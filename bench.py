"""apex_tpu benchmark suite over the BASELINE.json config matrix.

Headline (the ONE JSON line, driver contract): ResNet-50 mixed-precision
(O2) training throughput in images/sec/chip — the reference's flagship
config (``examples/imagenet/main_amp.py``: ResNet-50, amp O2, FusedSGD).
``vs_baseline`` normalizes against an adopted per-A100 figure for Apex RN50
AMP training (the repo publishes no numbers — BASELINE.md): NVIDIA NGC
PyTorch+Apex RN50 AMP convergence runs report ~2.5k images/sec per A100-80GB.

The ``extras`` field carries the rest of the BASELINE.json matrix, each
individually guarded so one failure cannot empty the record:

- ``resnet50_lamb_syncbn``  — RN50 + FusedLAMB + SyncBatchNorm (32k-style)
- ``bert_large``            — BERT-large encoder train step (fused
                              LN/dense/Adam), tokens/sec
- ``gpt_flash``             — flagship GPT with Pallas flash attention,
                              tokens/sec and **MFU**
- ``gpt_flash_fp8``         — same with delayed-scaling fp8 GEMMs
                              (``vs_bf16`` stated when both rows share a
                              platform)
- ``gpt_long_context``      — the seq-8192 flash config
- ``tp_gpt``                — tensor-parallel GPT train step (shard_map over
                              the tp axis; tp=#devices); A/B-measures the
                              ring-decomposed collective matmul
                              (``overlap_comm`` on/off — ``vs_monolithic``
                              < 1 = the overlap schedule wins)
- ``fused_adam_step``       — optimizer step-time microbench (the
                              "fused-optimizer step time" BASELINE metric);
                              measures per-leaf AND chunked-flat configs
- ``zero_adam_step``        — ZeRO step-time over the dp mesh: flat-bucket
                              vs per-leaf ``DistributedFusedAdam`` vs
                              replicated ``FusedAdam`` (``vs_per_leaf``
                              < 1 = the bucketed exchange wins)
- ``ckpt_save_restore``     — checkpoint-path wall-time: save/verify/
                              restore for the flat vs sharded layouts
                              (``vs_sharded`` = flat/sharded total), so
                              crash-safety machinery (checksums, fsync,
                              manifest commit) shows regressions
- ``ckpt_reshard``          — restore-anywhere wall-time: the same
                              flat-bucket ZeRO checkpoint restored onto
                              the writing mesh vs reshard-restored onto
                              dp/2 (``vs_same_mesh`` = reshard/plain —
                              the measured cost of an elastic resume)
- ``telemetry_overhead``    — instrumented vs bare 3D GPT train step
                              (in-graph TrainStats, ``observability``):
                              ``vs_bare`` pins "telemetry is free"
                              numerically (gate: <= 1.05 on the CPU mesh)
- ``input_pipeline``        — host decode + packed decode-free loader rates
                              vs the chip's consumption rate
- ``real_data_rn50``        — end-to-end real-JPEG training through the
                              packed loader (``vs_synthetic`` vs the
                              same-run headline)

Backend hardening (round-1 postmortem: BENCH_r01 rc=1 at ``jax.devices()``,
"Unable to initialize backend 'axon'"; round-2 observation: backend init can
also *hang* indefinitely mid-session): every bench runs in its own
subprocess (``bench.py --one <name>``) under a hard timeout, so the parent
process never initializes a backend and one wedged bench cannot empty the
record.  The platform is probed the same way; if the TPU plugin is
unusable, children run pinned to CPU with tiny shapes so a record is always
emitted.

Round-3 hardening (round-2 postmortem: BENCH_r02 fell back to CPU because a
3x150s probe at bench *start* happened to land in a wedge window, losing the
whole round's TPU evidence even though the chip worked the same day):

- the TPU probe now spans the *whole* bench window — after the CPU fallback
  suite secures a record, the parent keeps re-probing until ~80% of the
  deadline and runs the TPU matrix the moment a probe succeeds;
- any successful TPU suite is also written to ``bench_results/tpu_*.json``
  (stamped), and when TPU never materializes the emitted record *embeds* the
  newest such prior record with its timestamp, so the driver artifact always
  carries the best available TPU evidence with provenance;
- children enable the persistent XLA compilation cache
  (``bench_results/.xla_cache``) so a bench killed mid-compile retries warm;
- on child timeout the partial stderr breadcrumbs are logged, attributing
  the loss to backend-init vs compile vs run.

Round-4 hardening (round-3 postmortem: BENCH_r03 was ``rc=124, parsed=null``
— the CPU fallback suite had *finished* and the prior TPU record was sitting
in memory, but the record was printed only at process exit and the internal
deadline default of 2700 s exceeded the real driver window of ~2100 s, so
the driver's kill mid-poll evaporated the evidence).  A record held in RAM
is not a record:

- **emit early, emit often**: a complete record line (embedding the newest
  stamped prior TPU record) is printed the moment ``main()`` starts, then
  re-printed after *every* bench result change; the driver parses the last
  JSON line of the tail, so each emission supersedes the previous one and a
  kill at any instant still leaves a parseable record behind;
- the internal deadline default drops to 1800 s, safely inside the driver
  window, so the epilogue normally runs before any kill anyway.
"""

import json
import os
import shutil
import subprocess
import sys
import time
from functools import partial


def _log(msg: str) -> None:
    print(f"bench[{time.strftime('%H:%M:%S')}]: {msg}", file=sys.stderr,
          flush=True)

_REPO = os.path.dirname(os.path.abspath(__file__))


def _env_int(name: str, default: int) -> int:
    """Positive-int env knob; warn and fall back on malformed values (an
    operator typo must not cost a bench row — the evidence-loss mode the
    round-1..4 hardening notes exist to prevent)."""
    try:
        val = int(os.environ.get(name, str(default)))
        if val <= 0:
            raise ValueError(val)
        return val
    except ValueError:
        _log(f"ignoring invalid {name}={os.environ.get(name)!r}; "
             f"using {default}")
        return default


def adopted_baseline() -> float:
    """The adopted reference number for ``vs_baseline`` — read from
    BASELINE.json ("adopted" section, provenance recorded there and in
    BASELINE.md) rather than hardcoded here."""
    try:
        with open(os.path.join(_REPO, "BASELINE.json")) as f:
            rec = json.load(f)
        return float(rec["adopted"]["rn50_amp_a100_images_per_sec"]["value"])
    except Exception as e:
        _log(f"BASELINE.json adopted baseline unreadable ({e!r}); "
             "using 2500.0")
        return 2500.0

# bf16 peak FLOP/s per chip: ONE table, owned by the observability
# subsystem (its MFU metric and the bench rows must never disagree).
from apex_tpu.observability.metrics import peak_flops_for  # noqa: E402


def probe_platform(max_tries: int = 3, timeout: float = 150.0) -> str:
    """Decide the platform for bench children without initializing any
    backend in this process.  Returns "cpu" when the default plugin errors
    *or wedges* (both observed failure modes of the tunneled TPU)."""
    from apex_tpu.utils.platform import resolve_platform

    return resolve_platform(max_tries=max_tries, timeout=timeout, log=_log)


def _peak_flops(device) -> float:
    # Bench contract: always a number (MFU against the conservative v5e
    # peak on unknown/CPU devices, where peak_flops_for says None).
    return peak_flops_for(device) or 197e12


def _timeit(jax, step, state, steps):
    """Run ``state = step(*state)`` ``steps`` times; return (dt, state)."""
    t0 = time.perf_counter()
    for _ in range(steps):
        state = step(*state)
    jax.block_until_ready(state)
    return time.perf_counter() - t0, state


# ---------------------------------------------------------------------------
# ResNet-50 benches
# ---------------------------------------------------------------------------

def resnet_setup(jax, on_tpu, optimizer_name, sync_bn=False):
    """Build the RN50 train step — the ONE definition of the resnet50_*
    workloads, shared by the bench and ``examples/profile_resnet.py`` so
    a profile explains exactly the numbers the bench records.

    Returns ``(train_step, state0, meta)`` where ``state0 = (params,
    batch_stats, opt_state, sharded_batch)`` is the step's carry (the
    bench threads the batch through) and ``meta`` carries the record
    fields.  Call ``meta["mesh_cleanup"]()`` when done.
    """
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.models import ResNet50
    from apex_tpu.optimizers import FusedLAMB, FusedSGD
    from apex_tpu.parallel import (
        collectives as cc,
        dp_shard_batch,
        mesh as mesh_lib,
        replicate,
    )
    from apex_tpu.parallel.distributed import all_reduce_gradients

    n_chips = len(jax.devices())
    # APEX_TPU_RN50_BATCH: batch-per-chip sweep knob for hardware capture
    # (the shipped default stays 128 = the reference recipe's per-GPU
    # batch; a sweep that finds a better point records it in
    # bench_results/ and the default is bumped by hand, keeping records
    # comparable)
    batch_per_chip = _env_int("APEX_TPU_RN50_BATCH", 128) if on_tpu else 4
    image_size = 224 if on_tpu else 32
    steps = 20 if on_tpu else 3
    batch = batch_per_chip * n_chips

    mesh = mesh_lib.initialize_model_parallel()
    try:
        policy = amp.policy("O2")
        dp_axes = ("dcn", "dp")
        model = ResNet50(num_classes=1000,
                         axis_name="dp" if sync_bn else None,
                         dtype=policy.compute_dtype)

        x0 = jnp.zeros((2, image_size, image_size, 3), jnp.float32)
        variables = model.init(jax.random.PRNGKey(0), x0, train=True)
        params = policy.cast_to_param(variables["params"])
        batch_stats = variables["batch_stats"]
        if optimizer_name == "lamb":
            # APEX_TPU_LAMB_FLAT=0 falls back to the per-leaf update for a
            # live A/B of the chunked flat-buffer path (the r4 weak-#3
            # diagnosis lever); the record carries which path ran
            opt = FusedLAMB(lr=1e-3, weight_decay=1e-2,
                            master_weights=policy.master_weights,
                            flat=os.environ.get(
                                "APEX_TPU_LAMB_FLAT", "1") != "0")
        else:
            opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4,
                           master_weights=policy.master_weights)
        opt_state = opt.init(params)

        def loss_fn(params, batch_stats, batch):
            x, y = batch
            logits, mutated = model.apply(
                {"params": params, "batch_stats": batch_stats},
                policy.cast_to_compute(x),
                train=True,
                mutable=["batch_stats"],
            )
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.mean(logp[jnp.arange(y.shape[0]), y])
            return loss, mutated["batch_stats"]

        def local_step(params, batch_stats, opt_state, batch):
            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch_stats, batch)
            if sync_bn:
                # shard_map path: explicit dp gradient reduction (the pjit
                # path gets it implicitly from the global-mean loss).
                grads = all_reduce_gradients(grads, dp_axes)
            params, opt_state = opt.step(grads, opt_state, params)
            return params, new_stats, opt_state, batch

        if sync_bn:
            rep = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)

            def sharded_step(params, batch_stats, opt_state, batch):
                bspec = jax.tree_util.tree_map(
                    lambda x: P(dp_axes, *([None] * (jnp.ndim(x) - 1))),
                    batch)
                return cc.shard_over(
                    local_step, mesh=mesh,
                    in_specs=(rep(params), rep(batch_stats),
                              rep(opt_state), bspec),
                    out_specs=(rep(params), rep(batch_stats),
                               rep(opt_state), bspec),
                )(params, batch_stats, opt_state, batch)

            train_step = jax.jit(sharded_step, donate_argnums=(0, 1, 2))
        else:
            train_step = partial(jax.jit, donate_argnums=(0, 1, 2))(
                local_step)

        params = replicate(params, mesh)
        batch_stats = replicate(batch_stats, mesh)
        opt_state = replicate(opt_state, mesh)

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(batch, image_size, image_size, 3),
                        jnp.float32)
        y = jnp.asarray(rng.randint(0, 1000, size=(batch,)))
        sharded = dp_shard_batch((x, y), mesh)
    except BaseException:
        mesh_lib.destroy_model_parallel()
        raise

    meta = {
        "n_chips": n_chips,
        "batch": batch,
        "batch_per_chip": batch_per_chip,
        "image_size": image_size,
        "steps": steps,
        "optimizer": optimizer_name,
        "sync_bn": sync_bn,
        "mesh_cleanup": mesh_lib.destroy_model_parallel,
    }
    if optimizer_name == "lamb":
        meta["lamb_flat"] = opt.flat
    return train_step, (params, batch_stats, opt_state, sharded), meta


def _resnet_bench(jax, on_tpu, optimizer_name, sync_bn=False):
    train_step, st0, meta = resnet_setup(jax, on_tpu, optimizer_name,
                                         sync_bn=sync_bn)
    try:
        batch, steps = meta["batch"], meta["steps"]
        _log(f"resnet50({optimizer_name}): compile start")
        t0 = time.perf_counter()
        state = train_step(*st0)
        jax.block_until_ready(state)
        _log(f"resnet50({optimizer_name}): compiled in "
             f"{time.perf_counter() - t0:.1f}s; timing {steps} steps")
        dt, _ = _timeit(jax, train_step, state, steps)

        ips_per_chip = batch * steps / dt / meta["n_chips"]
        rec = {
            "value": round(ips_per_chip, 1),
            "unit": "images/sec/chip",
            "n_chips": meta["n_chips"],
            "batch_per_chip": meta["batch_per_chip"],
            "image_size": meta["image_size"],
            "optimizer": optimizer_name,
        }
        if "lamb_flat" in meta:
            rec["lamb_flat"] = meta["lamb_flat"]
        return rec
    finally:
        meta["mesh_cleanup"]()


def bench_resnet50_o2(jax, on_tpu):
    return _resnet_bench(jax, on_tpu, "sgd")


def bench_resnet50_lamb_syncbn(jax, on_tpu):
    # BASELINE.json "RN50 FusedLAMB 32k+SyncBN": SyncBatchNorm with the dp
    # axis genuinely bound (shard_map), cross-replica Welford psum included
    # in the measured step (a single chip binds a size-1 axis).
    return _resnet_bench(jax, on_tpu, "lamb", sync_bn=True)


# ---------------------------------------------------------------------------
# Transformer benches
# ---------------------------------------------------------------------------

def _lm_train_flops(cfg, n_params, batch, seq):
    """fwd+bwd FLOPs per step: 6*N*tokens + attention 12*L*h*B*S^2."""
    return (6.0 * n_params * batch * seq
            + 12.0 * cfg.num_layers * cfg.hidden_size * batch * seq * seq)


def bench_bert_large(jax, on_tpu):
    import jax.numpy as jnp

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
    from apex_tpu.transformer.testing import BertModel, TransformerConfig

    # use_flash_attention: BERT's padding mask rides the flash kernels'
    # segment-id mechanism (round-2 addition); the bench previously ran
    # the unfused-softmax path and still hit 0.488 MFU on v5e.
    if on_tpu:
        cfg = TransformerConfig(
            hidden_size=1024, num_layers=24, num_attention_heads=16,
            padded_vocab_size=30592, max_position_embeddings=512,
            hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
            use_flash_attention=True, dtype=jnp.bfloat16,
        )
        batch, seq, steps = 8, 512, 10
    else:
        cfg = TransformerConfig(
            hidden_size=64, num_layers=2, num_attention_heads=4,
            padded_vocab_size=512, max_position_embeddings=64,
            hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
            use_flash_attention=True,
        )
        batch, seq, steps = 2, 32, 2

    model = BertModel(cfg)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, mask)["params"]
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    opt = FusedAdam(lr=1e-4)
    state = opt.init(params)

    def loss_fn(p):
        lm_logits, bin_logits = model.apply({"params": p}, tokens, mask)
        # flatten the [s, b, v] logits in native order (transposing only
        # the tiny labels) and keep half logits half through the CE kernel
        # — the loss is a mean, so row order is irrelevant (the gpt_loss
        # bandwidth note, standalone_gpt.py)
        lm = softmax_cross_entropy_loss(
            lm_logits.reshape(-1, lm_logits.shape[-1]),
            tokens.T.reshape(-1), padding_idx=-1, half_to_float=True)
        sop = -jax.nn.log_softmax(bin_logits)[:, 0]
        return jnp.mean(lm) + jnp.mean(sop)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.step(grads, state, params)
        return params, state

    _log("compile start")
    t0 = time.perf_counter()
    st = step(params, state)
    jax.block_until_ready(st)
    _log(f"compiled in {time.perf_counter() - t0:.1f}s; timing %d steps"
         % steps)
    dt, _ = _timeit(jax, step, st, steps)

    tps = batch * seq * steps / dt
    flops = _lm_train_flops(cfg, n_params, batch, seq) * steps / dt
    return {
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "mfu": round(flops / _peak_flops(jax.devices()[0]), 4)
        if on_tpu else None,
        "params": int(n_params),
        "batch": batch,
        "seq": seq,
    }


def _tuned_gpt_batch(jax):
    """Per-chip batch from ``bench_results/gpt_batch_tuned.json`` (written
    by a TPU sweep of ``examples/tune_gpt_batch.py`` at the flagship seq),
    adopted only on a matching ``device_kind``."""
    from apex_tpu.utils.tuning import load_tuned_record

    rec = load_tuned_record("gpt_batch_tuned.json", jax)
    try:
        if rec and int(rec.get("base_batch", 0)) > 0:
            return int(rec["base_batch"])
    except (TypeError, ValueError):
        pass
    return None


def gpt_flash_setup(jax, on_tpu, seq=None, fp8=False):
    """Build the flagship GPT-124M flash train step — the ONE definition
    of the ``gpt_flash`` workload, shared by this bench, the block-size
    sweep (``examples/tune_flash_blocks.py``), and the profiler
    (``examples/profile_gpt.py``) so their configs cannot drift.

    Returns ``(cfg, step, st0, batch, seq, n_params)`` where ``step`` is
    the donated jitted train step and ``st0 = (params, opt_state,
    fp8_state)`` its initial carry (``fp8_state`` is ``{}`` when ``fp8``
    is off).  Batch policy: 8 up to seq 1024, token-budget-rescaled above.
    """
    import jax.numpy as jnp

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    if on_tpu:
        seq = seq or 1024
        # APEX_TPU_GPT_BATCH: per-chip batch sweep knob for hardware
        # capture.  Precedence: env > hardware-matched tuned file
        # (written by examples/tune_gpt_batch.py from a TPU sweep, the
        # flash-blocks auto-land pattern) > shipped 8.  The tuned file is
        # consulted only when the env knob is absent (sweep children set
        # it, so a stale tuned record can't contaminate a sweep).  The
        # record always carries the batch actually used.
        base_batch = (_env_int("APEX_TPU_GPT_BATCH", 8)
                      if "APEX_TPU_GPT_BATCH" in os.environ
                      else (_tuned_gpt_batch(jax) or 8))
        batch = base_batch if seq <= 1024 else max(
            1, base_batch * 1024 // seq)
        cfg = TransformerConfig(
            hidden_size=768, num_layers=12, num_attention_heads=12,
            padded_vocab_size=50304, max_position_embeddings=seq,
            hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
            use_flash_attention=True, dtype=jnp.bfloat16, fp8=fp8,
        )
    else:
        seq = min(seq or 128, 128)
        batch = 2
        cfg = TransformerConfig(
            hidden_size=64, num_layers=2, num_attention_heads=4,
            padded_vocab_size=512, max_position_embeddings=seq,
            hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
            use_flash_attention=True, fp8=fp8,
        )

    model = GPTModel(cfg)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    params = variables["params"]
    fp8_state = dict(variables.get("fp8_meta", {}))
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    opt = FusedAdam(lr=1e-4)
    state = opt.init(params)

    def loss_fn(p, fp8_state):
        if not fp8_state:
            return jnp.mean(model.apply({"params": p}, tokens,
                                        labels=tokens)), fp8_state
        losses, mut = model.apply(
            {"params": p, "fp8_meta": fp8_state}, tokens, labels=tokens,
            mutable=["fp8_meta"])
        return jnp.mean(losses), dict(mut)["fp8_meta"]

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(params, state, fp8_state):
        (_, fp8_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, fp8_state)
        params, state = opt.step(grads, state, params)
        return params, state, fp8_state

    return cfg, step, (params, state, fp8_state), batch, seq, n_params


def enable_compilation_cache(jax) -> None:
    """Persistent XLA compilation cache shared by bench children and the
    tuning/profiling harnesses (warm retries after timeouts/wedges)."""
    try:
        cache_dir = os.path.join(_REPO, "bench_results", ".xla_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        _log(f"compilation cache unavailable: {e!r}")


def _gpt_flash_bench(jax, on_tpu, fp8: bool):
    """Flagship GPT train-step bench; ``fp8=True`` threads the delayed-
    scaling ``fp8_meta`` collection through the step (e4m3 GEMMs for
    qkv/attn-out/fc1/fc2, e5m2 JIT cotangents — the fp8-vs-bf16 delta the
    r2 VERDICT asked to put in the bench extras)."""
    cfg, step, st, batch, seq, n_params = gpt_flash_setup(
        jax, on_tpu, fp8=fp8)
    steps = 10 if on_tpu else 2

    name = "gpt_flash_fp8" if fp8 else "gpt_flash"
    _log(f"{name}: compile start")
    t0 = time.perf_counter()
    st = step(*st)
    jax.block_until_ready(st)
    _log(f"{name}: compiled in {time.perf_counter() - t0:.1f}s; "
         f"timing {steps} steps")
    dt, _ = _timeit(jax, step, st, steps)

    tps = batch * seq * steps / dt
    flops = _lm_train_flops(cfg, n_params, batch, seq) * steps / dt
    rec = {
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "mfu": round(flops / _peak_flops(jax.devices()[0]), 4)
        if on_tpu else None,
        "params": int(n_params),
        "batch": batch,
        "seq": seq,
        "flash_attention": True,
    }
    if fp8:
        rec["fp8"] = True
    return rec


def bench_gpt_flash(jax, on_tpu):
    return _gpt_flash_bench(jax, on_tpu, fp8=False)


def bench_gpt_flash_fp8(jax, on_tpu):
    return _gpt_flash_bench(jax, on_tpu, fp8=True)


def bench_gpt_long_context(jax, on_tpu):
    """Long-context GPT train step: seq 8192 with the Pallas flash kernels.
    The unfused path would materialize [b, h, 8192, 8192] fp32 scores
    (3 GB/head-batch) — this config exists *because* of flash (SURVEY §5
    long-context; the reference caps at 16384 fused-softmax keys / 512
    fmha)."""
    import jax.numpy as jnp

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    if on_tpu:
        cfg = TransformerConfig(
            hidden_size=768, num_layers=12, num_attention_heads=12,
            padded_vocab_size=50304, max_position_embeddings=8192,
            hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
            use_flash_attention=True, dtype=jnp.bfloat16,
        )
        batch, seq, steps = 1, 8192, 5
    else:
        cfg = TransformerConfig(
            hidden_size=64, num_layers=2, num_attention_heads=4,
            padded_vocab_size=512, max_position_embeddings=512,
            hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
            use_flash_attention=True,
        )
        batch, seq, steps = 1, 512, 2

    model = GPTModel(cfg)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    opt = FusedAdam(lr=1e-4)
    state = opt.init(params)

    def loss_fn(p):
        return jnp.mean(model.apply({"params": p}, tokens, labels=tokens))

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.step(grads, state, params)
        return params, state

    _log("long_context: compile start")
    t0 = time.perf_counter()
    st = step(params, state)
    jax.block_until_ready(st)
    _log(f"long_context: compiled in {time.perf_counter() - t0:.1f}s")
    dt, _ = _timeit(jax, step, st, steps)

    tps = batch * seq * steps / dt
    flops = _lm_train_flops(cfg, n_params, batch, seq) * steps / dt
    return {
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "mfu": round(flops / _peak_flops(jax.devices()[0]), 4)
        if on_tpu else None,
        "params": int(n_params),
        "batch": batch,
        "seq": seq,
        "flash_attention": True,
    }


def bench_tp_gpt(jax, on_tpu):
    """Tensor-parallel GPT train step via shard_map over the tp axis
    (tp = all attached devices; tp=1 on the single bench chip still
    exercises the TP code path)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import collectives as cc
    from apex_tpu.transformer import tensor_parallel as tp
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    n = len(jax.devices())
    mesh = parallel.initialize_model_parallel(tensor_model_parallel_size=n)
    try:
        if on_tpu:
            cfg = TransformerConfig(
                hidden_size=1024, num_layers=4, num_attention_heads=16,
                padded_vocab_size=50304, max_position_embeddings=1024,
                hidden_dropout=0.0, attention_dropout=0.0,
                tensor_axis="tp", sequence_parallel=n > 1,
                dtype=jnp.bfloat16,
            )
            batch, seq, steps = 8, 1024, 10
        else:
            # heads/hidden must split over tp (8 on the virtual CPU mesh)
            cfg = TransformerConfig(
                hidden_size=128, num_layers=2, num_attention_heads=8,
                padded_vocab_size=512, max_position_embeddings=64,
                hidden_dropout=0.0, attention_dropout=0.0,
                tensor_axis="tp", sequence_parallel=n > 1,
            )
            batch, seq, steps = 2, 64, 2

        model = GPTModel(cfg)
        tokens = jnp.zeros((batch, seq), jnp.int32)

        def tp_init(tokens):
            return model.init(jax.random.PRNGKey(0), tokens)["params"]

        param_specs = tp.infer_param_specs(jax.eval_shape(tp_init, tokens))
        _log("tp_gpt: param specs inferred")

        def shardings_of(spec_tree):
            return jax.tree_util.tree_map(
                lambda s: cc.named_sharding(*s, mesh=mesh), spec_tree,
                is_leaf=lambda x: isinstance(x, P))

        # Init through plain jit with output shardings (the idiomatic
        # SPMD path) rather than shard_map: the r2/r4 900 s timeouts hung
        # before the step compile ever started, i.e. in this setup phase,
        # and a shard_map'd *initializer* is the one nonstandard compile
        # here.  The train step below still goes through shard_map — that
        # is the thing this row exists to measure.
        params = jax.jit(
            tp_init, out_shardings=shardings_of(param_specs))(tokens)
        jax.block_until_ready(params)
        _log("tp_gpt: params initialized")

        def tp_loss(p, t):
            losses = model.apply({"params": p}, t, labels=t)
            return jax.lax.pmean(jnp.mean(losses), "tp")

        opt = FusedAdam(lr=1e-4)
        state0 = jax.eval_shape(opt.init, params)
        state_specs = type(state0)(
            step=P(),
            slots={k: param_specs for k in state0.slots},
            master=param_specs if state0.master is not None else None,
        )
        state = jax.jit(
            opt.init, out_shardings=shardings_of(state_specs))(params)
        jax.block_until_ready(state)
        _log("tp_gpt: optimizer state initialized")

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(params, state, tokens):
            def local(p, s, t):
                g = jax.grad(tp_loss)(p, t)
                return opt.step(g, s, p)
            return cc.shard_over(
                local,
                in_specs=(param_specs, state_specs, P()),
                out_specs=(param_specs, state_specs),
            )(params, state, tokens)

        _log("tp_gpt: compile start")
        t0 = time.perf_counter()
        st = step(params, state, tokens)
        jax.block_until_ready(st)
        _log(f"tp_gpt: compiled in {time.perf_counter() - t0:.1f}s")
        dt, st = _timeit(jax, lambda p, s: step(p, s, tokens), st, steps)

        # A/B: the same step with overlap_comm=True — the SP
        # all-gather/reduce-scatter ring-decomposed into collective-permute
        # hops pipelined under partial GEMMs (tensor_parallel/overlap.py).
        # Shares this child's expensive setup (params/opt state thread
        # through — the monolithic timing loop's final buffers are valid
        # inputs); only the step recompiles.  vs_monolithic < 1 = overlap
        # wins (same time-ratio convention as zero_adam_step's
        # vs_per_leaf).
        dt_overlap = None
        if n > 1:
            import dataclasses

            model_ov = GPTModel(dataclasses.replace(cfg, overlap_comm=True))

            def tp_loss_ov(p, t):
                losses = model_ov.apply({"params": p}, t, labels=t)
                return jax.lax.pmean(jnp.mean(losses), "tp")

            @partial(jax.jit, donate_argnums=(0, 1))
            def step_ov(params, state, tokens):
                def local(p, s, t):
                    g = jax.grad(tp_loss_ov)(p, t)
                    return opt.step(g, s, p)
                return cc.shard_over(
                    local,
                    in_specs=(param_specs, state_specs, P()),
                    out_specs=(param_specs, state_specs),
                )(params, state, tokens)

            _log("tp_gpt: overlap variant compile start")
            t0 = time.perf_counter()
            st = step_ov(*st, tokens)
            jax.block_until_ready(st)
            _log("tp_gpt: overlap variant compiled in "
                 f"{time.perf_counter() - t0:.1f}s")
            dt_overlap, _ = _timeit(
                jax, lambda p, s: step_ov(p, s, tokens), st, steps)

        tps = batch * seq * steps / dt
        on_cpu_mesh = jax.devices()[0].platform != "tpu" and n > 1
        rec = {
            "value": round(tps, 1),
            "unit": "tokens/sec",
            "tp": n,
            "sequence_parallel": n > 1,
            "batch": batch,
            "seq": seq,
            # exactly what this row measured (r3 VERDICT weak #5: no
            # headline row whose collectives never execute)
            "measured": (
                "tp=%d shard_map step on a virtual %d-device CPU host "
                "mesh: TP collectives (all-gather/reduce-scatter) "
                "genuinely execute; step-time *shape* only, not TPU perf"
                % (n, n) if on_cpu_mesh else
                "tp=1 on the single attached chip: TP code path only, "
                "zero TP collectives; multi-chip shardings validated by "
                "dryrun_multichip + virtual-mesh scaling records" if n == 1
                else "tp=%d on %d attached TPU chips" % (n, n)),
        }
        if dt_overlap is not None:
            rec["overlap_tokens_per_sec"] = round(
                batch * seq * steps / dt_overlap, 1)
            rec["vs_monolithic"] = round(dt_overlap / dt, 3)
        return rec
    finally:
        parallel.mesh.destroy_model_parallel()


def _make_synth_jpeg_tree(root, n_classes: int, per_class: int,
                          side: int) -> None:
    """Deterministic synthetic ImageFolder tree (RandomState(0), quality
    90) — shared by bench_input_pipeline and bench_real_data_rn50 so the
    two measurements stay apples-to-apples."""
    import numpy as np
    from PIL import Image

    rng = np.random.RandomState(0)
    for c in range(n_classes):
        d = os.path.join(root, f"class_{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.randint(0, 256, (side, side, 3), dtype=np.uint8)
            Image.fromarray(arr).save(
                os.path.join(d, f"{i}.jpg"), quality=90)


def bench_input_pipeline(jax, on_tpu):
    """Host input-pipeline throughput: images decoded+augmented per second
    by ``ImageFolderLoader`` over a synthetic JPEG ImageFolder tree — the
    "can the loader feed the chip?" number (the reference's flagship
    recipe leans on DataLoader workers + DALI for this;
    ``examples/imagenet/main_amp.py:207-232``).

    ISSUE 8 shape: A/Bs the decode **backends** (process pool vs thread
    pool — ``loader_ips_per_backend``), measures the **overlapped stall
    per step** through the double-buffered device prefetcher for each
    path (``stall_ms_per_step``; ``stall_ms_single_buffer`` is the
    depth=0 synchronous-pull A/B — the pre-double-buffer shape), and
    cross-checks the bench-side stopwatch against the in-run
    ``data/stall_ms`` telemetry (``stall_ms_in_run_gauge`` — the two
    must agree within noise).  Also rates the decode-free packed image
    path and the packed-sequence **LM stream**
    (``packed_lm_tokens_per_sec``) — the GPT trainers' real-data input.

    Reported against the RN50 consumption rate (the newest stamped TPU
    headline): ``vs_rn50_consumption > 1`` means the pipeline outpaces
    the chip, i.e. the real-data path is not input-bound."""
    import shutil
    import tempfile

    from apex_tpu.data import ImageFolder, ImageFolderLoader
    from apex_tpu.data import prefetch_to_device
    from apex_tpu.observability.metrics import MetricRegistry

    # enough images that several batches fit per epoch: the pipeline
    # drains at epoch boundaries (by design), so a 1-batch epoch would
    # measure un-overlapped decode, not steady-state prefetch
    n_classes, per_class = 4, 128 if not on_tpu else 512
    side = 300  # ~typical resized ImageNet shard JPEG
    # consumption rate to beat: the newest stamped TPU headline (falls
    # back to the adopted A100 baseline if no TPU record exists yet)
    prior = _newest_prior_tpu_record()
    if prior and prior["record"].get("headline", {}).get("value"):
        rn50_rate = float(prior["record"]["headline"]["value"])
        rate_src = prior["path"]
    else:
        rn50_rate = adopted_baseline()
        rate_src = "BASELINE.json adopted (no stamped TPU record)"
    root = tempfile.mkdtemp(prefix="bench_jpegs_")
    try:
        _make_synth_jpeg_tree(root, n_classes, per_class, side)

        batch = 256 if on_tpu else 128  # >= 4 batches per epoch either way
        # effective quota, not raw core count (matches the host_cpus field)
        eff_cpus = (len(os.sched_getaffinity(0))
                    if hasattr(os, "sched_getaffinity")
                    else (os.cpu_count() or 8))
        workers = min(32, eff_cpus)
        ds = ImageFolder(root)

        # target + warm batch stays under the batches-per-epoch (8 on tpu
        # shapes, 4 on cpu) so neither loop times an epoch-boundary drain
        # + producer restart
        target = 6 if on_tpu else 2
        step_s = batch / rn50_rate  # an RN50 step's device time

        def measure_ips(make_loader):
            """Raw pipeline throughput: warm the POOL (worker spawn +
            imports — the one-time cost warm_up() exists for), then time
            from decode cold start and count every delivered batch, so
            prefetch's head start cannot credit undone work to the
            window."""
            with make_loader() as loader:
                if hasattr(loader, "warm_up"):
                    loader.warm_up()
                it = iter(loader)

                def batches():
                    nonlocal it
                    while True:  # re-iterating -> next epoch
                        for b in it:
                            yield b
                        it = iter(loader)

                src = batches()
                t0 = time.perf_counter()
                for _ in range(target + 1):
                    next(src)
                n = (target + 1) * batch
                return n / (time.perf_counter() - t0)

        def measure_stall(make_loader, depth=2):
            """Steady-state overlapped stall through the double-buffered
            device prefetcher: warm the pipeline, pace like the device
            (sleep an RN50 step), then time how long next() blocks.
            Returns (bench-side stall ms, in-run gauge-mean ms) — the
            agreement check for the data/stall_ms telemetry."""
            reg = MetricRegistry(rank=0, world=1)
            with make_loader() as loader:
                dev = prefetch_to_device(loader, depth=depth,
                                         place=lambda b: b, registry=reg)
                try:
                    next(dev)
                    # reset after warmup: the first pull pays cold decode
                    warm = reg.histogram("span_ms/data/next_wait")
                    warm_total, warm_count = warm.total, warm.count
                    stall = 0.0
                    for _ in range(target):
                        time.sleep(step_s)
                        s0 = time.perf_counter()
                        next(dev)
                        stall += time.perf_counter() - s0
                    hist = reg.histogram("span_ms/data/next_wait")
                    gauge_ms = ((hist.total - warm_total)
                                / max(hist.count - warm_count, 1))
                    return stall / target * 1e3, gauge_ms
                finally:
                    dev.close(close_source=False)

        def jpeg_loader(backend):
            return lambda: ImageFolderLoader(
                ds, local_batch=batch, image_size=224, workers=workers,
                prefetch=2, backend=backend)

        ips_per_backend = {}
        stall_per_path = {}
        gauge_per_path = {}
        for backend in ("thread", "process"):
            ips_per_backend[backend] = round(
                measure_ips(jpeg_loader(backend)), 1)
            stall_ms, gauge_ms = measure_stall(jpeg_loader(backend))
            stall_per_path[backend] = round(stall_ms, 2)
            gauge_per_path[backend] = round(gauge_ms, 2)
        best_backend = max(ips_per_backend, key=ips_per_backend.get)
        raw_ips = ips_per_backend[best_backend]
        # the pre-double-buffer A/B: depth=0 degenerates to the old
        # synchronous pull-at-next() shape on the winning backend
        single_ms, _ = measure_stall(jpeg_loader(best_backend), depth=0)

        # Packed (decode-free) image path: pack the same tree once, then
        # measure the memmap-gather loader the same two ways.  This is
        # the path that must feed the chip when per-core decode can't
        # (the DALI role; apex_tpu/data/packed.py module docstring).
        from apex_tpu.data import PackedLoader, pack_image_folder

        pds = pack_image_folder(
            ds, os.path.join(root, "packed"), side=232, workers=workers)

        def packed_loader():
            return PackedLoader(pds, local_batch=batch, prefetch=2)

        packed_ips = measure_ips(packed_loader)
        packed_stall_ms, packed_gauge_ms = measure_stall(packed_loader)
        stall_per_path["packed"] = round(packed_stall_ms, 2)
        gauge_per_path["packed"] = round(packed_gauge_ms, 2)

        # Packed-sequence LM stream (the GPT paths' real-data input):
        # synthetic pre-tokenized corpus -> pack once -> stream
        # (tokens, segment_ids) batches; rate in tokens/sec.
        from apex_tpu.data import (
            PackedSequenceLoader,
            pack_token_documents,
            synthetic_token_documents,
        )

        seq_len = 2048 if on_tpu else 512
        n_docs = 2048 if on_tpu else 256
        docs = synthetic_token_documents(n_docs, vocab=50_000,
                                         mean_len=seq_len // 2, seed=0)
        sds = pack_token_documents(
            docs, os.path.join(root, "lm", "train"), seq_len=seq_len,
            eos_id=0)
        lm_target = 4
        # size the batch so the lm_target+1 timed pulls stay INSIDE one
        # epoch — the same guard as the image legs: an epoch-boundary
        # drain + producer restart must not land in the timing window
        lm_batch = max(2, min(32, len(sds) // (lm_target + 2)))

        with PackedSequenceLoader(sds, local_batch=lm_batch,
                                  prefetch=2) as lm_loader:
            it = iter(lm_loader)

            def lm_batches():
                nonlocal it
                while True:
                    for b in it:
                        yield b
                    it = iter(lm_loader)

            src = lm_batches()
            t0 = time.perf_counter()
            for _ in range(lm_target + 1):
                next(src)
            lm_tps = ((lm_target + 1) * lm_batch * seq_len
                      / (time.perf_counter() - t0))

        return {
            "value": raw_ips,
            "unit": "images-decoded/sec",
            "vs_rn50_consumption": round(raw_ips / rn50_rate, 3),
            "rn50_rate_source": rate_src,
            # the ISSUE 8 backend A/B: process pool vs thread pool on the
            # same host/images (acceptance: process beats thread where
            # the GIL was the binding constraint)
            "loader_ips_per_backend": ips_per_backend,
            "decode_backend_used": best_backend,
            "per_worker_ips": round(raw_ips / workers, 1),
            # overlapped stall per step through the double-buffered
            # prefetcher, per input path; the in-run data/stall_ms gauge
            # must agree with the bench stopwatch within noise
            "stall_ms_per_step": stall_per_path,
            "stall_ms_in_run_gauge": gauge_per_path,
            "stall_ms_single_buffer": round(single_ms, 2),
            "rn50_step_ms": round(step_s * 1e3, 2),
            # decode-free packed shard (gather-memcpy + on-device augment)
            "packed_ips": round(packed_ips, 1),
            "packed_vs_rn50_consumption": round(packed_ips / rn50_rate, 3),
            # packed-sequence LM stream rate (tokens/sec incl. segments)
            "packed_lm_tokens_per_sec": round(lm_tps, 1),
            "lm_seq_len": seq_len,
            "batch": batch,
            "workers": workers,
            "jpeg_side": side,
            "n_images": n_classes * per_class,
            # host context: decode scales ~per core, so the same loader
            # reads very differently on a 1-core sandbox vs a TPU-VM host
            # (sched_getaffinity = the EFFECTIVE quota under cgroups)
            "host_cpus": eff_cpus,
            # which decode stage ran: the C kernel (_native/jpegdec.c,
            # DCT-scaled decode fused with crop+resize) or the PIL path
            "native_decode": _native_decode_available(),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _native_decode_available() -> bool:
    try:
        from apex_tpu.data import _jpeg_native
        return _jpeg_native.native_available()
    except Exception:
        return False


def bench_real_data_rn50(jax, on_tpu):
    """End-to-end REAL-DATA training throughput (VERDICT r4 missing #2):
    real JPEG files -> one-time pack -> ``PackedLoader`` host gather ->
    H2D prefetch -> jitted O2 train step with on-device crop/flip — the
    composition of the input_pipeline row (host side) with the
    resnet50_o2 row (device side), which had only ever been measured
    separately.  The reference capability is the flagship recipe's
    worker/prefetch loop feeding main_amp's step
    (``examples/imagenet/main_amp.py:207-232``).

    Drives ``examples/imagenet_amp.py`` itself (the user-facing recipe,
    not a bench-only path).  The JPEG tree and packed shard are cached
    under /tmp across runs, so only the first run pays dataset setup."""
    import sys as _sys

    examples_dir = os.path.join(_REPO, "examples")
    if examples_dir not in _sys.path:
        _sys.path.insert(0, examples_dir)
    import imagenet_amp

    n_classes, per_class = (8, 256) if on_tpu else (4, 16)
    # cpu-fallback shapes sized for the 300 s per-bench budget on a 1-CPU
    # host (batch-16 RN50 steps measured ~31 s each there)
    batch, steps = (128, 200) if on_tpu else (8, 3)
    side = 300
    cache = os.path.join("/tmp", "apex_tpu_bench_data",
                         f"synth_{n_classes}x{per_class}_{side}")
    done_marker = os.path.join(cache, ".complete")
    if not os.path.exists(done_marker):
        _make_synth_jpeg_tree(os.path.join(cache, "train"),
                              n_classes, per_class, side)
        with open(done_marker, "w") as f:
            f.write("ok")
    eff_cpus = (len(os.sched_getaffinity(0))
                if hasattr(os, "sched_getaffinity")
                else (os.cpu_count() or 8))
    # snapshot the in-run stall telemetry around the run: the example's
    # double-buffered prefetcher records every next() block into the
    # default registry (data/stall_ms gauge + span_ms/data/next_wait
    # histogram) — the stall lands in the record from the SAME run that
    # produced the throughput, not a separate bench-side loop
    from apex_tpu.observability import default_registry

    hist = default_registry().histogram("span_ms/data/next_wait")
    t0_count, t0_total = hist.count, hist.total
    ips = imagenet_amp.main([
        "--data", cache,
        "--packed", os.path.join(cache, "pack"),
        "--batch-size", str(batch),
        "--num-classes", str(n_classes),
        "--steps", str(steps),
        "--workers", str(min(32, eff_cpus)),
    ])
    stall_ms = ((hist.total - t0_total) / max(hist.count - t0_count, 1))
    return {
        "value": round(ips, 1),
        "unit": "images/sec/chip",
        "batch_per_chip": batch,
        "steps": steps,
        "image_size": 224,
        "n_images": n_classes * per_class,
        "data_path": "jpeg->packed-shard->PackedLoader->H2D prefetch",
        # in-run overlapped stall/step (the BENCH_r05 574 ms number,
        # re-measured through the rebuilt pipeline; the single- vs
        # double-buffer A/B lives in input_pipeline.stall_ms_single_buffer)
        "stall_ms_per_step": round(stall_ms, 2),
        "host_cpus": eff_cpus,
    }


def bench_fused_adam_step(jax, on_tpu):
    """Optimizer step-time microbench: FusedAdam over a resnet-sized tree
    vs the native-JAX baseline (optax.adamw) — the BASELINE
    "fused-optimizer step time <= native" metric (``vs_native`` < 1 means
    ours is faster)."""
    import jax.numpy as jnp

    from apex_tpu.optimizers import FusedAdam

    n_tensors = 161  # RN50-ish tree
    size = 160_000 if on_tpu else 1_000
    keys = [f"w{i}" for i in range(n_tensors)]
    steps = 50 if on_tpu else 5

    # One compiled program per tree instead of 161 eager jnp.full dispatches
    # (x4 trees): through the tunneled backend each tiny dispatch pays a
    # round trip, which is the prime suspect for the round-2 900s timeout
    # of this bench (r2 record: 161-tensor microbench dead at 15 min).
    @jax.jit
    def make_tree(fill):
        return {k: jnp.full((size,), fill, jnp.float32) for k in keys}

    grads = make_tree(1e-4)

    def fresh_params():
        # per-run trees: the jitted steps donate params/state, so each
        # optimizer needs its own buffers
        return make_tree(0.01)

    def timed(step, init):
        params = fresh_params()
        state = jax.jit(init)(params)  # one program, not 2x161 dispatches
        params, state = step(grads, state, params)  # compile
        jax.block_until_ready((params, state))
        t0 = time.perf_counter()
        for _ in range(steps):
            params, state = step(grads, state, params)
        jax.block_until_ready((params, state))
        return (time.perf_counter() - t0) / steps

    def time_fused(flat):
        opt = FusedAdam(lr=1e-3, weight_decay=1e-2, adam_w_mode=True,
                        flat=flat)

        @partial(jax.jit, donate_argnums=(1, 2))
        def fused_step(grads, state, params):
            return opt.step(grads, state, params)

        return timed(fused_step, opt.init)

    # both shipped configs: per-leaf (XLA fuses per tensor) and chunked
    # flat buffer (one wide kernel per op + pack/unpack copies) — which
    # wins depends on tree fragmentation and platform, and the update is
    # elementwise so the two agree to ~1 ulp; report the better one as
    # the headline with both measured
    dt_leaf = time_fused(flat=False)
    dt_flat = time_fused(flat=True)
    dt, config = ((dt_leaf, "per_leaf") if dt_leaf <= dt_flat
                  else (dt_flat, "flat"))

    dt_native = None
    try:
        import optax

        native = optax.adamw(1e-3, weight_decay=1e-2)

        @partial(jax.jit, donate_argnums=(1, 2))
        def native_step(grads, state, params):
            updates, state = native.update(grads, state, params)
            return optax.apply_updates(params, updates), state

        dt_native = timed(native_step, native.init)
    except ImportError:
        pass

    return {
        "value": round(dt * 1e6, 1),
        "unit": "us/step",
        "config": config,
        "per_leaf_us": round(dt_leaf * 1e6, 1),
        "flat_us": round(dt_flat * 1e6, 1),
        "native_optax_us": round(dt_native * 1e6, 1) if dt_native else None,
        "vs_native": round(dt / dt_native, 3) if dt_native else None,
        "n_tensors": n_tensors,
        "n_elements": n_tensors * size,
    }


def bench_zero_adam_step(jax, on_tpu):
    """ZeRO optimizer step-time microbench over the dp mesh: flat-bucket
    ``DistributedFusedAdam`` (one reduce-scatter + one all-gather per
    dtype-group bucket) vs the per-leaf port (one collective pair per
    tensor) vs the replicated ``FusedAdam`` baseline, on a 161-leaf
    RN50-ish tree.  ``vs_per_leaf`` < 1 means the bucketed exchange wins —
    the point of the reference's StateBucket design
    (``apex/contrib/optimizers/distributed_fused_adam.py:397``).  On CPU
    the child runs with 8 virtual host devices (same as ``tp_gpt``)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu import parallel
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import collectives as cc

    n_tensors = 161  # RN50-ish tree; >= 100 leaves is where per-leaf drowns
    size = 160_000 if on_tpu else 1_000
    steps = 50 if on_tpu else 5
    mesh = parallel.initialize_model_parallel()  # all devices on dp
    dp = mesh.shape["dp"]
    keys = [f"w{i}" for i in range(n_tensors)]

    @jax.jit
    def make_tree(fill):
        return {k: jnp.full((size,), fill, jnp.float32) for k in keys}

    grads = make_tree(1e-4)

    def timed(step, init_params_state):
        params, state = init_params_state()
        params, state = step(grads, state, params)  # compile
        jax.block_until_ready((params, state))
        t0 = time.perf_counter()
        for _ in range(steps):
            params, state = step(grads, state, params)
        jax.block_until_ready((params, state))
        return (time.perf_counter() - t0) / steps

    def time_dist(opt):
        param_spec = {k: P() for k in keys}
        state_specs = opt.state_partition_specs(grads)
        init = jax.jit(cc.shard_over(
            opt.init, mesh=mesh, in_specs=(param_spec,),
            out_specs=state_specs))
        step = jax.jit(
            cc.shard_over(
                lambda g, s, p: opt.step(g, s, p), mesh=mesh,
                in_specs=(param_spec, state_specs, param_spec),
                out_specs=(param_spec, state_specs)),
            donate_argnums=(1, 2))
        return timed(step,
                     lambda: (make_tree(0.01), init(make_tree(0.01))))

    dt_flat = time_dist(DistributedFusedAdam(
        lr=1e-3, weight_decay=1e-2, flat_bucket=True))
    dt_leaf = time_dist(DistributedFusedAdam(
        lr=1e-3, weight_decay=1e-2, flat_bucket=False))

    # replicated baseline: every replica does the full FusedAdam update,
    # no sharded state, no collectives (grads pre-averaged upstream)
    rep = FusedAdam(lr=1e-3, weight_decay=1e-2)

    @partial(jax.jit, donate_argnums=(1, 2))
    def rep_step(g, s, p):
        return rep.step(g, s, p)

    dt_rep = timed(rep_step,
                   lambda: (make_tree(0.01), jax.jit(rep.init)(
                       make_tree(0.01))))

    return {
        "value": round(dt_flat * 1e6, 1),
        "unit": "us/step",
        "config": "flat_bucket",
        "flat_bucket_us": round(dt_flat * 1e6, 1),
        "per_leaf_us": round(dt_leaf * 1e6, 1),
        "replicated_us": round(dt_rep * 1e6, 1),
        "vs_per_leaf": round(dt_flat / dt_leaf, 3),
        "n_tensors": n_tensors,
        "n_elements": n_tensors * size,
        "dp": dp,
    }


def bench_ckpt_save_restore(jax, on_tpu):
    """Checkpoint-path wall-time (ISSUE 3): save / verify / restore for
    the flat (``save_checkpoint``) vs sharded (``save_checkpoint_sharded``)
    layouts on the same train-state-shaped tree, so checkpoint-path
    regressions (checksumming cost, fsync stalls, manifest overhead)
    show up in the perf trajectory like any compute row.  ``vs_sharded``
    = flat total / sharded total (< 1 = flat faster; sharded wins once
    per-process parallel writes matter, which a single host can't show).
    On CPU the child runs with 8 virtual devices so the sharded layout
    actually splits shards over a dp mesh."""
    import tempfile

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import checkpoint as ckpt
    from apex_tpu import parallel

    n_tensors = 32
    size = 262_144 if on_tpu else 32_768  # fp32 elems per leaf
    reps = 3
    mesh = parallel.initialize_model_parallel()  # all devices on dp
    sharding = NamedSharding(mesh, P(("dcn", "dp")))
    tree = {
        f"w{i}": jax.device_put(
            jnp.full((size,), float(i % 7) + 0.5, jnp.float32), sharding)
        for i in range(n_tensors)
    }
    jax.block_until_ready(tree)
    nbytes = n_tensors * size * 4

    def timed(fn):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best * 1e3  # ms

    with tempfile.TemporaryDirectory() as d:
        flat = os.path.join(d, "flat.npz")
        flat_save = timed(lambda: ckpt.save_checkpoint(flat, tree, step=1))
        flat_verify = timed(lambda: ckpt.verify_checkpoint(flat))
        flat_restore = timed(lambda: ckpt.restore_checkpoint(flat, tree))

        shd = os.path.join(d, "sharded")
        shd_save = timed(
            lambda: ckpt.save_checkpoint_sharded(shd, tree, step=1))
        shd_verify = timed(lambda: ckpt.verify_checkpoint_sharded(shd))
        shd_restore = timed(
            lambda: ckpt.restore_checkpoint_sharded(shd, tree))

    parallel.destroy_model_parallel()
    flat_total = flat_save + flat_verify + flat_restore
    shd_total = shd_save + shd_verify + shd_restore
    return {
        "value": round(flat_total, 2),
        "unit": "ms/save+verify+restore",
        "config": "flat",
        "flat_save_ms": round(flat_save, 2),
        "flat_verify_ms": round(flat_verify, 2),
        "flat_restore_ms": round(flat_restore, 2),
        "sharded_save_ms": round(shd_save, 2),
        "sharded_verify_ms": round(shd_verify, 2),
        "sharded_restore_ms": round(shd_restore, 2),
        "vs_sharded": round(flat_total / max(shd_total, 1e-9), 3),
        "checkpoint_mb": round(nbytes / 2**20, 1),
        "dp": mesh.shape["dp"] if "dp" in mesh.shape else 1,
    }


def bench_ckpt_reshard(jax, on_tpu):
    """Restore-anywhere wall-time (ISSUE 6): the same committed
    flat-bucket ZeRO checkpoint restored onto the mesh that wrote it
    (the plain lazy path) vs onto a HALVED dp world
    (``resilience.reshard.restore_resharded`` — logical leaves
    reassembled on host, buckets re-chunked).  ``vs_same_mesh`` =
    reshard-restore / same-mesh-restore (> 1 expected: resharding
    materializes and re-packs every bucket on host); the row exists so
    the elastic-resume cost stays a measured number and host-path
    regressions (spec parsing, unflatten/re-chunk copies) show up in the
    perf trajectory."""
    import tempfile

    import numpy as np

    from apex_tpu import parallel
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.parallel.distributed import replicate, zero_init
    from apex_tpu.resilience import CheckpointManager, reshard

    n_tensors = 16
    size = 262_144 if on_tpu else 32_768  # fp32 elems per leaf
    reps = 3
    devices = jax.devices()
    if len(devices) < 2:
        return {"error": "needs >= 2 devices for a dp halving"}
    opt = DistributedFusedAdam(lr=1e-2, flat_bucket=True, n_buckets=4)
    host = {f"w{i}": jax.numpy.full((size,), float(i % 7) + 0.5)
            for i in range(n_tensors)}

    def build(devs):
        mesh = parallel.initialize_model_parallel(devices=devs)
        p = replicate(host, mesh)
        pack = {"params": p, "opt": zero_init(opt, p, mesh)}
        spec = reshard.build_spec(pack, mesh=mesh,
                                  zero_states=[("opt", opt, p)])
        return pack, spec

    def timed(fn):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            best = min(best, time.perf_counter() - t0)
        return best * 1e3  # ms

    with tempfile.TemporaryDirectory() as d:
        pack, spec = build(devices)
        writer = CheckpointManager(d, sharded=True, spec=spec)
        writer.save(pack, 0)
        same_ms = timed(lambda: writer.restore_latest(pack)[0])
        parallel.destroy_model_parallel()

        half, spec_half = build(devices[: len(devices) // 2])
        reader = CheckpointManager(d, sharded=True, spec=spec_half)
        reshard_ms = timed(lambda: reader.restore_latest(half)[0])
        dp_src, dp_dst = len(devices), len(devices) // 2
        parallel.destroy_model_parallel()

    nbytes = sum(np.asarray(x).nbytes
                 for x in jax.tree_util.tree_leaves(pack))
    return {
        "value": round(reshard_ms, 2),
        "unit": "ms/reshard-restore",
        "config": f"zero_flat_bucket dp{dp_src}->dp{dp_dst}",
        "same_mesh_restore_ms": round(same_ms, 2),
        "reshard_restore_ms": round(reshard_ms, 2),
        "vs_same_mesh": round(reshard_ms / max(same_ms, 1e-9), 3),
        "checkpoint_mb": round(nbytes / 2**20, 1),
        "measured": (
            "flat-bucket ZeRO train state: restore_latest onto the "
            "writing mesh (lazy slice assembly) vs restore_resharded "
            "onto dp/2 (host reassembly + re-chunk); verification on "
            "for both"),
    }


def bench_serving(jax, on_tpu):
    """Continuous-batching decode runtime (ISSUE 9): steady-state
    tokens/sec and p50/p99 time-per-output-token at several concurrent-
    request levels, plus the fused-vs-unfused decode A/B.

    ``tokens_per_sec_at`` / ``tpot_p50_ms_at`` / ``tpot_p99_ms_at`` are
    keyed by concurrency — the continuous-batching win IS the shape of
    that curve (a batched decode step costs ~the same wall time at c=1
    and c=max_batch, so tokens/sec should scale near-linearly until the
    chip saturates).  ``vs_unfused`` = fused tokens/sec over the
    unfused-XLA lowering's (paged-attention Pallas kernel + fused
    residual/norm epilogue vs gather + separate-HLO chain) at the top
    concurrency — > 1 means the fusions pay.  NB on the CPU mesh the
    Pallas kernels run in *interpret mode*, so the CPU ``vs_unfused``
    measures dispatch overhead, not the HBM-gather saving; the TPU
    window is where the ratio is meaningful (docs/serving.md)."""
    import numpy as np

    from apex_tpu import parallel
    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.serving import ServingConfig, ServingEngine
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    devices = jax.devices()
    tp = min(8, len(devices)) if not on_tpu else 1
    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=tp, devices=devices[:tp])
    hidden, layers, heads, vocab = (
        (512, 4, 8, 2048) if on_tpu else (128, 2, 8, 512))
    max_batch, prompt_len, gen = 8, 16, 24
    cfg = TransformerConfig(
        hidden_size=hidden, num_layers=layers, num_attention_heads=heads,
        padded_vocab_size=vocab, max_position_embeddings=256,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)
    init_fn, _, _ = build_gpt_3d(cfg, num_chunks=layers,
                                 num_microbatches=1, mesh=mesh)
    params, _ = init_fn(jax.random.PRNGKey(0),
                        jax.numpy.zeros((2, 8), jax.numpy.int32))
    rng = np.random.RandomState(0)

    def run_level(concurrency, fused):
        eng = ServingEngine(
            cfg, ServingConfig(max_batch=max_batch, block_size=16,
                               max_seq=prompt_len + gen + 8,
                               prefill_len=128, fused_attention=fused,
                               fuse_epilogue=fused),
            params, mesh=mesh, registry=MetricRegistry(rank=0))
        # warmup: pay the prefill+decode compiles outside the window
        eng.submit(rng.randint(1, vocab - 1, size=prompt_len).tolist(), 2)
        eng.run_until_drained(max_steps=100)
        registry = MetricRegistry(rank=0)   # steady-state window only
        eng.registry = registry
        reqs = [eng.submit(rng.randint(1, vocab - 1,
                                       size=prompt_len).tolist(), gen)
                for _ in range(concurrency)]
        t0 = time.perf_counter()
        eng.run_until_drained(max_steps=5000)
        dt = time.perf_counter() - t0
        tokens = registry.counter("serving/tokens_generated").value
        assert all(len(r.output_tokens) == gen for r in reqs)
        assert eng.decode_compile_count() == 1
        tpot = registry.histogram("serving/tpot_ms")
        return (tokens / max(dt, 1e-9), tpot.percentile(50.0),
                tpot.percentile(99.0))

    levels = [1, 4, max_batch]
    tps, p50, p99 = {}, {}, {}
    for c in levels:
        rate, l50, l99 = run_level(c, fused=True)
        tps[str(c)] = round(rate, 1)
        p50[str(c)] = round(l50, 2) if l50 is not None else None
        p99[str(c)] = round(l99, 2) if l99 is not None else None
        _log(f"serving: c={c} {tps[str(c)]} tok/s "
             f"p50={p50[str(c)]}ms p99={p99[str(c)]}ms")
    unfused_rate, _, _ = run_level(max_batch, fused=False)
    parallel.destroy_model_parallel()
    top = str(max_batch)
    return {
        "value": tps[top],
        "unit": "tokens/sec",
        "config": (f"gpt h{hidden} L{layers} tp{tp} max_batch{max_batch} "
                   f"prompt{prompt_len} gen{gen}"),
        "tokens_per_sec_at": tps,
        "tpot_p50_ms_at": p50,
        "tpot_p99_ms_at": p99,
        "vs_unfused": round(tps[top] / max(unfused_rate, 1e-9), 3),
        "measured": (
            "continuous-batching greedy decode, paged KV cache, steady "
            "state after the compile step; tokens/sec at concurrency "
            f"{levels}; vs_unfused = fused (Pallas paged attention + "
            "fused epilogue) over unfused XLA lowering at "
            f"c={max_batch} (interpret-mode Pallas on CPU)"),
    }


def bench_serving_occupancy(jax, on_tpu):
    """Serving at production occupancy (ISSUE 12): throughput and p99
    TPOT as the KV pool is oversubscribed 1x/2x/4x against the
    steady-state worst-case demand, on a shared-template workload.

    PR 8 admitted by worst-case reservation, so the pool had to cover
    every admitted request's full horizon; occupancy admission
    (on-demand growth + prefix-cache eviction + preemption with
    recompute-on-readmit) keeps the batch full from a fraction of the
    pool.  ``tokens_per_sec_at``/``tpot_p99_ms_at`` are keyed by the
    oversubscription factor; every admitted request must FINISH at
    every factor (preempt + recompute, zero failures — asserted).
    ``vs_reserve`` = occupancy tokens/sec over the worst-case-
    reservation baseline at the SAME 2x pool — > 1 means occupancy
    admission pays.  ``ttft_cold_ms``/``ttft_hit_ms`` time the first
    token of a long-template prompt cold vs after the template's
    blocks are prefix-cached (``ttft_hit_vs_cold`` < 1 = sharing
    pays); NB on CPU the Pallas kernels run in interpret mode, so the
    absolute numbers are CPU-shaped — the curve and the ratios are the
    signal, the TPU window is the real magnitude (docs/serving.md)."""
    import numpy as np

    from apex_tpu import parallel
    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.serving import ServingConfig, ServingEngine
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    devices = jax.devices()
    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=1, devices=devices[:1])
    hidden, layers, heads, vocab = (
        (512, 4, 8, 2048) if on_tpu else (128, 2, 8, 512))
    max_batch, block = 8, 16
    template_len, suffix_len, gen = 96, 8, 24
    prompt_len = template_len + suffix_len
    max_seq = prompt_len + gen + block
    n_requests = 16
    cfg = TransformerConfig(
        hidden_size=hidden, num_layers=layers, num_attention_heads=heads,
        padded_vocab_size=vocab, max_position_embeddings=max_seq,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)
    init_fn, _, _ = build_gpt_3d(cfg, num_chunks=layers,
                                 num_microbatches=1, mesh=mesh)
    params, _ = init_fn(jax.random.PRNGKey(0),
                        jax.numpy.zeros((2, 8), jax.numpy.int32))
    rng = np.random.RandomState(0)
    template = rng.randint(1, vocab - 1, size=template_len).tolist()
    prompts = [template + rng.randint(1, vocab - 1,
                                      size=suffix_len).tolist()
               for _ in range(n_requests)]
    per_req = -(-min(prompt_len + gen, max_seq) // block)
    demand = max_batch * per_req          # steady worst-case working set

    def build(n_blocks, admission):
        eng = ServingEngine(
            cfg, ServingConfig(max_batch=max_batch, block_size=block,
                               max_seq=max_seq, n_blocks=n_blocks,
                               prefill_len=64, admission=admission),
            params, mesh=mesh, registry=MetricRegistry(rank=0))
        # warmup: pay the prefill+decode compiles outside every window
        eng.submit(rng.randint(1, vocab - 1, size=8).tolist(), 2)
        eng.run_until_drained(max_steps=200)
        return eng

    def throughput(eng):
        registry = MetricRegistry(rank=0)   # steady-state window only
        eng.registry = registry
        reqs = [eng.submit(p, gen) for p in prompts]
        t0 = time.perf_counter()
        eng.run_until_drained(max_steps=50_000)
        dt = time.perf_counter() - t0
        assert all(len(r.output_tokens) == gen for r in reqs), \
            "an admitted request failed to finish"
        assert eng.decode_compile_count() == 1
        tokens = registry.counter("serving/tokens_generated").value
        p99 = registry.histogram("serving/tpot_ms").percentile(99.0)
        return (tokens / max(dt, 1e-9),
                round(p99, 2) if p99 is not None else None)

    def ttft_ms(eng, prompt):
        req = eng.submit(prompt, 2)
        eng.run_until_drained(max_steps=5000)
        return (req.t_first_token - req.t_submit) * 1e3

    tps, p99s, preempts = {}, {}, {}
    for factor in (1, 2, 4):
        pool = max(-(-demand // factor), per_req)
        eng = build(pool, "occupancy")
        if factor == 1:
            # TTFT A/B on the 1x engine while its prefix cache is cold:
            # same template, different suffix -> the second prompt
            # shares the template's blocks and prefills only the tail
            cold = ttft_ms(eng, template
                           + rng.randint(1, vocab - 1, size=8).tolist())
            hit = ttft_ms(eng, template
                          + rng.randint(1, vocab - 1, size=8).tolist())
        rate, p99 = throughput(eng)
        key = f"{factor}x"
        tps[key], p99s[key] = round(rate, 1), p99
        preempts[key] = int(eng.scheduler.preemptions)
        _log(f"serving_occupancy: {key} pool={pool} {tps[key]} tok/s "
             f"p99={p99}ms preemptions={preempts[key]}")
    pool_2x = max(-(-demand // 2), per_req)
    reserve_rate, _ = throughput(build(pool_2x, "reserve"))
    parallel.destroy_model_parallel()
    return {
        "value": tps["2x"],
        "unit": "tokens/sec",
        "config": (f"gpt h{hidden} L{layers} max_batch{max_batch} "
                   f"block{block} template{template_len} gen{gen} "
                   f"n_req{n_requests} demand{demand}blk"),
        "tokens_per_sec_at": tps,
        "tpot_p99_ms_at": p99s,
        "preemptions_at": preempts,
        "vs_reserve": round(tps["2x"] / max(reserve_rate, 1e-9), 3),
        "ttft_cold_ms": round(cold, 2),
        "ttft_hit_ms": round(hit, 2),
        "ttft_hit_vs_cold": round(hit / max(cold, 1e-9), 3),
        "measured": (
            "occupancy admission (prefix caching + eviction + "
            "preemption/recompute) at pool oversubscription 1x/2x/4x "
            f"of the {demand}-block steady demand; every request "
            "finishes at every factor; vs_reserve = occupancy over "
            "worst-case reservation at the same 2x pool; ttft hit vs "
            "cold on a shared 96-token template (interpret-mode Pallas "
            "on CPU)"),
    }


def bench_serving_fleet(jax, on_tpu):
    """Fleet serving (ISSUE 11): steady-state fleet tokens/sec over 3
    replica processes behind the router, and p99 TPOT during a
    staggered zero-downtime weight rollout vs steady state.

    ``value`` is fleet tokens/sec with all replicas up;
    ``p99_tpot_ms_steady`` / ``p99_tpot_ms_roll`` are router-observed
    inter-token p99s in the two windows, and ``roll_vs_steady`` their
    ratio — the SLO cost of rolling new weights through the fleet under
    load (the smoke gates it hard; here it is a tracked number).  Each
    replica is its own spawned process with its own mesh and compiled
    programs (CPU: 3x tp=1 on one host — measuring the router + process
    transport, not chip scaling; a TPU window would give each replica
    its own chip).

    ISSUE 14: the same steady wave then runs over the framed-TCP
    transport (3 ``replica_serve`` daemons on loopback) —
    ``tokens_per_sec_socket`` and ``wire_vs_inproc`` (socket/in-proc
    ratio) track the wire cost instead of guessing it.  Measured
    surprise, stable across runs: ~15x ABOVE in-proc on the CPU host —
    the socket server batches a whole event backlog into each 64 KB
    send while mp.Queue pays a feeder-thread wakeup per put (GIL-
    starved while the child decodes); the socket wave runs at the
    fleet's compute-bound ceiling (~16 ticks x p99 TPOT).  ISSUE 15
    re-stamp: the worker now batches its event backlog into one queue
    put per relay turn (fleet/relay_batch), and the ratio BARELY moved
    (15.7x, was ~15x) — the verdict is that the feeder-thread wakeup
    latency dominates, not the per-event pickle count, so the socket
    transport stays the performance path even single-host.  Loopback
    bounds framing+session cost only; cross-host adds real NIC
    latency on top."""
    import os
    import shutil
    import tempfile

    import numpy as np

    from apex_tpu import parallel
    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.resilience import CheckpointManager, reshard
    from apex_tpu.serving import (
        FleetRouter, ReplicaProcess, ReplicaSpec, ServingConfig)
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import (
        build_gpt_3d, gpt3d_logical_folds)

    n_replicas = 3
    hidden, layers, heads, vocab = (
        (256, 2, 8, 1024) if on_tpu else (64, 2, 4, 256))
    prompt_len, gen, wave = 12, 16, 24
    max_seq = prompt_len + gen + 4
    cfg = TransformerConfig(
        hidden_size=hidden, num_layers=layers, num_attention_heads=heads,
        padded_vocab_size=vocab, max_position_embeddings=max_seq,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)
    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=1, devices=jax.devices()[:1])
    init_fn, _, _ = build_gpt_3d(cfg, num_chunks=layers,
                                 num_microbatches=1, mesh=mesh)
    params, _ = init_fn(jax.random.PRNGKey(0),
                        jax.numpy.zeros((2, 8), jax.numpy.int32))
    workdir = tempfile.mkdtemp(prefix="apex_bench_fleet_")
    ckpt_dir = os.path.join(workdir, "ckpt")
    tree = {"params": params, "step_count": np.asarray(1)}
    spec = reshard.build_spec(tree, mesh=mesh,
                              folds=gpt3d_logical_folds(tree))
    CheckpointManager(ckpt_dir, sharded=True, spec=spec).save(tree, 1)
    rng = np.random.RandomState(0)
    router = None
    sock_procs = []
    try:
        rspec = ReplicaSpec(
            config=cfg,
            serving=ServingConfig(max_batch=8, block_size=8,
                                  max_seq=max_seq, prefill_len=64),
            tp=1, ckpt_dir=ckpt_dir, debug_server=False)
        replicas = [ReplicaProcess(rspec, f"r{i}")
                    for i in range(n_replicas)]
        for r in replicas:
            r.wait_ready(timeout=500)
        registry = MetricRegistry(rank=0, world=1)
        router = FleetRouter(replicas, max_queue_depth=4 * wave,
                             replica_queue_limit=wave,
                             heartbeat_timeout_s=30.0,
                             registry=registry)

        def run_wave(n, budget):
            reqs = [router.submit(
                rng.randint(1, vocab - 1, size=prompt_len).tolist(),
                budget) for _ in range(n)]
            router.run_until_idle(timeout_s=500)
            assert all(len(r.output_tokens) == budget for r in reqs)
            return reqs

        run_wave(n_replicas, 2)   # warm the transport path
        t0 = time.perf_counter()
        reqs = run_wave(wave, gen)
        steady_dt = time.perf_counter() - t0
        tokens = sum(len(r.output_tokens) for r in reqs)
        p99_steady = registry.histogram("fleet/tpot_ms").percentile(99)

        roll_reg = MetricRegistry(rank=0, world=1)
        router.registry = roll_reg
        drip, budget_left = [], [wave]

        def on_tick():
            if budget_left[0] > 0 and router.total_queue_depth() < 8:
                drip.append(router.submit(
                    rng.randint(1, vocab - 1,
                                size=prompt_len).tolist(), gen // 2))
                budget_left[0] -= 1

        t1 = time.perf_counter()
        router.rollout(lambda name: ReplicaProcess(rspec, name),
                       on_tick=on_tick, drain_timeout_s=200,
                       ready_timeout_s=500)
        router.run_until_idle(timeout_s=500)
        roll_dt = time.perf_counter() - t1
        assert all(r.output_tokens for r in drip)
        p99_roll = roll_reg.histogram("fleet/tpot_ms").percentile(99)

        # socket-transport leg (ISSUE 14): the same steady wave over
        # framed loopback TCP through replica_serve daemons
        from apex_tpu.serving.transport import (
            SocketTransport, start_replica_server)

        router.close()                 # free the mp fleet first
        started = [start_replica_server(rspec, f"s{i}",
                                        addr_timeout_s=500)
                   for i in range(n_replicas)]
        sock_procs = [p for p, _ in started]
        sock_clients = [SocketTransport(f"s{i}", addr)
                        for i, (_, addr) in enumerate(started)]
        for c in sock_clients:
            c.wait_ready(timeout=500)
        router = FleetRouter(sock_clients, max_queue_depth=4 * wave,
                             replica_queue_limit=wave,
                             heartbeat_timeout_s=30.0,
                             registry=MetricRegistry(rank=0, world=1))
        run_wave(n_replicas, 2)        # warm the socket path
        t2 = time.perf_counter()
        sreqs = run_wave(wave, gen)
        sock_dt = time.perf_counter() - t2
        sock_tps = sum(len(r.output_tokens)
                       for r in sreqs) / max(sock_dt, 1e-9)
        steady_tps = tokens / max(steady_dt, 1e-9)
        _log(f"serving_fleet: {steady_tps:.1f} tok/s steady "
             f"(p99 TPOT {p99_steady}ms), roll {roll_dt:.1f}s "
             f"(p99 TPOT {p99_roll}ms, {len(drip)} drip requests), "
             f"socket {sock_tps:.1f} tok/s "
             f"({sock_tps / steady_tps:.3f}x in-proc)")
        return {
            "value": round(tokens / max(steady_dt, 1e-9), 1),
            "unit": "tokens/sec",
            "config": (f"gpt h{hidden} L{layers} {n_replicas}x tp1 "
                       f"replicas prompt{prompt_len} gen{gen} "
                       f"wave{wave}"),
            "replicas": n_replicas,
            "p99_tpot_ms_steady": (round(p99_steady, 2)
                                   if p99_steady is not None else None),
            "p99_tpot_ms_roll": (round(p99_roll, 2)
                                 if p99_roll is not None else None),
            "roll_vs_steady": (round(p99_roll / p99_steady, 3)
                               if p99_roll and p99_steady else None),
            "roll_wall_s": round(roll_dt, 1),
            "tokens_per_sec_socket": round(sock_tps, 1),
            "wire_vs_inproc": round(sock_tps / steady_tps, 3),
            "measured": (
                f"{wave} requests x {gen} greedy tokens across "
                f"{n_replicas} replica processes via the fleet router "
                "(steady window, post-warmup); then a staggered SIGTERM "
                "drain + restore-from-checkpoint roll of every replica "
                f"under a {wave}-request drip — p99 TPOT per window is "
                "router-observed inter-token latency; then the same "
                "steady wave over the framed-TCP socket transport "
                "(replica_serve daemons, loopback) — wire_vs_inproc = "
                "socket/in-proc tokens-per-sec (>1 on CPU: batched "
                "socket event relay beats mp.Queue's one-pickle-per-"
                "feeder-wakeup)"),
        }
    finally:
        if router is not None:
            router.close()
        from apex_tpu.data._producer import reap_process
        for p in sock_procs:
            try:
                p.terminate()
            except Exception:
                pass
            reap_process(p, 15.0, what="socket replica")
        shutil.rmtree(workdir, ignore_errors=True)
        parallel.destroy_model_parallel()


def bench_serving_spec(jax, on_tpu):
    """Speculative decoding (ISSUE 13): accepted-tokens/sec of the
    self-speculative engine (n-gram drafting + fused k+1 verify) vs the
    non-speculative baseline at concurrency 1/4/8, on a
    template-heavy workload where prompt-lookup drafting actually
    fires.

    ``tokens_per_sec_at`` is the speculative engine's emitted-token
    rate per concurrency (every emitted token is an *accepted* token —
    the verify never emits an unverified draft);
    ``baseline_tokens_per_sec_at`` the plain engine's on the same wave;
    ``vs_baseline_at`` their per-concurrency ratios and ``vs_baseline``
    the top-concurrency ratio (>= 1 means speculation pays — the
    acceptance bar demands it never regresses, even on CPU).
    ``mean_accept_len`` is emitted tokens per decode/verify call (1.0 =
    nothing accepted, k+1 = every draft accepted);
    ``acceptance_rate`` the drafted-token hit rate.  NB on CPU the
    verify's extra FLOPs are nearly free only relative to CPU dispatch
    overhead; the TPU window measures the real memory-bound win
    (docs/serving.md — the decode tick is HBM-bound there, so k extra
    query positions ride the same paged gather)."""
    import numpy as np

    from apex_tpu import parallel
    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.serving import (
        ServingConfig, ServingEngine, SpeculativeConfig)
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    devices = jax.devices()
    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=1, devices=devices[:1])
    hidden, layers, heads, vocab = (
        (512, 4, 8, 2048) if on_tpu else (128, 2, 8, 512))
    max_batch, block, gen, k = 8, 16, 32, 4
    motif_len, reps, suffix_len = 4, 8, 4
    prompt_len = motif_len * reps + suffix_len
    max_seq = prompt_len + gen + block
    cfg = TransformerConfig(
        hidden_size=hidden, num_layers=layers, num_attention_heads=heads,
        padded_vocab_size=vocab, max_position_embeddings=max_seq,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)
    init_fn, _, _ = build_gpt_3d(cfg, num_chunks=layers,
                                 num_microbatches=1, mesh=mesh)
    params, _ = init_fn(jax.random.PRNGKey(0),
                        jax.numpy.zeros((2, 8), jax.numpy.int32))
    rng = np.random.RandomState(0)
    # template-heavy prompts: a repeated motif plus a short unique
    # suffix — the workload shape (shared templates, quoted context,
    # structured output) prompt-lookup drafting exists for
    prompts = []
    for _ in range(max_batch):
        motif = rng.randint(1, vocab - 1, size=motif_len).tolist()
        prompts.append(motif * reps
                       + rng.randint(1, vocab - 1,
                                     size=suffix_len).tolist())

    def build(spec):
        eng = ServingEngine(
            cfg, ServingConfig(max_batch=max_batch, block_size=block,
                               max_seq=max_seq, prefill_len=64,
                               speculative=spec),
            params, mesh=mesh, registry=MetricRegistry(rank=0))
        # warmup: pay the prefill + decode/verify compiles outside
        # every timed window
        eng.submit(rng.randint(1, vocab - 1, size=8).tolist(), 2)
        eng.run_until_drained(max_steps=200)
        return eng

    def level(eng, c):
        registry = MetricRegistry(rank=0)   # steady-state window only
        eng.registry = registry
        acc0, slots0 = eng.spec_accepted, eng._slot_steps
        reqs = [eng.submit(p, gen) for p in prompts[:c]]
        t0 = time.perf_counter()
        eng.run_until_drained(max_steps=20_000)
        dt = time.perf_counter() - t0
        assert all(len(r.output_tokens) == gen for r in reqs)
        assert eng.decode_compile_count() == 1
        tokens = registry.counter("serving/tokens_generated").value
        # mean accept length: tokens one slot emits per verify step —
        # 1 (the always-emitted verified token) + accepted drafts per
        # slot-step; 1.0 = plain decode, k+1 = every draft accepted
        mean_len = 1.0 + ((eng.spec_accepted - acc0)
                          / max(eng._slot_steps - slots0, 1))
        return tokens / max(dt, 1e-9), mean_len

    spec_eng = build(SpeculativeConfig(k=k))
    base_eng = build(None)
    levels = [1, 4, max_batch]
    tps, base_tps, ratio, accept = {}, {}, {}, {}
    for c in levels:
        key = str(c)
        rate, mean_len = level(spec_eng, c)
        base_rate, _ = level(base_eng, c)
        tps[key] = round(rate, 1)
        base_tps[key] = round(base_rate, 1)
        ratio[key] = round(rate / max(base_rate, 1e-9), 3)
        accept[key] = round(mean_len, 2)
        _log(f"serving_spec: c={c} spec {tps[key]} vs base "
             f"{base_tps[key]} tok/s (x{ratio[key]}, mean accept len "
             f"{accept[key]})")
    acc_rate = (spec_eng.spec_accepted / spec_eng.spec_proposed
                if spec_eng.spec_proposed else None)
    parallel.destroy_model_parallel()
    top = str(max_batch)
    return {
        "value": tps[top],
        "unit": "tokens/sec",
        "config": (f"gpt h{hidden} L{layers} max_batch{max_batch} k{k} "
                   f"prompt{prompt_len} (motif{motif_len}x{reps}) "
                   f"gen{gen}"),
        "tokens_per_sec_at": tps,
        "baseline_tokens_per_sec_at": base_tps,
        "vs_baseline_at": ratio,
        "vs_baseline": ratio[top],
        "mean_accept_len": accept[top],
        "acceptance_rate": (round(acc_rate, 3)
                            if acc_rate is not None else None),
        "measured": (
            "self-speculative n-gram decode (fused [max_batch, k+1] "
            f"verify, k={k}) vs the non-speculative engine on a "
            "template-heavy greedy wave at concurrency "
            f"{levels}; emitted tokens are verified-accepted tokens, "
            "so vs_baseline is accepted-tokens/sec over baseline "
            "tokens/sec (interpret-mode Pallas on CPU — the TPU window "
            "measures the memory-bound win)"),
    }


def bench_serving_lora(jax, on_tpu):
    """Batched multi-LoRA serving (ISSUE 17): emitted-tokens/sec of the
    LoRA-enabled engine on waves tagged round-robin over 1 / 8 / 64
    concurrent adapters, vs the bare (``lora=None``) engine on the same
    untagged wave.

    Every request in the tagged wave carries an ``adapter_id`` through
    ``SamplingParams``, so every decode tick runs the per-slot gathered
    low-rank delta (the scalar-prefetch kernel indexes the paged
    adapter arena with the per-slot adapter-slot vector — data, never
    shape).  ``tokens_per_sec_at`` keys on the number of *distinct*
    concurrent adapters; ``vs_bare_at`` the per-level ratios; and
    ``vs_bare_1adapter`` — the single-tenant ratio, where the delta is
    pure overhead — is the floored acceptance signal (>= 0.9: one
    adapter must cost <= ~10%).  The decode compile count is asserted
    == 1 across all levels: 1 adapter and 64 adapters run the exact
    same jit program.  NB the CPU row runs the ``jnp.take`` unfused
    twin (``fused=False`` — same values): interpret-mode Pallas would
    gate interpreter dispatch, not the adapter math; the TPU window
    measures the real fused scalar-prefetch gather riding the decode
    tick."""
    import numpy as np

    from apex_tpu import parallel
    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.serving import (
        LoRAConfig, SamplingParams, ServingConfig, ServingEngine)
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    devices = jax.devices()
    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=1, devices=devices[:1])
    # rank deliberately small relative to hidden: the production regime
    # is r << h (16 vs 4096) — at the tiny-model r/h the delta's FLOPs
    # fraction stops representing what the floor gates.  max_batch is
    # the other half of that argument: the delta adds a fixed handful
    # of ops per layer, so a thin batch gates op-dispatch overhead
    # instead of the adapter math
    hidden, layers, heads, vocab, rank = (
        (512, 4, 8, 2048, 8) if on_tpu else (256, 2, 8, 512, 4))
    max_batch, block, gen = 32, 16, 32
    n_adapters, n_reqs, rounds = 64, 64, 3
    prompt_len = 16
    max_seq = prompt_len + gen + block
    cfg = TransformerConfig(
        hidden_size=hidden, num_layers=layers, num_attention_heads=heads,
        padded_vocab_size=vocab, max_position_embeddings=max_seq,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)
    init_fn, _, _ = build_gpt_3d(cfg, num_chunks=layers,
                                 num_microbatches=1, mesh=mesh)
    params, _ = init_fn(jax.random.PRNGKey(0),
                        jax.numpy.zeros((2, 8), jax.numpy.int32))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, vocab - 1, size=prompt_len).tolist()
               for _ in range(n_reqs)]

    def build(lora):
        eng = ServingEngine(
            cfg, ServingConfig(max_batch=max_batch, block_size=block,
                               max_seq=max_seq, prefill_len=64,
                               lora=lora),
            params, mesh=mesh, registry=MetricRegistry(rank=0))
        if lora is not None:
            # adapter registration (pack + device put) happens outside
            # every timed window — the steady state being measured is
            # decode with residents, not cold loads
            for i in range(n_adapters):
                eng.register_adapter(f"tenant-{i}", seed=i)
        # warmup: pay the prefill + decode compiles (including the
        # gathered-delta path) outside the timed windows
        warm = (SamplingParams(adapter_id="tenant-0")
                if lora is not None else None)
        eng.submit(rng.randint(1, vocab - 1, size=8).tolist(), 2,
                   sampling=warm)
        eng.run_until_drained(max_steps=500)
        return eng

    def level(eng, c):
        registry = MetricRegistry(rank=0)   # steady-state window only
        eng.registry = registry
        reqs = []
        for i, p in enumerate(prompts):
            sp = (SamplingParams(adapter_id=f"tenant-{i % c}")
                  if c else None)
            reqs.append(eng.submit(p, gen, sampling=sp))
        t0 = time.perf_counter()
        eng.run_until_drained(max_steps=50_000)
        dt = time.perf_counter() - t0
        assert all(len(r.output_tokens) == gen for r in reqs)
        # the jit-stability claim, measured where it matters: adapter
        # mix is data, so the whole sweep shares ONE decode program
        assert eng.decode_compile_count() == 1
        tokens = registry.counter("serving/tokens_generated").value
        return tokens / max(dt, 1e-9)

    # fused only where the kernel is real: the CPU fallback row would
    # otherwise gate the Pallas interpreter's dispatch overhead (~4x)
    # instead of the adapter math the floor is about
    lora_eng = build(LoRAConfig(rank=rank, max_adapters=n_adapters,
                                fused=on_tpu))
    base_eng = build(None)
    levels = [1, 8, n_adapters]
    tps, base_tps, ratio = {}, {}, {}
    for c in levels:
        key = str(c)
        # paired rounds, median ratio: host drift cancels (the
        # serving_trace_overhead discipline — the gated signal is a
        # ratio near 1, so single-window noise would flip the floor)
        pairs = [(level(lora_eng, c), level(base_eng, 0))
                 for _ in range(rounds)]
        ratios = sorted(r / max(b, 1e-9) for r, b in pairs)
        rates = sorted(r for r, _ in pairs)
        base_rates = sorted(b for _, b in pairs)
        tps[key] = round(rates[rounds // 2], 1)
        base_tps[key] = round(base_rates[rounds // 2], 1)
        ratio[key] = round(ratios[rounds // 2], 3)
        _log(f"serving_lora: adapters={c} lora {tps[key]} vs bare "
             f"{base_tps[key]} tok/s (x{ratio[key]} median of "
             f"{[round(x, 3) for x in ratios]})")
    parallel.destroy_model_parallel()
    top = str(n_adapters)
    return {
        "value": tps[top],
        "unit": "tokens/sec",
        "config": (f"gpt h{hidden} L{layers} max_batch{max_batch} "
                   f"rank{rank} adapters{n_adapters} reqs{n_reqs} "
                   f"prompt{prompt_len} gen{gen}"),
        "tokens_per_sec_at": tps,
        "bare_tokens_per_sec_at": base_tps,
        "vs_bare_at": ratio,
        "vs_bare_1adapter": ratio["1"],
        "measured": (
            f"{n_reqs}-request greedy waves tagged round-robin over "
            f"{levels} distinct adapters (rank-{rank} deltas gathered "
            "per slot from the paged arena via scalar-prefetch) vs the "
            "bare lora=None engine on the same untagged wave — "
            f"median of {rounds} paired rounds per level, so host "
            "drift cancels out of the gated ratio; one decode program "
            "across the whole sweep (CPU runs the jnp.take unfused "
            "twin — the TPU window measures the fused HBM-bound "
            "gather)"),
    }


def bench_serving_disagg(jax, on_tpu):
    """Disaggregated prefill/decode fleets (ISSUE 16): decode p99 TPOT
    under a concurrent prefill flood, 1-prefill + 1-decode vs 2
    co-located ``role="both"`` replicas at EQUAL pool size, plus the
    cost of the handoff itself (``kv_migrate_ms_per_req``,
    ``kv_migrate_kb_per_req`` — blocks on the wire per migrated
    request).

    The workload: a wave of decode-heavy requests (the latency-
    sensitive traffic) decodes while prefill-heavy flood requests
    (long prompt, 2 tokens) drip in continuously.  Co-located, every
    flood's prefill chunk steals engine ticks from decode on BOTH
    replicas; disaggregated, floods stay on the prefill replica
    (2-token budgets never cross ``migrate_min_remaining``) while the
    decode wave migrates over and decodes undisturbed.

    ``vs_colocated`` = co-located p99 / disaggregated p99 of the
    steady decode TPOT (>= 1.0 is the acceptance floor: disaggregation
    must protect the decode tail).  Both sides read the same steady
    signal: co-located from the decode tenant's SLO histogram (no
    migrations happen there), disaggregated from the decode ROLE
    histogram, which excludes the one inter-token gap spanning the
    handoff — that gap is reported separately as
    ``kv_migrate_ms_per_req``, not hidden.  The tenant-side p99
    INCLUDING the handoff gap rides along as
    ``p99_tpot_ms_disagg_tenant``."""
    import dataclasses as dc

    import numpy as np

    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.serving import (
        FleetRouter, ReplicaProcess, ReplicaSpec, ServingConfig)
    from apex_tpu.transformer.testing import TransformerConfig

    # the flood chunk must be EXPENSIVE relative to a decode tick —
    # head-of-line blocking inside a co-located engine is the effect
    # disaggregation removes, and it only rises above host scheduling
    # noise when one prefill chunk costs many decode ticks
    hidden, layers, heads, vocab = (
        (256, 2, 8, 1024) if on_tpu else (128, 2, 4, 256))
    flood_len, dec_len, dec_gen = 64, 8, 48
    n_dec, flood_total, flood_inflight = 4, 24, 6
    max_seq = flood_len + dec_gen + 8
    cfg = TransformerConfig(
        hidden_size=hidden, num_layers=layers, num_attention_heads=heads,
        padded_vocab_size=vocab, max_position_embeddings=max_seq,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)
    rspec = ReplicaSpec(
        config=cfg,
        serving=ServingConfig(max_batch=8, block_size=8,
                              max_seq=max_seq, prefill_len=flood_len),
        tp=1, ckpt_dir=None, debug_server=False)
    # per-role engine tuning — the knob disaggregation unlocks: a
    # decode-pool replica only ever prefills one-token import re-dos
    # (and failover replays), so its chunk width shrinks to a block
    # and an import costs ~1/8th of a flood chunk.  A co-located
    # replica cannot do this: it needs the wide chunk for the floods.
    dspec = dc.replace(rspec, serving=dc.replace(
        rspec.serving, prefill_len=8))
    rng = np.random.RandomState(7)
    dec_prompts = [rng.randint(1, vocab - 1, size=dec_len).tolist()
                   for _ in range(n_dec)]

    def run_fleet(roles):
        replicas = [ReplicaProcess(
            dc.replace(dspec if role == "decode" else rspec,
                       role=role), f"{role[0]}{i}")
                    for i, role in enumerate(roles)]
        for r in replicas:
            r.wait_ready(timeout=500)
        router = FleetRouter(replicas, max_queue_depth=128,
                             replica_queue_limit=32,
                             heartbeat_timeout_s=60.0,
                             registry=MetricRegistry(rank=0, world=1))
        frng = np.random.RandomState(11)
        try:
            # warm every shape on every engine, including the handoff
            # path (gen 6 crosses migrate_min_remaining, so the decode
            # replica compiles its import re-prefill here, not in the
            # measured window)
            warm = [router.submit(
                frng.randint(1, vocab - 1, size=dec_len).tolist(), 6)
                for _ in range(len(roles) * 2)]
            warm += [router.submit(
                frng.randint(1, vocab - 1, size=flood_len).tolist(), 2)
                for _ in range(len(roles))]
            router.run_until_idle(timeout_s=500)
            assert all(r.output_tokens for r in warm)
            # fresh registry for the measured window: the warm wave's
            # samples (compiles, its own migrations) must not ride
            # into the histograms this bench reads
            registry = MetricRegistry(rank=0, world=1)
            router.registry = registry
            # decode arrivals staggered 250ms apart — real latency-
            # sensitive streams start at independent times; back-to-
            # back submission would pile all the handoff imports into
            # one burst and measure the pileup, not the steady state
            dec, t0 = [], time.monotonic()
            budget, inflight = [flood_total], []
            deadline = t0 + 500
            while len(dec) < n_dec or not all(r.done for r in dec):
                router.pump()
                now = time.monotonic()
                if len(dec) < n_dec and now >= t0 + 0.25 * len(dec):
                    dec.append(router.submit(
                        dec_prompts[len(dec)], dec_gen, tenant="decode"))
                inflight[:] = [r for r in inflight if not r.done]
                while budget[0] > 0 and len(inflight) < flood_inflight:
                    inflight.append(router.submit(
                        frng.randint(1, vocab - 1,
                                     size=flood_len).tolist(),
                        2, tenant="flood"))
                    budget[0] -= 1
                if now > deadline:
                    raise RuntimeError("decode wave not terminal")
                time.sleep(0.0005)
            router.run_until_idle(timeout_s=500)
            status = router.fleet_statusz()
            snap = registry.snapshot()
            tenant_p99 = (status["slo"]["tenants"]["decode"]
                          ["tpot_ms"]["p99"])
            role_p99 = registry.histogram(
                "fleet/role/decode/tpot_ms").percentile(99)
            return {
                "streams": [list(r.output_tokens) for r in dec],
                "tenant_p99": tenant_p99,
                "role_p99": role_p99,
                "migrations": snap.get("fleet/kv_migrate_completed",
                                       0.0),
                "migrate_failed": snap.get("fleet/kv_migrate_failed",
                                           0.0),
                "migrate_ms_p50": registry.histogram(
                    "fleet/kv_migrate_ms").percentile(50),
                "migrate_bytes": snap.get("fleet/kv_migrate_bytes",
                                          0.0),
                "failovers": snap.get("fleet/failovers", 0.0),
            }
        finally:
            router.close()

    coloc = run_fleet(["both", "both"])
    disagg = run_fleet(["prefill", "decode"])
    # equal pool, same prompts, greedy: the decode streams must be
    # bitwise identical however the fleet is carved up
    assert coloc["streams"] == disagg["streams"], \
        "disaggregated decode streams diverged from co-located"
    assert coloc["failovers"] == 0 and disagg["failovers"] == 0
    assert disagg["migrations"] >= n_dec, \
        (f"only {disagg['migrations']} of {n_dec} decode requests "
         "migrated")
    p99_coloc = coloc["tenant_p99"]
    p99_disagg = disagg["role_p99"]
    mig_ms = disagg["migrate_ms_p50"]
    mig_kb = (disagg["migrate_bytes"] / disagg["migrations"] / 1024.0
              if disagg["migrations"] else None)
    vs = (round(p99_coloc / p99_disagg, 3)
          if p99_coloc and p99_disagg else None)
    _log(f"serving_disagg: decode p99 TPOT {p99_disagg:.1f}ms "
         f"disaggregated vs {p99_coloc:.1f}ms co-located "
         f"(x{vs}), {disagg['migrations']:.0f} migrations "
         f"({mig_ms:.0f}ms p50, {mig_kb:.1f} KiB/req on the wire)")
    return {
        "value": round(p99_disagg, 2),
        "unit": "ms",
        "config": (f"gpt h{hidden} L{layers} pool2 "
                   f"(1 prefill + 1 decode vs 2x both) "
                   f"dec {n_dec}x{dec_gen}tok prompt{dec_len}, flood "
                   f"{flood_total}x prompt{flood_len} gen2 "
                   f"({flood_inflight} in flight)"),
        "p99_tpot_ms_colocated": (round(p99_coloc, 2)
                                  if p99_coloc is not None else None),
        "p99_tpot_ms_disagg_tenant": (
            round(disagg["tenant_p99"], 2)
            if disagg["tenant_p99"] is not None else None),
        "vs_colocated": vs,
        "kv_migrate_ms_per_req": (round(mig_ms, 2)
                                  if mig_ms is not None else None),
        "kv_migrate_kb_per_req": (round(mig_kb, 2)
                                  if mig_kb is not None else None),
        "migrations": disagg["migrations"],
        "measured": (
            f"p99 inter-token latency of {n_dec} decode-heavy requests "
            f"under a continuous {flood_total}-request prefill flood, "
            "2-replica pool either co-located (both role=both; decode-"
            "tenant SLO histogram) or disaggregated (1 prefill + 1 "
            "decode; decode-ROLE histogram, which excludes the one "
            "handoff gap — reported separately as kv_migrate_ms_per_"
            "req).  vs_colocated = coloc p99 / disagg p99 (>= 1.0: "
            "disaggregation protects the decode tail); decode streams "
            "asserted bitwise identical across both fleet shapes"),
    }


def bench_telemetry_overhead(jax, on_tpu):
    """Instrumented vs bare 3D GPT train step (ISSUE 5): the same
    ``build_gpt_3d`` step compiled with and without
    ``collect_stats=True`` (in-graph TrainStats riding the existing
    collectives, ``apex_tpu.observability``), timed back-to-back so the
    "observability is free" claim is a number, not prose.  ``vs_bare``
    = instrumented/bare step time; the steady-state (non-logging) step
    fetches nothing, so the honest expectation is ~1.0 — the acceptance
    gate is <= 1.05 on the CPU mesh.  Runs dp=2 x pp=2 x tp=2(+sp) on 8
    virtual devices (CPU) or whatever the attached chips factor into.

    ISSUE 10: the instrumented variant additionally runs with the
    FLIGHT RECORDER armed (per-step timeline events spilled to JSONL),
    so the ``vs_bare <= 1.05`` gate now also covers the run-timeline
    layer's host cost — the recorder must ride inside the same
    free-telemetry budget, not get its own."""
    import tempfile

    import jax.numpy as jnp

    from apex_tpu.observability import timeline as tl
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    n = len(jax.devices())
    tp = 2 if n % 2 == 0 else 1
    pp = 2 if (n // tp) % 2 == 0 else 1
    dp = n // tp // pp
    tl_dir = None
    mesh = mesh_lib.initialize_model_parallel(
        tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp)
    try:
        if on_tpu:
            hidden, heads, vocab, seq, steps = 512, 8, 50304, 512, 10
        else:
            hidden, heads, vocab, seq, steps = 64, 4, 128, 32, 6
        cfg = TransformerConfig(
            hidden_size=hidden, num_layers=pp, num_attention_heads=heads,
            padded_vocab_size=vocab, max_position_embeddings=seq,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_axis="tp" if tp > 1 else None,
            sequence_parallel=tp > 1,
        )
        num_microbatches = 2
        init_fn, _, make_train_step = build_gpt_3d(
            cfg, num_chunks=1, num_microbatches=num_microbatches,
            mesh=mesh)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (dp * num_microbatches * 2, seq), 0,
            vocab)
        params, specs = init_fn(jax.random.PRNGKey(0), tokens)
        opt = FusedAdam(lr=1e-3)
        state = opt.init(params)

        bare = jax.jit(make_train_step(opt, specs))
        instr = jax.jit(make_train_step(opt, specs, collect_stats=True))
        # the recorder spills to a tempdir (removed in the finally);
        # only the INSTRUMENTED passes emit step events, so dt_instr
        # carries the full armed-recorder host cost and dt_bare none
        tl_dir = tempfile.mkdtemp(prefix="apex_bench_tl_")
        recorder = tl.arm(os.path.join(tl_dir, "timeline.jsonl"))

        def one_pass(step_fn):
            p, s = params, state
            armed = step_fn is instr
            t0 = time.perf_counter()
            for k in range(steps):
                if armed:
                    with tl.scope("step", step=k):
                        res = step_fn(p, s, tokens)
                else:
                    res = step_fn(p, s, tokens)
                p, s = res[0], res[1]
            jax.block_until_ready((p, s))
            return (time.perf_counter() - t0) / steps
        # Compile + warm BOTH before timing either, then interleave the
        # timed passes and take per-variant minima: back-to-back A-then-B
        # timing on the shared-thread CPU mesh hands whichever variant
        # runs second a warmed allocator/thread pool and skews the ratio
        # either way.
        _log("telemetry_overhead: compiling bare + instrumented steps")
        for fn in (bare, instr):
            jax.block_until_ready(fn(params, state, tokens))
        dt_bare, dt_instr = float("inf"), float("inf")
        for r in range(4):
            order = ((bare, instr) if r % 2 == 0 else (instr, bare))
            for fn in order:
                dt = one_pass(fn)
                if fn is bare:
                    dt_bare = min(dt_bare, dt)
                else:
                    dt_instr = min(dt_instr, dt)
        _log(f"telemetry_overhead: bare {dt_bare * 1e3:.1f}ms "
             f"instr {dt_instr * 1e3:.1f}ms "
             f"({recorder.events_emitted} timeline events)")

        return {
            "value": round(dt_instr * 1e6, 1),
            "unit": "us/step",
            "config": "instrumented",
            "bare_us": round(dt_bare * 1e6, 1),
            "instrumented_us": round(dt_instr * 1e6, 1),
            "vs_bare": round(dt_instr / dt_bare, 3),
            "timeline_events": recorder.events_emitted,
            "dp": dp, "pp": pp, "tp": tp,
            "measured": (
                "gpt_3d train step (dp=%d,pp=%d,tp=%d%s) A/B: TrainStats "
                "in-graph telemetry + armed flight recorder (per-step "
                "JSONL timeline spill) on vs off, steady-state (no host "
                "fetch); vs_bare ~1.0 = telemetry is free"
                % (dp, pp, tp, "+sp" if tp > 1 else "")),
        }
    finally:
        tl.disarm()
        if tl_dir is not None:
            shutil.rmtree(tl_dir, ignore_errors=True)
        mesh_lib.destroy_model_parallel()


def bench_serving_trace_overhead(jax, on_tpu):
    """Distributed tracing on the serving hot path (ISSUE 15): the same
    continuous-batching wave with the flight recorder DISARMED vs ARMED
    with per-request trace contexts (request lifecycle events + decode
    ticks spilled to JSONL, trace ids stamped on every event — exactly
    what a traced fleet replica pays).  ``vs_bare`` = traced/bare wave
    wall time at the SHIPPED default tick sampling (every 8th token —
    what a production replica arms); the standing free-telemetry
    acceptance gate is <= 1.05 (scripts/bench_regress.py, beside the
    PR 9 telemetry gate) — tracing must ride inside the existing
    telemetry budget, not get its own.  ``vs_bare_tick1`` additionally
    reports the every-token worst case (what the trace smoke arms for
    exact hop boundaries) — tracked, not gated: on this tiny CPU
    config a decode tick is ~5 ms, so even a ~20µs spill per token
    reads as whole percent; on a real chip serving real shapes it
    vanishes into the step.  Unarmed tracing is a None check and is
    not measured here because it is the bare leg."""
    import tempfile

    import numpy as np

    from apex_tpu import parallel
    from apex_tpu.observability import timeline as tl
    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.serving import ServingConfig, ServingEngine
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=1, devices=jax.devices()[:1])
    tl_dir = tempfile.mkdtemp(prefix="apex_bench_trace_")
    try:
        # hidden 256 (vs the serving row's 128): a realistically-heavy
        # decode tick, so the gate measures the tracing plane against a
        # step that does real work — on the 128-wide toy the ~20µs
        # per-event spill reads as whole percent of a ~4ms tick and
        # host jitter dominates the ratio
        hidden, layers, heads, vocab = (
            (512, 4, 8, 2048) if on_tpu else (256, 2, 8, 512))
        max_batch, prompt_len, gen = 8, 12, 24
        cfg = TransformerConfig(
            hidden_size=hidden, num_layers=layers,
            num_attention_heads=heads, padded_vocab_size=vocab,
            max_position_embeddings=256, hidden_dropout=0.0,
            attention_dropout=0.0, tensor_axis="tp",
            use_flash_attention=True)
        init_fn, _, _ = build_gpt_3d(cfg, num_chunks=layers,
                                     num_microbatches=1, mesh=mesh)
        params, _ = init_fn(jax.random.PRNGKey(0),
                            jax.numpy.zeros((2, 8), jax.numpy.int32))
        engine = ServingEngine(
            cfg, ServingConfig(max_batch=max_batch, block_size=16,
                               max_seq=prompt_len + gen + 8,
                               prefill_len=128),
            params, mesh=mesh, registry=MetricRegistry(rank=0, world=1))
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, vocab - 1, size=prompt_len).tolist()
                   for _ in range(max_batch)]
        recorder = tl.FlightRecorder(
            os.path.join(tl_dir, "timeline.jsonl"))

        def wave(traced: bool, wave_id: int) -> float:
            t0 = time.perf_counter()
            for i, p in enumerate(prompts):
                trace = ({"trace_id": f"w{wave_id}r{i}", "attempt": 1}
                         if traced else None)
                engine.submit(p, gen, trace=trace)
            engine.run_until_drained(max_steps=5000)
            return time.perf_counter() - t0

        wave(False, 0)                 # compile + warm both programs
        # interleave timed passes, per-variant minima (the
        # telemetry_overhead discipline: back-to-back A-then-B on a
        # shared CPU host skews the ratio either way)
        # PAIRED rounds, median-of-ratios: on the shared CPU host the
        # wave-to-wave jitter is whole percent while the true armed
        # overhead is ~1-2% — minima of independent samples let drift
        # trip a 5% gate (observed: the same build measured 1.005 and
        # 1.065 in consecutive runs).  Pairing each traced wave with
        # an adjacent bare wave cancels the drift; the median ratio is
        # the gated number.
        import statistics

        def traced_wave(wid, tick_every):
            engine.timeline_tick_every = tick_every
            tl.arm(recorder)
            try:
                return wave(True, wid)
            finally:
                engine.timeline_tick_every = 8
                tl.disarm()

        def paired(n, tick_every, base):
            out = []
            for r in range(1, n + 1):
                if r % 2:
                    b = wave(False, base + 2 * r)
                    t = traced_wave(base + 2 * r + 1, tick_every)
                else:
                    t = traced_wave(base + 2 * r, tick_every)
                    b = wave(False, base + 2 * r + 1)
                out.append((t, b))
            return out

        pairs = paired(10, 8, 0)
        pairs_tick1 = paired(4, 1, 100)
        vs_bare = statistics.median(t / b for t, b in pairs)
        vs_bare_tick1 = statistics.median(t / b for t, b in pairs_tick1)
        dt_bare = min(b for _, b in pairs)
        dt_traced = min(t for t, _ in pairs)
        tokens = max_batch * gen
        _log(f"serving_trace_overhead: bare {dt_bare * 1e3:.1f}ms "
             f"traced {dt_traced * 1e3:.1f}ms, paired vs_bare "
             f"{vs_bare:.3f} (tick_every=1: {vs_bare_tick1:.3f}) over "
             f"{len(pairs)}+{len(pairs_tick1)} rounds "
             f"({recorder.events_emitted} timeline events)")
        return {
            "value": round(tokens / max(dt_traced, 1e-9), 1),
            "unit": "tokens/sec",
            "config": (f"gpt h{hidden} L{layers} c={max_batch} "
                       f"gen{gen}, default tick sampling"),
            "bare_tokens_per_sec": round(tokens / max(dt_bare, 1e-9), 1),
            "vs_bare": round(vs_bare, 3),
            "vs_bare_tick1": round(vs_bare_tick1, 3),
            "timeline_events": recorder.events_emitted,
            "measured": (
                "continuous-batching wave A/B: flight recorder armed "
                "with per-request trace contexts (lifecycle events + "
                "sampled decode ticks, JSONL spill) vs disarmed; "
                "vs_bare (median of per-round paired ratios — host "
                "drift cancels) at the shipped tick_every=8 default "
                "is the <= 1.05 hard gate, vs_bare_tick1 tracks the "
                "every-token worst case ungated"),
        }
    finally:
        tl.disarm()
        shutil.rmtree(tl_dir, ignore_errors=True)
        parallel.destroy_model_parallel()


def bench_serving_slo_overhead(jax, on_tpu):
    """Longitudinal history + SLO burn-rate evaluation on the serving
    hot path (ISSUE 20): the same continuous-batching wave BARE vs
    ARMED with a :class:`MetricHistory` sampling the engine registry
    and an :class:`SLOEvaluator` walking its burn-rate state machine
    every 4th step — a far hotter cadence than the shipped per-second
    default, so the gate bounds a deliberate worst case.  Both legs
    drive the engine through an identical manual step loop (only the
    sample/evaluate calls differ), paired rounds, median-of-ratios —
    the serving_trace_overhead discipline.  ``vs_bare`` <= 1.05 is the
    standing free-telemetry acceptance gate (scripts/bench_regress.py):
    the history plane must ride inside the existing telemetry budget.
    A disarmed fleet is a single None check and is the bare leg."""
    import numpy as np

    from apex_tpu import parallel
    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.observability.slo import SLOEvaluator, SLOPolicy
    from apex_tpu.observability.timeseries import MetricHistory
    from apex_tpu.serving import ServingConfig, ServingEngine
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=1, devices=jax.devices()[:1])
    try:
        hidden, layers, heads, vocab = (
            (512, 4, 8, 2048) if on_tpu else (256, 2, 8, 512))
        max_batch, prompt_len, gen = 8, 12, 24
        cfg = TransformerConfig(
            hidden_size=hidden, num_layers=layers,
            num_attention_heads=heads, padded_vocab_size=vocab,
            max_position_embeddings=256, hidden_dropout=0.0,
            attention_dropout=0.0, tensor_axis="tp",
            use_flash_attention=True)
        init_fn, _, _ = build_gpt_3d(cfg, num_chunks=layers,
                                     num_microbatches=1, mesh=mesh)
        params, _ = init_fn(jax.random.PRNGKey(0),
                            jax.numpy.zeros((2, 8), jax.numpy.int32))
        registry = MetricRegistry(rank=0, world=1)
        engine = ServingEngine(
            cfg, ServingConfig(max_batch=max_batch, block_size=16,
                               max_seq=prompt_len + gen + 8,
                               prefill_len=128),
            params, mesh=mesh, registry=registry)
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, vocab - 1, size=prompt_len).tolist()
                   for _ in range(max_batch)]
        history = MetricHistory(registry)
        evaluator = SLOEvaluator(history, [
            SLOPolicy(name="ttft", metric="serving/ttft_ms:p99",
                      objective=50.0, fast_window_s=5.0,
                      slow_window_s=30.0, compliance_window_s=300.0),
            SLOPolicy(name="tpot", metric="serving/tpot_ms:p99",
                      objective=20.0, fast_window_s=5.0,
                      slow_window_s=30.0, compliance_window_s=300.0),
        ])

        def wave(armed: bool) -> float:
            t0 = time.perf_counter()
            for p in prompts:
                engine.submit(p, gen)
            steps = 0
            for _ in range(5000):
                if engine.scheduler.idle:
                    break
                engine.step()
                steps += 1
                if armed and steps % 4 == 0:
                    history.sample()
                    evaluator.evaluate()
            return time.perf_counter() - t0

        wave(False)                    # compile + warm both programs
        import statistics

        pairs = []
        for r in range(16):
            if r % 2:
                b = wave(False)
                t = wave(True)
            else:
                t = wave(True)
                b = wave(False)
            pairs.append((t, b))
        vs_bare = statistics.median(t / b for t, b in pairs)
        dt_bare = min(b for _, b in pairs)
        dt_armed = min(t for t, _ in pairs)
        tokens = max_batch * gen
        _log(f"serving_slo_overhead: bare {dt_bare * 1e3:.1f}ms armed "
             f"{dt_armed * 1e3:.1f}ms, paired vs_bare {vs_bare:.3f} "
             f"over {len(pairs)} rounds "
             f"({history.introspect()['samples']} history samples, "
             f"{len(evaluator.last_rows)} slo rows)")
        return {
            "value": round(tokens / max(dt_armed, 1e-9), 1),
            "unit": "tokens/sec",
            "config": (f"gpt h{hidden} L{layers} c={max_batch} "
                       f"gen{gen}, sample+evaluate every 4th step"),
            "bare_tokens_per_sec": round(tokens / max(dt_bare, 1e-9), 1),
            "vs_bare": round(vs_bare, 3),
            "history_samples": history.introspect()["samples"],
            "measured": (
                "continuous-batching wave A/B: MetricHistory registry "
                "sampling + SLOEvaluator burn-rate evaluation every "
                "4th engine step vs the identical bare loop; vs_bare "
                "(median of per-round paired ratios — host drift "
                "cancels) is the <= 1.05 hard gate: the longitudinal "
                "plane rides inside the telemetry budget"),
        }
    finally:
        parallel.destroy_model_parallel()


def bench_serving_autopilot(jax, on_tpu):
    """SLO autopilot (ISSUE 18): a tenant burst against a one-replica
    fleet with the autopilot closing the scale loop (warm-standby
    spawn, ready-handshake join) vs the same burst on the static
    single-replica fleet.

    ``vs_static`` is the paired median-of-ratios of burst p99 TTFT
    (static / autopilot) — the SLO the scale loop exists to protect:
    the static replica queues the burst behind ``max_batch`` so the
    tail requests wait out whole decode generations before their first
    token, while the scaled pool admits the burst immediately.  The
    floor is >= 1.0 (scripts/bench_regress.py): an autopilot that does
    not beat the fleet it operates is a regression.  TTFT (not wall
    tokens/sec) is the judged metric because it holds on a single-core
    CPU host too, where three timesharing replica processes add no
    throughput — the win is admission, not FLOPs.  ``recover_s`` is
    the drain-back: wall seconds from quiesce until the autopilot has
    SIGTERM-drained the pool back to one replica (includes the trend
    window settling to flat — quiesce *detection* is part of the
    loop's cost).  ``actions`` counts autopilot actuations
    (``fleet/autopilot/actions``)."""
    import os
    import shutil
    import statistics
    import tempfile

    import numpy as np

    from apex_tpu import parallel
    from apex_tpu.observability.metrics import MetricRegistry
    from apex_tpu.resilience import CheckpointManager, reshard
    from apex_tpu.serving import (
        AutopilotConfig, FleetAutopilot, FleetRouter, ReplicaProcess,
        ReplicaSpec, ServingConfig)
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import (
        build_gpt_3d, gpt3d_logical_folds)

    hidden, layers, heads, vocab = (
        (256, 2, 8, 1024) if on_tpu else (64, 2, 4, 256))
    prompt_len, gen, wave, rounds = 12, 16, 24, 3
    max_seq = prompt_len + gen + 4
    cfg = TransformerConfig(
        hidden_size=hidden, num_layers=layers, num_attention_heads=heads,
        padded_vocab_size=vocab, max_position_embeddings=max_seq,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis="tp",
        use_flash_attention=True)
    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=1, devices=jax.devices()[:1])
    init_fn, _, _ = build_gpt_3d(cfg, num_chunks=layers,
                                 num_microbatches=1, mesh=mesh)
    params, _ = init_fn(jax.random.PRNGKey(0),
                        jax.numpy.zeros((2, 8), jax.numpy.int32))
    workdir = tempfile.mkdtemp(prefix="apex_bench_autopilot_")
    ckpt_dir = os.path.join(workdir, "ckpt")
    tree = {"params": params, "step_count": np.asarray(1)}
    spec = reshard.build_spec(tree, mesh=mesh,
                              folds=gpt3d_logical_folds(tree))
    CheckpointManager(ckpt_dir, sharded=True, spec=spec).save(tree, 1)
    rng = np.random.RandomState(0)
    routers, pool = [], []
    try:
        rspec = ReplicaSpec(
            config=cfg,
            serving=ServingConfig(max_batch=8, block_size=8,
                                  max_seq=max_seq, prefill_len=64),
            tp=1, ckpt_dir=ckpt_dir, debug_server=False)
        # static fleet: one replica, no controller.  autopilot fleet:
        # one primary + a warm standby pool the spawn actuator draws
        # from (scale-up from standby — the join is the ordinary ready
        # handshake, just without a cold compile in the middle)
        static_rep = ReplicaProcess(rspec, "s0")
        primary = ReplicaProcess(rspec, "a0")
        pool = [ReplicaProcess(rspec, f"auto{i}") for i in (1, 2)]
        for r in [static_rep, primary] + pool:
            r.wait_ready(timeout=500)

        def spawn(name):
            if not pool:
                raise RuntimeError("standby pool exhausted")
            client = pool.pop(0)
            assert client.name == name, (client.name, name)
            return client

        # replica_queue_limit == max_batch: the router keeps the burst
        # backlog on its own queue instead of stuffing one replica's —
        # identical admission policy for both fleets, so the only
        # difference the pairing sees is the capacity the autopilot adds
        static_router = FleetRouter(
            [static_rep], max_queue_depth=4 * wave,
            replica_queue_limit=8, heartbeat_timeout_s=30.0,
            registry=MetricRegistry(rank=0, world=1))
        auto_router = FleetRouter(
            [primary], max_queue_depth=4 * wave,
            replica_queue_limit=8, heartbeat_timeout_s=30.0,
            registry=MetricRegistry(rank=0, world=1))
        routers = [static_router, auto_router]
        # burst-phase policy: grow eagerly (no cool-down gate between
        # the two standby joins), never drain mid-burst (min==max) —
        # the drain-back phase swaps in the quiesce policy below
        ap = FleetAutopilot(auto_router, spawn=spawn,
                            config=AutopilotConfig(
                                min_replicas=3, max_replicas=3,
                                scale_up_queue_depth=8,
                                scale_cooldown_s=0.0))

        def burst(router, prompts, autopilot=None, budget=gen):
            reg = MetricRegistry(rank=0, world=1)
            router.registry = reg
            t0 = time.perf_counter()
            reqs = [router.submit(p, budget) for p in prompts]
            while not router.idle():
                router.pump()
                if autopilot is not None:
                    autopilot.tick()
                if time.perf_counter() - t0 > 500:
                    raise RuntimeError("autopilot bench burst wedged")
                time.sleep(0.002)
            dt = time.perf_counter() - t0
            assert all(len(r.output_tokens) == budget for r in reqs)
            return {"dt": dt,
                    "p99_ttft": reg.histogram("fleet/ttft_ms")
                    .percentile(99),
                    "p99_tpot": reg.histogram("fleet/tpot_ms")
                    .percentile(99)}

        warm = [rng.randint(1, vocab - 1, size=prompt_len).tolist()
                for _ in range(3)]
        burst(static_router, warm, budget=2)
        burst(auto_router, warm, budget=2)     # no scale: depth < 8
        stat_rows, auto_rows = [], []
        for _ in range(rounds):
            prompts = [rng.randint(1, vocab - 1,
                                   size=prompt_len).tolist()
                       for _ in range(wave)]
            stat_rows.append(burst(static_router, prompts))
            auto_rows.append(burst(auto_router, prompts,
                                   autopilot=ap))
        def live():
            return sum(1 for v in auto_router._views.values()
                       if not v.down and v.client.alive())

        assert live() == 3, "autopilot never grew the pool"
        vs_static = statistics.median(
            s["p99_ttft"] / max(a["p99_ttft"], 1e-9)
            for s, a in zip(stat_rows, auto_rows))
        # quiesce: swap in the drain-back policy and measure the wall
        # time until the pool is back to one replica (the spawned
        # replicas leave via the ordinary SIGTERM-drain path)
        ap.config = AutopilotConfig(min_replicas=1, max_replicas=3,
                                    scale_down_queue_depth=2,
                                    scale_cooldown_s=0.0)
        t0 = time.perf_counter()
        while live() > 1:
            auto_router.pump()
            ap.tick()
            if time.perf_counter() - t0 > 200:
                raise RuntimeError("drain-back wedged")
            time.sleep(0.01)
        recover_s = time.perf_counter() - t0
        actions = int(ap.registry.counter(
            "fleet/autopilot/actions").value)
        p99_burst = statistics.median(a["p99_ttft"] for a in auto_rows)
        p99_static = statistics.median(s["p99_ttft"] for s in stat_rows)
        tokens = wave * gen
        tps = statistics.median(tokens / a["dt"] for a in auto_rows)
        _log(f"serving_autopilot: burst p99 TTFT {p99_burst:.1f}ms "
             f"autopilot vs {p99_static:.1f}ms static "
             f"(vs_static {vs_static:.2f}x, {actions} actions, "
             f"drain-back {recover_s:.1f}s)")
        return {
            "value": round(tps, 1),
            "unit": "tokens/sec",
            "config": (f"gpt h{hidden} L{layers} 1+2-standby tp1 "
                       f"replicas prompt{prompt_len} gen{gen} "
                       f"wave{wave} x{rounds} rounds"),
            "p99_ttft_ms_burst": round(p99_burst, 2),
            "p99_ttft_ms_static": round(p99_static, 2),
            "p99_tpot_ms_burst": round(statistics.median(
                a["p99_tpot"] for a in auto_rows), 2),
            "vs_static": round(vs_static, 3),
            "actions": actions,
            "recover_s": round(recover_s, 1),
            "measured": (
                f"{rounds} paired rounds of a {wave}-request tenant "
                f"burst x {gen} greedy tokens: static one-replica "
                "fleet vs the same fleet with the autopilot scaling "
                "onto 2 warm standbys through the ready handshake; "
                "vs_static = median per-round (static p99 TTFT / "
                "autopilot p99 TTFT) — admission latency, the metric "
                "the scale loop protects; recover_s = quiesce-policy "
                "drain back to one replica (includes trend-flat "
                "detection)"),
        }
    finally:
        for router in routers:
            router.close()
        for r in pool:
            try:
                r.close()
            except Exception:
                pass
        shutil.rmtree(workdir, ignore_errors=True)
        parallel.destroy_model_parallel()


# ---------------------------------------------------------------------------

BENCHES = {
    "resnet50_o2": bench_resnet50_o2,
    "resnet50_lamb_syncbn": bench_resnet50_lamb_syncbn,
    "bert_large": bench_bert_large,
    "gpt_flash": bench_gpt_flash,
    "gpt_flash_fp8": bench_gpt_flash_fp8,
    "gpt_long_context": bench_gpt_long_context,
    "tp_gpt": bench_tp_gpt,
    "fused_adam_step": bench_fused_adam_step,
    "zero_adam_step": bench_zero_adam_step,
    "ckpt_save_restore": bench_ckpt_save_restore,
    "ckpt_reshard": bench_ckpt_reshard,
    "telemetry_overhead": bench_telemetry_overhead,
    "serving": bench_serving,
    "serving_occupancy": bench_serving_occupancy,
    "serving_fleet": bench_serving_fleet,
    "serving_spec": bench_serving_spec,
    "serving_disagg": bench_serving_disagg,
    "serving_trace_overhead": bench_serving_trace_overhead,
    "serving_slo_overhead": bench_serving_slo_overhead,
    "serving_lora": bench_serving_lora,
    "serving_autopilot": bench_serving_autopilot,
    "input_pipeline": bench_input_pipeline,
    "real_data_rn50": bench_real_data_rn50,
    # Diagnostic-only combos (run via ``--one``, not in BENCH_ORDER):
    # isolate which factor of the lamb+syncbn row costs what — the r4
    # first window measured resnet50_o2 (sgd, plain BN, pjit) 3.4x faster
    # than resnet50_lamb_syncbn (lamb, SyncBN, shard_map) on one chip.
    "resnet50_sgd_syncbn": lambda jax, on_tpu: _resnet_bench(
        jax, on_tpu, "sgd", sync_bn=True),
    "resnet50_lamb_nosync": lambda jax, on_tpu: _resnet_bench(
        jax, on_tpu, "lamb"),
}
# headline first: if the deadline hits, the most important number exists.
# Then the r4-VERDICT capture priorities: fused_adam_step (North-Star #2,
# never yet measured on hardware) ahead of the fp8/long-context rows.
# tp_gpt deliberately LAST: its r2/r3 mode of failure was a 900 s setup
# hang, and running it mid-suite starved every config behind it of TPU
# window (observed r4 first pass: fp8/long-context/input-pipeline all fell
# back to CPU because tp_gpt ate 900 s + the retry).
BENCH_ORDER = ["resnet50_o2", "gpt_flash", "bert_large",
               "resnet50_lamb_syncbn", "fused_adam_step",
               "zero_adam_step", "ckpt_save_restore", "ckpt_reshard",
               "telemetry_overhead", "serving", "serving_occupancy",
               "serving_fleet", "serving_spec", "serving_disagg",
               "serving_trace_overhead", "serving_slo_overhead",
               "serving_lora", "serving_autopilot",
               "gpt_flash_fp8", "gpt_long_context", "input_pipeline",
               "real_data_rn50", "tp_gpt"]


def run_one(name: str) -> None:
    """Child mode: init the backend, run one bench, print its JSON."""
    import jax

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()
    else:
        # Persistent compilation cache: a child killed mid-compile (900s
        # timeout) leaves its XLA work on disk, so the retry pass resumes
        # warm instead of recompiling from scratch.
        enable_compilation_cache(jax)
    _log(f"{name}: initializing backend")
    t0 = time.perf_counter()
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    _log(f"{name}: backend up in {time.perf_counter() - t0:.1f}s "
         f"({dev.platform} {getattr(dev, 'device_kind', '')})")
    rec = BENCHES[name](jax, on_tpu)
    rec["platform"] = dev.platform
    _log(f"{name}: done -> {rec.get('value')} {rec.get('unit')}")
    print(json.dumps(rec), flush=True)


def _run_child(name: str, platform: str, timeout: float) -> dict:
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        if name in ("tp_gpt", "zero_adam_step", "ckpt_save_restore",
                    "ckpt_reshard", "telemetry_overhead", "serving"):
            # r3 VERDICT weak #5: tp_gpt at tp=1 on the single bench chip
            # exercises zero TP collectives.  The CPU row instead runs a
            # *real* tp=8 shard_map on a virtual 8-device host mesh, so at
            # least the collective step-time shape is measured somewhere;
            # the row's "measured" field states exactly what it is.
            # zero_adam_step needs the same mesh: its whole point is the
            # flat-bucket-vs-per-leaf collective count over dp=8.
            # ckpt_save_restore: the sharded layout only splits shards
            # when there is a real multi-device dp mesh to shard over.
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=8")
    _log(f"launching {name} (timeout {timeout:.0f}s)")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", name],
            timeout=timeout, capture_output=True, env=env,
        )
    except subprocess.TimeoutExpired as e:
        # Partial stderr attributes the loss: no "backend up" line means the
        # tunnel wedged at init; "compile start" without "compiled" means a
        # compile blowup; otherwise the bench itself was too slow.
        tail = (e.stderr or b"").decode(errors="replace")[-600:]
        _log(f"{name}: TIMEOUT after {timeout:.0f}s; partial stderr:\n{tail}")
        return {"error": f"timeout after {timeout:.0f}s",
                "stderr_tail": tail[-300:]}
    err_tail = proc.stderr.decode(errors="replace")[-1500:]
    if proc.returncode != 0:
        _log(f"{name}: rc={proc.returncode}\n{err_tail}")
        return {"error": f"rc={proc.returncode}: {err_tail[-300:]}"}
    try:
        return json.loads(proc.stdout.decode().strip().splitlines()[-1])
    except Exception as e:
        _log(f"{name}: unparseable output ({e!r})\n{err_tail}")
        return {"error": f"unparseable output: {e!r}"}


# Expected single-chip TPU runtimes are minutes; a wedge burns the whole
# per-bench budget, so cheap benches get tighter caps than the 900s default.
_TPU_BENCH_CAP_S = {"fused_adam_step": 420.0, "zero_adam_step": 420.0,
                    "ckpt_save_restore": 420.0, "ckpt_reshard": 420.0,
                    "telemetry_overhead": 600.0, "serving": 600.0,
                    "serving_occupancy": 600.0,
                    "serving_fleet": 600.0, "serving_spec": 600.0,
                    "serving_disagg": 600.0,
                    "serving_trace_overhead": 600.0,
                    "serving_slo_overhead": 600.0,
                    "serving_lora": 600.0,
                    "serving_autopilot": 600.0,
                    "tp_gpt": 900.0}


# Failed TPU attempts per bench that were *not* attributable to a chip
# wedge; a deterministically crashing/too-slow bench stops retrying after
# the cap instead of burning the poll window one failure at a time.
_TPU_FAILS: dict = {}
_TPU_FAIL_CAP = 2


def _run_suite(results, platform, deadline, per_bench, upgrade=True,
               on_update=None):
    """Run every bench not yet successful on ``platform``.  Returns the
    platform still believed healthy ("tpu" may degrade to "cpu" after a
    timeout + failed re-probe; CPU runs never degrade).

    ``upgrade=True`` (TPU passes): a success on another platform does not
    satisfy the pass — the poll window exists to upgrade CPU records to
    TPU ones.  ``upgrade=False`` (CPU fallback passes): any error-free
    record satisfies the pass, so a fallback can never clobber TPU
    evidence.  A failure never overwrites an existing success.

    ``on_update`` is called after every change to ``results`` (r3
    postmortem: emit the upgraded record *immediately*, never hold
    evidence in RAM until process exit)."""
    for name in BENCH_ORDER:
        prev = results.get(name, {"error": "unrun"})
        if "error" not in prev and (
                not upgrade or prev.get("platform") == platform):
            continue
        if platform == "tpu" and _TPU_FAILS.get(name, 0) >= _TPU_FAIL_CAP:
            continue
        cap = _TPU_BENCH_CAP_S.get(name, per_bench) if platform == "tpu" \
            else per_bench
        budget = min(cap, deadline - time.monotonic())
        if budget < 60:
            _log(f"{name}: skipped (deadline)")
            results.setdefault(name, {"error": "skipped: global deadline"})
            continue
        rec = _run_child(name, platform, budget)
        if "error" not in rec or "error" in prev:
            results[name] = rec
            if on_update is not None:
                on_update()
        # The tunneled TPU can die *mid-suite* (observed: backend init
        # wedges for every subsequent child).  After a timeout, re-probe
        # before burning the remaining budget a full cap at a time.
        if platform == "tpu" and "error" in rec:
            _TPU_FAILS[name] = _TPU_FAILS.get(name, 0) + 1
            if "timeout" in str(rec.get("error", "")):
                _log("timeout on tpu: re-probing backend health")
                if probe_platform(max_tries=1, timeout=120.0) != "tpu":
                    # chip wedge, not the bench's fault: uncount it
                    _TPU_FAILS[name] -= 1
                    _log("tpu backend wedged; pausing the tpu suite")
                    return "cpu"
    return platform


def _newest_prior_tpu_record():
    """Newest stamped bench_results/tpu_*.json, embedded (with provenance)
    when the chip never materializes during this bench window."""
    import glob

    paths = sorted(glob.glob(os.path.join(_REPO, "bench_results",
                                          "tpu_*.json")))
    best, best_mtime = None, -1.0
    for p in paths:
        try:
            mtime = os.path.getmtime(p)
            with open(p) as f:
                rec = json.load(f)
            if mtime > best_mtime:
                best, best_mtime = (p, rec), mtime
        except Exception:
            continue
    if best is None:
        return None
    path, rec = best
    return {
        "path": os.path.relpath(path, _REPO),
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S",
                                     time.localtime(best_mtime)),
        "note": ("builder-recorded TPU run embedded because the TPU backend "
                 "never initialized during this bench window"),
        "record": rec,
    }


# One stamp per bench run: repeated saves of an improving TPU record
# overwrite the same file instead of littering bench_results/.
_RUN_STAMP = time.strftime("%Y%m%d_%H%M%S")


def _save_tpu_record(record) -> None:
    path = os.path.join(_REPO, "bench_results", f"tpu_{_RUN_STAMP}.json")
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)
        _log(f"tpu record saved to {path}")
    except Exception as e:
        _log(f"could not save tpu record: {e!r}")


def build_record(results, platform) -> dict:
    """Assemble the driver-contract record from the current results.
    Safe to call at any point in the run — missing benches appear as
    ``error: unrun`` and the newest stamped prior TPU record is embedded
    whenever the headline itself did not run on TPU."""
    headline = results.get("resnet50_o2", {"error": "unrun"})
    ok = "error" not in headline
    headline_on_tpu = headline.get("platform") == "tpu"
    baseline = adopted_baseline()
    record = {
        "metric": "resnet50_o2_train_throughput",
        "value": headline.get("value", 0.0) if ok else 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": (round(headline["value"] / baseline, 3)
                        if ok and headline_on_tpu else None),
        "platform": headline.get("platform", platform),
        "headline": headline,
        "extras": {k: v for k, v in results.items() if k != "resnet50_o2"},
    }
    # State the fp8-vs-bf16 delta plainly when both rows ran on the same
    # platform (the fp8 path is a storage/numerics capability on this chip
    # generation — the honest expectation is ~1.0x, not a win).
    bf16, fp8 = results.get("gpt_flash", {}), results.get("gpt_flash_fp8", {})
    if ("error" not in bf16 and "error" not in fp8
            and bf16.get("platform") == fp8.get("platform")
            and bf16.get("value")):
        record["extras"]["gpt_flash_fp8"] = dict(
            fp8, vs_bf16=round(fp8["value"] / bf16["value"], 3))
    # Real-data vs synthetic RN50: how much of the device rate survives
    # feeding the step from actual files (1.0 = the input path costs
    # nothing; VERDICT r4 missing #2 asks for this composition).
    real = results.get("real_data_rn50", {})
    if ("error" not in real and ok and real.get("value")
            and headline.get("platform") == real.get("platform")
            and headline.get("value")):
        record["extras"]["real_data_rn50"] = dict(
            real, vs_synthetic=round(real["value"] / headline["value"], 3))
    if not headline_on_tpu:
        prior = _newest_prior_tpu_record()
        if prior is not None:
            record["prior_tpu_record"] = prior
            if record["vs_baseline"] is None:
                record["vs_baseline"] = prior["record"].get("vs_baseline")
                record["vs_baseline_source"] = "prior_tpu_record"
    return record


def compact_record(record, max_bytes: int = 1500) -> dict:
    """Distill a full record into a line guaranteed to fit the driver's
    2000-byte stdout tail (round-4 postmortem: the full record line grew to
    ~2.9 KB once the prior TPU evidence was embedded, so the tail's last
    line started mid-JSON and BENCH_r0{1..4} were all ``parsed: null``).

    Keeps the driver-contract header plus per-row {value, unit, mfu,
    platform} — provenance prose stays in the full line and in
    ``bench_results/``.  Degrades further (drop units, then rows) if a
    future record still exceeds ``max_bytes``; never returns an oversized
    payload."""
    row_keys = ("value", "unit", "mfu", "platform", "vs_native", "vs_bf16",
                "vs_synthetic", "vs_per_leaf", "vs_monolithic",
                "vs_sharded", "vs_bare", "vs_same_mesh", "vs_unfused",
                "vs_reserve", "ttft_cold_ms", "ttft_hit_ms",
                "ttft_hit_vs_cold", "vs_baseline", "mean_accept_len",
                "acceptance_rate",
                "loader_ips_per_backend", "stall_ms_per_step",
                "packed_lm_tokens_per_sec", "tokens_per_sec_at",
                "tpot_p50_ms_at", "tpot_p99_ms_at",
                "p99_tpot_ms_steady", "p99_tpot_ms_roll",
                "roll_vs_steady", "wire_vs_inproc",
                "vs_colocated", "p99_tpot_ms_colocated",
                "kv_migrate_ms_per_req", "kv_migrate_kb_per_req",
                "vs_bare_1adapter", "vs_static",
                "p99_ttft_ms_burst", "recover_s")
    rows = {}
    for name, row in list(record.get("extras", {}).items()):
        if not isinstance(row, dict):
            continue
        slim = {k: row[k] for k in row_keys if row.get(k) is not None}
        if "error" in row:
            slim["error"] = str(row["error"])[:48]
        rows[name] = slim
    compact = {
        "metric": record["metric"],
        "value": record["value"],
        "unit": record["unit"],
        "vs_baseline": record.get("vs_baseline"),
        "platform": record.get("platform"),
        "rows": rows,
    }
    if "vs_baseline_source" in record:
        compact["vs_baseline_source"] = record["vs_baseline_source"]
    prior = record.get("prior_tpu_record")
    if isinstance(prior, dict) and "path" in prior:
        compact["prior_tpu_record_path"] = prior["path"]
    size = lambda: len(json.dumps(compact, separators=(",", ":")))
    if size() > max_bytes:
        for slim in rows.values():
            slim.pop("unit", None)
    if size() > max_bytes:
        # drop per-row platform stamps that just repeat the record's
        # own (a uniform-platform day, the common case): pure
        # redundancy, and at seventeen rows it is ~300 bytes
        for slim in rows.values():
            if slim.get("platform") == compact.get("platform"):
                slim.pop("platform", None)
    if size() > max_bytes:
        # shed secondary sub-fields before mutilating the rows: the p50
        # curve is a nice-to-have (the regression gate and the history
        # read values, ratios, and p99s), and the absolute TTFT pair is
        # reconstructible enough from the ratio the gate actually reads
        for slim in rows.values():
            slim.pop("tpot_p50_ms_at", None)
    if size() > max_bytes:
        for slim in rows.values():
            slim.pop("ttft_cold_ms", None)
            slim.pop("ttft_hit_ms", None)
            # reconstructible from mean_accept_len (~(len-1)/k); the
            # gate reads vs_baseline and the accept length
            slim.pop("acceptance_rate", None)
    if size() > max_bytes:
        # the roll-window p99 is exactly steady * roll_vs_steady — the
        # ratio (what the gate and the ISSUE 11 bar read) plus the
        # steady absolute reconstruct it
        for slim in rows.values():
            slim.pop("p99_tpot_ms_roll", None)
    if size() > max_bytes:
        # degrade the per-concurrency curves to their top point — the
        # headline the gates read; the full record keeps the curves
        for slim in rows.values():
            for key in ("tokens_per_sec_at", "tpot_p99_ms_at"):
                curve = slim.get(key)
                if isinstance(curve, dict) and len(curve) > 1:
                    top = max(curve, key=lambda k: float(
                        str(k).rstrip("x")))
                    slim[key] = {top: curve[top]}
    if size() > max_bytes:
        # the autopilot's secondary timings: the gate reads vs_static;
        # the absolute burst TTFT and the drain-back wall stay in the
        # full record
        for slim in rows.values():
            slim.pop("p99_ttft_ms_burst", None)
            slim.pop("recover_s", None)
    if size() > max_bytes:
        # provenance pointers next — the full stdout line and the
        # bench_results/ stamp carry them; the gate reads neither
        compact.pop("vs_baseline_source", None)
        compact.pop("prior_tpu_record_path", None)
    if size() > max_bytes:
        compact["rows"] = {n: s.get("value") for n, s in rows.items()}
    if size() > max_bytes:
        compact.pop("rows", None)
    return compact


def emit_record(results, platform) -> dict:
    """Print the current record as a full stdout JSON line followed by a
    compact (<=1500-byte) one.  The driver keeps only the last 2000 bytes
    of stdout and parses the *last* JSON line, so the compact line — always
    printed last, always under the tail size — is what it sees; the full
    line and the bench_results/ stamp carry the provenance detail.  Each
    emission supersedes the previous one, so a kill at any instant leaves
    the newest evidence behind.  Stamps to bench_results/ when the
    headline is TPU."""
    record = build_record(results, platform)
    if record["headline"].get("platform") == "tpu":
        # Only a record whose *headline* ran on TPU is worth embedding in a
        # later round as TPU evidence — a CPU headline with one stray TPU
        # extra must not masquerade as a TPU run.
        _save_tpu_record(record)
    try:
        # Full record always lands on disk too (not only on TPU days), so
        # a truncated stdout tail never loses provenance.
        path = os.path.join(_REPO, "bench_results", "latest_record.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "w") as f:
            json.dump(record, f)
        os.replace(path + ".tmp", path)
    except Exception as e:
        _log(f"could not save latest record: {e!r}")
    print(json.dumps(record), flush=True)
    print(json.dumps(compact_record(record), separators=(",", ":")),
          flush=True)
    return record


def main():
    from apex_tpu.utils.platform import probe_default_platform

    t_start = time.monotonic()
    # 1800s default: safely inside the observed ~2100s driver window (the
    # r3 default of 2700s exceeded it and the kill landed mid-poll).
    deadline = t_start + float(os.environ.get("BENCH_DEADLINE_S", "1800"))
    # Keep probing for the chip until ~80% of the window is gone — a wedge
    # at bench start must not forfeit the round's TPU evidence (BENCH_r02).
    # An explicit CPU pin disables the poll (the probe honors the pin, so
    # polling could never upgrade the platform).
    cpu_pinned = os.environ.get("JAX_PLATFORMS", "").lower() == "cpu"
    poll_deadline = t_start if cpu_pinned else (
        t_start + 0.8 * (deadline - t_start))

    results = {}
    # Bootstrap record before anything that can hang (probe, suites): even a
    # kill during the first backend probe leaves a parseable record carrying
    # the embedded prior TPU evidence.
    emit = lambda: emit_record(results, platform)
    platform = "cpu"
    emit()
    probed = None if cpu_pinned else probe_default_platform(
        max_tries=1, timeout=150.0, log=_log)
    platform = probed if probed is not None else "cpu"
    if probed is not None and probed != "tpu":
        # The default backend initialized cleanly and it is NOT a TPU —
        # there is no wedged tunnel to wait out (dev box / CI without the
        # plugin); polling could never upgrade the platform.
        _log(f"default backend is '{probed}' (no tpu plugin); not polling")
        poll_deadline = t_start

    cpu_fallback_done = False

    def cpu_fallback():
        # Secure a CPU record (tiny shapes, minutes); never clobbers
        # existing successes.  Runs at most once — before polling when the
        # chip is down at start, or the moment a mid-suite wedge pauses
        # the TPU pass (the round-2 behavior of degrading immediately,
        # kept so a wedge can never leave benches with no record at all).
        nonlocal cpu_fallback_done
        if not cpu_fallback_done:
            _log("running cpu fallback suite")
            _run_suite(results, "cpu",
                       min(deadline, time.monotonic() + 900),
                       per_bench=300.0, upgrade=False, on_update=emit)
            cpu_fallback_done = True

    if platform != "tpu":
        _log("tpu down at start")
        cpu_fallback()

    while True:
        if platform == "tpu":
            platform = _run_suite(results, "tpu", deadline, per_bench=900.0,
                                  on_update=emit)
            done_or_capped = all(
                r.get("platform") == "tpu"
                or _TPU_FAILS.get(n, 0) >= _TPU_FAIL_CAP
                for n, r in results.items())
            if platform == "tpu" and done_or_capped:
                break
            if platform != "tpu":
                cpu_fallback()  # wedged mid-suite: record before polling
        if time.monotonic() > poll_deadline:
            break
        _log("polling for tpu backend "
             f"({poll_deadline - time.monotonic():.0f}s of window left)")
        time.sleep(60)
        platform = "tpu" if probe_platform(
            max_tries=1, timeout=120.0) == "tpu" else "cpu"

    # CPU fallback for anything that still has no record at all (never
    # clobbers an existing success on any platform).
    if any("error" in r for r in results.values()) or not results:
        _run_suite(results, "cpu", deadline, per_bench=300.0, upgrade=False,
                   on_update=emit)

    # Final (possibly redundant) emission — the last JSON line wins.
    emit()


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--one":
        run_one(sys.argv[2])
    else:
        main()
